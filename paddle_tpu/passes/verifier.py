"""verify_program: static-analysis lint over a Program.

The reference validates OpDescs at op-creation time (framework.py
Operator.__init__ checks against OpProto) and again in C++ at run time;
malformed programs here used to surface as opaque TraceErrors deep in
lowering (core/lowering.py). This pass walks every block BEFORE tracing
and emits structured diagnostics:

  error  — the tracer/registry will reject this program (undefined
           inputs, use-before-def, unregistered op, dangling sub-block,
           unreachable fetch target, invalid dtype attr)
  warn   — suspicious but runnable (outputs nothing consumes, declared
           shape/dtype disagreeing with what the op registry infers)

Levels: 'fast' runs the structural checks only (the Executor runs this
per program epoch before its analysis cache); 'full' adds the
registry-backed shape/dtype consistency sweep (the lint CLI and the
optimization pipelines use this).
"""
from __future__ import annotations

import numpy as np

from ..core import registry
from ..framework import convert_dtype
from .base import (Pass, register_pass, op_reads, op_writes,
                   sub_block_indices, _SUB_BLOCK_ATTRS)


class ProgramVerifyError(RuntimeError):
    """Raised under strict verification (PTPU_STRICT_VERIFY=1) when the
    verifier finds error-level diagnostics."""

    def __init__(self, diagnostics):
        self.diagnostics = list(diagnostics)
        errs = [d for d in self.diagnostics if d.level == 'error']
        lines = '\n'.join('  ' + str(d) for d in errs[:20])
        more = '' if len(errs) <= 20 else '\n  ... and %d more' % (
            len(errs) - 20)
        super().__init__(
            "program failed verification with %d error(s):\n%s%s\n"
            "(set PTPU_STRICT_VERIFY=0 to downgrade to warnings)"
            % (len(errs), lines, more))


class Diagnostic(object):
    """One verifier finding, anchored to (block id, op index)."""

    __slots__ = ('level', 'code', 'message', 'block', 'op_index', 'var')

    def __init__(self, level, code, message, block=0, op_index=-1, var=None):
        self.level = level        # 'error' | 'warn'
        self.code = code          # stable kebab-case class
        self.message = message
        self.block = block
        self.op_index = op_index  # -1: not tied to one op
        self.var = var

    def as_dict(self):
        return {'level': self.level, 'code': self.code,
                'message': self.message, 'block': self.block,
                'op_index': self.op_index, 'var': self.var}

    def __repr__(self):
        at = 'block %d' % self.block
        if self.op_index >= 0:
            at += ' op %d' % self.op_index
        return "[%s] %s (%s): %s" % (self.level, self.code, at, self.message)


# op types the tracer handles without a registry entry
_TRACER_BUILTIN_OPS = ('feed', 'fetch')


def _registered(op_type):
    if op_type in _TRACER_BUILTIN_OPS:
        return True
    return registry.is_registered(op_type)


def _initially_defined(program, feed_names):
    """Names the executor seeds into env before any op runs: explicit
    feeds, data vars, scope-present persistables, feed-op outputs, and
    non-tensor var kinds (readers/tensor arrays) that ops materialize
    lazily."""
    defined = set(feed_names or ())
    for v in program.list_vars():
        if v.persistable or getattr(v, 'is_data', False):
            defined.add(v.name)
        if getattr(v, 'type', 'lod_tensor') != 'lod_tensor':
            defined.add(v.name)
    for op in program.global_block().ops:
        if op.type == 'feed':
            defined.update(op.output_arg_names())
    return defined


def verify_program(program, feed_names=None, fetch_names=None, level='full'):
    """Lint `program`; returns a list of Diagnostic (possibly empty).

    feed_names/fetch_names: the run boundary when known. Defaults come
    from the program itself (feed ops / data vars; fetch ops /
    `_fetch_names` recorded by save_inference_model).
    """
    if level not in ('fast', 'full'):
        raise ValueError("level must be 'fast' or 'full', got %r" % (level,))
    diags = []
    feed_names = list(feed_names if feed_names is not None
                      else getattr(program, '_feed_names', ()) or ())
    fetch_names = list(fetch_names if fetch_names is not None
                       else getattr(program, '_fetch_names', ()) or ())

    defined0 = _initially_defined(program, feed_names)

    # ordered recursive walk from block 0: sub-blocks verify against the
    # names defined at their owning op's position plus the bindings the
    # control op itself creates (rnn inner slots); orphan blocks nothing
    # references fall back to the unordered declared-somewhere check
    visited = set()
    _verify_block(program, program.global_block(), set(defined0), diags,
                  visited)
    for block in program.blocks:
        if block.idx not in visited:
            _verify_block(program, block, set(defined0), diags, visited,
                          ordered=False)

    if level == 'full':
        for block in program.blocks:
            _check_registry_consistency(program, block, diags)
        _warn_dead_outputs(program, program.global_block(), diags,
                           fetch_names)
        _check_rebind_and_dead_persistables(program, diags, feed_names,
                                            fetch_names)

    # fetch reachability: every fetch target must be produced by some op,
    # fed, or live in the scope (persistable)
    produced = set(defined0)
    for op in program.global_block().ops:
        produced |= op_writes(op, program)
    fetch_targets = list(fetch_names)
    for i, op in enumerate(program.global_block().ops):
        if op.type == 'fetch':
            fetch_targets.extend(op.input_arg_names())
    for name in fetch_targets:
        if name and name not in produced:
            diags.append(Diagnostic(
                'error', 'unreachable-fetch',
                "fetch target %r is produced by no op, never fed, and not "
                "persistable" % name, block=0, var=name))
    return diags


# inner sub-block names a control op binds into its body's env before
# any body op runs (ops/control_ops.py): rnn step-input/static-input
# slots and memory `pre` vars — each attr entry carries the inner name
# at index 1
_SUB_BLOCK_BINDING_ATTRS = ('rnn_step_inputs', 'rnn_static_inputs',
                            'rnn_memories')


def _op_sub_bindings(op):
    names = set()
    for key in _SUB_BLOCK_BINDING_ATTRS:
        for entry in op.attrs.get(key, ()) or ():
            try:
                if entry[1]:
                    names.add(entry[1])
            except (TypeError, IndexError):
                continue
    return names


def _verify_block(program, block, defined, diags, visited, ordered=True):
    """Order-exact use-before-def walk, recursive through sub-blocks.

    The tracer runs every body against `dict(tracer.env)` at the owning
    op's position (while carries live in the outer env by construction;
    rnn inner slots are bound by the op — _op_sub_bindings), so a
    sub-block read of a name with neither an incoming binding nor an
    earlier in-block write fails the trace on the first iteration:
    order-exact checking inside sub-blocks is sound, not conservative.
    `defined` is mutated (callers pass a copy per scope)."""
    visited.add(block.idx)

    for i, op in enumerate(block.ops):
        if not _registered(op.type):
            diags.append(Diagnostic(
                'error', 'unregistered-op',
                "op type %r has no registered lowering" % op.type,
                block=block.idx, op_index=i))

        # dtype attrs must canonicalize
        for attr in ('dtype', 'in_dtype', 'out_dtype'):
            if op.has_attr(attr) and op.attrs[attr] not in (None, -1):
                try:
                    convert_dtype(op.attrs[attr])
                except Exception:
                    diags.append(Diagnostic(
                        'error', 'bad-dtype',
                        "op %r attr %s=%r is not a valid dtype"
                        % (op.type, attr, op.attrs[attr]),
                        block=block.idx, op_index=i))

        # sub-block references must point at a real, distinct block
        for key in _SUB_BLOCK_ATTRS:
            idx = op.attrs.get(key)
            if idx is None:
                continue
            if (not isinstance(idx, int) or isinstance(idx, bool)
                    or idx <= 0 or idx >= len(program.blocks)
                    or idx == block.idx):
                diags.append(Diagnostic(
                    'error', 'dangling-sub-block',
                    "op %r attr %s=%r does not reference a valid "
                    "sub-block (program has %d blocks)"
                    % (op.type, key, idx, len(program.blocks)),
                    block=block.idx, op_index=i))

        for name in op.input_arg_names():
            if not name:
                continue
            if block._find_var_recursive(name) is None:
                diags.append(Diagnostic(
                    'error', 'undefined-input',
                    "op %r reads %r which is declared in no block"
                    % (op.type, name), block=block.idx, op_index=i,
                    var=name))
            elif ordered and name not in defined:
                where = '' if block.idx == 0 else \
                    ' inside sub-block %d' % block.idx
                diags.append(Diagnostic(
                    'error', 'use-before-def',
                    "op %r reads %r before any op produces it%s (not "
                    "fed, not persistable, not bound by the owning "
                    "control op — check op ordering)"
                    % (op.type, name, where), block=block.idx,
                    op_index=i, var=name))

        # recurse into bodies with the names defined AT THIS POINT plus
        # the op's own inner bindings — the env the tracer hands them
        for idx in sub_block_indices(op):
            if 0 < idx < len(program.blocks) and idx != block.idx \
                    and idx not in visited:
                _verify_block(program, program.block(idx),
                              defined | _op_sub_bindings(op), diags,
                              visited, ordered=ordered)
        defined |= op_writes(op, program)


# ---------------------------------------------------------------------------
# full-level checks
# ---------------------------------------------------------------------------
def _check_registry_consistency(program, block, diags):
    """Re-infer each op's output shapes/dtypes through the registry
    (the same jax.eval_shape the build-time InferShape uses) and compare
    against the DECLARED vars — a corrupted attr (fill_constant shape
    edited after append, dtype rewritten) shows up as a mismatch."""
    from ..core.registry import (get, ShapeCtx, _probe_shape, _unprobe_dim)
    import jax
    import jax.numpy as jnp

    for i, op in enumerate(block.ops):
        d = get(op.type)
        if d is None or d.infer_shape is not None or d.lower is None:
            continue  # custom/absent inference: trust the op
        if op.type.endswith('_grad') or op.attrs.get('fuse_act'):
            continue
        had_probe = False
        ins = {}
        ok = True
        for slot, names in op.inputs.items():
            vals = []
            for n in names:
                if not n:
                    vals.append(None)
                    continue
                v = block._find_var_recursive(n)
                if v is None or v.shape is None:
                    ok = False
                    break
                if any(s in (-1, None) for s in v.shape):
                    had_probe = True
                try:
                    vals.append(jax.ShapeDtypeStruct(
                        _probe_shape(v.shape), jnp.dtype(v.dtype)))
                except Exception:
                    ok = False
                    break
            if not ok:
                break
            ins[slot] = vals
        if not ok:
            continue
        ctx = ShapeCtx(op, block)
        try:
            outs = jax.eval_shape(lambda kw: d.lower(ctx, kw), ins)
        except Exception:
            continue  # lowering needs concrete values; nothing to check
        for slot, names in op.outputs.items():
            vals = outs.get(slot)
            if vals is None:
                continue
            for n, sds in zip(names, vals):
                if not n or sds is None:
                    continue
                v = block._find_var_recursive(n)
                if v is None or v.shape is None:
                    continue
                inferred = tuple(_unprobe_dim(s, had_probe)
                                 for s in sds.shape)
                declared = tuple(v.shape)
                if len(inferred) != len(declared) or any(
                        dd not in (-1, None) and di not in (-1, None)
                        and dd != di
                        for dd, di in zip(declared, inferred)):
                    diags.append(Diagnostic(
                        'warn', 'shape-mismatch',
                        "op %r output %r declared shape %s but the "
                        "registry infers %s"
                        % (op.type, n, declared, inferred),
                        block=block.idx, op_index=i, var=n))
                    continue
                inferred_dt = convert_dtype(np.dtype(sds.dtype).name)
                if v.dtype and inferred_dt != convert_dtype(v.dtype) \
                        and convert_dtype(v.dtype) not in (
                            'int64', 'float64'):  # 32-bit carrier dtypes
                    diags.append(Diagnostic(
                        'warn', 'dtype-mismatch',
                        "op %r output %r declared dtype %s but the "
                        "registry infers %s"
                        % (op.type, n, v.dtype, inferred_dt),
                        block=block.idx, op_index=i, var=n))


def _warn_dead_outputs(program, block, diags, fetch_names=()):
    """Outputs nothing consumes (not fetched, not persistable): often a
    built-but-forgotten metric branch. Warn-level — the executor prunes
    them from the trace anyway."""
    if block.idx != 0:
        return
    consumed = set(fetch_names or ())
    consumed |= set(getattr(program, '_fetch_names', ()) or ())
    for b in program.blocks:
        for op in b.ops:
            consumed |= set(n for n in op.input_arg_names() if n)
    for i, op in enumerate(block.ops):
        if op.type in ('feed', 'fetch'):
            continue
        outs = [n for n in op.output_arg_names() if n]
        if not outs:
            continue
        dead = []
        for n in outs:
            v = block._find_var_recursive(n)
            if v is not None and (v.persistable
                                  or getattr(v, 'is_data', False)):
                break
            if n in consumed:
                break
            dead.append(n)
        else:
            if dead:
                diags.append(Diagnostic(
                    'warn', 'dead-output',
                    "op %r outputs %s are consumed by nothing (not "
                    "fetched, not persistable)" % (op.type, dead),
                    block=block.idx, op_index=i, var=dead[0]))


def _check_rebind_and_dead_persistables(program, diags, feed_names=(),
                                        fetch_names=()):
    """Program-level full checks riding the dataflow engine:

    double-write — two ops bind one name with no read of the first
    binding in between (the first write is dead; usually a forgotten
    rename). Warn: the tracer's rebinding semantics run it fine.

    dead-persistable — a persistable var no op reads or writes and
    nothing fetches: it costs scope memory and checkpoint bytes every
    step for nothing (often a pruned branch's orphaned parameter).
    """
    from .dataflow import DataflowAnalysis
    dfa = DataflowAnalysis(program, feed_names=feed_names,
                           fetch_names=fetch_names)
    for hz in dfa.hazards():
        if hz.code == 'double-write':
            diags.append(Diagnostic('warn', 'double-write', hz.message,
                                    block=0, op_index=hz.op_index,
                                    var=hz.var))
    keep = set(fetch_names or ()) | set(feed_names or ())
    for name in sorted(dfa.persistables):
        if name in dfa.written or name in dfa.uses or name in keep:
            continue
        diags.append(Diagnostic(
            'warn', 'dead-persistable',
            "persistable %r is read and written by no op and never "
            "fetched — it spends scope/checkpoint bytes for nothing"
            % name, block=0, var=name))


@register_pass
class VerifyProgramPass(Pass):
    """Pipeline wrapper: runs verify_program and stores the diagnostics
    in the report; error-level findings raise under PTPU_STRICT_VERIFY=1
    and warn otherwise (the fail-loudly-at-build-time contract)."""

    name = 'verify_program'

    def __init__(self, level='full'):
        self.level = level

    def run_on_program(self, program, ctx, report):
        diags = verify_program(program, feed_names=ctx.feed_names,
                               fetch_names=ctx.fetch_names,
                               level=self.level)
        report.diagnostics.extend(diags)
        report.details['errors'] = sum(1 for d in diags
                                       if d.level == 'error')
        report.details['warnings'] = sum(1 for d in diags
                                         if d.level == 'warn')
        maybe_raise_or_warn(diags)


def strict_verify_enabled():
    import os
    return os.environ.get('PTPU_STRICT_VERIFY', '') == '1'


def maybe_raise_or_warn(diags, warned_key=None, _warned=set()):
    """Shared error policy: strict env raises ProgramVerifyError; default
    emits ONE RuntimeWarning per warned_key (None: always warn)."""
    errs = [d for d in diags if d.level == 'error']
    if not errs:
        return
    if strict_verify_enabled():
        raise ProgramVerifyError(diags)
    if warned_key is not None:
        if warned_key in _warned:
            return
        _warned.add(warned_key)
    import warnings
    head = '; '.join(str(d) for d in errs[:3])
    more = '' if len(errs) <= 3 else ' (+%d more)' % (len(errs) - 3)
    warnings.warn(
        "program verification found %d error(s): %s%s — the trace will "
        "likely fail; set PTPU_STRICT_VERIFY=1 to raise at build time"
        % (len(errs), head, more), RuntimeWarning, stacklevel=3)
