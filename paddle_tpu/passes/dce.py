"""dead_op_elimination: backward liveness from fetch targets + persistables.

The reference prunes through framework/prune.cc (save_inference_model) and
reuses buffers via memory_optimization_transpiler; on TPU XLA owns buffer
reuse, so the payoff here is a smaller traced graph: ops whose outputs can
never reach a fetch target or a persistable write are dropped before the
tracer walks the block (an unfetched metric branch costs trace time and —
under gradient merge — can drag scan intermediates out of the loop).

Liveness is sub-block-aware in both directions: a live control-flow op
keeps every outer var its body reads (closure reads are not listed in
op.inputs), and counts its body's writes as its own (a while carry commits
them to the outer env).

Root selection:
  * fetch targets known (executor/predictor/export): roots = fetches +
    persistables (+ ctx.preserve). Real pruning.
  * unknown (bare memory_optimize on a program with no fetch ops): roots
    additionally include every terminal var a user could still fetch —
    conservative by design; only vars feeding literally nothing die.
"""
from __future__ import annotations

from .base import Pass, register_pass, op_reads, op_writes, sub_block_indices

# ops kept regardless of liveness (host side effects)
_SIDE_EFFECT_OPS = ('print',)


@register_pass
class DeadOpEliminationPass(Pass):
    """keep_persistable_writers=False + feed_fetch='drop' reproduces
    io.prune_program (inference export) semantics; the defaults are the
    training-safe optimization-pipeline mode."""

    name = 'dead_op_elimination'

    def __init__(self, keep_persistable_writers=True, feed_fetch='keep',
                 prune_vars=True):
        if feed_fetch not in ('keep', 'drop'):
            raise ValueError("feed_fetch must be 'keep' or 'drop'")
        self.keep_persistable_writers = keep_persistable_writers
        self.feed_fetch = feed_fetch
        self.prune_vars = prune_vars

    # ------------------------------------------------------------------
    def _roots(self, program, ctx):
        roots = set(ctx.preserve)
        explicit_fetches = ctx.fetch_names is not None
        if explicit_fetches:
            roots |= set(ctx.fetch_names)
        block = program.global_block()
        for op in block.ops:
            if op.type == 'fetch':
                explicit_fetches = True
                roots |= set(n for n in op.input_arg_names() if n)
        fetch_attr = getattr(program, '_fetch_names', None)
        if fetch_attr:
            explicit_fetches = True
            roots |= set(fetch_attr)
        if self.keep_persistable_writers:
            roots |= {v.name for v in program.list_vars() if v.persistable}
        if not explicit_fetches:
            # no fetch info: any terminal var is a potential fetch target
            consumed = set()
            for b in program.blocks:
                for op in b.ops:
                    consumed |= set(n for n in op.input_arg_names() if n)
            for op in block.ops:
                roots |= {n for n in op.output_arg_names()
                          if n and n not in consumed}
        return roots

    def run_on_program(self, program, ctx, report):
        block = program.global_block()
        live = self._roots(program, ctx)
        keep = []
        removed_types = {}
        for op in reversed(block.ops):
            if op.type in ('feed', 'fetch'):
                if self.feed_fetch == 'keep':
                    keep.append(op)
                    if op.type == 'fetch':
                        live |= set(n for n in op.input_arg_names() if n)
                continue
            writes = op_writes(op, program)
            if (op.type in _SIDE_EFFECT_OPS or writes & live):
                keep.append(op)
                live |= op_reads(op, program)
            else:
                removed_types[op.type] = removed_types.get(op.type, 0) + 1
        keep.reverse()
        if len(keep) != len(block.ops):
            block.ops = keep
        report.details['removed_op_types'] = removed_types

        if self.prune_vars:
            self._prune_vars(program, block, ctx, live)

    def _prune_vars(self, program, block, ctx, live):
        """Drop block-0 vars no remaining op touches. Parameters, data
        slots, preserve-set and fetch roots always stay (a pruned program
        must keep its run boundary loadable/feedable)."""
        referenced = set(live) | set(ctx.preserve)
        referenced |= set(ctx.feed_names or ())
        for b in program.blocks:
            for op in b.ops:
                referenced |= set(n for n in op.input_arg_names() if n)
                referenced |= set(n for n in op.output_arg_names() if n)
        dead = [n for n, v in block.vars.items()
                if n not in referenced
                and not v.persistable and not getattr(v, 'is_data', False)]
        for n in dead:
            del block.vars[n]
