"""fuse_activation: merge an elementwise activation into its producer.

The reference ships dedicated fused kernels (fused_elemwise_activation_op,
conv+act fusion through BuildStrategy.fuse_elewise_add_act_ops); here the
fusion is an IR rewrite: the producer op takes a `fuse_act` attr and the
tracer applies the activation's OWN registered lowering to the producer's
primary output inside the same traced expression (core/lowering.py) —
identical math, one fewer op for the tracer/verifier/serializer to walk,
and the pattern every later epilogue-fusion pass (bias+act, residual+act)
builds on.

Fusion fires only when the intermediate is consumed by EXACTLY the
activation op: a training program's grad ops list forward intermediates
among their inputs, so fusion is structurally confined to inference
programs — which is where the inference pipeline runs it.
"""
from __future__ import annotations

from .base import Pass, register_pass, op_reads

# activation op -> nothing (attrs ride along); all single-input/single-
# output elementwise ops whose lowering is a pure function of X + attrs
FUSABLE_ACTS = frozenset((
    'relu', 'relu6', 'sigmoid', 'tanh', 'gelu', 'leaky_relu', 'elu',
    'brelu', 'soft_relu', 'softplus', 'softsign', 'hard_sigmoid',
    'swish',
))

# producer op type -> its primary output slot (int8 producers output
# DEQUANTIZED f32, so an activation fuses into their epilogue exactly as
# into the float form — passes/quantize.py runs before this pass)
FUSABLE_PRODUCERS = {
    'conv2d': 'Output',
    'depthwise_conv2d': 'Output',
    'conv2d_transpose': 'Output',
    'mul': 'Out',
    'matmul': 'Out',
    'elementwise_add': 'Out',
    'conv2d_int8': 'Output',
    'depthwise_conv2d_int8': 'Output',
    'mul_int8': 'Out',
}


@register_pass
class FuseActivationPass(Pass):
    name = 'fuse_activation'

    def run_on_program(self, program, ctx, report):
        block = program.global_block()
        # names the rewrite must leave observable: fetches + anything a
        # caller asked to preserve
        keep_visible = set(ctx.preserve)
        keep_visible |= set(ctx.fetch_names or ())
        keep_visible |= set(getattr(program, '_fetch_names', ()) or ())
        for op in block.ops:
            if op.type == 'fetch':
                keep_visible |= set(op.input_arg_names())

        # consumer counts over the whole program (sub-block closure reads
        # included): fusing away a var someone else reads would break them
        readers = {}
        for b in program.blocks:
            for op in b.ops:
                for n in op_reads(op, program) if b.idx == 0 \
                        else op.input_arg_names():
                    readers[n] = readers.get(n, 0) + 1

        producer_of = {}  # var name -> (op, slot) for fusable producers
        fused = 0
        out_ops = []
        for op in block.ops:
            t = op.type
            if (t in FUSABLE_ACTS and len(op.input_arg_names()) == 1
                    and len(op.output_arg_names()) == 1):
                x = op.input_arg_names()[0]
                hit = producer_of.get(x)
                if hit is not None and self._fusable(block, x, readers,
                                                     keep_visible):
                    prod, slot = hit
                    out_name = op.output_arg_names()[0]
                    prod.outputs[slot] = [out_name]
                    prod.attrs['fuse_act'] = t
                    prod.attrs['fuse_act_slot'] = slot
                    prod.attrs['fuse_act_attrs'] = {
                        k: v for k, v in op.attrs.items()
                        if not k.startswith('_') and k != 'op_role'}
                    if x in block.vars:
                        del block.vars[x]
                    producer_of.pop(x, None)
                    producer_of.pop(out_name, None)
                    fused += 1
                    continue  # drop the activation op
            # any write invalidates a stale producer entry for that name
            for n in op.output_arg_names():
                producer_of.pop(n, None)
            slot = FUSABLE_PRODUCERS.get(t)
            if slot is not None and 'fuse_act' not in op.attrs:
                names = op.outputs.get(slot, [])
                if len(names) == 1 and names[0]:
                    producer_of[names[0]] = (op, slot)
            out_ops.append(op)
        if fused:
            block.ops = out_ops
        report.details['fused'] = fused

    @staticmethod
    def _fusable(block, name, readers, keep_visible):
        if name in keep_visible or readers.get(name, 0) != 1:
            return False
        v = block._find_var_recursive(name)
        if v is None:
            return True
        return not (v.persistable or getattr(v, 'is_data', False))
