"""Program pass & lint subsystem (ref: paddle/fluid/framework/ir/).

The reference rewrites its graph through a registry of C++ IR passes
ordered by build_strategy; here the same layer operates directly on
Program/Block (framework.py is the IR). Five passes ship today:

  verify_program        static lint: undefined inputs, use-before-def,
                        unregistered ops, dangling sub-blocks,
                        unreachable fetch targets, registry shape/dtype
                        consistency (error/warn diagnostics)
  constant_fold         host-evaluate compile-time-constant chains and
                        splice literal vars (IEEE-exact ops only)
  dead_op_elimination   backward liveness from fetch targets +
                        persistables; subsumes io.prune_program
  horizontal_fuse       merge sibling same-input convs (the inception
                        branch pattern) into one wider conv + split,
                        def-use-guarded, reason-coded report
  fuse_activation       merge elementwise activations into conv/mul/
                        elementwise_add producers (tracer applies the
                        act lowering in the same expression)

Alongside the rewriting passes sits the read-only dataflow analysis
engine (dataflow.py, ISSUE 7): def-use chains + last-writer resolution
across sub-blocks, per-var live intervals, alias/in-place hazards, a
bytes-from-shape peak-memory estimator (per program and per export
bucket), and the donation-safety certifier that lets warm-started
cached executables donate state again (PERF_NOTES round 8/10).

Consumers: Executor runs a fast warn-only verify per program epoch
(PTPU_STRICT_VERIFY=1 raises) and certifies donation per run boundary,
CompiledProgram and export_compiled run the optimization pipeline
before lowering, InferenceTranspiler.transpile and memory_optimize are
thin calls into PassManager (memory_optimize returns the liveness
report), tools/program_doctor.py runs the whole suite over the zoo.

    import paddle_tpu as fluid
    prog, reports = fluid.passes.apply_optimization_pipeline(
        main_prog, fetch_names=[loss.name])
    for r in reports:
        print(r)   # PassReport(dead_op_elimination: ops 87->71 ...)
"""
from __future__ import annotations

from .base import (Pass, PassContext, PassManager, PassReport,
                   register_pass, create_pass, get_pass_class,
                   registered_passes)
from .verifier import (VerifyProgramPass, Diagnostic, ProgramVerifyError,
                       verify_program)
from .dce import DeadOpEliminationPass
from .const_fold import ConstantFoldPass
from .fuse_act import FuseActivationPass
from .dataflow import (DataflowAnalysis, DonationCertificate, Hazard,
                       MemoryEstimate, MemoryOptimizeReport,
                       analyze_program, certify_donation, donation_plan,
                       var_bytes)
from .quantize import (CalibrationResult, QuantizeProgramPass,
                       calibrate_program, calibration_targets,
                       quantize_program, quantize_weight)
from .horizontal_fuse import HorizontalFusePass, horizontal_fuse_program
from .recompute import RecomputePass, recompute_program

# constant_fold runs first so dead_op_elimination sweeps the literal
# producers whose consumers folded; fuse_activation last, on the final
# op list. verify_program leads: fail loudly before rewriting garbage.
#
# ORDER NOTE — horizontal_fuse before fuse_activation: widening sibling
# convs first leaves each branch's bias+act epilogue reading its own
# split output, so fuse_activation still folds the per-branch relu into
# the per-branch elementwise_add afterwards (single-reader guard intact).
# Run the other way round, an act already folded INTO a conv would have
# to be part of the widening decision; horizontal_fuse handles that too
# (fuse_act attrs are in its group key — elementwise acts commute with
# the channel concat), but only the fuse-first order can fold the acts
# that live behind the per-branch bias adds. Regression:
# tests/test_horizontal_fuse.py::test_per_branch_act_epilogues_survive.
OPTIMIZATION_PIPELINE = ('verify_program', 'constant_fold',
                         'dead_op_elimination', 'horizontal_fuse',
                         'fuse_activation')

# same ordered passes, but dead-op elimination roots liveness at the
# FETCHES ONLY (keep_persistable_writers=False): an inference program has
# no optimizer, and a train-derived clone handed to the inference
# pipeline sheds its whole training cone (grad ops, optimizer writes) —
# reference InferenceTranspiler semantics. The configured instance sits
# in the tuple so PassManager(INFERENCE_PIPELINE) reproduces exactly
# what apply_inference_pipeline runs.
INFERENCE_PIPELINE = ('verify_program', 'constant_fold',
                      DeadOpEliminationPass(keep_persistable_writers=False),
                      'horizontal_fuse', 'fuse_activation')


def pipeline_names(pipeline):
    """Names of a pipeline's entries (str entries pass through)."""
    return [p if isinstance(p, str) else p.name for p in pipeline]


def _disabled():
    import os
    return os.environ.get('PTPU_DISABLE_PASSES', '') == '1'


def apply_optimization_pipeline(program, fetch_names=None, feed_names=None,
                                inplace=False):
    """Run the standard optimization pipeline; returns (program, reports).
    PTPU_DISABLE_PASSES=1 short-circuits to the input program."""
    if _disabled():
        return program, []
    return PassManager(OPTIMIZATION_PIPELINE).apply(
        program, fetch_names=fetch_names, feed_names=feed_names,
        inplace=inplace)


def apply_inference_pipeline(program, fetch_names=None, feed_names=None,
                             inplace=False):
    """Inference-program variant: liveness roots at the fetches only, so
    a train-derived program sheds grads/optimizer. Do not point this at a
    program you still intend to train."""
    if _disabled():
        return program, []
    return PassManager(INFERENCE_PIPELINE).apply(
        program, fetch_names=fetch_names, feed_names=feed_names,
        inplace=inplace)
