"""Activation rematerialization pass (ISSUE 18 tentpole, IR layer).

Sublinear-memory recompute in the Chen et al. 2016 style, the rewrite
the reference lineage shipped as RecomputeOptimizer: partition the
block-0 forward into contiguous segments, move each segment's ops into
a fresh sub-block, and splice a single ``remat_segment`` op over the
segment's boundary names:

    remat_segment: {X: [seg inputs]} -> {Out: [seg outputs]}  sub_block=k

The tracer lowers ``remat_segment`` by running the sub-block under
``jax.checkpoint`` (ops/control_ops.py), so only the boundary values
survive the forward; when ``append_backward`` later differentiates the
op through the generic vjp path, the interior recomputes inside the
checkpoint's rematerialized trace instead of staying live from forward
to backward. Interior ops move VERBATIM — their ``_op_uid`` attrs (the
rng fold for dropout et al.) are untouched, so recomputed stochastic
ops replay bit-identical draws.

Segment boundaries come from either
  * explicit checkpoints — var names the user handed to
    ``append_backward(checkpoints=...)`` / ``minimize(checkpoints=...)``;
    each checkpoint's def site closes a segment, or
  * auto (√N) selection — K ≈ √M segments over each eligible run of M
    ops, each cut placed inside a ±M/2K window at the program point
    crossed by the fewest live temp bytes (dataflow live intervals).

The pass runs BEFORE backward only (it declines programs that already
contain grad/optimizer ops) and reports at the horizontal_fuse
standard: every ineligible op and rejected segment carries a reason
code, and ``report.details['segments']`` records the applied rewrite.

    from paddle_tpu.passes.recompute import recompute_program
    prog, report = recompute_program(prog, checkpoints='auto',
                                     fetch_names=[loss.name])
    report.details['segments'][0]['interior_bytes']   # bytes freed
"""
from __future__ import annotations

import math
import os

from ..framework import Operator
from .base import Pass, PassManager, register_pass, sub_block_indices
from .dataflow import analyze_program, var_bytes

# -- reason codes (module-level constants: tests & tools key on these) ------
REASON_BACKWARD_PRESENT = 'backward-ops-present'    # program already has
                                                    # grad/optimizer ops
REASON_FEED_FETCH = 'feed-fetch-boundary'           # feed/fetch plumbing op
REASON_SUB_BLOCK = 'sub-block-op'                   # control flow: already
                                                    # owns a sub-block
REASON_UNREGISTERED = 'unregistered-op'             # no lowering rule
REASON_NO_GRAD_OP = 'no-grad-op'                    # metric/decode op: no
                                                    # backward, outputs are
                                                    # fetch targets
REASON_LOD_VAR = 'lod-boundary-var'                 # variable-length value
                                                    # at the op boundary
REASON_HOST_OP = 'host-callback-op'                 # py_func/reader: not
                                                    # replayable in-graph
REASON_SEGMENT_TOO_SMALL = 'segment-too-small'      # fewer ops than min_ops
REASON_SEGMENT_REBINDS = 'segment-rebinds-outer'    # segment rebinds an
                                                    # outer non-persistable
                                                    # name (stale replay
                                                    # hazard at grad time)
REASON_NO_INTERIOR = 'segment-saves-nothing'        # every written name
                                                    # escapes: recompute
                                                    # would free 0 bytes
REASON_CODES = (REASON_BACKWARD_PRESENT, REASON_FEED_FETCH,
                REASON_SUB_BLOCK, REASON_UNREGISTERED, REASON_NO_GRAD_OP,
                REASON_LOD_VAR, REASON_HOST_OP, REASON_SEGMENT_TOO_SMALL,
                REASON_SEGMENT_REBINDS, REASON_NO_INTERIOR)

# ops that punch through to the host or stream data: replaying them inside
# a checkpointed trace would double side effects / reads
_HOST_TYPES = frozenset(('py_func', 'read', 'create_py_reader', 'print',
                         'save', 'load'))
_BOUNDARY_TYPES = frozenset(('feed', 'fetch'))

_OP_ROLE_BACKWARD = 1
_OP_ROLE_OPTIMIZE = 2


def _env_disabled():
    return os.environ.get('PTPU_REMAT', '') == '0'


def _checkpoint_names(checkpoints):
    """Normalize a checkpoints argument to a list of var names."""
    out = []
    for c in checkpoints:
        name = getattr(c, 'name', c)
        if not isinstance(name, str):
            raise TypeError(
                "checkpoints must be Variables or names, got %r" % (c,))
        out.append(name)
    return out


@register_pass
class RecomputePass(Pass):
    """Partition the block-0 forward into remat_segment sub-blocks.

    checkpoints: None/'auto' for √N auto-selection, or a list of var
    names/Variables whose def sites close segments (the reference
    RecomputeOptimizer contract).
    min_ops: smallest segment worth wrapping (a 1-op segment saves
    nothing and costs a checkpoint boundary).
    batch: the -1-dim substitution used when ranking auto cut points by
    crossing bytes (relative ordering is all that matters).
    """

    name = 'recompute'

    def __init__(self, checkpoints=None, min_ops=2, batch=32):
        if checkpoints is None or checkpoints == 'auto':
            self.checkpoints = None
        else:
            self.checkpoints = _checkpoint_names(checkpoints)
        self.min_ops = max(int(min_ops), 1)
        self.batch = max(int(batch), 1)

    # -- eligibility -----------------------------------------------------
    def _op_reason(self, op, program, lod_names):
        from ..core import registry
        if op.type in _BOUNDARY_TYPES:
            return REASON_FEED_FETCH
        if op.type in _HOST_TYPES:
            return REASON_HOST_OP
        if sub_block_indices(op):
            return REASON_SUB_BLOCK
        d = registry.get(op.type)
        if d is None:
            return REASON_UNREGISTERED
        if d.no_grad:
            return REASON_NO_GRAD_OP
        for n in op.input_arg_names() + op.output_arg_names():
            if n in lod_names:
                return REASON_LOD_VAR
        return None

    # -- segmentation ----------------------------------------------------
    def _explicit_cuts(self, dfa, start, end, cps):
        """Cut points inside [start, end]: each checkpoint's def sites
        close the segment containing them (cut AFTER the def)."""
        cuts = set()
        for name in cps:
            for d in dfa.defs.get(name, ()):
                if start <= d < end:
                    cuts.add(d + 1)
        return sorted(cuts)

    def _auto_cuts(self, dfa, start, end, sizes):
        """√N cuts over [start, end]: K ≈ √M segments, each boundary
        slid within ±M/2K to the point crossed by the fewest live temp
        bytes (don't carry a wide activation across a checkpoint when a
        narrow bottleneck sits one op over)."""
        m = end - start + 1
        k = max(1, int(round(math.sqrt(m))))
        if k <= 1:
            return []
        intervals = [(n, s, e) for n, (s, e) in dfa.live_intervals().items()
                     if n not in dfa.persistables and n not in dfa.inputs
                     and sizes.get(n)]

        def crossing(p):       # bytes live across the cut before op p
            return sum(sizes[n] for n, s, e in intervals if s < p <= e)

        window = max(1, m // (2 * k))
        cuts, lo = [], start + 1
        for i in range(1, k):
            target = start + int(round(i * m / float(k)))
            cands = [p for p in range(max(lo, target - window),
                                      min(end, target + window) + 1)]
            if not cands:
                continue
            best = min(cands, key=lambda p: (crossing(p), abs(p - target)))
            cuts.append(best)
            lo = best + 1
        return cuts

    # -- boundary computation --------------------------------------------
    def _segment_io(self, dfa, ops, start, end, live_out):
        """(B_in, B_out, interior_bytes, boundary_bytes, rebinds) of the
        segment ops[start..end]. B_in: names read before any segment-
        internal write. B_out: segment writes read after the segment,
        persistable, or in the live-out set. rebinds: outer-defined
        non-persistable names the segment overwrites (decline those —
        the grad-time replay would read the post-segment binding)."""
        written = set()
        b_in, b_out, rebinds = [], [], []
        sizes = self._sizes_cache
        for i in range(start, end + 1):
            op = ops[i]
            for n in op.input_arg_names():
                if n and n not in written and n not in b_in:
                    b_in.append(n)
            for n in op.output_arg_names():
                if not n:
                    continue
                if n not in written:
                    outer_def = any(d < start for d in dfa.defs.get(n, ()))
                    if (outer_def or n in dfa.inputs) \
                            and n not in dfa.persistables:
                        rebinds.append(n)
                written.add(n)
        for i in range(start, end + 1):
            for n in ops[i].output_arg_names():
                if not n or n in b_out:
                    continue
                reads_after = any(u > end for u in dfa.uses.get(n, ()))
                if reads_after or n in dfa.persistables or n in live_out:
                    b_out.append(n)
        interior = sum(sizes.get(n, 0) for n in written
                       if n not in b_out and n not in dfa.persistables)
        boundary = sum(sizes.get(n, 0) for n in b_out)
        return b_in, b_out, interior, boundary, rebinds

    # -- main ------------------------------------------------------------
    def run_on_program(self, program, ctx, report):
        report.details.update({
            'mode': 'explicit' if self.checkpoints is not None else 'auto',
            'checkpoints': list(self.checkpoints or ()),
            'segments': [], 'skipped': [], 'skip_reasons': {},
            'declined': None,
        })
        if _env_disabled():
            report.details['disabled'] = True
            return

        block = program.global_block()
        ops = list(block.ops)
        skipped = report.details['skipped']
        reasons = report.details['skip_reasons']

        def skip(idx, kind, reason):
            skipped.append({'op_index': idx, 'block': 0, 'type': kind,
                            'reason': reason})
            reasons[reason] = reasons.get(reason, 0) + 1

        for i, op in enumerate(ops):
            role = int(op.attrs.get('op_role', 0) or 0)
            if role & (_OP_ROLE_BACKWARD | _OP_ROLE_OPTIMIZE):
                report.details['declined'] = REASON_BACKWARD_PRESENT
                skip(i, op.type, REASON_BACKWARD_PRESENT)
                return

        dfa = analyze_program(program, feed_names=ctx.feed_names,
                              fetch_names=ctx.fetch_names)
        sizes = {}
        for name, v in dfa.vars.items():
            sizes[name], _ = var_bytes(v, self.batch)
        self._sizes_cache = sizes
        lod_names = {n for n, v in dfa.vars.items()
                     if getattr(v, 'lod_level', 0)}
        live_out = set(ctx.fetch_names or ()) | set(ctx.preserve or ())

        if self.checkpoints is not None:
            known = set(dfa.defs) | set(dfa.vars)
            unknown = [n for n in self.checkpoints if n not in known]
            if unknown:
                raise ValueError(
                    "recompute checkpoints name vars the program never "
                    "defines: %s" % ', '.join(sorted(unknown)))

        # eligible runs: maximal contiguous stretches of wrappable ops
        runs, cur = [], None
        for i, op in enumerate(ops):
            reason = self._op_reason(op, program, lod_names)
            if reason is None:
                cur = [i, i] if cur is None else [cur[0], i]
            else:
                skip(i, op.type, reason)
                if cur is not None:
                    runs.append(tuple(cur))
                    cur = None
        if cur is not None:
            runs.append(tuple(cur))

        # candidate segments per run
        candidates = []
        for (rs, re_) in runs:
            if self.checkpoints is not None:
                cuts = self._explicit_cuts(dfa, rs, re_, self.checkpoints)
                if not cuts and not any(
                        rs <= d <= re_ for n in self.checkpoints
                        for d in dfa.defs.get(n, ())):
                    # run holds no checkpoint at all: leave it alone
                    # (explicit mode only wraps around named boundaries)
                    continue
            else:
                cuts = self._auto_cuts(dfa, rs, re_, sizes)
            bounds = [rs] + cuts + [re_ + 1]
            for s, e in zip(bounds, bounds[1:]):
                if s < e:
                    candidates.append((s, e - 1))

        accepted = []
        for (s, e) in candidates:
            if e - s + 1 < self.min_ops:
                skip(s, 'segment[%d:%d]' % (s, e), REASON_SEGMENT_TOO_SMALL)
                continue
            b_in, b_out, interior, boundary, rebinds = \
                self._segment_io(dfa, ops, s, e, live_out)
            if rebinds:
                skip(s, 'segment[%d:%d]' % (s, e), REASON_SEGMENT_REBINDS)
                continue
            if not b_out or not interior:
                skip(s, 'segment[%d:%d]' % (s, e), REASON_NO_INTERIOR)
                continue
            accepted.append((s, e, b_in, b_out, interior, boundary))

        if not accepted:
            return

        # rewrite: move each segment into a sub-block, splice remat ops
        new_ops, pos = [], 0
        for (s, e, b_in, b_out, interior, boundary) in accepted:
            new_ops.extend(ops[pos:s])
            sub = program._create_block(parent_idx=0)
            program._rollback()
            for op in ops[s:e + 1]:
                op.block = sub
                sub.ops.append(op)
            remat = Operator(block, 'remat_segment',
                             inputs={'X': list(b_in)},
                             outputs={'Out': list(b_out)},
                             attrs={'sub_block': sub.idx, 'op_role': 0})
            new_ops.append(remat)
            pos = e + 1
            report.details['segments'].append({
                'sub_block': sub.idx, 'start': s, 'end': e,
                'n_ops': e - s + 1, 'inputs': list(b_in),
                'outputs': list(b_out), 'interior_bytes': int(interior),
                'boundary_bytes': int(boundary),
            })
        new_ops.extend(ops[pos:])
        block.ops = new_ops
        del self._sizes_cache


def recompute_program(program, checkpoints=None, fetch_names=None,
                      feed_names=None, preserve=(), min_ops=2, batch=32,
                      inplace=False):
    """One-call wrapper: returns (program, PassReport). checkpoints is
    None/'auto' for √N auto-selection or a list of names/Variables."""
    p = RecomputePass(checkpoints=checkpoints, min_ops=min_ops, batch=batch)
    prog, reports = PassManager([p]).apply(
        program, fetch_names=fetch_names, feed_names=feed_names,
        preserve=preserve, inplace=inplace)
    return prog, reports[0]


def apply_recompute_for_backward(program, loss, checkpoints):
    """append_backward's entry: rewrite `program` in place around the
    user's checkpoints (or 'auto') before grad ops are emitted. The
    applied report is stored as program._recompute_report; a checkpoints
    request that applies zero segments warns loudly (it is NOT a silent
    no-op: the report says exactly why each segment was rejected)."""
    fetch = [loss.name] + list(getattr(program, '_fetch_names', ()) or ())
    _, report = recompute_program(program, checkpoints=checkpoints,
                                  fetch_names=fetch, inplace=True)
    program._recompute_report = report
    if not report.details['segments'] \
            and not report.details.get('disabled'):
        import warnings
        warnings.warn(
            "append_backward(checkpoints=...) applied 0 recompute "
            "segments: %s" % (report.details['skip_reasons'] or
                              report.details['declined'],),
            stacklevel=3)
    return report
