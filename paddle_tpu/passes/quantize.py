"""quantize_program: post-training int8 quantization as an IR pass
(ISSUE 11 tentpole).

The reference's inference transpiler grew INT8 calibration after Fluid
1.2 (PAPER.md §6: collect activation ranges over a representative feed,
freeze per-channel int8 weights, emit a dequant-fused program). Here the
same design lands on the pass + dataflow subsystem:

1. **Calibration sweep** (`calibrate_program`): run the inference
   program through the existing Executor over a representative feed and
   observe every quantizable activation edge — abs-max AND per-batch
   percentile statistics per tensor, both recorded so the pass can pick
   either observer (`mode='abs_max' | 'percentile'`).
2. **Rewrite** (`QuantizeProgramPass`, registered as
   'quantize_program'): per-CHANNEL symmetric int8 weight quantization
   for conv2d/depthwise_conv2d/mul (host-side, values from the scope;
   quantized weight + per-channel scales become new persistable vars),
   per-TENSOR activation quant via a `quantize_int8` op placed only on
   SAFE edges — the dataflow engine's def-use chains prove the producer
   binding each consumer sees, so a re-written var never reuses a stale
   quantized copy — and dequant FUSED into the consumer (the int8 ops
   dequantize in their own epilogue; no standalone dequant op remains).
3. **Report**: the PassReport names EVERY op left in float with a
   machine-checkable reason code (REASON_* below) plus the calibrated
   scales, so a serving owner can audit exactly what the quantized tier
   computes. `report.details['float_ops']` is the contract the
   program-doctor baseline and the export signature carry.

Downstream: `inference.export_compiled(quantize='int8')` runs this pass
and writes the quantized bucket tier next to the bf16 one (AOT sidecars
included); the executor serves the quantized program directly too — the
compile-cache fingerprint covers it like any other program (the int8
ops/attrs are part of the serialized desc).
"""
from __future__ import annotations

import numpy as np

from .base import Pass, register_pass, PassManager
from . import dataflow as _dataflow

# ops the pass can quantize, with their (activation slot, weight slot,
# weight flatten attr) — the MXU-bound matmul family (SURVEY.md §2.2)
QUANTIZABLE = {
    'conv2d': ('Input', 'Filter', None),
    'depthwise_conv2d': ('Input', 'Filter', None),
    'mul': ('X', 'Y', 'y_num_col_dims'),
}
_INT8_TYPE = {'conv2d': 'conv2d_int8',
              'depthwise_conv2d': 'depthwise_conv2d_int8',
              'mul': 'mul_int8'}

# machine-checkable reasons an op stayed in float (the report contract)
REASON_OP_TYPE = 'op_type_unsupported'
REASON_SUB_BLOCK = 'sub_block_op'
REASON_NO_CALIBRATION = 'no_calibration'
REASON_ZERO_RANGE = 'zero_activation_range'
REASON_W_NOT_PERSISTABLE = 'weight_not_persistable'
REASON_W_VALUE_MISSING = 'weight_value_missing'
REASON_W_WRITTEN = 'weight_written_in_program'
REASON_LOD_INPUT = 'lod_input'
REASON_NON_FLOAT = 'non_float_dtype'
REASON_USER_SKIP = 'user_skip'

REASON_CODES = (REASON_OP_TYPE, REASON_SUB_BLOCK, REASON_NO_CALIBRATION,
                REASON_ZERO_RANGE, REASON_W_NOT_PERSISTABLE,
                REASON_W_VALUE_MISSING, REASON_W_WRITTEN,
                REASON_LOD_INPUT, REASON_NON_FLOAT, REASON_USER_SKIP)

# ONE symmetric-int8 grid + rounding rule everywhere: the runtime ops
# and the host-side weight quantization below share ops/quant_ops'
# constant and quantize_array, so activation and weight parity cannot
# drift apart by edits to one copy
from ..ops.quant_ops import QMAX as _QMAX, quantize_array as _q_array


class CalibrationResult(object):
    """Per-tensor activation statistics from a calibration sweep:
    `stats[var] = {'abs_max': float, 'percentile': float, 'q': float,
    'batches': int}`. `percentile` is the max over batches of each
    batch's q-th percentile of |x| — the standard clipping observer that
    shrugs off single-element outliers abs-max would chase."""

    def __init__(self, stats=None, q=99.9):
        self.stats = dict(stats or {})
        self.q = float(q)

    def observe(self, name, arr):
        arr = np.abs(np.asarray(arr, np.float64)).reshape(-1)
        if not arr.size:
            return
        ent = self.stats.setdefault(
            name, {'abs_max': 0.0, 'percentile': 0.0, 'q': self.q,
                   'batches': 0})
        ent['abs_max'] = max(ent['abs_max'], float(arr.max()))
        ent['percentile'] = max(ent['percentile'],
                                float(np.percentile(arr, self.q)))
        ent['batches'] += 1

    def scale(self, name, mode='abs_max'):
        """The int8 scale for `name` under `mode`, or None when the var
        was never observed (or observed all-zero). A bad mode fails fast
        even for unobserved vars — a typo must not masquerade as
        'no_calibration'."""
        if mode not in ('abs_max', 'percentile'):
            raise ValueError("quantize mode must be 'abs_max' or "
                             "'percentile', got %r" % (mode,))
        ent = self.stats.get(name)
        if ent is None:
            return None
        r = float(ent[mode])
        # a clipped-to-zero percentile on a nonzero tensor must not
        # produce a degenerate scale: fall back to the abs-max observer
        if r <= 0.0:
            r = float(ent['abs_max'])
        return (r / _QMAX) if r > 0.0 else 0.0

    def as_dict(self):
        return {'q': self.q, 'stats': {k: dict(v)
                                       for k, v in self.stats.items()}}

    @classmethod
    def from_dict(cls, d):
        return cls(d.get('stats'), d.get('q', 99.9))


def calibration_targets(program, quant_ops=None):
    """Activation input names of every block-0 quantizable op (deduped,
    program order): the tensors a calibration sweep must observe."""
    quant_ops = set(quant_ops or QUANTIZABLE)
    block = program.global_block()
    seen, out = set(), []
    for op in block.ops:
        if op.type not in quant_ops:
            continue
        a_slot = QUANTIZABLE[op.type][0]
        names = op.inputs.get(a_slot) or ()
        for n in names:
            v = block._find_var_recursive(n)
            if v is not None and getattr(v, 'persistable', False):
                continue  # constant input: quantized host-side if at all
            if n not in seen:
                seen.add(n)
                out.append(n)
    return out


def calibrate_program(program, feed_batches, executor, scope=None,
                      quant_ops=None, q=99.9):
    """Run the calibration sweep: execute `program` over every feed in
    `feed_batches` (list of feed dicts) through `executor`, fetching the
    quantizable activation edges, and return a CalibrationResult.

    The sweep runs the UNMODIFIED program — observed ranges describe
    exactly the tensors the quantized program will see (PAPER.md §6's
    offline calibration step). `scope` defaults to the executor's global
    scope discipline (pass the predictor's scope when calibrating a
    loaded model)."""
    from ..core.scope import scope_guard
    import contextlib
    targets = calibration_targets(program, quant_ops)
    result = CalibrationResult(q=q)
    if not targets:
        return result
    ctxm = scope_guard(scope) if scope is not None \
        else contextlib.nullcontext()
    with ctxm:
        for feed in feed_batches:
            outs = executor.run(program, feed=dict(feed),
                                fetch_list=list(targets),
                                return_numpy=True)
            for name, val in zip(targets, outs):
                result.observe(name, val)
    return result


def quantize_weight(w, flatten_cols=None):
    """Per-channel symmetric int8 quantization of one weight array.

    conv filters (OIHW, flatten_cols=None): one scale per OUTPUT channel
    (axis 0). mul weights: one scale per output column of the [K, N]
    flattened form (N = prod(shape[flatten_cols:])). Returns (int8 array
    in the ORIGINAL shape, f32 scales [channels]). All-zero channels get
    scale 1.0 (they dequantize to exact zero either way)."""
    w = np.asarray(w, np.float32)
    if flatten_cols is None:
        flat = w.reshape(w.shape[0], -1)        # [O, I*KH*KW]
        absmax = np.abs(flat).max(axis=1)
        scales = np.where(absmax > 0.0, absmax / _QMAX, 1.0)
        q = np.asarray(_q_array(flat, scales[:, None]))
    else:
        lead = int(np.prod(w.shape[:flatten_cols])) if flatten_cols else 1
        flat = w.reshape(lead, -1)              # [K, N]
        absmax = np.abs(flat).max(axis=0)
        scales = np.where(absmax > 0.0, absmax / _QMAX, 1.0)
        q = np.asarray(_q_array(flat, scales[None, :]))
    return q.reshape(w.shape), scales.astype(np.float32)


def _is_float_var(v):
    from ..framework import is_float_dtype
    try:
        return v is not None and is_float_dtype(v.dtype)
    except Exception:
        return False


@register_pass
class QuantizeProgramPass(Pass):
    """Rewrite calibrated conv2d/depthwise_conv2d/mul ops to their int8
    forms. Constructor args:

      calibration   CalibrationResult (or its as_dict) from
                    calibrate_program; None quantizes nothing and
                    reports every candidate as 'no_calibration'.
      scope         Scope holding the weight values (required to
                    quantize anything; new int8 weight + scale vars are
                    written back into it).
      mode          'abs_max' (default) or 'percentile' activation
                    observer.
      skip_vars     activation/weight/output names to keep in float
                    (reported as 'user_skip').
    """

    name = 'quantize_program'

    def __init__(self, calibration=None, scope=None, mode='abs_max',
                 skip_vars=(), quant_ops=None):
        if isinstance(calibration, dict):
            calibration = CalibrationResult.from_dict(calibration)
        self.calibration = calibration
        self.scope = scope
        self.mode = mode
        self.skip_vars = set(skip_vars or ())
        self.quant_ops = set(quant_ops or QUANTIZABLE)

    # -- per-op eligibility -------------------------------------------------
    def _float_reason(self, op, block, dfa, op_idx):
        """None when the op is quantizable right now, else the reason
        code it stays float."""
        if op.type not in QUANTIZABLE or op.type not in self.quant_ops:
            return REASON_OP_TYPE
        a_slot, w_slot, _ = QUANTIZABLE[op.type]
        a_names = op.inputs.get(a_slot) or ()
        w_names = op.inputs.get(w_slot) or ()
        if len(a_names) != 1 or len(w_names) != 1:
            return REASON_OP_TYPE
        x_name, w_name = a_names[0], w_names[0]
        if self.skip_vars & ({x_name, w_name}
                             | set(op.output_arg_names())):
            return REASON_USER_SKIP
        vx = block._find_var_recursive(x_name)
        vw = block._find_var_recursive(w_name)
        if not _is_float_var(vx) or not _is_float_var(vw):
            return REASON_NON_FLOAT
        if int(getattr(vx, 'lod_level', 0) or 0):
            return REASON_LOD_INPUT
        if not getattr(vw, 'persistable', False):
            return REASON_W_NOT_PERSISTABLE
        # def-use: a weight some op WRITES cannot be frozen host-side
        # (its value at this op would differ from the scope snapshot)
        defs, _ = dfa.def_use(w_name)
        if defs:
            return REASON_W_WRITTEN
        if self.scope is None or self.scope.get(w_name) is None:
            return REASON_W_VALUE_MISSING
        if self.calibration is None:
            return REASON_NO_CALIBRATION
        scale = self.calibration.scale(x_name, self.mode)
        if scale is None:
            return REASON_NO_CALIBRATION
        if scale <= 0.0:
            return REASON_ZERO_RANGE
        return None

    # -- the rewrite --------------------------------------------------------
    def run_on_program(self, program, ctx, report):
        from ..framework import Operator
        from ..core.lod import LoDArray

        block = program.global_block()
        dfa = _dataflow.analyze_program(
            program, feed_names=ctx.feed_names, fetch_names=ctx.fetch_names)

        float_ops = []     # every op left in float, with its reason
        act_scales = {}    # activation var -> calibrated scale used
        quantized = 0
        weight_bytes_before = 0
        weight_bytes_after = 0
        # (x_name, def_site) -> quantized var name: the def-use key that
        # makes reuse of a quantized activation SAFE — a consumer after a
        # re-write of x gets a fresh quantize op on the new binding
        q_cache = {}
        # w_name -> {flatten_cols: (wq_name, ws_name)}: a weight SHARED
        # by several quantizable consumers is quantized exactly once per
        # channel axis (bytes counted once per weight); a pathological
        # share across different flatten axes gets one suffixed pair per
        # axis, each also reused by later consumers
        w_done = {}
        new_ops = []

        for idx, op in enumerate(block.ops):
            if op.type in ('feed', 'fetch'):
                new_ops.append(op)
                continue
            reason = self._float_reason(op, block, dfa, idx)
            if reason is not None:
                # only FLOAT-computing ops belong in the kept-in-float
                # report; integer/bookkeeping ops aren't "left in float"
                if any(_is_float_var(block._find_var_recursive(n))
                       for n in op.input_arg_names() + op.output_arg_names()):
                    float_ops.append({'op_index': idx, 'block': 0,
                                      'type': op.type, 'reason': reason})
                new_ops.append(op)
                continue

            a_slot, w_slot, flat_attr = QUANTIZABLE[op.type]
            x_name = op.inputs[a_slot][0]
            w_name = op.inputs[w_slot][0]
            scale = self.calibration.scale(x_name, self.mode)
            act_scales[x_name] = float(scale)

            # -- weight: host-side per-channel quant (once per weight
            # and channel axis) -------------------------------------------
            flatten_cols = (int(op.attrs.get(flat_attr, 1) or 1)
                            if flat_attr else None)
            variants = w_done.setdefault(w_name, {})
            if flatten_cols in variants:
                wq_name, ws_name = variants[flatten_cols]
            else:
                w_val = self.scope.get(w_name)
                w_arr = np.asarray(w_val.data
                                   if isinstance(w_val, LoDArray)
                                   else w_val)
                wq, ws = quantize_weight(w_arr, flatten_cols)
                suffix = '' if not variants else '.f%d' % idx
                wq_name = w_name + '.int8' + suffix
                ws_name = w_name + '.scale' + suffix
                block.create_var(name=wq_name, shape=list(w_arr.shape),
                                 dtype='int8', persistable=True,
                                 stop_gradient=True)
                block.create_var(name=ws_name, shape=[int(ws.shape[0])],
                                 dtype='float32', persistable=True,
                                 stop_gradient=True)
                self.scope.set(wq_name, wq)
                self.scope.set(ws_name, ws)
                if not variants:  # count each weight's bytes ONCE
                    weight_bytes_before += w_arr.nbytes
                variants[flatten_cols] = (wq_name, ws_name)
                weight_bytes_after += wq.nbytes + ws.nbytes

            # -- activation: one quantize_int8 per (var, def site) ----------
            def_site = dfa.last_writer(x_name, before=idx)
            key = (x_name, def_site)
            xq_name = q_cache.get(key)
            if xq_name is None:
                xq_name = x_name + '.q8'
                if block.has_var_local(xq_name):  # rebound upstream var
                    xq_name = '%s.q8.%d' % (x_name, idx)
                vx = block._find_var_recursive(x_name)
                block.create_var(name=xq_name,
                                 shape=list(getattr(vx, 'shape', None)
                                            or []) or None,
                                 dtype='int8', stop_gradient=True)
                new_ops.append(Operator(
                    block, 'quantize_int8', {'X': [x_name]},
                    {'Out': [xq_name]}, {'scale': float(scale)}))
                q_cache[key] = xq_name

            # -- the op itself: int8 form, dequant fused in its epilogue ----
            op.type = _INT8_TYPE[op.type]
            new_inputs = dict(op.inputs)
            new_inputs[a_slot] = [xq_name]
            new_inputs[w_slot] = [wq_name]
            new_inputs['Scale'] = [ws_name]
            op.inputs = new_inputs
            op.attrs['in_scale'] = float(scale)
            new_ops.append(op)
            quantized += 1

        block.ops = new_ops

        # a replaced f32 weight no op touches anymore leaves the PROGRAM
        # (the export must not bake it, the doctor must not count a dead
        # persistable) — its SCOPE value stays untouched: the bf16 tier
        # and the caller's checkpoint still own the float weights
        from .base import op_reads, op_writes
        still_used = set()
        for b in program.blocks:
            for op in b.ops:
                still_used |= op_reads(op, program)
                still_used |= op_writes(op, program)
        pruned = 0
        for w_name in w_done:
            if w_name not in still_used and block.has_var_local(w_name):
                del block.vars[w_name]
                pruned += 1

        # sub-block candidates stay float: the rewrite is block-0-linear
        # (control-flow bodies re-enter per iteration; a stale quantized
        # binding there is not provable safe with linear def-use)
        for b in program.blocks[1:]:
            for idx, op in enumerate(b.ops):
                if op.type in QUANTIZABLE:
                    float_ops.append({'op_index': idx, 'block': b.idx,
                                      'type': op.type,
                                      'reason': REASON_SUB_BLOCK})

        reasons = {}
        for e in float_ops:
            reasons[e['reason']] = reasons.get(e['reason'], 0) + 1
        report.details.update({
            'mode': self.mode,
            'quantized_ops': quantized,
            'float_ops': float_ops,
            'float_op_reasons': reasons,
            'act_scales': {k: round(v, 10) for k, v in act_scales.items()},
            'weight_bytes_before': int(weight_bytes_before),
            'weight_bytes_after': int(weight_bytes_after),
            'float_weights_pruned': pruned,
        })


def quantize_program(program, calibration, scope, mode='abs_max',
                     fetch_names=None, feed_names=None, skip_vars=(),
                     inplace=False):
    """One-call form: apply QuantizeProgramPass and return
    (quantized_program, PassReport). The returned report's
    details['float_ops'] names every op left in float with a
    machine-checkable reason code (REASON_CODES)."""
    p = QuantizeProgramPass(calibration=calibration, scope=scope,
                            mode=mode, skip_vars=skip_vars)
    prog, reports = PassManager([p]).apply(
        program, fetch_names=fetch_names, feed_names=feed_names,
        inplace=inplace)
    return prog, reports[0]
