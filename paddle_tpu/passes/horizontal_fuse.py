"""horizontal_fuse: merge sibling same-input convs into one wider conv.

GoogLeNet's inception block launches several small convolutions off the
SAME tensor (the 1x1 branch-entry convs of `_inception` share input,
kernel geometry, and stride — only the output-channel count differs).
Each one pads its filter bank to the MXU independently, so the model
sits at 0.27 MFU (ROADMAP item 5, PERF_NOTES round 5 verdict). The
reference attacks this class of problem with graph-rewriting IR passes
(paddle/fluid/framework/ir/ fusion passes); here the same rewrite lands
on the Program IR directly:

    conv(x, W1) -> t1   |                           concat(W1..Wn, axis=0)
    conv(x, W2) -> t2   |   becomes    ->  wide conv(x, Wcat) -> tcat
    conv(x, Wn) -> tn   |                  split(tcat, axis=1) -> t1..tn

The split writes the ORIGINAL output names, so every downstream reader
— the per-branch bias/activation epilogues, fetch targets, and training
grad ops — is untouched. Grad ops in particular stay correct without
rewriting: `<type>_grad` is self-contained (backward.py carries
`_fwd_inputs`/`_fwd_outputs` + forward attrs and re-lowers through
jax.vjp), so it only needs the forward input/output NAMES to still hold
the same values at its position — which the split guarantees. That is
what makes this pass safe in the TRAINING pipeline, not just inference.

Safety guards are reaching-definition proofs from the dataflow engine
(dataflow.py), in the same single-reader spirit as `fuse_activation`'s
consumer count and `quantize_program`'s (name, def site) cache keys:

  * group key includes the (input name, def site) pair — two convs
    reading a REBOUND name across a redefinition never merge;
  * a member's output must be defined exactly once and never read
    before the member's own position, so hoisting its definition to the
    group head cannot change any reader's view;
  * filters must be persistable and never written in-program, so the
    filter concat is legal at the group head.

Every conv2d candidate the pass declines is reported with a
machine-checkable reason code (REASON_* below, the `quantize_program`
report contract); `report.details['fused_groups']` names every fusion.

Pipeline order: this pass runs BEFORE fuse_activation — see the note on
OPTIMIZATION_PIPELINE in passes/__init__.py.
"""
from __future__ import annotations

import os

from .base import Pass, register_pass, PassManager
from . import dataflow as _dataflow

# machine-checkable reasons a conv2d candidate was not fused
REASON_GROUPED = 'grouped_conv'
REASON_SUB_BLOCK = 'sub_block_op'
REASON_OP_SHAPE = 'unexpected_op_shape'
REASON_W_NOT_PERSISTABLE = 'filter_not_persistable'
REASON_W_WRITTEN = 'filter_written_in_program'
REASON_W_SHAPE_UNKNOWN = 'filter_shape_unknown'
REASON_NON_FLOAT = 'non_float_dtype'
REASON_LOD_INPUT = 'lod_input'
REASON_OUTPUT_REBOUND = 'output_rebound'
REASON_NO_SIBLING = 'no_sibling'
REASON_USER_SKIP = 'user_skip'

REASON_CODES = (REASON_GROUPED, REASON_SUB_BLOCK, REASON_OP_SHAPE,
                REASON_W_NOT_PERSISTABLE, REASON_W_WRITTEN,
                REASON_W_SHAPE_UNKNOWN, REASON_NON_FLOAT,
                REASON_LOD_INPUT, REASON_OUTPUT_REBOUND,
                REASON_NO_SIBLING, REASON_USER_SKIP)

# the attrs that define conv semantics and must agree across a group;
# anything else (use_cudnn, namescopes) rides along from the first member
_GROUP_ATTRS = ('strides', 'paddings', 'dilations', 'groups',
                'fuse_act', 'fuse_act_slot', 'fuse_act_attrs')


def _is_float_var(v):
    from ..framework import is_float_dtype
    try:
        return v is not None and is_float_dtype(v.dtype)
    except Exception:
        return False


def _env_disabled():
    return os.environ.get('PTPU_HFUSE', '') == '0'


@register_pass
class HorizontalFusePass(Pass):
    """Fuse sibling same-input conv2d ops into one wider conv + split.

    Constructor args:
      skip_vars   input/filter/output names to leave unfused (reported
                  as 'user_skip') — same escape hatch quantize_program
                  gives a serving owner.
      min_group   smallest sibling set worth widening (default 2).

    PTPU_HFUSE=0 disables the rewrite (report carries disabled=True) —
    the A/B switch bench.py's ablation mode flips in one session.
    """

    name = 'horizontal_fuse'

    def __init__(self, skip_vars=(), min_group=2):
        self.skip_vars = set(skip_vars or ())
        self.min_group = int(min_group)

    # -- per-op eligibility -------------------------------------------------
    def _skip_reason(self, op, block, dfa, idx):
        """None when the conv can join a sibling group, else the reason
        code it stays unfused."""
        in_names = op.inputs.get('Input') or ()
        w_names = op.inputs.get('Filter') or ()
        out_names = op.outputs.get('Output') or ()
        if len(in_names) != 1 or len(w_names) != 1 or len(out_names) != 1:
            return REASON_OP_SHAPE
        if int(op.attrs.get('groups', 1) or 1) != 1:
            return REASON_GROUPED
        x_name, w_name, y_name = in_names[0], w_names[0], out_names[0]
        if self.skip_vars & {x_name, w_name, y_name}:
            return REASON_USER_SKIP
        vx = block._find_var_recursive(x_name)
        vw = block._find_var_recursive(w_name)
        vy = block._find_var_recursive(y_name)
        if not (_is_float_var(vx) and _is_float_var(vw)
                and _is_float_var(vy)):
            return REASON_NON_FLOAT
        if int(getattr(vx, 'lod_level', 0) or 0):
            return REASON_LOD_INPUT
        if not getattr(vw, 'persistable', False):
            return REASON_W_NOT_PERSISTABLE
        w_shape = list(getattr(vw, 'shape', None) or ())
        if len(w_shape) != 4 or any(d is None or int(d) <= 0
                                    for d in w_shape):
            return REASON_W_SHAPE_UNKNOWN
        # def-use: hoisting this op's output definition to the group
        # head is only invisible when the name is defined exactly here
        # and nothing reads it earlier
        y_defs, y_uses = dfa.def_use(y_name)
        if y_defs != [idx] or any(u < idx for u in y_uses):
            return REASON_OUTPUT_REBOUND
        return None

    @staticmethod
    def _group_key(op, block, dfa, idx):
        """Two convs with equal keys compute the same function family off
        the same input BINDING (not just the same name): the reaching-def
        site disambiguates rebound names, exactly like quantize_program's
        (x_name, def_site) activation cache."""
        x_name = op.inputs['Input'][0]
        vw = block._find_var_recursive(op.inputs['Filter'][0])
        vy = block._find_var_recursive(op.outputs['Output'][0])
        w_shape = tuple(int(d) for d in vw.shape)
        attrs = tuple((k, repr(op.attrs.get(k))) for k in _GROUP_ATTRS)
        return (x_name, dfa.last_writer(x_name, before=idx),
                w_shape[1:], str(vw.dtype), str(vy.dtype), attrs)

    @staticmethod
    def _filter_stable_runs(members, dfa):
        """Split a sibling group (idx-sorted) into maximal runs whose
        filters all reach the run head unchanged: for every member, the
        reaching definition of its filter at its own position must equal
        the one at the run head, or the concat hoisted there would read
        a different value. Optimizer writes sit AFTER the forward cone,
        so in practice a whole inception group is one run; a program
        that re-writes a filter mid-forward splits here. Yields
        (run, broke) where `broke` marks runs cut by such a write."""
        members = sorted(members, key=lambda m: m[0])
        run, broke = [], False
        for idx, op in members:
            if run:
                head_idx = run[0][0]
                w = op.inputs['Filter'][0]
                if dfa.last_writer(w, before=idx) != \
                        dfa.last_writer(w, before=head_idx):
                    yield run, True
                    run, broke = [], True
            run.append((idx, op))
        if run:
            yield run, broke

    def _widen(self, block, dfa, key, members, head_ops, drop,
               fused_groups):
        """Splice concat(filters) -> wide conv -> split(original names)
        at the first member's position; mark the members for removal."""
        from ..framework import Operator
        first_idx, first = members[0][0], members[0][1]
        w_names = [op.inputs['Filter'][0] for _, op in members]
        y_names = [op.outputs['Output'][0] for _, op in members]
        sections = [int(block._find_var_recursive(w).shape[0])
                    for w in w_names]
        vw0 = block._find_var_recursive(w_names[0])
        vy0 = block._find_var_recursive(y_names[0])
        base = first.outputs['Output'][0]
        wcat = block.create_var(
            name='%s.hfuse_w' % base,
            shape=[sum(sections)] + [int(d) for d in vw0.shape[1:]],
            dtype=vw0.dtype, stop_gradient=True)
        y_shape = list(getattr(vy0, 'shape', None) or ()) or None
        if y_shape and len(y_shape) == 4:
            y_shape = [y_shape[0], sum(sections)] + y_shape[2:]
        ycat = block.create_var(
            name='%s.hfuse_out' % base, shape=y_shape,
            dtype=vy0.dtype, stop_gradient=True)
        attrs = {k: v for k, v in first.attrs.items()
                 if not k.startswith('_')}
        head_ops[first_idx] = [
            Operator(block, 'concat', {'X': list(w_names)},
                     {'Out': [wcat.name]}, {'axis': 0}),
            Operator(block, 'conv2d', {'Input': [key[0]],
                                       'Filter': [wcat.name]},
                     {'Output': [ycat.name]}, attrs),
            Operator(block, 'split', {'X': [ycat.name]},
                     {'Out': list(y_names)},
                     {'axis': 1, 'sections': list(sections)}),
        ]
        drop.update(id(op) for _, op in members)
        fused_groups.append({
            'input': key[0], 'op_indices': [i for i, _ in members],
            'filters': w_names, 'outputs': y_names,
            'out_channels': sections})

    # -- the rewrite --------------------------------------------------------
    def run_on_program(self, program, ctx, report):
        if _env_disabled():
            report.details.update({'disabled': True, 'fused_groups': [],
                                   'skipped': [], 'skip_reasons': {}})
            return

        block = program.global_block()
        dfa = _dataflow.analyze_program(
            program, feed_names=ctx.feed_names, fetch_names=ctx.fetch_names)

        skipped = []            # every conv2d left alone, with its reason
        groups = {}             # group key -> [(idx, op), ...]
        for idx, op in enumerate(block.ops):
            if op.type != 'conv2d':
                continue
            reason = self._skip_reason(op, block, dfa, idx)
            if reason is not None:
                skipped.append({'op_index': idx, 'block': 0,
                                'type': op.type, 'reason': reason})
                continue
            groups.setdefault(
                self._group_key(op, block, dfa, idx), []).append((idx, op))

        fused_groups = []
        head_ops = {}           # first-member idx -> [concat, conv, split]
        drop = set()            # op ids replaced by a widened group
        n_fused = 0
        for key, members in groups.items():
            for sub, broke in self._filter_stable_runs(members, dfa):
                if len(sub) >= self.min_group:
                    self._widen(block, dfa, key, sub, head_ops, drop,
                                fused_groups)
                    n_fused += len(sub)
                    continue
                # a filter written mid-span breaks the hoist (the concat
                # at the run head would read a different value than the
                # member did); everything else is just a lone conv
                reason = REASON_W_WRITTEN if broke else REASON_NO_SIBLING
                for idx, op in sub:
                    skipped.append({'op_index': idx, 'block': 0,
                                    'type': op.type, 'reason': reason})
        if head_ops:
            new_ops = []
            for idx, op in enumerate(block.ops):
                if idx in head_ops:
                    new_ops.extend(head_ops[idx])
                if id(op) not in drop:
                    new_ops.append(op)
            block.ops = new_ops

        # sub-block convs stay put: the rewrite is block-0-linear
        # (control-flow bodies re-enter per iteration — linear def-use
        # cannot prove the hoist safe there), same as quantize_program
        for b in program.blocks[1:]:
            for idx, op in enumerate(b.ops):
                if op.type == 'conv2d':
                    skipped.append({'op_index': idx, 'block': b.idx,
                                    'type': op.type,
                                    'reason': REASON_SUB_BLOCK})

        reasons = {}
        for e in skipped:
            reasons[e['reason']] = reasons.get(e['reason'], 0) + 1
        report.details.update({
            'groups_fused': len(fused_groups),
            'convs_fused': n_fused,
            'fused_groups': fused_groups,
            'skipped': skipped,
            'skip_reasons': reasons,
        })


def horizontal_fuse_program(program, fetch_names=None, feed_names=None,
                            skip_vars=(), inplace=False):
    """One-call form: apply HorizontalFusePass alone and return
    (program, PassReport). details['skipped'] names every conv left
    unfused with a machine-checkable reason code (REASON_CODES)."""
    p = HorizontalFusePass(skip_vars=skip_vars)
    prog, reports = PassManager([p]).apply(
        program, fetch_names=fetch_names, feed_names=feed_names,
        inplace=inplace)
    return prog, reports[0]
