"""Dataflow analysis engine over Program/Block (ISSUE 7 tentpole).

The reference ships real static analyses over ProgramDesc — the
memory-optimization transpiler computes per-var live ranges for buffer
reuse (memory_optimization_transpiler.py:491 ControlFlowGraph) and the
inference analysis pass walks def-use chains. This module is that layer
for the TPU stack: one reusable analysis over a Program that every
consumer shares instead of re-walking blocks ad hoc.

What it computes (all static, no tracing, no device):

  * def-use chains and SSA-style last-writer resolution, sub-block
    aware: control-flow bodies (while/cond/rnn closures) fold into
    their owning op through the shared ``op_reads``/``op_writes``
    closure walk (passes/base.py), and ``last_writer_at`` resolves a
    read site through the block-parent chain the tracer's env scoping
    follows.
  * per-var live intervals over the block-0 linear order — the interval
    XLA's buffer assignment (and the reference's reuse rewrite) roots
    on.
  * alias / in-place hazard analysis: write-after-read rebinds,
    dead double-writes, caller-visible aliased inputs (a name that is
    both fed and persistable state).
  * a bytes-from-shape static peak-memory estimator per program and per
    export batch bucket — the number ROADMAP's pod-scale planning needs
    BEFORE compiling (shard-layout decisions), and the ``peak_bytes_est``
    field bench.py now emits.
  * a donation-safety certifier: the static proof that lets reloaded
    (warm-started) executables donate state buffers again — recovering
    the one-copy-per-step tax PERF_NOTES round 8 recorded when the
    compile cache had to disable donation blind.

Consumers: Executor.run/run_steps (donation certificate for the
compile-cache warm path), transpiler.memory_optimize (liveness report),
tools/program_doctor.py (the CLI over the model zoo), inference/export
(per-bucket peak-bytes in signature.json), bench.py.

    from paddle_tpu.passes import dataflow
    dfa = dataflow.analyze_program(prog, feed_names=['x'],
                                   fetch_names=[loss.name])
    dfa.live_intervals()['fc_0.tmp_0']     # (first def, last use)
    dfa.peak_memory(batch=32).peak_bytes   # static estimate
    cert = dataflow.certify_donation(prog, state_names, feed_names=['x'],
                                     fetch_names=[loss.name])
    cert.safe                              # -> donate on the warm path
"""
from __future__ import annotations

import numpy as np

from ..framework import convert_dtype
from .base import (PassReport as _PassReport, op_reads, op_writes,
                   sub_block_indices)


# ---------------------------------------------------------------------------
# bytes-from-shape
# ---------------------------------------------------------------------------
def dtype_bytes(dtype):
    """Per-element bytes of a declared var dtype (bfloat16-aware); 0 when
    the dtype is absent/unknown (raw/reader vars)."""
    try:
        s = convert_dtype(dtype)
        if s is None:
            return 0
        if s == 'bfloat16':
            return 2
        return int(np.dtype(s).itemsize)
    except Exception:
        return 0


def var_bytes(var, batch=1):
    """(bytes, dynamic) static size of one var: prod(shape) * dtype size,
    with every -1/None dim substituted by `batch`. dynamic=True when a
    substitution happened (the estimate scales with the bucket). Vars
    with no declared shape (readers, raw) estimate 0 bytes."""
    shape = getattr(var, 'shape', None)
    if shape is None:
        return 0, False
    n = 1
    dynamic = False
    for d in shape:
        if d in (-1, None):
            n *= max(int(batch), 1)
            dynamic = True
        else:
            n *= max(int(d), 0)
    return n * dtype_bytes(getattr(var, 'dtype', None)), dynamic


# ---------------------------------------------------------------------------
# findings
# ---------------------------------------------------------------------------
class Hazard(object):
    """One alias/in-place finding. Levels mirror verifier.Diagnostic plus
    'info' for dependence facts that are not defects by themselves (a
    write-after-read rebind is legal in the rebinding IR — it only
    constrains in-place buffer reuse)."""

    __slots__ = ('level', 'code', 'message', 'var', 'op_index')

    def __init__(self, level, code, message, var=None, op_index=-1):
        self.level = level        # 'error' | 'warn' | 'info'
        self.code = code
        self.message = message
        self.var = var
        self.op_index = op_index  # block-0 linear index; -1: program-level

    def as_dict(self):
        return {'level': self.level, 'code': self.code,
                'message': self.message, 'var': self.var,
                'op_index': self.op_index}

    def __repr__(self):
        return "[%s] %s: %s" % (self.level, self.code, self.message)


class MemoryEstimate(object):
    """Static peak-memory estimate of one program at one batch bucket.

    peak_bytes = resident (params + feeds, alive for the whole dispatch)
    + the worst-case sum of temporaries whose live intervals overlap one
    program point. A pure shape/dtype computation — XLA's real assignment
    reuses buffers at finer (SSA-value) granularity and fuses away many
    temporaries, so this is an upper bound on activations and an exact
    count on resident state."""

    __slots__ = ('peak_bytes', 'peak_op_index', 'peak_op_type',
                 'resident_bytes', 'params_bytes', 'feeds_bytes',
                 'temps_peak_bytes', 'temps_total_bytes', 'n_temps',
                 'unknown_shape_vars', 'dynamic_vars', 'batch', 'top',
                 'remat_aware', 'remat_segments', 'remat_interior_bytes')

    def as_dict(self):
        return {k: getattr(self, k) for k in self.__slots__}

    def __repr__(self):
        return ("MemoryEstimate(peak=%s @ op %d %s, resident=%s, "
                "temps_peak=%s, batch=%s)"
                % (_fmt_bytes(self.peak_bytes), self.peak_op_index,
                   self.peak_op_type, _fmt_bytes(self.resident_bytes),
                   _fmt_bytes(self.temps_peak_bytes), self.batch))


def _fmt_bytes(n):
    for unit in ('B', 'KiB', 'MiB', 'GiB'):
        if abs(n) < 1024 or unit == 'GiB':
            return ('%d%s' % (n, unit)) if unit == 'B' \
                else ('%.2f%s' % (n, unit))
        n /= 1024.0
    return str(n)


# ---------------------------------------------------------------------------
# the engine
# ---------------------------------------------------------------------------
class DataflowAnalysis(object):
    """Def-use chains, live intervals, hazards, and memory estimation for
    one Program snapshot. Build once per (program, feed, fetch) boundary
    and query freely — nothing here mutates the program, and every index
    refers to the block-0 linear op order (sub-block work folds into the
    owning control op, exactly how the executor traces)."""

    def __init__(self, program, feed_names=None, fetch_names=None):
        self.program = program
        self.feed_names = list(feed_names if feed_names is not None
                               else getattr(program, '_feed_names', ())
                               or ())
        fetches = list(fetch_names if fetch_names is not None
                       else getattr(program, '_fetch_names', ()) or ())
        for op in program.global_block().ops:
            if op.type == 'fetch':
                fetches.extend(n for n in op.input_arg_names() if n)
            if op.type == 'feed':
                self.feed_names.extend(n for n in op.output_arg_names()
                                       if n)
        self.fetch_names = fetches
        self.ops = list(program.global_block().ops)

        # name -> Variable, block-0 first (outer declarations win, the
        # tracer's recursive-find order)
        self.vars = {}
        for b in program.blocks:
            for n, v in b.vars.items():
                self.vars.setdefault(n, v)

        self.persistables = {v.name for v in program.list_vars()
                             if v.persistable}
        self.inputs = set(self.feed_names) | self.persistables
        for v in program.list_vars():
            if getattr(v, 'is_data', False) \
                    or getattr(v, 'type', 'lod_tensor') != 'lod_tensor':
                self.inputs.add(v.name)

        # block-0 linear def/use chains (closure-folded)
        self.defs = {}   # name -> sorted [op index]
        self.uses = {}   # name -> sorted [op index]
        for i, op in enumerate(self.ops):
            for n in op_reads(op, program):
                self.uses.setdefault(n, []).append(i)
            for n in op_writes(op, program):
                self.defs.setdefault(n, []).append(i)

        # per-block DIRECT def sites + sub-block ownership (last-writer
        # resolution walks these, not the folded view)
        self.block_defs = {}   # (block_idx, name) -> [op index in block]
        self.owner = {}        # sub-block idx -> (owner block idx, op idx)
        for b in program.blocks:
            for i, op in enumerate(b.ops):
                for n in op.output_arg_names():
                    if n:
                        self.block_defs.setdefault((b.idx, n),
                                                   []).append(i)
                for sub in sub_block_indices(op):
                    if 0 < sub < len(program.blocks):
                        self.owner.setdefault(sub, (b.idx, i))

        self.written = set(self.defs)
        self._intervals = None
        self._remat = None

    # -- def-use ---------------------------------------------------------
    def def_use(self, name):
        """(def op indices, use op indices) of `name` in block-0 linear
        order. Empty lists when the program never touches it."""
        return (list(self.defs.get(name, ())),
                list(self.uses.get(name, ())))

    def last_writer(self, name, before=None):
        """Block-0 index of the last op writing `name` strictly before
        op index `before` (None: before program end); -1 when the name
        is a program input with no earlier write, None when undefined."""
        lim = len(self.ops) if before is None else int(before)
        for i in reversed(self.defs.get(name, ())):
            if i < lim:
                return i
        return -1 if name in self.inputs else None

    def last_writer_at(self, block_idx, op_idx, name):
        """SSA-style reaching definition for a READ of `name` by the op
        at (block_idx, op_idx), resolved through the sub-block scope
        chain the tracer's env follows: search this block's earlier ops,
        then hop to the owning control op's position in the parent block
        and continue. Returns (block idx, op idx), -1 for a program
        input binding, or None when nothing defines it (use-before-def
        territory — the verifier's error)."""
        b, lim = int(block_idx), int(op_idx)
        while True:
            for i in reversed(self.block_defs.get((b, name), ())):
                if i < lim:
                    return (b, i)
            if b == 0:
                return -1 if name in self.inputs else None
            if b not in self.owner:
                return None  # orphan block: no scope chain to walk
            b, lim = self.owner[b]
            # a while body may read its own later write via the loop
            # carry; resolving to the owning op itself models that
            owner_op = self.program.block(b).ops[lim]
            if name in op_writes(owner_op, self.program):
                return (b, lim)

    # -- liveness --------------------------------------------------------
    def live_intervals(self):
        """{name: (start, end)} over block-0 op indices: start = first
        def (-1 for program inputs), end = last use, or len(ops) when the
        value must outlive the dispatch (fetch targets, persistables —
        the state the scope commit reads). Names the program never
        touches are absent."""
        if self._intervals is not None:
            return self._intervals
        n_ops = len(self.ops)
        live_out = set(self.fetch_names) | self.persistables
        out = {}
        for name in set(self.defs) | set(self.uses):
            ds, us = self.defs.get(name), self.uses.get(name)
            start = ds[0] if ds else -1
            if name in self.inputs:
                start = -1
            end = us[-1] if us else (ds[-1] if ds else -1)
            if name in live_out:
                end = n_ops
            out[name] = (start, max(start, end))
        self._intervals = out
        return out

    # -- hazards ---------------------------------------------------------
    def hazards(self, feed_names=None, state_names=None):
        """Alias/in-place findings. error: caller-visible aliased input
        (fed name that is also persistable state — the donation killer);
        warn: dead double-write (a binding no op ever reads before the
        next rebind); info: write-after-read rebinds (legal, but they
        pin the order an in-place reuse of that buffer must respect)."""
        feeds = set(self.feed_names if feed_names is None else feed_names)
        state = set(self.persistables if state_names is None
                    else state_names)
        out = []
        for name in sorted(feeds & state):
            out.append(Hazard(
                'error', 'aliased-input',
                "%r is both a feed and persistable state: the caller and "
                "the scope see one buffer, so neither donation nor "
                "in-place update is provably safe" % name, var=name))
        for name, ds in sorted(self.defs.items()):
            if len(ds) < 2:
                continue
            us = self.uses.get(name, ())
            for prev, cur in zip(ds, ds[1:]):
                if cur == prev:
                    continue  # one op writing two slots to one name
                if any(prev < u <= cur for u in us):
                    # the earlier binding was read: a write-after-read
                    # rebind (in-place reuse of the buffer would need
                    # a copy or ordering)
                    out.append(Hazard(
                        'info', 'war',
                        "op %d rebinds %r after op %d read the previous "
                        "binding" % (cur, name,
                                     max(u for u in us
                                         if prev < u <= cur)),
                        var=name, op_index=cur))
                elif self.ops[prev].type == 'remat_segment' \
                        and self.ops[cur].type == 'remat_segment_grad':
                    # a recompute interior: the grad replay re-derives
                    # the forward segment's value by design — the first
                    # write is exactly the one remat chose NOT to keep
                    continue
                else:
                    out.append(Hazard(
                        'warn', 'double-write',
                        "op %d (%s) writes %r but op %d overwrites it "
                        "before any op reads it — the first write is "
                        "dead" % (prev, self.ops[prev].type, name, cur),
                        var=name, op_index=prev))
        return out

    # -- memory ----------------------------------------------------------
    def remat_interiors(self):
        """(n_segments, {interior name}) of the program's recompute
        segments (passes/recompute.py): names a `remat_segment` sub-block
        writes but does NOT expose through its `Out` boundary. The folded
        def/use view charges each of them from the forward op to its grad
        replay — exactly the span rematerialization exists to NOT pay —
        so `peak_memory(remat_aware=True)` converts them to point
        charges at each def/use site instead."""
        if self._remat is not None:
            return self._remat
        n_seg, interiors = 0, set()
        for op in self.ops:
            if op.type != 'remat_segment':
                continue
            n_seg += 1
            sub = int(op.attrs.get('sub_block', -1))
            if not 0 < sub < len(self.program.blocks):
                continue
            outs = set(op.outputs.get('Out', ()))
            for sop in self.program.block(sub).ops:
                for n in op_writes(sop, self.program):
                    if n and n not in outs:
                        interiors.add(n)
        self._remat = (n_seg, interiors)
        return self._remat

    def peak_memory(self, batch=1, top=8, remat_aware=False):
        """Static peak-bytes estimate at one batch bucket (every -1 dim
        substitutes `batch`). Resident = persistables + feed/data vars
        (alive across the whole dispatch); temporaries charge over their
        live interval; peak is the worst program point.

        remat_aware=True models activation recompute: a var interior to a
        `remat_segment` is materialized only WHILE its segment (forward
        or grad replay) runs, so it charges a point interval at each of
        its def/use op indices instead of the fwd..grad span. Without
        segments the two modes agree."""
        batch = max(int(batch), 1)
        est = MemoryEstimate()
        est.batch = batch
        est.unknown_shape_vars = 0
        est.dynamic_vars = 0
        n_ops = len(self.ops)
        sizes = {}
        for name in set(self.defs) | set(self.uses) | self.inputs:
            v = self.vars.get(name)
            if v is None:
                continue
            b, dyn = var_bytes(v, batch)
            sizes[name] = b
            if getattr(v, 'shape', None) is None:
                est.unknown_shape_vars += 1
            if dyn:
                est.dynamic_vars += 1

        est.params_bytes = sum(sizes.get(n, 0) for n in self.persistables)
        feedlike = {n for n in sizes
                    if n not in self.persistables and n in self.inputs}
        est.feeds_bytes = sum(sizes[n] for n in feedlike)
        est.resident_bytes = est.params_bytes + est.feeds_bytes

        n_seg, interiors = self.remat_interiors()
        est.remat_aware = bool(remat_aware)
        est.remat_segments = n_seg
        est.remat_interior_bytes = sum(sizes.get(n, 0) for n in interiors)

        # temporaries: defined by some op, not resident
        delta = [0] * (n_ops + 2)
        temps = []
        for name, (start, end) in self.live_intervals().items():
            if name in self.persistables or name in feedlike:
                continue
            b = sizes.get(name, 0)
            if not b:
                continue
            temps.append((name, b, start, end))
            if remat_aware and name in interiors:
                # alive only while a segment executes: point charges at
                # each touching op, not the fwd..grad span
                for i in sorted(set(self.defs.get(name, ()))
                                | set(self.uses.get(name, ()))):
                    delta[max(i, 0)] += b
                    delta[min(i, n_ops) + 1] -= b
                continue
            delta[max(start, 0)] += b
            delta[min(end, n_ops) + 1] -= b
        est.n_temps = len(temps)
        est.temps_total_bytes = sum(b for _, b, _, _ in temps)

        peak, peak_i, cur = 0, -1, 0
        for i in range(n_ops + 1):
            cur += delta[i]
            if cur > peak:
                peak, peak_i = cur, i
        est.temps_peak_bytes = peak
        est.peak_bytes = est.resident_bytes + peak
        est.peak_op_index = min(peak_i, n_ops - 1) if n_ops else -1
        est.peak_op_type = (self.ops[est.peak_op_index].type
                            if 0 <= est.peak_op_index < n_ops else None)
        alive = [(n, b) for n, b, s, e in temps if s <= peak_i <= e]
        alive.sort(key=lambda kv: (-kv[1], kv[0]))
        est.top = [{'name': n, 'bytes': b} for n, b in alive[:top]]
        return est

    def peak_memory_per_bucket(self, batch_sizes, top=0):
        """{batch: MemoryEstimate} across export buckets — the shard-
        layout planning view (ROADMAP items 2/5): how the static peak
        scales with the served batch."""
        return {int(b): self.peak_memory(batch=b, top=top)
                for b in batch_sizes}

    # -- reuse -----------------------------------------------------------
    def reuse_report(self, batch=1, max_pairs=16):
        """Liveness-based buffer-reuse opportunity (the reference
        memory_optimize rewrite, reported instead of rewritten — XLA owns
        the actual assignment): temporaries whose intervals are disjoint
        can share one buffer, so a perfect reuse allocator needs only
        the interval-overlap peak, not the naive sum."""
        est = self.peak_memory(batch=batch, top=0)
        pairs = []
        by_size = {}
        for name, (s, e) in sorted(self.live_intervals().items()):
            if name in self.persistables or name in self.inputs:
                continue
            b, _ = var_bytes(self.vars[name], batch) \
                if name in self.vars else (0, False)
            if b:
                by_size.setdefault(b, []).append((s, e, name))
        for b, ivs in sorted(by_size.items(), reverse=True):
            ivs.sort()
            for (s1, e1, n1), (s2, e2, n2) in zip(ivs, ivs[1:]):
                if e1 < s2:  # disjoint: n2 could reuse n1's buffer
                    pairs.append({'reuse': n2, 'of': n1, 'bytes': b})
                    if len(pairs) >= max_pairs:
                        break
            if len(pairs) >= max_pairs:
                break
        return {
            'temps_total_bytes': est.temps_total_bytes,
            'temps_peak_bytes': est.temps_peak_bytes,
            'reusable_bytes': max(
                est.temps_total_bytes - est.temps_peak_bytes, 0),
            'n_temps': est.n_temps,
            'pairs': pairs,
        }


def analyze_program(program, feed_names=None, fetch_names=None):
    """Build a DataflowAnalysis (the module's main entry)."""
    return DataflowAnalysis(program, feed_names=feed_names,
                            fetch_names=fetch_names)


class MemoryOptimizeReport(_PassReport):
    """What transpiler.memory_optimize now returns: the dead-op sweep's
    PassReport (isinstance-compatible — consumers keep working) PLUS the
    real liveness story the reference's memory_optimization_transpiler
    printed: per-var live ranges, reuse opportunities, and the static
    peak before/after the sweep."""

    __slots__ = ('live_ranges', 'peak_bytes_before', 'peak_bytes_after',
                 'reuse', 'batch')

    def __init__(self, dce_report, live_ranges, peak_before, peak_after,
                 reuse, batch):
        super().__init__(dce_report.name)
        for k in ('ops_before', 'ops_after', 'ops_added', 'ops_removed',
                  'vars_added', 'vars_removed'):
            setattr(self, k, getattr(dce_report, k))
        self.details = dict(dce_report.details)
        self.diagnostics = list(dce_report.diagnostics)
        self.live_ranges = dict(live_ranges)   # name -> (start, end)
        self.peak_bytes_before = int(peak_before)
        self.peak_bytes_after = int(peak_after)
        self.reuse = dict(reuse)               # dataflow.reuse_report
        self.batch = int(batch)
        self.details['peak_bytes_before'] = self.peak_bytes_before
        self.details['peak_bytes_after'] = self.peak_bytes_after
        self.details['reusable_bytes'] = self.reuse.get('reusable_bytes',
                                                        0)

    def as_dict(self):
        return {'pass': self.name,
                'ops': {'before': self.ops_before, 'after': self.ops_after,
                        'added': self.ops_added,
                        'removed': self.ops_removed},
                'vars': {'added': self.vars_added,
                         'removed': self.vars_removed},
                'details': dict(self.details),
                'diagnostics': [d.as_dict() for d in self.diagnostics],
                'memory': {'batch': self.batch,
                           'peak_bytes_before': self.peak_bytes_before,
                           'peak_bytes_after': self.peak_bytes_after,
                           'live_ranges': {n: list(iv) for n, iv
                                           in self.live_ranges.items()},
                           'reuse': dict(self.reuse)}}

    def __repr__(self):
        return ("MemoryOptimizeReport(ops %d->%d (-%d), peak %s -> %s, "
                "reusable %s, %d live ranges)"
                % (self.ops_before, self.ops_after, self.ops_removed,
                   _fmt_bytes(self.peak_bytes_before),
                   _fmt_bytes(self.peak_bytes_after),
                   _fmt_bytes(self.reuse.get('reusable_bytes', 0)),
                   len(self.live_ranges)))


# ---------------------------------------------------------------------------
# donation-safety certifier
# ---------------------------------------------------------------------------
class DonationCertificate(object):
    """Static proof (or refusal) that the executor's state dict may be
    donated on a RELOADED executable.

    Background (PERF_NOTES round 8): `serialize_executable` preserves
    XLA's input/output aliasing, but after `deserialize_and_load` jax's
    buffer bookkeeping no longer guards the donated args — a reloaded
    donating executable scribbles over any buffer the caller still
    holds. The compile cache therefore disabled donation wholesale,
    paying one extra state copy per step. This certificate restores
    donation exactly when the program's run boundary PROVES the only
    holder of the state buffers is the executor itself, which replaces
    them at scope commit:

      * no donated name is also fed (a fed buffer is caller-visible);
      * no donated name is fetched (the returned array would alias a
        buffer the next dispatch donates);
      * every donated name is persistable (scope-owned, replaced by
        `_finish` — the staged `run_steps` state contract);
      * no error-level alias hazard touches a donated name;
      * never for mesh programs (reload aliasing on composed mesh
        programs measurably produced NaN — round 8).

    `safe` is all-or-nothing: `jit(step, donate_argnums=(0,))` donates
    the whole state pytree, so one unsafe name rejects the plan.
    """

    __slots__ = ('safe', 'donate', 'reasons', 'bytes', 'state_names')

    def __init__(self, safe, donate, reasons, nbytes, state_names):
        self.safe = bool(safe)
        self.donate = tuple(donate)
        self.reasons = list(reasons)
        self.bytes = int(nbytes)
        self.state_names = tuple(state_names)

    def as_dict(self):
        return {'safe': self.safe, 'donate': list(self.donate),
                'bytes': self.bytes, 'reasons': list(self.reasons),
                'state_names': list(self.state_names)}

    def __repr__(self):
        if self.safe:
            return ("DonationCertificate(safe, %d vars, %s)"
                    % (len(self.donate), _fmt_bytes(self.bytes)))
        return ("DonationCertificate(REJECTED: %s)"
                % '; '.join(self.reasons[:3]))


def certify_donation(program, state_names, feed_names=(), fetch_names=(),
                     mesh=False, analysis=None):
    """Certify that donating `state_names` (the executor's state dict)
    stays safe when the compiled step is later RELOADED from the
    persistent cache. Returns a DonationCertificate; `analysis` reuses
    an existing DataflowAnalysis for the same boundary."""
    state = [str(n) for n in state_names]
    feeds = set(feed_names or ())
    fetches = set(fetch_names or ())
    reasons = []
    if mesh:
        reasons.append(
            'mesh-program: jax buffer bookkeeping cannot guard reloaded '
            'aliasing on composed mesh programs (measured NaN, PERF_NOTES '
            'round 8)')
    dfa = analysis
    if dfa is None:
        dfa = DataflowAnalysis(program, feed_names=sorted(feeds),
                               fetch_names=sorted(fetches))
    sset = set(state)
    for name in sorted(sset & feeds):
        reasons.append(
            'caller-visible aliased input: %r is both fed and donated '
            'state' % name)
    for name in sorted(sset & fetches):
        reasons.append(
            'fetch %r would hand the caller an alias of a donated state '
            'buffer' % name)
    for name in sorted(sset - dfa.persistables):
        reasons.append(
            'state %r is not persistable — not scope-owned, so the '
            'executor cannot prove it replaces the only reference' % name)
    for hz in dfa.hazards(feed_names=feeds, state_names=sset):
        if hz.level == 'error' and (hz.var in sset or hz.var is None):
            msg = '%s: %s' % (hz.code, hz.message)
            if msg not in reasons and not any(
                    hz.var and hz.var in r for r in reasons):
                reasons.append(msg)
    nbytes = 0
    for name in state:
        v = dfa.vars.get(name)
        if v is not None:
            nbytes += var_bytes(v, 1)[0]
    safe = not reasons
    return DonationCertificate(safe, state if safe else (), reasons,
                               nbytes, state)


def donation_plan(program, feed_names=None, fetch_names=None,
                  analysis=None):
    """The program_doctor view: certify the program's own run_steps
    boundary (state = persistables the program writes, the
    `_gather_state` contract) and return the certificate."""
    dfa = analysis or DataflowAnalysis(program, feed_names=feed_names,
                                       fetch_names=fetch_names)
    state = sorted(dfa.persistables & dfa.written)
    return certify_donation(program, state, feed_names=dfa.feed_names,
                            fetch_names=dfa.fetch_names, analysis=dfa)
