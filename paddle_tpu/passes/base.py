"""Pass framework over Program/Block — the Fluid IR-pass layer, TPU-native.

The reference rewrites graphs through `paddle/fluid/framework/ir/`
(pass.h:42 Pass::Apply, pass registry via REGISTER_PASS, and
build_strategy.cc assembling ordered pipelines). Here the Program IS the
IR (framework.py), so a Pass mutates a Program in place and the
PassManager owns cloning, ordering, and per-pass accounting:

    new_prog, reports = PassManager(['constant_fold',
                                     'dead_op_elimination']).apply(prog)

Each report records exactly which ops/vars the pass added and removed
(computed by identity diff, so a pass that splices a literal over a
computed op counts as one removed + one added, not zero).
"""
from __future__ import annotations


class PassContext(object):
    """Per-apply() context handed to every pass in the pipeline.

    fetch_names / feed_names: the run boundary, when the caller knows it
    (executor fetch list, predictor signature). None means unknown —
    passes must then stay conservative (dead_op_elimination keeps every
    terminal var a user could still fetch).
    preserve: extra var names a pass must not remove (the reference's
    memory_optimize skip_opt_set).
    """

    def __init__(self, fetch_names=None, feed_names=None, preserve=None):
        self.fetch_names = list(fetch_names) if fetch_names is not None \
            else None
        self.feed_names = list(feed_names) if feed_names is not None else None
        self.preserve = set(preserve or ())


class PassReport(object):
    """What one pass did to one program (ref: the per-pass VLOG counters
    in framework/ir/graph_pattern_detector.cc, made structured)."""

    __slots__ = ('name', 'ops_before', 'ops_after', 'ops_added',
                 'ops_removed', 'vars_added', 'vars_removed', 'details',
                 'diagnostics')

    def __init__(self, name):
        self.name = name
        self.ops_before = 0
        self.ops_after = 0
        self.ops_added = 0
        self.ops_removed = 0
        self.vars_added = 0
        self.vars_removed = 0
        self.details = {}      # pass-specific counters/notes
        self.diagnostics = []  # verifier.Diagnostic entries

    def as_dict(self):
        return {'pass': self.name,
                'ops': {'before': self.ops_before, 'after': self.ops_after,
                        'added': self.ops_added, 'removed': self.ops_removed},
                'vars': {'added': self.vars_added,
                         'removed': self.vars_removed},
                'details': dict(self.details),
                'diagnostics': [d.as_dict() for d in self.diagnostics]}

    def __repr__(self):
        extra = ''
        if self.diagnostics:
            errs = sum(1 for d in self.diagnostics if d.level == 'error')
            extra = ', %d diagnostics (%d errors)' % (len(self.diagnostics),
                                                      errs)
        return ("PassReport(%s: ops %d->%d (+%d/-%d), vars +%d/-%d%s)" %
                (self.name, self.ops_before, self.ops_after, self.ops_added,
                 self.ops_removed, self.vars_added, self.vars_removed, extra))


class Pass(object):
    """Base class: subclass, set `name`, implement run_on_program.

    run_on_program mutates `program` in place; the PassManager handles
    cloning and fills the report's op/var counters afterwards, so a pass
    only records pass-specific numbers in report.details.
    """

    name = None

    def run_on_program(self, program, ctx, report):
        raise NotImplementedError

    def __repr__(self):
        return "<Pass %s>" % (self.name,)


# ---------------------------------------------------------------------------
# registry (ref: framework/ir/pass.h REGISTER_PASS / PassRegistry::Get)
# ---------------------------------------------------------------------------
_PASS_REGISTRY = {}


def register_pass(cls):
    """Class decorator: register a Pass subclass under its `name`."""
    if not getattr(cls, 'name', None):
        raise ValueError("pass class %r must set a `name`" % (cls,))
    _PASS_REGISTRY[cls.name] = cls
    return cls


def get_pass_class(name):
    cls = _PASS_REGISTRY.get(name)
    if cls is None:
        raise KeyError("no pass registered under %r (have: %s)"
                       % (name, ', '.join(registered_passes())))
    return cls


def create_pass(name, **kwargs):
    return get_pass_class(name)(**kwargs)


def registered_passes():
    return sorted(_PASS_REGISTRY)


# ---------------------------------------------------------------------------
# manager
# ---------------------------------------------------------------------------
def _count_ops(program):
    return sum(len(b.ops) for b in program.blocks)


def _op_ids(program):
    return {id(op) for b in program.blocks for op in b.ops}


def _var_keys(program):
    return {(b.idx, n) for b in program.blocks for n in b.vars}


# Program metadata set outside __init__ that clones must inherit: the
# executor reads these off whatever program object it is handed.
_DYNAMIC_PROGRAM_ATTRS = ('_py_readers', '_amp_bf16', '_grad_accum_k',
                          '_feed_names', '_fetch_names')


def _clone_with_metadata(program):
    clone = program.clone()
    for k in _DYNAMIC_PROGRAM_ATTRS:
        if hasattr(program, k) and not hasattr(clone, k):
            setattr(clone, k, getattr(program, k))
    return clone


class PassManager(object):
    """Ordered pipeline runner: resolves names through the registry,
    applies each pass, and returns (program, [PassReport])."""

    def __init__(self, pipeline=None):
        self.passes = []
        for p in (pipeline or ()):
            if isinstance(p, str):
                p = create_pass(p)
            if not isinstance(p, Pass):
                raise TypeError("pipeline entries must be pass names or "
                                "Pass instances, got %r" % (p,))
            self.passes.append(p)

    def pipeline_names(self):
        return [p.name for p in self.passes]

    def apply(self, program, fetch_names=None, feed_names=None,
              preserve=None, inplace=False):
        """Run the pipeline. Returns (new_program, reports); inplace=True
        mutates `program` itself (reference-transpiler semantics) and
        returns it."""
        ctx = PassContext(fetch_names=fetch_names, feed_names=feed_names,
                          preserve=preserve)
        prog = program if inplace else _clone_with_metadata(program)
        reports = []
        from .. import profiler
        for p in self.passes:
            report = PassReport(p.name)
            report.ops_before = _count_ops(prog)
            ids0, vars0 = _op_ids(prog), _var_keys(prog)
            with profiler.record_event('pass/%s' % p.name):
                p.run_on_program(prog, ctx, report)
            report.ops_after = _count_ops(prog)
            ids1, vars1 = _op_ids(prog), _var_keys(prog)
            report.ops_added = len(ids1 - ids0)
            report.ops_removed = len(ids0 - ids1)
            report.vars_added = len(vars1 - vars0)
            report.vars_removed = len(vars0 - vars1)
            reports.append(report)
        # structural mutation: compiled-step caches must not replay
        prog._build_epoch += 1
        return prog, reports


# ---------------------------------------------------------------------------
# shared graph-walk helpers (sub-block-aware read/write sets)
# ---------------------------------------------------------------------------
_SUB_BLOCK_ATTRS = ('sub_block', 'sub_block_false')


def sub_block_indices(op):
    out = []
    for key in _SUB_BLOCK_ATTRS:
        idx = op.attrs.get(key)
        if isinstance(idx, int) and not isinstance(idx, bool):
            out.append(idx)
    return out


def op_reads(op, program, _seen=None):
    """All var names an op may read: declared inputs plus the closure
    reads of its sub-blocks (control-flow bodies read outer vars that are
    NOT listed in op.inputs — the tracer resolves them from env)."""
    names = set(n for n in op.input_arg_names() if n)
    for idx in sub_block_indices(op):
        if idx < 0 or idx >= len(program.blocks):
            continue  # dangling ref: the verifier reports it
        _seen = _seen or set()
        if idx in _seen:
            continue
        _seen.add(idx)
        for sop in program.block(idx).ops:
            names |= op_reads(sop, program, _seen)
    return names


def op_writes(op, program, _seen=None):
    """All var names an op may write, transitively through sub-blocks
    (a while carry commits sub-block writes back to the outer env)."""
    names = set(n for n in op.output_arg_names() if n)
    for idx in sub_block_indices(op):
        if idx < 0 or idx >= len(program.blocks):
            continue
        _seen = _seen or set()
        if idx in _seen:
            continue
        _seen.add(idx)
        for sop in program.block(idx).ops:
            names |= op_writes(sop, program, _seen)
    return names
