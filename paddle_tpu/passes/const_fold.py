"""constant_fold: evaluate compile-time-constant ops on the host and
splice literal vars into the program.

The reference folds through framework/ir/ passes at graph level; here a
folded op is replaced IN PLACE by an `assign_value` literal carrying the
evaluated result, so every consumer (including sub-block closure reads
and the tracer's host-const side channel) sees the identical value.
Running dead_op_elimination afterwards sweeps literal producers whose
only consumers were themselves folded — that is how a fill_constant →
scale → elementwise_add chain nets out to one literal.

Two discipline rules keep folding bit-identical to the traced graph:
  * whitelist only IEEE-exact ops (adds, muls, casts, shapes, slices —
    no transcendentals, no rng, nothing platform-tuned), and
  * evaluate through the op's OWN registered lowering eagerly on the
    host CPU backend, in the op's declared dtypes — the same jnp calls
    the jit trace would record, just executed now.
"""
from __future__ import annotations

import numpy as np

from ..core import registry
from ..framework import convert_dtype
from .base import Pass, register_pass, op_writes

# largest literal worth embedding in the program (elements)
_FOLD_SIZE_LIMIT = 1 << 16

# ops that (a) are deterministic pure functions of inputs+attrs and
# (b) lower to IEEE-exact arithmetic, so a host eval equals the in-graph
# value bitwise on every platform
_FOLDABLE_OPS = frozenset((
    'fill_constant', 'assign_value', 'fill_zeros_like', 'fill_any_like',
    'assign', 'cast', 'scale', 'shape',
    'elementwise_add', 'elementwise_sub', 'elementwise_mul',
    'elementwise_div', 'elementwise_max', 'elementwise_min',
    'elementwise_floordiv', 'elementwise_mod',
    'sum', 'concat', 'stack', 'split',
    'reshape', 'reshape2', 'squeeze', 'squeeze2', 'unsqueeze',
    'unsqueeze2', 'transpose', 'transpose2', 'slice', 'expand',
    'abs', 'floor', 'ceil', 'round', 'sign', 'square', 'sqrt',
    'clip', 'equal', 'not_equal', 'less_than', 'less_equal',
    'greater_than', 'greater_equal', 'logical_not', 'logical_and',
    'logical_or', 'range',
))

# deliberately NOT foldable even though pure: shape depends on a feed
_BATCH_DEPENDENT = frozenset((
    'fill_constant_batch_size_like',
))


class _HostConstShim(object):
    """Stands in for the Tracer during eager eval: some lowerings
    (assign_value) record host constants on ctx.tracer.host_consts."""

    def __init__(self):
        self.host_consts = {}
        self.static_lengths = {}


class _FoldCtx(object):
    """OpCtx lookalike for eager host evaluation of a lowering."""

    def __init__(self, op, block):
        self.op = op
        self.attrs = op.attrs
        self.block = block
        self.abstract = False
        self.tracer = _HostConstShim()

    def attr(self, name, default=None):
        return self.attrs.get(name, default)

    @property
    def is_test(self):
        return bool(self.attrs.get('is_test', False))

    def rng(self):
        raise RuntimeError("constant folding must not evaluate rng ops")

    def var(self, name):
        return self.block._find_var_recursive(name)


def _eval_op(op, block, const_env):
    """Evaluate one whitelisted op on the host cpu backend; returns
    {slot: [np arrays]} or None when evaluation is not possible."""
    import jax
    import jax.numpy as jnp
    try:
        cpu = jax.local_devices(backend='cpu')[0]
    except RuntimeError:
        cpu = None
    ins = {}
    for slot, names in op.inputs.items():
        vals = []
        for n in names:
            if not n:
                vals.append(None)
                continue
            if n not in const_env:
                return None
            vals.append(jnp.asarray(const_env[n]))
        ins[slot] = vals
    d = registry.get(op.type)
    if d is None:
        return None
    ctx = _FoldCtx(op, block)
    try:
        if cpu is not None:
            with jax.default_device(cpu):
                outs = d.lower(ctx, ins)
        else:
            outs = d.lower(ctx, ins)
    except Exception:
        return None
    if not outs:
        return None
    host = {}
    for slot, vals in outs.items():
        if vals is None:
            continue
        host[slot] = [None if v is None else np.asarray(v) for v in vals]
    return host


def _literal_attrs(arr, declared_dtype):
    """assign_value attrs carrying `arr` exactly. Python floats are f64
    (supersets f32/bf16/f16) and python ints are unbounded, so the
    round-trip through the attr list is lossless for every supported
    dtype; None when the dtype has no literal encoding."""
    if arr.size == 0:
        return None  # empty literals have no attr encoding (falsy lists)
    dt = convert_dtype(declared_dtype or arr.dtype.name)
    if dt in ('float16', 'bfloat16', 'float32', 'float64'):
        vals = {'fp32_values': [float(x)
                                for x in np.asarray(arr, np.float64).ravel()]}
    elif dt == 'bool':
        vals = {'int32_values': [int(x) for x in arr.ravel()]}
    elif dt in ('int8', 'uint8', 'int16', 'int32'):
        vals = {'int32_values': [int(x) for x in arr.ravel()]}
    elif dt == 'int64':
        vals = {'int64_values': [int(x) for x in arr.ravel()]}
    else:
        return None
    return {'shape': list(arr.shape), 'dtype': dt, **vals}


@register_pass
class ConstantFoldPass(Pass):
    name = 'constant_fold'

    def run_on_program(self, program, ctx, report):
        block = program.global_block()
        const_env = {}   # var name -> np value
        folded = 0
        i = 0
        while i < len(block.ops):
            op = block.ops[i]
            outs = self._fold_op(block, op, const_env)
            if outs is None:
                # the op recomputes its outputs at runtime: any const
                # recorded under those names (in-place increment, assign-
                # back counters, sub-block writes) is stale from here on
                for n in op_writes(op, block.program):
                    const_env.pop(n, None)
                i += 1
                continue
            for slot, names in op.outputs.items():
                vals = outs.get(slot)
                if vals is None:
                    continue
                for n, v in zip(names, vals):
                    if n and v is not None:
                        const_env[n] = v
            if not op.input_arg_names():
                # fill_constant / assign_value: already a literal — record
                # the value for downstream folds, keep the op as-is
                i += 1
                continue
            n_spliced = self._splice_literals(block, i, op, outs)
            if n_spliced:
                folded += 1
                i += n_spliced
            else:
                i += 1
        report.details['folded_ops'] = folded
        report.details['const_vars'] = len(const_env)

    def _fold_op(self, block, op, const_env):
        """{slot: [np values]} when op is a compile-time constant, else
        None."""
        t = op.type
        if t in _BATCH_DEPENDENT or t not in _FOLDABLE_OPS:
            return None
        ins = [n for n in op.input_arg_names() if n]
        # NOTE deliberately NOT folded: shape(x) of a var whose DECLARED
        # shape is static — the executor is shape-polymorphic (the
        # compile cache keys on actual feed shapes), so declared shapes
        # are documentation, not compile-time constants
        if ins and any(n not in const_env for n in ins):
            return None
        if not ins and t not in ('fill_constant', 'assign_value'):
            return None
        outs = _eval_op(op, block, const_env)
        if outs is None:
            return None
        for vals in outs.values():
            for v in vals:
                if v is not None and v.size > _FOLD_SIZE_LIMIT:
                    return None
        return outs

    @staticmethod
    def _splice_literals(block, i, op, outs):
        """Replace op i with one assign_value literal per output. Returns
        the number of spliced literals, or 0 (op kept) when any consumed
        output has no evaluated value or no literal encoding for its
        dtype."""
        from ..framework import Operator
        lits = []
        evaluated = {}
        for slot, names in op.outputs.items():
            vals = outs.get(slot) or []
            for j, n in enumerate(names):
                if n:
                    evaluated[n] = vals[j] if j < len(vals) else None
        if any(v is None for v in evaluated.values()):
            return 0  # an output the graph may read has no value: keep op
        for n, v in evaluated.items():
            var = block._find_var_recursive(n)
            attrs = _literal_attrs(v, var.dtype if var is not None else None)
            if attrs is None:
                return 0
            attrs['op_role'] = op.attrs.get('op_role', 0)
            lits.append(Operator(block, 'assign_value', {},
                                 {'Out': [n]}, attrs))
        if not lits:
            return 0
        block.ops[i:i + 1] = lits
        return len(lits)
