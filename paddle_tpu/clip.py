"""Gradient clipping as graph ops (ref: python/paddle/fluid/clip.py)."""
from __future__ import annotations

from . import unique_name
from .framework import Parameter, default_main_program
from .backward import OP_ROLE_BACKWARD


class BaseErrorClipAttr(object):
    def _append_clip_op(self, block, grad_name):
        raise NotImplementedError


class ErrorClipByValue(BaseErrorClipAttr):
    def __init__(self, max, min=None):
        max = float(max)
        self.max = max
        self.min = float(min) if min is not None else -max

    def _append_clip_op(self, block, grad_name):
        block.append_op(type='clip', inputs={'X': [grad_name]},
                        outputs={'Out': [grad_name]},
                        attrs={'min': self.min, 'max': self.max,
                               'op_role': OP_ROLE_BACKWARD, '_grad_transform': True}, infer_shape=False)


def error_clip_callback(block, context):
    pass  # error clip hooks run at append_backward time in the reference


class BaseGradientClipAttr(object):
    def _process_context(self, context, param, grad):
        pass

    def _create_operators(self, param, grad):
        raise NotImplementedError


class NullGradientClipAttr(BaseGradientClipAttr):
    def _create_operators(self, param, grad):
        return param, grad


class GradientClipByValue(BaseGradientClipAttr):
    def __init__(self, max, min=None):
        max = float(max)
        self.max = max
        self.min = float(min) if min is not None else -max

    def _create_operators(self, param, grad):
        block = grad.block
        out = block.create_var(dtype=grad.dtype, shape=grad.shape,
                               name=grad.name + '@CLIP')
        block.append_op(type='clip', inputs={'X': [grad.name]},
                        outputs={'Out': [out.name]},
                        attrs={'min': self.min, 'max': self.max,
                               'op_role': OP_ROLE_BACKWARD, '_grad_transform': True}, infer_shape=False)
        return param, out


class GradientClipByNorm(BaseGradientClipAttr):
    def __init__(self, clip_norm):
        self.clip_norm = float(clip_norm)

    def _create_operators(self, param, grad):
        block = grad.block
        out = block.create_var(dtype=grad.dtype, shape=grad.shape,
                               name=grad.name + '@CLIP')
        block.append_op(type='clip_by_norm', inputs={'X': [grad.name]},
                        outputs={'Out': [out.name]},
                        attrs={'max_norm': self.clip_norm,
                               'op_role': OP_ROLE_BACKWARD, '_grad_transform': True}, infer_shape=False)
        return param, out


class GradientClipByGlobalNorm(BaseGradientClipAttr):
    """sqrt(sum over all grads) global rescale (ref clip.py
    GradientClipByGlobalNorm)."""

    def __init__(self, clip_norm, group_name="default_group"):
        self.clip_norm = float(clip_norm)
        self.group_name = group_name

    def _process_context(self, context, param, grad):
        if self.group_name not in context:
            context[self.group_name] = []
        block = grad.block
        sq = block.create_var(dtype=grad.dtype, shape=())
        block.append_op(type='squared_l2_norm', inputs={'X': [grad.name]},
                        outputs={'Out': [sq.name]},
                        attrs={'op_role': OP_ROLE_BACKWARD, '_grad_transform': True}, infer_shape=False)
        context[self.group_name].append(sq)
        self.context = context

    def _create_operators(self, param, grad):
        block = grad.block
        group = self.context[self.group_name]
        scale_key = self.group_name + '@SCALE'
        if scale_key not in self.context:
            gsum = block.create_var(dtype=grad.dtype, shape=())
            block.append_op(type='sum', inputs={'X': [v.name for v in group]},
                            outputs={'Out': [gsum.name]},
                            attrs={'op_role': OP_ROLE_BACKWARD, '_grad_transform': True},
                            infer_shape=False)
            gnorm = block.create_var(dtype=grad.dtype, shape=())
            block.append_op(type='sqrt', inputs={'X': [gsum.name]},
                            outputs={'Out': [gnorm.name]},
                            attrs={'op_role': OP_ROLE_BACKWARD, '_grad_transform': True},
                            infer_shape=False)
            scale = block.create_var(dtype=grad.dtype, shape=(),
                                     name=unique_name.generate(
                                         self.group_name + '@SCALE'))
            block.append_op(type='global_norm_scale',
                            inputs={'Norm': [gnorm.name]},
                            outputs={'Out': [scale.name]},
                            attrs={'clip_norm': self.clip_norm,
                                   'op_role': OP_ROLE_BACKWARD, '_grad_transform': True},
                            infer_shape=False)
            self.context[scale_key] = scale.name
        out = block.create_var(dtype=grad.dtype, shape=grad.shape,
                               name=grad.name + '@CLIP')
        block.append_op(
            type='elementwise_mul',
            inputs={'X': [grad.name], 'Y': [self.context[scale_key]]},
            outputs={'Out': [out.name]},
            attrs={'axis': -1, 'op_role': OP_ROLE_BACKWARD, '_grad_transform': True}, infer_shape=False)
        return param, out


def set_gradient_clip(clip, param_list=None, program=None):
    program = program or default_main_program()
    if param_list is None:
        param_list = program.global_block().all_parameters()
    param_list = [program.global_block().var(p) if isinstance(p, str) else p
                  for p in param_list]
    for param in param_list:
        param.gradient_clip_attr = clip


def append_gradient_clip_ops(param_grads):
    context = {}
    clips = []
    for p, g in param_grads:
        if g is None:
            continue
        clip_attr = getattr(p, 'gradient_clip_attr', None) or NullGradientClipAttr()
        clip_attr._process_context(context=context, param=p, grad=g)
        clips.append(clip_attr)
    res = []
    for (p, g), clip_attr in zip([pg for pg in param_grads if pg[1] is not None],
                                 clips):
        res.append(clip_attr._create_operators(param=p, grad=g))
    res.extend([(p, g) for p, g in param_grads if g is None])
    return res
