"""Parameter initializers (ref: python/paddle/fluid/initializer.py).

As in the reference, an initializer appends an init op to the STARTUP
program; running the startup program materializes parameters on device.
"""
from __future__ import annotations

import math

import numpy as np

from . import framework


class Initializer(object):
    def __call__(self, var, block):
        raise NotImplementedError


class ConstantInitializer(Initializer):
    def __init__(self, value=0.0, force_cpu=False):
        self.value = value

    def __call__(self, var, block):
        return block.append_op(
            type='fill_constant', outputs={'Out': [var.name]},
            attrs={'shape': list(var.shape), 'dtype': var.dtype,
                   'value': float(self.value)}, infer_shape=False)


class UniformInitializer(Initializer):
    def __init__(self, low=-1.0, high=1.0, seed=0):
        self.low, self.high, self.seed = low, high, seed

    def __call__(self, var, block):
        return block.append_op(
            type='uniform_random', outputs={'Out': [var.name]},
            attrs={'shape': list(var.shape), 'dtype': var.dtype,
                   'min': self.low, 'max': self.high, 'seed': self.seed},
            infer_shape=False)


class NormalInitializer(Initializer):
    def __init__(self, loc=0.0, scale=1.0, seed=0):
        self.loc, self.scale, self.seed = loc, scale, seed

    def __call__(self, var, block):
        return block.append_op(
            type='gaussian_random', outputs={'Out': [var.name]},
            attrs={'shape': list(var.shape), 'dtype': var.dtype,
                   'mean': self.loc, 'std': self.scale, 'seed': self.seed},
            infer_shape=False)


class TruncatedNormalInitializer(Initializer):
    def __init__(self, loc=0.0, scale=1.0, seed=0):
        self.loc, self.scale, self.seed = loc, scale, seed

    def __call__(self, var, block):
        return block.append_op(
            type='truncated_gaussian_random', outputs={'Out': [var.name]},
            attrs={'shape': list(var.shape), 'dtype': var.dtype,
                   'mean': self.loc, 'std': self.scale, 'seed': self.seed},
            infer_shape=False)


def _fan_in_out(var):
    shape = var.shape
    if len(shape) < 2:
        return int(shape[0]) if shape else 1, int(shape[0]) if shape else 1
    receptive = int(np.prod(shape[2:])) if len(shape) > 2 else 1
    fan_in = int(shape[1]) * receptive
    fan_out = int(shape[0]) * receptive
    # fc weights are [in, out]
    if len(shape) == 2:
        fan_in, fan_out = int(shape[0]), int(shape[1])
    return fan_in, fan_out


class XavierInitializer(Initializer):
    def __init__(self, uniform=True, fan_in=None, fan_out=None, seed=0):
        self.uniform, self.fan_in, self.fan_out, self.seed = (
            uniform, fan_in, fan_out, seed)

    def __call__(self, var, block):
        fi, fo = _fan_in_out(var)
        fi = self.fan_in if self.fan_in is not None else fi
        fo = self.fan_out if self.fan_out is not None else fo
        if self.uniform:
            limit = math.sqrt(6.0 / (fi + fo))
            return UniformInitializer(-limit, limit, self.seed)(var, block)
        std = math.sqrt(2.0 / (fi + fo))
        return NormalInitializer(0.0, std, self.seed)(var, block)


class MSRAInitializer(Initializer):
    def __init__(self, uniform=True, fan_in=None, seed=0):
        self.uniform, self.fan_in, self.seed = uniform, fan_in, seed

    def __call__(self, var, block):
        fi, _ = _fan_in_out(var)
        fi = self.fan_in if self.fan_in is not None else fi
        if self.uniform:
            limit = math.sqrt(6.0 / fi)
            return UniformInitializer(-limit, limit, self.seed)(var, block)
        std = math.sqrt(2.0 / fi)
        return NormalInitializer(0.0, std, self.seed)(var, block)


class BilinearInitializer(Initializer):
    """For conv-transpose upsampling kernels (ref initializer.py Bilinear)."""

    def __call__(self, var, block):
        shape = var.shape
        if len(shape) != 4:
            raise ValueError("BilinearInitializer needs a 4-D weight")
        c, k, h, w = shape
        f = np.ceil(w / 2.0)
        cc = (2 * f - 1 - f % 2) / (2.0 * f)
        weight = np.zeros(shape, dtype='float32')
        for i in range(np.prod(shape[2:])):
            x, y = i % w, i // w
            v = (1 - abs(x / f - cc)) * (1 - abs(y / f - cc))
            weight[:, :, y, x] = v
        return NumpyArrayInitializer(weight)(var, block)


class NumpyArrayInitializer(Initializer):
    def __init__(self, value):
        self.value = np.asarray(value)

    def __call__(self, var, block):
        vals = self.value.reshape(-1)
        if self.value.dtype in (np.int32, np.int64):
            attr = {'int32_values': [int(v) for v in vals]}
        else:
            attr = {'fp32_values': [float(v) for v in vals]}
        return block.append_op(
            type='assign_value', outputs={'Out': [var.name]},
            attrs={'shape': list(self.value.shape), 'dtype': var.dtype, **attr},
            infer_shape=False)


# reference-compatible aliases
Constant = ConstantInitializer
Uniform = UniformInitializer
Normal = NormalInitializer
TruncatedNormal = TruncatedNormalInitializer
Xavier = XavierInitializer
MSRA = MSRAInitializer
Bilinear = BilinearInitializer

_global_weight_initializer = None
_global_bias_initializer = None


def force_init_on_cpu():
    return False


def init_on_cpu():
    import contextlib

    @contextlib.contextmanager
    def _noop():
        yield
    return _noop()
