"""Multi-step dispatch smoke for CI (ISSUE 2): on CPU,

1. SmallNet, K=4: run_steps through a prefetch_to_device ring must track
   8 sequential single-step run() calls step for step (losses AND
   params). Tolerance note: XLA:CPU compiles CONV kernels inside while
   bodies through a different code path than at top level, so conv
   models match to ~1e-6 relative on CPU rather than bit-for-bit;
   matmul-based models ARE bit-identical (tests/test_multi_step.py
   asserts exact equality across dropout/momentum/grad-merge nets).
2. fc proxy, K=16: same-session dispatch-rate A/B must improve >= 3x —
   the CPU dispatch-overhead proxy for the tunnel-floor amortization
   (smallnet itself is NOT used for the CPU speedup check: XLA:CPU runs
   conv scan bodies ~10x slower than at top level, PERF_NOTES round 6;
   on the accelerator the conv model amortizes like any other).

Exits non-zero on any violation. Runtime: ~30 s on 2 CPU cores.
"""
import json
import os
import sys
import time

os.environ.setdefault('JAX_PLATFORMS', 'cpu')
os.environ.setdefault('PTPU_PLATFORM', 'cpu')
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def smallnet_bit_identity():
    import paddle_tpu as fluid
    from paddle_tpu import unique_name
    from models.smallnet import build_train_net

    batch, k, steps = 8, 4, 8
    rng = np.random.RandomState(0)
    xs = [rng.randn(batch, 3, 32, 32).astype(np.float32)
          for _ in range(steps)]
    labs = [rng.randint(0, 10, (batch, 1)) for _ in range(steps)]

    def build():
        with unique_name.guard():
            main_p, startup_p = fluid.Program(), fluid.Program()
            main_p.random_seed = startup_p.random_seed = 7
            with fluid.program_guard(main_p, startup_p):
                _img, _lab, loss, _acc = build_train_net()
        return main_p, startup_p, loss

    main_p, startup_p, loss = build()
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.core.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup_p)
        seq = [np.asarray(exe.run(main_p,
                                  feed={'data': xs[i], 'label': labs[i]},
                                  fetch_list=[loss])[0]).reshape(-1)
               for i in range(steps)]
        p_seq = {v.name: np.asarray(scope.get(v.name)).copy()
                 for v in main_p.list_vars() if v.persistable
                 and scope.get(v.name) is not None}

    main_p, startup_p, loss = build()
    reader = None
    with fluid.program_guard(main_p, startup_p):
        pass
    from paddle_tpu.reader.pipeline import PyReader
    dvars = [main_p.global_block().var('data'),
             main_p.global_block().var('label')]
    reader = PyReader(dvars, capacity=4).prefetch_to_device(k)
    reader.decorate_tensor_provider(lambda: iter(
        [{'data': x, 'label': l} for x, l in zip(xs, labs)]))
    exe2 = fluid.Executor(fluid.CPUPlace())
    scope2 = fluid.core.Scope()
    multi = []
    with fluid.scope_guard(scope2):
        exe2.run(startup_p)
        reader.start()
        for _ in range(steps // k):
            out, = exe2.run_steps(main_p, reader=reader, fetch_list=[loss],
                                  steps=k, fetch_policy='stack')
            multi.extend(np.asarray(out).reshape(k, -1))
        reader.reset()
        p_multi = {v.name: np.asarray(scope2.get(v.name)).copy()
                   for v in main_p.list_vars() if v.persistable
                   and scope2.get(v.name) is not None}

    for i, (s, m) in enumerate(zip(seq, multi)):
        if not np.allclose(s, m, rtol=1e-5, atol=1e-6):
            raise SystemExit('smallnet K=%d step %d loss mismatch: %r vs %r'
                             % (k, i, s, m))
    if set(p_seq) != set(p_multi):
        raise SystemExit('smallnet K=%d persistable name sets differ' % k)
    for name in p_seq:
        if not np.allclose(p_seq[name], p_multi[name],
                           rtol=1e-4, atol=2e-5):
            raise SystemExit(
                'smallnet K=%d persistable %r mismatch (max abs diff %g)'
                % (k, name, np.abs(p_seq[name] - p_multi[name]).max()))
    return {'smoke': 'smallnet_bit_identity', 'k': k, 'steps': steps,
            'ok': True}


def fc_dispatch_ab():
    import paddle_tpu as fluid
    import jax.numpy as jnp

    main_p, startup_p = fluid.Program(), fluid.Program()
    with fluid.program_guard(main_p, startup_p):
        x = fluid.layers.data(name='x', shape=[64], dtype='float32')
        lab = fluid.layers.data(name='lab', shape=[1], dtype='int64')
        h = fluid.layers.fc(x, size=128, act='relu')
        loss = fluid.layers.mean(fluid.layers.softmax_with_cross_entropy(
            logits=fluid.layers.fc(h, 10), label=lab))
        fluid.optimizer.SGD(0.1).minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup_p)
    rng = np.random.RandomState(0)
    feed = {'x': jnp.asarray(rng.randn(32, 64), jnp.float32),
            'lab': jnp.asarray(rng.randint(0, 10, (32, 1)), jnp.int32)}
    k = 16
    stacked = {n: jnp.stack([v] * k) for n, v in feed.items()}

    for _ in range(4):
        out = exe.run(main_p, feed=feed, fetch_list=[loss],
                      return_numpy=False)
    np.asarray(out[0])
    t0 = time.perf_counter()
    n = 60
    for _ in range(n):
        out = exe.run(main_p, feed=feed, fetch_list=[loss],
                      return_numpy=False)
    np.asarray(out[0])
    single_ms = (time.perf_counter() - t0) / n * 1e3

    for _ in range(2):
        out = exe.run_steps(main_p, feed=stacked, fetch_list=[loss],
                            steps=k, return_numpy=False)
    np.asarray(out[0])
    t0 = time.perf_counter()
    d = 10
    for _ in range(d):
        out = exe.run_steps(main_p, feed=stacked, fetch_list=[loss],
                            steps=k, return_numpy=False)
    np.asarray(out[0])
    multi_ms = (time.perf_counter() - t0) / (d * k) * 1e3

    speedup = single_ms / multi_ms
    line = {'smoke': 'fc_dispatch_ab', 'k': k,
            'single_ms_step': round(single_ms, 3),
            'multi_ms_step': round(multi_ms, 3),
            'speedup': round(speedup, 2)}
    if speedup < 3.0:
        line['ok'] = False
        print(json.dumps(line))
        raise SystemExit(
            'multi-step dispatch speedup %.2fx < 3x acceptance floor'
            % speedup)
    line['ok'] = True
    return line


def main():
    print(json.dumps(smallnet_bit_identity()), flush=True)
    print(json.dumps(fc_dispatch_ab()), flush=True)
    print('multi-step smoke OK')
    return 0


if __name__ == '__main__':
    sys.exit(main())
