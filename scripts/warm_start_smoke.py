#!/usr/bin/env python
"""Warm-start smoke (ISSUE 5, wired into scripts/ci.sh): cold A/B warm in
FRESH subprocesses against a tmp cache dir.

Serving half (the acceptance bar): export a 3-bucket artifact WITHOUT
sidecars, measure a cold replica (load + first answer per bucket =
3 XLA compiles), prewarm it with `tools/cache_ctl.py prewarm`, then
measure a warm replica — which must perform ZERO XLA compiles, answer
with byte-identical fetches, and cut the cold-start wall time >= 3x.

Executor half: tests/compile_cache_worker.py twice against one
PTPU_COMPILE_CACHE dir — run 2 must hit the executable tier for every
entry (zero compiles) with byte-identical fetches.

Also exercises cache_ctl stats/prune/prewarm exit codes.
"""
import json
import os
import shutil
import subprocess
import sys
import tempfile

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

MIN_SPEEDUP = float(os.environ.get('PTPU_WARM_START_MIN_SPEEDUP', '3'))

# a fresh serving replica, framework-free (serve.py by path): loads every
# bucket of the artifact and answers one request per bucket; prints wall
# time (post-import, the compile-dominated cold-start cost) and the net
# XLA compile count
PROBE = r'''
import json, sys, time
import numpy as np
sys.path.insert(0, sys.argv[3])
from jax._src import monitoring
n = [0, 0]
monitoring.register_event_duration_secs_listener(
    lambda ev, s, **kw: n.__setitem__(0, n[0] + 1)
    if ev == '/jax/core/compile/backend_compile_duration' else None)
monitoring.register_event_listener(
    lambda ev, **kw: n.__setitem__(1, n[1] + 1)
    if ev == '/jax/compilation_cache/cache_hits' else None)
import serve
art, out_path = sys.argv[1], sys.argv[2]
t0 = time.perf_counter()
with open(art + '/signature.json') as f:
    buckets = json.load(f)['buckets']
outs = {}
for b in buckets:
    pred = serve.CompiledPredictor(art + '/' + serve._BUCKET_DIR % b)
    feed = {e['name']: np.ones(e['shape'], dtype=np.dtype(e['dtype']))
            for e in pred._sig['feeds']}
    outs['b%d' % b] = np.asarray(pred.run(feed)[0])
wall = time.perf_counter() - t0
assert not any(m.startswith('paddle_tpu') for m in sys.modules)
np.savez(out_path, **outs)
print('PROBE ' + json.dumps({'wall_s': round(wall, 4),
                             'xla_compiles_net': n[0] - n[1]}))
'''


def run(cmd, env_extra=None, tag=''):
    env = dict(os.environ)
    env.update(env_extra or {})
    p = subprocess.run(cmd, capture_output=True, text=True, env=env,
                       timeout=900)
    if p.returncode != 0:
        print(p.stdout)
        print(p.stderr, file=sys.stderr)
        raise SystemExit('%s failed (exit %d)' % (tag or cmd[0],
                                                  p.returncode))
    return p.stdout


def parse(stdout, marker):
    line = [l for l in stdout.splitlines() if l.startswith(marker)][0]
    return json.loads(line[len(marker):])


def main():
    import numpy as np
    os.environ.setdefault('JAX_PLATFORMS', 'cpu')
    os.environ.setdefault('PTPU_PLATFORM', 'cpu')
    tmp = tempfile.mkdtemp(prefix='ptpu_warm_smoke_')
    art = os.path.join(tmp, 'artifact')
    cache = os.path.join(tmp, 'cache')
    ctl = os.path.join(REPO, 'tools', 'cache_ctl.py')
    try:
        # -- build + export the 3-bucket artifact, NO sidecars (cold) ----
        import paddle_tpu as fluid
        from paddle_tpu.inference import (Config, create_predictor,
                                          export_compiled)
        main_p, startup = fluid.Program(), fluid.Program()
        main_p.random_seed = startup.random_seed = 21
        with fluid.program_guard(main_p, startup):
            # deep enough that the cold path's 3 bucket compiles dominate
            # the measurement (the warm path's cost is load-only and does
            # not grow with model size — the smoke's >=3x margin widens
            # with depth)
            x = fluid.layers.data(name='x', shape=[64], dtype='float32')
            h = fluid.layers.fc(x, size=1024, act='relu')
            h = fluid.layers.fc(h, size=1024, act='relu')
            h = fluid.layers.fc(h, size=1024, act='relu')
            out = fluid.layers.fc(h, size=16, act='softmax')
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        model_dir = os.path.join(tmp, 'model')
        fluid.io.save_inference_model(model_dir, ['x'], [out], exe, main_p)
        cfg = Config(model_dir)
        cfg.disable_gpu()
        pred = create_predictor(cfg)
        export_compiled(pred, {'x': np.ones((32, 64), np.float32)},
                        art, batch_sizes=[8, 16, 32], precompile=False)

        inference_dir = os.path.join(REPO, 'paddle_tpu', 'inference')
        probe = [sys.executable, '-c', PROBE]

        # -- cold replica -----------------------------------------------
        cold = parse(run(probe + [art, os.path.join(tmp, 'cold.npz'),
                                  inference_dir], tag='cold probe'),
                     'PROBE ')
        assert cold['xla_compiles_net'] > 0, \
            'cold replica performed no compiles?! %r' % cold

        # -- prewarm via the CLI, then the warm replica ------------------
        run([sys.executable, ctl, 'prewarm', art], tag='cache_ctl prewarm')
        warm = parse(run(probe + [art, os.path.join(tmp, 'warm.npz'),
                                  inference_dir], tag='warm probe'),
                     'PROBE ')
        assert warm['xla_compiles_net'] == 0, \
            'warm replica still compiled: %r' % warm
        with np.load(os.path.join(tmp, 'cold.npz')) as a, \
                np.load(os.path.join(tmp, 'warm.npz')) as b:
            for k in a.files:
                assert a[k].tobytes() == b[k].tobytes(), \
                    'fetch %s differs cold vs warm' % k
        speedup = cold['wall_s'] / max(warm['wall_s'], 1e-9)
        print('artifact cold-start: cold=%.3fs (%d compiles)  '
              'warm=%.3fs (0 compiles)  speedup=%.1fx'
              % (cold['wall_s'], cold['xla_compiles_net'], warm['wall_s'],
                 speedup))
        assert speedup >= MIN_SPEEDUP, \
            'warm start must cut artifact cold-start wall time >= %.1fx, ' \
            'got %.2fx' % (MIN_SPEEDUP, speedup)

        # -- executor warm start through the persistent cache ------------
        worker = os.path.join(REPO, 'tests', 'compile_cache_worker.py')
        c = parse(run([sys.executable, worker, cache,
                       os.path.join(tmp, 'exe_cold.npz')],
                      tag='executor cold'), 'CC_STATS ')
        w = parse(run([sys.executable, worker, cache,
                       os.path.join(tmp, 'exe_warm.npz')],
                      tag='executor warm'), 'CC_STATS ')
        assert c['misses'] >= 3 and c['compiles'] == c['misses'], c
        assert w['misses'] == 0 and w['compiles'] == 0, w
        assert w['xla_compiles_net'] == 0, w
        with np.load(os.path.join(tmp, 'exe_cold.npz')) as a, \
                np.load(os.path.join(tmp, 'exe_warm.npz')) as b:
            for k in a.files:
                assert a[k].tobytes() == b[k].tobytes(), k
        print('executor warm start: cold=%.2fs (%d compiles, %.2fs '
              'compiling)  warm=%.2fs (0 compiles, %d exec hits)'
              % (c['wall_s'], c['compiles'], c['compile_s'], w['wall_s'],
                 w['exec_hits']))

        # -- cache_ctl exit codes ---------------------------------------
        run([sys.executable, ctl, 'stats', '--dir', cache],
            tag='cache_ctl stats')
        run([sys.executable, ctl, 'prune', '--dir', cache, '--all'],
            tag='cache_ctl prune')
        rc = subprocess.run([sys.executable, ctl, 'prewarm',
                             os.path.join(tmp, 'missing')],
                            capture_output=True).returncode
        assert rc == 2, 'prewarm on a missing dir must exit 2, got %d' % rc
        print('WARM_START_SMOKE_OK speedup=%.1fx' % speedup)
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


if __name__ == '__main__':
    main()
