"""Elastic pod resize smoke (ISSUE 14, wired into ci.sh).

1. An uninterrupted 4-host composed-mesh reference run over the sharded
   data plane (dp spans hosts x mp within; exactly-once chunk journal):
   losses replicated across hosts, per-step record sets recorded.
2. The same pod killed MID-EPOCH at a committed boundary (victim waits
   for POD_COMMIT, survivors exit through the heartbeat watchdog).
3. Resume on 2 hosts AND on 8 hosts (fresh copies of the checkpoint
   dir): topology-change restore reshards the stitched global state to
   each new mesh, the data journal re-strides onto the new host count —
   loss trajectory within float-accumulation tolerance of the
   reference, per-step record SETS identical, every epoch's sample
   accounting exactly-once (digest over the effective history).
4. Same-shape (4-host) resume stays on the bit-exact fast path: ZERO
   resharding programs, losses and final params digest BIT-match the
   reference.
5. tools/chaos.py --pod 2 --resize round (randomized kill/resize).

Bounded wall time: the whole smoke must finish inside BUDGET_S.
"""
import importlib.util
import os
import shutil
import signal
import subprocess
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

_spec = importlib.util.spec_from_file_location(
    'ptpu_chaos', os.path.join(REPO, 'tools', 'chaos.py'))
chaos = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(chaos)

BUDGET_S = 900.0
TOTAL, EVERY, KILL_AT = 12, 2, 6       # 4 steps/epoch: step 6 is mid-epoch
T_START = time.time()

# the 8-host arm runs 8 gloo processes on a 2-core CI box: a first-step
# XLA compile can hold a worker's GIL long enough to starve its
# heartbeat thread past the default 8s and false-positive the watchdog.
# Detection latency is pod_ft_smoke's metric, not this smoke's — give
# liveness room to breathe under 4x oversubscription.
os.environ.setdefault('PTPU_POD_HB_TIMEOUT', '25')


def main():
    work = tempfile.mkdtemp(prefix='ptpu-elastic-smoke-')
    cache = os.path.join(work, 'compile-cache')
    data = os.path.join(work, 'data.rio')
    ckpt = os.path.join(work, 'ckpts')

    def fail(msg):
        print('[elastic-smoke] FAIL: %s (workdir kept at %s)'
              % (msg, work))
        return 1

    outs = lambda tag, n: [os.path.join(work, '%s-r%d.txt' % (tag, r))  # noqa: E731,E501
                           for r in range(n)]

    r = subprocess.run([sys.executable, chaos.ELASTIC_WORKER,
                        '--make-data', data, '64'], capture_output=True,
                       text=True, cwd=REPO, timeout=240)
    if r.returncode != 0:
        return fail('dataset build failed:\n%s' % r.stderr[-1500:])
    dataset = [l.strip() for l in open(data + '.hashes') if l.strip()]

    # 1) uninterrupted 4-host reference
    t0 = time.time()
    res = chaos.run_pod(os.path.join(work, 'ref-ck'), outs('ref', 4),
                        TOTAL, EVERY, cache_dir=cache, timeout=400,
                        worker=chaos.ELASTIC_WORKER, data_file=data)
    if any(rc != 0 for rc, _ in res):
        return fail('reference run failed:\n%s'
                    % '\n'.join(e[-1200:] for _, e in res))
    refs = [chaos.read_elastic_out(p) for p in outs('ref', 4)]
    for i in range(1, 4):
        if refs[i]['losses'] != refs[0]['losses']:
            return fail('reference: replicated losses differ between '
                        'hosts 0 and %d' % i)
    failures = []
    _collect = lambda msg: (failures.append(msg), 1)[1]  # noqa: E731
    _err, ref_recs = chaos.merge_pod_recs(refs, _collect)
    if failures:
        return fail(failures[0])
    print('[elastic-smoke] reference: 4 hosts, %d steps, %d records/'
          'epoch  %.1fs' % (len(refs[0]['losses']), 64,
                            time.time() - t0))

    # 2) kill the 4-host pod mid-epoch at a committed boundary
    t0 = time.time()
    res = chaos.run_pod(ckpt, outs('kill', 4), TOTAL, EVERY,
                        kill_rank=2, kill_at=KILL_AT, cache_dir=cache,
                        timeout=400, worker=chaos.ELASTIC_WORKER,
                        data_file=data)
    if res[2][0] != -signal.SIGKILL:
        return fail('victim exited %s, expected SIGKILL' % res[2][0])
    if any('WEDGED' in err for _, err in res):
        return fail('a survivor never detected the dead host')
    killed = [chaos.read_elastic_out(p) for p in outs('kill', 4)]
    print('[elastic-smoke] kill: victim h2 SIGKILLed at the committed '
          'step-%d boundary (mid-epoch), survivors exited in bounded '
          'time  %.1fs' % (KILL_AT, time.time() - t0))

    # 3) resume the SAME checkpoint on 2 and on 8 hosts. The 8-host and
    # same-shape arms run from COPIES; the 2-host arm then runs from a
    # MOVE of the original tree — proving a relocated checkpoint dir
    # (original path gone, journals carried inside the tree) still
    # re-strides and resumes.
    arms = {8: os.path.join(work, 'ck-resume-8'),
            4: os.path.join(work, 'ck-resume-same'),
            2: os.path.join(work, 'ck-resume-2')}
    shutil.copytree(ckpt, arms[8])
    shutil.copytree(ckpt, arms[4])
    shutil.move(ckpt, arms[2])
    table = []
    for new_n in (2, 8):
        arm = arms[new_n]
        t0 = time.time()
        res = chaos.run_pod(arm, outs('re%d' % new_n, new_n), TOTAL,
                            EVERY, cache_dir=cache, timeout=500,
                            worker=chaos.ELASTIC_WORKER, data_file=data)
        wall = time.time() - t0
        if any(rc != 0 for rc, _ in res):
            return fail('resume on %d hosts failed:\n%s'
                        % (new_n, '\n'.join(e[-1200:] for _, e in res)))
        resumed = [chaos.read_elastic_out(p)
                   for p in outs('re%d' % new_n, new_n)]
        resume_at = resumed[0]['resume']
        for i, o in enumerate(resumed):
            if o['resume'] != resume_at or not resume_at \
                    or resume_at > KILL_AT:
                return fail('resume@%d host %d resumed at %s'
                            % (new_n, i, o['resume']))
            if o['topo'] != (4, new_n):
                return fail('resume@%d host %d topo %r' % (new_n, i,
                                                           o['topo']))
            if o['reshard'][0] < 1 or o['restride'] is None:
                return fail('resume@%d host %d: reshard/restride did '
                            'not engage (%r/%r)'
                            % (new_n, i, o['reshard'], o['restride']))
        err = chaos.check_resize_round(
            refs[0]['losses'], ref_recs, killed, resumed, resume_at,
            TOTAL, dataset, _collect, 'resume@%d' % new_n)
        if err is not None or failures:
            return fail(failures[0] if failures else 'resume@%d' % new_n)
        rs = resumed[0]['reshard']
        table.append((new_n, resume_at, rs[1], rs[2], rs[3],
                      resumed[0]['losses'][resume_at], wall))
        print('[elastic-smoke] resume on %d hosts: committed step %d, '
              'reshard %d arrays (stitch %.0f ms, place %.0f ms), loss '
              'parity within tolerance, epochs exactly-once  %.1fs'
              % (new_n, resume_at, rs[1], rs[2] * 1e3, rs[3] * 1e3,
                 wall))

    # 4) same-shape resume stays bit-exact with ZERO resharding programs
    # (also from a relocated copy: the original tree moved away above)
    t0 = time.time()
    res = chaos.run_pod(arms[4], outs('re4', 4), TOTAL, EVERY,
                        cache_dir=cache, timeout=400,
                        worker=chaos.ELASTIC_WORKER, data_file=data)
    if any(rc != 0 for rc, _ in res):
        return fail('same-shape resume failed:\n%s'
                    % '\n'.join(e[-1200:] for _, e in res))
    fins = [chaos.read_elastic_out(p) for p in outs('re4', 4)]
    for i, o in enumerate(fins):
        if o['topo'] != (4, 4):
            return fail('same-shape host %d topo %r' % (i, o['topo']))
        if o['reshard'][0] != 0 or o['reshard'][1] != 0:
            return fail('same-shape resume compiled %d resharding '
                        'program(s) — the fast path regressed'
                        % o['reshard'][0])
        for s, v in o['losses'].items():
            if v != refs[0]['losses'].get(s):
                return fail('same-shape host %d: loss at step %d not '
                            'BIT-equal after resume' % (i, s))
        if o['sha'] != refs[i]['sha']:
            return fail('same-shape host %d: params digest diverged' % i)
    print('[elastic-smoke] same-shape resume: bit-exact fast path, 0 '
          'resharding programs, params digest matches the reference  '
          '%.1fs' % (time.time() - t0))

    # 5) randomized chaos resize round
    rc = chaos.main(['--pod', '2', '--resize', '--rounds', '1',
                     '--total', '12', '--every', '2', '--seed', '14',
                     '--resize-counts', '1,2,4'])
    if rc != 0:
        return fail('chaos --resize exited %d' % rc)

    wall = time.time() - T_START
    if wall > BUDGET_S:
        return fail('smoke exceeded its wall-time budget: %.0fs > %.0fs'
                    % (wall, BUDGET_S))
    print('[elastic-smoke] resharding cost table '
          '(hosts, resume_step, arrays, stitch_s, place_s, '
          'first_loss, wall_s):')
    for row in table:
        print('[elastic-smoke]   %r' % (row,))
    shutil.rmtree(work, ignore_errors=True)
    print('[elastic-smoke] OK (%.0fs total)' % wall)
    return 0


if __name__ == '__main__':
    sys.exit(main())
