"""Bulk-inference loop smoke for CI (ISSUE 3), mirroring
multi_step_smoke.py: on CPU,

1. fc artifact, K=8: CompiledPredictor.run_batches must match 8
   sequential run() calls BIT FOR BIT (matmul model — XLA compiles
   matmul scan bodies identically to top-level code; conv models round
   to ~1e-6 on XLA:CPU, PERF_NOTES.md).
2. fc artifact, K=32: same-session dispatch-rate A/B — per-batch time
   through ONE run_batches(K) dispatch must beat sequential run() calls
   by >= 3x. This is the CPU dispatch-overhead proxy for the ~200 ms
   tunnel floor (only the per-call host cost is amortizable on CPU);
   through the tunnel the same mechanism amortizes the full floor.

Exits non-zero on any violation. Runtime: ~15 s on 2 CPU cores.
"""
import json
import os
import sys
import tempfile
import time

os.environ.setdefault('JAX_PLATFORMS', 'cpu')
os.environ.setdefault('PTPU_PLATFORM', 'cpu')
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def _export_fc_artifact(art_dir):
    import paddle_tpu as fluid
    from paddle_tpu.inference import Config, create_predictor, export_compiled

    model_dir = os.path.join(os.path.dirname(art_dir), 'model')
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 7
    with fluid.program_guard(main, startup):
        x = fluid.layers.data(name='x', shape=[64], dtype='float32')
        h = fluid.layers.fc(x, 128, act='relu')
        out = fluid.layers.fc(h, 10, act='softmax')
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    fluid.io.save_inference_model(model_dir, ['x'], [out], exe, main)
    cfg = Config(model_dir)
    cfg.disable_gpu()
    pred = create_predictor(cfg)
    sample = np.random.RandomState(0).randn(32, 64).astype(np.float32)
    export_compiled(pred, [sample], art_dir)
    return sample


def bit_identity(served, sample):
    rng = np.random.RandomState(1)
    xs = [rng.randn(*sample.shape).astype(np.float32) for _ in range(8)]
    seq = [served.run([x])[0] for x in xs]
    bulk = served.run_batches([[x] for x in xs])
    for i, (s, b) in enumerate(zip(seq, bulk)):
        if not np.array_equal(s, b[0]):
            raise SystemExit(
                'run_batches batch %d mismatch: max abs diff %g'
                % (i, np.abs(s - b[0]).max()))
    return {'smoke': 'run_batches_bit_identity', 'k': len(xs), 'ok': True}


def dispatch_ab(served, sample, attempts=2):
    """Best-of-N same-session A/B (a cold first jit-dispatch or a loaded
    CI host can depress one round; the floor is 3x with ~4x typical)."""
    k = 32
    batches = [[sample]] * k
    served.run([sample])        # warm the single-batch executable
    served.run_batches(batches)  # warm the K-group executable
    best = None
    for _ in range(attempts):
        t0 = time.perf_counter()
        n = 60
        for _ in range(n):
            served.run([sample])
        seq_ms = (time.perf_counter() - t0) / n * 1e3

        t0 = time.perf_counter()
        d = 6
        for _ in range(d):
            served.run_batches(batches)
        bulk_ms = (time.perf_counter() - t0) / (d * k) * 1e3
        if best is None or seq_ms / bulk_ms > best[0]:
            best = (seq_ms / bulk_ms, seq_ms, bulk_ms)
    speedup, seq_ms, bulk_ms = best
    line = {'smoke': 'infer_loop_dispatch_ab', 'k': k,
            'seq_ms_batch': round(seq_ms, 3),
            'bulk_ms_batch': round(bulk_ms, 3),
            'speedup': round(speedup, 2)}
    if speedup < 3.0:
        line['ok'] = False
        print(json.dumps(line))
        raise SystemExit(
            'bulk-inference dispatch speedup %.2fx < 3x acceptance floor'
            % speedup)
    line['ok'] = True
    return line


def main():
    from paddle_tpu.inference import load_compiled
    with tempfile.TemporaryDirectory() as d:
        art = os.path.join(d, 'artifact')
        sample = _export_fc_artifact(art)
        served = load_compiled(art)
        print(json.dumps(bit_identity(served, sample)), flush=True)
        print(json.dumps(dispatch_ab(served, sample)), flush=True)
        print(json.dumps({'smoke': 'bulk_stats',
                          **served.bulk_stats()}), flush=True)
    print('infer loop smoke OK')
    return 0


if __name__ == '__main__':
    sys.exit(main())
