#!/usr/bin/env python
"""Smoke the `serve.py bench` CLI on a tiny multi-bucket artifact
(ISSUE 1 CI satellite): build a small model, export batch buckets {1, 4},
then drive the dynamic batcher from a fresh framework-free process.

    python scripts/serve_bench_smoke.py

Exits non-zero if the bench fails or reports no throughput.
"""
import json
import os
import subprocess
import sys
import tempfile

os.environ.setdefault('JAX_PLATFORMS', 'cpu')
os.environ.setdefault('PTPU_PLATFORM', 'cpu')

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import numpy as np  # noqa: E402

import paddle_tpu as fluid  # noqa: E402
from paddle_tpu.inference import (Config, create_predictor,  # noqa: E402
                                  export_compiled)


def main():
    main_p, startup_p = fluid.Program(), fluid.Program()
    with fluid.program_guard(main_p, startup_p):
        img = fluid.layers.data(name='img', shape=[16], dtype='float32')
        out = fluid.layers.fc(fluid.layers.fc(img, 32, act='relu'), 4,
                              act='softmax')
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup_p)
    with tempfile.TemporaryDirectory() as d:
        model_dir = os.path.join(d, 'model')
        art_dir = os.path.join(d, 'artifact')
        fluid.io.save_inference_model(model_dir, ['img'], [out], exe,
                                      main_p)
        cfg = Config(model_dir)
        cfg.disable_gpu()
        pred = create_predictor(cfg)
        sample = np.random.RandomState(0).randn(4, 16).astype(np.float32)
        export_compiled(pred, [sample], art_dir, batch_sizes=[1, 4])

        in_path = os.path.join(d, 'in.npz')
        np.savez(in_path, img=sample[:1])
        serve_py = os.path.join(REPO, 'paddle_tpu', 'inference', 'serve.py')
        r = subprocess.run(
            [sys.executable, serve_py, 'bench', art_dir, in_path, '16'],
            capture_output=True, text=True, timeout=600)
        sys.stdout.write(r.stdout)
        sys.stderr.write(r.stderr)
        if r.returncode != 0:
            return r.returncode
        stats = json.loads(
            [l for l in r.stdout.splitlines() if l.strip()][-1])
        if stats['req_s'] <= 0:
            print('serve.py bench reported no throughput', file=sys.stderr)
            return 1
    print('serve bench smoke OK (%.0f req/s, p99 %.2f ms)'
          % (stats['req_s'], stats['p99_ms']))
    return 0


if __name__ == '__main__':
    sys.exit(main())
