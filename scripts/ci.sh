#!/usr/bin/env bash
# CI entry (ref: paddle/scripts/paddle_build.sh) — build native components,
# run the test suite on the 8-device virtual CPU mesh, gate the public API
# surface, and smoke the benchmark in a tiny configuration.
set -euo pipefail
cd "$(dirname "$0")/.."

echo "== native components =="
make -C paddle_tpu/native

echo "== api surface =="
python tools/print_signatures.py --check API.spec

echo "== program lint over models/ (passes verifier; errors fail the build) =="
JAX_PLATFORMS=cpu PTPU_PLATFORM=cpu python tools/program_lint.py --models

echo "== program doctor over models/ (dataflow engine: liveness, hazards, peak-bytes, donation plan; any NEW hazard vs the checked-in baseline fails) =="
JAX_PLATFORMS=cpu PTPU_PLATFORM=cpu PTPU_STRICT_VERIFY=1 \
python tools/program_doctor.py --models --check-baseline tools/doctor_baseline.json

echo "== tests (8-device virtual cpu mesh, tier-1: not slow) =="
# tier-1 includes tests/test_multi_step.py (K-step dispatch bit-identity)
# and the prefetch-ring units in test_data_pipeline.py; the threaded ring
# stress variant is slow-marked and runs in the slow tier below
python -m pytest tests/ -q -m 'not slow'

echo "== multi-step dispatch smoke (CPU, K=4 smallnet + fc dispatch A/B) =="
PTPU_PLATFORM=cpu python scripts/multi_step_smoke.py

echo "== bulk-inference loop smoke (CPU, run_batches bit-identity + >=3x dispatch A/B) =="
PTPU_PLATFORM=cpu python scripts/infer_loop_smoke.py

echo "== mfu pass smoke (googlenet horizontal_fuse + stacked-LSTM fuse_layers A/B in one session: numeric parity asserted; CPU speedups emitted, not asserted — the MXU-padding/scan-dispatch wins are TPU-only, PERF_NOTES round 18) =="
JAX_PLATFORMS=cpu PTPU_PLATFORM=cpu python scripts/mfu_smoke.py

echo "== warm-start smoke (persistent compile cache: cold A/B warm in fresh processes, >=3x artifact cold-start cut, cache_ctl stats/prune/prewarm) =="
JAX_PLATFORMS=cpu PTPU_PLATFORM=cpu python scripts/warm_start_smoke.py

echo "== donation smoke (certified warm-path state donation: 0 compiles, in-place state update recovered, bit-identity across donated/undonated/uncached arms) =="
JAX_PLATFORMS=cpu PTPU_PLATFORM=cpu python scripts/donation_smoke.py

echo "== remat smoke (activation recompute A/B on BERT-tiny: bitwise loss parity with dropout on + >=30% measured XLA temp-bytes reduction for the compiled train step) =="
JAX_PLATFORMS=cpu PTPU_PLATFORM=cpu python scripts/remat_smoke.py

echo "== crash-resume smoke (SIGKILL mid-epoch -> seconds-scale resume with bit/loss parity; chaos kill+corrupt rounds; checkpoint stall < 2%) =="
JAX_PLATFORMS=cpu PTPU_PLATFORM=cpu python scripts/crash_resume_smoke.py

echo "== pod fault-tolerance smoke (2-process composed-mesh kill-one-host + full-pod resume in seconds off the warm compile cache; sharded two-phase checkpoints, stall < 2%, chaos --pod round with corruption) =="
JAX_PLATFORMS=cpu PTPU_PLATFORM=cpu python scripts/pod_ft_smoke.py

echo "== elastic resume smoke (topology-change restore: 4-host run killed mid-epoch, resumed on 2 AND 8 hosts with loss parity within float tolerance + exactly-once epoch digests; same-shape resume bit-exact with 0 resharding programs; chaos --resize round) =="
JAX_PLATFORMS=cpu PTPU_PLATFORM=cpu python scripts/elastic_resume_smoke.py

echo "== data plane smoke (sharded streaming input: serial-vs-pooled feeder A/B >=3x with bit-identical epochs, exactly-once journal resume, host-stall < 2% on the smallnet loop) =="
JAX_PLATFORMS=cpu PTPU_PLATFORM=cpu python scripts/data_plane_smoke.py

echo "== slow tier (threaded stress, Poisson serving scenario) =="
python -m pytest tests/ -q -m slow

echo "== bench smoke (tiny config; device-time off: XLA:CPU runs conv scan bodies ~10x slower) =="
PTPU_BENCH_ONLY=resnet PTPU_BENCH_BATCH=16 PTPU_BENCH_STEPS=3 \
PTPU_BENCH_DEVICE_TIME=0 \
PTPU_PLATFORM=cpu python bench.py

echo "== serving bench smoke (serve.py bench on a tiny artifact) =="
python scripts/serve_bench_smoke.py

echo "== decode serving smoke (continuous in-flight batching: Poisson A/B >=3x tokens/s vs sequential decode, bit-identical transcripts, 0-compile warm replica; block tier: prefix-share A/B >=1.5x effective capacity at fixed cache HBM, beam reorder >=10x fewer dispatch bytes block-level, chunked prefill >=2x below the monolithic-prefill stall) =="
JAX_PLATFORMS=cpu PTPU_PLATFORM=cpu python scripts/decode_serve_smoke.py

echo "== speculative decode smoke (draft-and-verify over the block-paged cache: bit-identical transcripts across plain/ngram/adversarial arms, >=1.5x tokens/s on the screened repetitive-suffix workload, zero-acceptance arm <=1.15x via acceptance-aware backoff) =="
JAX_PLATFORMS=cpu PTPU_PLATFORM=cpu python scripts/spec_decode_smoke.py

echo "== quantized serving smoke (int8 tier: calibrate -> export both tiers, top-1 parity, 0-compile warm int8 replica, >=1.3x fixed-cache-HBM decode throughput via 2x max_slots) =="
JAX_PLATFORMS=cpu PTPU_PLATFORM=cpu python scripts/quant_smoke.py

echo "== serving fleet smoke (3-replica warm fleet 0 compiles at spin-up; SIGKILL chaos loses only the victim's in-flight work with bit-identical survivors; autoscaler holds p99 TTFT across a 5x Poisson swing with zero dropped streams; rolling int8 rollout promotes on parity and rolls back loudly on an injected failure; fleet_ctl 0/1/2 exit codes) =="
JAX_PLATFORMS=cpu PTPU_PLATFORM=cpu python scripts/fleet_smoke.py

echo "== serving gateway smoke (serve.py gateway over a 2-replica fleet: SSE byte-identical to the direct predictor; 401/429 admission with Retry-After; SIGKILL chaos 502s only the victim's in-flight streams; SIGTERM drain finishes every stream and exits 0) =="
JAX_PLATFORMS=cpu PTPU_PLATFORM=cpu python scripts/gateway_smoke.py

echo "== tpu smoke tier (when a real chip is visible) =="
if env -u JAX_PLATFORMS -u PTPU_PLATFORM -u XLA_FLAGS python - <<'EOF'
import sys
try:
    import jax
    sys.exit(0 if any(d.platform == 'tpu' for d in jax.devices()) else 1)
except Exception:
    sys.exit(1)
EOF
then
  PTPU_RUN_TPU_TESTS=1 python -m pytest tests/test_tpu_smoke.py -q -m tpu
else
  echo "no TPU visible; skipping"
fi

echo "CI OK"
