#!/usr/bin/env python
"""Smoke the serving-fleet control plane (ISSUE 12 CI satellite).

    python scripts/fleet_smoke.py

Asserts, on the CPU dispatch-floor proxy:

  A. WARM SPIN-UP — a 3-replica decode fleet comes up with ZERO XLA
     compiles across every replica (AOT sidecars + framework-free
     fleet_worker.py replicas).
  B. CHAOS — SIGKILL one replica while decode streams are in flight:
     only that replica's in-flight requests fail (loudly, with
     ReplicaFailed; at most inflight_per_replica of them), every other
     request completes BIT-IDENTICAL to a single-replica reference,
     queued work re-routes, the fleet keeps serving, and p99 latency
     stays bounded.
  C. AUTOSCALE — a 5x Poisson load swing against min=1/max=3: the
     autoscaler scales out under the surge and DRAINS back in when it
     subsides, with zero dropped in-flight streams (every submitted
     future resolves with a transcript) and p99 TTFT within budget.
  D. ROLLING ROLLOUT — the int8 tier canaries on one replica, the
     canary's probe sweeps measure bit-deterministic, promotion happens
     on top-1 parity >= 0.99 + latency budget, and the whole fleet
     rolls to int8 at unchanged replica count; an injected parity
     failure (bit-agreement across tiers) ROLLS BACK LOUDLY leaving
     the fleet untouched.
  E. fleet_ctl — status exits 0 on a healthy fleet, drain retires a
     replica through the control-file path, status degrades to exit 1
     once the router is gone.

Exits non-zero on any failed bar.
"""
import json
import os
import signal
import subprocess
import sys
import tempfile
import threading
import time
import warnings

os.environ.setdefault('JAX_PLATFORMS', 'cpu')
os.environ.setdefault('PTPU_PLATFORM', 'cpu')

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import numpy as np  # noqa: E402

import paddle_tpu as fluid  # noqa: E402
from paddle_tpu.inference import (Autoscaler, Config,  # noqa: E402
                                  DecodingPredictor, FleetRouter,
                                  ReplicaFailed, RollingRollout,
                                  RolloutRolledBack, create_predictor,
                                  export_compiled, export_decode)

VOCAB, SLOTS = 211, 4
MAX_NEW = 24
TTFT_BUDGET_MS = float(os.environ.get('PTPU_FLEET_SMOKE_TTFT_MS', 5000))


def _export_decode_artifact(art):
    from models.transformer import build_decode_spec
    scope = fluid.core.Scope()
    with fluid.scope_guard(scope), fluid.unique_name.guard():
        spec = build_decode_spec(vocab=VOCAB, d_model=48, n_head=4,
                                 n_layer=2, d_ff=96, max_slots=SLOTS,
                                 max_cache_len=128, prompt_buckets=(4, 8),
                                 eos_id=1)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(spec['startup'])
        export_decode(spec, art, scope=scope)


def _export_dense_artifact(art):
    """Tiny classifier exported with BOTH tiers (bf16 + calibrated
    int8) — the rollout target."""
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 7
    with fluid.scope_guard(fluid.core.Scope()), fluid.unique_name.guard():
        with fluid.program_guard(main, startup):
            img = fluid.layers.data(name='img', shape=[16],
                                    dtype='float32')
            h = fluid.layers.fc(img, 32, act='relu')
            out = fluid.layers.fc(h, 8, act='softmax')
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        model_dir = os.path.join(os.path.dirname(art), 'model')
        fluid.io.save_inference_model(model_dir, ['img'], [out], exe,
                                      main)
        cfg = Config(model_dir)
        cfg.disable_gpu()
        pred = create_predictor(cfg)
        rng = np.random.RandomState(3)
        calib = [[rng.randn(8, 16).astype(np.float32)]
                 for _ in range(6)]
        export_compiled(pred, calib[0], art, batch_sizes=[8],
                        quantize='int8', calibration=calib)
    return calib


def _prompts(n, seed=0):
    rng = np.random.RandomState(seed)
    return [rng.randint(2, VOCAB, rng.randint(2, 9)) for _ in range(n)]


def part_a_b_warm_and_chaos(art):
    prompts = _prompts(96)
    with DecodingPredictor(art, platform='cpu') as ref:
        want = {i: ref.generate(p, max_new_tokens=MAX_NEW)
                for i, p in enumerate(prompts)}

    fleet_dir = tempfile.mkdtemp(prefix='ptpu_fleet_smoke_')
    router = FleetRouter(art, replicas=3, platform='cpu',
                         fleet_dir=fleet_dir, hb_timeout_s=3.0,
                         inflight_per_replica=4)
    snap = router.fleet_snapshot()
    compiles = {rid: s['compiles'] for rid, s in
                snap['replicas'].items()}
    spinup = {rid: s['spinup_s'] for rid, s in snap['replicas'].items()}
    assert all(c == 0 for c in compiles.values()), \
        'warm spin-up must compile nothing, got %r' % compiles
    print('A. warm 3-replica spin-up: compiles=%r spinup_s=%r' %
          (compiles, spinup))

    futs = {i: router.submit(p, max_new_tokens=MAX_NEW)
            for i, p in enumerate(prompts)}
    # let the fleet get properly mid-stream, then SIGKILL one replica
    # that has streams in flight
    time.sleep(0.15)
    victim = max(router._replicas.values(),
                 key=lambda r: len(r.outstanding)
                 if r.state == 'serving' else -1).rid
    victim_pid = router._replicas[victim].proc.pid
    t_kill = time.perf_counter()
    os.kill(victim_pid, signal.SIGKILL)
    with warnings.catch_warnings():
        warnings.simplefilter('ignore')
        while router._replicas[victim].state != 'dead' \
                and time.perf_counter() - t_kill < 15:
            time.sleep(0.02)
        detect_s = time.perf_counter() - t_kill
        done, failed = {}, []
        for i, f in futs.items():
            try:
                done[i] = f.result(300)
            except ReplicaFailed:
                failed.append(i)
    resolve_s = time.perf_counter() - t_kill
    assert router._replicas[victim].state == 'dead', \
        'kill must be detected in bounded time'
    assert len(failed) <= 4, \
        'only the victim\'s in-flight work may fail, got %d' % len(failed)
    assert len(done) + len(failed) == len(prompts)
    mismatch = [i for i, r in done.items() if r != want[i]]
    assert not mismatch, \
        'surviving requests must be bit-identical: %r' % mismatch[:5]
    st = router.fleet_snapshot()
    assert st['replica_deaths'] == 1
    assert st['p99_ms'] > 0
    # the fleet keeps serving on the survivors
    again = router.run(prompts[0], max_new_tokens=MAX_NEW, timeout=300)
    assert again == want[0]
    print('B. chaos SIGKILL: %d/%d completed bit-identical, %d in-flight '
          'failed loudly, %d rerouted, p99 %.0fms (death detected in '
          '%.2fs, all resolved %.1fs after kill)'
          % (len(done), len(prompts), len(failed), st['rerouted'],
             st['p99_ms'], detect_s, resolve_s))
    return router, fleet_dir


def part_c_autoscale(art):
    router = FleetRouter(art, replicas=1, platform='cpu',
                         hb_timeout_s=5.0, inflight_per_replica=4)
    scaler = Autoscaler(router, min_replicas=1, max_replicas=3,
                        high_queue_per_replica=3.0, idle_steps=2,
                        cooldown_s=1.0)
    rng = np.random.RandomState(7)
    prompts = _prompts(200, seed=11)
    futs = []
    lock = threading.Lock()

    def _wave(n, rate_hz, seed_off):
        for k in range(n):
            with lock:
                futs.append(router.submit(prompts[(seed_off + k)
                                                  % len(prompts)],
                                          max_new_tokens=96))
            time.sleep(rng.exponential(1.0 / rate_hz))

    # self-calibrate the swing to THIS host: measure one replica's
    # request throughput on a closed-loop burst, then drive the low
    # phase at ~40% of it and the 5x surge at ~2x capacity — the surge
    # oversubscribes a single replica on any CI machine, the low phase
    # never does
    t0 = time.perf_counter()
    burst = [router.submit(prompts[k], max_new_tokens=96)
             for k in range(24)]
    for f in burst:
        f.result(300)
    cap_hz = 24.0 / (time.perf_counter() - t0)
    # cap the base so the 5x surge stays generatable from one Python
    # submitter thread (sleep granularity) even on a fast host
    base_hz = float(os.environ.get('PTPU_FLEET_SMOKE_HZ',
                                   str(min(0.4 * cap_hz, 30.0))))
    phases = [(16, base_hz), (60, base_hz * 5), (16, base_hz)]
    print('C. calibrated single-replica capacity %.1f req/s -> swing '
          '%.1f/%.1f req/s' % (cap_hz, base_hz, base_hz * 5))
    scale_trace = []
    for pi, (n, hz) in enumerate(phases):
        t = threading.Thread(target=_wave, args=(n, hz, pi * 37))
        t.start()
        while t.is_alive():
            scaler.step()
            scale_trace.append(len(router.serving_replicas()))
            time.sleep(0.25)
        t.join()
    # drain the tail, then let the idle fleet scale back in
    with warnings.catch_warnings():
        warnings.simplefilter('ignore')
        results = [f.result(300) for f in futs]
    for _ in range(30):
        scaler.step()
        scale_trace.append(len(router.serving_replicas()))
        if len(router.serving_replicas()) == 1:
            break
        time.sleep(0.3)
    snap = router.fleet_snapshot()
    assert all(r is not None for r in results) \
        and len(results) == sum(n for n, _ in phases), \
        'zero dropped streams: every submitted future must resolve'
    assert snap['failed'] == 0, \
        'load swing must drop nothing, failed=%d' % snap['failed']
    assert snap['scale_out'] >= 1, 'the 5x surge must scale out'
    assert snap['scale_in'] >= 1, 'the idle tail must scale (drain) in'
    assert max(scale_trace) >= 2 and scale_trace[-1] == 1
    assert snap['ttft_p99_ms'] <= TTFT_BUDGET_MS, \
        'p99 TTFT %.0fms > budget %.0fms' % (snap['ttft_p99_ms'],
                                             TTFT_BUDGET_MS)
    print('C. autoscale 5x swing: replicas 1->%d->1, scale_out=%d '
          'scale_in=%d, %d requests all resolved (0 failed), ttft p50 '
          '%.0fms p99 %.0fms (budget %.0fms)'
          % (max(scale_trace), snap['scale_out'], snap['scale_in'],
             len(results), snap['ttft_p50_ms'], snap['ttft_p99_ms'],
             TTFT_BUDGET_MS))
    router.close()
    return {'max_replicas': max(scale_trace),
            'ttft_p50_ms': snap['ttft_p50_ms'],
            'ttft_p99_ms': snap['ttft_p99_ms']}


def part_d_rollout(art, calib):
    # parity probes = the calibration set (the round-14 parity measure:
    # top-1 agreement on the feeds the scales were calibrated on)
    probes = [{'img': c[0]} for c in calib]
    router = FleetRouter(art, replicas=2, platform='cpu')
    n0 = len(router.serving_replicas())
    rollout = RollingRollout(router, tier='int8', probes=probes,
                             agreement='top1', min_agreement=0.99,
                             latency_budget=100.0)
    report = rollout.run()
    assert report['promoted'] and report['deterministic']
    snap = router.fleet_snapshot()
    tiers = {rid: s['tier'] for rid, s in snap['replicas'].items()
             if s['state'] == 'serving'}
    assert len(tiers) == n0 and set(tiers.values()) == {'int8'}, tiers
    print('D. rolling int8 rollout: promoted (canary bit-deterministic, '
          'top-1 agreement %.3f, latency ratio %s), fleet of %d now %r'
          % (report['agreement'], report['latency_ratio'], len(tiers),
             sorted(set(tiers.values()))))
    # injected parity failure: bf16-vs-int8 logits can never bit-match
    bad = RollingRollout(router, tier=None, probes=probes,
                         agreement='bit', latency_budget=100.0)
    rolled_back = False
    with warnings.catch_warnings(record=True) as wlog:
        warnings.simplefilter('always')
        try:
            bad.run()
        except RolloutRolledBack:
            rolled_back = True
    assert rolled_back, 'parity failure must roll back loudly'
    assert any('ROLLED BACK' in str(w.message) for w in wlog)
    snap = router.fleet_snapshot()
    tiers = {rid: s['tier'] for rid, s in snap['replicas'].items()
             if s['state'] == 'serving'}
    assert len(tiers) == n0 and set(tiers.values()) == {'int8'}, \
        'rollback must leave the fleet untouched: %r' % tiers
    assert snap['rollout']['state'] == 'rolled_back'
    print('D. injected parity failure: rolled back loudly, fleet '
          'untouched (%d int8 replicas)' % len(tiers))
    router.close()


def part_e_fleet_ctl(router, fleet_dir):
    ctl = [sys.executable, os.path.join(REPO, 'tools', 'fleet_ctl.py')]
    rc = subprocess.call(ctl + ['status', fleet_dir],
                         stdout=subprocess.DEVNULL)
    assert rc == 0, 'status on a healthy fleet must exit 0, got %d' % rc
    rid = router.serving_replicas()[-1]
    out = subprocess.run(ctl + ['drain', fleet_dir, str(rid)],
                         capture_output=True, text=True, timeout=120)
    assert out.returncode == 0, out.stderr
    assert router._replicas[rid].state == 'retired'
    rc2 = subprocess.call(ctl + ['status', '/definitely/not/a/fleet'],
                          stderr=subprocess.DEVNULL)
    assert rc2 == 2, 'usage error must exit 2, got %d' % rc2
    router.close()
    # router gone -> stale status -> unhealthy
    rc3 = subprocess.call(ctl + ['status', fleet_dir, '--stale-s', '0'],
                          stdout=subprocess.DEVNULL)
    assert rc3 == 1, 'closed fleet must exit 1, got %d' % rc3
    print('E. fleet_ctl: status 0 on healthy, drain retired replica %d '
          'via control file, 2 on usage error, 1 once the router closed'
          % rid)


def main():
    t0 = time.time()
    tmp = tempfile.mkdtemp(prefix='ptpu_fleet_smoke_art_')
    decode_art = os.path.join(tmp, 'decode_art')
    dense_art = os.path.join(tmp, 'dense_art')
    _export_decode_artifact(decode_art)
    calib = _export_dense_artifact(dense_art)

    router, fleet_dir = part_a_b_warm_and_chaos(decode_art)
    c_stats = part_c_autoscale(decode_art)
    part_d_rollout(dense_art, calib)
    part_e_fleet_ctl(router, fleet_dir)
    print('FLEET SMOKE OK (%.0fs): ttft p99 %.0fms under the 5x swing'
          % (time.time() - t0, c_stats['ttft_p99_ms']))


if __name__ == '__main__':
    main()
