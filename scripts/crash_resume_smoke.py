"""CI smoke for fault-tolerant training (ISSUE 6):

1. Kill-and-resume: SIGKILL a trainer mid-epoch at a step boundary
   (racing the async checkpoint writer), restart it on the same
   checkpoint dir with the persistent compile cache on, and assert
   (a) the restart actually resumed from a committed checkpoint,
   (b) seconds-scale resume (startup+restore bounded), and
   (c) BIT parity: every loss — including re-run overlap steps — and
       the final params digest match an uninterrupted run.
2. Chaos loop: tools/chaos.py, 2 kill rounds with random checkpoint
   corruption between incarnations — restore must fall back loudly,
   never load a damaged checkpoint.
3. Checkpoint-stall budget: the smallnet multi-step loop with
   checkpointing every dispatch group reports ckpt stall < 2% of step
   time via profiler.training_report() (the ISSUE 6 acceptance bar).
"""
import os
import signal
import subprocess
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
os.environ.setdefault('JAX_PLATFORMS', 'cpu')
os.environ.setdefault('PTPU_PLATFORM', 'cpu')

WORKER = os.path.join(REPO, 'tests', 'checkpoint_kill_worker.py')
TOTAL, K, EVERY, KILL_AT = 24, 4, 4, 12
RESUME_BUDGET_S = 60.0      # "seconds-scale": startup+restore+cache-warm


def read_out(path):
    resume, startup_s, losses, sha = None, None, {}, None
    for line in open(path):
        parts = line.split()
        if parts[0] == 'RESUME':
            resume = int(parts[1])
            startup_s = float(parts[2]) if len(parts) > 2 else None
        elif parts[0] == 'DONE':
            sha = parts[1]
        else:
            losses[int(parts[0])] = float(parts[1])
    return resume, startup_s, losses, sha


def run_worker(env, ckpt, out, kill_at=0):
    argv = [sys.executable, WORKER, ckpt, out, str(TOTAL), str(K),
            str(EVERY)]
    if kill_at:
        argv += [str(kill_at), '1']
    t0 = time.time()
    r = subprocess.run(argv, env=env, capture_output=True, text=True,
                       timeout=600)
    return r, time.time() - t0


def kill_resume_phase(work):
    env = dict(os.environ)
    env['PTPU_COMPILE_CACHE'] = '1'
    env['PTPU_COMPILE_CACHE_DIR'] = os.path.join(work, 'cache')

    r, ref_wall = run_worker(env, '-', os.path.join(work, 'ref.txt'))
    assert r.returncode == 0, r.stderr[-2000:]
    _, _, ref_losses, ref_sha = read_out(os.path.join(work, 'ref.txt'))
    assert len(ref_losses) == TOTAL and ref_sha

    out1 = os.path.join(work, 'run1.txt')
    ckpt = os.path.join(work, 'ckpts')
    r, _ = run_worker(env, ckpt, out1, kill_at=KILL_AT)
    assert r.returncode == -signal.SIGKILL, \
        'worker survived its own SIGKILL? rc=%s' % r.returncode
    _, _, losses1, sha1 = read_out(out1)
    assert sha1 is None and len(losses1) >= KILL_AT

    out2 = os.path.join(work, 'run2.txt')
    r, resume_wall = run_worker(env, ckpt, out2)
    assert r.returncode == 0, r.stderr[-2000:]
    resume, startup_s, losses2, sha2 = read_out(out2)
    assert resume and 0 < resume <= KILL_AT, \
        'no committed checkpoint was restored (resume=%r)' % resume
    assert startup_s is not None and startup_s < RESUME_BUDGET_S, \
        'restore took %.1fs — not seconds-scale' % (startup_s or -1)
    assert sha2 == ref_sha, 'final params diverged after kill+resume'
    for idx, v in {**losses1, **losses2}.items():
        assert v == ref_losses[idx], 'loss diverged at step %d' % idx
    for idx in set(losses1) & set(losses2):
        assert losses1[idx] == losses2[idx], \
            'overlap step %d not reproducible' % idx
    print('[crash_resume] kill@%d -> resumed@%d: %d/%d losses bit-match, '
          'params digest equal; restore %.2fs, resumed run wall %.1fs '
          '(ref %.1fs)' % (KILL_AT, resume, len(losses1) + len(losses2
                           ) - len(set(losses1) & set(losses2)), TOTAL,
                           startup_s, resume_wall, ref_wall))


def chaos_phase(work):
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, 'tools', 'chaos.py'),
         '--rounds', '2', '--corrupt', 'random',
         '--workdir', os.path.join(work, 'chaos')],
        capture_output=True, text=True, timeout=600)
    sys.stdout.write(r.stdout)
    assert r.returncode == 0, 'chaos loop failed:\n%s%s' % (
        r.stdout[-2000:], r.stderr[-2000:])


def stall_budget_phase(work):
    import numpy as np
    import paddle_tpu as fluid
    from paddle_tpu import profiler
    from paddle_tpu.core.checkpoint import CheckpointManager
    sys.path.insert(0, os.path.join(REPO, 'models'))
    from smallnet import build_train_net

    main_p, startup_p = fluid.Program(), fluid.Program()
    main_p.random_seed = startup_p.random_seed = 7
    with fluid.program_guard(main_p, startup_p):
        _img, _lab, avg_loss, _acc = build_train_net()
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.core.Scope()
    r = np.random.RandomState(0)
    bs, dispatches = 32, 4

    def feed(d):
        return {'data': np.stack([r.randn(bs, 3, 32, 32).astype(np.float32)
                                  for _ in range(K)]),
                'label': np.stack([r.randint(0, 10, (bs, 1))
                                   for _ in range(K)])}

    with fluid.scope_guard(scope):
        exe.run(startup_p)
        with CheckpointManager(os.path.join(work, 'smallnet-ckpts'),
                               every_steps=K, keep_last_n=2) as mgr:
            for d in range(dispatches):
                exe.run_steps(main_p, feed=feed(d), fetch_list=[avg_loss],
                              steps=K, checkpoint=mgr)
            mgr.flush()
            committed = mgr.stats['commits']
    snap = profiler.training_report()['executor@%x' % id(exe)]
    exe.close()
    assert committed >= 1, 'no checkpoint committed during the loop'
    assert snap['ckpt_stall_pct'] < 2.0, \
        'checkpoint stall %.2f%% of step time exceeds the 2%% budget' \
        % snap['ckpt_stall_pct']
    print('[crash_resume] smallnet multi-step: %d commits, checkpoint '
          'stall %.3f%% of step time (< 2%% budget), %.1f ms total stall'
          % (committed, snap['ckpt_stall_pct'], snap['ckpt_stall_ms']))


def main():
    work = tempfile.mkdtemp(prefix='ptpu-crash-resume-')
    kill_resume_phase(work)
    chaos_phase(work)
    stall_budget_phase(work)
    print('[crash_resume] OK')


if __name__ == '__main__':
    main()
