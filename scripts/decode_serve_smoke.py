#!/usr/bin/env python
"""Smoke the continuous-decode serving tier (ISSUE 8 CI satellite):
build a tiny decoder LM, export the two-program paged-KV artifact, then
A/B a Poisson arrival stream through DecodingPredictor's in-flight
batching against strictly sequential (one-request-at-a-time) decode.

    python scripts/decode_serve_smoke.py

Asserts, on the CPU dispatch-floor proxy:
  * per-request transcripts BIT-IDENTICAL between the two arms (and a
    fresh framework-free subprocess reproduces them with 0 XLA compiles
    — the warm-start bar);
  * continuous batching >= 3x sequential tokens/s under the Poisson
    load (fixed [max_slots] step cost amortizes across co-resident
    requests exactly like the batch dispatch floor);
  * measured p50/p99 time-to-first-token reported for the Poisson arm.
Exits non-zero on any failed bar.
"""
import json
import os
import subprocess
import sys
import tempfile
import time

os.environ.setdefault('JAX_PLATFORMS', 'cpu')
os.environ.setdefault('PTPU_PLATFORM', 'cpu')

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import numpy as np  # noqa: E402

import paddle_tpu as fluid  # noqa: E402
from paddle_tpu.inference import (DecodingPredictor,  # noqa: E402
                                  export_decode)

# enough total work that each arm runs ~a second on the CPU proxy —
# with tiny configs the arms finish in tens of ms and scheduler noise
# swamps the capacity ratio the bar is about. Vocab is large enough
# that a random-init greedy decoder rarely emits eos immediately:
# prefill is serial per request in BOTH arms, so a fleet of 1-token
# requests would cap the achievable step-sharing speedup well below
# the bar regardless of scheduling.
VOCAB, SLOTS = 251, 8
MAX_NEW = int(os.environ.get('PTPU_DECODE_SMOKE_MAX_NEW', '24'))
N_REQ = int(os.environ.get('PTPU_DECODE_SMOKE_REQS', '96'))


def _export(art_dir):
    from models.transformer import build_decode_spec
    scope = fluid.core.Scope()
    with fluid.scope_guard(scope):
        spec = build_decode_spec(vocab=VOCAB, d_model=16, n_head=2,
                                 n_layer=2, d_ff=32, max_slots=SLOTS,
                                 max_cache_len=48, prompt_buckets=(4, 8),
                                 eos_id=1)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(spec['startup'])
        export_decode(spec, art_dir, scope=scope)


def _prompts(n):
    rng = np.random.RandomState(5)
    return [rng.randint(2, VOCAB, int(rng.randint(2, 9))) for _ in range(n)]


def main():
    with tempfile.TemporaryDirectory() as d:
        art = os.path.join(d, 'decode_art')
        _export(art)
        prompts = _prompts(N_REQ)
        pred = DecodingPredictor(art)
        try:
            pred.warmup()
            # -- sequential arm: one request at a time -------------------
            t0 = time.perf_counter()
            seq = [pred.generate(p, max_new_tokens=MAX_NEW)
                   for p in prompts]
            seq_s = time.perf_counter() - t0
            seq_tokens = sum(len(t) for t in seq)
            seq_tok_s = seq_tokens / seq_s
            seq_steps = pred.stats.snapshot()['steps']
            pred.stats.reset()
            # -- continuous arm: Poisson arrivals offered ABOVE the
            # MEASURED sequential request rate (early-eos sequences make
            # requests much cheaper than MAX_NEW tokens, so a token-
            # derived rate would under-offer and idle the slots). The
            # backlog keeps every slot occupied — the regime continuous
            # batching exists for; shedding off so every transcript
            # completes for the A/B.
            rate = float(os.environ.get('PTPU_DECODE_SMOKE_RATE_X', '8')) \
                * (N_REQ / seq_s)
            arrivals = np.cumsum(np.random.RandomState(1).exponential(
                1.0 / rate, N_REQ))
            streams = []
            t0 = time.perf_counter()
            for i, p in enumerate(prompts):
                delay = t0 + arrivals[i] - time.perf_counter()
                if delay > 0:
                    time.sleep(delay)
                streams.append(pred.submit(p, max_new_tokens=MAX_NEW))
            con = [s.result(300) for s in streams]
            con_s = time.perf_counter() - t0
            snap = pred.stats.snapshot()
        finally:
            pred.close()
        con_tok_s = sum(len(t) for t in con) / con_s
        speedup = con_tok_s / seq_tok_s
        print('sequential: %7.1f tok/s  (%d requests, %d tokens, %d steps '
              'of %d slots)' % (seq_tok_s, N_REQ, seq_tokens, seq_steps,
                                SLOTS))
        print('continuous: %7.1f tok/s  (%d steps, occupancy %.2f, '
              'offered %.1f req/s)' % (con_tok_s, snap['steps'],
                                       snap['occupancy'], rate))
        print('ttft ms: p50=%.2f p99=%.2f   itl ms: p50=%.2f p99=%.2f' %
              (snap['ttft_p50_ms'], snap['ttft_p99_ms'],
               snap['itl_p50_ms'], snap['itl_p99_ms']))
        print(json.dumps({'seq_tok_s': round(seq_tok_s, 1),
                          'con_tok_s': round(con_tok_s, 1),
                          'speedup': round(speedup, 2),
                          'occupancy': snap['occupancy'],
                          'ttft_p50_ms': snap['ttft_p50_ms'],
                          'ttft_p99_ms': snap['ttft_p99_ms']}))
        if con != seq:
            print('FAIL: continuous transcripts diverge from sequential',
                  file=sys.stderr)
            return 1
        if speedup < 3.0:
            print('FAIL: continuous batching %.2fx < 3x sequential '
                  'tokens/s' % speedup, file=sys.stderr)
            return 1
        # -- warm fresh-process arm: 0 compiles, same bits ---------------
        worker = os.path.join(REPO, 'tests', 'decode_serve_worker.py')
        r = subprocess.run(
            [sys.executable, worker, art, '23', '4', str(MAX_NEW)],
            capture_output=True, text=True, timeout=600)
        if r.returncode != 0 or 'DECODE_OK' not in r.stdout:
            sys.stderr.write(r.stdout + r.stderr)
            print('FAIL: warm decode worker failed', file=sys.stderr)
            return 1
        payload = json.loads(
            [l for l in r.stdout.splitlines()
             if l.startswith('DECODE ')][0][len('DECODE '):])
        if payload['compiles'] != 0:
            print('FAIL: warm fresh process performed %d XLA compiles '
                  '(want 0)' % payload['compiles'], file=sys.stderr)
            return 1
        rng = np.random.RandomState(23)
        warm_prompts = [rng.randint(2, VOCAB, rng.randint(2, 9))
                        for _ in range(4)]
        pred = DecodingPredictor(art)
        try:
            want = [pred.generate(p, max_new_tokens=MAX_NEW)
                    for p in warm_prompts]
        finally:
            pred.close()
        if payload['greedy'] != want:
            print('FAIL: warm-process transcripts diverge', file=sys.stderr)
            return 1
        print('decode smoke OK: %.2fx tokens/s, bit-identical transcripts, '
              '0 warm compiles' % speedup)
    return 0


if __name__ == '__main__':
    sys.exit(main())
