#!/usr/bin/env python
"""Smoke the continuous-decode serving tier (ISSUE 8 CI satellite;
block-paged tier bars added by ISSUE 13): build a tiny decoder LM,
export the two-program paged-KV artifact, then A/B a Poisson arrival
stream through DecodingPredictor's in-flight batching against strictly
sequential (one-request-at-a-time) decode.

    python scripts/decode_serve_smoke.py

Asserts, on the CPU dispatch-floor proxy:
  * per-request transcripts BIT-IDENTICAL between the two arms (and a
    fresh framework-free subprocess reproduces them with 0 XLA compiles
    — the warm-start bar);
  * continuous batching >= 3x sequential tokens/s under the Poisson
    load (fixed [max_slots] step cost amortizes across co-resident
    requests exactly like the batch dispatch floor);
  * measured p50/p99 time-to-first-token reported for the Poisson arm.

Block-paged tier (ISSUE 13):
  * prefix-share A/B: a shared-system-prompt workload vs the same
    workload with unique prefixes — peak cache blocks (= cache HBM)
    must drop >= 1.5x (the effective-slot-capacity multiplier at fixed
    cache bytes), transcripts bit-identical to the no-sharing serve;
  * beam reorder measured BLOCK-level: copy-on-write dispatch bytes
    must undercut the slot tier's whole-state reorder gathers >= 10x;
  * chunked prefill: while a max-length prompt admits, the running
    streams' worst inter-token gap must stay >= 2x below the measured
    stall the slot tier's monolithic prefill inflicts, with the long
    prompt's transcript bit-identical across both tiers.
Exits non-zero on any failed bar.
"""
import json
import os
import subprocess
import sys
import tempfile
import time

os.environ.setdefault('JAX_PLATFORMS', 'cpu')
os.environ.setdefault('PTPU_PLATFORM', 'cpu')

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import numpy as np  # noqa: E402

import paddle_tpu as fluid  # noqa: E402
from paddle_tpu.inference import (DecodingPredictor,  # noqa: E402
                                  export_decode)

# enough total work that each arm runs ~a second on the CPU proxy —
# with tiny configs the arms finish in tens of ms and scheduler noise
# swamps the capacity ratio the bar is about. Vocab is large enough
# that a random-init greedy decoder rarely emits eos immediately:
# prefill is serial per request in BOTH arms, so a fleet of 1-token
# requests would cap the achievable step-sharing speedup well below
# the bar regardless of scheduling.
VOCAB, SLOTS = 251, 8
MAX_NEW = int(os.environ.get('PTPU_DECODE_SMOKE_MAX_NEW', '24'))
N_REQ = int(os.environ.get('PTPU_DECODE_SMOKE_REQS', '96'))


def _export(art_dir, **kw):
    from models.transformer import build_decode_spec
    scope = fluid.core.Scope()
    with fluid.scope_guard(scope), fluid.unique_name.guard():
        cfg = dict(vocab=VOCAB, d_model=16, n_head=2, n_layer=2,
                   d_ff=32, max_slots=SLOTS, max_cache_len=48,
                   prompt_buckets=(4, 8), eos_id=1)
        cfg.update(kw)
        spec = build_decode_spec(**cfg)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(spec['startup'])
        export_decode(spec, art_dir, scope=scope)


def _prompts(n):
    rng = np.random.RandomState(5)
    return [rng.randint(2, VOCAB, int(rng.randint(2, 9))) for _ in range(n)]


def _consume(stream, stamps):
    for _ in stream:
        stamps.append(time.perf_counter())


def _prefix_share_ab(d):
    """ISSUE 13 part B: shared-system-prompt workload vs the same
    workload with unique prefixes, on one block-paged artifact. Returns
    the result dict; raises AssertionError on a failed bar."""
    art = os.path.join(d, 'block_art')
    _export(art, max_cache_len=64, block_size=8, prompt_buckets=(8, 16))
    rng = np.random.RandomState(9)
    system = rng.randint(2, VOCAB, 32)           # 4 full blocks
    n = 16
    suffixes = [rng.randint(2, VOCAB, 6) for _ in range(n)]
    shared = [np.concatenate([system, s]) for s in suffixes]
    unique = [np.concatenate([rng.randint(2, VOCAB, 32), s])
              for s in suffixes]

    def run(prompts, no_share=False):
        pred = DecodingPredictor(art)
        try:
            pred.warmup()
            if no_share:
                out = []
                for p in prompts:
                    pred.block_manager.evict_all_prefixes()
                    out.append(pred.generate(p, max_new_tokens=12))
                pred.block_manager.evict_all_prefixes()
                return out, pred.stats.snapshot()
            # let the first request finish prefill (publishing the
            # prefix) before the rest arrive: the A/B measures steady-
            # state sharing, not the cold first wave
            first = pred.submit(prompts[0], max_new_tokens=12)
            next(iter(first))
            rest = [pred.submit(p, max_new_tokens=12)
                    for p in prompts[1:]]
            out = [first.result(300)] + [s.result(300) for s in rest]
            return out, pred.stats.snapshot()
        finally:
            pred.close()

    truth, _ = run(shared, no_share=True)        # sharing disabled
    got_shared, snap_s = run(shared)
    _, snap_u = run(unique)
    assert got_shared == truth, \
        'prefix sharing changed transcripts'
    assert snap_s['prefix_hits'] >= n - 2, snap_s['prefix_hits']
    cap_x = snap_u['blocks_peak'] / float(snap_s['blocks_peak'])
    # bytes per block: block_size rows x d_model, K+V per layer, f32
    blk_bytes = 8 * 16 * 4 * (2 * 2)
    print('prefix share: peak blocks %d (unique) -> %d (shared) = '
          '%.2fx effective capacity at fixed cache HBM '
          '(%.1f -> %.1f KiB), %d hits, %d prompt tokens reused'
          % (snap_u['blocks_peak'], snap_s['blocks_peak'], cap_x,
             snap_u['blocks_peak'] * blk_bytes / 1024.0,
             snap_s['blocks_peak'] * blk_bytes / 1024.0,
             snap_s['prefix_hits'], snap_s['prefix_tokens_reused']))
    assert cap_x >= 1.5, \
        'prefix sharing bought only %.2fx effective capacity' % cap_x

    # -- beam reorder, measured block-level --------------------------------
    pred = DecodingPredictor(art)
    try:
        pred.warmup()
        beams = [pred.submit(p, max_new_tokens=12, beam=4)
                 for p in shared[:4]]
        for s in beams:
            s.result(300)
        bsnap = pred.stats.snapshot()
    finally:
        pred.close()
    # one slot-layout reorder gathers the WHOLE cache state (S rows x
    # max_cache_len x d_model, K+V per layer); the block tier dispatches
    # only the diverged blocks' copy pairs
    slot_bytes = bsnap['reorders'] * SLOTS * 64 * 16 * 4 * (2 * 2)
    cow_bytes = bsnap['cow_blocks'] * blk_bytes
    ratio = slot_bytes / max(cow_bytes, 1)
    print('beam reorder: %d reorders -> %d CoW blocks in %d copy '
          'dispatches; %.1f KiB slot-gather equivalent vs %.1f KiB '
          'block copies (%.0fx less dispatched)'
          % (bsnap['reorders'], bsnap['cow_blocks'],
             bsnap['blockcopies'], slot_bytes / 1024.0,
             cow_bytes / 1024.0, ratio))
    assert bsnap['cow_blocks'] > 0
    assert ratio >= 10.0, \
        'block-level reorder saved only %.1fx dispatch bytes' % ratio
    return {'capacity_x': round(cap_x, 2),
            'peak_blocks_shared': snap_s['blocks_peak'],
            'peak_blocks_unique': snap_u['blocks_peak'],
            'prefix_hits': snap_s['prefix_hits'],
            'reorder_bytes_x': round(ratio, 1)}


def _chunked_prefill_itl(d):
    """ISSUE 13 part C: p99 ITL of running streams while a max-length
    prompt admits — chunked prefill (block tier) vs the monolithic
    prefill stall (slot tier). Returns the result dict; raises
    AssertionError on a failed bar."""
    import threading
    # big enough that the monolithic prefill stall is unmistakable on
    # the CPU proxy (a 1000-token causal prefill at d_model 128), small
    # enough to export in seconds
    cfg = dict(d_model=128, n_head=8, n_layer=2, d_ff=256, max_slots=4,
               max_cache_len=1088)
    slot_art = os.path.join(d, 'itl_slot')
    blk_art = os.path.join(d, 'itl_block')
    _export(slot_art, prompt_buckets=(8, 1024), **cfg)
    _export(blk_art, prompt_buckets=(8, 32), block_size=32, **cfg)
    rng = np.random.RandomState(11)
    bg_prompts = [rng.randint(2, VOCAB, 6) for _ in range(3)]
    long_prompt = rng.randint(2, VOCAB, 1000)

    def trial(art):
        pred = DecodingPredictor(art)
        try:
            pred.warmup()
            stamps = [[] for _ in bg_prompts]
            threads = []
            bgs = []
            for p, ts in zip(bg_prompts, stamps):
                s = pred.submit(p, max_new_tokens=160)
                bgs.append(s)
                t = threading.Thread(target=_consume, args=(s, ts),
                                     daemon=True)
                t.start()
                threads.append(t)
            while any(len(ts) < 12 for ts in stamps):
                time.sleep(0.005)
            t_admit = time.perf_counter()
            long_s = pred.submit(long_prompt, max_new_tokens=8)
            long_out = long_s.result(600)
            t_done = time.perf_counter()
            for t in threads:
                t.join(300)
            base, stall = [], 0.0
            for ts in stamps:
                gaps = np.diff([t for t in ts if t <= t_admit])
                base.extend(gaps.tolist())
                w = [t for t in ts if t_admit - 0.05 <= t <= t_done]
                if len(w) >= 2:
                    stall = max(stall, float(np.max(np.diff(w))))
                # a stream that emitted NOTHING across the window
                # stalled for the whole admission
                inside = [t for t in ts if t_admit <= t <= t_done]
                if not inside and ts and ts[-1] > t_done:
                    stall = max(stall, t_done - t_admit)
            return (long_out, float(np.percentile(base, 99)) * 1e3,
                    stall * 1e3)
        finally:
            pred.close()

    def run(art, trials=3):
        # the stall statistic is a one-shot MAX gap: scheduler jitter,
        # GC, or a slow consumer wakeup can only inflate it, never
        # shrink it — so the MIN across trials is the tightest estimate
        # of the true admission stall (and what the 2x bar compares)
        outs, bases, stalls = [], [], []
        for _ in range(trials):
            o, b, s = trial(art)
            outs.append(o)
            bases.append(b)
            stalls.append(s)
        assert all(o == outs[0] for o in outs[1:]), \
            'long-prompt transcript varied across trials'
        return outs[0], float(np.median(bases)), float(min(stalls))

    long_slot, base_slot, stall_slot = run(slot_art)
    long_blk, base_blk, stall_blk = run(blk_art)
    assert long_slot == long_blk, \
        'chunked prefill changed the long prompt transcript'
    print('chunked prefill: worst running-stream gap while a %d-token '
          'prompt admits: slot %.1f ms (baseline itl p99 %.1f) vs '
          'block %.1f ms (baseline %.1f)'
          % (len(long_prompt), stall_slot, base_slot, stall_blk,
             base_blk))
    assert stall_slot >= 2.0 * stall_blk, \
        'monolithic prefill stall %.1f ms not >= 2x chunked %.1f ms' \
        % (stall_slot, stall_blk)
    return {'stall_slot_ms': round(stall_slot, 1),
            'stall_block_ms': round(stall_blk, 1),
            'itl_p99_base_ms': round(base_blk, 1)}


def main():
    with tempfile.TemporaryDirectory() as d:
        art = os.path.join(d, 'decode_art')
        _export(art)
        prompts = _prompts(N_REQ)
        pred = DecodingPredictor(art)
        try:
            pred.warmup()
            # -- sequential arm: one request at a time -------------------
            t0 = time.perf_counter()
            seq = [pred.generate(p, max_new_tokens=MAX_NEW)
                   for p in prompts]
            seq_s = time.perf_counter() - t0
            seq_tokens = sum(len(t) for t in seq)
            seq_tok_s = seq_tokens / seq_s
            seq_steps = pred.stats.snapshot()['steps']
            pred.stats.reset()
            # -- continuous arm: Poisson arrivals offered ABOVE the
            # MEASURED sequential request rate (early-eos sequences make
            # requests much cheaper than MAX_NEW tokens, so a token-
            # derived rate would under-offer and idle the slots). The
            # backlog keeps every slot occupied — the regime continuous
            # batching exists for; shedding off so every transcript
            # completes for the A/B.
            rate = float(os.environ.get('PTPU_DECODE_SMOKE_RATE_X', '8')) \
                * (N_REQ / seq_s)
            arrivals = np.cumsum(np.random.RandomState(1).exponential(
                1.0 / rate, N_REQ))
            streams = []
            t0 = time.perf_counter()
            for i, p in enumerate(prompts):
                delay = t0 + arrivals[i] - time.perf_counter()
                if delay > 0:
                    time.sleep(delay)
                streams.append(pred.submit(p, max_new_tokens=MAX_NEW))
            con = [s.result(300) for s in streams]
            con_s = time.perf_counter() - t0
            snap = pred.stats.snapshot()
        finally:
            pred.close()
        con_tok_s = sum(len(t) for t in con) / con_s
        speedup = con_tok_s / seq_tok_s
        print('sequential: %7.1f tok/s  (%d requests, %d tokens, %d steps '
              'of %d slots)' % (seq_tok_s, N_REQ, seq_tokens, seq_steps,
                                SLOTS))
        print('continuous: %7.1f tok/s  (%d steps, occupancy %.2f, '
              'offered %.1f req/s)' % (con_tok_s, snap['steps'],
                                       snap['occupancy'], rate))
        print('ttft ms: p50=%.2f p99=%.2f   itl ms: p50=%.2f p99=%.2f' %
              (snap['ttft_p50_ms'], snap['ttft_p99_ms'],
               snap['itl_p50_ms'], snap['itl_p99_ms']))
        print(json.dumps({'seq_tok_s': round(seq_tok_s, 1),
                          'con_tok_s': round(con_tok_s, 1),
                          'speedup': round(speedup, 2),
                          'occupancy': snap['occupancy'],
                          'ttft_p50_ms': snap['ttft_p50_ms'],
                          'ttft_p99_ms': snap['ttft_p99_ms']}))
        if con != seq:
            print('FAIL: continuous transcripts diverge from sequential',
                  file=sys.stderr)
            return 1
        if speedup < 3.0:
            print('FAIL: continuous batching %.2fx < 3x sequential '
                  'tokens/s' % speedup, file=sys.stderr)
            return 1
        # -- warm fresh-process arm: 0 compiles, same bits ---------------
        worker = os.path.join(REPO, 'tests', 'decode_serve_worker.py')
        r = subprocess.run(
            [sys.executable, worker, art, '23', '4', str(MAX_NEW)],
            capture_output=True, text=True, timeout=600)
        if r.returncode != 0 or 'DECODE_OK' not in r.stdout:
            sys.stderr.write(r.stdout + r.stderr)
            print('FAIL: warm decode worker failed', file=sys.stderr)
            return 1
        payload = json.loads(
            [l for l in r.stdout.splitlines()
             if l.startswith('DECODE ')][0][len('DECODE '):])
        if payload['compiles'] != 0:
            print('FAIL: warm fresh process performed %d XLA compiles '
                  '(want 0)' % payload['compiles'], file=sys.stderr)
            return 1
        rng = np.random.RandomState(23)
        warm_prompts = [rng.randint(2, VOCAB, rng.randint(2, 9))
                        for _ in range(4)]
        pred = DecodingPredictor(art)
        try:
            want = [pred.generate(p, max_new_tokens=MAX_NEW)
                    for p in warm_prompts]
        finally:
            pred.close()
        if payload['greedy'] != want:
            print('FAIL: warm-process transcripts diverge', file=sys.stderr)
            return 1
        # -- ISSUE 13: block-paged tier bars -----------------------------
        try:
            share = _prefix_share_ab(d)
            itl = _chunked_prefill_itl(d)
        except AssertionError as e:
            print('FAIL: %s' % e, file=sys.stderr)
            return 1
        print(json.dumps(dict(share, **itl)))
        print('decode smoke OK: %.2fx tokens/s, bit-identical '
              'transcripts, 0 warm compiles; prefix share %.2fx '
              'capacity, reorder bytes %.0fx down, chunked-prefill '
              'stall %.1f -> %.1f ms'
              % (speedup, share['capacity_x'], share['reorder_bytes_x'],
                 itl['stall_slot_ms'], itl['stall_block_ms']))
    return 0


if __name__ == '__main__':
    sys.exit(main())
