#!/usr/bin/env python
"""Data-plane smoke (ISSUE 9 acceptance): sharded streaming input must
saturate the prefetch ring.

1) Feeder A/B on the synthetic image pipeline (dataset/synthetic.py):
   the SAME shards and the SAME decode fn (zlib + numpy normalize + a
   modeled remote-fetch latency), read serially vs through the decode
   pool. Asserts pooled >= 3x serial samples/s AND bit-identical epoch
   contents (the pool decodes out of order but delivers in order).
2) Exactly-once resume: kill the pooled epoch mid-flight, resume from
   the elastic journal with a fresh reader — the union of deliveries is
   exactly one epoch.
3) Real image train loop (smallnet conv path) driven by
   MultiStepTrainer over a prefetch ring fed by the pooled reader:
   training_report() must show host-stall < 2%.
"""
import os
import sys
import time
import hashlib
import tempfile

os.environ.setdefault('JAX_PLATFORMS', 'cpu')
os.environ.setdefault('PTPU_PLATFORM', 'cpu')

sys.path.insert(0, os.path.join(os.path.dirname(__file__), '..'))

import numpy as np  # noqa: E402

NUM_SHARDS = int(os.environ.get('PTPU_DP_SHARDS', '4'))
SAMPLES_PER_SHARD = int(os.environ.get('PTPU_DP_SAMPLES', '128'))
WORKERS = int(os.environ.get('PTPU_DP_WORKERS', '8'))
LATENCY_MS = float(os.environ.get('PTPU_DP_LATENCY_MS', '3.0'))
MODE = os.environ.get('PTPU_DP_MODE', 'thread')
MIN_SPEEDUP = float(os.environ.get('PTPU_DP_MIN_SPEEDUP', '3.0'))


def epoch_digest_and_rate(reader_callable, decode_inline=None):
    """Drain one epoch; returns (sha256 hexdigest, samples/s, n)."""
    h = hashlib.sha256()
    n = 0
    t0 = time.perf_counter()
    for item in reader_callable():
        if decode_inline is not None:
            item = decode_inline(item)
        img, label = item
        h.update(img.tobytes())
        h.update(label.tobytes())
        n += 1
    dt = time.perf_counter() - t0
    return h.hexdigest(), n / dt, n


def main():
    from paddle_tpu.dataset import synthetic
    from paddle_tpu.reader.sharded import ShardedFileReader

    tmp = tempfile.mkdtemp(prefix='ptpu_dp_smoke_')
    files = synthetic.write_shards(
        tmp, num_shards=NUM_SHARDS, samples_per_shard=SAMPLES_PER_SHARD,
        seed=7)
    decode = synthetic.make_decode_fn(latency_s=LATENCY_MS * 1e-3)
    total = NUM_SHARDS * SAMPLES_PER_SHARD

    # -- 1) serial vs pooled A/B -------------------------------------------
    serial = ShardedFileReader(files)
    d_serial, r_serial, n = epoch_digest_and_rate(serial.records,
                                                  decode_inline=decode)
    assert n == total, (n, total)

    pooled_src = ShardedFileReader(files)
    pooled = pooled_src.pooled(decode, num_workers=WORKERS, mode=MODE)
    d_pooled, r_pooled, n = epoch_digest_and_rate(pooled)
    assert n == total, (n, total)
    stats = pooled.feeder_stats()

    speedup = r_pooled / r_serial
    print('feeder A/B: serial %.0f samples/s, pooled(%d %s) %.0f '
          'samples/s -> %.2fx (occupancy %.2f, decode %.2f ms avg, '
          'max in-flight %d)'
          % (r_serial, WORKERS, MODE, r_pooled, speedup,
             stats['occupancy'], stats['decode_ms_avg'],
             stats['max_inflight']))
    assert d_serial == d_pooled, 'epoch contents differ serial vs pooled'
    print('epoch contents bit-identical: sha256 %s' % d_serial[:16])
    assert speedup >= MIN_SPEEDUP, (
        'pooled feeder %.2fx < %.1fx floor' % (speedup, MIN_SPEEDUP))

    # -- 2) exactly-once resume through the elastic journal ----------------
    jp = os.path.join(tmp, 'feed.journal')
    r1 = ShardedFileReader(files, journal_path=jp, progress_every=1)
    g = r1.pooled(decode, num_workers=4, mode=MODE)()
    killed_at = total // 3
    seen = [next(g) for _ in range(killed_at)]
    g.close()   # simulated kill: leases release, journal keeps progress
    r1.close()
    r2 = ShardedFileReader(files, journal_path=jp, progress_every=1)
    rest = list(r2.pooled(decode, num_workers=4, mode=MODE)())
    r2.close()
    assert len(seen) + len(rest) == total, (len(seen), len(rest), total)
    h = hashlib.sha256()
    for img, label in seen + rest:
        h.update(img.tobytes())
        h.update(label.tobytes())
    # delivery order is deterministic, so resume must CONTINUE the same
    # stream: concatenated digests match the uninterrupted epoch
    assert h.hexdigest() == d_serial, 'kill+resume epoch diverged'
    print('exactly-once resume: %d + %d = %d samples, digest matches'
          % (len(seen), len(rest), total))

    # -- 3) real image train loop: host-stall < 2% -------------------------
    import paddle_tpu as fluid
    from paddle_tpu.reader.pipeline import PyReader
    from paddle_tpu.parallel import MultiStepTrainer
    from models.smallnet import build_train_net

    batch = 32
    k = 4
    main_p, startup_p = fluid.Program(), fluid.Program()
    with fluid.program_guard(main_p, startup_p):
        images, label, loss, acc = build_train_net()

    train_src = ShardedFileReader(files)
    train_pooled = train_src.pooled(decode, num_workers=WORKERS, mode=MODE)
    batched = fluid.reader.batch(train_pooled, batch, drop_last=True)

    py_reader = PyReader([images, label], capacity=8)
    py_reader.decorate_paddle_reader(batched)
    py_reader.prefetch_to_device(k, depth=2)

    trainer = MultiStepTrainer(main_p, steps_per_dispatch=k,
                               fetch_list=[loss])
    trainer.startup(startup_p)
    losses = []
    for epoch in range(2):
        for fetches in trainer.iter_epoch(py_reader):
            losses.append(float(np.asarray(fetches[0]).reshape(-1)[-1]))
    from paddle_tpu import profiler
    report = profiler.training_report()
    exe_rows = [s for name, s in report.items()
                if name != 'feeders' and 'dispatches' in s]
    assert exe_rows, 'no training source registered'
    stall_pct = exe_rows[0].get('host_stall_pct', 100.0)
    print('train loop: %d dispatches, %d losses, host-stall %.2f%%'
          % (exe_rows[0]['dispatches'], len(losses), stall_pct))
    assert np.isfinite(losses).all()
    assert stall_pct < 2.0, 'host-stall %.2f%% >= 2%%' % stall_pct
    feeders = report.get('feeders', {})
    assert feeders, 'feeder source missing from training_report'

    print('DATA PLANE SMOKE OK: %.2fx feeder speedup, bit-identical '
          'epochs, exactly-once resume, host-stall %.2f%%'
          % (speedup, stall_pct))


if __name__ == '__main__':
    main()
