#!/usr/bin/env python
"""Certified warm-path donation smoke (ISSUE 7, wired into scripts/ci.sh).

PERF_NOTES round 8 recorded the blind tax: reloaded (warm-started)
executables compiled WITHOUT state donation because aliasing safety was
unprovable, costing one extra state copy per run_steps step. The
dataflow donation certifier (passes/dataflow.py) now proves it, so this
smoke runs tests/donation_worker.py in FOUR fresh processes against tmp
cache dirs and asserts the recovery is real AND bit-identity guarded:

  cold    cache on           — certifies, compiles donated, persists
  warm    same cache dir     — executable-tier hits, ZERO XLA compiles,
                               and the state update still lands IN PLACE
                               (old buffers die / addresses reused: the
                               round-8 copy is measurably gone)
  nodon   PTPU_WARM_DONATION=0 — the control arm: same program, no
                               donation, zero in-place updates (the tax)
  ref     PTPU_COMPILE_CACHE=0 — the uncached reference semantics

Every fetch and every final state var must be byte-identical across all
four arms.
"""
import json
import os
import subprocess
import sys
import tempfile

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WORKER = os.path.join(REPO, 'tests', 'donation_worker.py')


def run_worker(cache_dir, out_npz, env_extra=None):
    env = dict(os.environ)
    env.update(env_extra or {})
    p = subprocess.run([sys.executable, WORKER, cache_dir, out_npz],
                       capture_output=True, text=True, env=env,
                       cwd=REPO)
    if p.returncode != 0 or 'DONATION_OK' not in p.stdout:
        print(p.stdout)
        print(p.stderr)
        raise SystemExit('donation worker failed')
    line = next(l for l in p.stdout.splitlines()
                if l.startswith('DONATION_STATS '))
    return json.loads(line[len('DONATION_STATS '):])


def main():
    import numpy as np
    tmp = tempfile.mkdtemp(prefix='ptpu_donation_smoke_')
    cache = os.path.join(tmp, 'cache')
    arms = {}
    stats = {}
    stats['cold'] = run_worker(cache, os.path.join(tmp, 'cold.npz'))
    stats['warm'] = run_worker(cache, os.path.join(tmp, 'warm.npz'))
    stats['nodon'] = run_worker(os.path.join(tmp, 'cache_nodon'),
                                os.path.join(tmp, 'nodon.npz'),
                                {'PTPU_WARM_DONATION': '0'})
    stats['ref'] = run_worker(os.path.join(tmp, 'cache_ref'),
                              os.path.join(tmp, 'ref.npz'),
                              {'PTPU_COMPILE_CACHE': '0'})
    for k, s in stats.items():
        print('%-5s %s' % (k, json.dumps(s)))
        arms[k] = {n: v for n, v in
                   np.load(os.path.join(tmp, k + '.npz')).items()}

    # certifier verdicts
    assert stats['cold']['cert_safe'] is True, 'certifier must accept'
    assert stats['nodon']['cert_safe'] is False
    assert stats['cold']['donated_entries'] >= 1, \
        'cold run must persist donated entries'
    assert stats['nodon']['donated_entries'] == 0

    # warm start: executable-tier hits, zero real compiles
    assert stats['warm']['exec_hits'] >= 2, stats['warm']
    assert stats['warm']['misses'] == 0, stats['warm']
    assert stats['warm']['xla_compiles_net'] == 0, stats['warm']

    # the measured copy elimination: wherever this backend honors
    # donation on the cold (bookkept) path, the RELOADED executable must
    # keep updating state in place — and the no-donation control arm
    # must not
    if stats['cold']['aliased_state'] > 0:
        assert stats['warm']['aliased_state'] >= \
            stats['cold']['aliased_state'], \
            'warm path lost the in-place state update: %s' % stats['warm']
        assert stats['warm']['old_deleted'] > 0, stats['warm']
    assert stats['nodon']['aliased_state'] == 0, stats['nodon']

    # bit-identity across every arm (fetches + final state)
    base = arms['cold']
    for name in ('warm', 'nodon', 'ref'):
        other = arms[name]
        assert set(base) == set(other), (name, set(base) ^ set(other))
        for k in sorted(base):
            assert np.array_equal(base[k], other[k]), \
                '%s: %r differs from cold' % (name, k)

    print('DONATION SMOKE OK — warm run: %d exec hits, 0 compiles, '
          '%d/%d state buffers updated in place (nodon control: %d)'
          % (stats['warm']['exec_hits'], stats['warm']['aliased_state'],
             stats['warm']['state_total'],
             stats['nodon']['aliased_state']))


if __name__ == '__main__':
    main()
