#!/usr/bin/env python
"""Smoke the int8 quantized serving tiers (ISSUE 11 CI satellite):
calibrate a small conv net, export BOTH artifact tiers
(export_compiled(quantize='int8')), and drive the quantized decode tier
at fixed cache HBM.

    python scripts/quant_smoke.py

Asserts, on the CPU proxy:
  * the quantize PassReport audits cleanly: >0 ops quantized, every op
    left in float carries a machine-checkable reason code;
  * TOP-1 PARITY on the calibration set between the int8 and bf16 tiers
    (>= 99% of rows agree; abs-max observer on a conv/fc net);
  * a WARM FRESH REPLICA of the int8 tier performs 0 XLA compiles and
    reproduces the in-process int8 fetches bit-exactly (per-tier AOT
    sidecars + tier-aware prewarm);
  * decode THROUGHPUT RATIO >= 1.3x: the int8 paged KV cache costs
    ~(1+4/D)/2 the bytes per slot, so a FIXED cache-HBM budget holds 2x
    max_slots — under saturating load the doubled occupancy amortizes
    the fixed per-step cost across twice the streams (tokens/s ratio vs
    the fp-KV artifact at equal cache bytes);
  * int8-KV transcripts match the fp-KV reference (shared weights)
    within tolerance: >= 90% greedy token agreement.
Exits non-zero on any failed bar.
"""
import json
import os
import subprocess
import sys
import tempfile
import time

os.environ.setdefault('JAX_PLATFORMS', 'cpu')
os.environ.setdefault('PTPU_PLATFORM', 'cpu')

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import numpy as np  # noqa: E402

import paddle_tpu as fluid  # noqa: E402
from paddle_tpu import passes  # noqa: E402
from paddle_tpu.inference import (Config, create_predictor,  # noqa: E402
                                  export_compiled, export_decode,
                                  CompiledPredictor, DecodingPredictor)

# 2 fp slots (int8 gets 4): the smaller the per-step tensor work, the
# more the fixed per-step cost dominates — the regime the slot-doubling
# bar measures (on TPU the same role is played by the per-dispatch
# floor at serving batch sizes). Enough total work that each measured
# arm runs a few hundred ms on the CPU proxy: tens-of-ms windows make
# the capacity ratio hostage to scheduler noise on a loaded CI host.
SLOTS = int(os.environ.get('PTPU_QUANT_SMOKE_SLOTS', '2'))
N_REQ = int(os.environ.get('PTPU_QUANT_SMOKE_REQS', '128'))
MAX_NEW = int(os.environ.get('PTPU_QUANT_SMOKE_MAX_NEW', '24'))
RATIO_BAR = 1.3
PARITY_BAR = 0.99
MATCH_BAR = 0.90


def fail(msg):
    print('FAIL: %s' % msg, file=sys.stderr)
    sys.exit(1)


# ---------------------------------------------------------------------------
# arm 1: bucket tier — calibrate, export both tiers, parity + 0-compile
# ---------------------------------------------------------------------------
def bucket_tier_arm(d):
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        img = fluid.layers.data(name='img', shape=[3, 24, 24],
                                dtype='float32')
        c1 = fluid.layers.conv2d(img, 16, 3, padding=1, act='relu')
        p1 = fluid.layers.pool2d(c1, 2, 'max', pool_stride=2)
        c2 = fluid.layers.conv2d(p1, 32, 3, padding=1, act='relu')
        p2 = fluid.layers.pool2d(c2, 2, 'max', pool_stride=2)
        fc = fluid.layers.fc(p2, 64, act='relu')
        logits = fluid.layers.fc(fc, 10, act='softmax')
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    mdir, adir = os.path.join(d, 'model'), os.path.join(d, 'artifact')
    fluid.io.save_inference_model(mdir, ['img'], [logits], exe, main)
    pred = create_predictor(Config(mdir))
    rng = np.random.RandomState(0)
    calib = [{'img': rng.randn(8, 3, 24, 24).astype(np.float32)}
             for _ in range(4)]
    export_compiled(pred, [calib[0]['img']], adir, batch_sizes=[1, 8],
                    quantize='int8', calibration=calib)

    with open(os.path.join(adir, 'signature.json')) as f:
        sig = json.load(f)
    if sig.get('tiers') != ['bf16', 'int8']:
        fail('top signature lacks the tier inventory: %r'
             % sig.get('tiers'))
    q = sig['quantization']
    if q['quantized_ops'] <= 0:
        fail('quantize pass quantized nothing')
    bad = [e for e in q['float_ops']
           if e.get('reason') not in passes.quantize.REASON_CODES]
    if bad:
        fail('float ops without machine-checkable reasons: %r' % bad)
    print('quantized_ops=%d float_ops=%d reasons=%s'
          % (q['quantized_ops'], len(q['float_ops']),
             q['float_op_reasons']))

    # -- top-1 parity over the calibration set ---------------------------
    p_b = CompiledPredictor(adir)                 # bf16 tier
    p_q = CompiledPredictor(adir, tier='int8')
    agree = total = 0
    q_ref_outs = []
    for c in calib:
        ob = p_b.run([c['img']])[0]
        oq = p_q.run([c['img']])[0]
        q_ref_outs.append(oq)
        agree += int((ob.argmax(1) == oq.argmax(1)).sum())
        total += ob.shape[0]
    parity = agree / total
    print('top-1 parity on calibration set: %.4f (%d/%d rows)'
          % (parity, agree, total))
    if parity < PARITY_BAR:
        fail('top-1 parity %.4f < %.2f' % (parity, PARITY_BAR))

    # -- warm fresh int8 replica: 0 compiles, bit-identical --------------
    in_npz = os.path.join(d, 'in.npz')
    np.savez(in_npz, img=calib[0]['img'])
    worker = os.path.join(REPO, 'tests', 'quant_serve_worker.py')
    out = subprocess.run([sys.executable, worker, adir, in_npz, 'int8'],
                         capture_output=True, text=True, timeout=300)
    if out.returncode or 'QUANT_OK' not in out.stdout:
        fail('int8 warm-replica worker failed:\n%s\n%s'
             % (out.stdout, out.stderr))
    payload = json.loads(next(l for l in out.stdout.splitlines()
                              if l.startswith('QUANT '))[len('QUANT '):])
    if payload['compiles'] != 0:
        fail('warm int8 replica performed %d XLA compiles (want 0)'
             % payload['compiles'])
    import hashlib
    digest = hashlib.sha256()
    digest.update(np.ascontiguousarray(q_ref_outs[0]).tobytes())
    if payload['sha'] != digest.hexdigest():
        fail('warm int8 replica fetches differ from the in-process tier')
    print('warm int8 replica: 0 XLA compiles, bit-identical fetches')


# ---------------------------------------------------------------------------
# arm 2: decode tier — int8 KV at fixed cache HBM, >= 1.3x tokens/s
# ---------------------------------------------------------------------------
def _build_decode(kv, slots):
    from models.transformer import build_decode_spec
    scope = fluid.core.Scope()
    with fluid.scope_guard(scope):
        # small d_model keeps the per-step cost dispatch-floor-dominated
        # (the regime the slot-doubling bar is about — on TPU the same
        # role is played by the fixed per-dispatch cost at serving batch)
        spec = build_decode_spec(vocab=251, d_model=32, n_head=4,
                                 n_layer=2, d_ff=64, max_slots=slots,
                                 max_cache_len=48, prompt_buckets=(4, 8),
                                 eos_id=1, kv_cache_dtype=kv)
        # seeded init: the transcript-agreement bar must measure the
        # quantization step, not a fresh weight draw per run
        spec['startup'].random_seed = 7
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(spec['startup'], scope=scope)
    return spec, scope


def decode_tier_arm(d):
    fp_spec, fp_scope = _build_decode('float32', SLOTS)
    q_spec, q_scope = _build_decode('int8', 2 * SLOTS)
    cache_names = set(q_spec['cache_vars'])
    for n in q_scope.local_var_names():   # shared weights: honest parity
        if n not in cache_names and fp_scope.get(n) is not None:
            q_scope.set(n, fp_scope.get(n))
    rng = np.random.RandomState(5)
    prompts = [rng.randint(2, 251, int(rng.randint(2, 9)))
               for _ in range(N_REQ)]

    def load(spec, scope, art):
        with fluid.scope_guard(scope):
            export_decode(spec, art, scope=scope)
        with open(os.path.join(art, 'decode_signature.json')) as f:
            sig = json.load(f)
        return DecodingPredictor(art).warmup(), sig

    def measure(pred):
        pred.stats.reset()
        t0 = time.perf_counter()   # saturating load: submit all
        streams = [pred.submit(p, max_new_tokens=MAX_NEW)
                   for p in prompts]
        outs = [s.result(600) for s in streams]
        tok_s = sum(len(t) for t in outs) / (time.perf_counter() - t0)
        return outs, tok_s, pred.stats.snapshot()

    fp_pred, fp_sig = load(fp_spec, fp_scope, os.path.join(d, 'fp'))
    q_pred, q_sig = load(q_spec, q_scope, os.path.join(d, 'int8'))
    try:
        # INTERLEAVED best-of-3 capacity per arm: the ratio bar measures
        # slot-doubling against the fixed per-step cost; alternating the
        # arms round by round keeps a shared-CI-host load spike from
        # landing on one arm only, and best-of filters the spike itself
        fp_tok_s = q_tok_s = 0.0
        fp_out = q_out = fp_snap = q_snap = None
        for _ in range(3):
            outs, tok_s, snap = measure(fp_pred)
            if tok_s > fp_tok_s:
                fp_out, fp_tok_s, fp_snap = outs, tok_s, snap
            outs, tok_s, snap = measure(q_pred)
            if tok_s > q_tok_s:
                q_out, q_tok_s, q_snap = outs, tok_s, snap
    finally:
        fp_pred.close()
        q_pred.close()

    if q_sig['cache_bytes'] > fp_sig['cache_bytes']:
        fail('int8 cache (%d B, %d slots) costs MORE than fp (%d B, %d '
             'slots) — the fixed-HBM premise broke'
             % (q_sig['cache_bytes'], q_sig['max_slots'],
                fp_sig['cache_bytes'], fp_sig['max_slots']))
    match = float(np.mean([
        np.mean(np.asarray(a[:min(len(a), len(b))])
                == np.asarray(b[:min(len(a), len(b))]))
        for a, b in zip(fp_out, q_out)]))
    ratio = q_tok_s / fp_tok_s
    print('decode @fixed cache HBM: fp %d slots %.0f B -> int8 %d slots '
          '%.0f B' % (fp_sig['max_slots'], fp_sig['cache_bytes'],
                      q_sig['max_slots'], q_sig['cache_bytes']))
    print('tokens/s: fp %.0f (occ %.2f) vs int8 %.0f (occ %.2f) — '
          'ratio %.2fx; transcript agreement %.3f; int8 tier=%s'
          % (fp_tok_s, fp_snap['occupancy'], q_tok_s,
             q_snap['occupancy'], ratio, match, q_snap['tier']))
    if q_snap['tier'] != 'int8':
        fail('decode stats report tier %r, want int8' % q_snap['tier'])
    if match < MATCH_BAR:
        fail('int8-KV transcripts agree %.3f < %.2f with the fp-KV '
             'reference' % (match, MATCH_BAR))
    if ratio < RATIO_BAR:
        fail('int8 tier serves %.2fx fp tokens/s at fixed cache HBM '
             '(bar %.1fx)' % (ratio, RATIO_BAR))


def main():
    t0 = time.perf_counter()
    with tempfile.TemporaryDirectory() as d:
        bucket_tier_arm(d)
        decode_tier_arm(d)
    print('QUANT SMOKE OK (%.1fs): both tiers exported, parity + '
          '0-compile warm replica + >=%.1fx fixed-HBM decode throughput'
          % (time.perf_counter() - t0, RATIO_BAR))


if __name__ == '__main__':
    main()
