#!/usr/bin/env python
"""Smoke the HTTP serving gateway (ISSUE 19 CI satellite).

    python scripts/gateway_smoke.py

Asserts, on the CPU dispatch-floor proxy:

  A. END-TO-END SERVE — `serve.py gateway` brings a 2-replica decode
     fleet up behind HTTP; SSE streams come back BYTE-IDENTICAL to a
     direct in-process DecodingPredictor, token-for-token; a dense
     /v1/infer npz round trip is bit-exact against Predictor.run.
  B. ADMISSION — unknown API key 401s; a burst-1 tenant's second
     request 429s with Retry-After; a zero-quota tenant 429s; none of
     these ever reach the fleet.
  C. CHAOS — SIGKILL one replica while SSE streams are mid-flight:
     only that replica's in-flight streams end in an `event: error`
     502 (loud, request_id attached), every surviving stream completes
     bit-identical, and the gateway keeps serving on the survivor.
  D. DRAIN — SIGTERM the serving process while streams are in flight:
     every in-flight stream runs to its `done` event (zero dropped),
     the process exits 0, and the listener is gone afterwards.

Exits non-zero on any failed bar.
"""
import json
import os
import signal
import subprocess
import sys
import tempfile
import threading
import time
import urllib.error
import urllib.request
import warnings

os.environ.setdefault('JAX_PLATFORMS', 'cpu')
os.environ.setdefault('PTPU_PLATFORM', 'cpu')

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import numpy as np  # noqa: E402

import paddle_tpu as fluid  # noqa: E402
from paddle_tpu.inference import (BatchingPredictor, Config,  # noqa: E402
                                  DecodingPredictor, FleetRouter,
                                  Gateway, create_predictor,
                                  export_compiled, export_decode)
from paddle_tpu.inference import gateway as gateway_mod  # noqa: E402

VOCAB = 211
MAX_NEW = 24


def _export_decode_artifact(art):
    from models.transformer import build_decode_spec
    scope = fluid.core.Scope()
    with fluid.scope_guard(scope), fluid.unique_name.guard():
        spec = build_decode_spec(vocab=VOCAB, d_model=48, n_head=4,
                                 n_layer=2, d_ff=96, max_slots=4,
                                 max_cache_len=128, prompt_buckets=(4, 8),
                                 eos_id=1)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(spec['startup'])
        export_decode(spec, art, scope=scope)


def _prompts(n, seed=0):
    rng = np.random.RandomState(seed)
    return [rng.randint(2, VOCAB, rng.randint(2, 9)) for _ in range(n)]


def _post(url, path, body, key=None, rid=None, timeout=300):
    req = urllib.request.Request(url + path,
                                 data=json.dumps(body).encode(),
                                 method='POST')
    req.add_header('Content-Type', 'application/json')
    if key:
        req.add_header('X-API-Key', key)
    if rid:
        req.add_header('X-Request-Id', rid)
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, dict(r.headers), r.read().decode('utf-8')
    except urllib.error.HTTPError as e:
        return e.code, dict(e.headers), e.read().decode('utf-8')


def _sse(raw):
    """-> (tokens, done-dict-or-None, error-dict-or-None)."""
    toks, done, err = [], None, None
    for block in raw.strip().split('\n\n'):
        ev, data = None, None
        for line in block.split('\n'):
            if line.startswith('event: '):
                ev = line[len('event: '):]
            elif line.startswith('data: '):
                data = json.loads(line[len('data: '):])
        if ev is None and data and 'toks' in data:
            toks.extend(data['toks'])
        elif ev == 'done':
            done = data
        elif ev == 'error':
            err = data
    return toks, done, err


def _decode_body(prompt, **kw):
    body = {'prompt': [int(t) for t in prompt], 'max_new_tokens': MAX_NEW}
    body.update(kw)
    return body


def part_a_dense_infer(tmp):
    """Dense /v1/infer: base64-npz feeds over HTTP, outputs bit-exact
    against the direct predictor."""
    art = os.path.join(tmp, 'dense_art')
    with fluid.scope_guard(fluid.core.Scope()), fluid.unique_name.guard():
        main, startup = fluid.Program(), fluid.Program()
        main.random_seed = startup.random_seed = 7
        with fluid.program_guard(main, startup):
            img = fluid.layers.data(name='img', shape=[16],
                                    dtype='float32')
            h = fluid.layers.fc(img, 32, act='relu')
            out = fluid.layers.fc(h, 8, act='softmax')
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        model_dir = os.path.join(tmp, 'dense_model')
        fluid.io.save_inference_model(model_dir, ['img'], [out], exe,
                                      main)
        pred = create_predictor(Config(model_dir))
        x = np.random.RandomState(3).randn(8, 16).astype(np.float32)
        export_compiled(pred, [x], art, batch_sizes=[8])
    ref, = pred.run([x])
    with BatchingPredictor(art, platform='cpu') as bp:
        bp.warmup()
        with Gateway(bp) as gw:
            code, _, raw = _post(
                gw.url, '/v1/infer',
                {'npz': gateway_mod.encode_arrays({'img': x})})
            assert code == 200, raw[:300]
            outs = gateway_mod.decode_arrays(json.loads(raw)['npz'])
    assert np.array_equal(outs['o0'], ref), \
        'dense infer over HTTP must be bit-exact'
    print('A. dense /v1/infer npz round trip bit-exact vs '
          'Predictor.run (batch 8)')


def part_a_b_serve_and_admission(art, want, prompts):
    tenants_path = os.path.join(os.path.dirname(art), 'tenants.json')
    with open(tenants_path, 'w') as f:
        json.dump({
            'k-admin': {'tenant': 'admin', 'admin': True},
            'k-burst1': {'tenant': 'burst1', 'rate': 0.001, 'burst': 1},
            'k-zero': {'tenant': 'zero', 'max_inflight': 0},
        }, f)
    serve = os.path.join(REPO, 'paddle_tpu', 'inference', 'serve.py')
    proc = subprocess.Popen(
        [sys.executable, serve, 'gateway', art, '0', '--replicas', '2',
         '--tenants', tenants_path],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        cwd=REPO)
    hello = {}

    def _read_hello():
        hello['line'] = proc.stdout.readline()

    t = threading.Thread(target=_read_hello, daemon=True)
    t.start()
    t.join(300)
    assert hello.get('line'), 'serve.py gateway never printed its URL'
    url = json.loads(hello['line'])['url']

    with urllib.request.urlopen(url + '/healthz', timeout=30) as r:
        health = json.loads(r.read().decode())
    assert health['ok'] and health['kind'] == 'decoding', health

    t0 = time.perf_counter()
    n_tok = 0
    for i, p in enumerate(prompts[:24]):
        code, hdrs, raw = _post(url, '/v1/decode', _decode_body(p),
                                key='k-admin', rid='smoke-%d' % i)
        assert code == 200, raw[:300]
        assert hdrs.get('X-Request-Id') == 'smoke-%d' % i
        toks, done, err = _sse(raw)
        assert err is None, err
        assert toks == want[i] and done['tokens'] == want[i], \
            'stream %d diverged from the direct predictor' % i
        n_tok += len(toks)
    dt = time.perf_counter() - t0
    print('A. serve.py gateway up at %s: 24/24 SSE streams '
          'byte-identical to the direct predictor (%d tokens, %.2fs)'
          % (url, n_tok, dt))

    code, _, raw = _post(url, '/v1/decode', _decode_body(prompts[0]))
    assert code == 401, 'no key must 401, got %d' % code
    code, _, _ = _post(url, '/v1/decode', _decode_body(prompts[0]),
                       key='k-wrong')
    assert code == 401
    code, _, _ = _post(url, '/v1/decode',
                       _decode_body(prompts[0], stream=False),
                       key='k-burst1')
    assert code == 200
    code, hdrs, raw = _post(url, '/v1/decode', _decode_body(prompts[0]),
                            key='k-burst1')
    assert code == 429, 'burst-1 second request must 429, got %d' % code
    assert float(hdrs.get('Retry-After', 0)) >= 1
    code, _, _ = _post(url, '/v1/decode', _decode_body(prompts[0]),
                       key='k-zero')
    assert code == 429, 'zero-quota tenant must 429, got %d' % code
    with urllib.request.urlopen(url + '/metrics', timeout=30) as r:
        metrics = r.read().decode()
    assert 'ptpu_gateway_requests_total' in metrics
    assert 'ptpu_fleet_' in metrics
    print('B. admission: 401 unknown key, 429 + Retry-After on the '
          'burst-1 tenant, 429 on the zero-quota tenant; /metrics '
          'exposes gateway + fleet counters')
    return proc, url


def part_c_chaos(art, want, prompts):
    results = [None] * 16
    with warnings.catch_warnings():
        warnings.simplefilter('ignore')
        router = FleetRouter(art, replicas=2, platform='cpu',
                             hb_timeout_s=3.0, inflight_per_replica=4)
        with Gateway(router) as gw:
            def one(i):
                code, _, raw = _post(gw.url, '/v1/decode',
                                     _decode_body(prompts[i]),
                                     rid='chaos-%d' % i)
                results[i] = (code, _sse(raw))

            threads = [threading.Thread(target=one, args=(i,),
                                        daemon=True)
                       for i in range(16)]
            for t in threads:
                t.start()
            time.sleep(0.05)  # streams mid-flight
            victim = max(router._replicas.values(),
                         key=lambda r: len(r.outstanding)
                         if r.state == 'serving' else -1).rid
            os.kill(router._replicas[victim].proc.pid, signal.SIGKILL)
            for t in threads:
                t.join(300)
            assert all(not t.is_alive() for t in threads)
            ok, failed = [], []
            for i, (code, (toks, done, err)) in enumerate(results):
                if code == 502:
                    # failed before the first token: clean HTTP 502
                    failed.append(i)
                    continue
                assert code == 200, 'stream %d: HTTP %d' % (i, code)
                if err is not None:
                    # failed mid-stream: loud SSE error event
                    assert err['code'] == 502, err
                    assert err['request_id'] == 'chaos-%d' % i
                    failed.append(i)
                else:
                    assert toks == want[i] and done['tokens'] == want[i]
                    ok.append(i)
            assert len(failed) <= 4, \
                'only the victim\'s in-flight streams may 502: %r' % failed
            assert len(ok) + len(failed) == 16
            # the gateway keeps serving on the survivor
            code, _, raw = _post(gw.url, '/v1/decode',
                                 _decode_body(prompts[0]))
            toks, done, err = _sse(raw)
            assert code == 200 and err is None and toks == want[0]
            snap = gw.snapshot()
            assert snap['failed'] == len(failed)
        router.close()
    print('C. chaos SIGKILL replica %d mid-stream: %d/16 streams '
          'completed bit-identical, %d ended in a loud 502, '
          'gateway kept serving on the survivor'
          % (victim, len(ok), len(failed)))


def part_d_drain(proc, url, want, prompts):
    streams = [None] * 8
    body = [_decode_body(p, max_new_tokens=96) for p in prompts[:8]]

    def one(i):
        try:
            code, _, raw = _post(url, '/v1/decode', body[i],
                                 key='k-admin')
            streams[i] = (code, _sse(raw))
        except Exception as e:  # loud placeholder, not a None unpack
            streams[i] = (type(e).__name__, ([], None, None))

    threads = [threading.Thread(target=one, args=(i,), daemon=True)
               for i in range(8)]
    for t in threads:
        t.start()
    # SIGTERM only once all 8 streams are provably admitted — drain
    # must then finish every one of them
    deadline = time.time() + 60
    while time.time() < deadline:
        with urllib.request.urlopen(url + '/healthz', timeout=30) as r:
            if int(json.loads(r.read().decode())['inflight']) >= 8:
                break
        time.sleep(0.02)
    else:
        raise AssertionError('8 streams never went in-flight')
    proc.send_signal(signal.SIGTERM)
    for t in threads:
        t.join(300)
    assert all(not t.is_alive() for t in threads)
    dropped = [i for i, (code, (toks, done, err)) in enumerate(streams)
               if code != 200 or done is None or err is not None]
    assert not dropped, \
        'drain must finish every in-flight stream: dropped %r' % dropped
    _out, err = proc.communicate(timeout=300)
    assert proc.returncode == 0, \
        'drained gateway must exit 0: rc=%s\n%s' \
        % (proc.returncode, err[-2000:])
    try:
        urllib.request.urlopen(url + '/healthz', timeout=5)
        raise AssertionError('listener must be gone after drain')
    except (urllib.error.URLError, ConnectionError, OSError):
        pass
    print('D. SIGTERM drain: 8/8 in-flight streams ran to their done '
          'event (zero dropped), process exited 0, listener gone')


def main():
    t0 = time.time()
    tmp = tempfile.mkdtemp(prefix='ptpu_gateway_smoke_')
    art = os.path.join(tmp, 'decode_art')
    _export_decode_artifact(art)
    prompts = _prompts(24, seed=5)
    with DecodingPredictor(art, platform='cpu') as ref:
        want = [[int(t) for t in ref.generate(p, max_new_tokens=MAX_NEW)]
                for p in prompts]

    part_a_dense_infer(tmp)
    proc, url = part_a_b_serve_and_admission(art, want, prompts)
    try:
        part_c_chaos(art, want, prompts)
        part_d_drain(proc, url, want, prompts)
    finally:
        if proc.poll() is None:
            proc.kill()
    print('GATEWAY SMOKE OK (%.0fs)' % (time.time() - t0))


if __name__ == '__main__':
    main()
