"""CI smoke for activation rematerialization (ISSUE 18):

Same-seed A/B on BERT-tiny (2 layers, d=32): arm A trains without
recompute, arm B with explicit per-layer checkpoints
(build_bert_pretrain(checkpoints=True)). Asserts

1. BIT parity: with dropout ON, every loss over 3 steps is bitwise
   identical across the arms (recompute replays the same _op_uid rng
   folds — it changes what is STORED, never what is computed), and
2. the saving is MEASURED, not estimated: XLA's buffer assignment for
   the compiled train step (compiled_memory_stats) plans >= 30% fewer
   temp bytes for the remat arm at the same batch — the ISSUE 18
   acceptance bar, gated on the CPU proxy backend.
"""
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
os.environ.setdefault('JAX_PLATFORMS', 'cpu')
os.environ.setdefault('PTPU_PLATFORM', 'cpu')

import numpy as np  # noqa: E402

import paddle_tpu as fluid  # noqa: E402
import models.bert  # noqa: E402
from paddle_tpu.executor import compiled_memory_stats  # noqa: E402

STEPS = 3
BATCH = 8
REDUCTION_BAR = 0.30


def _feed(batch=BATCH, S=16, vocab=1000, seed=0):
    rng = np.random.RandomState(seed)
    return {
        'tok_ids': rng.randint(0, vocab, (batch, S)).astype(np.int64),
        'seg_ids': rng.randint(0, 2, (batch, S)).astype(np.int64),
        'mlm_labels': rng.randint(0, vocab, (batch, S)).astype(np.int64),
        'mlm_weights': (rng.rand(batch, S) < 0.15).astype(np.float32),
    }


def _run_arm(checkpoints, feed):
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 42
    with fluid.program_guard(main, startup):
        _, loss = models.bert.build_bert_pretrain(
            vocab=1000, max_len=16, d_model=32, d_ff=64, n_head=2,
            n_layer=2, checkpoints=checkpoints)
    n_seg = 0
    rep = getattr(main, '_recompute_report', None)
    if rep is not None:
        n_seg = len(rep.details['segments'])
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.core.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
        stats = compiled_memory_stats(main, feed=feed, fetch_list=[loss],
                                      scope=scope, exe=exe)
        losses = [np.asarray(exe.run(main, feed=feed,
                                     fetch_list=[loss])[0])
                  for _ in range(STEPS)]
    return np.stack(losses), stats, n_seg


def main():
    feed = _feed()
    base, base_mem, base_seg = _run_arm(None, feed)
    remat, remat_mem, remat_seg = _run_arm(True, feed)

    assert base_seg == 0, base_seg
    assert remat_seg > 0, \
        "checkpoints=True applied 0 segments (pass regressed)"
    print("remat arm: %d recompute segment(s)" % remat_seg)

    # 1. bit parity, dropout on
    assert np.isfinite(base).all() and np.isfinite(remat).all()
    if not np.array_equal(base, remat):
        raise AssertionError(
            "losses diverged (must be BITWISE identical):\n"
            "  base  %s\n  remat %s" % (base.ravel(), remat.ravel()))
    print("bit parity over %d steps OK: %s" % (STEPS, base.ravel()))

    # 2. measured temp-bytes reduction at the acceptance bar
    if base_mem is None or remat_mem is None:
        print("backend exposes no memory_analysis(); skipping the "
              "reduction gate")
        return
    bt, rt = base_mem['temp_bytes'], remat_mem['temp_bytes']
    cut = 1.0 - rt / float(bt)
    print("compiled temp bytes (batch=%d): base %d -> remat %d "
          "(-%.1f%%); peak %d -> %d" % (BATCH, bt, rt, 100 * cut,
                                        base_mem['peak_bytes'],
                                        remat_mem['peak_bytes']))
    assert cut >= REDUCTION_BAR, (
        "measured temp-bytes reduction %.1f%% below the %.0f%% bar"
        % (100 * cut, 100 * REDUCTION_BAR))
    print("remat smoke OK")


if __name__ == '__main__':
    main()
