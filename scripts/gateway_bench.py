#!/usr/bin/env python
"""Measure the HTTP gateway's wire overhead (ISSUE 19, PERF_NOTES
round 21).

    python scripts/gateway_bench.py [N]

Four closed-loop arms over the same decode artifact, same prompts,
same max_new_tokens (sequential, so the numbers are per-request
latency, not throughput):

  direct            DecodingPredictor.submit().result()   (in-process)
  gateway/direct    POST /v1/decode stream=false over HTTP loopback
  fleet             FleetRouter.submit().result()         (1 replica)
  gateway/fleet     POST /v1/decode stream=false -> FleetRouter

plus one SSE arm (gateway/direct, stream=true) so the streaming path's
first-token and total latency are on the record. Prints a markdown
table of p50/p99 per arm and the gateway-minus-backend delta — the
price of the HTTP door.
"""
import json
import os
import sys
import tempfile
import time
import urllib.request
import warnings

os.environ.setdefault('JAX_PLATFORMS', 'cpu')
os.environ.setdefault('PTPU_PLATFORM', 'cpu')

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import numpy as np  # noqa: E402

import paddle_tpu as fluid  # noqa: E402
from paddle_tpu.inference import (DecodingPredictor,  # noqa: E402
                                  FleetRouter, Gateway, export_decode)

VOCAB = 211
MAX_NEW = 24


def _export(art):
    from models.transformer import build_decode_spec
    scope = fluid.core.Scope()
    with fluid.scope_guard(scope), fluid.unique_name.guard():
        spec = build_decode_spec(vocab=VOCAB, d_model=48, n_head=4,
                                 n_layer=2, d_ff=96, max_slots=4,
                                 max_cache_len=128, prompt_buckets=(4, 8),
                                 eos_id=1)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(spec['startup'])
        export_decode(spec, art, scope=scope)


def _prompts(n, seed=5):
    rng = np.random.RandomState(seed)
    return [rng.randint(2, VOCAB, rng.randint(2, 9)) for _ in range(n)]


def _pcts(ms):
    a = np.sort(np.asarray(ms))
    return (float(np.percentile(a, 50)), float(np.percentile(a, 99)))


def _bench_backend(target, prompts):
    ms = []
    for p in prompts:
        t0 = time.perf_counter()
        target.submit(p, max_new_tokens=MAX_NEW).result(300)
        ms.append((time.perf_counter() - t0) * 1e3)
    return ms


def _bench_http(url, prompts, stream):
    ms = []
    for p in prompts:
        body = json.dumps({'prompt': [int(t) for t in p],
                           'max_new_tokens': MAX_NEW,
                           'stream': stream}).encode()
        req = urllib.request.Request(url + '/v1/decode', data=body,
                                     method='POST')
        req.add_header('Content-Type', 'application/json')
        t0 = time.perf_counter()
        with urllib.request.urlopen(req, timeout=300) as r:
            r.read()
        ms.append((time.perf_counter() - t0) * 1e3)
    return ms


def main():
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 200
    tmp = tempfile.mkdtemp(prefix='ptpu_gateway_bench_')
    art = os.path.join(tmp, 'decode_art')
    _export(art)
    warm, prompts = _prompts(16, seed=3), _prompts(n)
    rows = []

    with DecodingPredictor(art, platform='cpu') as pred:
        pred.warmup()
        _bench_backend(pred, warm)
        direct = _bench_backend(pred, prompts)
        rows.append(('direct', _pcts(direct), None))
        with Gateway(pred) as gw:
            _bench_http(gw.url, warm, stream=False)
            gw_direct = _bench_http(gw.url, prompts, stream=False)
            rows.append(('gateway/direct', _pcts(gw_direct), 'direct'))
            _bench_http(gw.url, warm, stream=True)
            gw_sse = _bench_http(gw.url, prompts, stream=True)
            rows.append(('gateway/direct SSE', _pcts(gw_sse), 'direct'))

    with warnings.catch_warnings():
        warnings.simplefilter('ignore')
        with FleetRouter(art, replicas=1, platform='cpu',
                         inflight_per_replica=4) as router:
            router.hb_timeout_s = 60.0
            _bench_backend(router, warm)
            fleet = _bench_backend(router, prompts)
            rows.append(('fleet', _pcts(fleet), None))
            with Gateway(router) as gw:
                _bench_http(gw.url, warm, stream=False)
                gw_fleet = _bench_http(gw.url, prompts, stream=False)
                rows.append(('gateway/fleet', _pcts(gw_fleet), 'fleet'))

    base = {name: p for name, p, _ in rows}
    print('\n%d sequential requests/arm, %d new tokens each '
          '(CPU dispatch-floor proxy)\n' % (n, MAX_NEW))
    print('| arm                | p50 ms | p99 ms | door cost p50 | p99 |')
    print('|--------------------|-------:|-------:|--------------:|----:|')
    for name, (p50, p99), ref in rows:
        if ref:
            d50, d99 = p50 - base[ref][0], p99 - base[ref][1]
            print('| %-18s | %6.2f | %6.2f | %+12.2f | %+3.2f |'
                  % (name, p50, p99, d50, d99))
        else:
            print('| %-18s | %6.2f | %6.2f | %13s | %3s |'
                  % (name, p50, p99, '-', '-'))
    print()


if __name__ == '__main__':
    main()
