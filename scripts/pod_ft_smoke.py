"""Pod-scale fault-tolerance smoke (ISSUE 10, wired into ci.sh).

1. An uninterrupted 2-process composed-mesh pod run (dp spans hosts x mp
   within; sharded two-phase checkpoints every 4 steps): both hosts must
   report IDENTICAL losses and a checkpoint stall < 2% of run time.
2. The same pod on a fresh checkpoint dir with host 1 SIGKILLed
   mid-training: the survivor must exit in bounded time (heartbeat
   watchdog), never wedge.
3. A full-pod restart on that dir: resumes from the newest POD-committed
   checkpoint in seconds (warm compile cache), and every host's losses +
   final params digest BIT-MATCH the uninterrupted run.
4. tools/chaos.py --pod 2 with random corruption: kill-one-host rounds +
   checkpoint rot, exit 0 required.
"""
import importlib.util
import os
import shutil
import signal
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

_spec = importlib.util.spec_from_file_location(
    'ptpu_chaos', os.path.join(REPO, 'tools', 'chaos.py'))
chaos = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(chaos)

STALL_BUDGET_PCT = 2.0


def read_stall(path):
    for line in open(path):
        if line.startswith('STALL'):
            return float(line.split()[1])
    return None


def main():
    work = tempfile.mkdtemp(prefix='ptpu-pod-smoke-')
    cache = os.path.join(work, 'compile-cache')
    ckpt = os.path.join(work, 'ckpts')
    outs = lambda tag: [os.path.join(work, '%s-r%d.txt' % (tag, r))  # noqa: E731,E501
                        for r in range(2)]

    def fail(msg):
        print('[pod-smoke] FAIL: %s (workdir kept at %s)' % (msg, work))
        return 1

    # 1) uninterrupted reference
    t0 = time.time()
    ref_outs = outs('ref')
    res = chaos.run_pod(os.path.join(work, 'ref-ckpts'), ref_outs,
                        total=12, every=4, cache_dir=cache)
    if any(rc != 0 for rc, _ in res):
        return fail('reference pod run failed:\n%s'
                    % '\n'.join(e[-1200:] for _, e in res))
    refs = [chaos.read_out(p) for p in ref_outs]
    if refs[0][1] != refs[1][1]:
        return fail('replicated losses differ between hosts')
    stalls = [read_stall(p) for p in ref_outs]
    if any(s is None or s >= STALL_BUDGET_PCT for s in stalls):
        return fail('checkpoint stall %r over the %.1f%% budget'
                    % (stalls, STALL_BUDGET_PCT))
    print('[pod-smoke] reference: 12 steps, losses identical across '
          'hosts, ckpt stall %s%%  %.1fs'
          % (['%.3f' % s for s in stalls], time.time() - t0))

    # 2) kill host 1 mid-training
    t0 = time.time()
    res = chaos.run_pod(ckpt, outs('kill'), total=12, every=4,
                        kill_rank=1, kill_at=8, cache_dir=cache)
    if res[1][0] != -signal.SIGKILL:
        return fail('victim exited %s, expected SIGKILL' % res[1][0])
    if any('WEDGED' in err for _, err in res):
        return fail('survivor never detected the dead host')
    print('[pod-smoke] kill round: victim SIGKILLed at step 8, survivor '
          'exited %s in bounded time  %.1fs'
          % (res[0][0], time.time() - t0))

    # 3) full-pod resume: seconds-scale off the warm compile cache
    t0 = time.time()
    fin_outs = outs('final')
    res = chaos.run_pod(ckpt, fin_outs, total=12, every=4,
                        cache_dir=cache)
    resume_s = time.time() - t0
    if any(rc != 0 for rc, _ in res):
        return fail('resume pod run failed:\n%s'
                    % '\n'.join(e[-1200:] for _, e in res))
    for r in range(2):
        resume, losses, sha = chaos.read_out(fin_outs[r])
        if resume < 4:
            return fail('host %d resumed at step %d — no pod-committed '
                        'checkpoint was restored' % (r, resume))
        for idx, v in losses.items():
            if v != refs[r][1].get(idx):
                return fail('host %d: loss at step %d diverged after '
                            'resume' % (r, idx))
        if sha != refs[r][2]:
            return fail('host %d: final params digest diverged' % r)
    print('[pod-smoke] resume: full pod restarted from step %d with '
          'bit/loss parity in %.1fs (warm compile cache)'
          % (chaos.read_out(fin_outs[0])[0], resume_s))

    # 3b) idempotent resume at the final step: re-launching a completed
    # pod must neither retrain nor destroy the committed checkpoint
    res = chaos.run_pod(ckpt, outs('again'), total=12, every=4,
                        cache_dir=cache)
    if any(rc != 0 for rc, _ in res):
        return fail('resume-at-final-step pod run failed:\n%s'
                    % '\n'.join(e[-1200:] for _, e in res))
    for r in range(2):
        resume, _losses, sha = chaos.read_out(
            os.path.join(work, 'again-r%d.txt' % r))
        if resume != 12 or sha != refs[r][2]:
            return fail('idempotent re-launch diverged (resume=%s)'
                        % resume)
    print('[pod-smoke] idempotent re-launch: resumed at 12, committed '
          'checkpoint preserved')

    # 4) chaos pod rounds with corruption
    rc = chaos.main(['--pod', '2', '--rounds', '1', '--total', '12',
                     '--every', '4', '--corrupt', 'random', '--seed', '5'])
    if rc != 0:
        return fail('chaos --pod exited %d' % rc)

    shutil.rmtree(work, ignore_errors=True)
    print('[pod-smoke] OK')
    return 0


if __name__ == '__main__':
    sys.exit(main())
