"""MFU-pass smoke for CI (ISSUE 16): both round-18 rewrites A/B'd in one
session on CPU.

1. GoogLeNet horizontal_fuse: the widened train program must track the
   unfused one to ~1e-5 relative per step (XLA:CPU reduces the widened
   conv with a different grouping than three narrow convs — last-ulp
   drift, tests/test_horizontal_fuse.py documents the same tolerance;
   matmul nets are bit-exact). Speedup is NOT asserted on CPU: XLA:CPU
   runs conv bodies through a different code path and the MXU-padding
   win this pass targets does not exist there (PERF_NOTES round 6/18) —
   the A/B table is emitted for the log instead.
2. Stacked-LSTM fuse_layers: the single-scan multi-layer body must be
   BIT-IDENTICAL to the per-layer path across Adam steps (same rng
   stream, same gate math). Speedup is also not asserted: the fused win
   is scan-loop dispatch overhead on the accelerator; on CPU the two
   bodies are within noise of each other. Table emitted.

Exits non-zero on any parity violation. Runtime: ~60 s on 2 CPU cores.
"""
import json
import os
import sys
import time

os.environ.setdefault('JAX_PLATFORMS', 'cpu')
os.environ.setdefault('PTPU_PLATFORM', 'cpu')
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def _emit_table(title, headers, rows):
    print('\n%s' % title)
    print('| ' + ' | '.join(headers) + ' |')
    print('|' + '|'.join('---' for _ in headers) + '|')
    for row in rows:
        print('| ' + ' | '.join(str(c) for c in row) + ' |')
    print('', flush=True)


def _timed_ms(run, warmup=1, reps=3):
    for _ in range(warmup):
        run()
    t0 = time.perf_counter()
    for _ in range(reps):
        run()
    return (time.perf_counter() - t0) / reps * 1e3


def googlenet_ab():
    import paddle_tpu as fluid
    from paddle_tpu.passes.horizontal_fuse import horizontal_fuse_program
    from models.googlenet import build_train_net

    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 11
    with fluid.program_guard(main, startup):
        _img, _lab, loss, _acc = build_train_net(
            dshape=(3, 64, 64), class_dim=10, lr=0.001)
    fused, report = horizontal_fuse_program(main, fetch_names=[loss.name])
    if report.details['convs_fused'] != 27:
        raise SystemExit('expected 27 inception convs fused, got %r'
                         % report.details['convs_fused'])

    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.core.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
        snap = {k: np.asarray(v) for k, v in scope._vars.items()
                if v is not None}
    rng = np.random.RandomState(0)
    feed = {'data': rng.randn(4, 3, 64, 64).astype(np.float32),
            'label': rng.randint(0, 10, (4, 1)).astype(np.int64)}

    arms = {}
    for name, prog in (('base', main), ('hfused', fused)):
        sc = fluid.core.Scope()
        for k, v in snap.items():
            sc.set(k, v)
        with fluid.scope_guard(sc):
            losses = [float(np.asarray(
                exe.run(prog, feed=feed, fetch_list=[loss.name])[0])
                .reshape(-1)[0]) for _ in range(2)]
            ms = _timed_ms(lambda: np.asarray(
                exe.run(prog, feed=feed, fetch_list=[loss.name],
                        return_numpy=False)[0]))
        arms[name] = {'losses': losses, 'ms_step': ms}

    base, hf = arms['base'], arms['hfused']
    dloss = max(abs(a - b) for a, b in zip(base['losses'], hf['losses']))
    rel = dloss / max(abs(v) for v in base['losses'])
    _emit_table(
        'googlenet horizontal_fuse A/B (train, batch 4, 64x64, CPU)',
        ['arm', 'convs fused', 'ms/step', 'speedup', 'parity rel |d|'],
        [['base', 0, '%.1f' % base['ms_step'], '1.00', '-'],
         ['hfused', report.details['convs_fused'],
          '%.1f' % hf['ms_step'],
          '%.2f' % (base['ms_step'] / hf['ms_step']),
          '%.2e' % rel]])
    if rel > 1e-5:
        raise SystemExit('googlenet hfused parity %.3e > 1e-5: %r vs %r'
                         % (rel, base['losses'], hf['losses']))
    return {'smoke': 'googlenet_hfuse_ab',
            'convs_fused': report.details['convs_fused'],
            'parity_rel': rel,
            'speedup_cpu': round(base['ms_step'] / hf['ms_step'], 3),
            'ok': True}


def lstm_ab():
    import paddle_tpu as fluid
    from paddle_tpu import unique_name
    from models.stacked_lstm import build_stacked_lstm_train

    def build(fuse):
        main, startup = fluid.Program(), fluid.Program()
        main.random_seed = startup.random_seed = 7
        with unique_name.guard():
            with fluid.program_guard(main, startup):
                _ids, _lab, loss, _fl = build_stacked_lstm_train(
                    batch=8, vocab=200, emb_dim=16, hidden=16,
                    num_layers=3, seq_len=12, fuse_layers=fuse)
        return main, startup, loss

    rng = np.random.RandomState(1)
    feed = {'ids': rng.randint(1, 200, (8, 12)).astype(np.int64),
            'label': rng.randint(0, 2, (8, 1)).astype(np.int64)}
    arms = {}
    for name, fuse in (('perlayer', False), ('fused', True)):
        main, startup, loss = build(fuse)
        exe = fluid.Executor(fluid.CPUPlace())
        scope = fluid.core.Scope()
        with fluid.scope_guard(scope):
            exe.run(startup)
            losses = [float(np.asarray(
                exe.run(main, feed=feed, fetch_list=[loss])[0])
                .reshape(-1)[0]) for _ in range(3)]
            ms = _timed_ms(lambda: np.asarray(
                exe.run(main, feed=feed, fetch_list=[loss],
                        return_numpy=False)[0]))
        arms[name] = {'losses': losses, 'ms_step': ms}

    pl, fu = arms['perlayer'], arms['fused']
    _emit_table(
        'stacked-LSTM fuse_layers A/B (3 layers, batch 8, CPU)',
        ['arm', 'ms/step', 'speedup', 'losses bit-equal'],
        [['perlayer', '%.1f' % pl['ms_step'], '1.00', '-'],
         ['fused', '%.1f' % fu['ms_step'],
          '%.2f' % (pl['ms_step'] / fu['ms_step']),
          pl['losses'] == fu['losses']]])
    if pl['losses'] != fu['losses']:
        raise SystemExit('fused lstm losses diverged: %r vs %r'
                         % (pl['losses'], fu['losses']))
    return {'smoke': 'lstm_fuse_layers_ab',
            'speedup_cpu': round(pl['ms_step'] / fu['ms_step'], 3),
            'ok': True}


def main():
    print(json.dumps(googlenet_ab()), flush=True)
    print(json.dumps(lstm_ab()), flush=True)
    print('mfu smoke OK')
    return 0


if __name__ == '__main__':
    sys.exit(main())
