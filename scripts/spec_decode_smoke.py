#!/usr/bin/env python
"""Smoke the speculative-decode tier (ISSUE 17 CI satellite): build a
tiny decoder LM whose export carries a draft_k=6 verify program over the
block-paged KV cache, then A/B an acceptance-friendly repetitive-suffix
workload through draft-and-verify decode against plain
one-token-per-dispatch decode, in the single-stream latency-bound
regime speculative decoding exists for (batch-1 decode leaves the chip
idle; accepted drafts buy tokens per dispatch the way batching buys
tokens per step elsewhere).

    python scripts/spec_decode_smoke.py

The workload is screened for acceptance-friendliness the way real
deployments route traffic to drafting replicas: candidate prompts tile
short patterns (retrieval-grounded / structured-output shape), are
plain-decoded once (untimed), and the most n-gram-predictable
transcripts form the timed A/B set.

Asserts, on the CPU dispatch-floor proxy:
  * per-request transcripts BIT-IDENTICAL across all three arms (greedy
    longest-prefix acceptance is lossless by construction — every
    emitted token is the target model's own argmax);
  * n-gram-drafted decode >= 1.5x plain tokens/s on the screened
    workload;
  * an adversarial always-wrong drafter costs <= 1.15x plain wall time
    (the acceptance-aware exponential backoff caps mis-speculation at
    ~log(max_new) verify ticks per request — the precondition for
    leaving drafting ON for mixed traffic).
Exits non-zero on any failed bar.
"""
import json
import os
import sys
import tempfile
import time

os.environ.setdefault('JAX_PLATFORMS', 'cpu')
os.environ.setdefault('PTPU_PLATFORM', 'cpu')

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import numpy as np  # noqa: E402

import paddle_tpu as fluid  # noqa: E402
from paddle_tpu.inference import (DecodingPredictor,  # noqa: E402
                                  NgramDrafter, export_decode)

# tiny weights keep every dispatch near the fixed floor (the regime the
# tokens-per-dispatch win is about); max_slots=2 so the verify program
# carries little dead padding in the batch-1 regime under test
VOCAB, SLOTS, K = 251, 2, 6
MAX_NEW = int(os.environ.get('PTPU_SPEC_SMOKE_MAX_NEW', '96'))
N_REQ = int(os.environ.get('PTPU_SPEC_SMOKE_REQS', '6'))
N_CAND = int(os.environ.get('PTPU_SPEC_SMOKE_CANDS', '32'))
TRIALS = int(os.environ.get('PTPU_SPEC_SMOKE_TRIALS', '3'))


class _WrongDrafter(object):
    """Adversarial drafter: proposes a constant alphabet disjoint from
    the prompts — (almost) every proposal is rejected, making the run a
    pure mis-speculation stress."""

    def draft(self, tokens, k):
        return [0] * k


def _export(art_dir):
    from models.transformer import build_decode_spec
    scope = fluid.core.Scope()
    with fluid.scope_guard(scope), fluid.unique_name.guard():
        spec = build_decode_spec(
            vocab=VOCAB, d_model=16, n_head=2, n_layer=2, d_ff=32,
            max_slots=SLOTS, max_cache_len=128, prompt_buckets=(8, 16),
            block_size=8, eos_id=1, draft_k=K)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(spec['startup'])
        export_decode(spec, art_dir, scope=scope)


def _candidates(n):
    """Self-repetitive suffixes: each prompt tiles a short pattern, the
    shape retrieval-grounded and structured-output serving traffic
    takes (and the n-gram drafter exists for)."""
    rng = np.random.RandomState(7)
    out = []
    for _ in range(n):
        pat = rng.randint(2, VOCAB, int(rng.randint(2, 4)))
        out.append(np.tile(pat, 8)[:int(rng.randint(8, 17))])
    return out


def _predictability(prompt, out):
    """Teacher-forced n-gram hit rate over a finished transcript: the
    screening score for the acceptance-friendly A/B set."""
    d = NgramDrafter()
    full = list(prompt) + out
    hits = tot = 0
    for i in range(len(prompt), len(full) - 1):
        for j, t in enumerate(d.draft(full[:i + 1], K)):
            tot += 1
            if i + 1 + j < len(full) and full[i + 1 + j] == t:
                hits += 1
            else:
                break
    return hits / max(tot, 1)


def _arm(art, prompts, draft=None):
    """One single-stream serving arm: decode the prompts one at a time,
    return (transcripts, wall seconds, stats snapshot). Trials keep the
    MIN wall time — CPU scheduler jitter only ever inflates a run."""
    best = None
    for _ in range(TRIALS):
        pred = DecodingPredictor(art, draft=draft)
        try:
            pred.warmup()
            pred.stats.reset()
            t0 = time.perf_counter()
            out = [pred.generate(p, max_new_tokens=MAX_NEW)
                   for p in prompts]
            dt = time.perf_counter() - t0
            snap = pred.stats.snapshot()
        finally:
            pred.close()
        if best is not None and out != best[0]:
            print('FAIL: transcripts varied across trials',
                  file=sys.stderr)
            sys.exit(1)
        if best is None or dt < best[1]:
            best = (out, dt, snap)
    return best


def main():
    with tempfile.TemporaryDirectory() as d:
        art = os.path.join(d, 'spec_art')
        _export(art)
        # -- screen: keep the most drafter-predictable transcripts ----
        cands = _candidates(N_CAND)
        pred = DecodingPredictor(art)
        try:
            pred.warmup()
            outs = [pred.generate(q, max_new_tokens=MAX_NEW)
                    for q in cands]
        finally:
            pred.close()
        scored = sorted(zip(cands, outs),
                        key=lambda co: -_predictability(*co))
        prompts = [c for c, _ in scored[:N_REQ]]
        pred_rates = [_predictability(c, o) for c, o in scored[:N_REQ]]
        print('screened %d/%d candidates, teacher-forced n-gram hit '
              'rates %s' % (N_REQ, N_CAND,
                            ['%.2f' % r for r in pred_rates]))

        plain, plain_s, plain_snap = _arm(art, prompts)
        spec, spec_s, spec_snap = _arm(art, prompts, draft='ngram')
        zero, zero_s, zero_snap = _arm(art, prompts,
                                       draft=_WrongDrafter())

        tokens = sum(len(t) for t in plain)
        plain_tok_s = tokens / plain_s
        spec_tok_s = sum(len(t) for t in spec) / spec_s
        speedup = spec_tok_s / plain_tok_s
        slowdown = zero_s / plain_s
        print('plain : %7.1f tok/s  (%d requests, %d tokens, %d step '
              'dispatches)' % (plain_tok_s, N_REQ, tokens,
                               plain_snap['steps']))
        print('ngram : %7.1f tok/s  (%.2fx; %d verify dispatches, '
              'acc %.2f, %.2f tok/dispatch)'
              % (spec_tok_s, speedup, spec_snap['verify_steps'],
                 spec_snap['acc_rate'],
                 spec_snap['tokens_per_dispatch']))
        print('wrong : %7.1f tok/s  (%.2fx wall vs plain; %d verify '
              'dispatches after backoff, acc %.2f)'
              % (sum(len(t) for t in zero) / zero_s, slowdown,
                 zero_snap['verify_steps'], zero_snap['acc_rate']))
        print(json.dumps({
            'plain_tok_s': round(plain_tok_s, 1),
            'spec_tok_s': round(spec_tok_s, 1),
            'speedup': round(speedup, 2),
            'acc_rate': spec_snap['acc_rate'],
            'tokens_per_dispatch': spec_snap['tokens_per_dispatch'],
            'zero_acc_slowdown': round(slowdown, 3)}))
        if spec != plain or zero != plain:
            print('FAIL: speculative transcripts diverge from plain '
                  'decode', file=sys.stderr)
            return 1
        if spec_snap['drafted'] == 0 or spec_snap['accepted'] == 0:
            print('FAIL: the n-gram arm never drafted/accepted — '
                  'vacuous A/B', file=sys.stderr)
            return 1
        if speedup < 1.5:
            print('FAIL: speculative decode %.2fx < 1.5x plain tokens/s'
                  % speedup, file=sys.stderr)
            return 1
        if slowdown > 1.15:
            print('FAIL: zero-acceptance drafting cost %.2fx > 1.15x '
                  'plain wall time' % slowdown, file=sys.stderr)
            return 1
        print('spec decode smoke OK: %.2fx tokens/s at acc %.2f '
              '(%.2f tok/dispatch), bit-identical transcripts, '
              'mis-speculation overhead %.2fx'
              % (speedup, spec_snap['acc_rate'],
                 spec_snap['tokens_per_dispatch'], slowdown))
    return 0


if __name__ == '__main__':
    sys.exit(main())
