"""Block-granular KV-cache management (ISSUE 13): BlockManager edge
cases — refcount-to-zero frees, copy-on-write ownership, prefix-hash
collision safety, LRU eviction under pressure — plus the served block
tier: bit-identity with the slot layout (greedy, beam, chunked prefill,
int8 pages), CoW under beam divergence at block boundaries, and prefix
sharing's capacity effect."""
import json
import os

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu.inference import DecodingPredictor, export_decode
from paddle_tpu.inference.kv_blocks import (BlockManager,
                                            BlockPoolExhausted,
                                            TRASH_BLOCK)

VOCAB, SLOTS, CACHE = 41, 4, 64


# -- allocator units ---------------------------------------------------------

def test_capacity_excludes_trash_block():
    m = BlockManager(num_blocks=8, block_size=4)
    assert m.capacity() == 7
    assert m.free_blocks() == 7
    got = m.alloc(7)
    assert TRASH_BLOCK not in got
    assert sorted(got) == list(range(1, 8))
    with pytest.raises(ValueError):
        BlockManager(num_blocks=1, block_size=4)


def test_refcount_to_zero_frees():
    m = BlockManager(num_blocks=6, block_size=2)
    blocks = m.alloc(3)
    m.incref(blocks)                      # share (beam fork)
    m.decref(blocks)
    assert m.free_blocks() == 2           # still referenced once
    assert m.in_use() == 3
    m.decref(blocks)                      # refcount-to-zero
    assert m.free_blocks() == 5
    assert m.in_use() == 0
    st = m.stats()
    assert st['allocs'] == 3 and st['frees'] == 3
    # freed blocks are allocatable again
    assert sorted(m.alloc(5)) == sorted(range(1, 6))


def test_double_free_and_foreign_incref_raise():
    m = BlockManager(num_blocks=4, block_size=2)
    b = m.alloc(1)
    m.decref(b)
    with pytest.raises(RuntimeError, match='double free'):
        m.decref(b)
    with pytest.raises(RuntimeError, match='unallocated'):
        m.incref(b)
    # trash block refs are ignored, never counted
    m.incref([TRASH_BLOCK])
    m.decref([TRASH_BLOCK])
    assert m.refcount(TRASH_BLOCK) == 0


def test_writable_is_sole_ownership():
    m = BlockManager(num_blocks=4, block_size=2)
    b = m.alloc(1)[0]
    assert m.writable(b)
    m.incref([b])                         # shared: fork / prefix hit
    assert not m.writable(b)              # must copy-on-write
    m.decref([b])
    assert m.writable(b)
    assert not m.writable(TRASH_BLOCK)    # trash is never writable


def test_alloc_all_or_nothing_when_pinned():
    m = BlockManager(num_blocks=4, block_size=2)
    m.alloc(2)
    with pytest.raises(BlockPoolExhausted):
        m.alloc(2)                        # only 1 free, nothing evictable
    assert m.free_blocks() == 1           # failed alloc leaked nothing
    assert m.alloc(1)


def test_prefix_register_match_and_refcounts():
    m = BlockManager(num_blocks=16, block_size=4)
    tokens = list(range(100, 111))        # 11 tokens = 2 full blocks + 3
    blocks = m.alloc(3)
    m.register_prefix(tokens, blocks)     # publishes 1- and 2-block entries
    assert m.prefix_entries() == 2
    # full prompt released: prefix refs keep the FULL blocks alive
    m.decref(blocks)
    assert m.in_use() == 2                # tail block freed, 2 pinned
    shared, covered = m.match_prefix(tokens)
    assert covered == 8 and shared == blocks[:2]
    st = m.stats()
    assert st['prefix_hits'] == 1 and st['prefix_tokens_reused'] == 8
    # shorter prompt sharing only the first block hits the 1-block entry
    shared1, covered1 = m.match_prefix(tokens[:4] + [7, 8])
    assert covered1 == 4 and shared1 == blocks[:1]
    # a prompt the cache covers ENTIRELY still leaves its last token
    # uncovered: the admitting request must compute first-token logits
    sh, cov = m.match_prefix(tokens[:8])
    assert cov == 4 and sh == blocks[:1]
    m.decref(shared + shared1 + sh)
    assert m.in_use() == 2


def test_prefix_hash_collision_never_aliases():
    # force EVERY key onto one bucket: a colliding entry whose tokens
    # differ must be a miss, never an alias onto foreign blocks
    m = BlockManager(num_blocks=16, block_size=2,
                     hash_fn=lambda b: 'same')
    a = m.alloc(2)
    m.register_prefix([1, 2, 3, 4], a)
    b = m.alloc(2)
    m.register_prefix([9, 8, 7, 6], b)
    sh_a, cov_a = m.match_prefix([1, 2, 3, 4, 5])
    sh_b, cov_b = m.match_prefix([9, 8, 7, 6, 5])
    assert (sh_a, cov_a) == (a, 4)
    assert (sh_b, cov_b) == (b, 4)
    miss, cov = m.match_prefix([2, 1, 8, 9, 5])
    assert (miss, cov) == ([], 0)
    assert m.stats()['prefix_misses'] == 1


def test_lru_eviction_under_pressure():
    m = BlockManager(num_blocks=9, block_size=2)
    a, b = m.alloc(2), m.alloc(2)
    m.register_prefix([1, 2, 3, 4], a)
    m.register_prefix([5, 6, 7, 8], b)
    m.decref(a)
    m.decref(b)                           # both live only via the cache
    assert m.in_use() == 4 and m.free_blocks() == 4
    m.match_prefix([1, 2, 3, 4, 0])       # touch a: b becomes LRU
    m.decref(a)                           # drop the match's refs again
    got = m.alloc(6)                      # needs eviction to cover
    assert len(got) == 6
    st = m.stats()
    assert st['evictions'] >= 1
    # a (recently used) survived where possible; b evicted first
    sh, cov = m.match_prefix([5, 6, 7, 8, 0])
    assert (sh, cov) == ([], 0)


def test_reserve_preflight_contract():
    m = BlockManager(num_blocks=6, block_size=2)
    a = m.alloc(2)
    m.register_prefix([1, 2, 3, 4], a)
    m.decref(a)                           # evictable
    assert m.reserve(5)                   # evicts the prefix entry
    for _ in range(5):
        m.alloc(1)                        # cannot fail after reserve
    assert not m.reserve(1)               # fully pinned now
    m.alloc(1) if m.free_blocks() else None
    with pytest.raises(BlockPoolExhausted):
        m.alloc(1)


def test_evict_all_and_stats_keys():
    m = BlockManager(num_blocks=8, block_size=2)
    a = m.alloc(2)
    m.register_prefix([1, 2, 3, 4], a)
    m.decref(a)
    m.evict_all_prefixes()
    assert m.prefix_entries() == 0 and m.in_use() == 0
    st = m.stats()
    for k in ('num_blocks', 'block_size', 'blocks_in_use', 'blocks_peak',
              'blocks_free', 'allocs', 'frees', 'prefix_entries',
              'prefix_hits', 'prefix_misses', 'prefix_hit_rate',
              'prefix_tokens_reused', 'evictions'):
        assert k in st, k


def test_doomed_alloc_does_not_wipe_prefix_cache():
    """An over-capacity alloc whose shortfall eviction CANNOT cover
    (every prefix entry's blocks also table-pinned) must fail without
    evicting anything: wiping the cache would trade the prefix-sharing
    capacity win for zero freed blocks."""
    m = BlockManager(num_blocks=6, block_size=2)
    a = m.alloc(3)
    m.register_prefix([1, 2, 3, 4, 5, 6], a)   # entries share pinned blocks
    m.alloc(2)                                 # pool now fully pinned
    with pytest.raises(BlockPoolExhausted):
        m.alloc(1)
    assert m.prefix_entries() == 3             # cache survived the miss
    assert not m.reserve(1)
    assert m.prefix_entries() == 3
    m.decref(a)   # table gone: entries alone hold the prefix blocks
    got, cov = m.match_prefix([1, 2, 3, 4, 5, 6, 7])
    assert cov == 6 and got == a


# -- served block tier -------------------------------------------------------

def _build(tmp, **kw):
    from models.transformer import build_decode_spec
    scope = fluid.core.Scope()
    with fluid.scope_guard(scope), fluid.unique_name.guard():
        spec = build_decode_spec(
            vocab=VOCAB, d_model=16, n_head=2, n_layer=2, d_ff=32,
            max_slots=SLOTS, max_cache_len=CACHE, eos_id=1, **kw)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(spec['startup'])
        export_decode(spec, tmp, scope=scope)
    return tmp


@pytest.fixture(scope='module')
def arts(tmp_path_factory):
    """Slot/block artifact pairs (f32 and int8 tiers) of the same tiny
    LM: the slot tier is the bit-identity reference."""
    t = tmp_path_factory.mktemp('kvblocks')
    return {
        'slot': _build(str(t / 'slot'), prompt_buckets=(4, 8)),
        'block': _build(str(t / 'block'), prompt_buckets=(4, 8),
                        block_size=4),
        'slot8': _build(str(t / 'slot8'), prompt_buckets=(4, 8),
                        kv_cache_dtype='int8'),
        'block8': _build(str(t / 'block8'), prompt_buckets=(4, 8),
                         block_size=4, kv_cache_dtype='int8'),
    }


def _prompts(seed, n, lo=2):
    rng = np.random.RandomState(seed)
    return [rng.randint(lo, VOCAB, int(rng.randint(2, 9)))
            for _ in range(n)]


def test_block_artifact_layout(arts):
    from paddle_tpu.inference import decoding
    with open(os.path.join(arts['block'],
                           decoding._DECODE_SIGNATURE)) as f:
        sig = json.load(f)
    assert sig['layout'] == 'block'
    blk = sig['block']
    assert blk['block_size'] == 4
    assert blk['max_blocks_per_slot'] == CACHE // 4
    assert blk['num_blocks'] == SLOTS * (CACHE // 4) + 1
    for e in sig['state']:
        assert e['shape'][:2] == [blk['num_blocks'], 4]
    for d in ([decoding._STEP_DIR, decoding._REORDER_DIR,
               decoding._BLOCKCOPY_DIR] +
              [decoding._CHUNK_DIR % c for c in sig['chunk_buckets']]):
        assert os.path.exists(os.path.join(arts['block'], d,
                                           'module.jaxexport'))
        assert os.path.exists(os.path.join(arts['block'], d,
                                           'aot_cpu.jaxexec'))


def test_block_greedy_and_beam_bit_identical_to_slot(arts):
    prompts = _prompts(31, 8)
    with DecodingPredictor(arts['slot']) as ps:
        g_ref = [ps.generate(p, max_new_tokens=10) for p in prompts]
        b_ref = [ps.generate(p, max_new_tokens=8, beam=3)
                 for p in prompts[:3]]
    with DecodingPredictor(arts['block']) as pb:
        assert pb.layout == 'block'
        g = [pb.generate(p, max_new_tokens=10) for p in prompts]
        b = [pb.generate(p, max_new_tokens=8, beam=3)
             for p in prompts[:3]]
        snap = pb.stats.snapshot()
    assert g == g_ref
    for (i1, s1), (i2, s2) in zip(b_ref, b):
        np.testing.assert_array_equal(i1, i2)
        np.testing.assert_array_equal(s1, s2)
    # beam history moves were table permutations + block CoW — and the
    # copies dispatched blocks, not slot rows
    assert snap['cow_blocks'] > 0
    assert snap['blockcopies'] <= snap['cow_blocks']


def test_block_int8_pages_bit_identical_to_slot_int8(arts):
    """int8 KV pages compose with block paging (round-14 x ISSUE 13):
    per-page scales ride the pool and, with a COLD prefix cache,
    transcripts AND beam scores match the int8 slot tier exactly (the
    chunk op attends the current chunk's fresh f32 rows — the slot
    tier's int8 prefill semantics). Once prefix sharing engages, a hit
    attends the covered span via its int8 pages where a cold prefill
    recomputes it at f32: token ids stay identical, scores track within
    the quantization step — the (vLLM-standard) int8 prefix-cache
    boundary."""
    prompts = _prompts(32, 6)
    with DecodingPredictor(arts['slot8']) as ps:
        ref = [ps.generate(p, max_new_tokens=10) for p in prompts]
        b_ref = ps.generate(prompts[0], max_new_tokens=8, beam=3)
    with DecodingPredictor(arts['block8']) as pb:
        assert pb.stats.tier == 'int8'
        b_cold = pb.generate(prompts[0], max_new_tokens=8, beam=3)
        got = [pb.generate(p, max_new_tokens=10) for p in prompts]
        b_warm = pb.generate(prompts[0], max_new_tokens=8, beam=3)
        warm_snap = pb.stats.snapshot()
    assert got == ref
    np.testing.assert_array_equal(b_ref[0], b_cold[0])
    np.testing.assert_array_equal(b_ref[1], b_cold[1])
    # warm (prefix-hit) serve: same tokens, scores within quant step
    assert warm_snap['prefix_hits'] > 0
    np.testing.assert_array_equal(b_ref[0], b_warm[0])
    np.testing.assert_allclose(b_ref[1], b_warm[1], atol=0.05)
    with open(os.path.join(arts['block8'],
                           'decode_signature.json')) as f:
        sig = json.load(f)
    dt = {e['name']: e['dtype'] for e in sig['state']}
    assert dt['kv_k_0'] == 'int8' and dt['kv_ks_0'] == 'float32'


def test_beam_divergence_cow_at_block_boundary(arts):
    """Force beam CoW exactly where it is subtle: a prompt whose length
    is a multiple of block_size (the fork point is a BLOCK BOUNDARY, so
    the first divergent write extends into a fresh block — no copy) and
    one mid-block (the shared partial tail must CoW). Both must match
    the slot tier bit-for-bit."""
    rng = np.random.RandomState(33)
    at_boundary = rng.randint(2, VOCAB, 8)    # 8 % 4 == 0
    mid_block = rng.randint(2, VOCAB, 6)      # 6 % 4 != 0
    with DecodingPredictor(arts['slot']) as ps:
        ref = [ps.generate(p, max_new_tokens=10, beam=3)
               for p in (at_boundary, mid_block)]
    with DecodingPredictor(arts['block']) as pb:
        got = [pb.generate(p, max_new_tokens=10, beam=3)
               for p in (at_boundary, mid_block)]
        snap = pb.stats.snapshot()
    for (i1, s1), (i2, s2) in zip(ref, got):
        np.testing.assert_array_equal(i1, i2)
        np.testing.assert_array_equal(s1, s2)
    assert snap['cow_blocks'] > 0


def test_prefix_sharing_skips_compute_and_storage(arts):
    """Two requests with the same prompt: the second hits the prefix
    cache — fewer chunk slices (covered span skips prefill compute) and
    shared full blocks (storage) — with an identical transcript."""
    rng = np.random.RandomState(34)
    prompt = rng.randint(2, VOCAB, 9)          # 2 full blocks + 1
    with DecodingPredictor(arts['block']) as pb:
        a = pb.generate(prompt, max_new_tokens=10)
        s1 = pb.stats.snapshot()
        b = pb.generate(prompt, max_new_tokens=10)
        s2 = pb.stats.snapshot()
    assert a == b
    assert s2['prefix_hits'] == s1['prefix_hits'] + 1
    assert s2['prefix_tokens_reused'] == s1['prefix_tokens_reused'] + 8
    # the covered 8 tokens (2 blocks) admitted without chunk dispatches:
    # request 1 took 2 slices (8 + 1 tokens), request 2 only 1
    assert (s2['chunk_slices'] - s1['chunk_slices']
            < s1['chunk_slices'])


def test_chunked_prefill_admits_beyond_largest_chunk(arts):
    """A prompt longer than the largest chunk size admits in slices (the
    slot tier would reject it: no bucket fits) and its transcript
    matches a short-prompt continuation computed the long way around:
    greedy decode is deterministic, so serving the same prompt twice on
    the block tier across chunk boundaries must agree."""
    rng = np.random.RandomState(35)
    long_prompt = rng.randint(2, VOCAB, 23)    # > max chunk (8)
    with DecodingPredictor(arts['block']) as pb:
        one = pb.generate(long_prompt, max_new_tokens=12)
        s = pb.stats.snapshot()
        two = pb.generate(long_prompt, max_new_tokens=12)
    assert one == two
    assert s['chunk_slices'] >= 3              # 23 tokens over 8-chunks
    with DecodingPredictor(arts['slot']) as ps:
        with pytest.raises(ValueError, match='exceeds'):
            ps.generate(long_prompt, max_new_tokens=4)


def test_mp_sharded_decode_transcripts_match_single_chip(arts,
                                                         tmp_path):
    """ISSUE 13 acceptance: the 2-chip mp-sharded decode artifact's
    TOKEN TRANSCRIPTS (greedy and beam ids) are bit-identical to the
    single-chip artifact's. The replicate-hint discipline keeps every
    contraction full-width (no partial-sum all-reduces), so logits
    agree to within local-fusion ulps — accumulated float beam scores
    may differ in the last ~1e-6 (the standard the sharded serving
    systems hold); ids must not."""
    mp2 = _build(str(tmp_path / 'mp2'), prompt_buckets=(4, 8),
                 block_size=4, mp_shard=2)
    with open(os.path.join(mp2, 'decode_signature.json')) as f:
        sig = json.load(f)
    assert sig['mesh']['axes'] == {'mp': 2}
    assert sig['mesh']['tag'] == 'cpu_mp2'
    # mesh-tagged sidecars: a sharded executable can never load into an
    # unsharded serve (or another mesh shape)
    from paddle_tpu.inference import decoding
    for d in (decoding._STEP_DIR, decoding._REORDER_DIR,
              decoding._BLOCKCOPY_DIR):
        assert os.path.exists(os.path.join(mp2, d,
                                           'aot_cpu_mp2.jaxexec'))
        assert not os.path.exists(os.path.join(mp2, d,
                                               'aot_cpu.jaxexec'))
    prompts = _prompts(36, 6)
    with DecodingPredictor(arts['block']) as p1:
        g1 = [p1.generate(p, max_new_tokens=10) for p in prompts]
        b1 = [p1.generate(p, max_new_tokens=8, beam=3)
              for p in prompts[:2]]
    with DecodingPredictor(mp2) as p2:
        assert p2.mesh_tag == 'cpu_mp2'
        g2 = [p2.generate(p, max_new_tokens=10) for p in prompts]
        b2 = [p2.generate(p, max_new_tokens=8, beam=3)
              for p in prompts[:2]]
    assert g1 == g2
    for (i1, s1), (i2, s2) in zip(b1, b2):
        np.testing.assert_array_equal(i1, i2)
        np.testing.assert_allclose(s1, s2, atol=1e-4)


def test_mp_sharded_warm_replica_zero_compiles(arts, tmp_path):
    """A FRESH process loading the prewarmed mp-sharded artifact serves
    greedy + beam with ZERO XLA compiles (mesh-tagged AOT sidecars),
    and its transcripts equal the single-chip artifact served the same
    way — the full ISSUE 13 sharded-serve acceptance bar."""
    import subprocess
    import sys as _sys
    mp2 = _build(str(tmp_path / 'mp2w'), prompt_buckets=(4, 8),
                 block_size=4, mp_shard=2)
    here = os.path.dirname(os.path.abspath(__file__))
    outs = []
    for art in (arts['block'], mp2):
        env = dict(os.environ)
        env['XLA_FLAGS'] = '--xla_force_host_platform_device_count=2'
        env['JAX_PLATFORMS'] = 'cpu'
        p = subprocess.run(
            [_sys.executable, os.path.join(here,
                                           'decode_serve_worker.py'),
             art, '5', '4', '8'],
            capture_output=True, text=True, env=env, timeout=600)
        assert 'DECODE_OK' in p.stdout, p.stdout + p.stderr
        line = [ln for ln in p.stdout.splitlines()
                if ln.startswith('DECODE ')][0]
        outs.append(json.loads(line[len('DECODE '):]))
    single, sharded = outs
    assert sharded['compiles'] == 0
    assert sharded['greedy'] == single['greedy']
    assert sharded['beam_ids'] == single['beam_ids']


def test_chunk_pad_overflow_lands_in_trash_block():
    """A near-max-length prompt whose FINAL chunk slice runs past
    max_cache_len (take < size with a FULL block table) must scatter
    its pad rows into the trash block: gather clamping would resolve
    their overflowing positions to the LAST table column — a real
    block when the table is full — and pad garbage would overwrite
    prompt K/V written in the same dispatch. The transcript through a
    big chunk (pad rows overflow) and a small chunk (none do) must
    agree."""
    import tempfile
    t = tempfile.mkdtemp()
    big = _build(os.path.join(t, 'big'), prompt_buckets=(8,),
                 block_size=8, chunk_sizes=(48,))
    small = _build(os.path.join(t, 'small'), prompt_buckets=(8,),
                   block_size=8, chunk_sizes=(8,))
    rng = np.random.RandomState(36)
    prompt = rng.randint(2, VOCAB, CACHE - 1)  # 63 tokens: table full
    with DecodingPredictor(small) as ps:
        ref = ps.generate(prompt, max_new_tokens=1)
    with DecodingPredictor(big) as pb:
        # final slice: start=48, take=15, size=48 -> pad positions
        # 64..95 overflow the 8-column table
        assert pb.generate(prompt, max_new_tokens=1) == ref


def test_waiting_request_rematches_published_prefix():
    """A request whose FIRST admission attempt misses the prefix cache
    (its twin ahead of it is still prefilling) and then stalls on
    blocks must RE-match once it can admit: the twin published the
    shared prefix while it waited. A cached miss holds no refs, so
    only a cached HIT may pin across attempts."""
    import tempfile
    art = _build(tempfile.mkdtemp() + '/rematch', prompt_buckets=(4, 8),
                 block_size=4, num_blocks=5)   # 4 usable blocks
    rng = np.random.RandomState(37)
    prompt = rng.randint(2, VOCAB, 12)         # 3 blocks at admission
    with DecodingPredictor(art) as pb:
        # A admits (3 blocks + 1 decode extension = the whole pool):
        # B's first attempt MISSES the prefix cache and stalls on
        # blocks; A publishes at prefill end and frees at finish —
        # B must then admit on the re-matched HIT (2 shared + 1 fresh)
        sa = pb.submit(prompt, max_new_tokens=4)
        sb = pb.submit(prompt, max_new_tokens=4)
        a = sa.result(120)
        b = sb.result(120)
        snap = pb.stats.snapshot()
    assert a == b
    assert snap['prefix_hits'] >= 1


def test_pool_exhaustion_sheds_loudly(arts):
    """A pool too small for the offered prompts sheds the unservable
    request with ServerOverloaded instead of deadlocking."""
    from paddle_tpu.inference import ServerOverloaded
    import tempfile
    small = _build(tempfile.mkdtemp() + '/tiny', prompt_buckets=(4, 8),
                   block_size=4, num_blocks=3)  # 2 usable blocks
    with DecodingPredictor(small) as pb:
        ok = pb.generate(np.asarray([3, 4, 5]), max_new_tokens=4)
        assert len(ok) == 4
        with pytest.raises(ServerOverloaded, match='block pool'):
            # needs 4 blocks (12 tokens + new): can never fit
            pb.submit(np.asarray(range(2, 14)),
                      max_new_tokens=4).result(60)
