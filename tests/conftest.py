"""Test config: force an 8-device virtual CPU platform so SPMD/mesh tests
exercise real sharding without TPU hardware (the driver's dryrun_multichip
uses the same mechanism)."""
import os

os.environ.setdefault('XLA_FLAGS',
                      (os.environ.get('XLA_FLAGS', '') +
                       ' --xla_force_host_platform_device_count=8').strip())
os.environ['JAX_PLATFORMS'] = 'cpu'
# the TPU plugin registers itself as default regardless of JAX_PLATFORMS;
# PTPU_PLATFORM pins every paddle_tpu executor/mesh to the virtual CPU devices
os.environ['PTPU_PLATFORM'] = 'cpu'

import numpy as np
import pytest


@pytest.fixture(autouse=True)
def _fresh_programs():
    """Each test builds into fresh default programs + scope."""
    import paddle_tpu as fluid
    from paddle_tpu import unique_name
    main, startup = fluid.Program(), fluid.Program()
    prev_m = fluid.switch_main_program(main)
    prev_s = fluid.switch_startup_program(startup)
    scope = fluid.core.Scope()
    with fluid.scope_guard(scope), unique_name.guard():
        yield
    fluid.switch_main_program(prev_m)
    fluid.switch_startup_program(prev_s)
