"""OpTest matrix, part 2: optimizer update math, random ops, and the
remaining nn/sequence/detection tail — completing at-least-one-check
coverage of the registered op library (VERDICT r2 directive 5).
"""
import numpy as np
import pytest

import paddle_tpu as fluid
from op_test import OpTest
from test_op_matrix import _run_spec, _forward_only, _x


# ---------------------------------------------------------------------------
# optimizer update rules vs numpy (ref operators/optimizers/*)
# ---------------------------------------------------------------------------
def _opt_run(op, ins, attrs, outs):
    t = OpTest()
    t.op_type = op
    t.inputs = ins
    t.attrs = attrs
    t.outputs = outs
    t.check_output(atol=1e-5, rtol=1e-5,
                   no_check_set=[n for n, v in outs.items() if v is None])


def test_optimizer_updates_match_numpy():
    p = _x((4,), seed=1)
    g = _x((4,), seed=2)
    lr = np.array([0.1], np.float32)

    # adadelta (ref adadelta_op.h)
    avg_sq_g = np.abs(_x((4,), seed=3))
    avg_sq_u = np.abs(_x((4,), seed=4))
    rho, eps = 0.95, 1e-6
    nsg = rho * avg_sq_g + (1 - rho) * g * g
    upd = -np.sqrt((avg_sq_u + eps) / (nsg + eps)) * g
    nsu = rho * avg_sq_u + (1 - rho) * upd * upd
    _opt_run('adadelta',
             {'Param': p, 'Grad': g, 'AvgSquaredGrad': avg_sq_g,
              'AvgSquaredUpdate': avg_sq_u},
             {'rho': rho, 'epsilon': eps},
             {'ParamOut': p + upd, 'AvgSquaredGradOut': nsg,
              'AvgSquaredUpdateOut': nsu})

    # adamax (ref adamax_op.h)
    m = _x((4,), seed=5)
    inf = np.abs(_x((4,), seed=6)) + 0.5
    b1p = np.array([0.9], np.float32)
    b1, b2, eps = 0.9, 0.999, 1e-8
    m_out = b1 * m + (1 - b1) * g
    inf_out = np.maximum(b2 * inf, np.abs(g))
    p_out = p - (0.1 / (1 - b1p)) * (m_out / (inf_out + eps))
    _opt_run('adamax',
             {'Param': p, 'Grad': g, 'LearningRate': lr, 'Moment': m,
              'InfNorm': inf, 'Beta1Pow': b1p},
             {'beta1': b1, 'beta2': b2, 'epsilon': eps},
             {'ParamOut': p_out.astype(np.float32), 'MomentOut': m_out,
              'InfNormOut': inf_out})

    # decayed_adagrad (ref decayed_adagrad_op.h)
    mom = np.abs(_x((4,), seed=7))
    decay, eps = 0.95, 1e-6
    mo = decay * mom + (1 - decay) * g * g
    _opt_run('decayed_adagrad',
             {'Param': p, 'Grad': g, 'LearningRate': lr, 'Moment': mom},
             {'decay': decay, 'epsilon': eps},
             {'ParamOut': p - 0.1 * g / (np.sqrt(mo) + eps),
              'MomentOut': mo})

    # rmsprop (ref rmsprop_op.h, centered=False)
    ms = np.abs(_x((4,), seed=8))
    mom2 = _x((4,), seed=9)
    rho, eps2, mu = 0.95, 1e-6, 0.9
    ms_out = rho * ms + (1 - rho) * g * g
    mom_out = mu * mom2 + 0.1 * g / np.sqrt(ms_out + eps2)
    _opt_run('rmsprop',
             {'Param': p, 'Grad': g, 'LearningRate': lr,
              'MeanSquare': ms, 'Moment': mom2},
             {'decay': rho, 'epsilon': eps2, 'momentum': mu},
             {'ParamOut': p - mom_out, 'MeanSquareOut': ms_out,
              'MomentOut': mom_out, 'MeanGradOut': None})

    # proximal_gd (ref proximal_gd_op.h)
    l1, l2 = 0.01, 0.01
    prox = p - 0.1 * g
    po = (np.sign(prox) * np.maximum(np.abs(prox) - 0.1 * l1, 0)
          / (1 + 0.1 * l2))
    _opt_run('proximal_gd', {'Param': p, 'Grad': g, 'LearningRate': lr},
             {'l1': l1, 'l2': l2}, {'ParamOut': po.astype(np.float32)})


def test_lars_ftrl_proximal_adagrad_run_and_descend():
    """Update rules with more intricate accumulators: check they run and
    step in a descent direction."""
    p = _x((4,), lo=0.5, hi=1.0, seed=10)
    g = np.abs(_x((4,), seed=11)) + 0.1
    lr = np.array([0.1], np.float32)
    outs = _forward_only('lars_momentum',
                         {'Param': p, 'Grad': g, 'LearningRate': lr,
                          'Velocity': np.zeros(4, np.float32)},
                         {'mu': 0.9, 'lars_coeff': 0.001,
                          'lars_weight_decay': 0.0005},
                         outs=('ParamOut', 'VelocityOut'))
    assert (np.asarray(outs[0]) < p).all()  # positive grad -> param down
    outs = _forward_only('ftrl',
                         {'Param': p, 'Grad': g, 'LearningRate': lr,
                          'SquaredAccumulator': np.zeros(4, np.float32),
                          'LinearAccumulator': np.zeros(4, np.float32)},
                         {'l1': 0.0, 'l2': 0.0, 'lr_power': -0.5},
                         outs=('ParamOut', 'SquaredAccumOut',
                               'LinearAccumOut'))
    assert np.isfinite(np.asarray(outs[0])).all()
    outs = _forward_only('proximal_adagrad',
                         {'Param': p, 'Grad': g, 'LearningRate': lr,
                          'Moment': np.zeros(4, np.float32) + 0.1},
                         {'l1': 0.0, 'l2': 0.0},
                         outs=('ParamOut', 'MomentOut'))
    assert (np.asarray(outs[0]) < p).all()


def test_average_accumulates():
    p = _x((4,), seed=12)
    outs = _forward_only(
        'average_accumulates',
        {'param': p,
         'in_sum_1': np.zeros(4, np.float32),
         'in_sum_2': np.zeros(4, np.float32),
         'in_sum_3': np.zeros(4, np.float32),
         'in_num_accumulates': np.array([0], np.int32),
         'in_old_num_accumulates': np.array([0], np.int32),
         'in_num_updates': np.array([0], np.int32)},
        {'average_window': 10, 'max_average_window': 20,
         'min_average_window': 5},
        outs=('out_sum_1', 'out_num_accumulates'))
    np.testing.assert_allclose(np.asarray(outs[0]), p, rtol=1e-6)


# ---------------------------------------------------------------------------
# random ops: shape + statistics
# ---------------------------------------------------------------------------
def test_random_ops_statistics():
    for op, attrs, check in [
        ('uniform_random', {'shape': [500], 'min': -1.0, 'max': 1.0,
                            'dtype': 'float32'},
         lambda v: (-1 <= v).all() and (v <= 1).all() and abs(v.mean()) < 0.2),
        ('gaussian_random', {'shape': [500], 'mean': 2.0, 'std': 0.5,
                             'dtype': 'float32'},
         lambda v: abs(v.mean() - 2.0) < 0.2 and abs(v.std() - 0.5) < 0.2),
        ('truncated_gaussian_random', {'shape': [500], 'mean': 0.0,
                                       'std': 1.0, 'dtype': 'float32'},
         lambda v: (np.abs(v) <= 2.01).all()),
    ]:
        v, = _forward_only(op, {}, attrs)
        assert check(np.asarray(v)), op
    v, = _forward_only('randperm', {}, {'n': 16, 'dtype': 'int64'})
    assert sorted(np.asarray(v).tolist()) == list(range(16))
    probs = np.array([[0.0, 1.0, 0.0]] * 4, np.float32)
    v, = _forward_only('sampling_id', {'X': probs}, {})
    assert (np.asarray(v) == 1).all()
    img = np.arange(36, dtype=np.float32).reshape(1, 1, 6, 6)
    v, = _forward_only('random_crop', {'X': img}, {'shape': [4, 4]})
    assert np.asarray(v).shape == (1, 1, 4, 4)


# ---------------------------------------------------------------------------
# nn tail
# ---------------------------------------------------------------------------
def test_pad2d_and_pad_constant_like():
    x = _x((1, 1, 2, 2), seed=13)
    v, = _forward_only('pad2d', {'X': x},
                       {'paddings': [1, 1, 1, 1], 'mode': 'constant',
                        'pad_value': 0.0})
    assert np.asarray(v).shape == (1, 1, 4, 4)
    big = np.zeros((3, 4), np.float32)
    small = _x((2, 3), seed=14)
    v, = _forward_only('pad_constant_like', {'X': big, 'Y': small},
                       {'pad_value': 9.0})
    v = np.asarray(v)
    assert v.shape == (3, 4)
    np.testing.assert_allclose(v[:2, :3], small)
    assert (v[2:, :] == 9.0).all()


def test_prelu_and_selu():
    x = _x((2, 3), away_from=0.0, seed=15)
    alpha = np.array([0.25], np.float32)
    _run_spec('prelu', {'X': x, 'Alpha': alpha}, {'mode': 'all'},
              {'Out': np.where(x > 0, x, 0.25 * x)}, grads=['X'])
    scale, a = 1.0507009873554805, 1.6732632423543772
    _run_spec('selu', {'X': x}, {'scale': scale, 'alpha': a},
              {'Out': np.where(x > 0, scale * x,
                               scale * a * (np.exp(x) - 1))
               .astype(np.float32)})


def test_log_softmax_and_mean_iou():
    x = _x((2, 4), seed=16)
    want = x - np.log(np.exp(x).sum(1, keepdims=True))
    _run_spec('log_softmax', {'X': x}, {'axis': -1}, {'Out': want},
              atol=1e-5, rtol=1e-4)
    pred = np.array([0, 1, 1, 2], np.int32)
    lab = np.array([0, 1, 2, 2], np.int32)
    outs = _forward_only('mean_iou',
                         {'Predictions': pred, 'Labels': lab},
                         {'num_classes': 3},
                         outs=('OutMeanIou', 'OutWrong', 'OutCorrect'))
    # ious: c0 1/1; c1 1/2; c2 1/2 -> mean 2/3
    np.testing.assert_allclose(np.asarray(outs[0]).reshape(-1)[0],
                               2.0 / 3.0, rtol=1e-5)


def test_grid_and_affine():
    theta = np.tile(np.array([[[1.0, 0, 0], [0, 1.0, 0]]], np.float32),
                    (1, 1, 1))
    grid, = _forward_only('affine_grid', {'Theta': theta},
                          {'output_shape': [1, 1, 3, 3]},
                          outs=('Output',))
    grid = np.asarray(grid)
    assert grid.shape == (1, 3, 3, 2)
    # identity affine: corners at +-1
    np.testing.assert_allclose(grid[0, 0, 0], [-1, -1], atol=1e-5)
    x = np.arange(9, dtype=np.float32).reshape(1, 1, 3, 3)
    out, = _forward_only('grid_sampler', {'X': x, 'Grid': grid},
                         {}, outs=('Output',))
    np.testing.assert_allclose(np.asarray(out), x, atol=1e-4)


def test_data_norm_and_hash():
    x = _x((4, 3), seed=17)
    bsize = np.full((3,), 4.0, np.float32)
    bsum = x.sum(0)
    bsq = (x * x).sum(0) + 1e-4
    outs = _forward_only('data_norm',
                         {'X': x, 'BatchSize': bsize, 'BatchSum': bsum,
                          'BatchSquareSum': bsq},
                         {'epsilon': 1e-4}, outs=('Y',))
    means = bsum / bsize
    scales = np.sqrt(bsize / bsq)
    np.testing.assert_allclose(np.asarray(outs[0]), (x - means) * scales,
                               rtol=1e-4)
    ids = np.array([[1], [7]], np.int64)
    v, = _forward_only('hash', {'X': ids},
                       {'num_hash': 2, 'mod_by': 100})
    v = np.asarray(v)
    assert v.shape[-2:] == (2, 1) or v.shape == (2, 2, 1)
    assert (0 <= v).all() and (v < 100).all()


def test_similarity_focus_and_im2sequence():
    x = np.abs(_x((1, 2, 2, 2), seed=18))
    v, = _forward_only('similarity_focus', {'X': x},
                       {'axis': 1, 'indexes': [0]})
    assert np.asarray(v).shape == x.shape
    img = np.arange(16, dtype=np.float32).reshape(1, 1, 4, 4)
    v, = _forward_only('im2sequence', {'X': img},
                       {'kernels': [2, 2], 'strides': [2, 2],
                        'paddings': [0, 0, 0, 0]})
    v = np.asarray(v)
    assert v.shape == (4, 4)
    np.testing.assert_allclose(v[0], [0, 1, 4, 5])


def test_conv3d_transpose_shape():
    x = _x((1, 2, 2, 2, 2), seed=19)
    w = _x((2, 1, 2, 2, 2), seed=20)
    v, = _forward_only('conv3d_transpose', {'Input': x, 'Filter': w},
                       {'strides': [1, 1, 1], 'paddings': [0, 0, 0],
                        'dilations': [1, 1, 1], 'groups': 1},
                       outs=('Output',))
    assert np.asarray(v).shape == (1, 1, 3, 3, 3)


# ---------------------------------------------------------------------------
# the *2 variants + fill/is_empty/lod_reset
# ---------------------------------------------------------------------------
def test_shape2_variants_and_fill():
    x = _x((2, 6), seed=21)
    for op, attrs, want, outs in [
        ('reshape2', {'shape': [3, 4]}, x.reshape(3, 4), ('Out', 'XShape')),
        ('transpose2', {'axis': [1, 0]}, x.T, ('Out', 'XShape')),
        ('flatten2', {'axis': 1}, x, ('Out', 'XShape')),
        ('squeeze2', {'axes': []}, x, ('Out', 'XShape')),
        ('unsqueeze2', {'axes': [0]}, x[None], ('Out', 'XShape')),
    ]:
        got = _forward_only(op, {'X': x}, attrs, outs=outs)
        np.testing.assert_allclose(np.asarray(got[0]), want, rtol=1e-6,
                                   err_msg=op)
    v, = _forward_only('fill_zeros_like', {'X': x}, {})
    assert (np.asarray(v) == 0).all()
    v, = _forward_only('fill_any_like', {'X': x}, {'value': 3.5})
    assert (np.asarray(v) == 3.5).all()
    v, = _forward_only('is_empty', {'X': x}, {})
    assert not bool(np.asarray(v).reshape(-1)[0])


def test_sequence_tail_ops():
    data = np.arange(12, dtype=np.float32).reshape(6, 2)
    lod = fluid.create_lod_tensor(data, [[2, 4]])
    x = fluid.layers.data(name='x', shape=[2], dtype='float32', lod_level=1)
    outs = [fluid.layers.sequence_reshape(x, new_dim=4),
            fluid.layers.sequence_slice(
                x,
                offset=fluid.layers.assign(np.array([[0], [1]], np.int32)),
                length=fluid.layers.assign(np.array([[1], [2]], np.int32))),
            fluid.layers.sequence_concat([x, x])]
    exe = fluid.Executor(fluid.CPUPlace())
    rs = exe.run(feed={'x': lod}, fetch_list=outs, return_numpy=False)
    assert np.asarray(rs[0].data).shape == (3, 4)
    np.testing.assert_allclose(np.asarray(rs[1].data),
                               data[[0, 3, 4]], rtol=1e-6)
    np.testing.assert_allclose(np.asarray(rs[2].data)[:4],
                               np.vstack([data[:2], data[:2]]), rtol=1e-6)

    ids = fluid.create_lod_tensor(
        np.array([[1], [2], [3]], np.int64), [[3]])
    xi = fluid.layers.data(name='xi', shape=[1], dtype='int64', lod_level=1)
    enum = fluid.layers.sequence_enumerate(xi, win_size=2, pad_value=0)
    er = fluid.layers.sequence_erase(xi, tokens=[2])
    r2 = exe.run(feed={'x': lod, 'xi': ids}, fetch_list=[enum, er],
                 return_numpy=False)
    np.testing.assert_array_equal(np.asarray(r2[0].data),
                                  [[1, 2], [2, 3], [3, 0]])
    # static-shape erase: survivors left-aligned, -1 padding after
    np.testing.assert_array_equal(np.asarray(r2[1].data).reshape(-1),
                                  [1, 3, -1])

    # sequence_scatter: add updates at (seq row, id) positions
    base = np.zeros((2, 5), np.float32)
    xb = fluid.layers.data(name='xb', shape=[5], dtype='float32')
    sid = fluid.layers.data(name='sid', shape=[1], dtype='int64',
                            lod_level=1)
    upd = fluid.layers.data(name='upd', shape=[1], dtype='float32',
                            lod_level=1)
    out = fluid.layers.sequence_scatter(xb, sid, upd)
    got, = exe.run(feed={
        'x': lod, 'xi': ids,
        'xb': base,
        'sid': fluid.create_lod_tensor(np.array([[1], [3]], np.int64),
                                       [[1, 1]]),
        'upd': fluid.create_lod_tensor(np.array([[2.0], [5.0]],
                                                np.float32), [[1, 1]])},
        fetch_list=[out])
    want = base.copy()
    want[0, 1] = 2.0
    want[1, 3] = 5.0
    np.testing.assert_allclose(got, want, rtol=1e-6)


def test_density_prior_box_and_psroi():
    x = fluid.layers.data(name='x', shape=[4, 2, 2], dtype='float32')
    img = fluid.layers.data(name='img', shape=[3, 16, 16], dtype='float32')
    boxes, var = fluid.layers.density_prior_box(
        x, img, densities=[2], fixed_sizes=[4.0], fixed_ratios=[1.0])
    exe = fluid.Executor(fluid.CPUPlace())
    b, = exe.run(feed={'x': np.zeros((1, 4, 2, 2), np.float32),
                       'img': np.zeros((1, 3, 16, 16), np.float32)},
                 fetch_list=[boxes])
    assert np.asarray(b).shape == (2, 2, 4, 4)  # density^2 = 4 priors

    feat = fluid.layers.data(name='feat', shape=[8, 4, 4], dtype='float32')
    rois = fluid.layers.data(name='rois', shape=[4], dtype='float32',
                             lod_level=1)
    pool = fluid.layers.psroi_pool(feat, rois, output_channels=2,
                                   spatial_scale=1.0, pooled_height=2,
                                   pooled_width=2)
    v, = exe.run(feed={
        'x': np.zeros((1, 4, 2, 2), np.float32),
        'img': np.zeros((1, 3, 16, 16), np.float32),
        'feat': np.random.RandomState(0).randn(1, 8, 4, 4)
        .astype(np.float32),
        'rois': fluid.create_lod_tensor(
            np.array([[0, 0, 3, 3]], np.float32), [[1]])},
        fetch_list=[pool])
    assert np.asarray(v).shape == (1, 2, 2, 2)


def test_rpn_target_assign_and_proposal_labels_shapes():
    anchors = fluid.layers.data(name='an', shape=[4], dtype='float32')
    gt = fluid.layers.data(name='gt', shape=[4], dtype='float32',
                           lod_level=1)
    bbox_pred = fluid.layers.data(name='bp', shape=[16, 4],
                                  dtype='float32')
    cls_logits = fluid.layers.data(name='cl', shape=[16, 1],
                                   dtype='float32')
    pred_loc, pred_score, tgt_bbox, tgt_lbl, iw = \
        fluid.layers.rpn_target_assign(
            bbox_pred, cls_logits, anchors, anchors, gt,
            rpn_batch_size_per_im=8, rpn_fg_fraction=0.5)
    exe = fluid.Executor(fluid.CPUPlace())
    rng = np.random.RandomState(0)
    an = np.abs(rng.rand(16, 4).astype(np.float32))
    an[:, 2:] = an[:, :2] + 0.5
    gtb = np.array([[0.1, 0.1, 0.6, 0.6]], np.float32)
    outs = exe.run(feed={'an': an,
                         'gt': fluid.create_lod_tensor(gtb, [[1]]),
                         'bp': rng.randn(1, 16, 4).astype(np.float32),
                         'cl': rng.randn(1, 16, 1).astype(np.float32)},
                   fetch_list=[pred_loc, tgt_bbox, tgt_lbl, iw])
    # 1:1 pairing between predicted locations and bbox targets
    assert np.asarray(outs[0]).shape == np.asarray(outs[1]).shape
    assert np.asarray(outs[2]).shape[0] == 8  # batch_size_per_im
    assert set(np.asarray(outs[2]).reshape(-1)) <= {-1, 0, 1}


def test_roi_perspective_transform_shape():
    x = fluid.layers.data(name='x', shape=[1, 8, 8], dtype='float32')
    rois = fluid.layers.data(name='r', shape=[8], dtype='float32',
                             lod_level=1)
    out = fluid.layers.roi_perspective_transform(x, rois, 4, 4, 1.0)
    exe = fluid.Executor(fluid.CPUPlace())
    quad = np.array([[1, 1, 6, 1, 6, 6, 1, 6]], np.float32)
    v, = exe.run(feed={'x': np.random.RandomState(0)
                       .randn(1, 1, 8, 8).astype(np.float32),
                       'r': fluid.create_lod_tensor(quad, [[1]])},
                 fetch_list=[out])
    assert np.asarray(v).shape == (1, 1, 4, 4)
    assert np.isfinite(np.asarray(v)).all()
