"""Observability tail: profiler report + Chrome export, evaluators,
debugger/graphviz, teacher_student loss, new datasets."""
import json
import os

import numpy as np
import pytest

import paddle_tpu as fluid


def test_profiler_events_and_chrome_export(tmp_path, capsys):
    from paddle_tpu import profiler
    profiler.reset_profiler()
    with profiler.profiler():
        x = fluid.layers.data(name='x', shape=[4], dtype='float32')
        y = fluid.layers.fc(x, size=2)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(fluid.default_startup_program())
        for _ in range(3):
            exe.run(feed={'x': np.ones((2, 4), np.float32)},
                    fetch_list=[y])
    out = capsys.readouterr().out
    # the aggregate report lists the executor's per-run events
    assert 'executor_run' in out and 'Calls' in out
    path = profiler.export_chrome_tracing(str(tmp_path / 'trace.json'))
    with open(path) as f:
        trace = json.load(f)
    evs = [e for e in trace['traceEvents']
           if e['name'].startswith('executor_run')]
    assert len(evs) >= 3
    assert all(e['ph'] == 'X' and e['dur'] >= 0 for e in evs)


def test_chunk_evaluator_accumulates():
    inf = fluid.layers.data(name='i', shape=[1], dtype='int64', lod_level=1)
    lab = fluid.layers.data(name='l', shape=[1], dtype='int64', lod_level=1)
    ev = fluid.evaluator.ChunkEvaluator(inf, lab, chunk_scheme='IOB',
                                        num_chunk_types=2)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    gold = np.array([0, 1, 2, 3, 0], np.int64).reshape(-1, 1)
    pred = np.array([0, 1, 0, 1, 0], np.int64).reshape(-1, 1)
    feed = {'i': fluid.create_lod_tensor(pred, [[5]]),
            'l': fluid.create_lod_tensor(gold, [[5]])}
    for _ in range(2):  # two batches accumulate
        exe.run(feed=feed, fetch_list=[ev.metrics[0]])
    p, r, f1 = ev.eval(exe)
    assert p[0] == pytest.approx(2 / 3)
    assert r[0] == pytest.approx(2 / 3)
    ev.reset(exe)
    p, r, f1 = ev.eval(exe)
    assert p[0] == 0.0


def test_debugger_outputs(tmp_path):
    x = fluid.layers.data(name='x', shape=[4], dtype='float32')
    y = fluid.layers.fc(x, size=2, act='relu')
    path = fluid.debugger.draw_block_graphviz(
        fluid.default_main_program().global_block(),
        path=str(tmp_path / 'g.dot'))
    dot = open(path).read()
    assert 'digraph' in dot and 'mul' in dot and 'relu' in dot
    text = fluid.debugger.pprint_program_codes(
        fluid.default_main_program())
    assert 'mul' in text


def test_teacher_student_sigmoid_loss_values():
    x = fluid.layers.data(name='x', shape=[1], dtype='float32')
    lab = fluid.layers.data(name='lab', shape=[1], dtype='float32')
    loss = fluid.layers.teacher_student_sigmoid_loss(x, lab)
    exe = fluid.Executor(fluid.CPUPlace())
    xs = np.array([[0.5], [0.5], [0.5], [0.5]], np.float32)
    # labels: no-teacher clk0 (-2), no-teacher clk1 (-1),
    #         teacher 0.3 clk0 (0.3), teacher 0.3 clk1 (1.3)
    labs = np.array([[-2.0], [-1.0], [0.3], [1.3]], np.float32)
    got, = exe.run(feed={'x': xs, 'lab': labs}, fetch_list=[loss])
    got = np.asarray(got).reshape(-1)
    b = lambda x_, z: max(x_, 0) - x_ * z + np.log1p(np.exp(-abs(x_)))
    want = [b(0.5, 0), b(0.5, 1), b(0.5, 0) + b(0.5, 0.3),
            b(0.5, 1) + b(0.5, 0.3)]
    np.testing.assert_allclose(got, want, rtol=1e-5)


def test_new_datasets_learnable():
    from paddle_tpu.dataset import sentiment, mq2007, voc2012
    s = list(sentiment.test()())
    assert len(s) == 400 and {lab for _, lab in s[:10]} <= {0, 1}
    pair = next(mq2007.train_reader('pairwise')())
    assert pair[0].shape == (46,) and pair[1].shape == (46,)
    listw = next(mq2007.train_reader('listwise')())
    assert listw[0].ndim == 2
    img, seg = next(voc2012.train()())
    assert img.shape[0] == 3 and seg.shape == img.shape[1:]
    assert seg.max() < voc2012.CLASS_NUM
