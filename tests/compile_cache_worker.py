"""Subprocess worker for test_compile_cache.py and warm_start_smoke.py:
one autoscaled-replica "cold start". Builds a small deterministic train
program, runs it through the persistent compile cache (run() steps plus a
run_steps multi-step group), saves every fetch to an npz, and prints the
cache counters as a JSON line:

    python compile_cache_worker.py CACHE_DIR OUT.npz

The caller runs it twice against one cache dir: run 1 is the cold miss
path (trace + compile + persist), run 2 must perform ZERO XLA compiles
for the cached entries and produce byte-identical fetches — the ISSUE 5
acceptance bar.
"""
import json
import os
import sys


def main():
    cache_dir, out_path = sys.argv[1], sys.argv[2]
    os.environ['JAX_PLATFORMS'] = 'cpu'
    os.environ['PTPU_PLATFORM'] = 'cpu'
    os.environ['PTPU_COMPILE_CACHE'] = '1'
    os.environ['PTPU_COMPILE_CACHE_DIR'] = cache_dir
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    sys.path.insert(0, repo)

    import time

    import numpy as np
    import paddle_tpu as fluid
    from paddle_tpu.core import compile_cache as cc

    t0 = time.perf_counter()

    main_p, startup = fluid.Program(), fluid.Program()
    main_p.random_seed = startup.random_seed = 11
    with fluid.program_guard(main_p, startup):
        x = fluid.layers.data(name='x', shape=[6], dtype='float32')
        y = fluid.layers.data(name='y', shape=[1], dtype='float32')
        h = fluid.layers.fc(x, size=8, act='relu')
        pred = fluid.layers.fc(h, size=1)
        loss = fluid.layers.reduce_mean(
            fluid.layers.square(pred - y))
        fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)

    rng = np.random.RandomState(0)
    feeds = [{'x': rng.randn(4, 6).astype(np.float32),
              'y': rng.randn(4, 1).astype(np.float32)} for _ in range(6)]

    scope = fluid.core.Scope()
    exe = fluid.Executor(fluid.CPUPlace())
    save = {}
    with fluid.scope_guard(scope):
        exe.run(startup)
        for i in range(3):
            out, = exe.run(main_p, feed=feeds[i], fetch_list=[loss])
            save['run%d' % i] = np.asarray(out)
        # a K=3 multi-step dispatch rides the same persistent cache
        group = {'x': np.stack([f['x'] for f in feeds[3:]]),
                 'y': np.stack([f['y'] for f in feeds[3:]])}
        stacked, = exe.run_steps(main_p, feed=group, fetch_list=[loss],
                                 fetch_policy='stack')
        save['steps'] = np.asarray(stacked)
    np.savez(out_path, **save)

    s = cc.stats()
    out = {k: s[k] for k in ('exec_hits', 'hlo_hits', 'misses', 'compiles',
                             'corrupt', 'xla_compiles', 'xla_pcache_hits',
                             'xla_compiles_net')}
    out['compile_s'] = round(s['compile_s'], 3)
    out['wall_s'] = round(time.perf_counter() - t0, 3)
    print('CC_STATS %s' % json.dumps(out))
    print('CC_OK')


if __name__ == '__main__':
    main()
