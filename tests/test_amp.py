"""bf16 mixed-precision (contrib.mixed_precision) tests.

Checks the full wiring the reference era lacked and VERDICT r2 demanded:
decorate() -> program._amp_bf16 -> Executor amp.scope -> amp.matmul/conv
lowerings — plus convergence parity with fp32.
"""
import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu.core import amp


def _build_mlp(seed=7):
    main_p, startup_p = fluid.Program(), fluid.Program()
    main_p.random_seed = startup_p.random_seed = seed
    with fluid.program_guard(main_p, startup_p):
        x = fluid.layers.data('x', shape=[16], dtype='float32')
        y = fluid.layers.data('y', shape=[1], dtype='float32')
        h = fluid.layers.fc(x, size=32, act='relu')
        pred = fluid.layers.fc(h, size=1)
        loss = fluid.layers.mean(fluid.layers.square_error_cost(pred, y))
    return main_p, startup_p, x, y, loss


def _train(decorate_amp, steps=12, seed=7):
    rng = np.random.RandomState(0)
    xs = rng.randn(64, 16).astype(np.float32)
    w = rng.randn(16, 1).astype(np.float32)
    ys = xs @ w + 0.01 * rng.randn(64, 1).astype(np.float32)

    main_p, startup_p, x, y, loss = _build_mlp(seed)
    with fluid.program_guard(main_p, startup_p):
        opt = fluid.optimizer.SGD(learning_rate=0.05)
        if decorate_amp:
            opt = fluid.contrib.mixed_precision.decorate(opt)
        opt.minimize(loss)

    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup_p)
    losses = []
    for _ in range(steps):
        lv, = exe.run(main_p, feed={'x': xs, 'y': ys}, fetch_list=[loss])
        losses.append(float(np.asarray(lv).reshape(-1)[0]))
    return losses


def test_decorate_marks_program():
    main_p, startup_p, x, y, loss = _build_mlp()
    with fluid.program_guard(main_p, startup_p):
        opt = fluid.contrib.mixed_precision.decorate(
            fluid.optimizer.SGD(learning_rate=0.1))
        opt.minimize(loss)
    assert getattr(main_p, '_amp_bf16', False) is True


def test_amp_matmul_is_bf16_under_scope():
    import jax
    import jax.numpy as jnp
    a = jnp.ones((8, 8), jnp.float32)
    with amp.scope(True):
        jaxpr = str(jax.make_jaxpr(lambda x: amp.matmul(x, x))(a))
    assert 'bf16' in jaxpr or 'bfloat16' in jaxpr
    # outside the scope: plain fp32 matmul
    jaxpr = str(jax.make_jaxpr(lambda x: amp.matmul(x, x))(a))
    assert 'bf16' not in jaxpr and 'bfloat16' not in jaxpr


def test_amp_grads_are_bf16():
    import jax
    import jax.numpy as jnp
    a = jnp.ones((8, 8), jnp.float32)
    with amp.scope(True):
        jaxpr = str(jax.make_jaxpr(
            jax.grad(lambda x: amp.matmul(x, x).sum()))(a))
    assert 'bf16' in jaxpr or 'bfloat16' in jaxpr


def test_amp_convergence_matches_fp32():
    fp32 = _train(decorate_amp=False)
    bf16 = _train(decorate_amp=True)
    # both must converge; bf16 loss curve tracks fp32 loosely
    assert fp32[-1] < fp32[0] * 0.7
    assert bf16[-1] < bf16[0] * 0.7
    assert abs(bf16[-1] - fp32[-1]) < 0.25 * max(abs(fp32[0]), 1.0)


def test_amp_params_stay_fp32():
    main_p, startup_p, x, y, loss = _build_mlp()
    with fluid.program_guard(main_p, startup_p):
        opt = fluid.contrib.mixed_precision.decorate(
            fluid.optimizer.SGD(learning_rate=0.05))
        opt.minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup_p)
    xs = np.random.randn(8, 16).astype(np.float32)
    ys = np.random.randn(8, 1).astype(np.float32)
    exe.run(main_p, feed={'x': xs, 'y': ys}, fetch_list=[loss])
    scope = fluid.global_scope()
    for v in main_p.list_vars():
        if getattr(v, 'persistable', False):
            arr = scope.get(v.name)
            if arr is not None and np.issubdtype(
                    np.asarray(arr).dtype, np.floating):
                assert np.asarray(arr).dtype == np.float32, v.name


def test_dropout_bits_flag_numerics():
    """FLAGS_dropout_bits low-bit keep-decision (PERF_NOTES dropout-tax
    ablation): keep rate tracks 1-p and kept values upscale by 1/(1-p)."""
    import numpy as np
    import paddle_tpu as fluid
    from paddle_tpu.core import config as cfg

    x = np.ones((64, 512), np.float32)
    exe = fluid.Executor(fluid.CPUPlace())
    prev = cfg.get_flag('dropout_bits')
    try:
        for bits in (8, 16):
            # fresh program per value (belt; the executor cache is ALSO
            # keyed on the flag now, asserted below)
            cfg.set_flags({'dropout_bits': bits})
            main, startup = fluid.Program(), fluid.Program()
            with fluid.program_guard(main, startup):
                inp = fluid.layers.data('xb', shape=[512],
                                        dtype='float32')
                out = fluid.layers.dropout(
                    inp, dropout_prob=0.25,
                    dropout_implementation='upscale_in_train')
            o, = exe.run(main, feed={'xb': x}, fetch_list=[out])
            o = np.asarray(o)
            kept = o != 0.0
            rate = kept.mean()
            assert abs(rate - 0.75) < 0.03, (bits, rate)
            np.testing.assert_allclose(o[kept], 1.0 / 0.75, rtol=1e-5)
        # same program, flag toggled: the compile cache must miss (the
        # key includes trace-time rng flags), not silently reuse
        n0 = len(exe._cache)
        cfg.set_flags({'dropout_bits': 0})
        exe.run(main, feed={'xb': x}, fetch_list=[out])
        assert len(exe._cache) == n0 + 1
    finally:
        cfg.set_flags({'dropout_bits': prev})
