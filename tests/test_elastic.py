"""Elastic data plane (VERDICT r3 missing #2): leased task dispatch,
failure caps, journal-backed mid-epoch resume, and exactly-once delivery
across a killed feeder — the Go master's capabilities
(go/master/service.go:89 todo/pending/done queues, :140 timeout re-queue)
re-homed as a library over the shared filesystem.

Also covers the checkpoint CRC / atomic-rename hardening in io.py
(go/pserver/service.go:346).
"""
import os
import threading
import time

import numpy as np
import pytest

from paddle_tpu.reader.elastic import TaskService, elastic_sample_stream


# ---------------------------------------------------------------------------
# TaskService mechanics
# ---------------------------------------------------------------------------
def test_lease_finish_cycle():
    svc = TaskService(['a', 'b'])
    t1 = svc.get_task()
    t2 = svc.get_task()
    assert {t1[1], t2[1]} == {'a', 'b'}
    assert svc.get_task() is None and not svc.epoch_done  # all leased
    svc.task_finished(t1[0])
    svc.task_finished(t2[0])
    assert svc.epoch_done


def test_failed_task_requeues_until_cap():
    svc = TaskService(['a'], max_failures=3, retry_backoff_s=0)
    for _ in range(2):
        tid, _, _ = svc.get_task()
        svc.task_failed(tid)
    tid, _, _ = svc.get_task()   # 3rd lease still dispatchable
    with pytest.warns(RuntimeWarning, match='DROPPED'):
        svc.task_failed(tid)     # 3rd failure hits the cap — loudly
    assert svc.get_task() is None
    assert svc.counts['dropped'] == 1
    assert svc.epoch_done        # dropped tasks don't wedge the epoch


def test_lease_timeout_requeues():
    svc = TaskService(['a'], lease_timeout_s=0.05, max_failures=10,
                      retry_backoff_s=0)
    tid, _, _ = svc.get_task()
    assert svc.get_task() is None
    time.sleep(0.08)
    got = svc.get_task()         # expired lease re-dispatches
    assert got is not None and got[1] == 'a'


def test_failed_task_backs_off_exponentially_before_release():
    """A failed task is NOT immediately re-leasable (a poisoned task
    would hot-loop through its failure cap in microseconds and starve
    good tasks); it re-dispatches after a jittered exponential delay."""
    svc = TaskService(['bad', 'good'], max_failures=10,
                      retry_backoff_s=0.08, retry_jitter=0.0)
    tid, task, _ = svc.get_task()
    assert task == 'bad'  # FIFO
    svc.task_failed(tid)
    # backing off: 'bad' is not dispatchable, but 'good' still is
    leased = svc.get_task()
    assert leased is not None and leased[1] == 'good'
    assert svc.get_task() is None          # 'bad' held back
    assert not svc.epoch_done              # ...but still owed this epoch
    time.sleep(0.1)
    leased = svc.get_task()
    assert leased is not None and leased[1] == 'bad'
    svc.task_failed(leased[0])             # 2nd failure: delay doubles
    time.sleep(0.1)
    assert svc.get_task() is None          # 0.16s not yet elapsed
    time.sleep(0.08)
    assert svc.get_task()[1] == 'bad'


def test_backoff_jitter_and_cap_bounds():
    svc = TaskService(['t'], max_failures=100, retry_backoff_s=0.1,
                      retry_backoff_max_s=0.4, retry_jitter=0.25)
    now = time.monotonic()
    for n in range(1, 8):
        with svc._lock:
            svc._fail_locked('t', 'test')
        base = min(0.4, 0.1 * 2 ** (n - 1))
        delay = svc._not_before['t'] - now
        assert base * 0.7 <= delay <= base * 1.3, (n, delay, base)
        svc._todo = ['t']  # reset queue state between iterations
    # warns-on-drop fires when the cap is eventually hit
    svc2 = TaskService(['p'], max_failures=1)
    with pytest.warns(RuntimeWarning, match='DROPPED'):
        svc2.task_failed('p')


def test_progress_heartbeat_extends_lease():
    svc = TaskService(['a'], lease_timeout_s=0.1, max_failures=10)
    tid, _, _ = svc.get_task()
    for _ in range(4):
        time.sleep(0.06)
        svc.report_progress(tid, 1)  # heartbeat: keeps the lease alive
    assert svc.get_task() is None    # never re-queued while heartbeating


def test_new_epoch_resets():
    svc = TaskService(['a', 'b'])
    for _ in range(2):
        tid, _, _ = svc.get_task()
        svc.task_finished(tid)
    assert svc.epoch_done
    svc.new_epoch()
    assert not svc.epoch_done and svc.counts['todo'] == 2


# ---------------------------------------------------------------------------
# journal recovery + exactly-once stream across a killed feeder
# ---------------------------------------------------------------------------
def _tasks():
    # task -> its samples; str(task) is the id
    return {'f0': list(range(0, 7)), 'f1': list(range(10, 15)),
            'f2': list(range(20, 26))}


def test_kill_feeder_mid_epoch_exactly_once(tmp_path):
    data = _tasks()
    journal = str(tmp_path / 'tasks.journal')
    read_task = lambda t: iter(data[t])

    # first incarnation: consume 9 samples (mid f1 or f0+...), then die
    svc1 = TaskService(sorted(data), journal_path=journal)
    stream = elastic_sample_stream(svc1, read_task)
    got_first = [next(stream) for _ in range(9)]
    stream.close()   # the kill: no task_finished for the in-flight task
    svc1.close()

    # second incarnation over the SAME journal resumes mid-task
    svc2 = TaskService(sorted(data), journal_path=journal)
    got_second = list(elastic_sample_stream(svc2, read_task))
    svc2.close()

    everything = got_first + got_second
    want = sorted(s for samples in data.values() for s in samples)
    assert sorted(everything) == want          # nothing lost
    assert len(everything) == len(want)        # nothing duplicated


def test_journal_done_tasks_never_redispatch(tmp_path):
    data = _tasks()
    journal = str(tmp_path / 'tasks.journal')
    svc1 = TaskService(sorted(data), journal_path=journal)
    tid, t, skip = svc1.get_task()
    assert skip == 0
    svc1.task_finished(tid)
    svc1.close()

    svc2 = TaskService(sorted(data), journal_path=journal)
    seen = set()
    while True:
        leased = svc2.get_task()
        if leased is None:
            break
        seen.add(leased[1])
        svc2.task_finished(leased[0])
    assert tid not in seen and len(seen) == len(data) - 1


def test_torn_journal_tail_ignored(tmp_path):
    journal = str(tmp_path / 'tasks.journal')
    svc1 = TaskService(['a', 'b'], journal_path=journal)
    tid, _, _ = svc1.get_task()
    svc1.task_finished(tid)
    svc1.close()
    with open(journal, 'a') as f:
        f.write('{"event": "done", "ta')   # crash mid-write
    svc2 = TaskService(['a', 'b'], journal_path=journal)
    assert svc2.counts['todo'] == 1        # torn record dropped, not fatal


# ---------------------------------------------------------------------------
# AsyncExecutor integration: journaled run resumes at zero-cost
# ---------------------------------------------------------------------------
def _write_multislot(path, label_vals):
    # one used dense float slot 'x' (dim 2) + int label slot 'y'
    lines = []
    for v in label_vals:
        lines.append('2 %f %f 1 %d' % (v * 0.1, v * 0.2, v % 2))
    with open(path, 'w') as f:
        f.write('\n'.join(lines) + '\n')


def _feed_desc():
    import paddle_tpu as fluid
    proto = '''
name: "MultiSlotDataFeed"
batch_size: 2
multi_slot_desc {
  slots { name: "x" type: "float" is_dense: true is_used: true dense_dim: 2 }
  slots { name: "y" type: "uint64" is_dense: true is_used: true dense_dim: 1 }
}
'''
    return fluid.DataFeedDesc(proto)


def test_async_executor_journal_resume(tmp_path):
    import paddle_tpu as fluid

    files = []
    for i in range(3):
        p = str(tmp_path / ('part-%d.txt' % i))
        _write_multislot(p, range(i * 4, i * 4 + 4))
        files.append(p)

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data(name='x', shape=[2], dtype='float32')
        y = fluid.layers.data(name='y', shape=[1], dtype='int64')
        pred = fluid.layers.fc(x, size=2, act='softmax')
        loss = fluid.layers.mean(
            fluid.layers.cross_entropy(input=pred, label=y))
        fluid.optimizer.SGD(0.1).minimize(loss)

    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    jdir = str(tmp_path / 'journal')

    ae = fluid.AsyncExecutor(fluid.CPUPlace())
    r1 = ae.run(main, _feed_desc(), files, thread_num=2, fetch=[loss],
                journal_dir=jdir)
    assert len(r1) == 6  # 12 samples / bs 2

    # `epochs` is the TOTAL the journal should reach: re-running the same
    # call over a completed journal trains NOTHING (no over-training on
    # resume), while raising the total to 2 trains exactly one more epoch
    r2 = ae.run(main, _feed_desc(), files, thread_num=2, fetch=[loss],
                journal_dir=jdir)
    assert len(r2) == 0
    r2b = ae.run(main, _feed_desc(), files, thread_num=2, fetch=[loss],
                 journal_dir=jdir, epochs=2)
    assert len(r2b) == 6

    # a resume with a different batch size would mis-skip: rejected loudly
    bad = _feed_desc()
    bad.set_batch_size(4)
    with pytest.raises(ValueError, match='batch_size'):
        ae.run(main, bad, files, thread_num=2, fetch=[loss],
               journal_dir=jdir)

    # pre-mark two files done in a fresh journal: resume trains ONLY the
    # remaining file's batches (mid-epoch recovery without duplication)
    jdir2 = str(tmp_path / 'journal2')
    os.makedirs(jdir2)
    svc = TaskService(files,
                      journal_path=os.path.join(jdir2, 'data_tasks.journal'))
    for f in files[:2]:
        tid, _, _ = svc.get_task()
        svc.task_finished(tid)
    svc.close()
    r3 = ae.run(main, _feed_desc(), files, thread_num=2, fetch=[loss],
                journal_dir=jdir2)
    assert len(r3) == 2  # only part-2's 4 samples / bs 2


# ---------------------------------------------------------------------------
# checkpoint CRC + atomic rename (io.py side of the Go design)
# ---------------------------------------------------------------------------
def test_checkpoint_crc_detects_corruption(tmp_path):
    import paddle_tpu as fluid

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data(name='x', shape=[4], dtype='float32')
        fluid.layers.fc(x, size=3)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    d = str(tmp_path / 'ckpt')
    fluid.io.save_persistables(exe, d, main)
    target = os.path.join(d, 'fc_0.w_0')
    blob = bytearray(open(target, 'rb').read())
    blob[-2] ^= 0xFF  # flip a payload byte
    with open(target, 'wb') as f:
        f.write(bytes(blob))
    # first line of defense: the save manifest's sha256 (ISSUE 6)
    with pytest.raises(RuntimeError, match='manifest'):
        fluid.io.load_persistables(exe, d, main)
    # the per-tensor CRC still guards manifest-less (pre-ISSUE-6) dirs
    os.remove(os.path.join(d, '.ptpu_manifest.json'))
    with pytest.raises(ValueError, match='CRC'):
        fluid.io.load_persistables(exe, d, main)


def test_save_leaves_no_temp_files(tmp_path):
    import paddle_tpu as fluid

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data(name='x', shape=[4], dtype='float32')
        fluid.layers.fc(x, size=3)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    d = str(tmp_path / 'ckpt')
    written = fluid.io.save_persistables(exe, d, main)
    assert written and all(os.path.exists(p) for p in written)
    assert not [f for f in os.listdir(d) if '.tmp.' in f]


# ---------------------------------------------------------------------------
# Real-kill recovery across a PROCESS boundary (VERDICT r4 weak #5: the
# in-process generator-close simulation never exercised a dead feeder;
# the reference's tier kills processes with signals, test_dist_base.py:339)
# ---------------------------------------------------------------------------
import signal
import subprocess
import sys

_KILL_WORKER = os.path.join(os.path.dirname(__file__),
                            'elastic_kill_worker.py')
_ALL_SAMPLES = {t * 100 + i for t in range(4) for i in range(25)}


def _read_ids(path):
    if not os.path.exists(path):
        return [], False
    done = False
    ids = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if line == 'EPOCH_DONE':
                done = True
            elif line:
                ids.append(int(line))
    return ids, done


def _kill_restart(tmp_path, mode):
    journal = str(tmp_path / 'journal.jsonl')
    out1 = str(tmp_path / 'run1.txt')
    out2 = str(tmp_path / 'run2.txt')
    p = subprocess.Popen([sys.executable, _KILL_WORKER, mode, journal,
                          out1, '15'])
    try:
        progressed = False
        deadline = time.time() + 60
        while time.time() < deadline:
            ids, _ = _read_ids(out1)
            if len(ids) >= 12:
                progressed = True
                break
            time.sleep(0.05)
    finally:
        # SIGKILL unconditionally: on the timeout path a hung feeder must
        # fail the test, not block p.wait() until the CI job timeout
        try:
            os.kill(p.pid, signal.SIGKILL)     # a REAL dead feeder
        except ProcessLookupError:
            pass
        p.wait()
    assert progressed, 'worker produced no samples in time'
    ids1, done1 = _read_ids(out1)
    assert not done1, 'kill landed after the epoch finished'

    r = subprocess.run([sys.executable, _KILL_WORKER, mode, journal,
                        out2, '0'], capture_output=True, text=True,
                       timeout=120)
    assert r.returncode == 0, r.stderr[-2000:]
    ids2, done2 = _read_ids(out2)
    assert done2, 'restarted feeder did not finish the epoch'
    return ids1, ids2


def test_sigkill_feeder_stream_exactly_once(tmp_path):
    """elastic_sample_stream journals BEFORE the hand-off: across a
    SIGKILL + restart no sample is ever delivered twice, and at most the
    single in-flight sample (the documented at-most-once margin) is
    lost."""
    ids1, ids2 = _kill_restart(tmp_path, 'stream')
    assert len(ids1) == len(set(ids1)) and len(ids2) == len(set(ids2))
    dup = set(ids1) & set(ids2)
    assert not dup, 'samples delivered twice across the kill: %r' % dup
    missing = _ALL_SAMPLES - set(ids1) - set(ids2)
    assert len(missing) <= 1, 'lost more than the margin: %r' % missing


def test_sigkill_feeder_afterstep_at_least_once(tmp_path):
    """Journal-AFTER-the-step (the AsyncExecutor contract): across a
    SIGKILL + restart nothing is lost, and at most the single in-flight
    sample is replayed."""
    ids1, ids2 = _kill_restart(tmp_path, 'afterstep')
    missing = _ALL_SAMPLES - set(ids1) - set(ids2)
    assert not missing, 'at-least-once violated, lost: %r' % missing
    replays = len(ids1) + len(ids2) - len(_ALL_SAMPLES)
    assert 0 <= replays <= 1, 'more than the 1-sample replay margin'


def test_journal_single_writer_guard(tmp_path):
    """Two TaskServices on one journal_path must refuse, not silently
    interleave appends (the Go master serialized via one server,
    go/master/service.go:89)."""
    from paddle_tpu.reader.elastic import TaskService
    j = str(tmp_path / 'j.jsonl')
    a = TaskService(['a', 'b'], journal_path=j)
    with pytest.raises(RuntimeError, match='locked'):
        TaskService(['a', 'b'], journal_path=j)
    a.close()
    b = TaskService(['a', 'b'], journal_path=j)   # lock released on close
    b.close()


def test_dropped_poison_task_survives_restart(tmp_path):
    """A task that exhausted max_failures is journaled as dropped: a
    restarted service must not re-dispatch (and re-fail) it (ADVICE r4:
    elastic.py:109)."""
    from paddle_tpu.reader.elastic import TaskService
    j = str(tmp_path / 'j.jsonl')
    svc = TaskService(['good', 'poison'], journal_path=j, max_failures=2)
    for _ in range(2):
        svc.task_failed('poison')
    assert svc.is_dropped('poison')
    svc.close()

    svc2 = TaskService(['good', 'poison'], journal_path=j, max_failures=2)
    assert svc2.is_dropped('poison'), 'drop did not survive the restart'
    leased = svc2.get_task()
    assert leased is not None and leased[0] == 'good'
    assert svc2.get_task() is None     # poison never re-dispatches
    svc2.close()


def test_stale_lease_reports_ignored():
    """A worker whose lease expired (and whose task was re-leased) must
    not clobber the live holder: its task_failed/report_progress/finish
    are no-ops once the generation moved on."""
    from paddle_tpu.reader.elastic import TaskService
    svc = TaskService(['t'], lease_timeout_s=0.01, max_failures=10,
                      retry_backoff_s=0)
    a = svc.get_task()
    assert a is not None and a[0] == 't'
    time.sleep(0.05)                       # A's lease expires
    b = svc.get_task()                     # requeued + re-leased to B
    assert b is not None and b[0] == 't' and b.gen != a.gen

    svc.report_progress('t', 1, gen=b.gen)     # B is at sample 1
    svc.task_failed('t', gen=a.gen)            # A's LATE failure report
    # B's lease must still be live and t must not be double-queued
    assert svc.counts['pending'] == 1 and svc.counts['todo'] == 0
    svc.report_progress('t', 99, gen=a.gen)    # stale progress: ignored
    assert svc.get_task() is None              # nothing leasable
    svc.task_finished('t', gen=a.gen)          # stale finish: ignored
    assert svc.counts['done'] == 0
    svc.task_finished('t', gen=b.gen)          # the live holder finishes
    assert svc.counts['done'] == 1 and svc.epoch_done


def test_journal_position_and_limit_rewind(tmp_path):
    """journal_position() marks a consistent point; a restart with
    journal_limit truncates the tail so data consumed AFTER a checkpoint
    re-dispatches instead of being skipped against pre-checkpoint
    params (core/checkpoint.py resume contract)."""
    j = str(tmp_path / 'j.jsonl')
    svc = TaskService(['a', 'b'], journal_path=j)
    ta = svc.get_task()
    svc.report_progress(ta[0], 3, gen=ta.gen)
    pos = svc.journal_position()           # "checkpoint" taken here
    assert pos == os.path.getsize(j)
    svc.report_progress(ta[0], 7, gen=ta.gen)   # post-checkpoint progress
    svc.task_finished(ta[0], gen=ta.gen)
    svc.close()

    # plain restart replays everything: 'a' is done, skip would be 7
    svc2 = TaskService(['a', 'b'], journal_path=j)
    assert svc2.counts['done'] == 1
    svc2.close()

    # checkpoint-consistent restart rewinds to pos: 'a' redispatches
    # with the journaled skip of 3 (what the restored params trained on)
    svc3 = TaskService(['a', 'b'], journal_path=j, journal_limit=pos)
    assert svc3.counts['done'] == 0
    assert os.path.getsize(j) == pos       # tail physically truncated
    leased = {}
    while True:
        t = svc3.get_task()
        if t is None:
            break
        leased[t[1]] = t[2]
    assert leased == {'a': 3, 'b': 0}
    svc3.close()
