"""AlexNet builds and trains (benchmark parity: the reference's committed
AlexNet numbers live in BASELINE.md)."""
import numpy as np

import paddle_tpu as fluid
from models.alexnet import build_train_net


def test_alexnet_trains_one_batch():
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 9
    with fluid.program_guard(main, startup):
        # small lr: the 4096-wide fc head overshoots at tiny batch sizes
        images, label, loss, acc = build_train_net(class_dim=10, lr=1e-3)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    r = np.random.RandomState(0)
    feed = {'data': r.randn(2, 3, 224, 224).astype(np.float32),
            'label': r.randint(0, 10, (2, 1)).astype(np.int64)}
    vals = []
    for _ in range(4):
        l, = exe.run(main, feed=feed, fetch_list=[loss])
        vals.append(float(np.asarray(l).reshape(-1)[0]))
    assert np.isfinite(vals).all(), vals
    assert vals[-1] < vals[0], vals
