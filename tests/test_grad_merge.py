"""Gradient merge (contrib.gradient_merge): k microbatches == 1 big batch.

Parity methodology follows the reference's dist_mnist_batch_merge test
(multi_batch_merge_pass): the merged-gradient run must track the big-batch
run step for step.
"""
import numpy as np
import pytest

import paddle_tpu as fluid


def _train(k, steps=6, seed=17, fetch_acc=False):
    main_p, startup_p = fluid.Program(), fluid.Program()
    main_p.random_seed = startup_p.random_seed = seed
    with fluid.program_guard(main_p, startup_p):
        x = fluid.layers.data(name='x', shape=[16], dtype='float32')
        lab = fluid.layers.data(name='lab', shape=[1], dtype='int64')
        h = fluid.layers.fc(x, size=32, act='relu')
        logits = fluid.layers.fc(h, size=5)
        loss = fluid.layers.mean(fluid.layers.softmax_with_cross_entropy(
            logits=logits, label=lab))
        opt = fluid.optimizer.Momentum(
            learning_rate=fluid.layers.exponential_decay(0.1, 10, 0.9),
            momentum=0.9)
        if k > 1:
            opt = fluid.contrib.gradient_merge.decorate(opt, k)
        opt.minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.core.Scope()
    rng = np.random.RandomState(3)
    xs = rng.randn(32, 16).astype(np.float32)
    labs = rng.randint(0, 5, (32, 1))
    losses = []
    with fluid.scope_guard(scope):
        exe.run(startup_p)
        for _ in range(steps):
            l, = exe.run(main_p, feed={'x': xs, 'lab': labs},
                         fetch_list=[loss])
            losses.append(float(np.asarray(l).reshape(-1)[0]))
        counter = scope.get('@LR_DECAY_COUNTER@')
    return losses, int(np.asarray(counter).reshape(-1)[0])


def test_k_microbatches_match_big_batch():
    base, c1 = _train(1)
    merged, c4 = _train(4)
    # same data, same lr schedule: trajectories must match (fp32, no BN)
    np.testing.assert_allclose(base, merged, rtol=1e-4, atol=1e-5)
    assert base[-1] < base[0]
    # LR counter increments once per STEP, not once per microbatch
    assert c1 == c4


def test_merge_with_clip_and_metric_matches_big_batch():
    """Gradient clip must apply ONCE to the merged grad (mean(clip(g_i)) !=
    clip(mean(g_i)) would diverge), and an unfetched metric op in the block
    must not break the partition."""
    def run(k):
        main_p, startup_p = fluid.Program(), fluid.Program()
        main_p.random_seed = startup_p.random_seed = 23
        with fluid.program_guard(main_p, startup_p):
            x = fluid.layers.data(name='x', shape=[16], dtype='float32')
            lab = fluid.layers.data(name='lab', shape=[1], dtype='int64')
            logits = fluid.layers.fc(fluid.layers.fc(x, 32, act='relu'), 5)
            sm = fluid.layers.softmax(logits)
            _acc = fluid.layers.accuracy(input=sm, label=lab)  # never fetched
            loss = fluid.layers.mean(
                fluid.layers.softmax_with_cross_entropy(logits=logits,
                                                        label=lab))
            fluid.set_gradient_clip(fluid.GradientClipByGlobalNorm(0.01))
            opt = fluid.optimizer.SGD(learning_rate=0.5)
            if k > 1:
                opt = fluid.contrib.gradient_merge.decorate(opt, k)
            opt.minimize(loss)
        exe = fluid.Executor(fluid.CPUPlace())
        scope = fluid.core.Scope()
        rng = np.random.RandomState(8)
        xs = rng.randn(32, 16).astype(np.float32)
        # strong per-microbatch signal so per-microbatch clipping WOULD
        # change the trajectory if it (incorrectly) ran inside the scan
        xs[:8] *= 10.0
        labs = rng.randint(0, 5, (32, 1))
        out = []
        with fluid.scope_guard(scope):
            exe.run(startup_p)
            for _ in range(5):
                l, = exe.run(main_p, feed={'x': xs, 'lab': labs},
                             fetch_list=[loss])
                out.append(float(np.asarray(l).reshape(-1)[0]))
        return out

    np.testing.assert_allclose(run(1), run(4), rtol=1e-4, atol=1e-5)


def test_batch_not_divisible_raises():
    main_p, startup_p = fluid.Program(), fluid.Program()
    with fluid.program_guard(main_p, startup_p):
        x = fluid.layers.data(name='x', shape=[4], dtype='float32')
        y = fluid.layers.data(name='y', shape=[1], dtype='float32')
        loss = fluid.layers.mean(fluid.layers.square_error_cost(
            fluid.layers.fc(x, size=1), y))
        fluid.contrib.gradient_merge.decorate(
            fluid.optimizer.SGD(0.1), 3).minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup_p)
    with pytest.raises(ValueError, match='divisible'):
        exe.run(main_p, feed={'x': np.ones((8, 4), np.float32),
                              'y': np.ones((8, 1), np.float32)},
                fetch_list=[loss])


def test_grad_merge_with_batchnorm_trains():
    """BN inside the scan updates running stats k times per step (reference
    batch-merge repeats the forward subgraph the same way) — must train."""
    main_p, startup_p = fluid.Program(), fluid.Program()
    main_p.random_seed = startup_p.random_seed = 2
    with fluid.program_guard(main_p, startup_p):
        x = fluid.layers.data(name='x', shape=[1, 8, 8], dtype='float32')
        lab = fluid.layers.data(name='lab', shape=[1], dtype='int64')
        c = fluid.layers.conv2d(x, num_filters=4, filter_size=3, padding=1)
        c = fluid.layers.batch_norm(c, act='relu')
        logits = fluid.layers.fc(c, size=3)
        loss = fluid.layers.mean(fluid.layers.softmax_with_cross_entropy(
            logits=logits, label=lab))
        fluid.contrib.gradient_merge.decorate(
            fluid.optimizer.Adam(1e-2), 2).minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.core.Scope()
    rng = np.random.RandomState(0)
    xs = rng.randn(16, 1, 8, 8).astype(np.float32)
    labs = rng.randint(0, 3, (16, 1))
    with fluid.scope_guard(scope):
        exe.run(startup_p)
        losses = []
        for _ in range(10):
            l, = exe.run(main_p, feed={'x': xs, 'lab': labs},
                         fetch_list=[loss])
            losses.append(float(np.asarray(l).reshape(-1)[0]))
        # BN running stats must have moved off their init (mean 0)
        bn_means = [np.asarray(scope.get(v.name))
                    for v in main_p.list_vars()
                    if v.persistable and 'mean' in v.name]
    assert losses[-1] < losses[0] * 0.7
    assert any(np.abs(m).sum() > 0 for m in bn_means)
