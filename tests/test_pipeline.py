"""SPMD pipeline parallelism over the mesh 'pp' axis: gpipe_apply parity
with f64 numpy, gradient flow, the pipelined_ffn_stack op matching its own
sequential lowering, and a training step over a dp x pp mesh.

References use NUMPY math: jnp's eager CPU matmul carries ~4e-4 fast-math
error that would otherwise mask/flag parity incorrectly.
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as fluid
from paddle_tpu.parallel import make_mesh
from paddle_tpu.parallel.compiler import CompiledProgram
from paddle_tpu.parallel.pipeline import gpipe_apply


def test_gpipe_matches_f64_numpy():
    P_, M, mb, D = 4, 8, 4, 16
    r = np.random.RandomState(0)
    w = (r.randn(P_, D, D) * 0.3).astype(np.float32)
    b = (r.randn(P_, D) * 0.1).astype(np.float32)
    xs = r.randn(M, mb, D).astype(np.float32)

    def layer(p, x):
        return jnp.tanh(x @ p[0] + p[1])

    mesh = make_mesh(num_devices=4, axes={'pp': 4})
    out = jax.jit(lambda p, x: gpipe_apply(layer, p, x, mesh))(
        (jnp.asarray(w), jnp.asarray(b)), jnp.asarray(xs))
    ref = xs.astype(np.float64)
    for l in range(P_):
        ref = np.tanh(ref @ w[l].astype(np.float64) + b[l])
    np.testing.assert_allclose(np.asarray(out, np.float64), ref, atol=1e-5)


def test_gpipe_gradients_flow():
    P_, M, mb, D = 4, 4, 2, 8
    r = np.random.RandomState(1)
    w = jnp.asarray(r.randn(P_, D, D) * 0.3, jnp.float32)
    xs = jnp.asarray(r.randn(M, mb, D), jnp.float32)
    mesh = make_mesh(num_devices=4, axes={'pp': 4})

    def layer(p, x):
        return jnp.tanh(x @ p)

    def loss(w, xs):
        return jnp.sum(gpipe_apply(layer, w, xs, mesh) ** 2)

    g = jax.jit(jax.grad(loss))(w, xs)
    g = np.asarray(g)
    assert np.isfinite(g).all()
    assert (np.abs(g) > 0).any(axis=(1, 2)).all(), \
        "every stage's params must receive gradient"


def _build_stack(seed=13, mb_attr=0):
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = seed
    with fluid.program_guard(main, startup):
        x = fluid.layers.data(name='x', shape=[16], dtype='float32')
        out = fluid.layers.pipelined_ffn_stack(x, num_layers=4, d_ff=32,
                                               num_microbatches=mb_attr)
    return main, startup, out


def test_pipelined_op_pp_matches_sequential():
    """The SAME program: sequential lowering on one device vs GPipe over a
    dp x pp mesh — outputs must agree (programs are mesh-portable)."""
    main, startup, out = _build_stack()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    r = np.random.RandomState(3)
    x = r.randn(8, 16).astype(np.float32)
    single, = exe.run(main, feed={'x': x}, fetch_list=[out])

    main2, startup2, out2 = _build_stack()
    mesh = make_mesh(axes={'dp': 2, 'pp': 4})
    prog = CompiledProgram(main2).with_data_parallel(mesh=mesh)
    exe2 = fluid.Executor(fluid.CPUPlace())
    exe2.run(startup2)
    piped, = exe2.run(prog, feed={'x': x}, fetch_list=[out2])
    np.testing.assert_allclose(np.asarray(piped), np.asarray(single),
                               rtol=2e-3, atol=2e-3)  # CPU matmul fastmath


def test_pipelined_stack_trains_over_pp_mesh():
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 21
    with fluid.program_guard(main, startup):
        x = fluid.layers.data(name='x', shape=[16], dtype='float32')
        y = fluid.layers.data(name='y', shape=[16], dtype='float32')
        out = fluid.layers.pipelined_ffn_stack(x, num_layers=4, d_ff=32)
        loss = fluid.layers.mean(fluid.layers.square(out - y))
        fluid.optimizer.Adam(1e-2).minimize(loss)
    mesh = make_mesh(axes={'dp': 2, 'pp': 4})
    prog = CompiledProgram(main).with_data_parallel(loss_name=loss.name,
                                                    mesh=mesh)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    r = np.random.RandomState(0)
    feed = {'x': r.randn(8, 16).astype(np.float32),
            'y': r.randn(8, 16).astype(np.float32)}
    vals = []
    for _ in range(15):
        l, = exe.run(prog, feed=feed, fetch_list=[loss])
        vals.append(float(np.asarray(l).reshape(-1)[0]))
    assert np.isfinite(vals).all()
    assert vals[-1] < vals[0], (vals[0], vals[-1])
