"""Compiled bulk-inference loop (ISSUE 3 tentpole): run_batches scans the
per-batch compiled program over K pre-staged batches in ONE dispatch,
bit-identical per batch to K sequential run() calls through the same
bucket — the inference mirror of Executor.run_steps. Covers: exact
bit-identity (dense matmul model, in-framework Predictor AND the
framework-free CompiledPredictor), a LoD bucket artifact, partial-tail
flush through a smaller compiled group, donation safety (no
caller-visible buffer reuse), partial dense-batch padding, the profiler
bulk-infer report, and a fresh-process CLI loop round-trip."""
import json
import os
import subprocess
import sys

import numpy as np

import paddle_tpu as fluid
from paddle_tpu.inference import (Config, create_predictor, export_compiled,
                                  load_compiled)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _build_and_save(dirname, seed=3):
    """Dense matmul-only model: XLA compiles scan bodies bit-identically
    to top-level code for matmuls (PERF_NOTES.md conv-in-scan caveat is
    why this is NOT a conv net), so run_batches must match run() EXACTLY."""
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = seed
    with fluid.program_guard(main, startup):
        img = fluid.layers.data(name='img', shape=[8], dtype='float32')
        h = fluid.layers.fc(img, 16, act='relu')
        out = fluid.layers.fc(h, 4, act='softmax')
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    fluid.io.save_inference_model(dirname, ['img'], [out], exe, main)


def _predictor(tmp_path):
    model_dir = str(tmp_path / 'model')
    _build_and_save(model_dir)
    cfg = Config(model_dir)
    cfg.disable_gpu()
    return create_predictor(cfg)


def test_predictor_run_batches_bit_identity(tmp_path):
    pred = _predictor(tmp_path)
    rng = np.random.RandomState(0)
    xs = [rng.randn(5, 8).astype(np.float32) for _ in range(6)]
    seq = [pred.run([x])[0] for x in xs]
    bulk = pred.run_batches([[x] for x in xs])
    assert len(bulk) == 6
    for i, (s, b) in enumerate(zip(seq, bulk)):
        assert np.array_equal(s, b[0]), i
    # dict-form batches and list-form batches agree
    bulk2 = pred.run_batches([{'img': x} for x in xs])
    for b, b2 in zip(bulk, bulk2):
        assert np.array_equal(b[0], b2[0])


def test_predictor_run_batches_validates(tmp_path):
    pred = _predictor(tmp_path)
    x = np.zeros((5, 8), np.float32)
    assert pred.run_batches([]) == []
    try:
        pred.run_batches([{'wrong': x}])
        assert False, 'missing feed must raise'
    except ValueError as e:
        assert 'img' in str(e)


def test_compiled_run_batches_bit_identity_and_tail(tmp_path):
    pred = _predictor(tmp_path)
    art = str(tmp_path / 'artifact')
    rng = np.random.RandomState(1)
    xs = [rng.randn(5, 8).astype(np.float32) for _ in range(5)]
    export_compiled(pred, [xs[0]], art)
    served = load_compiled(art)
    seq = [served.run([x])[0] for x in xs]

    bulk = served.run_batches([[x] for x in xs])
    for i, (s, b) in enumerate(zip(seq, bulk)):
        assert np.array_equal(s, b[0]), i
    st = served.bulk_stats()
    assert st['dispatches'] == 1 and st['batches'] == 5
    assert st['tail_flushes'] == 0

    # group=2 over 5 batches: 3 dispatches, the last a PARTIAL tail (1
    # batch) flushed through a smaller compiled group — same results
    bulk2 = served.run_batches([[x] for x in xs], group=2)
    for i, (s, b) in enumerate(zip(seq, bulk2)):
        assert np.array_equal(s, b[0]), i
    st = served.bulk_stats()
    assert st['dispatches'] == 4 and st['batches'] == 10
    assert st['tail_flushes'] == 1
    assert st['batches_per_dispatch'] == 2.5

    # group > K is a single smaller chunk, NOT a tail flush (no full
    # chunk preceded it — only its own size ever compiled)
    served.run_batches([[xs[0]], [xs[1]]], group=8)
    assert served.bulk_stats()['tail_flushes'] == 1


def test_compiled_run_batches_donation_safety(tmp_path):
    """Stacked loop inputs are donated to XLA — but they are staged
    copies: the caller's own arrays must stay intact and reusable, and
    repeated calls over the same arrays must reproduce bit-identically."""
    import jax
    pred = _predictor(tmp_path)
    art = str(tmp_path / 'artifact')
    rng = np.random.RandomState(2)
    x_np = rng.randn(5, 8).astype(np.float32)
    export_compiled(pred, [x_np], art)
    served = load_compiled(art)

    x_dev = jax.device_put(x_np)  # a caller-owned DEVICE array
    keep_np = x_np.copy()
    first = served.run_batches([[x_np], [x_dev], [x_np]])
    assert not x_dev.is_deleted()  # donation never consumed caller buffers
    assert np.array_equal(np.asarray(x_dev), keep_np)
    assert np.array_equal(x_np, keep_np)
    second = served.run_batches([[x_np], [x_dev], [x_np]])
    for a, b in zip(first, second):
        assert np.array_equal(a[0], b[0])


def test_compiled_run_batches_partial_dense_pad(tmp_path):
    """A partial dense batch (rows < compiled bucket) pads per-batch and
    slices back — run()'s pad_partial discipline, inside the loop."""
    pred = _predictor(tmp_path)
    art = str(tmp_path / 'artifact')
    rng = np.random.RandomState(3)
    full = rng.randn(5, 8).astype(np.float32)
    part = rng.randn(2, 8).astype(np.float32)
    export_compiled(pred, [full], art)
    served = load_compiled(art)
    want_full, = served.run([full])
    want_part, = served.run([part])  # padded by run()
    bulk = served.run_batches([[full], [part], [full]])
    assert np.array_equal(bulk[0][0], want_full)
    assert bulk[1][0].shape == (2, 4)
    assert np.array_equal(bulk[1][0], want_part)
    assert np.array_equal(bulk[2][0], want_full)


def _build_lod_model(dirname):
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 5
    with fluid.program_guard(main, startup):
        ids = fluid.layers.data('ids', shape=[1], dtype='int64', lod_level=1)
        emb = fluid.layers.embedding(input=ids, size=[50, 8])
        pooled = fluid.layers.sequence_pool(emb, 'average')
        out = fluid.layers.fc(pooled, size=4, act='softmax')
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    fluid.io.save_inference_model(dirname, ['ids'], [out], exe, main)


def _ids_pair(lens, bucket_rows, seed):
    rng = np.random.RandomState(seed)
    total = int(sum(lens))
    data = rng.randint(0, 50, (total, 1)).astype(np.int64)
    offs = np.concatenate([[0], np.cumsum(lens)]).astype(np.int32)
    padded = np.zeros((bucket_rows, 1), np.int64)
    padded[:total] = data
    return (padded, [offs])


def test_compiled_run_batches_lod_bucket(tmp_path):
    """LoD feeds ride the scan as stacked runtime data+offsets (the
    traced-lod artifact convention): one bucket artifact serves K batches
    with DIFFERENT lod patterns in one dispatch, matching sequential
    run() per batch."""
    model_dir = str(tmp_path / 'model')
    art = str(tmp_path / 'artifact')
    _build_lod_model(model_dir)
    cfg = Config(model_dir)
    cfg.disable_gpu()
    pred = create_predictor(cfg)
    bucket = 12
    pairs = [_ids_pair(lens, bucket, seed=i) for i, lens in
             enumerate([[3, 5, 2], [4, 1, 6], [2, 2, 2]])]
    export_compiled(pred, {'ids': pairs[0]}, art)
    served = load_compiled(art)
    seq = [served.run({'ids': p})[0] for p in pairs]
    bulk = served.run_batches([{'ids': p} for p in pairs])
    for i, (s, b) in enumerate(zip(seq, bulk)):
        assert np.array_equal(s, b[0]), i


def test_profiler_infer_report_sources(tmp_path):
    from paddle_tpu import profiler
    pred = _predictor(tmp_path)
    art = str(tmp_path / 'artifact')
    x = np.random.RandomState(4).randn(5, 8).astype(np.float32)
    export_compiled(pred, [x], art)
    served = load_compiled(art)
    served.run_batches([[x], [x]])
    pred.run_batches([[x], [x], [x]])
    rep = profiler.infer_report()
    bulk = [v for k, v in rep.items() if k.startswith('bulk_infer:')]
    execs = [v for k, v in rep.items() if k.startswith('executor@')
             and v.get('batches') == 3]
    assert bulk and bulk[-1]['batches'] >= 2
    assert 0.0 < bulk[-1]['occupancy'] <= 1.0
    assert execs and execs[-1]['dispatches'] >= 1
    assert 'batches_per_dispatch' in execs[-1]


def test_fresh_process_loop_roundtrip(tmp_path):
    """serve.py loop in a FRESH process (run by file path — the package
    __init__ never executes): run_batches over a stacked npz must match
    in-process sequential run(), and the framework must never load."""
    pred = _predictor(tmp_path)
    art = str(tmp_path / 'artifact')
    rng = np.random.RandomState(6)
    xs = np.stack([rng.randn(5, 8).astype(np.float32) for _ in range(4)])
    export_compiled(pred, [xs[0]], art)
    served = load_compiled(art)
    want = np.stack([served.run([x])[0] for x in xs])
    np.savez(str(tmp_path / 'in.npz'), img=xs)

    probe = (
        "import runpy, sys\n"
        "sys.argv = ['serve.py', 'loop', %r, %r, %r, '3']\n"
        "try:\n"
        "    runpy.run_path(%r, run_name='__main__')\n"
        "except SystemExit as e:\n"
        "    assert (e.code or 0) == 0, e.code\n"
        "bad = [m for m in sys.modules if m.startswith('paddle_tpu')]\n"
        "assert not bad, 'framework leaked into serving: %%r' %% bad\n"
        % (art, str(tmp_path / 'in.npz'), str(tmp_path / 'out.npz'),
           os.path.join(REPO, 'paddle_tpu', 'inference', 'serve.py')))
    env = dict(os.environ)
    env['PTPU_PLATFORM'] = 'cpu'
    r = subprocess.run([sys.executable, '-c', probe], env=env,
                       capture_output=True, text=True, timeout=300)
    assert r.returncode == 0, r.stderr[-2000:]
    with np.load(str(tmp_path / 'out.npz')) as out:
        got = out[list(out.files)[0]]
    # group='3' over 4 batches exercised the tail path cross-process too
    assert np.array_equal(got, want)
