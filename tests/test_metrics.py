"""Host-side metric accumulators (paddle_tpu/metrics.py) vs direct numpy."""
import numpy as np
import pytest

from paddle_tpu import metrics


def test_precision_recall():
    p, r = metrics.Precision(), metrics.Recall()
    preds = np.array([0.9, 0.2, 0.8, 0.1, 0.7])
    labels = np.array([1, 0, 0, 1, 1])
    p.update(preds, labels)
    r.update(preds, labels)
    # predictions rint -> [1,0,1,0,1]; tp=2 fp=1 fn=1
    assert p.eval() == pytest.approx(2 / 3)
    assert r.eval() == pytest.approx(2 / 3)
    # accumulation across batches
    p.update(np.array([1.0]), np.array([1]))
    assert p.eval() == pytest.approx(3 / 4)
    p.reset()
    assert p.eval() == 0.0


def test_accuracy_weighted():
    a = metrics.Accuracy()
    a.update(0.5, 10)
    a.update(1.0, 30)
    assert a.eval() == pytest.approx((0.5 * 10 + 1.0 * 30) / 40)
    with pytest.raises(ValueError):
        metrics.Accuracy().eval()


def test_auc_matches_exact():
    rng = np.random.RandomState(3)
    scores = rng.rand(500)
    labels = (rng.rand(500) < scores).astype(int)  # informative scores
    m = metrics.Auc()
    m.update(np.stack([1 - scores, scores], 1), labels)
    got = m.eval()
    # exact AUC via rank statistic
    order = np.argsort(scores)
    ranks = np.empty(500)
    ranks[order] = np.arange(1, 501)
    npos = labels.sum()
    nneg = 500 - npos
    exact = (ranks[labels == 1].sum() - npos * (npos + 1) / 2) / (npos * nneg)
    assert got == pytest.approx(exact, abs=2e-3)  # bucketization error only


def test_chunk_evaluator_and_edit_distance():
    c = metrics.ChunkEvaluator()
    c.update(4, 5, 3)
    c.update(1, 0, 0)
    prec, rec, f1 = c.eval()
    assert prec == pytest.approx(3 / 5)
    assert rec == pytest.approx(3 / 5)
    e = metrics.EditDistance()
    e.update(np.array([2.0, 0.0, 1.0]), 3)
    avg, err = e.eval()
    assert avg == pytest.approx(1.0)
    assert err == pytest.approx(2 / 3)


def test_composite():
    cm = metrics.CompositeMetric()
    cm.add_metric(metrics.Precision())
    cm.add_metric(metrics.Recall())
    cm.update(np.array([1.0, 0.0]), np.array([1, 1]))
    assert cm.eval() == [1.0, 0.5]
    with pytest.raises(TypeError):
        cm.add_metric(object())
