"""Switch-MoE FFN (expert parallelism over the mesh 'ep' axis): numeric
parity vs a numpy reference, capacity-drop semantics, training, and
ep-sharded execution matching single-device outputs.

TPU-native extension (the reference has no MoE); GShard/Switch einsum
dispatch (ops/moe_ops.py) keeps every shape static so GSPMD inserts the
all-to-alls.
"""
import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu.parallel import make_mesh
from paddle_tpu.parallel.compiler import CompiledProgram


def _np_switch_moe(x, gw, w1, w2, cap_factor=1.25):
    n, d = x.shape
    e = gw.shape[1]
    cap = max(1, int(np.ceil(n * cap_factor / e)))
    logits = x @ gw
    z = logits - logits.max(-1, keepdims=True)
    gates = np.exp(z) / np.exp(z).sum(-1, keepdims=True)
    idx = gates.argmax(-1)
    out = np.zeros_like(x)
    counts = np.zeros(e, np.int64)
    for i in range(n):
        ex = idx[i]
        if counts[ex] >= cap:
            counts[ex] += 1
            continue  # dropped token: zero output
        counts[ex] += 1
        h = np.maximum(x[i] @ w1[ex], 0.0)
        out[i] = (h @ w2[ex]) * gates[i, ex]
    return out


def _build(n_tok, d, e, f, cap=1.25, seed=7):
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = seed
    with fluid.program_guard(main, startup):
        x = fluid.layers.data(name='x', shape=[d], dtype='float32')
        out, aux = fluid.layers.switch_moe_ffn(x, num_experts=e, d_ff=f,
                                               capacity_factor=cap)
    return main, startup, out, aux


def test_switch_moe_matches_numpy():
    n, d, e, f = 32, 8, 4, 16
    main, startup, out, aux = _build(n, d, e, f)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    from paddle_tpu.core.scope import global_scope
    params = main.global_block().all_parameters()
    gw, w1, w2 = [np.asarray(global_scope().get(p.name)) for p in params]
    rng = np.random.RandomState(0)
    x = rng.randn(n, d).astype(np.float32)
    got, aux_v = exe.run(main, feed={'x': x}, fetch_list=[out, aux])
    want = _np_switch_moe(x, gw, w1, w2)
    np.testing.assert_allclose(np.asarray(got), want, rtol=1e-4, atol=1e-4)
    assert np.isfinite(float(np.asarray(aux_v).reshape(-1)[0]))


def test_capacity_drops_overflow_tokens():
    # capacity_factor so small every expert takes exactly 1 token
    n, d, e, f = 8, 4, 4, 8
    main, startup, out, aux = _build(n, d, e, f, cap=0.5)  # cap = 1
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    from paddle_tpu.core.scope import global_scope
    params = main.global_block().all_parameters()
    gw, w1, w2 = [np.asarray(global_scope().get(p.name)) for p in params]
    rng = np.random.RandomState(1)
    x = rng.randn(n, d).astype(np.float32)
    got, = exe.run(main, feed={'x': x}, fetch_list=[out])
    want = _np_switch_moe(x, gw, w1, w2, cap_factor=0.5)
    np.testing.assert_allclose(np.asarray(got), want, rtol=1e-4, atol=1e-4)
    # with 8 tokens / 4 experts / capacity 1, some rows MUST be dropped
    assert (np.abs(want).sum(axis=1) == 0).any()


def test_moe_trains_with_aux_loss():
    n, d, e, f = 16, 8, 4, 16
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 3
    with fluid.program_guard(main, startup):
        x = fluid.layers.data(name='x', shape=[d], dtype='float32')
        y = fluid.layers.data(name='y', shape=[d], dtype='float32')
        out, aux = fluid.layers.switch_moe_ffn(x, num_experts=e, d_ff=f)
        mse = fluid.layers.mean(fluid.layers.square(out - y))
        loss = mse + 0.01 * aux
        fluid.optimizer.Adam(1e-2).minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    rng = np.random.RandomState(0)
    feed = {'x': rng.randn(n, d).astype(np.float32),
            'y': rng.randn(n, d).astype(np.float32)}
    vals = []
    for _ in range(25):
        l, = exe.run(main, feed=feed, fetch_list=[loss])
        vals.append(float(np.asarray(l).reshape(-1)[0]))
    assert np.isfinite(vals).all()
    assert vals[-1] < vals[0], (vals[0], vals[-1])


def test_expert_parallel_matches_single_device():
    n, d, e, f = 32, 8, 4, 16
    main, startup, out, aux = _build(n, d, e, f, seed=11)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    rng = np.random.RandomState(2)
    x = rng.randn(n, d).astype(np.float32)
    single, = exe.run(main, feed={'x': x}, fetch_list=[out])

    main2, startup2, out2, aux2 = _build(n, d, e, f, seed=11)
    mesh = make_mesh(axes={'dp': 2, 'ep': 4})
    prog = CompiledProgram(main2).with_data_parallel(mesh=mesh)
    exe2 = fluid.Executor(fluid.CPUPlace())
    exe2.run(startup2)
    sharded, = exe2.run(prog, feed={'x': x}, fetch_list=[out2])
    np.testing.assert_allclose(np.asarray(sharded), np.asarray(single),
                               rtol=1e-4, atol=1e-4)
