"""Dynamic-batching serving (ISSUE 1): BatchingPredictor coalescing,
multi-bucket artifacts, partial dense-batch padding in CompiledPredictor,
serving metrics through the profiler, and the serve.py bench CLI.

Determinism contract under test: per-request outputs are bit-identical to
an unbatched CompiledPredictor.run through the SAME bucket (row position
inside a compiled batch never changes per-row results); across different
buckets only allclose holds, as with any XLA batch-size change.
"""
import json
import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import profiler
from paddle_tpu.inference import (BatchingPredictor, CompiledPredictor,
                                  Config, create_predictor, export_compiled)
from paddle_tpu.inference.batching import select_bucket

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
DIM = 8


def _build_predictor(tmp, reduce_fetch=False):
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 7
    with fluid.program_guard(main, startup):
        img = fluid.layers.data(name='img', shape=[DIM], dtype='float32')
        h = fluid.layers.fc(img, 32, act='relu')
        out = fluid.layers.fc(h, 4, act='softmax')
        fetches = [out]
        if reduce_fetch:
            fetches.append(fluid.layers.reduce_mean(out))
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    model_dir = os.path.join(tmp, 'model')
    fluid.io.save_inference_model(model_dir, ['img'],
                                  fetches, exe, main)
    cfg = Config(model_dir)
    cfg.disable_gpu()
    return create_predictor(cfg)


@pytest.fixture(scope='module')
def artifacts(tmp_path_factory):
    """One model, exported three ways: multi-bucket {1,8,32}, single
    bucket {16} (for strict bit-identity), and a simulated legacy v2
    single-bucket artifact (no fetch shapes, no buckets key)."""
    tmp = str(tmp_path_factory.mktemp('batching'))
    with fluid.scope_guard(fluid.core.Scope()), fluid.unique_name.guard():
        pred = _build_predictor(tmp)
        sample = np.random.RandomState(0).randn(4, DIM).astype(np.float32)
        multi = os.path.join(tmp, 'multi')
        export_compiled(pred, [sample], multi, batch_sizes=[1, 8, 32])
        single = os.path.join(tmp, 'single')
        export_compiled(pred, [sample], single, batch_sizes=[16])
        legacy = os.path.join(tmp, 'legacy')
        export_compiled(pred, [np.resize(sample, (8, DIM))], legacy)
        sig_path = os.path.join(legacy, 'signature.json')
        with open(sig_path) as f:
            sig = json.load(f)
        sig['version'] = 2  # v2 artifacts carried no fetch shapes
        for e in sig['fetches']:
            e.pop('shape', None)
        with open(sig_path, 'w') as f:
            json.dump(sig, f)
    return {'multi': multi, 'single': single, 'legacy': legacy,
            'pred': pred}


def _x(seed, rows):
    return np.random.RandomState(100 + seed).randn(
        rows, DIM).astype(np.float32)


# -- multi-bucket export round-trip -----------------------------------------

def test_multibucket_layout_and_signature(artifacts):
    multi = artifacts['multi']
    sig = json.load(open(os.path.join(multi, 'signature.json')))
    assert sig['buckets'] == [1, 8, 32]
    assert sig['feeds'][0]['shape'] == [32, DIM]  # top mirrors largest
    assert sig['fetches'][0]['shape'] == [32, 4]  # v3 records fetch shapes
    for b in (1, 8, 32):
        bdir = os.path.join(multi, 'bucket_%05d' % b)
        bsig = json.load(open(os.path.join(bdir, 'signature.json')))
        assert bsig['feeds'][0]['shape'] == [b, DIM]
        assert 'buckets' not in bsig  # each bucket is a plain artifact


def test_multibucket_loads_in_old_and_new_entry_points(artifacts):
    multi, pred = artifacts['multi'], artifacts['pred']
    x = _x(0, 32)
    want, = pred.run([x])
    # old entry point: CompiledPredictor sees the largest bucket
    old = CompiledPredictor(multi)
    got, = old.run([x])
    np.testing.assert_allclose(got, want, rtol=1e-6, atol=1e-6)
    # each bucket dir is itself a loadable standard artifact
    b8 = CompiledPredictor(os.path.join(multi, 'bucket_00008'))
    got8, = b8.run([x[:8]])
    np.testing.assert_allclose(got8, want[:8], rtol=1e-6, atol=1e-6)
    # new entry point
    with BatchingPredictor(multi, batch_timeout_ms=1.0) as batcher:
        assert batcher.buckets == [1, 8, 32]
        assert batcher.get_input_names() == ['img']
        res, = batcher.run([x[:3]])
        np.testing.assert_allclose(res, want[:3], rtol=1e-6, atol=1e-6)


def test_v2_single_bucket_artifact_still_loads(artifacts):
    legacy, pred = artifacts['legacy'], artifacts['pred']
    x = _x(1, 8)
    want, = pred.run([x])
    got, = CompiledPredictor(legacy).run([x])
    np.testing.assert_allclose(got, want, rtol=1e-6, atol=1e-6)
    with BatchingPredictor(legacy, batch_timeout_ms=1.0) as batcher:
        assert batcher.buckets == [8]
        res, = batcher.run([x[:2]])
        np.testing.assert_allclose(res, want[:2], rtol=1e-6, atol=1e-6)


# -- partial dense-batch padding in CompiledPredictor ------------------------

def test_compiled_predictor_pads_partial_dense_batch(artifacts):
    pred = artifacts['pred']
    served = CompiledPredictor(artifacts['single'])  # compiled for 16 rows
    x = _x(2, 5)
    got, = served.run([x])
    assert got.shape == (5, 4)
    want, = pred.run([x])
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


def test_partial_batch_row_dependent_fetch_errors_loudly(tmp_path):
    with fluid.scope_guard(fluid.core.Scope()), fluid.unique_name.guard():
        pred = _build_predictor(str(tmp_path), reduce_fetch=True)
    art = str(tmp_path / 'artifact')
    export_compiled(pred, [_x(3, 8)], art)
    served = CompiledPredictor(art)
    # exact batch: fine, both fetches come back
    outs = served.run([_x(3, 8)])
    assert outs[0].shape == (8, 4) and outs[1].size == 1
    # partial batch: the scalar reduce_mean depends on padded rows —
    # must error loudly, not silently average in zeros
    with pytest.raises(ValueError, match='not batch-aligned'):
        served.run([_x(3, 3)])


# -- batcher core ------------------------------------------------------------

def test_select_bucket_unsorted_prefers_smallest_fit():
    """Regression (ISSUE 8 satellite): with an UNSORTED bucket list the
    old prefix walk returned the first fit, not the smallest — a
    hand-edited signature once routed 2-row batches to the 128 bucket.
    select_bucket is now order-independent; loaders still sort once at
    load so the common path stays a prefix walk."""
    import random
    buckets = [1, 8, 32, 128]
    for seed in range(6):
        shuffled = list(buckets)
        random.Random(seed).shuffle(shuffled)
        for rows, want in ((1, 1), (2, 8), (8, 8), (9, 32), (33, 128),
                           (128, 128)):
            assert select_bucket(shuffled, rows) == want, shuffled
    with pytest.raises(ValueError):
        select_bucket([128, 1, 32, 8], 129)


def test_batcher_routes_through_smallest_bucket_with_shuffled_sig(
        artifacts):
    """A signature whose bucket list is NOT sorted ascending (hand-edited
    or produced by an older exporter) still routes each batch to the
    smallest fitting bucket: the predictor sorts once at load."""
    import shutil
    shuffled_dir = artifacts['multi'] + '_shuffled'
    if not os.path.isdir(shuffled_dir):
        shutil.copytree(artifacts['multi'], shuffled_dir)
        sig_path = os.path.join(shuffled_dir, 'signature.json')
        with open(sig_path) as f:
            sig = json.load(f)
        sig['buckets'] = [32, 1, 8]
        with open(sig_path, 'w') as f:
            json.dump(sig, f)
    b = BatchingPredictor(shuffled_dir, batch_timeout_ms=1.0)
    try:
        assert b.buckets == [1, 8, 32]
        b.run([_x(77, 2)])
        snap = b.stats.snapshot()
        # 2 rows padded into the 8-bucket (occupancy 2/8), never 32
        assert snap['occupancy'] == pytest.approx(0.25)
    finally:
        b.close()


def test_select_bucket_boundaries():
    buckets = [1, 8, 32]
    assert select_bucket(buckets, 1) == 1
    assert select_bucket(buckets, 2) == 8
    assert select_bucket(buckets, 8) == 8
    assert select_bucket(buckets, 9) == 32
    assert select_bucket(buckets, 32) == 32
    with pytest.raises(ValueError, match='exceeds the largest'):
        select_bucket(buckets, 33)


def test_coalescing_routes_results_to_the_right_caller(artifacts):
    pred = artifacts['pred']
    with BatchingPredictor(artifacts['multi'],
                           batch_timeout_ms=20.0) as batcher:
        reqs = [(_x(10 + i, 1 + i % 3)) for i in range(12)]
        futs = [batcher.submit([x]) for x in reqs]
        for x, fut in zip(reqs, futs):
            got, = fut.result(timeout=30)
            assert got.shape == (x.shape[0], 4)
            want, = pred.run([x])
            np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)
        snap = batcher.stats.snapshot()
        assert snap['requests'] == 12
        assert snap['batches'] <= 12  # some coalescing happened or not —
        # but every row was accounted
        assert snap['queue_depth'] == 0


def test_timeout_flushes_lone_request(artifacts):
    # single bucket of 16: a lone 1-row request can only leave the queue
    # via the timeout flush (rows < max never fills the bucket)
    with BatchingPredictor(artifacts['single'],
                           batch_timeout_ms=60.0) as batcher:
        t0 = time.perf_counter()
        got, = batcher.run([_x(20, 1)], timeout=30)
        dt = time.perf_counter() - t0
    assert got.shape == (1, 4)
    assert dt >= 0.055  # held for the full coalescing window before flush
    want, = artifacts['pred'].run([_x(20, 1)])
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


def test_per_request_error_isolation(artifacts):
    with BatchingPredictor(artifacts['multi'],
                           batch_timeout_ms=20.0) as batcher:
        good1 = batcher.submit([_x(30, 2)])
        bad_shape = batcher.submit([_x(31, 2).reshape(2, 2, DIM // 2)])
        too_big = batcher.submit([_x(32, 64)])  # > largest bucket
        good2 = batcher.submit([_x(33, 3)])
        with pytest.raises(ValueError, match='per-request shape'):
            bad_shape.result(timeout=30)
        with pytest.raises(ValueError, match='exceeds max_batch_size'):
            too_big.result(timeout=30)
        for fut, seed, rows in ((good1, 30, 2), (good2, 33, 3)):
            got, = fut.result(timeout=30)
            want, = artifacts['pred'].run([_x(seed, rows)])
            np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


def test_cancelled_future_does_not_poison_the_batch(artifacts):
    # queued futures are never marked running, so a client cancel() always
    # wins; delivery must skip it without killing the worker thread or
    # stranding the batch's other requests
    pred = artifacts['pred']
    with BatchingPredictor(artifacts['single'],
                           batch_timeout_ms=40.0) as batcher:
        doomed = batcher.submit([_x(80, 1)])
        assert doomed.cancel()
        live = batcher.submit([_x(81, 2)])
        got, = live.result(timeout=30)
        want, = pred.run([_x(81, 2)])
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)
        got2, = batcher.run([_x(82, 1)], timeout=30)  # next batch serves too
        assert got2.shape == (1, 4)


def test_caller_buffer_reuse_does_not_corrupt_request(artifacts):
    # dispatch is async: a client that refills its own buffer right after
    # submit() (standard producer pattern) must not corrupt the in-flight
    # request — submit snapshots caller-owned arrays
    pred = artifacts['pred']
    buf = _x(90, 2)
    want, = pred.run([buf.copy()])
    with BatchingPredictor(artifacts['multi'],
                           batch_timeout_ms=30.0) as batcher:
        fut = batcher.submit([buf])
        buf[:] = -1e9  # refill for the "next" request while in flight
        got, = fut.result(timeout=30)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


def test_pad_partial_false_restores_strict_shapes(artifacts):
    served = CompiledPredictor(artifacts['single'])
    with pytest.raises(ValueError, match='expected shape'):
        served.run([_x(21, 5)], pad_partial=False)


def test_submit_after_close_raises(artifacts):
    batcher = BatchingPredictor(artifacts['single'], batch_timeout_ms=1.0)
    batcher.run([_x(40, 1)], timeout=30)
    batcher.close()
    batcher.close()  # idempotent
    with pytest.raises(RuntimeError, match='closed'):
        batcher.submit([_x(40, 1)])


def test_batcher_rejects_lod_and_unaligned_artifacts(tmp_path):
    with fluid.scope_guard(fluid.core.Scope()), fluid.unique_name.guard():
        pred = _build_predictor(str(tmp_path), reduce_fetch=True)
    art = str(tmp_path / 'artifact')
    export_compiled(pred, [_x(3, 8)], art)
    # the scalar reduce_mean fetch cannot be sliced per request: load-time
    # refusal (v3 signatures record fetch shapes)
    with pytest.raises(ValueError, match='not batch-aligned'):
        BatchingPredictor(art)


# -- acceptance: throughput + bit-identity ----------------------------------

def test_64_concurrent_requests_4x_faster_and_bit_identical(tmp_path):
    """ISSUE 1 acceptance: 64 concurrent bs-1 requests through the batcher
    achieve >= 4x the request throughput of sequential
    CompiledPredictor.run calls, with bit-identical per-request outputs
    (single 32-row bucket: every path runs the same compiled module).

    The model carries real per-bucket compute (4 fc layers of 2048 —
    heavy enough that the padded-bucket forward, not Python overhead,
    dominates both sides) so the comparison measures what batching
    amortizes: sequential serving pays a FULL padded-bucket forward per
    bs-1 request, the batcher pays it once per ~32 coalesced requests."""
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 11
    with fluid.program_guard(main, startup):
        img = fluid.layers.data(name='img', shape=[DIM], dtype='float32')
        h = img
        for _ in range(4):
            h = fluid.layers.fc(h, 2048, act='relu')
        out = fluid.layers.fc(h, 4, act='softmax')
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    model_dir = str(tmp_path / 'model')
    fluid.io.save_inference_model(model_dir, ['img'], [out], exe, main)
    cfg = Config(model_dir)
    cfg.disable_gpu()
    pred = create_predictor(cfg)
    art = str(tmp_path / 'artifact')
    export_compiled(pred, [_x(49, 4)], art, batch_sizes=[32])
    xs = [_x(50 + i, 1) for i in range(64)]

    seq = CompiledPredictor(art)
    seq.run([xs[0]])  # warm the compile cache
    t0 = time.perf_counter()
    seq_out = [seq.run([x])[0] for x in xs]
    seq_dt = time.perf_counter() - t0

    # barrier: all 64 clients submit in one burst, so the coalescing
    # window races the sub-ms submits, not 64 thread startups (which can
    # exceed the window and split the batch — the flush is then measuring
    # thread-spawn time, not serving)
    with BatchingPredictor(art, batch_timeout_ms=250.0) as batcher:
        batcher.warmup()
        results = [None] * 64
        gate = threading.Barrier(64)

        def client(i):
            gate.wait(timeout=60)
            results[i] = batcher.submit([xs[i]]).result(timeout=60)

        threads = [threading.Thread(target=client, args=(i,))
                   for i in range(64)]
        for t in threads:
            t.start()
        t0 = time.perf_counter()
        for t in threads:
            t.join()
        bat_dt = time.perf_counter() - t0
        snap = batcher.stats.snapshot()

    for i in range(64):
        got, = results[i]
        assert np.array_equal(got, seq_out[i]), (
            'request %d not bit-identical to its unbatched run' % i)
    assert snap['requests'] == 64
    speedup = seq_dt / bat_dt
    assert speedup >= 4.0, (
        'batched serving only %.1fx sequential (%.3fs vs %.3fs, '
        'occupancy %.2f)' % (speedup, bat_dt, seq_dt, snap['occupancy']))


# -- serving metrics ---------------------------------------------------------

def test_serving_stats_and_profiler_report(artifacts):
    batcher = BatchingPredictor(artifacts['multi'], batch_timeout_ms=5.0)
    name = batcher._profiler_name
    assert name and name in profiler._serving_sources  # auto-registered
    for i in range(6):
        batcher.run([_x(60 + i, 2)], timeout=30)
    report = profiler.serving_report()
    snap = report[name]
    assert snap['requests'] == 6
    assert snap['queue_depth'] == 0
    assert 0.0 < snap['occupancy'] <= 1.0
    assert snap['p99_ms'] >= snap['p50_ms'] > 0.0
    batcher.close()
    assert name not in profiler._serving_sources


# -- load shedding + per-request deadlines (ISSUE 6 satellite) ---------------

def test_overloaded_queue_sheds_requests_fast(artifacts):
    """Beyond max_queue, submit() resolves to ServerOverloaded instead of
    queueing into unbounded latency; shed requests are counted and never
    cost a padded batch slot."""
    from paddle_tpu.inference import ServerOverloaded
    batcher = BatchingPredictor(artifacts['multi'], max_queue=2,
                                batch_timeout_ms=5.0)
    with batcher.stats._lock:
        batcher.stats.queue_depth = 2       # simulate a standing backlog
    fut = batcher.submit([_x(0, 1)])
    with pytest.raises(ServerOverloaded, match='shed'):
        fut.result(5)
    with batcher.stats._lock:
        batcher.stats.queue_depth = 0
    out, = batcher.run([_x(1, 1)], timeout=30)  # back under: serves fine
    assert out.shape[0] == 1
    assert batcher.stats.snapshot()['shed'] == 1
    batcher.close()


def test_overload_flood_all_requests_resolve(artifacts):
    """Under a flood with a tight max_queue every future resolves — to a
    result or to ServerOverloaded — and the sum adds up; nothing hangs."""
    from paddle_tpu.inference import ServerOverloaded
    batcher = BatchingPredictor(artifacts['multi'], max_queue=4,
                                batch_timeout_ms=1.0)
    batcher.warmup()
    futs = [batcher.submit([_x(i, 1)]) for i in range(64)]
    served = shed = 0
    for f in futs:
        try:
            f.result(60)
            served += 1
        except ServerOverloaded:
            shed += 1
    assert served + shed == 64 and served >= 1
    snap = batcher.stats.snapshot()
    assert snap['shed'] == shed and snap['requests'] == served
    assert snap['queue_depth'] == 0
    batcher.close()


def test_expired_deadline_fails_before_dispatch(artifacts):
    from paddle_tpu.inference import DeadlineExceeded
    batcher = BatchingPredictor(artifacts['multi'], batch_timeout_ms=5.0)
    batcher.warmup()
    fut = batcher.submit([_x(2, 1)], deadline_ms=0.0)
    with pytest.raises(DeadlineExceeded, match='expired'):
        fut.result(5)
    out, = batcher.run([_x(3, 1)], timeout=30)   # no-deadline peer serves
    assert out.shape[0] == 1
    snap = batcher.stats.snapshot()
    assert snap['expired'] == 1 and snap['queue_depth'] == 0
    assert snap['requests'] == 1   # the expired one never dispatched
    batcher.close()


def test_generous_deadline_is_met(artifacts):
    batcher = BatchingPredictor(artifacts['multi'], batch_timeout_ms=1.0)
    batcher.warmup()
    out, = batcher.run([_x(4, 2)], timeout=30, deadline_ms=60000.0)
    assert out.shape[0] == 2
    assert batcher.stats.snapshot()['expired'] == 0
    batcher.close()


def test_shed_and_expired_in_profiler_serving_report(artifacts):
    from paddle_tpu.inference import ServerOverloaded, DeadlineExceeded
    batcher = BatchingPredictor(artifacts['multi'], max_queue=1,
                                batch_timeout_ms=5.0)
    batcher.warmup()
    with batcher.stats._lock:
        batcher.stats.queue_depth = 1
    with pytest.raises(ServerOverloaded):
        batcher.submit([_x(5, 1)]).result(5)
    with batcher.stats._lock:
        batcher.stats.queue_depth = 0
    with pytest.raises(DeadlineExceeded):
        batcher.submit([_x(6, 1)], deadline_ms=0.0).result(5)
    snap = profiler.serving_report()[batcher._profiler_name]
    assert snap['shed'] == 1 and snap['expired'] == 1
    batcher.close()


# -- serve.py bench CLI (framework-free process) -----------------------------

def test_serve_bench_cli_fresh_process_framework_free(artifacts, tmp_path):
    in_path = str(tmp_path / 'in.npz')
    np.savez(in_path, img=_x(70, 1))
    probe = (
        "import runpy, sys\n"
        "sys.argv = ['serve.py', 'bench', %r, %r, '24', '5']\n"
        "try:\n"
        "    runpy.run_path(%r, run_name='__main__')\n"
        "except SystemExit as e:\n"
        "    assert (e.code or 0) == 0, e.code\n"
        "bad = [m for m in sys.modules if m.startswith('paddle_tpu')]\n"
        "assert not bad, 'framework leaked into serving: %%r' %% bad\n"
        % (artifacts['multi'], in_path,
           os.path.join(REPO, 'paddle_tpu', 'inference', 'serve.py')))
    env = dict(os.environ)
    env['PTPU_PLATFORM'] = 'cpu'
    env['JAX_PLATFORMS'] = 'cpu'
    r = subprocess.run([sys.executable, '-c', probe], env=env,
                       capture_output=True, text=True, timeout=600)
    assert r.returncode == 0, r.stderr[-2000:]
    last = [l for l in r.stdout.splitlines() if l.strip()][-1]
    stats = json.loads(last)
    assert stats['req_s'] > 0 and stats['p99_ms'] >= stats['p50_ms']


# -- slow tier: threaded stress + Poisson bench scenario ---------------------

@pytest.mark.slow
def test_threaded_stress(artifacts):
    pred = artifacts['pred']
    wants = {}
    for i in range(40):
        rows = 1 + i % 5
        wants[i] = (rows, pred.run([_x(200 + i, rows)])[0])
    with BatchingPredictor(artifacts['multi'],
                           batch_timeout_ms=2.0) as batcher:
        errors = []

        def client(tid):
            try:
                for i in range(tid, 40, 8):
                    rows, want = wants[i]
                    got, = batcher.submit(
                        [_x(200 + i, rows)]).result(timeout=60)
                    np.testing.assert_allclose(got, want, rtol=1e-5,
                                               atol=1e-6)
            except Exception as e:  # surfaced after join
                errors.append((tid, e))

        threads = [threading.Thread(target=client, args=(t,))
                   for t in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        snap = batcher.stats.snapshot()
    assert not errors, errors[:3]
    assert snap['requests'] == 40
    assert snap['queue_depth'] == 0


@pytest.mark.slow
def test_bench_poisson_serving_scenario(monkeypatch):
    """The bench.py serving scenario end-to-end in a tiny configuration
    (Poisson arrivals, auto-calibrated rate)."""
    import bench
    monkeypatch.setenv('PTPU_BENCH_SMOKE_BUCKETS', '1,4')
    monkeypatch.setenv('PTPU_BENCH_SMOKE_REQS', '16')
    monkeypatch.setenv('PTPU_BENCH_SMOKE_TIMEOUT_MS', '5')
    line = bench._bench_image_serving(
        'smoke_serving_img_s', lambda images: fluid.layers.fc(
            images, 4, act='softmax'),
        'SMOKE', 1.0, 'self', dshape=(DIM,))
    assert line['metric'] == 'smoke_serving_img_s'
    assert line['value'] > 0
    assert line['p99_ms'] >= line['p50_ms'] > 0
    assert 0 < line['occupancy'] <= 1.0
