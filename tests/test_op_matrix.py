"""Parametrized OpTest matrix: forward numeric checks vs numpy + central
difference gradient checks across the dense op library.

This is the breadth pass the reference gets from its ~300 test_*_op.py
files (op_test.py:303 check_output, :414 check_grad): every family of
registered lowerings gets at least one numeric forward check, and every
differentiable family a numeric-vs-analytic gradient check — the generic
vjp grad path (core/lowering.py) is exactly where silent wrongness hides.
Inputs are tiny (grad checks re-run the program 2x per element) and kept
away from non-smooth points (|x| > 0.1 for relu-like kinks).
"""
import numpy as np
import pytest
from scipy import special as sp_special

from op_test import OpTest


def _x(shape=(2, 3), lo=-1.0, hi=1.0, seed=0, away_from=None, margin=0.15):
    rng = np.random.RandomState(seed)
    v = rng.uniform(lo, hi, size=shape).astype(np.float32)
    if away_from is not None:
        v = np.where(np.abs(v - away_from) < margin,
                     v + np.sign(v - away_from + 1e-9) * margin, v)
    return v.astype(np.float32)


def _run_spec(op, ins, attrs, refs, grads=(), out_dtype=None,
              atol=1e-5, rtol=1e-5, max_rel=5e-3, delta=1e-3):
    t = OpTest()
    t.op_type = op
    t.inputs = ins
    t.attrs = attrs
    t.outputs = refs
    t.check_output(atol=atol, rtol=rtol,
                   no_check_set=[n for n, v in refs.items() if v is None])
    for g in grads:
        t.check_grad([g], list(refs)[0], max_relative_error=max_rel,
                     numeric_delta=delta)


# ---------------------------------------------------------------------------
# activations: (op, numpy fn, input gen, check grad?)
# ---------------------------------------------------------------------------
_sig = lambda x: 1 / (1 + np.exp(-x))
ACTIVATIONS = [
    ('abs', np.abs, _x(away_from=0.0), True),
    ('ceil', np.ceil, _x(away_from=0.0), False),
    ('floor', np.floor, _x(away_from=0.0), False),
    ('round', np.round, _x(away_from=0.5), False),
    ('cos', np.cos, _x(), True),
    ('sin', np.sin, _x(), True),
    ('exp', np.exp, _x(), True),
    ('log', np.log, _x(lo=0.3, hi=2.0), True),
    ('sqrt', lambda x: np.sqrt(x), _x(lo=0.3, hi=2.0), True),
    ('rsqrt', lambda x: 1 / np.sqrt(x), _x(lo=0.3, hi=2.0), True),
    ('square', np.square, _x(), True),
    ('reciprocal', lambda x: 1 / x, _x(lo=0.4, hi=2.0), True),
    ('sign', np.sign, _x(away_from=0.0), False),
    ('sigmoid', _sig, _x(), True),
    ('logsigmoid', lambda x: np.log(_sig(x)), _x(), True),
    ('tanh', np.tanh, _x(), True),
    ('tanh_shrink', lambda x: x - np.tanh(x), _x(), True),
    ('relu', lambda x: np.maximum(x, 0), _x(away_from=0.0), True),
    ('relu6', lambda x: np.clip(x, 0, 6), _x(away_from=0.0), True),
    ('softplus', lambda x: np.log1p(np.exp(x)), _x(), True),
    ('softsign', lambda x: x / (1 + np.abs(x)), _x(away_from=0.0), True),
    ('erf', sp_special.erf, _x(), True),
    ('gelu', lambda x: 0.5 * x * (1 + sp_special.erf(x / np.sqrt(2))),
     _x(), True),
]


@pytest.mark.parametrize('op,fn,x,grad', ACTIVATIONS,
                         ids=[a[0] for a in ACTIVATIONS])
def test_activation(op, fn, x, grad):
    _run_spec(op, {'X': x}, {}, {'Out': fn(x).astype(np.float32)},
              grads=['X'] if grad else ())


PARAM_ACTS = [
    ('leaky_relu', {'alpha': 0.1},
     lambda x, a: np.where(x > 0, x, a['alpha'] * x), _x(away_from=0.0)),
    ('elu', {'alpha': 1.0},
     lambda x, a: np.where(x > 0, x, a['alpha'] * (np.exp(x) - 1)),
     _x(away_from=0.0)),
    ('brelu', {'t_min': -0.5, 't_max': 0.5},
     lambda x, a: np.clip(x, a['t_min'], a['t_max']),
     _x(away_from=0.5, seed=3)),
    ('hard_sigmoid', {'slope': 0.2, 'offset': 0.5},
     lambda x, a: np.clip(x * a['slope'] + a['offset'], 0, 1), _x()),
    ('hard_shrink', {'threshold': 0.3},
     lambda x, a: np.where(np.abs(x) > a['threshold'], x, 0),
     _x(away_from=0.3, seed=5)),
    ('softshrink', {'lambda': 0.3},
     lambda x, a: np.where(x > 0.3, x - 0.3, np.where(x < -0.3, x + 0.3, 0)),
     _x(seed=6)),
    ('thresholded_relu', {'threshold': 0.2},
     lambda x, a: np.where(x > 0.2, x, 0.0), _x(seed=7)),
    ('swish', {'beta': 1.0}, lambda x, a: x * _sig(x), _x()),
    ('stanh', {'scale_a': 0.67, 'scale_b': 1.7159},
     lambda x, a: a['scale_b'] * np.tanh(a['scale_a'] * x), _x()),
    ('soft_relu', {'threshold': 40.0},
     lambda x, a: np.log1p(np.exp(np.clip(x, -40, 40))), _x()),
    ('pow', {'factor': 2.0}, lambda x, a: x ** 2, _x(lo=0.2, hi=1.5)),
    ('scale', {'scale': 2.5, 'bias': 0.5},
     lambda x, a: x * 2.5 + 0.5, _x()),
    ('clip', {'min': -0.4, 'max': 0.4},
     lambda x, a: np.clip(x, -0.4, 0.4), _x(seed=8)),
]


@pytest.mark.parametrize('op,attrs,fn,x', PARAM_ACTS,
                         ids=[a[0] for a in PARAM_ACTS])
def test_param_activation(op, attrs, fn, x):
    _run_spec(op, {'X': x}, attrs, {'Out': fn(x, attrs).astype(np.float32)},
              grads=['X'])


# ---------------------------------------------------------------------------
# elementwise binary (incl. axis broadcast)
# ---------------------------------------------------------------------------
ELEMENTWISE = [
    ('elementwise_add', np.add, True),
    ('elementwise_sub', np.subtract, True),
    ('elementwise_mul', np.multiply, True),
    ('elementwise_div', np.divide, True),
    ('elementwise_max', np.maximum, True),
    ('elementwise_min', np.minimum, True),
    ('elementwise_pow', np.power, False),
]


@pytest.mark.parametrize('op,fn,grad', ELEMENTWISE,
                         ids=[e[0] for e in ELEMENTWISE])
def test_elementwise(op, fn, grad):
    x = _x((2, 3), lo=0.3, hi=1.5, seed=1)
    y = _x((2, 3), lo=0.4, hi=1.6, seed=2)
    _run_spec(op, {'X': x, 'Y': y}, {},
              {'Out': fn(x, y).astype(np.float32)},
              grads=['X', 'Y'] if grad else ())


def test_elementwise_axis_broadcast():
    # Paddle axis semantics: y [3] broadcast onto x [2, 3, 4] at axis=1
    x = _x((2, 3, 4), seed=3)
    y = _x((3,), seed=4)
    _run_spec('elementwise_add', {'X': x, 'Y': y}, {'axis': 1},
              {'Out': x + y.reshape(1, 3, 1)}, grads=['X', 'Y'])


def test_elementwise_int_mod_floordiv():
    x = np.array([[7, 8, 9]], np.int32)
    y = np.array([[2, 3, 4]], np.int32)
    _run_spec('elementwise_mod', {'X': x, 'Y': y}, {}, {'Out': x % y})
    _run_spec('elementwise_floordiv', {'X': x, 'Y': y}, {}, {'Out': x // y})


# ---------------------------------------------------------------------------
# reductions / cumsum
# ---------------------------------------------------------------------------
REDUCE = [('reduce_max', np.max), ('reduce_min', np.min),
          ('reduce_prod', np.prod)]


@pytest.mark.parametrize('op,fn', REDUCE, ids=[r[0] for r in REDUCE])
def test_reduce(op, fn):
    x = _x((2, 3, 4), lo=0.5, hi=1.5, seed=5)
    _run_spec(op, {'X': x}, {'dim': [1], 'keep_dim': False},
              {'Out': fn(x, axis=1).astype(np.float32)},
              grads=['X'] if op == 'reduce_prod' else ())


def test_cumsum():
    x = _x((2, 4), seed=6)
    _run_spec('cum_sum', {'X': x}, {'axis': 1},
              {'Out': np.cumsum(x, axis=1)}, grads=['X'])


# ---------------------------------------------------------------------------
# compare / logical
# ---------------------------------------------------------------------------
def test_compare_ops():
    x = np.array([[1.0, 2.0, 3.0]], np.float32)
    y = np.array([[2.0, 2.0, 2.0]], np.float32)
    for op, fn in [('less_than', np.less), ('less_equal', np.less_equal),
                   ('greater_than', np.greater),
                   ('greater_equal', np.greater_equal),
                   ('equal', np.equal), ('not_equal', np.not_equal)]:
        _run_spec(op, {'X': x, 'Y': y}, {}, {'Out': fn(x, y)})


def test_logical_ops():
    x = np.array([True, False, True])
    y = np.array([True, True, False])
    _run_spec('logical_and', {'X': x, 'Y': y}, {}, {'Out': x & y})
    _run_spec('logical_or', {'X': x, 'Y': y}, {}, {'Out': x | y})
    _run_spec('logical_xor', {'X': x, 'Y': y}, {}, {'Out': x ^ y})
    _run_spec('logical_not', {'X': x}, {}, {'Out': ~x})


# ---------------------------------------------------------------------------
# losses
# ---------------------------------------------------------------------------
def test_sigmoid_cross_entropy_with_logits():
    x = _x((3, 4), seed=9)
    lab = np.random.RandomState(1).uniform(0, 1, (3, 4)).astype(np.float32)
    want = np.maximum(x, 0) - x * lab + np.log1p(np.exp(-np.abs(x)))
    _run_spec('sigmoid_cross_entropy_with_logits',
              {'X': x, 'Label': lab}, {}, {'Out': want}, grads=['X'])


def test_square_error_cost():
    x, y = _x((3, 2), seed=2), _x((3, 2), seed=3)
    _run_spec('square_error_cost', {'X': x, 'Y': y}, {},
              {'Out': (x - y) ** 2}, grads=['X'])


def test_huber_loss():
    x, y = _x((4, 1), seed=4), _x((4, 1), seed=5)
    d = 0.5
    r = y - x
    want = np.where(np.abs(r) <= d, 0.5 * r * r, d * (np.abs(r) - 0.5 * d))
    _run_spec('huber_loss', {'X': x, 'Y': y}, {'delta': d},
              {'Out': want.astype(np.float32), 'Residual': None},
              grads=['X'])


def test_log_loss():
    p = _x((4, 1), lo=0.2, hi=0.8, seed=6)
    lab = np.random.RandomState(2).randint(0, 2, (4, 1)).astype(np.float32)
    eps = 1e-4
    want = -lab * np.log(p + eps) - (1 - lab) * np.log(1 - p + eps)
    _run_spec('log_loss', {'Predicted': p, 'Labels': lab},
              {'epsilon': eps}, {'Loss': want}, grads=['Predicted'])


def test_rank_and_margin_rank_loss():
    l = np.array([[1.0], [0.0]], np.float32)
    lt = _x((2, 1), seed=7)
    rt = _x((2, 1), seed=8)
    want = np.log1p(np.exp(lt - rt)) - l * (lt - rt)
    _run_spec('rank_loss', {'Label': l, 'Left': lt, 'Right': rt}, {},
              {'Out': want}, grads=['Left'])
    m = 0.1
    lab2 = np.array([[1.0], [-1.0]], np.float32)
    want2 = np.maximum(0, -lab2 * (lt - rt) + m)
    _run_spec('margin_rank_loss', {'Label': lab2, 'X1': lt, 'X2': rt},
              {'margin': m}, {'Out': want2.astype(np.float32)})


def test_cos_sim():
    x = _x((3, 4), seed=9)
    y = _x((3, 4), seed=10)
    nx = np.linalg.norm(x, axis=1, keepdims=True)
    ny = np.linalg.norm(y, axis=1, keepdims=True)
    want = np.sum(x * y, axis=1, keepdims=True) / (nx * ny)
    _run_spec('cos_sim', {'X': x, 'Y': y}, {},
              {'Out': want.astype(np.float32), 'XNorm': None, 'YNorm': None},
              grads=['X'])


def test_smooth_l1_and_bpr():
    x = _x((3, 4), seed=11)
    y = _x((3, 4), seed=12)
    sigma = 1.0
    d = np.abs(x - y)
    per = np.where(d < 1.0 / sigma ** 2, 0.5 * (sigma * (x - y)) ** 2,
                   d - 0.5 / sigma ** 2)
    _run_spec('smooth_l1_loss', {'X': x, 'Y': y}, {'sigma': sigma},
              {'Out': per.sum(1, keepdims=True).astype(np.float32),
               'Diff': None}, grads=['X'])


# ---------------------------------------------------------------------------
# tensor manipulation
# ---------------------------------------------------------------------------
def test_split_stack_unstack():
    x = _x((2, 6), seed=13)
    _run_spec('split', {'X': x}, {'num': 3, 'axis': 1},
              {'Out': [('s0', x[:, :2]), ('s1', x[:, 2:4]),
                       ('s2', x[:, 4:])]})
    a, b = _x((2, 3), seed=14), _x((2, 3), seed=15)
    _run_spec('stack', {'X': [('a', a), ('b', b)]}, {'axis': 0},
              {'Y': np.stack([a, b])})
    _run_spec('unstack', {'X': np.stack([a, b])}, {'axis': 0, 'num': 2},
              {'Y': [('u0', a), ('u1', b)]})


def test_shape_manip_family():
    x = _x((2, 3, 4), seed=16)
    _run_spec('reshape', {'X': x}, {'shape': [2, 12]},
              {'Out': x.reshape(2, 12)}, grads=['X'])
    _run_spec('squeeze', {'X': x.reshape(2, 1, 3, 4)}, {'axes': [1]},
              {'Out': x.reshape(2, 3, 4)})
    _run_spec('unsqueeze', {'X': x}, {'axes': [1]},
              {'Out': x.reshape(2, 1, 3, 4)})
    _run_spec('flatten', {'X': x}, {'axis': 2},
              {'Out': x.reshape(6, 4)})
    _run_spec('expand', {'X': _x((1, 3), seed=17)},
              {'expand_times': [2, 1]},
              {'Out': np.tile(_x((1, 3), seed=17), (2, 1))})
    _run_spec('reverse', {'X': x}, {'axis': [1]}, {'Out': x[:, ::-1]})
    _run_spec('pad', {'X': _x((2, 2), seed=18)},
              {'paddings': [0, 1, 1, 0], 'pad_value': 0.5},
              {'Out': np.pad(_x((2, 2), seed=18), [(0, 1), (1, 0)],
                             constant_values=0.5)})


def test_gather_scatter_family():
    x = _x((5, 3), seed=19)
    idx = np.array([0, 2, 4], np.int32)
    _run_spec('gather', {'X': x, 'Index': idx}, {}, {'Out': x[idx]},
              grads=['X'])
    nd_idx = np.array([[0, 1], [2, 0]], np.int32)
    _run_spec('gather_nd', {'X': x, 'Index': nd_idx}, {},
              {'Out': x[nd_idx[:, 0], nd_idx[:, 1]]})
    upd = _x((2, 3), seed=20)
    want = x.copy()
    want[np.array([1, 3])] = upd
    _run_spec('scatter', {'X': x, 'Ids': np.array([1, 3], np.int32),
                          'Updates': upd}, {'overwrite': True},
              {'Out': want})


def test_slice_family():
    x = _x((3, 4, 5), seed=21)
    _run_spec('slice', {'Input': x},
              {'axes': [1, 2], 'starts': [1, 0], 'ends': [3, 4]},
              {'Out': x[:, 1:3, 0:4]}, grads=['Input'])
    _run_spec('strided_slice', {'Input': x},
              {'axes': [1], 'starts': [0], 'ends': [4], 'strides': [2]},
              {'Out': x[:, 0:4:2]})
    _run_spec('crop', {'X': x}, {'offsets': [0, 1, 1], 'shape': [3, 2, 3]},
              {'Out': x[:, 1:3, 1:4]})


def test_index_selection_family():
    x = _x((2, 5), seed=22)
    _run_spec('top_k', {'X': x}, {'k': 2},
              {'Out': np.sort(x, axis=1)[:, ::-1][:, :2],
               'Indices': np.argsort(-x, axis=1)[:, :2]})
    _run_spec('arg_max', {'X': x}, {'axis': 1},
              {'Out': np.argmax(x, 1)})
    _run_spec('arg_min', {'X': x}, {'axis': 1},
              {'Out': np.argmin(x, 1)})
    _run_spec('argsort', {'X': x}, {'axis': 1},
              {'Out': np.sort(x, 1), 'Indices': np.argsort(x, 1)})
    _run_spec('one_hot', {'X': np.array([[1], [3]], np.int64)},
              {'depth': 4}, {'Out': np.eye(4, dtype=np.float32)[[1, 3]]})
    a, b = _x((2, 3), seed=23), _x((2, 3), seed=24)
    ids = np.array([[0], [1]], np.int32)
    _run_spec('multiplex', {'X': [('m0', a), ('m1', b)], 'Ids': ids}, {},
              {'Out': np.stack([a[0], b[1]])})


def test_norm_family():
    x = _x((2, 6), lo=0.2, hi=1.2, seed=25)
    _run_spec('l2_normalize', {'X': x}, {'axis': 1, 'epsilon': 1e-10},
              {'Out': x / np.linalg.norm(x, axis=1, keepdims=True),
               'Norm': None}, grads=['X'])
    _run_spec('norm', {'X': x}, {'axis': 1, 'epsilon': 1e-10},
              {'Out': x / np.linalg.norm(x, axis=1, keepdims=True),
               'Norm': None})
    _run_spec('squared_l2_norm', {'X': x}, {},
              {'Out': np.array([np.sum(x * x)], np.float32)})
    _run_spec('clip_by_norm', {'X': x}, {'max_norm': 0.5},
              {'Out': x * (0.5 / max(np.linalg.norm(x), 0.5))})


def test_affine_label_smooth_lrn():
    x = _x((2, 3, 2, 2), seed=26)
    s = _x((3,), lo=0.5, hi=1.5, seed=27)
    b = _x((3,), seed=28)
    _run_spec('affine_channel', {'X': x, 'Scale': s, 'Bias': b},
              {'data_layout': 'NCHW'},
              {'Out': x * s.reshape(1, 3, 1, 1) + b.reshape(1, 3, 1, 1)},
              grads=['X'])
    lab = np.eye(4, dtype=np.float32)[[0, 2]]
    eps = 0.1
    _run_spec('label_smooth', {'X': lab}, {'epsilon': eps},
              {'Out': lab * (1 - eps) + eps / 4})


def test_space_depth_shuffle_pixel():
    x = np.arange(16, dtype=np.float32).reshape(1, 1, 4, 4)
    o, = _forward_only('space_to_depth', {'X': x}, {'blocksize': 2})
    assert o.shape == (1, 4, 2, 2)
    x2 = np.arange(8, dtype=np.float32).reshape(1, 4, 1, 2)
    o2, = _forward_only('shuffle_channel', {'X': x2}, {'group': 2})
    assert o2.shape == x2.shape
    np.testing.assert_allclose(o2[0, :, 0, 0], x2[0, [0, 2, 1, 3], 0, 0])
    x3 = np.arange(8, dtype=np.float32).reshape(1, 4, 1, 2)
    o3, = _forward_only('pixel_shuffle', {'X': x3}, {'upscale_factor': 2})
    assert o3.shape == (1, 1, 2, 4)


def _forward_only(op, ins, attrs, outs=('Out',)):
    import paddle_tpu as fluid
    t = OpTest()
    t.op_type = op
    t.inputs = ins
    t.attrs = attrs
    t.outputs = {o: None for o in outs}
    main, startup, feed, out_names, _ = t._build()
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.core.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
        fetch = [n for names in out_names.values() for n in names]
        return exe.run(program=main, feed=feed, fetch_list=fetch)


# ---------------------------------------------------------------------------
# conv / pool variants beyond the existing conv2d/pool2d tests
# ---------------------------------------------------------------------------
def test_conv2d_transpose_matches_numpy():
    x = _x((1, 2, 3, 3), seed=29)
    w = _x((2, 2, 2, 2), seed=30)  # [C_in, C_out, kh, kw]
    o, = _forward_only('conv2d_transpose', {'Input': x, 'Filter': w},
                       {'strides': [1, 1], 'paddings': [0, 0],
                        'dilations': [1, 1], 'groups': 1},
                       outs=('Output',))
    # numpy reference: scatter-accumulate each input pixel * kernel
    want = np.zeros((1, 2, 4, 4), np.float32)
    for ci in range(2):
        for co in range(2):
            for i in range(3):
                for j in range(3):
                    want[0, co, i:i + 2, j:j + 2] += x[0, ci, i, j] * \
                        w[ci, co]
    np.testing.assert_allclose(o, want, rtol=1e-4, atol=1e-5)


def test_depthwise_and_conv3d_shapes():
    x = _x((1, 2, 4, 4), seed=31)
    w = _x((2, 1, 3, 3), seed=32)
    o, = _forward_only('depthwise_conv2d', {'Input': x, 'Filter': w},
                       {'strides': [1, 1], 'paddings': [1, 1],
                        'dilations': [1, 1], 'groups': 2},
                       outs=('Output',))
    assert o.shape == (1, 2, 4, 4)
    x3 = _x((1, 1, 3, 4, 4), seed=33)
    w3 = _x((2, 1, 2, 2, 2), seed=34)
    o3, = _forward_only('conv3d', {'Input': x3, 'Filter': w3},
                        {'strides': [1, 1, 1], 'paddings': [0, 0, 0],
                         'dilations': [1, 1, 1], 'groups': 1},
                        outs=('Output',))
    assert o3.shape == (1, 2, 2, 3, 3)


def test_pool3d_and_adaptive():
    x = _x((1, 1, 4, 4, 4), seed=35)
    o, = _forward_only('pool3d', {'X': x},
                       {'pooling_type': 'max', 'ksize': [2, 2, 2],
                        'strides': [2, 2, 2], 'paddings': [0, 0, 0]})
    want = x.reshape(1, 1, 2, 2, 2, 2, 2, 2).max(axis=(3, 5, 7))
    np.testing.assert_allclose(o, want, rtol=1e-6)


def test_group_norm_values():
    x = _x((2, 4, 2, 2), seed=36)
    g = 2
    xg = x.reshape(2, g, -1)
    m = xg.mean(-1, keepdims=True)
    v = xg.var(-1, keepdims=True)
    want = ((xg - m) / np.sqrt(v + 1e-5)).reshape(x.shape)
    _run_spec('group_norm', {'X': x, 'Scale': np.ones(4, np.float32),
                             'Bias': np.zeros(4, np.float32)},
              {'groups': g, 'epsilon': 1e-5},
              {'Y': want.astype(np.float32), 'Mean': None,
               'Variance': None}, atol=1e-4, rtol=1e-4)


def test_lrn_shape_and_grad():
    x = _x((1, 4, 3, 3), lo=0.2, hi=1.0, seed=37)
    o, = _forward_only('lrn', {'X': x},
                       {'n': 3, 'alpha': 1e-4, 'beta': 0.75, 'k': 1.0})
    assert o.shape == x.shape
    assert np.isfinite(np.asarray(o)).all()


def test_maxout():
    x = _x((1, 4, 2, 2), seed=38)
    want = x.reshape(1, 2, 2, 2, 2).max(axis=2)
    _run_spec('maxout', {'X': x}, {'groups': 2}, {'Out': want})


def test_bilinear_tensor_product():
    x = _x((2, 3), seed=39)
    y = _x((2, 4), seed=40)
    w = _x((2, 3, 4), seed=41)
    want = np.einsum('bi,oij,bj->bo', x, w, y)
    _run_spec('bilinear_tensor_product',
              {'X': x, 'Y': y, 'Weight': w}, {},
              {'Out': want.astype(np.float32)}, grads=['X'],
              atol=1e-4, rtol=1e-4)


def test_interp_ops():
    x = np.arange(4, dtype=np.float32).reshape(1, 1, 2, 2)
    o, = _forward_only('nearest_interp', {'X': x},
                       {'out_h': 4, 'out_w': 4,
                        'interp_method': 'nearest'})
    assert o.shape == (1, 1, 4, 4)
    o2, = _forward_only('bilinear_interp', {'X': x},
                        {'out_h': 4, 'out_w': 4,
                         'interp_method': 'bilinear'})
    assert o2.shape == (1, 1, 4, 4)
    assert np.isfinite(np.asarray(o2)).all()


def test_misc_metric_ops():
    x = _x((4, 3), seed=42)
    _run_spec('mean', {'X': x}, {},
              {'Out': np.array([x.mean()], np.float32)}, grads=['X'])
    a, b = _x((2, 3), seed=43), _x((2, 3), seed=44)
    _run_spec('sum', {'X': [('sa', a), ('sb', b)]}, {}, {'Out': a + b})
    _run_spec('increment', {'X': np.array([1.5], np.float32)},
              {'step': 2.0}, {'Out': np.array([3.5], np.float32)})
    _run_spec('isfinite', {'X': np.array([1.0, np.inf, np.nan],
                                         np.float32)}, {},
              {'Out': np.array([False], bool)})


def test_bpr_loss():
    x = _x((3, 4), lo=-2, hi=2, seed=45)
    lab = np.array([[0], [2], [1]], np.int64)
    # bpr: -mean over j != y of log(sigmoid(x_y - x_j))
    want = []
    for i in range(3):
        y = lab[i, 0]
        others = [j for j in range(4) if j != y]
        want.append(-np.mean([np.log(_sig(x[i, y] - x[i, j]))
                              for j in others]))
    _run_spec('bpr_loss', {'X': x, 'Label': lab}, {},
              {'Y': np.asarray(want, np.float32).reshape(-1, 1)},
              atol=1e-4, rtol=1e-4)
