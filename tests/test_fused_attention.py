"""fused_multihead_attention op: parity with the naive composition and
gradient flow (flash kernel on TPU, naive fallback elsewhere — on the CPU
test platform both paths are the same math, so this checks the op wiring,
shapes and grads)."""
import numpy as np
import pytest

import paddle_tpu as fluid


def test_fused_attention_matches_naive_and_has_grads():
    B, H, S, D = 2, 2, 8, 4
    q = fluid.layers.data(name='q', shape=[H, S, D], dtype='float32')
    k = fluid.layers.data(name='k', shape=[H, S, D], dtype='float32')
    v = fluid.layers.data(name='v', shape=[H, S, D], dtype='float32')
    for var in (q, k, v):
        var.stop_gradient = False
    fused = fluid.layers.fused_multihead_attention(q, k, v, causal=True,
                                                   scale=0.5)
    loss = fluid.layers.reduce_sum(fused)
    fluid.append_backward(loss)

    rng = np.random.RandomState(0)
    qv = rng.randn(B, H, S, D).astype(np.float32)
    kv = rng.randn(B, H, S, D).astype(np.float32)
    vv = rng.randn(B, H, S, D).astype(np.float32)
    exe = fluid.Executor(fluid.CPUPlace())
    out, gq = exe.run(feed={'q': qv, 'k': kv, 'v': vv},
                      fetch_list=[fused, 'q@GRAD'])

    # numpy reference: causal softmax attention
    s = np.einsum('bhqd,bhkd->bhqk', qv * 0.5, kv)
    mask = np.tril(np.ones((S, S), bool))
    s = np.where(mask, s, -1e30)
    e = np.exp(s - s.max(-1, keepdims=True))
    p = e / e.sum(-1, keepdims=True)
    want = np.einsum('bhqk,bhkd->bhqd', p, vv)
    np.testing.assert_allclose(np.asarray(out), want, rtol=1e-4, atol=1e-5)
    assert np.asarray(gq).shape == (B, H, S, D)
    assert np.abs(np.asarray(gq)).sum() > 0
