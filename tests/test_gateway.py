"""HTTP serving gateway (ISSUE 19): codec round trips, SSE streaming
byte-identity vs a direct DecodingPredictor, multi-tenant admission
(API keys, token-bucket 429s, inflight quotas), the full error-code
contract (never a silent drop), deadline propagation shed at all three
sites (gateway door / router queue / mid-decode), graceful drain,
Prometheus /metrics validity, the profiler gateway table, and the
gateway_ctl CLI.

The acceptance scenario rides a 2-replica decode fleet: a 64-request
mixed-tenant Poisson run where every request resolves to an HTTP
status and the per-tenant ledgers reconcile with the fleet's
served/shed totals.
"""
import json
import os
import re
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request
import warnings

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import profiler
from paddle_tpu.inference import (BatchingPredictor, Config,
                                  DecodingPredictor, FleetRouter,
                                  Gateway, TenantConfig,
                                  create_predictor, export_compiled,
                                  export_decode, render_metrics,
                                  tenants_from_json)
from paddle_tpu.inference import gateway as gateway_mod

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
DIM = 8
VOCAB = 61


@pytest.fixture(scope='module')
def dense_art(tmp_path_factory):
    tmp = str(tmp_path_factory.mktemp('gw_dense'))
    with fluid.scope_guard(fluid.core.Scope()), fluid.unique_name.guard():
        main, startup = fluid.Program(), fluid.Program()
        main.random_seed = startup.random_seed = 7
        with fluid.program_guard(main, startup):
            img = fluid.layers.data(name='img', shape=[DIM],
                                    dtype='float32')
            h = fluid.layers.fc(img, 32, act='relu')
            out = fluid.layers.fc(h, 4, act='softmax')
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        model_dir = os.path.join(tmp, 'model')
        fluid.io.save_inference_model(model_dir, ['img'], [out], exe,
                                      main)
        pred = create_predictor(Config(model_dir))
        x0 = np.random.RandomState(3).randn(8, DIM).astype(np.float32)
        art = os.path.join(tmp, 'art')
        export_compiled(pred, [x0], art, batch_sizes=[8])
    return {'art': art, 'pred': pred}


@pytest.fixture(scope='module')
def decode_art(tmp_path_factory):
    tmp = str(tmp_path_factory.mktemp('gw_decode'))
    art = os.path.join(tmp, 'decode')
    from models.transformer import build_decode_spec
    scope = fluid.core.Scope()
    with fluid.scope_guard(scope), fluid.unique_name.guard():
        spec = build_decode_spec(vocab=VOCAB, d_model=8, n_head=2,
                                 n_layer=1, d_ff=16, max_slots=4,
                                 max_cache_len=40, prompt_buckets=(4,),
                                 eos_id=1)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(spec['startup'])
        export_decode(spec, art, scope=scope)
    return art


@pytest.fixture(scope='module')
def direct_pred(decode_art):
    with DecodingPredictor(decode_art, platform='cpu') as pred:
        pred.warmup()
        yield pred


@pytest.fixture(scope='module')
def decode_fleet(decode_art):
    """One 2-replica decode fleet shared by the fleet-backed tests."""
    with warnings.catch_warnings():
        warnings.simplefilter('ignore')
        router = FleetRouter(decode_art, replicas=2, platform='cpu',
                             inflight_per_replica=4)
        router.hb_timeout_s = 60.0  # busy-CI != hung (test_fleet idiom)
        yield router
        router.close()


def _prompts(n, seed=0):
    rng = np.random.RandomState(seed)
    return [rng.randint(2, VOCAB, rng.randint(2, 5)) for _ in range(n)]


def _req(url, path, body=None, key=None, rid=None, method=None):
    """One HTTP round trip -> (status, headers, parsed-or-raw body).
    HTTP errors come back as a status, never an exception: the tests
    assert the full error-code contract."""
    data = json.dumps(body).encode() if body is not None else None
    r = urllib.request.Request(
        url + path, data=data,
        method=method or ('POST' if body is not None else 'GET'))
    if body is not None:
        r.add_header('Content-Type', 'application/json')
    if key:
        r.add_header('X-API-Key', key)
    if rid:
        r.add_header('X-Request-Id', rid)
    try:
        with urllib.request.urlopen(r, timeout=120) as resp:
            raw = resp.read().decode('utf-8')
            ctype = resp.headers.get('Content-Type', '')
            hdrs = dict(resp.headers)
            return resp.status, hdrs, (json.loads(raw)
                                       if 'json' in ctype else raw)
    except urllib.error.HTTPError as e:
        raw = e.read().decode('utf-8')
        try:
            parsed = json.loads(raw)
        except ValueError:
            parsed = raw
        return e.code, dict(e.headers), parsed


def _sse_events(raw):
    """Parse one SSE response body -> [(event-or-None, data dict)]."""
    out = []
    for block in raw.strip().split('\n\n'):
        ev, data = None, None
        for line in block.split('\n'):
            if line.startswith('event: '):
                ev = line[len('event: '):]
            elif line.startswith('data: '):
                data = json.loads(line[len('data: '):])
        out.append((ev, data))
    return out


def _sse_tokens(raw):
    evs = _sse_events(raw)
    toks = [t for ev, d in evs if ev is None and d and 'toks' in d
            for t in d['toks']]
    done = [d for ev, d in evs if ev == 'done']
    errs = [d for ev, d in evs if ev == 'error']
    return toks, (done[0] if done else None), (errs[0] if errs else None)


# -- codec units -------------------------------------------------------------

def test_npz_codec_roundtrip():
    arrays = {'a': np.arange(12, dtype=np.float32).reshape(3, 4),
              'b': np.array([1, 2, 3], np.int64)}
    got = gateway_mod.decode_arrays(gateway_mod.encode_arrays(arrays))
    for k in arrays:
        np.testing.assert_array_equal(got[k], arrays[k])


def test_feeds_from_arrays_lod_convention():
    feeds = gateway_mod._feeds_from_arrays({
        'w': np.arange(5, dtype=np.float32),
        'w.lod0': np.array([0, 2, 5], np.int32),
        'x': np.ones(3, np.float32)})
    data, offs = feeds['w']
    np.testing.assert_array_equal(offs[0], [0, 2, 5])
    assert isinstance(feeds['x'], np.ndarray)
    with pytest.raises(ValueError):
        gateway_mod._feeds_from_arrays(
            {'q.lod0': np.array([0, 1], np.int32)})


def test_status_mapping():
    from paddle_tpu.inference import (DeadlineExceeded, ReplicaFailed,
                                      ServerOverloaded,
                                      FleetUnavailable)
    assert gateway_mod.status_for(DeadlineExceeded('x')) == 504
    assert gateway_mod.status_for(ReplicaFailed('x')) == 502
    assert gateway_mod.status_for(ServerOverloaded('x')) == 503
    assert gateway_mod.status_for(FleetUnavailable('x')) == 503
    assert gateway_mod.status_for(ValueError('x')) == 400
    assert gateway_mod.status_for(TimeoutError('x')) == 504
    assert gateway_mod.status_for(RuntimeError('x')) == 500


def test_token_bucket_and_tenants_json(tmp_path):
    t = TenantConfig('t', rate=2.0, burst=2)
    ok1, _ = t.acquire()
    ok2, _ = t.acquire()
    ok3, retry = t.acquire()
    assert ok1 and ok2 and not ok3 and retry > 0
    cfg = {'key-a': {'tenant': 'alpha', 'rate': 5, 'admin': True},
           'key-b': {'max_inflight': 3}}
    path = tmp_path / 'tenants.json'
    path.write_text(json.dumps(cfg))
    tenants = tenants_from_json(str(path))
    assert tenants['key-a'].name == 'alpha' and tenants['key-a'].admin
    assert tenants['key-b'].max_inflight == 3
    assert tenants['key-b'].rate is None


# -- Prometheus text exposition ----------------------------------------------

_PROM_METRIC = re.compile(
    r'^[a-zA-Z_:][a-zA-Z0-9_:]*'            # metric name
    r'(\{([a-zA-Z_][a-zA-Z0-9_]*="[^"]*")'   # first label
    r'(,[a-zA-Z_][a-zA-Z0-9_]*="[^"]*")*\})?'  # more labels
    r' [-+]?[0-9.eE+-]+$')                   # value
_PROM_COMMENT = re.compile(r'^# (HELP|TYPE) [a-zA-Z_:][a-zA-Z0-9_:]* .+$')


def _assert_prometheus_valid(text):
    assert text.endswith('\n')
    seen = 0
    for line in text.splitlines():
        if not line:
            continue
        if line.startswith('#'):
            assert _PROM_COMMENT.match(line), line
        else:
            assert _PROM_METRIC.match(line), line
            seen += 1
    assert seen > 0


def test_render_metrics_is_valid_prometheus(direct_pred):
    gw = Gateway(direct_pred)
    try:
        snap = gw.snapshot()
        text = render_metrics(snap, snap.get('backend'))
        _assert_prometheus_valid(text)
        assert 'ptpu_gateway_inflight' in text
        assert 'ptpu_decode_' in text  # backend counters flattened
    finally:
        gw.close()


# -- HTTP over a direct DecodingPredictor ------------------------------------

def test_sse_stream_byte_identical_to_direct(direct_pred):
    """The tentpole acceptance bar: an SSE decode stream served over
    HTTP carries exactly the transcript a direct DecodingPredictor
    produces — token-for-token and in the done event."""
    prompt = _prompts(4, seed=11)[0]
    want = [int(t) for t in
            direct_pred.submit(prompt, max_new_tokens=10).result(120)]
    with Gateway(direct_pred) as gw:
        code, hdrs, raw = _req(gw.url, '/v1/decode',
                               {'prompt': [int(p) for p in prompt],
                                'max_new_tokens': 10}, rid='sse-1')
        assert code == 200
        assert hdrs.get('X-Request-Id') == 'sse-1'
        toks, done, err = _sse_tokens(raw)
        assert err is None
        assert toks == want
        assert done['tokens'] == want
        assert done['request_id'] == 'sse-1'
        snap = gw.snapshot()
        assert snap['streams'] == 1 and snap['ok'] == 1
        assert snap['ttft_p99_ms'] > 0.0


def test_nonstream_and_beam_decode(direct_pred):
    prompt = _prompts(4, seed=12)[0]
    want = [int(t) for t in
            direct_pred.submit(prompt, max_new_tokens=6).result(120)]
    ids, scores = direct_pred.submit(prompt, max_new_tokens=6,
                                     beam=2).result(120)
    with Gateway(direct_pred) as gw:
        code, _, body = _req(gw.url, '/v1/decode',
                             {'prompt': [int(p) for p in prompt],
                              'max_new_tokens': 6, 'stream': False})
        assert code == 200 and body['tokens'] == want
        code, _, body = _req(gw.url, '/v1/decode',
                             {'prompt': [int(p) for p in prompt],
                              'max_new_tokens': 6, 'beam': 2})
        assert code == 200
        assert body['ids'] == np.asarray(ids).tolist()


def test_bad_requests_400_and_404(direct_pred):
    with Gateway(direct_pred) as gw:
        code, _, body = _req(gw.url, '/v1/decode', {})
        assert code == 400 and body['etype'] == 'ValueError'
        code, _, body = _req(gw.url, '/v1/decode', {'prompt': []})
        assert code == 400
        code, _, body = _req(gw.url, '/v1/infer', {'prompt': [1]})
        assert code == 400  # decode artifact behind /v1/infer
        code, _, _ = _req(gw.url, '/no/such/route')
        assert code == 404
        snap = gw.snapshot()
        assert snap['bad'] == 3


def test_auth_rate_limit_and_quota(direct_pred):
    tenants = {
        'k-fast': TenantConfig('fast', admin=True),
        'k-slow': TenantConfig('slow', rate=0.001, burst=1),
        'k-zero': TenantConfig('zero', max_inflight=0),
    }
    prompt = [5, 7]
    with Gateway(direct_pred, tenants=tenants) as gw:
        # no key / unknown key -> 401, never reaches the backend
        code, _, body = _req(gw.url, '/v1/decode', {'prompt': prompt})
        assert code == 401 and body['etype'] == 'Unauthorized'
        code, _, _ = _req(gw.url, '/v1/decode', {'prompt': prompt},
                          key='k-wrong')
        assert code == 401
        # token bucket: burst of 1 admits one, then 429 + Retry-After
        code, _, _ = _req(gw.url, '/v1/decode',
                          {'prompt': prompt, 'max_new_tokens': 2,
                           'stream': False}, key='k-slow')
        assert code == 200
        code, hdrs, body = _req(gw.url, '/v1/decode',
                                {'prompt': prompt}, key='k-slow',
                                rid='rl-1')
        assert code == 429
        assert int(hdrs.get('Retry-After')) >= 1
        assert 'rl-1' in body['error']
        # per-tenant inflight quota
        code, hdrs, _ = _req(gw.url, '/v1/decode', {'prompt': prompt},
                             key='k-zero')
        assert code == 429 and 'Retry-After' in hdrs
        # admin gating on /admin/drain
        code, _, _ = _req(gw.url, '/admin/drain', {}, key='k-slow')
        assert code == 403
        snap = gw.snapshot()
        assert snap['tenants']['slow']['rate_limited'] == 1
        assert snap['tenants']['zero']['quota'] == 1
        assert snap['rate_limited'] == 1 and snap['quota'] == 1


def test_dense_infer_roundtrip(dense_art):
    x = np.random.RandomState(5).randn(8, DIM).astype(np.float32)
    want, = dense_art['pred'].run([x])
    with BatchingPredictor(dense_art['art'], platform='cpu') as pred:
        pred.warmup()
        with Gateway(pred) as gw:
            code, _, body = _req(
                gw.url, '/v1/infer',
                {'npz': gateway_mod.encode_arrays({'img': x})})
            assert code == 200
            outs = gateway_mod.decode_arrays(body['npz'])
            np.testing.assert_array_equal(outs['o0'], want)
            # decode route on a dense artifact: 400, not a crash
            code, _, _ = _req(gw.url, '/v1/decode', {'prompt': [1, 2]})
            assert code == 400


def test_graceful_drain_and_healthz(direct_pred):
    with Gateway(direct_pred) as gw:
        code, _, body = _req(gw.url, '/healthz')
        assert code == 200 and body['ok']
        # admin drain flips healthz and sheds new data requests 503
        code, _, body = _req(gw.url, '/admin/drain', {})
        assert code == 202 and body['draining']
        assert gw.drain_requested.is_set()
        code, hdrs, body = _req(gw.url, '/v1/decode',
                                {'prompt': [5, 7]})
        assert code == 503 and 'draining' in body['error']
        assert 'Retry-After' in hdrs
        code, _, body = _req(gw.url, '/healthz')
        assert code == 503 and body['draining']
        assert gw.drain(timeout=10) is True


def test_profiler_gateway_report(direct_pred, capsys):
    with Gateway(direct_pred) as gw:
        _req(gw.url, '/v1/decode', {'prompt': [5, 7],
                                    'max_new_tokens': 2,
                                    'stream': False})
        sources = list(profiler._gateway_sources)
        assert any(s.startswith('gateway:') for s in sources)
        out = profiler.gateway_report()
        printed = capsys.readouterr().out
        assert 'Gateway source' in printed and 'tenant' in printed
        name = [s for s in sources if s.startswith('gateway:')][-1]
        assert out[name]['ok'] >= 1
    # close() unregisters: a dead gateway never haunts the report
    assert name not in profiler._gateway_sources


def test_gateway_ctl_cli(direct_pred):
    ctl = [sys.executable, os.path.join(REPO, 'tools',
                                        'gateway_ctl.py')]
    with Gateway(direct_pred) as gw:
        r = subprocess.run(ctl + ['status', gw.url, '--json'],
                           capture_output=True, text=True, timeout=60)
        assert r.returncode == 0, r.stderr
        js = json.loads(r.stdout)
        assert js['healthy'] and js['stats']['kind'] == 'gateway'
        r = subprocess.run(ctl + ['drain', gw.url, '--timeout', '30'],
                           capture_output=True, text=True, timeout=60)
        assert r.returncode == 0, r.stderr
        assert gw.drain_requested.is_set()
    # unreachable -> 1; usage -> 2
    r = subprocess.run(ctl + ['status', 'http://127.0.0.1:9'],
                       capture_output=True, timeout=60)
    assert r.returncode == 1
    r = subprocess.run(ctl + ['bogus'], capture_output=True,
                       timeout=60)
    assert r.returncode == 2


# -- deadline propagation: all three shed sites over HTTP (satellite) --------

def test_deadline_sheds_at_gateway_door(direct_pred):
    """Site 1: budget already spent when the gateway reads the body —
    504 before the backend ever sees the request."""
    with Gateway(direct_pred) as gw:
        before = direct_pred.stats.snapshot()['expired']
        code, _, body = _req(gw.url, '/v1/decode',
                             {'prompt': [5, 7], 'deadline_ms': 0},
                             rid='door-1')
        assert code == 504
        assert 'gateway door' in body['error']
        assert body['request_id'] == 'door-1'
        snap = gw.snapshot()
        assert snap['expired'] == 1
        # the backend never saw it
        assert direct_pred.stats.snapshot()['expired'] == before


def test_deadline_expires_mid_decode_slot_freed(direct_pred):
    """Site 3: the budget survives admission + first tokens but not the
    full decode — DeadlineExceeded names the mid-decode site and the
    request id, the slot frees, the expired counter increments, and
    follow-up traffic is unaffected."""
    prompt = _prompts(4, seed=13)[0]
    t0 = time.perf_counter()
    want = [int(t) for t in
            direct_pred.submit(prompt, max_new_tokens=30).result(300)]
    full_ms = (time.perf_counter() - t0) * 1e3
    before = direct_pred.stats.snapshot()['expired']
    with Gateway(direct_pred) as gw:
        code, _, raw = _req(gw.url, '/v1/decode',
                            {'prompt': [int(p) for p in prompt],
                             'max_new_tokens': 30,
                             'deadline_ms': full_ms * 0.4},
                            rid='mid-1')
        toks, done, err = _sse_tokens(raw)
        assert done is None
        assert err is not None and err['code'] == 504
        assert 'mid-decode' in err['error']
        assert '(request mid-1)' in err['error']
        assert err['request_id'] == 'mid-1'
        assert direct_pred.stats.snapshot()['expired'] == before + 1
        # recent_failures carries the trace id (satellite 3)
        fails = direct_pred.stats.snapshot()['recent_failures']
        assert any(f['request_id'] == 'mid-1' for f in fails)
        assert gw.snapshot()['expired'] == 1
        # slot freed: the same decode completes afterwards
        code, _, body = _req(gw.url, '/v1/decode',
                             {'prompt': [int(p) for p in prompt],
                              'max_new_tokens': 30, 'stream': False})
        assert code == 200 and body['tokens'] == want


def test_deadline_expires_in_router_queue(decode_art):
    """Site 2: the budget outlives the gateway door but dies in the
    FleetRouter's pending queue behind a saturated replica — 504 naming
    the router-queue site and the request id, router expired counter
    incremented, and the slot reuse proven by a follow-up request."""
    import signal
    with warnings.catch_warnings():
        warnings.simplefilter('ignore')
        with FleetRouter(decode_art, replicas=1, platform='cpu',
                         inflight_per_replica=1) as router:
            router.hb_timeout_s = 60.0  # paused != hung for this test
            with Gateway(router) as gw:
                # prove the replica serves, then pause it: the next
                # dispatch occupies the single frame slot forever and
                # the victim behind it can only die in the router queue
                code, _, _ = _req(gw.url, '/v1/decode',
                                  {'prompt': [5, 7],
                                   'max_new_tokens': 2,
                                   'stream': False})
                assert code == 200
                rid_ = router.serving_replicas()[0]
                pid = router._replicas[rid_].proc.pid
                os.kill(pid, signal.SIGSTOP)
                try:
                    hog = router.submit(_prompts(1, seed=14)[0],
                                        max_new_tokens=8)
                    code, _, body = _req(
                        gw.url, '/v1/decode',
                        {'prompt': [5, 7], 'max_new_tokens': 2,
                         'stream': False, 'deadline_ms': 250},
                        rid='rq-1')
                finally:
                    os.kill(pid, signal.SIGCONT)
                assert code == 504, body
                assert 'router queue' in body['error']
                assert '(request rq-1)' in body['error']
                assert router.stats.snapshot()['expired'] >= 1
                assert gw.snapshot()['expired'] == 1
                hog.result(600)
                # queue healthy again: the same request now serves
                code, _, body = _req(
                    gw.url, '/v1/decode',
                    {'prompt': [5, 7], 'max_new_tokens': 2,
                     'stream': False})
                assert code == 200


# -- fleet-backed serving ----------------------------------------------------

def test_fleet_sse_byte_identical_and_request_id(decode_fleet,
                                                 direct_pred):
    """SSE over the 2-replica fleet matches the direct predictor
    token-for-token, and the request id rides the wire frames into the
    replica (the fleet stats event log sees tagged failures; here the
    happy path just round-trips)."""
    prompts = _prompts(6, seed=21)
    with Gateway(decode_fleet) as gw:
        for i, p in enumerate(prompts):
            want = [int(t) for t in direct_pred.submit(
                p, max_new_tokens=8).result(300)]
            code, _, raw = _req(gw.url, '/v1/decode',
                                {'prompt': [int(t) for t in p],
                                 'max_new_tokens': 8},
                                rid='fleet-%d' % i)
            assert code == 200
            toks, done, err = _sse_tokens(raw)
            assert err is None
            assert toks == want and done['tokens'] == want
        assert gw.snapshot()['streams'] == len(prompts)


def test_poisson_mixed_tenant_zero_silent_drops(decode_fleet):
    """The acceptance scenario: 64 concurrent mixed-tenant requests in
    a Poisson arrival pattern over the 2-replica fleet. EVERY request
    resolves to one of 200/400/429/502/503/504 (no silent drops, no
    transport errors), and the gateway's per-tenant ledgers reconcile:
    codes sum to the request count, admitted = requests - door
    rejections, and every 200 maps onto a fleet completion."""
    N = 64
    tenants = {
        'k-alpha': TenantConfig('alpha'),
        'k-beta': TenantConfig('beta', rate=20.0, burst=4),
        'k-gamma': TenantConfig('gamma', max_inflight=2),
    }
    keys = ['k-alpha', 'k-beta', 'k-gamma']
    rng = np.random.RandomState(77)
    prompts = _prompts(N, seed=22)
    fleet_before = decode_fleet.stats.snapshot()
    results = [None] * N
    with Gateway(decode_fleet, tenants=tenants) as gw:
        def one(i):
            body = {'prompt': [int(t) for t in prompts[i]],
                    'max_new_tokens': int(rng.randint(2, 6)),
                    'stream': False}
            if i % 16 == 7:
                body['deadline_ms'] = 0  # deterministic door 504s
            code, _, _ = _req(gw.url, '/v1/decode', body,
                              key=keys[i % 3], rid='poisson-%d' % i)
            results[i] = code

        threads = []
        for i in range(N):
            t = threading.Thread(target=one, args=(i,), daemon=True)
            threads.append(t)
            t.start()
            time.sleep(float(rng.exponential(0.01)))
        for t in threads:
            t.join(300)
        assert all(not t.is_alive() for t in threads)
        snap = gw.snapshot()
    # zero silent drops: every request produced a terminal status
    allowed = {200, 400, 429, 502, 503, 504}
    assert None not in results
    assert set(results) <= allowed, sorted(set(results))
    n_ok = sum(1 for c in results if c == 200)
    assert n_ok >= N // 2  # the fleet actually served the bulk
    assert sum(1 for c in results if c == 504) >= 1  # forced door sheds
    # ledger reconciliation, per tenant and in total
    assert snap['requests'] == N
    for t in snap['tenants'].values():
        assert sum(t['codes'].values()) == t['requests']
        assert (t['ok'] + t['bad'] + t['rate_limited'] + t['quota']
                + t['shed'] + t['expired'] + t['failed']
                ) == t['requests']
    assert snap['ok'] == n_ok
    assert snap['inflight'] == 0
    # fleet-side reconciliation: door rejections never reached the
    # fleet; every gateway 200 is a fleet completion
    fleet_after = decode_fleet.stats.snapshot()
    door_rejected = (snap['rate_limited'] + snap['quota']
                     + snap['expired'] + snap['bad'])
    submitted = fleet_after['submitted'] - fleet_before['submitted']
    completed = fleet_after['completed'] - fleet_before['completed']
    assert submitted == N - door_rejected
    assert completed == n_ok


def test_fleet_metrics_endpoint_valid(decode_fleet):
    with Gateway(decode_fleet) as gw:
        _req(gw.url, '/v1/decode', {'prompt': [5, 7],
                                    'max_new_tokens': 2,
                                    'stream': False})
        code, hdrs, text = _req(gw.url, '/metrics')
        assert code == 200
        assert hdrs.get('Content-Type', '').startswith('text/plain')
        _assert_prometheus_valid(text)
        assert 'ptpu_gateway_requests_total' in text
        assert 'ptpu_fleet_' in text
        assert 'ptpu_fleet_replica_' in text
