"""Real-chip smoke tier (VERDICT r3 item 8): one subprocess drives every
axon-specific behavior on the actual TPU (tests/tpu_smoke_worker.py); each
check surfaces as its own @pytest.mark.tpu test here.

Opt-in: set PTPU_RUN_TPU_TESTS=1 (scripts/ci.sh does when a TPU is
visible). The default suite stays on the deterministic virtual-CPU mesh so
one tunnel flake can't sink `pytest tests/ -x`.
"""
import os
import subprocess
import sys

import pytest

pytestmark = pytest.mark.tpu

_CHECKS = ['conv_train_step', 'attention_train_step', 'sparse_ctr_train_step',
           'amp_bf16_numerics', 'dlpack_roundtrip',
           'py_func_capability_error', 'profiler_trace',
           'checkpoint_roundtrip', 'compiled_artifact_serves_on_chip',
           'crnn_ctc_train_step', 'flash_attention_parity',
           'pallas_bn_numerics']


@pytest.fixture(scope='module')
def smoke_results():
    if os.environ.get('PTPU_RUN_TPU_TESTS') != '1':
        pytest.skip('TPU smoke tier is opt-in: set PTPU_RUN_TPU_TESTS=1')
    worker = os.path.join(os.path.dirname(__file__), 'tpu_smoke_worker.py')
    env = dict(os.environ)
    for k in ('JAX_PLATFORMS', 'PTPU_PLATFORM', 'XLA_FLAGS'):
        env.pop(k, None)
    r = subprocess.run([sys.executable, worker], env=env,
                       capture_output=True, text=True, timeout=900,
                       cwd=os.path.dirname(os.path.dirname(
                           os.path.abspath(__file__))))
    results = {}
    for line in r.stdout.splitlines():
        if line.startswith('CHECK '):
            parts = line.split(None, 2)
            results[parts[1]] = (parts[2] if len(parts) > 2 else 'FAIL')
    if not results:
        pytest.fail('smoke worker produced no results: %s' % r.stderr[-2000:])
    return results


@pytest.mark.parametrize('name', _CHECKS)
def test_tpu(name, smoke_results):
    out = smoke_results.get(name)
    assert out is not None, 'check %s never ran' % name
    assert out.startswith('OK'), out
