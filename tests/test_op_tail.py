"""OpTest coverage for the round-4 op tail (VERDICT r3 missing #3):
hinge_loss, modified_huber_loss, squared_l2_distance, l1_norm,
max_pool2d_with_index, unpool, spp, conv_shift, ctc_align, layers.sum.

Forward checks vs independent numpy references; gradient checks ride the
generic vjp path (core/lowering.py), mirroring the reference's
test_hinge_loss_op.py et al. methodology (op_test.py:303/:414).
"""
import numpy as np
import pytest

import paddle_tpu as fluid
from op_test import OpTest


def _check(op, ins, attrs, outs, grads=(), atol=1e-5, max_rel=5e-3,
           no_check=()):
    t = OpTest()
    t.op_type = op
    t.inputs = ins
    t.attrs = attrs
    t.outputs = outs
    t.check_output(atol=atol, no_check_set=list(no_check))
    for g in grads:
        t.check_grad([g], list(outs)[0], max_relative_error=max_rel)


def test_hinge_loss():
    rng = np.random.RandomState(0)
    x = rng.uniform(-2, 2, (10, 1)).astype(np.float32)
    y = (rng.rand(10, 1) < 0.5).astype(np.float32)
    m = 1.0 - x * (2 * y - 1)
    # keep away from the hinge kink for the numeric grad
    x = np.where(np.abs(m) < 0.2, x + 0.5, x).astype(np.float32)
    ref = np.maximum(0.0, 1.0 - x * (2 * y - 1)).astype(np.float32)
    _check('hinge_loss', {'Logits': x, 'Labels': y}, {}, {'Loss': ref},
           grads=('Logits',))


def test_modified_huber_loss():
    rng = np.random.RandomState(1)
    x = rng.uniform(-3, 3, (12, 1)).astype(np.float32)
    y = (rng.rand(12, 1) < 0.5).astype(np.float32)
    z = x * (2 * y - 1)
    # away from the piecewise joints z = -1 and z = 1
    x = np.where(np.abs(np.abs(z) - 1.0) < 0.2, x * 1.5, x).astype(np.float32)
    z = (x * (2 * y - 1)).astype(np.float32)
    ref = np.where(z < -1, -4 * z,
                   np.square(np.maximum(0.0, 1 - z))).astype(np.float32)
    _check('modified_huber_loss', {'X': x, 'Y': y}, {},
           {'Out': ref.reshape(-1, 1), 'IntermediateVal': z}, grads=('X',))


def test_squared_l2_distance():
    rng = np.random.RandomState(2)
    x = rng.randn(5, 4).astype(np.float32)
    y = rng.randn(5, 4).astype(np.float32)
    sub = x - y
    out = np.sum(sub * sub, axis=1, keepdims=True).astype(np.float32)
    _check('squared_l2_distance', {'X': x, 'Y': y}, {},
           {'sub_result': sub, 'Out': out}, grads=('X', 'Y'))


def test_squared_l2_distance_broadcast_target():
    rng = np.random.RandomState(3)
    x = rng.randn(6, 3).astype(np.float32)
    y = rng.randn(1, 3).astype(np.float32)
    sub = x - y
    out = np.sum(sub * sub, axis=1, keepdims=True).astype(np.float32)
    _check('squared_l2_distance', {'X': x, 'Y': y}, {},
           {'sub_result': sub, 'Out': out})


def test_l1_norm():
    rng = np.random.RandomState(4)
    x = rng.uniform(0.2, 1.5, (3, 7)).astype(np.float32)
    x *= np.sign(rng.randn(3, 7)).astype(np.float32)  # away from 0
    ref = np.array([np.sum(np.abs(x))], np.float32)
    _check('l1_norm', {'X': x}, {}, {'Out': ref}, grads=('X',))


def _np_max_pool_with_index(x, k, s, p):
    n, c, h, w = x.shape
    oh = (h + 2 * p - k) // s + 1
    ow = (w + 2 * p - k) // s + 1
    out = np.zeros((n, c, oh, ow), x.dtype)
    mask = np.zeros((n, c, oh, ow), np.int32)
    for b in range(n):
        for ch in range(c):
            for i in range(oh):
                for j in range(ow):
                    hs, ws = i * s - p, j * s - p
                    best, bidx = -np.inf, -1
                    for hh in range(max(hs, 0), min(hs + k, h)):
                        for ww in range(max(ws, 0), min(ws + k, w)):
                            if x[b, ch, hh, ww] > best:
                                best = x[b, ch, hh, ww]
                                bidx = hh * w + ww
                    out[b, ch, i, j] = best
                    mask[b, ch, i, j] = bidx
    return out, mask


def test_max_pool2d_with_index():
    rng = np.random.RandomState(5)
    # distinct values -> unique argmax, so first-max tie-breaking is moot;
    # kept in [0,1) so the numeric-grad delta isn't rounded away in f32
    x = (rng.permutation(2 * 3 * 6 * 6).reshape(2, 3, 6, 6)
         / 216.0).astype(np.float32)
    out, mask = _np_max_pool_with_index(x, 2, 2, 0)
    _check('max_pool2d_with_index', {'X': x},
           {'ksize': [2, 2], 'strides': [2, 2], 'paddings': [0, 0]},
           {'Out': out, 'Mask': mask}, grads=('X',))


def test_max_pool2d_with_index_padded():
    rng = np.random.RandomState(6)
    x = (rng.permutation(1 * 2 * 5 * 5).reshape(1, 2, 5, 5)
         / 50.0).astype(np.float32)
    out, mask = _np_max_pool_with_index(x, 3, 2, 1)
    _check('max_pool2d_with_index', {'X': x},
           {'ksize': [3, 3], 'strides': [2, 2], 'paddings': [1, 1]},
           {'Out': out, 'Mask': mask})


def test_max_pool2d_with_index_dtype_min_tie():
    """A real value equal to dtype-min must win over a padded slot (the
    pad fill ties it; ADVICE r4 nn_ops.py:196): the Mask must stay an
    in-plane index, never a negative/out-of-plane one."""
    x = np.full((1, 1, 2, 2), np.finfo(np.float32).min, np.float32)
    out, mask = _np_max_pool_with_index(x, 2, 1, 1)
    # numpy oracle scans valid coords only -> in-plane indices
    assert (mask >= 0).all() and (mask < 4).all()
    _check('max_pool2d_with_index', {'X': x},
           {'ksize': [2, 2], 'strides': [1, 1], 'paddings': [1, 1]},
           {'Out': out, 'Mask': mask})


def test_max_pool2d_with_index_nan_keeps_mask_in_plane():
    """A NaN in a padded border window must not push the argmax onto a
    padded slot: Out propagates the NaN, Mask stays in-plane."""
    t = OpTest()
    t.op_type = 'max_pool2d_with_index'
    x = np.zeros((1, 1, 2, 2), np.float32)
    x[0, 0, 0, 0] = np.nan
    t.inputs = {'X': x}
    t.attrs = {'ksize': [2, 2], 'strides': [1, 1], 'paddings': [1, 1]}
    t.outputs = {'Out': np.zeros((1, 1, 3, 3), np.float32),
                 'Mask': np.zeros((1, 1, 3, 3), np.int32)}
    main, startup, feed, out_names, _ = t._build()
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.core.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
        o, m = exe.run(main, feed=feed,
                       fetch_list=[out_names['Out'][0],
                                   out_names['Mask'][0]])
    m = np.asarray(m)
    assert (m >= 0).all() and (m < 4).all(), m
    assert np.isnan(np.asarray(o)).any()   # NaN propagates in Out


def test_max_pool2d_with_index_pad_ge_kernel_rejected():
    """paddings >= ksize would create windows entirely inside padding
    (no valid argmax) — rejected, the reference's constraint."""
    x = np.zeros((1, 1, 4, 4), np.float32)
    with pytest.raises(Exception, match='paddings must be smaller'):
        _check('max_pool2d_with_index', {'X': x},
               {'ksize': [2, 2], 'strides': [1, 1], 'paddings': [2, 2]},
               {'Out': np.zeros((1, 1, 7, 7), np.float32),
                'Mask': np.zeros((1, 1, 7, 7), np.int32)})


def test_max_pool2d_with_index_global():
    rng = np.random.RandomState(11)
    x = (rng.permutation(2 * 2 * 4 * 4).reshape(2, 2, 4, 4)
         / 64.0).astype(np.float32)
    out = x.max((2, 3), keepdims=True)
    mask = x.reshape(2, 2, -1).argmax(-1).astype(np.int32).reshape(2, 2, 1, 1)
    _check('max_pool2d_with_index', {'X': x},
           {'ksize': [1, 1], 'global_pooling': True},
           {'Out': out, 'Mask': mask}, grads=('X',))


def test_unpool():
    rng = np.random.RandomState(7)
    n, c, h, w, k, s = 2, 3, 3, 3, 2, 2
    oh = (h - 1) * s + k
    ow = (w - 1) * s + k
    x = rng.randn(n, c, h, w).astype(np.float32)
    idx = np.stack([
        np.sort(rng.choice(oh * ow, h * w, replace=False)).reshape(h, w)
        for _ in range(n * c)]).reshape(n, c, h, w).astype(np.int32)
    ref = np.zeros((n, c, oh * ow), np.float32)
    for b in range(n):
        for ch in range(c):
            ref[b, ch, idx[b, ch].ravel()] = x[b, ch].ravel()
    _check('unpool', {'X': x, 'Indices': idx},
           {'ksize': [k, k], 'strides': [s, s], 'paddings': [0, 0],
            'unpooling_type': 'max'},
           {'Out': ref.reshape(n, c, oh, ow)}, grads=('X',))


def _np_spp(x, height, ptype):
    n, c, h, w = x.shape
    outs = []
    for p in range(height):
        bins = 2 ** p
        kh, kw = -(-h // bins), -(-w // bins)
        ph, pw = (kh * bins - h + 1) // 2, (kw * bins - w + 1) // 2
        lvl = np.zeros((n, c, bins, bins), np.float32)
        for i in range(bins):
            for j in range(bins):
                hs = max(i * kh - ph, 0)
                he = min(i * kh - ph + kh, h)
                ws = max(j * kw - pw, 0)
                we = min(j * kw - pw + kw, w)
                win = x[:, :, hs:he, ws:we]
                lvl[:, :, i, j] = (win.max((2, 3)) if ptype == 'max'
                                   else win.mean((2, 3)))
        outs.append(lvl.reshape(n, c * bins * bins))
    return np.concatenate(outs, axis=1)


def test_spp_max():
    rng = np.random.RandomState(8)
    x = rng.randn(2, 3, 7, 7).astype(np.float32)
    ref = _np_spp(x, 3, 'max')
    _check('spp', {'X': x}, {'pyramid_height': 3, 'pooling_type': 'max'},
           {'Out': ref})


def test_spp_avg():
    rng = np.random.RandomState(9)
    x = rng.randn(2, 2, 6, 5).astype(np.float32)
    ref = _np_spp(x, 2, 'avg')
    _check('spp', {'X': x}, {'pyramid_height': 2, 'pooling_type': 'avg'},
           {'Out': ref}, grads=('X',))


def test_conv_shift():
    rng = np.random.RandomState(10)
    b, m, nk = 4, 9, 3
    x = rng.randn(b, m).astype(np.float32)
    y = rng.randn(b, nk).astype(np.float32)
    half = (nk - 1) // 2
    ref = np.zeros_like(x)
    for i in range(m):
        for j in range(nk):
            ref[:, i] += x[:, (i + j - half) % m] * y[:, j]
    _check('conv_shift', {'X': x, 'Y': y}, {}, {'Out': ref},
           grads=('X', 'Y'))


def test_ctc_align():
    # two sequences: [0,1,1,0,2,2] -> [1,2] ; [3,0,3,3] -> [3,3]
    toks = np.array([0, 1, 1, 0, 2, 2, 3, 0, 3, 3], np.int32).reshape(-1, 1)
    lod = [[6, 4]]
    exp = np.array([1, 2, -1, -1, -1, -1, 3, 3, -1, -1],
                   np.int32).reshape(-1, 1)
    _check('ctc_align', {'Input': (toks, lod)},
           {'blank': 0, 'merge_repeated': True}, {'Output': exp})


def test_ctc_align_no_merge():
    toks = np.array([0, 1, 1, 0, 2, 2], np.int32).reshape(-1, 1)
    lod = [[6]]
    exp = np.array([1, 1, 2, 2, -1, -1], np.int32).reshape(-1, 1)
    _check('ctc_align', {'Input': (toks, lod)},
           {'blank': 0, 'merge_repeated': False}, {'Output': exp})


def test_layers_sum():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        a = fluid.layers.data(name='a', shape=[3], dtype='float32')
        b = fluid.layers.data(name='b', shape=[3], dtype='float32')
        s2 = fluid.layers.sum([a, b])
        s1 = fluid.layers.sum(a)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    av = np.ones((2, 3), np.float32)
    bv = np.full((2, 3), 2.0, np.float32)
    r2, r1 = exe.run(main, feed={'a': av, 'b': bv}, fetch_list=[s2, s1])
    np.testing.assert_allclose(r2, av + bv)
    np.testing.assert_allclose(r1, av)
