"""Per-op numeric forward + gradient checks through the OpTest harness
(ref: the ~300 test_*_op.py files; representative coverage per group)."""
import numpy as np
import pytest

from op_test import OpTest


def _softmax_np(x, axis=-1):
    e = np.exp(x - x.max(axis=axis, keepdims=True))
    return e / e.sum(axis=axis, keepdims=True)


class TestElementwiseAdd(OpTest):
    op_type = 'elementwise_add'

    def setup_method(self, m):
        x = np.random.rand(3, 4).astype(np.float32)
        y = np.random.rand(3, 4).astype(np.float32)
        self.inputs = {'X': x, 'Y': y}
        self.outputs = {'Out': x + y}

    def test_output(self):
        self.check_output()

    def test_grad(self):
        self.check_grad(['X', 'Y'], 'Out')


class TestElementwiseAddBroadcastAxis(OpTest):
    op_type = 'elementwise_add'

    def setup_method(self, m):
        x = np.random.rand(2, 3, 4).astype(np.float32)
        y = np.random.rand(3).astype(np.float32)
        self.inputs = {'X': x, 'Y': y}
        self.attrs = {'axis': 1}
        self.outputs = {'Out': x + y.reshape(1, 3, 1)}

    def test_output(self):
        self.check_output()

    def test_grad(self):
        self.check_grad(['X', 'Y'], 'Out')


class TestMul(OpTest):
    op_type = 'mul'

    def setup_method(self, m):
        x = np.random.rand(4, 5).astype(np.float32)
        y = np.random.rand(5, 3).astype(np.float32)
        self.inputs = {'X': x, 'Y': y}
        self.outputs = {'Out': x @ y}

    def test_output(self):
        self.check_output(atol=1e-4)

    def test_grad(self):
        self.check_grad(['X', 'Y'], 'Out', max_relative_error=1e-2)


class TestMatmulTranspose(OpTest):
    op_type = 'matmul'

    def setup_method(self, m):
        x = np.random.rand(4, 5).astype(np.float32)
        y = np.random.rand(3, 5).astype(np.float32)
        self.inputs = {'X': x, 'Y': y}
        self.attrs = {'transpose_X': False, 'transpose_Y': True}
        self.outputs = {'Out': x @ y.T}

    def test_output(self):
        self.check_output(atol=1e-4)


class TestSoftmax(OpTest):
    op_type = 'softmax'

    def setup_method(self, m):
        x = np.random.rand(5, 7).astype(np.float32)
        self.inputs = {'X': x}
        self.outputs = {'Out': _softmax_np(x)}

    def test_output(self):
        self.check_output()

    def test_grad(self):
        self.check_grad(['X'], 'Out')


class TestCrossEntropy(OpTest):
    op_type = 'cross_entropy'

    def setup_method(self, m):
        probs = _softmax_np(np.random.rand(6, 4).astype(np.float32))
        label = np.random.randint(0, 4, (6, 1)).astype(np.int64)
        out = -np.log(probs[np.arange(6), label[:, 0]])[:, None]
        self.inputs = {'X': probs, 'Label': label}
        self.outputs = {'Y': out}

    def test_output(self):
        self.check_output()


class TestReduceSum(OpTest):
    op_type = 'reduce_sum'

    def setup_method(self, m):
        x = np.random.rand(3, 4, 5).astype(np.float32)
        self.inputs = {'X': x}
        self.attrs = {'dim': [1], 'keep_dim': False, 'reduce_all': False}
        self.outputs = {'Out': x.sum(axis=1)}

    def test_output(self):
        self.check_output()

    def test_grad(self):
        self.check_grad(['X'], 'Out')


class TestReduceMeanAll(OpTest):
    op_type = 'reduce_mean'

    def setup_method(self, m):
        x = np.random.rand(3, 4).astype(np.float32)
        self.inputs = {'X': x}
        self.attrs = {'reduce_all': True, 'dim': [0]}
        self.outputs = {'Out': np.asarray(x.mean(), np.float32)}

    def test_output(self):
        self.check_output()


@pytest.mark.parametrize("act,fn", [
    ('relu', lambda x: np.maximum(x, 0)),
    ('sigmoid', lambda x: 1 / (1 + np.exp(-x))),
    ('tanh', np.tanh),
    ('exp', np.exp),
    ('square', np.square),
    ('softplus', lambda x: np.log1p(np.exp(x))),
    ('abs', np.abs),
    ('reciprocal', lambda x: 1.0 / x),
    ('sqrt', np.sqrt),
])
def test_activation_forward(act, fn):
    class T(OpTest):
        op_type = act
    t = T()
    x = (np.random.rand(4, 5).astype(np.float32) + 0.5)
    t.inputs = {'X': x}
    t.outputs = {'Out': fn(x).astype(np.float32)}
    t.attrs = {}
    t.check_output(atol=1e-5)


@pytest.mark.parametrize("act", ['sigmoid', 'tanh', 'softplus', 'square'])
def test_activation_grad(act):
    class T(OpTest):
        op_type = act
    t = T()
    x = (np.random.rand(3, 4).astype(np.float32) + 0.5)
    t.inputs = {'X': x}
    t.outputs = {'Out': x}  # unused for grad
    t.attrs = {}
    t.check_grad(['X'], 'Out', max_relative_error=1e-2)


class TestConv2d(OpTest):
    op_type = 'conv2d'

    def setup_method(self, m):
        x = np.random.rand(2, 3, 5, 5).astype(np.float32)
        w = np.random.rand(4, 3, 3, 3).astype(np.float32)
        # numpy reference conv (stride 1, pad 1)
        xp = np.pad(x, [(0, 0), (0, 0), (1, 1), (1, 1)])
        out = np.zeros((2, 4, 5, 5), np.float32)
        for n in range(2):
            for o in range(4):
                for i in range(5):
                    for j in range(5):
                        out[n, o, i, j] = np.sum(
                            xp[n, :, i:i + 3, j:j + 3] * w[o])
        self.inputs = {'Input': x, 'Filter': w}
        self.attrs = {'strides': [1, 1], 'paddings': [1, 1],
                      'dilations': [1, 1], 'groups': 1}
        self.outputs = {'Output': out}

    def test_output(self):
        self.check_output(atol=1e-4)

    def test_grad(self):
        self.check_grad(['Input', 'Filter'], 'Output',
                        max_relative_error=2e-2)


class TestPool2dMax(OpTest):
    op_type = 'pool2d'

    def setup_method(self, m):
        x = np.random.rand(2, 3, 4, 4).astype(np.float32)
        out = x.reshape(2, 3, 2, 2, 2, 2).max(axis=(3, 5))
        self.inputs = {'X': x}
        self.attrs = {'pooling_type': 'max', 'ksize': [2, 2],
                      'strides': [2, 2], 'paddings': [0, 0]}
        self.outputs = {'Out': out}

    def test_output(self):
        self.check_output()

    def test_grad(self):
        self.check_grad(['X'], 'Out', max_relative_error=1e-2)


class TestPool2dAvg(OpTest):
    op_type = 'pool2d'

    def setup_method(self, m):
        x = np.random.rand(2, 3, 4, 4).astype(np.float32)
        out = x.reshape(2, 3, 2, 2, 2, 2).mean(axis=(3, 5))
        self.inputs = {'X': x}
        self.attrs = {'pooling_type': 'avg', 'ksize': [2, 2],
                      'strides': [2, 2], 'paddings': [0, 0]}
        self.outputs = {'Out': out}

    def test_output(self):
        self.check_output()


class TestLayerNorm(OpTest):
    op_type = 'layer_norm'

    def setup_method(self, m):
        x = np.random.rand(4, 6).astype(np.float32)
        scale = np.random.rand(6).astype(np.float32)
        bias = np.random.rand(6).astype(np.float32)
        mu = x.mean(axis=1, keepdims=True)
        var = x.var(axis=1, keepdims=True)
        out = (x - mu) / np.sqrt(var + 1e-5) * scale + bias
        self.inputs = {'X': x, 'Scale': scale, 'Bias': bias}
        self.attrs = {'begin_norm_axis': 1, 'epsilon': 1e-5}
        self.outputs = {'Y': out}

    def test_output(self):
        self.check_output(atol=1e-4)

    def test_grad(self):
        self.check_grad(['X', 'Scale', 'Bias'], 'Y', max_relative_error=2e-2)


class TestLookupTable(OpTest):
    op_type = 'lookup_table'

    def setup_method(self, m):
        w = np.random.rand(10, 4).astype(np.float32)
        ids = np.random.randint(0, 10, (5, 1)).astype(np.int64)
        self.inputs = {'W': w, 'Ids': ids}
        self.attrs = {'padding_idx': -1}
        self.outputs = {'Out': w[ids[:, 0]]}

    def test_output(self):
        self.check_output()

    def test_grad(self):
        self.check_grad(['W'], 'Out', max_relative_error=1e-2)


class TestTranspose(OpTest):
    op_type = 'transpose'

    def setup_method(self, m):
        x = np.random.rand(2, 3, 4).astype(np.float32)
        self.inputs = {'X': x}
        self.attrs = {'axis': [1, 0, 2]}
        self.outputs = {'Out': x.transpose(1, 0, 2)}

    def test_output(self):
        self.check_output()


class TestConcat(OpTest):
    op_type = 'concat'

    def setup_method(self, m):
        a = np.random.rand(2, 3).astype(np.float32)
        b = np.random.rand(2, 5).astype(np.float32)
        self.inputs = {'X': [('x0', a), ('x1', b)]}
        self.attrs = {'axis': 1}
        self.outputs = {'Out': np.concatenate([a, b], axis=1)}

    def test_output(self):
        self.check_output()

    def test_grad(self):
        self.check_grad(['x0', 'x1'], 'Out')


class TestGather(OpTest):
    op_type = 'gather'

    def setup_method(self, m):
        x = np.random.rand(6, 3).astype(np.float32)
        idx = np.array([0, 2, 5], np.int64)
        self.inputs = {'X': x, 'Index': idx}
        self.outputs = {'Out': x[idx]}

    def test_output(self):
        self.check_output()

    def test_grad(self):
        self.check_grad(['X'], 'Out', max_relative_error=1e-2)


class TestBatchNormInference(OpTest):
    op_type = 'batch_norm'

    def setup_method(self, m):
        x = np.random.rand(2, 3, 4, 4).astype(np.float32)
        scale = np.random.rand(3).astype(np.float32)
        bias = np.random.rand(3).astype(np.float32)
        mean = np.random.rand(3).astype(np.float32)
        var = np.random.rand(3).astype(np.float32) + 0.5
        out = ((x - mean.reshape(1, 3, 1, 1)) /
               np.sqrt(var.reshape(1, 3, 1, 1) + 1e-5) *
               scale.reshape(1, 3, 1, 1) + bias.reshape(1, 3, 1, 1))
        self.inputs = {'X': x, 'Scale': scale, 'Bias': bias, 'Mean': mean,
                       'Variance': var}
        self.attrs = {'is_test': True, 'epsilon': 1e-5}
        self.outputs = {'Y': out}

    def test_output(self):
        self.check_output(atol=1e-4, no_check_set=(
            'MeanOut', 'VarianceOut', 'SavedMean', 'SavedVariance'))


class TestSoftmaxWithCrossEntropy(OpTest):
    op_type = 'softmax_with_cross_entropy'

    def setup_method(self, m):
        logits = np.random.rand(5, 7).astype(np.float32)
        label = np.random.randint(0, 7, (5, 1)).astype(np.int64)
        sm = _softmax_np(logits)
        loss = -np.log(sm[np.arange(5), label[:, 0]])[:, None]
        self.inputs = {'Logits': logits, 'Label': label}
        self.outputs = {'Softmax': sm, 'Loss': loss}

    def test_output(self):
        self.check_output(atol=1e-5)

    def test_grad(self):
        self.check_grad(['Logits'], 'Loss', max_relative_error=1e-2)
