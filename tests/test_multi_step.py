"""Multi-step training dispatch (ISSUE 2): Executor.run_steps wraps the
traced step in a lax.scan over K pre-staged batches, so one dispatch
advances optimizer state K steps. The contract under test is
BIT-IDENTITY with K sequential run() calls — params, rng stream,
metrics — plus EOF partial-tail flushing, gradient-merge composition,
fetch-thinning policies, and the numpy-side rng fallback (ADVICE r5
item 3)."""
import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import unique_name
from paddle_tpu.parallel import MultiStepTrainer


def _build_net(seed, dropout=True):
    """fc net with dropout (rng-consuming), momentum + LR decay (stateful
    optimizer slots + step-counter state)."""
    with unique_name.guard():
        main_p, startup_p = fluid.Program(), fluid.Program()
        main_p.random_seed = startup_p.random_seed = seed
        with fluid.program_guard(main_p, startup_p):
            x = fluid.layers.data(name='x', shape=[16], dtype='float32')
            lab = fluid.layers.data(name='lab', shape=[1], dtype='int64')
            h = fluid.layers.fc(x, size=32, act='relu')
            if dropout:
                h = fluid.layers.dropout(h, dropout_prob=0.3)
            logits = fluid.layers.fc(h, size=5)
            loss = fluid.layers.mean(
                fluid.layers.softmax_with_cross_entropy(logits=logits,
                                                        label=lab))
            acc = fluid.layers.accuracy(input=fluid.layers.softmax(logits),
                                        label=lab)
            fluid.optimizer.Momentum(
                learning_rate=fluid.layers.exponential_decay(0.1, 10, 0.9),
                momentum=0.9).minimize(loss)
    return main_p, startup_p, loss, acc


def _batches(n, rng_seed=3, batch=8):
    rng = np.random.RandomState(rng_seed)
    return ([rng.randn(batch, 16).astype(np.float32) for _ in range(n)],
            [rng.randint(0, 5, (batch, 1)) for _ in range(n)])


def _persist_state(program, scope):
    return {v.name: np.asarray(scope.get(v.name)).copy()
            for v in program.list_vars()
            if v.persistable and scope.get(v.name) is not None}


def _run_sequential(steps, fetch_extra=False, seed=17):
    main_p, startup_p, loss, acc = _build_net(seed)
    xs, labs = _batches(steps)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.core.Scope()
    fetches = [loss, acc] if fetch_extra else [loss]
    out = []
    with fluid.scope_guard(scope):
        exe.run(startup_p)
        for i in range(steps):
            vals = exe.run(main_p, feed={'x': xs[i], 'lab': labs[i]},
                           fetch_list=fetches)
            out.append([np.asarray(v).reshape(-1) for v in vals])
        state = _persist_state(main_p, scope)
    return out, state


def test_run_steps_bit_identical_to_sequential():
    """K-step dispatch == K single run() calls, bit for bit: per-step
    losses AND metrics (via 'stack'), every persistable (params, momentum
    slots, LR counter), and the rng stream (the net has dropout — any rng
    divergence would flip masks and change every number)."""
    seq, seq_state = _run_sequential(8, fetch_extra=True)

    main_p, startup_p, loss, acc = _build_net(17)
    xs, labs = _batches(8)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.core.Scope()
    multi = []
    with fluid.scope_guard(scope):
        exe.run(startup_p)
        for d in range(2):
            l, a = exe.run_steps(
                main_p, feed={'x': xs[4 * d:4 * d + 4],
                              'lab': labs[4 * d:4 * d + 4]},
                fetch_list=[loss, acc], steps=4, fetch_policy='stack')
            for i in range(4):
                multi.append([np.asarray(l)[i].reshape(-1),
                              np.asarray(a)[i].reshape(-1)])
        multi_state = _persist_state(main_p, scope)

    for s, m in zip(seq, multi):
        np.testing.assert_array_equal(s[0], m[0])  # loss
        np.testing.assert_array_equal(s[1], m[1])  # accuracy metric
    assert set(seq_state) == set(multi_state)
    for n in seq_state:
        np.testing.assert_array_equal(seq_state[n], multi_state[n],
                                      err_msg='state %r diverged' % n)


def test_fetch_policy_final_thins_to_every_k():
    seq, _ = _run_sequential(4)
    main_p, startup_p, loss, _acc = _build_net(17)
    xs, labs = _batches(4)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.core.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup_p)
        l, = exe.run_steps(main_p, feed={'x': xs, 'lab': labs},
                           fetch_list=[loss], steps=4,
                           fetch_policy='final')
    np.testing.assert_array_equal(np.asarray(l).reshape(-1), seq[-1][0])


def test_fetch_policy_validation():
    main_p, _startup_p, loss, _ = _build_net(1)
    exe = fluid.Executor(fluid.CPUPlace())
    with pytest.raises(ValueError, match='fetch_policy'):
        exe.run_steps(main_p, feed={'x': np.zeros((2, 4, 16), np.float32)},
                      fetch_list=[loss], fetch_policy='every_other')


def test_feed_step_dim_mismatch_raises():
    main_p, startup_p, loss, _ = _build_net(2)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.core.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup_p)
        with pytest.raises(ValueError, match='disagree on the step'):
            exe.run_steps(
                main_p,
                feed={'x': np.zeros((3, 8, 16), np.float32),
                      'lab': np.zeros((2, 8, 1), np.int64)},
                fetch_list=[loss])
        with pytest.raises(ValueError, match='stacked'):
            exe.run_steps(
                main_p,
                feed={'x': np.zeros((3, 8, 16), np.float32),
                      'lab': np.zeros((3, 8, 1), np.int64)},
                fetch_list=[loss], steps=4)


def test_rng_stream_shared_with_single_runs():
    """run() and run_steps() advance ONE step counter: 2 singles + one
    K=2 group == 4 singles, bit for bit (dropout makes rng drift
    visible)."""
    seq, seq_state = _run_sequential(4)

    main_p, startup_p, loss, _acc = _build_net(17)
    xs, labs = _batches(4)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.core.Scope()
    got = []
    with fluid.scope_guard(scope):
        exe.run(startup_p)
        for i in range(2):
            l, = exe.run(main_p, feed={'x': xs[i], 'lab': labs[i]},
                         fetch_list=[loss])
            got.append(np.asarray(l).reshape(-1))
        l, = exe.run_steps(main_p, feed={'x': xs[2:], 'lab': labs[2:]},
                           fetch_list=[loss], steps=2,
                           fetch_policy='stack')
        got.extend(np.asarray(l).reshape(2, -1))
        state = _persist_state(main_p, scope)
    for s, m in zip(seq, got):
        np.testing.assert_array_equal(s[0], m)
    for n in seq_state:
        np.testing.assert_array_equal(seq_state[n], state[n])


def test_grad_merge_composes_with_run_steps():
    """K outer steps x k=2 micro-batch scan: the gradient-merge program
    runs unchanged inside the multi-step dispatch, bit-matching
    sequential gradient-merge runs."""
    def build(seed):
        with unique_name.guard():
            main_p, startup_p = fluid.Program(), fluid.Program()
            main_p.random_seed = startup_p.random_seed = seed
            with fluid.program_guard(main_p, startup_p):
                x = fluid.layers.data(name='x', shape=[16], dtype='float32')
                lab = fluid.layers.data(name='lab', shape=[1],
                                        dtype='int64')
                logits = fluid.layers.fc(
                    fluid.layers.fc(x, 32, act='relu'), 5)
                loss = fluid.layers.mean(
                    fluid.layers.softmax_with_cross_entropy(
                        logits=logits, label=lab))
                fluid.contrib.gradient_merge.decorate(
                    fluid.optimizer.SGD(learning_rate=0.5), 2).minimize(
                        loss)
        return main_p, startup_p, loss

    xs, labs = _batches(6, rng_seed=8)
    main_p, startup_p, loss = build(23)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.core.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup_p)
        seq = [np.asarray(exe.run(main_p,
                                  feed={'x': xs[i], 'lab': labs[i]},
                                  fetch_list=[loss])[0]).reshape(-1)
               for i in range(6)]

    main_p, startup_p, loss = build(23)
    exe2 = fluid.Executor(fluid.CPUPlace())
    scope2 = fluid.core.Scope()
    with fluid.scope_guard(scope2):
        exe2.run(startup_p)
        multi = []
        for d in range(2):
            out, = exe2.run_steps(
                main_p, feed={'x': xs[3 * d:3 * d + 3],
                              'lab': labs[3 * d:3 * d + 3]},
                fetch_list=[loss], steps=3, fetch_policy='stack')
            multi.extend(np.asarray(out).reshape(3, -1))
    for s, m in zip(seq, multi):
        np.testing.assert_array_equal(s, m)


def _lod_group_roundtrip(lens_per_step):
    """Build an embedding+sequence_pool net, run the per-step batches
    sequentially and as one run_steps group; return (seq, multi)."""
    with unique_name.guard():
        main_p, startup_p = fluid.Program(), fluid.Program()
        main_p.random_seed = startup_p.random_seed = 5
        with fluid.program_guard(main_p, startup_p):
            w = fluid.layers.data(name='w', shape=[1], dtype='int64',
                                  lod_level=1)
            emb = fluid.layers.embedding(w, size=(50, 8))
            pooled = fluid.layers.sequence_pool(emb, 'sum')
            lab = fluid.layers.data(name='lab', shape=[1], dtype='int64')
            loss = fluid.layers.mean(
                fluid.layers.softmax_with_cross_entropy(
                    logits=fluid.layers.fc(pooled, 4), label=lab))
            fluid.optimizer.SGD(0.1).minimize(loss)

    rng = np.random.RandomState(0)
    batches = [(fluid.create_lod_tensor(
                    rng.randint(0, 50, (sum(lens), 1)), [list(lens)]),
                rng.randint(0, 4, (len(lens), 1)))
               for lens in lens_per_step]

    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.core.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup_p)
        seq = [np.asarray(exe.run(main_p, feed={'w': b[0], 'lab': b[1]},
                                  fetch_list=[loss])[0]).reshape(-1)
               for b in batches]
    exe2 = fluid.Executor(fluid.CPUPlace())
    scope2 = fluid.core.Scope()
    with fluid.scope_guard(scope2):
        exe2.run(startup_p)
        out, = exe2.run_steps(main_p,
                              feed={'w': [b[0] for b in batches],
                                    'lab': [b[1] for b in batches]},
                              fetch_list=[loss],
                              steps=len(lens_per_step),
                              fetch_policy='stack')
    return np.stack(seq).reshape(-1), np.asarray(out).reshape(-1)


def test_lod_feeds_identical_pattern_stack_static():
    """Identical static lod pattern across the group: offsets stay host
    structure (static stacking), so even host-lod ops would keep working
    — and the group bit-matches sequential runs."""
    seq, multi = _lod_group_roundtrip([[3, 2, 4]] * 4)
    np.testing.assert_array_equal(seq, multi)


def test_lod_feeds_varying_pattern_stack_traced():
    """Varying lod patterns within one bucket shape (same rows, same
    nseq) stack in TRACED form — offsets become scanned data — and
    bit-match sequential runs."""
    seq, multi = _lod_group_roundtrip(
        [[3, 2, 4], [2, 3, 4], [4, 4, 1], [1, 2, 6]])
    np.testing.assert_array_equal(seq, multi)


def test_lod_bucket_mismatch_raises():
    with unique_name.guard():
        main_p, startup_p = fluid.Program(), fluid.Program()
        with fluid.program_guard(main_p, startup_p):
            w = fluid.layers.data(name='w', shape=[1], dtype='int64',
                                  lod_level=1)
            emb = fluid.layers.embedding(w, size=(50, 8))
            loss = fluid.layers.mean(
                fluid.layers.sequence_pool(emb, 'sum'))
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.core.Scope()
    a = fluid.create_lod_tensor(np.zeros((5, 1), np.int64), [[3, 2]])
    b = fluid.create_lod_tensor(np.zeros((6, 1), np.int64), [[3, 3]])
    with fluid.scope_guard(scope):
        exe.run(startup_p)
        with pytest.raises(ValueError, match='bucket'):
            exe.run_steps(main_p, feed={'w': [a, b]}, fetch_list=[loss],
                          steps=2)


def _pyreader_program():
    reader = fluid.layers.py_reader(
        capacity=8, shapes=[(-1, 4), (-1, 1)], dtypes=['float32', 'int64'])
    x, label = fluid.layers.read_file(reader)
    logits = fluid.layers.fc(input=x, size=3)
    loss = fluid.layers.mean(fluid.layers.softmax_with_cross_entropy(
        logits=logits, label=label))
    fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
    return reader, loss


def _seven_batches():
    def data():
        rng = np.random.RandomState(0)
        for i in range(7):
            yield [(rng.rand(4).astype(np.float32),
                    np.array([i % 3], np.int64)) for _ in range(6)]
    return data


def test_eof_partial_tail_flush_prefetch_ring():
    """7 batches through a prefetch_to_device(4) ring: dispatch 1 runs 4
    steps, dispatch 2 flushes the 3-step tail through a smaller compiled
    bucket, then EOF — per epoch, for two epochs."""
    reader, loss = _pyreader_program()
    reader.decorate_paddle_reader(_seven_batches())
    reader.prefetch_to_device(4)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())

    for _epoch in range(2):
        reader.start()
        per_dispatch = []
        while True:
            try:
                l, = exe.run_steps(fetch_list=[loss], steps=4,
                                   fetch_policy='stack')
                per_dispatch.append(np.asarray(l).shape[0])
            except fluid.core.EOFException:
                reader.reset()
                break
        assert per_dispatch == [4, 3]
    assert exe._dispatch_stats['dispatches'] == 4
    assert exe._dispatch_stats['steps'] == 14
    assert exe._dispatch_stats['tail_flushes'] == 2
    assert reader.prefetch_stats['tail_groups'] == 1  # per start()


def test_eof_partial_tail_flush_plain_reader():
    """Without the ring, run_steps pulls K single batches and stacks on
    the spot; the EOF mid-group flushes the partial tail and the
    EOFException surfaces on the NEXT call (run() parity)."""
    reader, loss = _pyreader_program()
    reader.decorate_paddle_reader(_seven_batches())
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())

    reader.start()
    l1, = exe.run_steps(fetch_list=[loss], steps=4, fetch_policy='stack')
    l2, = exe.run_steps(fetch_list=[loss], steps=4, fetch_policy='stack')
    assert np.asarray(l1).shape[0] == 4 and np.asarray(l2).shape[0] == 3
    with pytest.raises(fluid.core.EOFException):
        exe.run_steps(fetch_list=[loss], steps=4)
    reader.reset()


def test_ring_fed_matches_explicit_feed():
    """The ring path (host-stacked, device-staged groups) feeds the same
    compiled program the explicit stacked feed hits — losses match."""
    reader, loss = _pyreader_program()
    rng = np.random.RandomState(7)
    feats = [rng.rand(6, 4).astype(np.float32) for _ in range(4)]
    labs = [rng.randint(0, 3, (6, 1)) for _ in range(4)]

    def data():
        for f, l in zip(feats, labs):
            yield [(f[j], l[j]) for j in range(6)]

    reader.decorate_paddle_reader(data)
    reader.prefetch_to_device(4)
    exe = fluid.Executor(fluid.CPUPlace())
    startup = fluid.default_startup_program()
    main = fluid.default_main_program()
    scope = fluid.core.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
        reader.start()
        ring, = exe.run_steps(fetch_list=[loss], steps=4,
                              fetch_policy='stack')
        reader.reset()
    names = [v.name for v in reader.feed_vars]
    scope2 = fluid.core.Scope()
    with fluid.scope_guard(scope2):
        exe2 = fluid.Executor(fluid.CPUPlace())
        exe2.run(startup)
        fed, = exe2.run_steps(main,
                              feed={names[0]: np.stack(feats),
                                    names[1]: np.stack(labs)},
                              fetch_list=[loss], steps=4,
                              fetch_policy='stack')
    np.testing.assert_array_equal(np.asarray(ring), np.asarray(fed))


def test_plain_reader_tail_flag_clears_on_restart():
    """The tail-flush EOF marker run_steps leaves on a plain reader must
    not leak into the next epoch: after reset()+start(), the first
    dispatch of epoch 2 runs (it must NOT raise a spurious EOF)."""
    reader, loss = _pyreader_program()
    reader.decorate_paddle_reader(_seven_batches())
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    for _epoch in range(2):
        reader.start()
        l1, = exe.run_steps(fetch_list=[loss], steps=4,
                            fetch_policy='stack')
        l2, = exe.run_steps(fetch_list=[loss], steps=4,
                            fetch_policy='stack')
        assert (np.asarray(l1).shape[0], np.asarray(l2).shape[0]) == (4, 3)
        # caller resets after seeing the short tail, WITHOUT consuming
        # the pending EOF — the flag must not survive the restart
        reader.reset()


def test_prefetch_config_mid_epoch_takes_effect_next_start():
    """prefetch_to_device called while a per-batch epoch is running must
    not break the running epoch (the mode is snapshotted at start())."""
    reader, loss = _pyreader_program()
    reader.decorate_paddle_reader(_seven_batches())
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    reader.start()
    exe.run(fetch_list=[loss])              # per-batch epoch in flight
    reader.prefetch_to_device(4)            # configure the NEXT epoch
    exe.run(fetch_list=[loss])              # current epoch keeps working
    reader.reset()
    reader.start()                          # group mode takes effect here
    l, = exe.run_steps(fetch_list=[loss], steps=4, fetch_policy='stack')
    assert np.asarray(l).shape[0] == 4
    reader.reset()


def test_missing_state_guidance():
    """run_steps refuses to create scan-carry state entries mid-loop: an
    un-run startup program yields actionable guidance, not a scan
    structure error."""
    main_p, _startup_p, loss, _ = _build_net(9)
    xs, labs = _batches(2)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.core.Scope()
    with fluid.scope_guard(scope):
        with pytest.raises(RuntimeError, match='startup'):
            exe.run_steps(main_p, feed={'x': xs, 'lab': labs},
                          fetch_list=[loss], steps=2)


def test_multi_step_trainer_wrapper():
    """MultiStepTrainer: startup + iter_epoch drive the full loop (ring
    start, dispatches, tail flush, reset) and surface stats."""
    reader, loss = _pyreader_program()
    reader.decorate_paddle_reader(_seven_batches())
    reader.prefetch_to_device(4)
    trainer = MultiStepTrainer(fluid.default_main_program(),
                               steps_per_dispatch=4, fetch_list=[loss],
                               fetch_policy='stack',
                               place=fluid.CPUPlace())
    trainer.startup(fluid.default_startup_program())
    sizes = [np.asarray(f[0]).shape[0] for f in trainer.iter_epoch(reader)]
    assert sizes == [4, 3]
    st = trainer.stats
    assert st['dispatches'] == 2 and st['steps'] == 7
    assert st['tail_flushes'] == 1
    # second epoch: iter_epoch restarts the (reset) reader
    sizes = [np.asarray(f[0]).shape[0] for f in trainer.iter_epoch(reader)]
    assert sizes == [4, 3]
    # third epoch from a DRAINED, un-reset reader (manual loop consumed
    # the EOF but never called reset): iter_epoch must restart, not hang
    reader.start()
    with pytest.raises(fluid.core.EOFException):
        while True:
            trainer.step_group(reader=reader)
    sizes = [np.asarray(f[0]).shape[0] for f in trainer.iter_epoch(reader)]
    assert sizes == [4, 3]


def test_prefetch_reader_steps_omitted_counts_tail():
    """steps= may be omitted when the reader prefetches fixed groups; the
    EOF tail flush must still be detected (counted against the reader's
    configured group size, not the steps argument)."""
    reader, loss = _pyreader_program()
    reader.decorate_paddle_reader(_seven_batches())
    reader.prefetch_to_device(4)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    reader.start()
    sizes = []
    while True:
        try:
            l, = exe.run_steps(fetch_list=[loss], fetch_policy='stack')
            sizes.append(np.asarray(l).shape[0])
        except fluid.core.EOFException:
            reader.reset()
            break
    assert sizes == [4, 3]
    assert exe._dispatch_stats['tail_flushes'] == 1


def test_serve_np_threefry_fold_matches_jax():
    """serve.py's framework-free numpy fold (CompiledTrainer._rng
    fallback under JAX_PLATFORMS=tpu) bit-matches jax's derivation."""
    import jax
    from paddle_tpu.inference.serve import _np_threefry_fold
    for seed in (1, 1234567, 2 ** 31 - 1, 123456789012, -3):
        for step in (0, 5, 999):
            key = jax.random.key(seed, impl='threefry2x32')
            want = np.asarray(jax.random.key_data(
                jax.random.fold_in(key, step)))
            np.testing.assert_array_equal(
                _np_threefry_fold(seed, step), want)


def test_host_rng_numpy_fallback_bit_identical():
    """The numpy-side threefry derivation (used when no cpu backend is
    registered, JAX_PLATFORMS=tpu — ADVICE r5 item 3) must bit-match
    jax's key math for single keys and whole dispatch groups."""
    from paddle_tpu.executor import Executor, _np_threefry_key_group
    # large (>= 2^32) and negative seeds exercise jax's x64-disabled seed
    # canonicalization (upper key word zero, lower word two's-complement)
    for seed in (1, 17, 1234567, 2 ** 31 - 1, 123456789012, -3):
        for step0, k in ((0, 5), (7, 3), (123456, 2), (0, 1)):
            via_jax = Executor._host_rng_group(seed, 'threefry2x32',
                                               step0, k)
            via_np = _np_threefry_key_group(seed, step0, k)
            np.testing.assert_array_equal(via_jax, via_np)
            singles = np.stack([
                Executor._host_rng(seed, 'threefry2x32', step0 + i)
                for i in range(k)])
            np.testing.assert_array_equal(via_jax, singles)


def test_profiler_training_report():
    """run_steps registers a training source; training_report renders and
    returns its per-dispatch counters."""
    from paddle_tpu import profiler
    main_p, startup_p, loss, _ = _build_net(11)
    xs, labs = _batches(4)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.core.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup_p)
        exe.run_steps(main_p, feed={'x': xs, 'lab': labs},
                      fetch_list=[loss], steps=4)
    try:
        report = profiler.training_report()
        snap = report['executor@%x' % id(exe)]
        assert snap['dispatches'] == 1 and snap['steps'] == 4
        assert snap['steps_per_dispatch'] == 4.0
        assert snap['tail_flushes'] == 0
    finally:
        exe.close()  # unregisters the source
    assert 'executor@%x' % id(exe) not in profiler.training_report()
