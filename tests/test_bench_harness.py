"""Bench harness hardening tests (no real benchmarks run here).

The r3 driver artifact was destroyed by one transient axon-tunnel flake
(VERDICT r3 weak #1): an uncaught INTERNAL remote_compile error crashed the
headline ResNet run. These tests pin the contract that can never lose the
headline again: per-metric isolation, transient retry with backoff, exit 0
always, headline printed first (insurance) and last (driver parse).

Reference analogue: benchmark/fluid/fluid_benchmark.py:139 prints every
metric it measures.
"""
import json
import sys

import bench


def _lines(capsys):
    out = capsys.readouterr().out
    return [json.loads(l) for l in out.splitlines() if l.strip()]


def test_transient_classifier():
    assert bench.is_transient(RuntimeError(
        'INTERNAL: http://127.0.0.1:8113/remote_compile: read body: '
        'response body closed before all bytes were read'))
    assert bench.is_transient(RuntimeError('UNAVAILABLE: Socket closed'))
    assert not bench.is_transient(ValueError('shape mismatch (3,) vs (4,)'))


def test_retry_transient_then_succeed():
    calls = []

    def flaky():
        calls.append(1)
        if len(calls) < 3:
            raise RuntimeError('INTERNAL: remote_compile: read body')
        return {'metric': 'm', 'value': 1.0}

    naps = []
    out = bench.run_metric('m', flaky, retries=3, backoff_s=1,
                           sleep=naps.append)
    assert out == {'metric': 'm', 'value': 1.0}
    assert len(calls) == 3
    assert naps == [1, 2]  # exponential backoff


def test_no_retry_on_non_transient():
    calls = []

    def broken():
        calls.append(1)
        raise ValueError('bad shape')

    out = bench.run_metric('m', broken, sleep=lambda s: None)
    assert len(calls) == 1
    assert out['metric'] == 'm' and 'bad shape' in out['error']
    assert out['transient'] is False


def test_retries_exhausted_yields_error_line():
    def always_flaky():
        raise RuntimeError('INTERNAL: remote_compile flake')

    out = bench.run_metric('m', always_flaky, retries=3, sleep=lambda s: None)
    assert out['attempts'] == 3 and out['transient'] is True
    assert 'remote_compile' in out['error']


def test_main_headline_first_and_last(capsys):
    benches = [
        ('headline', lambda: {'metric': 'headline', 'value': 10.0}),
        ('secondary', lambda: {'metric': 'secondary', 'value': 5.0}),
    ]
    rc = bench.main(benches)
    assert rc == 0
    lines = _lines(capsys)
    # headline printed immediately (insurance) AND re-printed last (driver
    # parses the final JSON line as the headline)
    assert lines[0]['metric'] == 'headline'
    assert lines[-1]['metric'] == 'headline'
    assert any(l['metric'] == 'secondary' for l in lines)


def test_main_survives_injected_fault(capsys):
    def dead_secondary():
        raise RuntimeError('INTERNAL: remote_compile: read body')

    benches = [
        ('headline', lambda: {'metric': 'headline', 'value': 10.0}),
        ('secondary', dead_secondary),
    ]
    # retries sleep 5/10s by default — patch backoff out via run_metric's
    # seam by monkeying time.sleep is avoided; the fault is non-recoverable
    # so just accept the ~15s... no: keep the test fast by patching sleep.
    orig_sleep = bench.time.sleep
    bench.time.sleep = lambda s: None
    try:
        rc = bench.main(benches)
    finally:
        bench.time.sleep = orig_sleep
    assert rc == 0
    lines = _lines(capsys)
    errs = [l for l in lines if 'error' in l]
    assert errs and errs[0]['metric'] == 'secondary'
    assert lines[-1]['metric'] == 'headline'  # headline survived the fault


def test_main_headline_fault_still_exits_zero(capsys):
    def dead_headline():
        raise ValueError('model build broke')

    benches = [
        ('headline', dead_headline),
        ('secondary', lambda: {'metric': 'secondary', 'value': 5.0}),
    ]
    rc = bench.main(benches)
    assert rc == 0
    lines = _lines(capsys)
    assert 'error' in lines[0] and lines[0]['metric'] == 'headline'
    # the headline's ERROR line is re-printed last: the driver must see an
    # explicit headline failure, never a secondary metric mislabeled as
    # the headline
    assert lines[-1]['metric'] == 'headline' and 'error' in lines[-1]
    assert any(l['metric'] == 'secondary' and 'error' not in l
               for l in lines)


def _fat_line(metric, device_failed=False):
    """A metric line with every field a real bench emits (device-time
    duals included) — the compactness contract must hold for the fattest
    realistic line, not a toy. With device_failed, the device-time miss
    shape (null + capped device_error) rides instead."""
    line = bench._line(metric, 123456.78, 'tokens/s', 33.17,
                       mfu=0.3312, dtype='bf16', batch=4096, seq_len=256,
                       grad_merge_k=2, baseline_ref='flops_eq_xeon',
                       steps_per_dispatch=16,
                       single_step_ms_batch=23.51,
                       speedup_vs_single=9.41)
    if device_failed:
        return bench._attach_device_time(line, lambda: (_ for _ in ()).throw(
            RuntimeError('INTERNAL: http://127.0.0.1:8113/remote_compile: '
                         'read body: response body closed before all bytes '
                         'were read through the axon tunnel session')))
    line.update(device_ms_per_step=2.513, device_k=16,
                device_img_s=5123.45)
    return line


def test_metric_lines_compact_and_under_byte_budget(capsys):
    """Every metric line must parse as STANDALONE JSON under
    LINE_BYTE_BUDGET bytes — the r5 driver artifact's tail byte-cap
    dropped every metric line before the last ~8 because prose baselines
    bloated them (prose belongs in BENCH_NOTES.md now)."""
    benches = [('m%d' % i, lambda i=i: _fat_line('metric_%d_img_s_per_chip'
                                                 % i)) for i in range(3)]
    benches.append(('m3', lambda: _fat_line(
        'metric_3_device_miss_img_s_per_chip', device_failed=True)))
    assert bench.main(benches) == 0
    raw = [l for l in capsys.readouterr().out.splitlines() if l.strip()]
    for l in raw:
        parsed = json.loads(l)  # standalone-parsable
        if 'metric' in parsed:
            assert len(l.encode()) <= bench.LINE_BYTE_BUDGET, (len(l), l)
            assert 'note' not in parsed and 'baseline' not in parsed


def test_summary_line_before_headline_reprint(capsys):
    benches = [
        ('headline', lambda: {'metric': 'headline', 'value': 10.0,
                              'vs_baseline': 2.0}),
        ('secondary', lambda: {'metric': 'secondary', 'value': 5.0,
                               'vs_baseline': 1.5}),
        ('broken', lambda: (_ for _ in ()).throw(ValueError('nope'))),
    ]
    assert bench.main(benches) == 0
    lines = _lines(capsys)
    # summary is the penultimate line: every metric present, errors marked
    assert lines[-1].get('metric') == 'headline'
    summary = lines[-2].get('summary')
    assert summary == {'headline': [10.0, 2.0], 'secondary': [5.0, 1.5],
                       'broken': 'error'}


def test_device_time_attach_isolated():
    """A device-time measurement failure must not cost the metric it
    rides on — the line keeps its value and records the miss."""
    line = bench._line('m', 1.0, 'img/s', 2.0)

    def boom():
        raise RuntimeError('scan unsupported here')
    out = bench._attach_device_time(dict(line), boom)
    assert out['value'] == 1.0
    assert out['device_ms_per_step'] is None
    assert 'scan unsupported' in out['device_error']

    ok = bench._attach_device_time(dict(line), lambda: (3.21987, 16))
    assert ok['device_ms_per_step'] == 3.22 and ok['device_k'] == 16


def test_device_time_env_disable(monkeypatch):
    monkeypatch.setenv('PTPU_BENCH_DEVICE_TIME', '0')
    line = bench._attach_device_time({'metric': 'm'},
                                     lambda: (_ for _ in ()).throw(
                                         AssertionError('must not run')))
    assert 'device_ms_per_step' not in line


def test_bench_only_typo_runs_nothing(capsys, monkeypatch):
    monkeypatch.setenv('PTPU_BENCH_ONLY', 'berts, resnetx')
    rc = bench.main()
    assert rc == 0
    lines = _lines(capsys)
    # unknown tokens surface as error lines and NO benchmark runs — a typo
    # must not burn TPU time on the full suite
    assert {l['metric'] for l in lines} == {'berts', 'resnetx'}
    assert all('error' in l for l in lines)
