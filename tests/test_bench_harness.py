"""Bench harness hardening tests (no real benchmarks run here).

The r3 driver artifact was destroyed by one transient axon-tunnel flake
(VERDICT r3 weak #1): an uncaught INTERNAL remote_compile error crashed the
headline ResNet run. These tests pin the contract that can never lose the
headline again: per-metric isolation, transient retry with backoff, exit 0
always, headline printed first (insurance) and last (driver parse).

Reference analogue: benchmark/fluid/fluid_benchmark.py:139 prints every
metric it measures.
"""
import json
import sys

import bench


def _lines(capsys):
    out = capsys.readouterr().out
    return [json.loads(l) for l in out.splitlines() if l.strip()]


def test_transient_classifier():
    assert bench.is_transient(RuntimeError(
        'INTERNAL: http://127.0.0.1:8113/remote_compile: read body: '
        'response body closed before all bytes were read'))
    assert bench.is_transient(RuntimeError('UNAVAILABLE: Socket closed'))
    assert not bench.is_transient(ValueError('shape mismatch (3,) vs (4,)'))


def test_retry_transient_then_succeed():
    calls = []

    def flaky():
        calls.append(1)
        if len(calls) < 3:
            raise RuntimeError('INTERNAL: remote_compile: read body')
        return {'metric': 'm', 'value': 1.0}

    naps = []
    out = bench.run_metric('m', flaky, retries=3, backoff_s=1,
                           sleep=naps.append)
    assert out == {'metric': 'm', 'value': 1.0}
    assert len(calls) == 3
    assert naps == [1, 2]  # exponential backoff


def test_no_retry_on_non_transient():
    calls = []

    def broken():
        calls.append(1)
        raise ValueError('bad shape')

    out = bench.run_metric('m', broken, sleep=lambda s: None)
    assert len(calls) == 1
    assert out['metric'] == 'm' and 'bad shape' in out['error']
    assert out['transient'] is False


def test_retries_exhausted_yields_error_line():
    def always_flaky():
        raise RuntimeError('INTERNAL: remote_compile flake')

    out = bench.run_metric('m', always_flaky, retries=3, sleep=lambda s: None)
    assert out['attempts'] == 3 and out['transient'] is True
    assert 'remote_compile' in out['error']


def test_main_headline_first_and_last(capsys):
    benches = [
        ('headline', lambda: {'metric': 'headline', 'value': 10.0}),
        ('secondary', lambda: {'metric': 'secondary', 'value': 5.0}),
    ]
    rc = bench.main(benches)
    assert rc == 0
    lines = _lines(capsys)
    # headline printed immediately (insurance) AND re-printed last (driver
    # parses the final JSON line as the headline)
    assert lines[0]['metric'] == 'headline'
    assert lines[-1]['metric'] == 'headline'
    assert any(l['metric'] == 'secondary' for l in lines)


def test_main_survives_injected_fault(capsys):
    def dead_secondary():
        raise RuntimeError('INTERNAL: remote_compile: read body')

    benches = [
        ('headline', lambda: {'metric': 'headline', 'value': 10.0}),
        ('secondary', dead_secondary),
    ]
    # retries sleep 5/10s by default — patch backoff out via run_metric's
    # seam by monkeying time.sleep is avoided; the fault is non-recoverable
    # so just accept the ~15s... no: keep the test fast by patching sleep.
    orig_sleep = bench.time.sleep
    bench.time.sleep = lambda s: None
    try:
        rc = bench.main(benches)
    finally:
        bench.time.sleep = orig_sleep
    assert rc == 0
    lines = _lines(capsys)
    errs = [l for l in lines if 'error' in l]
    assert errs and errs[0]['metric'] == 'secondary'
    assert lines[-1]['metric'] == 'headline'  # headline survived the fault


def test_main_headline_fault_still_exits_zero(capsys):
    def dead_headline():
        raise ValueError('model build broke')

    benches = [
        ('headline', dead_headline),
        ('secondary', lambda: {'metric': 'secondary', 'value': 5.0}),
    ]
    rc = bench.main(benches)
    assert rc == 0
    lines = _lines(capsys)
    assert 'error' in lines[0] and lines[0]['metric'] == 'headline'
    # the headline's ERROR line is re-printed last: the driver must see an
    # explicit headline failure, never a secondary metric mislabeled as
    # the headline
    assert lines[-1]['metric'] == 'headline' and 'error' in lines[-1]
    assert any(l['metric'] == 'secondary' and 'error' not in l
               for l in lines)


def test_bench_only_typo_runs_nothing(capsys, monkeypatch):
    monkeypatch.setenv('PTPU_BENCH_ONLY', 'berts, resnetx')
    rc = bench.main()
    assert rc == 0
    lines = _lines(capsys)
    # unknown tokens surface as error lines and NO benchmark runs — a typo
    # must not burn TPU time on the full suite
    assert {l['metric'] for l in lines} == {'berts', 'resnetx'}
    assert all('error' in l for l in lines)
