"""Speculative decoding (ISSUE 17): greedy bit-identity of
draft-and-verify decode vs plain decode across the slot/block and
f32/int8 tiers, mixed draft/no-draft/beam ticks, rejected-tail cache
invisibility and block rollback, EOS/max_new truncation inside the
draft window, acceptance stats, drafter units, and fresh-subprocess
warm start with zero XLA compiles over the verify sidecar."""
import json
import os
import shutil
import subprocess
import sys

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu.inference import (DecodingPredictor, DraftModelDrafter,
                                  NgramDrafter, export_decode)
from paddle_tpu.inference.kv_blocks import BlockManager

VOCAB, SLOTS, CACHE, K = 37, 4, 64, 4


def _build(tmp, **kw):
    from models.transformer import build_decode_spec
    scope = fluid.core.Scope()
    with fluid.scope_guard(scope), fluid.unique_name.guard():
        spec = build_decode_spec(
            vocab=VOCAB, d_model=16, n_head=2, n_layer=2, d_ff=32,
            max_slots=SLOTS, max_cache_len=CACHE, eos_id=1, **kw)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(spec['startup'])
        export_decode(spec, tmp, scope=scope)
    return tmp


@pytest.fixture(scope='module')
def arts(tmp_path_factory):
    """draft_k=K artifacts of the same tiny LM across all four KV
    tiers, plus one verify-less artifact for the negative tests."""
    t = tmp_path_factory.mktemp('spec')
    return {
        'slot': _build(str(t / 'slot'), prompt_buckets=(4, 8), draft_k=K),
        'block': _build(str(t / 'block'), prompt_buckets=(4, 8),
                        block_size=4, draft_k=K),
        'slot8': _build(str(t / 'slot8'), prompt_buckets=(4, 8),
                        kv_cache_dtype='int8', draft_k=K),
        'block8': _build(str(t / 'block8'), prompt_buckets=(4, 8),
                         block_size=4, kv_cache_dtype='int8', draft_k=K),
        'plain': _build(str(t / 'plain'), prompt_buckets=(4,)),
    }


def _prompts(seed, n):
    """Alternating self-repetitive (the n-gram drafter fires) and
    random (no draft — the slot rides the plain step) prompts."""
    rng = np.random.RandomState(seed)
    out = []
    for i in range(n):
        if i % 2 == 0:
            pat = rng.randint(2, VOCAB, 2)
            plen = int(rng.randint(4, 9))
            out.append(np.tile(pat, plen)[:plen])
        else:
            out.append(rng.randint(2, VOCAB, int(rng.randint(2, 9))))
    return out


class _ScriptedDrafter(object):
    """Proposes a fixed token sequence regardless of context — the
    zero/low-acceptance adversary for rejection-path tests."""

    def __init__(self, toks):
        self._toks = [int(t) for t in toks]

    def draft(self, tokens, k):
        return self._toks[:k]


class _OracleDrafter(object):
    """Proposes the known-true continuation of a transcript recorded
    from a plain run — deterministic full acceptance."""

    def __init__(self):
        self.full = {}

    def remember(self, prompt, out):
        key = tuple(int(t) for t in prompt)
        self.full[key] = list(key) + [int(t) for t in out]

    def draft(self, tokens, k):
        toks = [int(t) for t in tokens]
        for full in self.full.values():
            if full[:len(toks)] == toks:
                return full[len(toks):len(toks) + k]
        return []


# -- artifact layout ---------------------------------------------------------

def test_verify_artifact_layout(arts):
    from paddle_tpu.inference import decoding
    for name in ('slot', 'block', 'slot8', 'block8'):
        with open(os.path.join(arts[name],
                               decoding._DECODE_SIGNATURE)) as f:
            sig = json.load(f)
        assert sig['version'] == 3
        ver = sig['verify']
        assert ver['draft_k'] == K
        assert (sorted(e['name'] for e in ver['feeds']) ==
                sorted(e['name'] for e in sig['step']['feeds']))
        d = os.path.join(arts[name], decoding._VERIFY_DIR)
        assert os.path.exists(os.path.join(d, 'module.jaxexport'))
        # export-time AOT warm-start sidecar, same as the step program
        assert os.path.exists(os.path.join(d, 'aot_cpu.jaxexec'))
    with open(os.path.join(arts['plain'],
                           decoding._DECODE_SIGNATURE)) as f:
        sig = json.load(f)
    assert 'verify' not in sig
    assert not os.path.exists(os.path.join(arts['plain'],
                                           decoding._VERIFY_DIR))


# -- greedy bit-identity -----------------------------------------------------

@pytest.mark.parametrize('name', ['slot', 'block', 'slot8', 'block8'])
def test_spec_bit_identity_all_tiers(arts, name):
    """The ISSUE 17 bar: speculative greedy transcripts are
    BIT-IDENTICAL to plain decode on every KV tier, with real
    acceptance happening (not vacuous all-rejected runs)."""
    prompts = _prompts(17, 6)
    with DecodingPredictor(arts[name]) as pp:
        want = [pp.generate(p, max_new_tokens=10) for p in prompts]
    with DecodingPredictor(arts[name], draft='ngram') as ps:
        ps.stats.reset()
        streams = [ps.submit(p, max_new_tokens=10) for p in prompts]
        got = [s.result(120) for s in streams]
        snap = ps.stats.snapshot()
    assert got == want
    assert snap['verify_steps'] > 0 and snap['drafted'] > 0


def test_mixed_draft_nodraft_and_beam_tick(arts):
    """Drafted slots ride the verify program, undrafted slots the plain
    step, and a beam request (never drafted) decodes alongside — all in
    the same scheduler loop, all bit-identical to plain serving."""
    prompts = _prompts(23, 8)
    with DecodingPredictor(arts['slot']) as pp:
        want = [pp.generate(p, max_new_tokens=10) for p in prompts]
        want_ids, want_scores = pp.generate(prompts[1],
                                            max_new_tokens=8, beam=3)
    with DecodingPredictor(arts['slot'], draft='ngram') as ps:
        ps.stats.reset()
        streams = [ps.submit(p, max_new_tokens=10) for p in prompts]
        got = [s.result(120) for s in streams]
        ids, scores = ps.generate(prompts[1], max_new_tokens=8, beam=3)
        snap = ps.stats.snapshot()
    assert got == want
    np.testing.assert_array_equal(ids, want_ids)
    np.testing.assert_array_equal(scores, want_scores)
    assert snap['drafted'] > 0


# -- rejection path ----------------------------------------------------------

@pytest.mark.parametrize('name', ['block', 'block8'])
def test_rejected_tail_invisible_and_rolled_back(arts, name):
    """An adversarial drafter forces rejections every tick: the
    speculatively written KV past the accepted frontier must never be
    attended (transcripts stay bit-identical), and the blocks grown for
    the rejected tail must roll back to the pool (no leak)."""
    prompts = _prompts(29, 5)
    with DecodingPredictor(arts[name]) as pp:
        want = [pp.generate(p, max_new_tokens=12) for p in prompts]
    with DecodingPredictor(arts[name],
                           draft=_ScriptedDrafter([2, 3, 4, 2])) as ps:
        ps.stats.reset()
        got = [ps.generate(p, max_new_tokens=12) for p in prompts]
        # same prompts again: prefix-cache reuse over rolled-back
        # tables must still match
        again = [ps.generate(p, max_new_tokens=12) for p in prompts]
        snap = ps.stats.snapshot()
        bm = ps.block_manager
        bm.evict_all_prefixes()
        assert bm.in_use() == 0, 'speculative blocks leaked'
    assert got == want and again == want
    assert snap['drafted'] > 0
    assert snap['accepted'] < snap['drafted'], \
        'adversarial drafter was never rejected — vacuous test'


def test_truncation_inside_draft_window(arts):
    """max_new_tokens smaller than the draft window: emission must stop
    exactly where plain decode stops, never overshooting on accepted
    draft tokens."""
    prompts = _prompts(31, 6)
    with DecodingPredictor(arts['slot']) as pp, \
            DecodingPredictor(arts['slot'], draft='ngram') as ps:
        for max_new in (1, 2, 3):
            want = [pp.generate(p, max_new_tokens=max_new)
                    for p in prompts]
            got = [ps.generate(p, max_new_tokens=max_new)
                   for p in prompts]
            assert got == want
            assert all(len(g) <= max_new for g in got)


def test_eos_semantics_match_plain(arts):
    """EOS truncation is host-side (`g == eos` breaks the acceptance
    walk): re-point the predictor's eos at a token the tiny model
    actually emits, then spec — including an oracle drafter that
    PROPOSES the EOS mid-window — must stop exactly where plain does."""
    prompts = _prompts(43, 8)
    with DecodingPredictor(arts['slot']) as pp:
        base = [pp.generate(p, max_new_tokens=12) for p in prompts]
    toks = [t for w in base for t in w]
    eos = max(set(toks), key=toks.count)
    with DecodingPredictor(arts['slot']) as pp:
        pp._eos = eos
        want = [pp.generate(p, max_new_tokens=12) for p in prompts]
    assert any(len(w) < 12 and w[-1] == eos for w in want), \
        'eos never fired early — vacuous test'
    oracle = _OracleDrafter()
    for p, w in zip(prompts, want):
        oracle.remember(p, w)
    for drafter in ('ngram', oracle):
        with DecodingPredictor(arts['slot'], draft=drafter) as ps:
            ps._eos = eos
            got = [ps.generate(p, max_new_tokens=12) for p in prompts]
        assert got == want


# -- stats -------------------------------------------------------------------

def test_acceptance_stats(arts):
    oracle = _OracleDrafter()
    prompts = _prompts(37, 4)
    with DecodingPredictor(arts['slot']) as pp:
        pp.stats.reset()
        want = [pp.generate(p, max_new_tokens=10) for p in prompts]
        plain_snap = pp.stats.snapshot()
        for p, w in zip(prompts, want):
            oracle.remember(p, w)
    # plain serving: ratios identically 1.0, no drafting counted
    assert plain_snap['drafted'] == 0 and plain_snap['accepted'] == 0
    assert plain_snap['acc_rate'] == 1.0
    assert plain_snap['tokens_per_dispatch'] == 1.0
    with DecodingPredictor(arts['slot'], draft=oracle) as ps:
        ps.stats.reset()
        got = [ps.generate(p, max_new_tokens=10) for p in prompts]
        snap = ps.stats.snapshot()
    assert got == want
    assert snap['verify_steps'] > 0
    assert 0 < snap['accepted'] <= snap['drafted']
    assert snap['acc_rate'] == round(snap['accepted'] / snap['drafted'],
                                     4)
    if all(1 not in w for w in want):
        # an oracle drafter accepts everything it proposes (an EOS
        # inside the window legitimately truncates acceptance)
        assert snap['acc_rate'] == 1.0
    assert snap['tokens_per_dispatch'] > 1.0


def test_serving_report_spec_columns(arts, capsys):
    from paddle_tpu import profiler
    with DecodingPredictor(arts['slot'], draft='ngram') as ps:
        ps.generate(np.tile([5, 9], 4), max_new_tokens=8)
        out = profiler.serving_report()
        name = [k for k in out if k.startswith('decode:')]
        assert name, out
        snap = out[name[0]]
    for key in ('acc_rate', 'tokens_per_dispatch', 'verify_steps'):
        assert key in snap
    text = capsys.readouterr().out
    assert 'acc' in text and 'tok/d' in text


# -- token delivery ----------------------------------------------------------

def test_tokenstream_batches_coalesce(arts):
    """A verify tick that accepts tokens delivers them as ONE batch on
    the stream; plain decode delivers singletons."""
    oracle = _OracleDrafter()
    prompt = np.asarray([3, 4, 5, 6], np.int64)
    with DecodingPredictor(arts['slot']) as pp:
        want = pp.generate(prompt, max_new_tokens=10)
        st = pp.submit(prompt, max_new_tokens=10)
        plain_batches = list(st.batches())
    oracle.remember(prompt, want)
    assert all(len(b) == 1 for b in plain_batches)
    assert [t for b in plain_batches for t in b] == want
    with DecodingPredictor(arts['slot'], draft=oracle) as ps:
        st = ps.submit(prompt, max_new_tokens=10)
        batches = list(st.batches())
    assert [t for b in batches for t in b] == want
    assert any(len(b) > 1 for b in batches), \
        'oracle-drafted decode never coalesced a delivery'


# -- drafters ----------------------------------------------------------------

def test_ngram_drafter_unit():
    d = NgramDrafter()
    # longest suffix wins; continuation follows the matched site
    assert d.draft([5, 6, 7, 5, 6], 3) == [7, 5, 6]
    # the MOST RECENT earlier occurrence predicts (8, not 9)
    assert d.draft([1, 2, 9, 1, 2, 8, 1, 2], 1) == [8]
    # 1-gram fallback by default...
    assert d.draft([1, 2, 3, 1], 2) == [2, 3]
    # proposals extend periodically past the transcript's end
    assert d.draft([5, 6, 5, 6], 4) == [5, 6, 5, 6]
    # ...suppressed by min_ngram
    assert NgramDrafter(min_ngram=2).draft([1, 2, 3, 1], 2) == []
    # no repetition, degenerate inputs -> no proposal
    assert d.draft([1, 2, 3, 4], 3) == []
    assert d.draft([7], 3) == []
    assert d.draft([5, 6, 7, 5, 6], 0) == []
    with pytest.raises(ValueError):
        NgramDrafter(min_ngram=0)
    with pytest.raises(ValueError):
        NgramDrafter(max_ngram=2, min_ngram=3)


def test_draft_model_drafter(arts):
    """A draft artifact (here: the target itself — proposals match the
    target argmax, so acceptance is high) plugged in as the drafter."""
    prompts = _prompts(41, 4)
    with DecodingPredictor(arts['block']) as pp:
        want = [pp.generate(p, max_new_tokens=8) for p in prompts]
    with DecodingPredictor(arts['block']) as dp, \
            DecodingPredictor(arts['block'],
                              draft=DraftModelDrafter(dp)) as ps:
        ps.stats.reset()
        got = [ps.generate(p, max_new_tokens=8) for p in prompts]
        snap = ps.stats.snapshot()
    assert got == want
    assert snap['accepted'] > 0
    with pytest.raises(ValueError):
        DraftModelDrafter(object())


def test_draft_validation(arts):
    with pytest.raises(ValueError):
        DecodingPredictor(arts['plain'], draft='ngram')
    for bad_k in (0, K + 1):
        with pytest.raises(ValueError):
            DecodingPredictor(arts['slot'], draft='ngram',
                              draft_k=bad_k)
    # draft_k below the artifact's K narrows the window
    with DecodingPredictor(arts['slot'], draft='ngram',
                           draft_k=2) as ps:
        out = ps.generate(np.tile([5, 9], 4), max_new_tokens=8)
    with DecodingPredictor(arts['slot']) as pp:
        assert pp.generate(np.tile([5, 9], 4), max_new_tokens=8) == out


# -- allocator unit ----------------------------------------------------------

def test_blockmanager_rollback_unit():
    m = BlockManager(num_blocks=9, block_size=4)
    table = m.alloc(4)
    assert m.in_use() == 4
    # 9 tokens span 3 blocks: one speculative tail block returns
    assert m.rollback(table, 9) == 1
    assert len(table) == 3 and m.in_use() == 3
    # nothing past the keep point -> no-op
    assert m.rollback(table, 12) == 0
    assert m.rollback(table, 0) == 3
    assert table == [] and m.in_use() == 0


# -- warm start --------------------------------------------------------------

def test_warm_fresh_subprocess_zero_compiles(arts, tmp_path):
    """cache_ctl prewarm learns the verify program: strip every AOT
    sidecar from a copy, prewarm via the CLI, then a fresh speculative
    serving process must perform ZERO XLA compiles and match the
    in-process transcripts."""
    art = str(tmp_path / 'art')
    shutil.copytree(arts['slot'], art)
    stripped = 0
    for root, _dirs, files in os.walk(art):
        for f in files:
            if f.startswith('aot_') and f.endswith('.jaxexec'):
                os.remove(os.path.join(root, f))
                stripped += 1
    assert stripped > 0
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ, JAX_PLATFORMS='cpu', PTPU_PLATFORM='cpu')
    out = subprocess.run(
        [sys.executable, os.path.join(repo, 'tools', 'cache_ctl.py'),
         'prewarm', art], capture_output=True, text=True, env=env,
        timeout=600)
    assert out.returncode == 0, out.stderr
    from paddle_tpu.inference import decoding
    assert os.path.exists(os.path.join(art, decoding._VERIFY_DIR,
                                       'aot_cpu.jaxexec'))
    worker = os.path.join(os.path.dirname(__file__),
                          'spec_decode_worker.py')
    out = subprocess.run(
        [sys.executable, worker, art, '23', '4', '8'],
        capture_output=True, text=True, env=env, timeout=300)
    assert out.returncode == 0, out.stderr
    assert 'SPEC_OK' in out.stdout
    payload = json.loads(
        [l for l in out.stdout.splitlines()
         if l.startswith('SPEC ')][0][len('SPEC '):])
    assert payload['compiles'] == 0, payload
    assert payload['verify_steps'] > 0 and payload['drafted'] > 0
    # replicate the worker's prompts in-process and compare transcripts
    rng = np.random.RandomState(23)
    prompts = []
    for _ in range(4):
        pat = rng.randint(2, VOCAB, 2)
        plen = int(rng.randint(4, 9))
        prompts.append(np.tile(pat, plen)[:plen])
    with DecodingPredictor(arts['slot'], draft='ngram') as ps:
        want = [ps.submit(p, max_new_tokens=8) for p in prompts]
        want = [s.result(120) for s in want]
    assert payload['greedy'] == want
