"""Dataflow analysis engine tests (paddle_tpu/passes/dataflow.py):
def-use chains + last-writer resolution (incl. sub-block scope walks),
live intervals, hazard classes, the peak-memory estimator and its
per-bucket/export wiring, the memory_optimize liveness report, the
donation-safety certifier, the certified warm-donation path
(fresh-subprocess bit-identity A/B), and the program_doctor /
program_lint --json CLIs."""
import importlib.util
import json
import os
import subprocess
import sys

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import unique_name
from paddle_tpu.passes import dataflow, verify_program

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------------------
# builders
# ---------------------------------------------------------------------------
def _dense_net(seed=11):
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = seed
    with fluid.program_guard(main, startup), unique_name.guard():
        x = fluid.layers.data(name='x', shape=[6], dtype='float32')
        label = fluid.layers.data(name='y', shape=[1], dtype='int64')
        h = fluid.layers.fc(x, size=16, act='relu')
        logits = fluid.layers.fc(h, size=4)
        loss = fluid.layers.mean(
            fluid.layers.softmax_with_cross_entropy(logits=logits,
                                                    label=label))
        probs = fluid.layers.softmax(logits)
        acc = fluid.layers.accuracy(input=probs, label=label)
        fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
    return main, startup, loss, acc


def _while_net():
    """Counter loop: while i < 5: s = s + i; i += 1 — one sub-block."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup), unique_name.guard():
        i = fluid.layers.fill_constant([1], 'int64', 0)
        n = fluid.layers.fill_constant([1], 'int64', 5)
        s = fluid.layers.fill_constant([1], 'int64', 0)
        cond = fluid.layers.less_than(i, n)
        w = fluid.layers.While(cond)
        with w.block():
            s2 = fluid.layers.elementwise_add(s, i)
            fluid.layers.assign(s2, s)
            fluid.layers.increment(i)
            fluid.layers.less_than(i, n, cond=cond)
    return main, s


# ---------------------------------------------------------------------------
# def-use / last-writer
# ---------------------------------------------------------------------------
def test_def_use_chains_and_last_writer():
    main, _, loss, acc = _dense_net()
    dfa = dataflow.analyze_program(main, feed_names=['x', 'y'],
                                   fetch_names=[loss.name])
    defs, uses = dfa.def_use(loss.name)
    assert len(defs) == 1 and uses, (defs, uses)
    # the loss's single def is its last writer seen from program end
    assert dfa.last_writer(loss.name) == defs[0]
    # a param is a program input: last writer before its optimizer
    # update resolves to -1, after it to the sgd op
    w = 'fc_0.w_0'
    wdefs, wuses = dfa.def_use(w)
    assert wdefs, 'optimizer must write the param'
    assert dfa.last_writer(w, before=wdefs[0]) == -1
    assert dfa.last_writer(w) == wdefs[-1]
    # never-touched name
    assert dfa.last_writer('no_such_var') is None


def test_last_writer_at_walks_sub_block_scope():
    main, s = _while_net()
    dfa = dataflow.analyze_program(main)
    sub_idx = next(idx for idx in range(1, main.num_blocks))
    sub = main.block(sub_idx)
    # inside the body, reading `s` at op 0 resolves through the parent
    # chain (the owning while op models the loop carry)
    got = dfa.last_writer_at(sub_idx, 0, s.name)
    assert got is not None and got != -1
    blk, op_idx = got
    assert blk in (0, sub_idx)
    # a body-local temp read after its in-block def resolves locally
    local = next(n for n in sub.vars
                 if dfa.block_defs.get((sub_idx, n)))
    d0 = dfa.block_defs[(sub_idx, local)][0]
    assert dfa.last_writer_at(sub_idx, d0 + 1, local) == (sub_idx, d0)


# ---------------------------------------------------------------------------
# live intervals / memory / reuse
# ---------------------------------------------------------------------------
def test_live_intervals_shape():
    main, _, loss, acc = _dense_net()
    dfa = dataflow.analyze_program(main, feed_names=['x', 'y'],
                                   fetch_names=[loss.name])
    iv = dfa.live_intervals()
    n_ops = len(main.global_block().ops)
    # fetch target lives to program end
    assert iv[loss.name][1] == n_ops
    # persistables live to program end and start as inputs
    assert iv['fc_0.w_0'] == (-1, n_ops)
    # a pure temp is born at its def and dies at its last use, strictly
    # inside the program
    s, e = iv['fc_0.tmp_0']
    assert 0 <= s <= e < n_ops


def test_peak_memory_scales_with_batch_and_buckets():
    main, _, loss, acc = _dense_net()
    dfa = dataflow.analyze_program(main, feed_names=['x', 'y'],
                                   fetch_names=[loss.name])
    e1 = dfa.peak_memory(batch=1)
    e64 = dfa.peak_memory(batch=64)
    assert e64.peak_bytes > e1.peak_bytes
    assert e64.params_bytes == e1.params_bytes  # static state
    assert e1.peak_op_index >= 0 and e1.peak_op_type
    assert e1.top and all('name' in t and t['bytes'] > 0 for t in e1.top)
    per = dfa.peak_memory_per_bucket([1, 8, 64])
    assert set(per) == {1, 8, 64}
    assert per[8].peak_bytes < per[64].peak_bytes
    d = e1.as_dict()
    assert d['peak_bytes'] == e1.peak_bytes


def test_reuse_report_accounting():
    main, _, loss, acc = _dense_net()
    dfa = dataflow.analyze_program(main, feed_names=['x', 'y'],
                                   fetch_names=[loss.name])
    r = dfa.reuse_report(batch=32)
    assert r['temps_total_bytes'] >= r['temps_peak_bytes'] > 0
    assert r['reusable_bytes'] == (r['temps_total_bytes']
                                   - r['temps_peak_bytes'])
    for p in r['pairs']:
        # each pair: disjoint live intervals, same byte size
        iv = dfa.live_intervals()
        assert iv[p['of']][1] < iv[p['reuse']][0]


def test_var_bytes_dtypes():
    class V(object):
        def __init__(self, shape, dtype):
            self.shape, self.dtype = shape, dtype
    assert dataflow.var_bytes(V((4, 8), 'float32')) == (128, False)
    assert dataflow.var_bytes(V((-1, 8), 'bfloat16'), batch=4) == (64,
                                                                   True)
    assert dataflow.var_bytes(V(None, 'float32')) == (0, False)


# ---------------------------------------------------------------------------
# hazards
# ---------------------------------------------------------------------------
def test_hazard_aliased_input_is_error():
    main, _, loss, acc = _dense_net()
    hz = dataflow.analyze_program(
        main, feed_names=['x', 'fc_0.w_0'],
        fetch_names=[loss.name]).hazards()
    errs = [h for h in hz if h.level == 'error']
    assert errs and errs[0].code == 'aliased-input'
    assert errs[0].var == 'fc_0.w_0'


def test_hazard_double_write_and_war():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup), unique_name.guard():
        a = fluid.layers.fill_constant([2], 'float32', 1.0)
        b = fluid.layers.scale(a, scale=2.0)        # reads a
        # rebind a AFTER b read it: write-after-read (info)
        fluid.layers.assign(b, a)
        # dead write: c bound twice, first binding never read
        c = fluid.layers.fill_constant([2], 'float32', 3.0)
        main.global_block().append_op(
            type='assign', inputs={'X': [b.name]},
            outputs={'Out': [c.name]}, infer_shape=False)
    dfa = dataflow.analyze_program(main, fetch_names=[c.name])
    codes = {h.code: h for h in dfa.hazards()}
    assert 'war' in codes and codes['war'].level == 'info'
    assert 'double-write' in codes \
        and codes['double-write'].level == 'warn'
    # the verifier surfaces the dead write as a warn diagnostic
    diags = verify_program(main, fetch_names=[c.name])
    assert any(d.code == 'double-write' and d.level == 'warn'
               for d in diags)


def test_verifier_dead_persistable_warn():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup), unique_name.guard():
        x = fluid.layers.data(name='x', shape=[4], dtype='float32')
        y = fluid.layers.fc(x, size=2)
        main.global_block().create_var(
            name='orphan_state', shape=(4,), dtype='float32',
            persistable=True)
    diags = verify_program(main, fetch_names=[y.name])
    hits = [d for d in diags if d.code == 'dead-persistable']
    assert hits and hits[0].var == 'orphan_state' \
        and hits[0].level == 'warn'
    # parameters the program reads never warn
    assert not any(d.code == 'dead-persistable' and 'fc_0' in (d.var or
                                                               '')
                   for d in diags)


# ---------------------------------------------------------------------------
# sub-block use-before-def (satellite: verifier upgrade)
# ---------------------------------------------------------------------------
def test_sub_block_use_before_def_flagged():
    main, s = _while_net()
    sub = next(b for b in main.blocks if b.idx != 0)
    # corrupt the body: make its first op read a body-local temp that is
    # only produced later in the body
    local = sub.ops[0].output_arg_names()[0]
    reader = sub.ops[0]
    producer_idx = 0
    op = sub.ops.pop(producer_idx)
    sub.ops.append(op)   # producer now AFTER its consumers
    diags = verify_program(main, fetch_names=[s.name], level='fast')
    ubd = [d for d in diags if d.code == 'use-before-def'
           and d.block == sub.idx]
    assert ubd, 'expected sub-block use-before-def in %s' % diags
    assert all(d.level == 'error' for d in ubd)


def test_sub_block_clean_while_and_rnn_verify():
    main, s = _while_net()
    diags = verify_program(main, fetch_names=[s.name])
    assert [d for d in diags if d.level == 'error'] == []

    # StaticRNN: inner bindings (step inputs, memory pre) come from the
    # owning op's attrs — order-exact checking must accept them
    main2, startup2 = fluid.Program(), fluid.Program()
    with fluid.program_guard(main2, startup2), unique_name.guard():
        x = fluid.layers.data(name='x', shape=[3, 8], dtype='float32')
        xt = fluid.layers.transpose(x, perm=[1, 0, 2])
        rnn = fluid.layers.StaticRNN()
        with rnn.step():
            xi = rnn.step_input(xt)
            mem = rnn.memory(shape=[-1, 8], batch_ref=xi)
            h = fluid.layers.elementwise_add(mem, xi)
            rnn.update_memory(mem, h)
            rnn.step_output(h)
        out = rnn()
    diags2 = verify_program(main2, fetch_names=[out[0].name]
                            if isinstance(out, (list, tuple))
                            else [out.name])
    assert [d for d in diags2 if d.level == 'error'] == []


# ---------------------------------------------------------------------------
# donation certifier
# ---------------------------------------------------------------------------
def test_certifier_accepts_run_steps_state():
    main, _, loss, acc = _dense_net()
    plan = dataflow.donation_plan(main, feed_names=['x', 'y'],
                                  fetch_names=[loss.name])
    assert plan.safe and plan.donate and plan.bytes > 0
    assert set(plan.donate) <= dataflow.analyze_program(
        main).persistables


def test_certifier_rejects_caller_visible_alias():
    main, _, loss, acc = _dense_net()
    state = sorted(dataflow.analyze_program(main).persistables)
    # fed persistable: caller-visible aliased input
    cert = dataflow.certify_donation(main, state,
                                     feed_names=['x', state[0]],
                                     fetch_names=[loss.name])
    assert not cert.safe and cert.donate == ()
    assert any('aliased input' in r for r in cert.reasons)
    # fetched state: the returned array would alias a donated buffer
    cert2 = dataflow.certify_donation(main, state, feed_names=['x'],
                                      fetch_names=[state[0]])
    assert not cert2.safe
    assert any('alias of a donated state buffer' in r
               for r in cert2.reasons)
    # mesh programs never donate
    cert3 = dataflow.certify_donation(main, state, feed_names=['x'],
                                      fetch_names=[loss.name], mesh=True)
    assert not cert3.safe and any('mesh' in r for r in cert3.reasons)
    # non-persistable state name
    cert4 = dataflow.certify_donation(main, state + ['fc_0.tmp_0'],
                                      feed_names=['x'],
                                      fetch_names=[loss.name])
    assert not cert4.safe


def test_executor_records_certificates(tmp_path):
    main, startup, loss, acc = _dense_net()
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.core.Scope()
    feed = {'x': np.random.RandomState(0).randn(4, 6).astype(np.float32),
            'y': np.zeros((4, 1), np.int64)}
    with fluid.scope_guard(scope):
        exe.run(startup)
        exe.run(main, feed=feed, fetch_list=[loss])
    cert = exe._donation_certs[main._uid]
    assert cert.safe, cert.reasons
    # fetching a param makes the boundary unsafe — certificate flips
    with fluid.scope_guard(scope):
        exe.run(main, feed=feed, fetch_list=[loss, 'fc_0.w_0'])
    cert2 = exe._donation_certs[main._uid]
    assert not cert2.safe


# ---------------------------------------------------------------------------
# the certified warm-donation path: fresh-subprocess bit-identity A/B
# ---------------------------------------------------------------------------
def _run_donation_worker(cache_dir, out_npz, env_extra=None):
    env = dict(os.environ)
    env.update(env_extra or {})
    p = subprocess.run(
        [sys.executable, os.path.join(REPO, 'tests',
                                      'donation_worker.py'),
         str(cache_dir), str(out_npz)],
        capture_output=True, text=True, env=env, cwd=REPO)
    assert p.returncode == 0 and 'DONATION_OK' in p.stdout, \
        p.stdout + p.stderr
    line = next(l for l in p.stdout.splitlines()
                if l.startswith('DONATION_STATS '))
    return json.loads(line[len('DONATION_STATS '):])


def test_warm_donation_bit_identity_and_copy_elimination(tmp_path):
    """The ISSUE 7 acceptance bar: warm-started run_steps with certified
    donation performs zero compiles, stays bit-identical to both the
    cold and the undonated paths, and measurably updates state in place
    (the round-8 extra copy is gone) wherever the backend honors
    donation at all."""
    cache = str(tmp_path / 'cache')
    cold = _run_donation_worker(cache, tmp_path / 'cold.npz')
    warm = _run_donation_worker(cache, tmp_path / 'warm.npz')
    nodon = _run_donation_worker(
        str(tmp_path / 'cache2'), tmp_path / 'nodon.npz',
        {'PTPU_WARM_DONATION': '0'})

    assert cold['cert_safe'] and cold['donated_entries'] >= 1
    assert warm['exec_hits'] >= 2 and warm['misses'] == 0
    assert warm['xla_compiles_net'] == 0
    assert not nodon['cert_safe'] and nodon['donated_entries'] == 0
    assert nodon['aliased_state'] == 0
    if cold['aliased_state']:  # backend honors donation: copy is gone
        assert warm['aliased_state'] >= cold['aliased_state']
        assert warm['old_deleted'] > 0

    a = {k: v for k, v in np.load(tmp_path / 'cold.npz').items()}
    for name in ('warm.npz', 'nodon.npz'):
        b = np.load(tmp_path / name)
        assert set(a) == set(b.files)
        for k in sorted(a):
            assert np.array_equal(a[k], b[k]), (name, k)


def test_warm_donation_survives_host_backed_state(tmp_path):
    """Zero-copy hazard regression: state that re-enters the scope as
    HOST numpy (exactly what a checkpoint restore or io.load does) must
    never be donated in place by a reloaded executable —
    jax.device_put/jnp.asarray of host memory can be zero-copy, and the
    deserialized executable's baked-in aliasing has no external-buffer
    guard (measured pre-fix: NaN then heap corruption on kill-resume).
    The executor copies non-owned leaves at the donated boundary, so a
    mid-run host round-trip of the whole state must be a bit-exact
    no-op."""
    cache = str(tmp_path / 'cache')
    _run_donation_worker(cache, tmp_path / 'cold.npz')
    warm = _run_donation_worker(cache, tmp_path / 'warm.npz')
    reseed = _run_donation_worker(cache, tmp_path / 'reseed.npz',
                                  {'PTPU_DONATION_WORKER_RESEED': '1'})
    assert warm['exec_hits'] >= 2 and reseed['exec_hits'] >= 2
    a = np.load(tmp_path / 'warm.npz')
    b = np.load(tmp_path / 'reseed.npz')
    assert set(a.files) == set(b.files)
    for k in sorted(a.files):
        av, bv = a[k], b[k]
        assert np.isfinite(av).all() if av.dtype.kind == 'f' else True
        assert np.array_equal(av, bv), k


# ---------------------------------------------------------------------------
# memory_optimize liveness report (satellite b)
# ---------------------------------------------------------------------------
def test_memory_optimize_liveness_report():
    from paddle_tpu.passes import PassReport
    main, _, loss, acc = _dense_net()
    report = fluid.memory_optimize(main, fetch_list=[loss], batch=32)
    assert isinstance(report, PassReport)
    assert isinstance(report, dataflow.MemoryOptimizeReport)
    assert report.ops_removed >= 1               # metric branch pruned
    assert report.peak_bytes_before >= report.peak_bytes_after > 0
    assert report.live_ranges and report.batch == 32
    assert report.reuse['reusable_bytes'] >= 0
    d = report.as_dict()
    assert d['memory']['peak_bytes_after'] == report.peak_bytes_after
    assert d['details']['peak_bytes_before'] == report.peak_bytes_before
    json.dumps(d)  # report must stay machine-serializable


# ---------------------------------------------------------------------------
# export bucket estimates (tentpole: per export bucket)
# ---------------------------------------------------------------------------
def test_export_signature_carries_peak_bytes(tmp_path):
    from paddle_tpu.inference import Config, create_predictor
    from paddle_tpu.inference.export import export_compiled
    main, startup, loss, acc = _dense_net()
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.core.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
        logits = 'softmax_0.tmp_0'
        model_dir = str(tmp_path / 'model')
        fluid.io.save_inference_model(
            model_dir, ['x'], [main.global_block().var(logits)], exe,
            main)
    pred = create_predictor(Config(model_dir))
    sample = np.zeros((8, 6), np.float32)
    out_dir = str(tmp_path / 'artifact')
    export_compiled(pred, [sample], out_dir, batch_sizes=[4, 8])
    from paddle_tpu.inference.serve import _BUCKET_DIR
    sigs = {}
    for sub in (_BUCKET_DIR % 4, _BUCKET_DIR % 8, ''):
        with open(os.path.join(out_dir, sub, 'signature.json')) as f:
            sigs[sub] = json.load(f)
    assert sigs[_BUCKET_DIR % 4]['peak_bytes_est'] > 0
    assert sigs[_BUCKET_DIR % 8]['peak_bytes_est'] \
        > sigs[_BUCKET_DIR % 4]['peak_bytes_est']
    # top level mirrors the largest bucket
    assert sigs['']['peak_bytes_est'] == sigs[_BUCKET_DIR % 8][
        'peak_bytes_est']


# ---------------------------------------------------------------------------
# CLIs: program_doctor + program_lint --json
# ---------------------------------------------------------------------------
def _tool(name):
    path = os.path.join(REPO, 'tools', name + '.py')
    spec = importlib.util.spec_from_file_location(name, path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_program_doctor_cli(tmp_path, capsys):
    doctor = _tool('program_doctor')
    main, _, loss, acc = _dense_net()
    main._fetch_names = [loss.name]
    good = tmp_path / 'good.json'
    good.write_bytes(fluid.io.serialize_program(main))
    assert doctor.main([str(good)]) == 0
    human = capsys.readouterr().out
    assert 'peak est' in human and 'donation: SAFE' in human

    # --json: machine report with the full analysis payload
    assert doctor.main([str(good), '--json', '--batch', '16']) == 0
    rep = json.loads(capsys.readouterr().out)
    prog = rep['programs'][0]
    assert prog['errors'] == 0 and prog['peak']['batch'] == 16
    assert prog['donation']['safe'] is True
    assert prog['live_ranges']['temps'] > 0

    # corrupt program: exit 1 with the error surfaced
    bad_main, _, bloss, _ = _dense_net()
    op = next(o for o in bad_main.global_block().ops
              if o.type == 'mul')
    op.inputs['X'] = ['ghost_var']
    bad = tmp_path / 'bad.json'
    bad.write_bytes(fluid.io.serialize_program(bad_main))
    assert doctor.main([str(bad)]) == 1
    capsys.readouterr()
    assert doctor.main([str(tmp_path / 'missing.json')]) == 2
    capsys.readouterr()
    # --json still names the failing input
    assert doctor.main([str(tmp_path / 'missing.json'), '--json']) == 2
    rep = json.loads(capsys.readouterr().out)
    assert rep['failures'] == 1
    assert rep['build_failures'][0]['name'].endswith('missing.json')


def test_program_doctor_baseline_gate(tmp_path, capsys):
    doctor = _tool('program_doctor')
    base = tmp_path / 'baseline.json'
    assert doctor.main(['--models', 'smallnet',
                        '--write-baseline', str(base)]) == 0
    capsys.readouterr()
    # clean re-run passes the gate
    assert doctor.main(['--models', 'smallnet',
                        '--check-baseline', str(base)]) == 0
    capsys.readouterr()
    # a model missing from the baseline is a regression (exit 1)
    assert doctor.main(['--models', 'stacked_lstm',
                        '--check-baseline', str(base)]) == 1
    capsys.readouterr()


def test_checked_in_doctor_baseline_covers_zoo():
    with open(os.path.join(REPO, 'tools', 'doctor_baseline.json')) as f:
        base = json.load(f)
    lint = _tool('program_lint')
    assert set(base['programs']) == set(lint._model_builders())
    for name, entry in base['programs'].items():
        assert entry['errors'] == 0, (name, entry)
        assert entry['donation_safe'] is True, name


def test_program_lint_json_mode(tmp_path, capsys):
    lint = _tool('program_lint')
    main, _, loss, acc = _dense_net()
    good = tmp_path / 'good.json'
    good.write_bytes(fluid.io.serialize_program(main))
    assert lint.main([str(good), '--json']) == 0
    rep = json.loads(capsys.readouterr().out)
    assert rep['errors'] == 0 and rep['failures'] == 0
    assert rep['programs'][0]['ops'] > 0
    # exit-code contract documented in --help
    with pytest.raises(SystemExit):
        lint.main(['--help'])
    help_text = capsys.readouterr().out
    assert 'exit status' in help_text
    assert '1 on any error-level diagnostic' in help_text.replace('\n',
                                                                  ' ')
