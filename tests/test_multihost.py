"""Multi-host execution: 2 simulated hosts x 4 virtual devices.

Port of the reference's test_dist_base methodology
(python/paddle/fluid/tests/unittests/test_dist_base.py:339 _run_cluster):
spawn trainer subprocesses on 127.0.0.1, each joining the distributed
runtime and feeding its local shard; assert both report IDENTICAL losses
(the SPMD program is one global computation — replicated outputs must
agree bit-for-bit across hosts).
"""
import os
import re
import socket
import subprocess
import sys

import numpy as np
import pytest


def _free_port():
    s = socket.socket()
    s.bind(('127.0.0.1', 0))
    port = s.getsockname()[1]
    s.close()
    return port


def test_two_host_bert_dryrun(tmp_path):
    worker = os.path.join(os.path.dirname(__file__), 'multihost_worker.py')
    port = _free_port()
    procs = []
    for pid in range(2):
        env = dict(os.environ)
        env.pop('JAX_PLATFORMS', None)
        env.pop('PTPU_PLATFORM', None)
        env.update({
            'PADDLE_TRAINERS': '2',
            'PADDLE_TRAINER_ID': str(pid),
            'PADDLE_COORDINATOR': '127.0.0.1:%d' % port,
            'XLA_FLAGS': '--xla_force_host_platform_device_count=4',
            'PTPU_MH_CKPT': str(tmp_path / 'mh_ckpt'),
        })
        procs.append(subprocess.Popen(
            [sys.executable, worker], env=env, stdout=subprocess.PIPE,
            stderr=subprocess.PIPE, text=True,
            cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__)))))
    outs = []
    for p in procs:
        out, err = p.communicate(timeout=560)
        assert p.returncode == 0, \
            "worker failed:\nSTDOUT:%s\nSTDERR:%s" % (out, err[-3000:])
        outs.append(out)

    # Gloo's C++ threads interleave log lines into the same stdout fd, so
    # worker markers are extracted by regex, never by line splitting
    losses = {}
    for out in outs:
        m = re.search(r'\bMHLOSSES (\d+)((?: -?\d+\.\d+)+)', out)
        assert m, "missing loss line: %r" % (out,)
        losses[int(m.group(1))] = [float(v) for v in m.group(2).split()]
    assert set(losses) == {0, 1}, "missing loss lines: %r" % (outs,)
    # one global SPMD computation: replicated loss identical on both hosts
    np.testing.assert_allclose(losses[0], losses[1], rtol=0, atol=0)
    assert all(np.isfinite(losses[0]))
    # training moves the loss
    assert losses[0][0] != losses[0][-1]

    # dist save/load: ONLY process 0 writes; BOTH processes load (the
    # broadcast path) and verify restored state bit-for-bit
    saved = {}
    for out in outs:
        m = re.search(r'\bMHSAVED (\d+) (\d+)\b', out)
        assert m, "missing MHSAVED line: %r" % (out,)
        saved[int(m.group(1))] = int(m.group(2))
    assert saved.get(0, 0) > 0, "process 0 wrote nothing: %r" % (outs,)
    assert saved.get(1) == 0, "process 1 must not write: %r" % (outs,)
    assert all('MHLOADOK' in out for out in outs), \
        "broadcast load failed: %r" % (outs,)
