"""Pass & lint subsystem tests (paddle_tpu/passes/): registry + manager
contract, per-pass bit-identity on a dense net and an OCR-style LoD
program, verifier corruption classes, and the consumer wiring
(Executor strict verify, CompiledProgram pipeline, memory_optimize /
InferenceTranspiler reports, io.prune_program, program_lint CLI)."""
import importlib.util
import os

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import passes
from paddle_tpu.passes import (PassManager, PassReport, ProgramVerifyError,
                               registered_passes, verify_program)


# ---------------------------------------------------------------------------
# builders
# ---------------------------------------------------------------------------
def _dense_net(seed=11):
    """Small conv/fc train net with an (unfetched) metric branch and a
    foldable constant chain."""
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = seed
    with fluid.program_guard(main, startup):
        x = fluid.layers.data(name='x', shape=[6], dtype='float32')
        label = fluid.layers.data(name='y', shape=[1], dtype='int64')
        h = fluid.layers.fc(x, size=16, act='relu')
        logits = fluid.layers.fc(h, size=4)
        c = fluid.layers.fill_constant([1, 4], 'float32', 0.5)
        c = fluid.layers.scale(c, scale=0.5)
        logits = fluid.layers.elementwise_add(logits, c)
        loss = fluid.layers.mean(
            fluid.layers.softmax_with_cross_entropy(logits=logits,
                                                    label=label))
        probs = fluid.layers.softmax(logits)
        acc = fluid.layers.accuracy(input=probs, label=label)
        fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
    return main, startup, loss, acc


def _dense_feed(rng=None):
    rng = rng or np.random.RandomState(0)
    return {'x': rng.randn(8, 6).astype(np.float32),
            'y': rng.randint(0, 4, (8, 1)).astype(np.int64)}


def _lod_net(seed=13):
    """OCR-style LoD program: variable-length token sequences through
    embedding + sequence_pool into a classifier."""
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = seed
    with fluid.program_guard(main, startup):
        ids = fluid.layers.data(name='ids', shape=[1], dtype='int64',
                                lod_level=1)
        label = fluid.layers.data(name='lbl', shape=[1], dtype='int64')
        emb = fluid.layers.embedding(ids, size=[50, 8])
        pooled = fluid.layers.sequence_pool(emb, pool_type='sum')
        logits = fluid.layers.fc(pooled, size=3)
        loss = fluid.layers.mean(
            fluid.layers.softmax_with_cross_entropy(logits=logits,
                                                    label=label))
        fluid.optimizer.SGD(learning_rate=0.05).minimize(loss)
    return main, startup, loss


def _lod_feed(rng=None):
    rng = rng or np.random.RandomState(1)
    lens = [3, 1, 4]
    toks = rng.randint(0, 50, (sum(lens), 1)).astype(np.int64)
    ids = fluid.create_lod_tensor(toks, [lens])
    lbl = rng.randint(0, 3, (len(lens), 1)).astype(np.int64)
    return {'ids': ids, 'lbl': lbl}


def _init_state(startup):
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.core.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
    return exe, {k: np.asarray(v) for k, v in scope._vars.items()
                 if v is not None}


def _run_from(exe, snap, program, feed, fetches, steps=2):
    scope = fluid.core.Scope()
    for k, v in snap.items():
        scope.set(k, v)
    outs = []
    with fluid.scope_guard(scope):
        for _ in range(steps):
            outs.append(exe.run(program, feed=feed, fetch_list=fetches))
    return outs


def _assert_identical(a, b):
    for step_a, step_b in zip(a, b):
        for va, vb in zip(step_a, step_b):
            assert np.array_equal(np.asarray(va), np.asarray(vb))


# ---------------------------------------------------------------------------
# registry / manager / report shape
# ---------------------------------------------------------------------------
def test_registry_lists_core_passes():
    names = registered_passes()
    for want in ('verify_program', 'constant_fold', 'dead_op_elimination',
                 'fuse_activation'):
        assert want in names


def test_manager_preserves_pipeline_order_and_report_shape():
    main, startup, loss, acc = _dense_net()
    order = ['verify_program', 'constant_fold', 'dead_op_elimination']
    mgr = PassManager(order)
    assert mgr.pipeline_names() == order
    prog, reports = mgr.apply(main, fetch_names=[loss.name])
    assert [r.name for r in reports] == order
    assert prog is not main  # default: clone, source untouched
    for r in reports:
        assert isinstance(r, PassReport)
        d = r.as_dict()
        assert set(d) == {'pass', 'ops', 'vars', 'details', 'diagnostics'}
        assert {'before', 'after', 'added', 'removed'} <= set(d['ops'])
        assert r.ops_before - r.ops_removed + r.ops_added == r.ops_after


def test_unknown_pass_name_raises():
    with pytest.raises(KeyError):
        PassManager(['no_such_pass'])


def test_dce_prunes_metric_branch_and_reduces_ops():
    main, startup, loss, acc = _dense_net()
    before = len(main.global_block().ops)
    prog, reports = PassManager(['dead_op_elimination']).apply(
        main, fetch_names=[loss.name])
    after = len(prog.global_block().ops)
    assert after < before
    types = [op.type for op in prog.global_block().ops]
    assert 'accuracy' not in types  # unfetched metric branch dropped
    # source program untouched
    assert len(main.global_block().ops) == before


def test_constant_fold_splices_literals():
    main, startup, loss, acc = _dense_net()
    prog, reports = PassManager(['constant_fold',
                                 'dead_op_elimination']).apply(
        main, fetch_names=[loss.name])
    fold = reports[0]
    assert fold.details['folded_ops'] >= 1  # the scale(fill_constant)
    types = [op.type for op in prog.global_block().ops]
    assert 'scale' not in types or fold.details['folded_ops'] >= 1


# ---------------------------------------------------------------------------
# bit-identity: each pass alone + the full pipeline, dense and LoD
# ---------------------------------------------------------------------------
@pytest.mark.parametrize('pipeline', [
    ['verify_program'], ['constant_fold'], ['dead_op_elimination'],
    ['fuse_activation'], list(passes.OPTIMIZATION_PIPELINE)])
def test_bit_identity_dense(pipeline):
    main, startup, loss, acc = _dense_net()
    exe, snap = _init_state(startup)
    feed = _dense_feed()
    prog, _ = PassManager(pipeline).apply(main, fetch_names=[loss.name])
    base = _run_from(exe, snap, main, feed, [loss.name])
    opt = _run_from(exe, snap, prog, feed, [loss.name])
    _assert_identical(base, opt)


@pytest.mark.parametrize('pipeline', [
    ['constant_fold'], ['dead_op_elimination'],
    list(passes.OPTIMIZATION_PIPELINE)])
def test_bit_identity_lod(pipeline):
    main, startup, loss = _lod_net()
    exe, snap = _init_state(startup)
    feed = _lod_feed()
    prog, _ = PassManager(pipeline).apply(main, fetch_names=[loss.name])
    base = _run_from(exe, snap, main, feed, [loss.name])
    opt = _run_from(exe, snap, prog, feed, [loss.name])
    _assert_identical(base, opt)


def test_fuse_activation_inference_bit_identity():
    main, startup, loss, acc = _dense_net()
    exe, snap = _init_state(startup)
    feed = _dense_feed()
    infer = main.clone(for_test=True)
    out_name = 'softmax_0.tmp_0'
    assert any(out_name in op.output_arg_names()
               for op in infer.global_block().ops)
    prog, reports = passes.apply_inference_pipeline(
        infer, fetch_names=[out_name])
    fused = next(r for r in reports if r.name == 'fuse_activation')
    assert fused.details['fused'] >= 1
    assert 'relu' not in [op.type for op in prog.global_block().ops]
    base = _run_from(exe, snap, infer, feed, [out_name], steps=1)
    opt = _run_from(exe, snap, prog, feed, [out_name], steps=1)
    _assert_identical(base, opt)


def test_fuse_activation_skips_training_consumers():
    """Grad ops consume the activation input, so a train program must not
    fuse (the intermediate has >1 reader)."""
    main, startup, loss, acc = _dense_net()
    prog, reports = PassManager(['fuse_activation']).apply(
        main, fetch_names=[loss.name])
    assert reports[0].details['fused'] == 0


def test_const_fold_invalidates_overwritten_vars():
    """An in-place overwrite of a folded constant (increment) must kill
    the const-env entry: scale must NOT fold to the pre-overwrite value
    (code-review regression: fill_constant -> increment -> scale)."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        c = fluid.layers.fill_constant([1], 'float32', 0.0)
        fluid.layers.increment(c, value=1.0, in_place=True)
        out = fluid.layers.scale(c, scale=2.0)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.core.Scope()
    with fluid.scope_guard(scope):
        base, = exe.run(main, fetch_list=[out])
        prog, _ = passes.apply_optimization_pipeline(
            main, fetch_names=[out.name])
        opt, = exe.run(prog, fetch_list=[out])
    assert float(base[0]) == 2.0
    assert np.array_equal(base, opt)
    assert 'increment' in [op.type for op in prog.global_block().ops]


def test_const_fold_leaves_shape_of_runtime_data():
    """shape(x) of a feed var must never fold, even when the declared
    shape is fully static — the executor is shape-polymorphic per feed
    (code-review regression: declared (4, 3), fed (2, 3))."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data(name='x', shape=[4, 3], dtype='float32',
                              append_batch_size=False)
        shp = fluid.layers.shape(x)
    prog, _ = passes.apply_optimization_pipeline(main,
                                                 fetch_names=[shp.name])
    assert 'shape' in [op.type for op in prog.global_block().ops]
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.core.Scope()
    feed = {'x': np.zeros((2, 3), np.float32)}
    with fluid.scope_guard(scope):
        got, = exe.run(prog, feed=feed, fetch_list=[shp])
    assert list(got) == [2, 3]


def test_bit_identity_smallnet_model():
    """Full pipeline on a real bench model (conv net + metric branch)."""
    from models.smallnet import build_train_net
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = 5
    with fluid.program_guard(main, startup):
        images, label, loss, acc = build_train_net()
    exe, snap = _init_state(startup)
    rng = np.random.RandomState(2)
    feed = {'data': rng.randn(4, 3, 32, 32).astype(np.float32),
            'label': rng.randint(0, 10, (4, 1)).astype(np.int64)}
    prog, reports = passes.apply_optimization_pipeline(
        main, fetch_names=[loss.name])
    assert sum(len(b.ops) for b in prog.blocks) < \
        sum(len(b.ops) for b in main.blocks)
    base = _run_from(exe, snap, main, feed, [loss.name])
    opt = _run_from(exe, snap, prog, feed, [loss.name])
    _assert_identical(base, opt)


def test_bit_identity_stacked_lstm_model():
    """Full pipeline on the scan-based RNN bench model (static_rnn ops +
    sub-blocks must survive liveness untouched)."""
    from models.stacked_lstm import build_stacked_lstm_train
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = 6
    with fluid.program_guard(main, startup):
        ids, label, loss, _ = build_stacked_lstm_train(
            batch=4, vocab=60, emb_dim=8, hidden=8, seq_len=6)
    exe, snap = _init_state(startup)
    rng = np.random.RandomState(3)
    feed = {'ids': rng.randint(1, 60, (4, 6)).astype(np.int64),
            'label': rng.randint(0, 2, (4, 1)).astype(np.int64)}
    prog, _ = passes.apply_optimization_pipeline(
        main, fetch_names=[loss.name])
    base = _run_from(exe, snap, main, feed, [loss.name])
    opt = _run_from(exe, snap, prog, feed, [loss.name])
    _assert_identical(base, opt)


# ---------------------------------------------------------------------------
# verifier: clean nets + seeded corruption classes
# ---------------------------------------------------------------------------
def test_verifier_clean_on_models():
    from models.smallnet import build_train_net
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        images, label, loss, acc = build_train_net()
    diags = verify_program(main, fetch_names=[loss.name, acc.name])
    assert [d for d in diags if d.level == 'error'] == []


def _corrupt(kind):
    main, startup, loss, acc = _dense_net()
    block = main.global_block()
    fetch = [loss.name]
    if kind == 'undefined-input':
        op = next(op for op in block.ops if op.type == 'mul')
        op.inputs['X'] = ['ghost_var']
    elif kind == 'use-before-def':
        idx = next(i for i, op in enumerate(block.ops)
                   if op.type == 'mul')
        op = block.ops.pop(idx)
        block.ops.append(op)  # producer now AFTER its consumers
    elif kind == 'unregistered-op':
        block.append_op(type='definitely_not_an_op',
                        inputs={'X': [loss.name]},
                        outputs={'Out': [loss.name]}, infer_shape=False)
    elif kind == 'dangling-sub-block':
        block.ops[1].attrs['sub_block'] = 99
    elif kind == 'unreachable-fetch':
        fetch = ['never_produced_var']
    elif kind == 'bad-dtype':
        op = next(op for op in block.ops if op.type == 'fill_constant')
        op.attrs['dtype'] = 'float99'
    elif kind == 'shape-mismatch':
        op = next(op for op in block.ops if op.type == 'fill_constant')
        op.attrs['shape'] = [7, 9]  # declared var still says [1, 4]
    return main, fetch


_ERROR_KINDS = ['undefined-input', 'use-before-def', 'unregistered-op',
                'dangling-sub-block', 'unreachable-fetch', 'bad-dtype']


@pytest.mark.parametrize('kind', _ERROR_KINDS)
def test_verifier_flags_seeded_errors(kind):
    main, fetch = _corrupt(kind)
    diags = verify_program(main, fetch_names=fetch)
    hits = [d for d in diags if d.code == kind]
    assert hits, "expected %s in %s" % (kind, diags)
    assert all(d.level == 'error' for d in hits)
    d = hits[0]
    assert d.block == 0 and isinstance(d.op_index, int)


def test_verifier_flags_shape_mismatch_full_level():
    main, fetch = _corrupt('shape-mismatch')
    diags = verify_program(main, fetch_names=fetch, level='full')
    assert any(d.code == 'shape-mismatch' for d in diags)
    # fast level skips the registry sweep
    fast = verify_program(main, fetch_names=fetch, level='fast')
    assert not any(d.code == 'shape-mismatch' for d in fast)


def _while_counter_net():
    """while i < 5: s += i — the sub-block corruption target."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        i = fluid.layers.fill_constant([1], 'int64', 0)
        n = fluid.layers.fill_constant([1], 'int64', 5)
        s = fluid.layers.fill_constant([1], 'int64', 0)
        cond = fluid.layers.less_than(i, n)
        w = fluid.layers.While(cond)
        with w.block():
            s2 = fluid.layers.elementwise_add(s, i)
            fluid.layers.assign(s2, s)
            fluid.layers.increment(i)
            fluid.layers.less_than(i, n, cond=cond)
    return main, s


def test_verifier_flags_sub_block_use_before_def():
    """8th corruption class (ISSUE 7): use-before-def is now order-exact
    INSIDE sub-blocks too — reorder a while-body producer behind its
    consumer and the verifier must flag it at the sub-block."""
    main, s = _while_counter_net()
    sub = next(b for b in main.blocks if b.idx != 0)
    op = sub.ops.pop(0)
    sub.ops.append(op)  # body producer now AFTER its consumers
    diags = verify_program(main, fetch_names=[s.name], level='fast')
    hits = [d for d in diags if d.code == 'use-before-def'
            and d.block == sub.idx]
    assert hits and all(d.level == 'error' for d in hits), diags
    # the uncorrupted body verifies clean (no loop-carry false positive)
    clean, s2 = _while_counter_net()
    assert [d for d in verify_program(clean, fetch_names=[s2.name])
            if d.level == 'error'] == []


def test_verifier_flags_double_write_and_dead_persistable():
    """9th/10th corruption classes: a dead double-write and an orphaned
    persistable surface as warn diagnostics at full level."""
    main, startup, loss, acc = _dense_net()
    block = main.global_block()
    tgt = next(op for op in block.ops if op.type == 'mul')
    victim = tgt.outputs['Out'][0]
    # a second binding nobody reads between the two writes
    idx = next(i for i, op in enumerate(block.ops) if op is tgt)
    import copy
    dup = copy.copy(tgt)
    dup.inputs, dup.outputs = dict(tgt.inputs), dict(tgt.outputs)
    dup.attrs = dict(tgt.attrs)
    block.ops.insert(idx, dup)
    block.create_var(name='orphan_state', shape=(2,), dtype='float32',
                     persistable=True)
    diags = verify_program(main, fetch_names=[loss.name])
    assert any(d.code == 'double-write' and d.level == 'warn'
               for d in diags), diags
    assert any(d.code == 'dead-persistable' and d.var == 'orphan_state'
               for d in diags)


def test_verifier_warns_dead_outputs():
    main, startup, loss, acc = _dense_net()
    diags = verify_program(main, fetch_names=[loss.name])
    dead = [d for d in diags if d.code == 'dead-output']
    assert dead and all(d.level == 'warn' for d in dead)
    # fetching the metric silences it
    diags2 = verify_program(main, fetch_names=[loss.name, acc.name])
    assert not any(d.code == 'dead-output' and 'accuracy' in d.message
                   for d in diags2)


# ---------------------------------------------------------------------------
# consumer wiring
# ---------------------------------------------------------------------------
def test_executor_strict_verify_raises(monkeypatch):
    monkeypatch.setenv('PTPU_STRICT_VERIFY', '1')
    main, fetch = _corrupt('undefined-input')
    exe = fluid.Executor(fluid.CPUPlace())
    with pytest.raises(ProgramVerifyError):
        exe.run(main, feed=_dense_feed(), fetch_list=fetch)


def test_executor_warns_then_trace_fails(monkeypatch):
    monkeypatch.delenv('PTPU_STRICT_VERIFY', raising=False)
    main, fetch = _corrupt('undefined-input')
    exe = fluid.Executor(fluid.CPUPlace())
    with pytest.warns(RuntimeWarning, match='verification'):
        with pytest.raises(Exception):
            exe.run(main, feed=_dense_feed(), fetch_list=fetch)


def test_compiled_program_runs_optimized_pipeline():
    main, startup, loss, acc = _dense_net()
    exe, snap = _init_state(startup)
    feed = _dense_feed()
    base = _run_from(exe, snap, main, feed, [loss.name])
    compiled = fluid.CompiledProgram(main)
    opt = _run_from(exe, snap, compiled, feed, [loss.name])
    _assert_identical(base, opt)
    assert compiled._pass_reports, "pipeline must have run"
    dce = next(r for r in compiled._pass_reports
               if r.name == 'dead_op_elimination')
    assert dce.ops_removed >= 1  # the unfetched metric branch
    # a LATER fetch of the pruned metric still works: per-fetch-set clone
    extra = _run_from(exe, snap, compiled, feed, [loss.name, acc.name],
                      steps=1)
    assert np.array_equal(np.asarray(extra[0][0]), np.asarray(base[0][0]))


def test_memory_optimize_returns_report():
    main, startup, loss, acc = _dense_net()
    n0 = len(main.global_block().ops)
    report = fluid.memory_optimize(main)  # no fetch info: conservative
    assert isinstance(report, PassReport)
    assert len(main.global_block().ops) == n0  # every terminal kept
    report2 = fluid.memory_optimize(main, fetch_list=[loss])
    assert report2.ops_removed >= 1  # metric branch pruned in place
    assert 'accuracy' not in [op.type for op in main.global_block().ops]
    assert fluid.release_memory(main) is not None


def test_memory_optimize_skip_opt_set_preserved():
    main, startup, loss, acc = _dense_net()
    fluid.memory_optimize(main, skip_opt_set={acc.name},
                          fetch_list=[loss])
    assert 'accuracy' in [op.type for op in main.global_block().ops]


def test_inference_transpiler_returns_reports():
    main, startup, loss, acc = _dense_net()
    infer = main.clone(for_test=True)
    infer._fetch_names = [loss.name]
    t = fluid.InferenceTranspiler()
    reports = t.transpile(infer, fluid.CPUPlace())
    assert reports and [r.name for r in reports] == \
        passes.pipeline_names(passes.INFERENCE_PIPELINE)
    # the exported constant reproduces the inference pipeline exactly:
    # its DCE roots at fetches only (no persistable-writer keeping)
    from paddle_tpu.passes.dce import DeadOpEliminationPass
    dce = next(p for p in passes.INFERENCE_PIPELINE
               if isinstance(p, DeadOpEliminationPass))
    assert dce.keep_persistable_writers is False


def test_prune_program_drops_optimizer_and_keeps_fetch_cone():
    from paddle_tpu.io import prune_program
    main, startup, loss, acc = _dense_net()
    pruned = prune_program(main, ['x'], [loss.name])
    types = [op.type for op in pruned.global_block().ops]
    assert 'sgd' not in types and 'accuracy' not in types
    assert not any(t.endswith('_grad') for t in types)
    assert any(t == 'mul' for t in types)


def test_export_compiled_artifact_is_optimized(tmp_path):
    """export_compiled runs the pipeline; the artifact round-trips
    bit-identically against the unoptimized predictor."""
    from paddle_tpu.inference import Config, create_predictor
    from paddle_tpu.inference.export import export_compiled
    from paddle_tpu.inference.serve import CompiledPredictor
    main, startup, loss, acc = _dense_net()
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.core.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
        x = fluid.layers  # noqa: F841
        logits = 'softmax_0.tmp_0'
        model_dir = str(tmp_path / 'model')
        fluid.io.save_inference_model(
            model_dir, ['x'],
            [main.global_block().var(logits)], exe, main)
    pred = create_predictor(Config(model_dir))
    feed = _dense_feed()
    ref, = pred.run([feed['x']])
    out_dir = str(tmp_path / 'artifact')
    export_compiled(pred, [feed['x']], out_dir)
    served = CompiledPredictor(out_dir)
    got, = served.run([feed['x']])
    assert np.array_equal(np.asarray(ref), np.asarray(got))


# ---------------------------------------------------------------------------
# lint CLI
# ---------------------------------------------------------------------------
def _lint_cli():
    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), 'tools', 'program_lint.py')
    spec = importlib.util.spec_from_file_location('program_lint', path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_program_lint_cli_exit_codes(tmp_path):
    lint = _lint_cli()
    main, startup, loss, acc = _dense_net()
    good = tmp_path / 'good.json'
    good.write_bytes(fluid.io.serialize_program(main))
    assert lint.main([str(good)]) == 0
    bad_prog, _ = _corrupt('undefined-input')
    bad = tmp_path / 'bad.json'
    bad.write_bytes(fluid.io.serialize_program(bad_prog))
    assert lint.main([str(bad)]) == 1
    assert lint.main([str(tmp_path / 'missing.json')]) == 2


def test_program_lint_cli_models_subset():
    lint = _lint_cli()
    assert lint.main(['--models', 'smallnet']) == 0
