"""Subprocess worker for the real-kill elastic recovery test (the
reference kills trainer processes with signals in its distributed tier,
test_dist_base.py:339; this worker is the paddle_tpu feeder that gets
SIGKILL'd mid-epoch and later restarted on the same journal).

usage: elastic_kill_worker.py MODE JOURNAL OUT_FILE SLEEP_MS

MODE 'stream'    — elastic_sample_stream (journal BEFORE hand-off:
                   exactly-once between samples, at-most-once margin of 1)
MODE 'afterstep' — consume then report_progress (journal AFTER the step:
                   at-least-once margin of 1, the AsyncExecutor contract)

Each consumed sample id is appended (flushed) to OUT_FILE; on epoch
completion the sentinel EPOCH_DONE is written.
"""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from paddle_tpu.reader.elastic import TaskService, elastic_sample_stream

TASKS = ['t%d' % i for i in range(4)]
SAMPLES_PER_TASK = 25


def read_task(task):
    base = int(task[1:]) * 100
    for i in range(SAMPLES_PER_TASK):
        yield base + i


def main():
    mode, journal, out_path, sleep_ms = sys.argv[1:5]
    delay = float(sleep_ms) / 1000.0
    svc = TaskService(TASKS, journal_path=journal, lease_timeout_s=30.0)
    out = open(out_path, 'a')
    if mode == 'stream':
        for s in elastic_sample_stream(svc, read_task):
            out.write('%d\n' % s)
            out.flush()
            if delay:
                time.sleep(delay)
    elif mode == 'afterstep':
        while not svc.epoch_done:
            leased = svc.get_task()
            if leased is None:
                time.sleep(0.02)
                continue
            task_id, task, skip = leased
            n = 0
            for s in read_task(task):
                n += 1
                if n <= skip:
                    continue
                out.write('%d\n' % s)   # "train" on the batch...
                out.flush()
                if delay:
                    time.sleep(delay)
                svc.report_progress(task_id, n)  # ...then journal
            svc.task_finished(task_id)
    else:
        raise SystemExit('unknown mode %r' % mode)
    out.write('EPOCH_DONE\n')
    out.flush()
    svc.close()


if __name__ == '__main__':
    main()
