"""Subprocess worker: prove the FULL five-axis composition (dp x mp x sp x
ep x pp, every axis simultaneously) in ONE compiled train step, with
per-step loss parity against the single-device run of the same program.

Runs in its own process because --xla_force_host_platform_device_count must
be set before jax initializes, and the main test process is pinned to 8
devices by conftest.py. Invoked by tests/test_mesh_compose.py as

    python mesh_compose_worker.py dp=2 mp=1 sp=2 ep=2 pp=2   (16 devices)
    python mesh_compose_worker.py dp=2 mp=2 sp=2 ep=2 pp=2   (32 devices)

Methodology: reference test_dist_base.py check_with_place (same init, same
data, distributed losses must track single-process losses step for step);
the program is the exact one the driver dryruns (__graft_entry__.
build_five_axis_program).
"""
import os
import re
import sys

AXES = ('dp', 'mp', 'sp', 'ep', 'pp')


def main():
    sizes = {k: 1 for k in AXES}
    for kv in sys.argv[1:]:
        k, v = kv.split('=')
        assert k in AXES, k
        sizes[k] = int(v)
    n = 1
    for v in sizes.values():
        n *= v

    flags = re.sub(r'--xla_force_host_platform_device_count=\d+', '',
                   os.environ.get('XLA_FLAGS', ''))
    os.environ['XLA_FLAGS'] = (
        flags + ' --xla_force_host_platform_device_count=%d' % n).strip()
    os.environ['JAX_PLATFORMS'] = 'cpu'
    os.environ['PTPU_PLATFORM'] = 'cpu'
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    sys.path.insert(0, repo)

    import numpy as np
    import jax
    from jax.sharding import Mesh
    import paddle_tpu as fluid
    from paddle_tpu.core.config import set_backend
    set_backend('cpu')
    from paddle_tpu.parallel.compiler import CompiledProgram
    from __graft_entry__ import build_five_axis_program, compose_batch_size

    devs = jax.devices('cpu')
    assert len(devs) >= n, (n, len(devs))

    S = 16
    main_p, startup, loss = build_five_axis_program(
        mp=sizes['mp'], pp=sizes['pp'], seq_len=S)

    scope = fluid.core.Scope()
    exe = fluid.Executor()
    with fluid.scope_guard(scope):
        exe.run(startup)
    init = {nm: np.asarray(scope.get(nm))
            for nm in scope.local_var_names() if scope.get(nm) is not None}

    # batch must tile the auto microbatch count (2*pp) and the dp axis so
    # the pipeline runs its real GPipe schedule with no fallback pick;
    # enforce the invariant here rather than trusting a silent fallback
    bs = compose_batch_size(sizes['pp'], sizes['dp'])
    m_auto = 2 * sizes['pp']
    assert bs % m_auto == 0 and (bs // m_auto) % sizes['dp'] == 0, \
        (bs, m_auto, sizes)
    rng = np.random.RandomState(0)
    feeds = [{'ids': rng.randint(0, 64, (bs, S)).astype(np.int64),
              'label': rng.randint(0, 8, (bs, 1)).astype(np.int64)}
             for _ in range(3)]

    def run_steps(target):
        sc = fluid.core.Scope()
        for nm, v in init.items():
            sc.set(nm, v)
        ex = fluid.Executor()
        losses = []
        with fluid.scope_guard(sc):
            for f in feeds:
                out, = ex.run(program=target, feed=f, fetch_list=[loss])
                losses.append(float(np.asarray(out).reshape(-1)[0]))
        return losses

    single = run_steps(main_p)
    mesh = Mesh(np.asarray(devs[:n]).reshape(*(sizes[a] for a in AXES)),
                AXES)
    multi = run_steps(CompiledProgram(main_p).with_data_parallel(
        loss_name=loss.name, mesh=mesh))

    assert np.isfinite(single).all(), single
    assert np.isfinite(multi).all(), multi
    assert single[0] != single[-1], "training did not move: %r" % (single,)
    # repo-standard tolerance for single-vs-mesh on CPU fastmath
    # (test_pipeline.py:86); observed divergence is ~1e-7 relative
    np.testing.assert_allclose(single, multi, rtol=2e-3, atol=1e-5)
    # persistent compile cache (ISSUE 5): when the caller points
    # PTPU_COMPILE_CACHE_DIR at a shared dir, report the counters so the
    # test can assert a warm re-run skips the recompile of the largest
    # mesh ever compiled here
    from paddle_tpu.core import compile_cache as cc
    if cc.enabled():
        import json
        s = cc.stats()
        print('CC_STATS %s' % json.dumps(
            {k: s[k] for k in ('exec_hits', 'hlo_hits', 'misses',
                               'compiles', 'xla_compiles_net')}
            | {'compile_s': round(s['compile_s'], 2)}))
    print("MESH_COMPOSE_OK n=%d %s single=%r multi=%r"
          % (n, ' '.join('%s=%d' % (a, sizes[a]) for a in AXES),
             single, multi))


if __name__ == '__main__':
    main()
