"""Activation rematerialization (ISSUE 18): the recompute pass + the
measured-memory contract.

The invariants under test:
  * the pass reports at the horizontal_fuse standard (reason codes for
    every declined op/segment, per-segment boundary details);
  * recompute changes WHAT is stored, never WHAT is computed — with
    dropout on, losses are bit-identical with/without explicit
    checkpoints across every training harness (plain run(), the
    in-graph run_steps(K) loop, gradient merge, the exported
    CompiledTrainer);
  * the saving is real and MEASURED: XLA's buffer assignment plans
    strictly fewer temp bytes for the remat program at the same batch;
  * the rewrite composes with the mesh path (CompiledProgram).
"""
import warnings

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import transpiler
from paddle_tpu.executor import compiled_memory_stats
from paddle_tpu.inference import export_train_step, load_trainer
from paddle_tpu.parallel import make_mesh
from paddle_tpu.parallel.compiler import CompiledProgram
from paddle_tpu.passes import dataflow
from paddle_tpu.passes import recompute as R

STEPS = 3
BATCH = 8


# ---------------------------------------------------------------------------
# builders
# ---------------------------------------------------------------------------

def _forward_mlp(depth=4, width=32, dropout=0.2):
    """Forward-only tower; returns (loss, checkpoint vars)."""
    x = fluid.layers.data(name='x', shape=[16], dtype='float32')
    y = fluid.layers.data(name='y', shape=[1], dtype='float32')
    h, cps = x, []
    for _ in range(depth):
        h = fluid.layers.fc(h, size=width, act='relu')
        if dropout:
            h = fluid.layers.dropout(h, dropout_prob=dropout)
        cps.append(h)
    out = fluid.layers.fc(h, size=1)
    loss = fluid.layers.mean(fluid.layers.square(out - y))
    return loss, cps


def _build_train(checkpoints=None, seed=11, grad_merge_k=0, **fwd_kw):
    """(main, startup, loss) with Adam.minimize(checkpoints=...)."""
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = seed
    with fluid.program_guard(main, startup):
        loss, cps = _forward_mlp(**fwd_kw)
        if checkpoints is True:
            checkpoints = cps
        opt = fluid.optimizer.Adam(1e-2)
        if grad_merge_k > 1:
            opt = fluid.contrib.gradient_merge.decorate(opt, grad_merge_k)
        opt.minimize(loss, checkpoints=checkpoints)
    return main, startup, loss


def _feed(seed=3, batch=BATCH):
    rng = np.random.RandomState(seed)
    return {'x': rng.randn(batch, 16).astype(np.float32),
            'y': rng.randn(batch, 1).astype(np.float32)}


def _losses(main, startup, loss, steps=STEPS, use_run_steps=False):
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.core.Scope()
    feed = _feed()
    with fluid.scope_guard(scope):
        exe.run(startup)
        if use_run_steps:
            stacked = {n: np.stack([v] * steps) for n, v in feed.items()}
            vals, = exe.run_steps(main, feed=stacked, fetch_list=[loss],
                                  steps=steps, fetch_policy='stack')
            return np.asarray(vals).reshape(steps)
        out = []
        for _ in range(steps):
            l, = exe.run(main, feed=feed, fetch_list=[loss])
            out.append(np.asarray(l).reshape(()))
    return np.stack(out)


# ---------------------------------------------------------------------------
# pass report contract
# ---------------------------------------------------------------------------

def test_report_contract_explicit():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        loss, cps = _forward_mlp()
    prog, report = R.recompute_program(
        main, checkpoints=[c.name for c in cps[:-1]],
        fetch_names=[loss.name])
    d = report.details
    for key in ('mode', 'checkpoints', 'segments', 'skipped',
                'skip_reasons', 'declined'):
        assert key in d, key
    assert d['mode'] == 'explicit'
    assert d['declined'] is None
    assert d['segments'], "explicit checkpoints applied 0 segments"
    for seg in d['segments']:
        for key in ('sub_block', 'start', 'end', 'n_ops', 'inputs',
                    'outputs', 'interior_bytes', 'boundary_bytes'):
            assert key in seg, key
        assert seg['n_ops'] == seg['end'] - seg['start'] + 1
        assert seg['interior_bytes'] > 0
        sub = prog.block(seg['sub_block'])
        assert len(sub.ops) == seg['n_ops']
    # every skip carries a known reason code
    for s in d['skipped']:
        assert s['reason'] in R.REASON_CODES, s
    assert all(r in R.REASON_CODES for r in d['skip_reasons'])
    # the rewrite spliced remat_segment ops into block 0
    remats = [op for op in prog.global_block().ops
              if op.type == 'remat_segment']
    assert len(remats) == len(d['segments'])


def test_unknown_checkpoint_name_raises():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        loss, _ = _forward_mlp()
    with pytest.raises(ValueError, match='never.*defines|defines'):
        R.recompute_program(main, checkpoints=['no_such_var'],
                            fetch_names=[loss.name])


def test_declines_post_backward_program():
    """After append_backward the pass must refuse (recompute must wrap
    the forward BEFORE grads reference the interiors)."""
    main, startup, loss = _build_train(checkpoints=None)
    _, report = R.recompute_program(main, checkpoints='auto',
                                    fetch_names=[loss.name])
    assert report.details['declined'] == R.REASON_BACKWARD_PRESENT
    assert report.details['segments'] == []
    assert report.details['skip_reasons'] == {
        R.REASON_BACKWARD_PRESENT: 1}


def test_minimize_checkpoints_attaches_report():
    """minimize(checkpoints=...) is no longer a silent no-op: the applied
    report rides on the program and records real segments."""
    main, startup, loss = _build_train(checkpoints=True)
    rep = getattr(main, '_recompute_report', None)
    assert rep is not None
    assert rep.details['segments'], rep.details['skip_reasons']
    assert any(op.type == 'remat_segment'
               for op in main.global_block().ops)
    # the grad replay op is the generic one, reading the fwd boundary
    assert any(op.type == 'remat_segment_grad'
               for op in main.global_block().ops)


def test_zero_segment_checkpoint_request_warns():
    """A checkpoints= request that applies nothing must say so loudly."""
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 5
    with fluid.program_guard(main, startup):
        # one op per segment: every candidate is below min_ops
        x = fluid.layers.data(name='x', shape=[4], dtype='float32')
        h = fluid.layers.scale(x, scale=2.0)
        loss = fluid.layers.mean(h)
        with pytest.warns(UserWarning, match='0 recompute segments'):
            fluid.optimizer.SGD(0.1).minimize(loss, checkpoints=[h])


# ---------------------------------------------------------------------------
# numerics: recompute must not change the math (dropout rng included)
# ---------------------------------------------------------------------------

def test_bit_identity_plain_run():
    base = _losses(*_build_train(checkpoints=None))
    remat = _losses(*_build_train(checkpoints=True))
    np.testing.assert_array_equal(base, remat)


def test_bit_identity_run_steps():
    base = _losses(*_build_train(checkpoints=None), use_run_steps=True)
    remat = _losses(*_build_train(checkpoints=True), use_run_steps=True)
    np.testing.assert_array_equal(base, remat)
    # and the in-graph loop agrees with K sequential run() calls
    seq = _losses(*_build_train(checkpoints=True))
    np.testing.assert_array_equal(remat, seq)


def test_bit_identity_gradient_merge():
    base = _losses(*_build_train(checkpoints=None, grad_merge_k=2),
                   steps=4)
    remat = _losses(*_build_train(checkpoints=True, grad_merge_k=2),
                    steps=4)
    np.testing.assert_array_equal(base, remat)


def test_bit_identity_compiled_trainer(tmp_path):
    """The exported tracer-free train step carries the remat structure:
    CompiledTrainer losses bit-match the in-framework executor AND the
    no-remat trajectory."""
    main, startup, loss = _build_train(checkpoints=True)
    feed = _feed()

    scope = fluid.core.Scope()
    exe = fluid.Executor(fluid.CPUPlace())
    with fluid.scope_guard(scope):
        exe.run(startup)
        init = {n: np.asarray(scope.get(n))
                for n in scope.local_var_names()
                if scope.get(n) is not None}
        want = np.stack([
            np.asarray(exe.run(main, feed=feed, fetch_list=[loss])[0])
            for _ in range(STEPS)])

    art = str(tmp_path / 'remat_train_art')
    scope2 = fluid.core.Scope()
    for n, v in init.items():
        scope2.set(n, v)
    export_train_step(main, feed, [loss], art, scope=scope2)
    trainer = load_trainer(art)
    got = np.stack([trainer.step(feed)[0] for _ in range(STEPS)])
    np.testing.assert_array_equal(got, want)

    base = _losses(*_build_train(checkpoints=None))
    np.testing.assert_array_equal(got.reshape(-1), base.reshape(-1))


def test_auto_mode_applies_and_matches():
    """'auto' picks √N segments itself; trajectories agree to float
    tolerance (XLA may re-associate across the different checkpoint
    boundaries, so bit-exactness is only promised for explicit mode)."""
    main, startup, loss = _build_train(checkpoints='auto')
    rep = main._recompute_report
    assert rep.details['mode'] == 'auto'
    assert rep.details['segments']
    base = _losses(*_build_train(checkpoints=None))
    auto = _losses(main, startup, loss)
    np.testing.assert_allclose(auto, base, rtol=1e-5, atol=1e-6)


# ---------------------------------------------------------------------------
# measured memory
# ---------------------------------------------------------------------------

def test_hlo_temp_bytes_shrink():
    """The acceptance metric, at test scale: XLA's buffer assignment for
    the compiled train step plans measurably fewer temp bytes with
    per-layer checkpoints (same model, same batch, same fetches)."""
    feed = _feed(batch=32)

    def temps(checkpoints):
        main, startup, loss = _build_train(checkpoints=checkpoints,
                                           depth=6, width=64)
        scope = fluid.core.Scope()
        exe = fluid.Executor(fluid.CPUPlace())
        with fluid.scope_guard(scope):
            exe.run(startup)
            stats = compiled_memory_stats(main, feed=feed,
                                          fetch_list=[loss], scope=scope,
                                          exe=exe)
        if stats is None:
            pytest.skip('backend exposes no memory_analysis()')
        return stats['temp_bytes']

    base, remat = temps(None), temps(True)
    assert remat < base * 0.9, (base, remat)


def test_dataflow_remat_aware_estimate():
    """The static estimator understands remat_segment: interior temps are
    point-charged (def/use spikes) instead of living fwd..grad, so the
    remat-aware peak drops; without segments the two modes agree."""
    plain, _, ploss = _build_train(checkpoints=None)
    dfa = dataflow.analyze_program(plain, fetch_names=[ploss.name])
    span = dfa.peak_memory(batch=BATCH, top=0)
    aware = dfa.peak_memory(batch=BATCH, top=0, remat_aware=True)
    assert span.remat_segments == 0
    assert span.peak_bytes == aware.peak_bytes

    remat, _, rloss = _build_train(checkpoints=True)
    dfa2 = dataflow.analyze_program(remat, fetch_names=[rloss.name])
    span2 = dfa2.peak_memory(batch=BATCH, top=0)
    aware2 = dfa2.peak_memory(batch=BATCH, top=0, remat_aware=True)
    assert aware2.remat_segments > 0
    assert aware2.remat_interior_bytes > 0
    assert aware2.peak_bytes < span2.peak_bytes


def test_memory_optimize_routes_to_recompute():
    """The deprecated transpiler front door now drives the real passes:
    checkpoints= routes into the recompute pass and the report says so."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        loss, cps = _forward_mlp()
    with pytest.warns(DeprecationWarning, match='deprecated.*pass API'):
        report = transpiler.memory_optimize(
            main, fetch_list=[loss], batch=BATCH,
            checkpoints=[c.name for c in cps[:-1]])
    assert report.details['recompute']['segments'] > 0
    assert any(op.type == 'remat_segment'
               for op in main.global_block().ops)


# ---------------------------------------------------------------------------
# mesh composition
# ---------------------------------------------------------------------------

def test_remat_composes_with_mesh():
    """The remat program trains under CompiledProgram over a dp mesh and
    tracks the single-device trajectory (conftest provides 8 virtual
    devices)."""
    single = _losses(*_build_train(checkpoints=True))

    main, startup, loss = _build_train(checkpoints=True)
    prog = CompiledProgram(main).with_data_parallel(
        loss_name=loss.name, mesh=make_mesh(num_devices=2,
                                            axes={'dp': 2}))
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.core.Scope()
    feed = _feed()
    with fluid.scope_guard(scope):
        exe.run(startup)
        got = []
        for _ in range(STEPS):
            l, = exe.run(prog, feed=feed, fetch_list=[loss])
            got.append(np.asarray(l).reshape(()))
    got = np.stack(got)
    assert np.isfinite(got).all()
    np.testing.assert_allclose(got, single, rtol=2e-4, atol=2e-5)
