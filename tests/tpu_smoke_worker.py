"""Real-chip smoke worker (spawned by test_tpu_smoke.py with a clean env
so the axon TPU plugin is the backend — the in-suite conftest pins CPU).

Runs every check in ONE process/tunnel session (compiles dominate; ten
separate processes would blow the <3 min budget) and prints one
`CHECK <name> OK|FAIL <detail>` line per check. Covers the axon-specific
behaviors no CPU test can reach (VERDICT r3 weak #6): tunnel execution of
each flagship model family, bf16 AMP numerics, DLPack host-copy fallback,
the py_func capability error, profiler tracing, checkpoint round-trip, and
compiled-artifact serving.
"""
import os
import sys
import tempfile
import traceback

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

CHECKS = []


def check(fn):
    CHECKS.append(fn)
    return fn


def _train_step_net(build):
    import paddle_tpu as fluid
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        feeds, loss = build()
    exe = fluid.Executor(fluid.TPUPlace())
    exe.run(startup)
    rng = np.random.RandomState(0)
    feed = {name: gen(rng) for name, gen in feeds.items()}
    vals = []
    for _ in range(4):
        l, = exe.run(main, feed=feed, fetch_list=[loss])
        vals.append(float(np.asarray(l).reshape(-1)[0]))
    assert all(np.isfinite(vals)), vals
    assert vals[-1] < vals[0], vals  # same batch: loss must fall
    return vals


@check
def conv_train_step():
    import paddle_tpu as fluid

    def build():
        img = fluid.layers.data(name='img', shape=[3, 16, 16],
                                dtype='float32')
        lbl = fluid.layers.data(name='lbl', shape=[1], dtype='int64')
        c = fluid.layers.conv2d(img, 8, 3, padding=1, act=None)
        c = fluid.layers.batch_norm(c, act='relu')
        p = fluid.layers.pool2d(c, 2, 'max', 2)
        out = fluid.layers.fc(p, size=10, act='softmax')
        loss = fluid.layers.mean(
            fluid.layers.cross_entropy(input=out, label=lbl))
        fluid.optimizer.Momentum(0.05, 0.9).minimize(loss)
        return {'img': lambda r: r.randn(8, 3, 16, 16).astype(np.float32),
                'lbl': lambda r: r.randint(0, 10, (8, 1)).astype(np.int64)},\
            loss
    _train_step_net(build)


@check
def attention_train_step():
    import paddle_tpu as fluid
    from models.transformer import encoder_layer

    def build():
        x = fluid.layers.data(name='x', shape=[16, 32], dtype='float32')
        lbl = fluid.layers.data(name='lbl', shape=[1], dtype='int64')
        h = encoder_layer(x, 2, 32, 64, 16, 0.0)
        pooled = fluid.layers.reduce_mean(h, dim=1)
        out = fluid.layers.fc(pooled, size=4, act='softmax')
        loss = fluid.layers.mean(
            fluid.layers.cross_entropy(input=out, label=lbl))
        fluid.optimizer.Adam(1e-3).minimize(loss)
        return {'x': lambda r: r.randn(4, 16, 32).astype(np.float32),
                'lbl': lambda r: r.randint(0, 4, (4, 1)).astype(np.int64)},\
            loss
    _train_step_net(build)


@check
def sparse_ctr_train_step():
    import paddle_tpu as fluid

    def build():
        ids = fluid.layers.data(name='ids', shape=[4], dtype='int64')
        lbl = fluid.layers.data(name='clk', shape=[1], dtype='float32')
        emb = fluid.layers.embedding(ids, size=[1000, 8], is_sparse=True)
        flat = fluid.layers.reshape(emb, shape=[-1, 32])
        logit = fluid.layers.fc(flat, size=1)
        loss = fluid.layers.mean(
            fluid.layers.sigmoid_cross_entropy_with_logits(logit, lbl))
        fluid.optimizer.Adam(1e-2, lazy_mode=True).minimize(loss)
        return {'ids': lambda r: r.randint(0, 1000, (16, 4))
                .astype(np.int64),
                'clk': lambda r: (r.rand(16, 1) < 0.5)
                .astype(np.float32)}, loss
    _train_step_net(build)


@check
def amp_bf16_numerics():
    import paddle_tpu as fluid

    def run(bf16):
        main, startup = fluid.Program(), fluid.Program()
        main.random_seed = startup.random_seed = 11
        with fluid.program_guard(main, startup):
            x = fluid.layers.data(name='x', shape=[32], dtype='float32')
            lbl = fluid.layers.data(name='lbl', shape=[1], dtype='int64')
            out = fluid.layers.fc(fluid.layers.fc(x, 64, act='relu'), 8,
                                  act='softmax')
            loss = fluid.layers.mean(
                fluid.layers.cross_entropy(input=out, label=lbl))
            fluid.optimizer.SGD(0.1).minimize(loss)
        if bf16:
            fluid.contrib.mixed_precision.enable_bf16(main)
        exe = fluid.Executor(fluid.TPUPlace())
        exe.run(startup)
        r = np.random.RandomState(5)
        feed = {'x': r.randn(16, 32).astype(np.float32),
                'lbl': r.randint(0, 8, (16, 1)).astype(np.int64)}
        for _ in range(3):
            l, = exe.run(main, feed=feed, fetch_list=[loss])
        return float(np.asarray(l).reshape(-1)[0])

    f32, bf16 = run(False), run(True)
    assert np.isfinite(bf16), bf16
    # bf16 training must track f32 on this toy problem
    assert abs(f32 - bf16) < 0.15 * max(abs(f32), 1e-3), (f32, bf16)


@check
def dlpack_roundtrip():
    import jax.numpy as jnp
    from paddle_tpu import core
    x = jnp.arange(12, dtype=jnp.float32).reshape(3, 4) * 1.5
    cap = core.to_dlpack(x)  # axon path: host-copy fallback
    import torch.utils.dlpack as tdl
    t = tdl.from_dlpack(cap)
    np.testing.assert_allclose(np.asarray(t), np.asarray(x))
    back = core.from_dlpack(t * 2)  # torch tensor carries __dlpack__
    np.testing.assert_allclose(np.asarray(back), np.asarray(x) * 2)


@check
def py_func_capability_error():
    import paddle_tpu as fluid
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data(name='x', shape=[4], dtype='float32')
        out = fluid.layers.py_func(
            func=lambda a: np.asarray(a) * 2, x=[x],
            out=fluid.default_main_program().global_block().create_var(
                name='pyout', shape=[-1, 4], dtype='float32'))
    exe = fluid.Executor(fluid.TPUPlace())
    exe.run(startup)
    try:
        exe.run(main, feed={'x': np.ones((2, 4), np.float32)},
                fetch_list=['pyout'])
    except RuntimeError as e:
        assert 'host callbacks' in str(e), str(e)
    else:
        raise AssertionError("py_func on axon should raise the capability "
                             "error (or the platform now supports "
                             "callbacks — update this check)")


@check
def profiler_trace():
    import paddle_tpu as fluid
    from paddle_tpu import profiler
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data(name='x', shape=[8], dtype='float32')
        out = fluid.layers.fc(x, 4)
    exe = fluid.Executor(fluid.TPUPlace())
    exe.run(startup)
    with profiler.profiler('All', 'total'):
        exe.run(main, feed={'x': np.ones((2, 8), np.float32)},
                fetch_list=[out])
    d = tempfile.mkdtemp()
    path = os.path.join(d, 'trace.json')
    profiler.export_chrome_tracing(path)
    assert os.path.getsize(path) > 0


@check
def checkpoint_roundtrip():
    import paddle_tpu as fluid
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data(name='x', shape=[8], dtype='float32')
        fluid.layers.fc(x, 4)
    exe = fluid.Executor(fluid.TPUPlace())
    exe.run(startup)
    from paddle_tpu.core.scope import global_scope
    # unique_name counters are process-global: resolve the param name from
    # THIS program, not a hardcoded fc_0
    w_name = main.global_block().all_parameters()[0].name
    w = np.asarray(global_scope().get(w_name))
    d = tempfile.mkdtemp()
    fluid.io.save_persistables(exe, d, main)
    global_scope().set(w_name, np.zeros_like(w))
    fluid.io.load_persistables(exe, d, main)
    np.testing.assert_allclose(
        np.asarray(global_scope().get(w_name)), w)


@check
def compiled_artifact_serves_on_chip():
    import paddle_tpu as fluid
    from paddle_tpu.inference import (Config, create_predictor,
                                      export_compiled, load_compiled)
    d = tempfile.mkdtemp()
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 3
    with fluid.program_guard(main, startup):
        img = fluid.layers.data(name='img', shape=[8], dtype='float32')
        out = fluid.layers.fc(fluid.layers.fc(img, 16, act='relu'), 4,
                              act='softmax')
    exe = fluid.Executor(fluid.TPUPlace())
    exe.run(startup)
    fluid.io.save_inference_model(d, ['img'], [out], exe, main)
    cfg = Config(d)
    pred = create_predictor(cfg)
    x = np.random.RandomState(0).randn(5, 8).astype(np.float32)
    want, = pred.run([x])
    art = tempfile.mkdtemp()
    export_compiled(pred, [x], art)
    got, = load_compiled(art).run([x])
    np.testing.assert_allclose(got, want, rtol=2e-2, atol=2e-2)  # MXU bf16


@check
def train_artifact_steps_on_chip():
    """Tracer-free TRAIN export runs on the chip: export_train_step ->
    CompiledTrainer 3 steps, loss finite and decreasing-ish (bit-match is
    asserted CPU-side in test_export_train.py; on-chip MXU bf16 numerics
    differ by design)."""
    import paddle_tpu as fluid
    from paddle_tpu.inference import export_train_step, load_trainer
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 5
    with fluid.program_guard(main, startup):
        x = fluid.layers.data('x', shape=[12], dtype='float32')
        label = fluid.layers.data('label', shape=[1], dtype='int64')
        h = fluid.layers.dropout(fluid.layers.fc(x, 24, act='relu'),
                                 dropout_prob=0.2)
        loss = fluid.layers.mean(fluid.layers.softmax_with_cross_entropy(
            logits=fluid.layers.fc(h, 5), label=label))
        fluid.optimizer.Momentum(0.05, 0.9).minimize(loss)
    exe = fluid.Executor(fluid.TPUPlace())
    scope = fluid.core.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
    rng = np.random.RandomState(0)
    feed = {'x': rng.randn(16, 12).astype(np.float32),
            'label': rng.randint(0, 5, (16, 1)).astype(np.int64)}
    art = tempfile.mkdtemp()
    export_train_step(main, feed, [loss], art, scope=scope)
    trainer = load_trainer(art)
    losses = [float(np.asarray(trainer.step(feed)[0]).reshape(-1)[0])
              for _ in range(3)]
    assert np.isfinite(losses).all(), losses
    assert losses[-1] < losses[0], losses


@check
def crnn_ctc_train_step():
    """OCR north star: conv->im2sequence->BiGRU->warpctc with var-len LoD
    labels trains on the chip (the LoD path axon-side)."""
    import paddle_tpu as fluid
    from models.crnn import build_crnn_train
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        images, label, avg_cost, decoded, edit = build_crnn_train(
            num_classes=10, img_h=32, img_w=64, rnn_hidden=32, lr=1e-3)
    exe = fluid.Executor(fluid.TPUPlace())
    exe.run(startup)
    r = np.random.RandomState(0)
    imgs = r.randn(4, 1, 32, 64).astype(np.float32)
    lens = r.randint(1, 5, 4)
    toks = r.randint(0, 10, int(lens.sum())).astype(np.int32)
    lbl = fluid.create_lod_tensor(toks.reshape(-1, 1), [list(lens)])
    vals = []
    for _ in range(4):
        l, = exe.run(main, feed={'pixel': imgs, 'label': lbl},
                     fetch_list=[avg_cost])
        vals.append(float(np.asarray(l).reshape(-1)[0]))
    assert np.isfinite(vals).all() and vals[-1] < vals[0], vals


@check
def flash_attention_parity():
    """The auto-selected Pallas flash path must agree with the XLA
    composition at a shape where the policy engages it (S=512)."""
    import os
    import jax
    import jax.numpy as jnp
    import paddle_tpu as fluid
    from paddle_tpu.ops.nn_ops import _flash_policy
    assert _flash_policy(512, False)[0], "policy should pick flash @512"

    r = np.random.RandomState(2)
    qkv = [r.randn(2, 4, 512, 64).astype(np.float32) for _ in range(3)]

    def run(force):
        os.environ['PTPU_FLASH_ATTN'] = force
        try:
            main, startup = fluid.Program(), fluid.Program()
            with fluid.program_guard(main, startup):
                qv = fluid.layers.data(name='q', shape=[4, 512, 64],
                                       dtype='float32')
                kv = fluid.layers.data(name='k', shape=[4, 512, 64],
                                       dtype='float32')
                vv = fluid.layers.data(name='v', shape=[4, 512, 64],
                                       dtype='float32')
                out = fluid.layers.fused_multihead_attention(
                    qv, kv, vv, causal=False, scale=0.125)
            exe = fluid.Executor(fluid.TPUPlace())
            exe.run(startup)
            o, = exe.run(main, feed=dict(zip('qkv', qkv)),
                         fetch_list=[out])
            return np.asarray(o)
        finally:
            os.environ.pop('PTPU_FLASH_ATTN', None)

    flash, comp = run('1'), run('0')
    np.testing.assert_allclose(flash, comp, rtol=3e-2, atol=3e-2)


@check
def pallas_bn_numerics():
    import jax
    import jax.numpy as jnp
    from paddle_tpu.ops.pallas_bn import fused_bn_apply
    r = np.random.RandomState(3)
    x = jnp.asarray(r.randn(4, 64, 16, 16), jnp.bfloat16)
    k = jnp.asarray(r.randn(64), jnp.float32)
    b = jnp.asarray(r.randn(64), jnp.float32)
    y = jax.jit(lambda x, k, b: fused_bn_apply(x, k, b, 'relu'))(x, k, b)
    ref = np.maximum(np.asarray(x, np.float32)
                     * np.asarray(k).reshape(1, -1, 1, 1)
                     + np.asarray(b).reshape(1, -1, 1, 1), 0.0)
    np.testing.assert_allclose(np.asarray(y, np.float32), ref,
                               rtol=2e-2, atol=2e-2)  # bf16 compute

    def lossf(x, k, b):
        return jnp.sum(fused_bn_apply(x, k, b, 'relu')
                       .astype(jnp.float32) ** 2)
    gx, gk, gb = jax.jit(jax.grad(lossf, argnums=(0, 1, 2)))(x, k, b)
    assert np.isfinite(np.asarray(gx, np.float32)).all()
    assert gk.shape == (64,) and gb.shape == (64,)


def main():
    failed = 0
    for fn in CHECKS:
        name = fn.__name__
        try:
            fn()
            print('CHECK %s OK' % name, flush=True)
        except Exception:
            failed += 1
            detail = traceback.format_exc().strip().replace('\n', ' | ')
            print('CHECK %s FAIL %s' % (name, detail[-800:]), flush=True)
    return 1 if failed else 0


if __name__ == '__main__':
    sys.exit(main())
