"""horizontal_fuse pass (paddle_tpu/passes/horizontal_fuse.py): sibling
same-input convs widen into one conv + split. Bit-identity through the
grad path (the split rebinds the ORIGINAL output names, so vjp-derived
grad ops never notice), reason-coded report contract, the
fuse_activation interaction the pipeline order note promises, and
pass-off/pass-on parity through run_steps(K)."""
import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import passes
from paddle_tpu.passes import PassManager
from paddle_tpu.passes.horizontal_fuse import (
    REASON_CODES, REASON_GROUPED, REASON_NO_SIBLING, REASON_USER_SKIP,
    horizontal_fuse_program)

from test_passes import (_assert_identical, _init_state,  # noqa: F401
                         _run_from)


# ---------------------------------------------------------------------------
# builders
# ---------------------------------------------------------------------------
def _inception_head(x, act=None):
    """Three sibling 1x1 convs off one tensor — the googlenet branch-entry
    pattern the pass exists for."""
    branches = [fluid.layers.conv2d(x, num_filters=f, filter_size=1,
                                    act=act) for f in (3, 5, 2)]
    return fluid.layers.concat(branches, axis=1)


def _sibling_train_net(seed=7):
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = seed
    with fluid.program_guard(main, startup):
        x = fluid.layers.data(name='x', shape=[4, 8, 8], dtype='float32')
        label = fluid.layers.data(name='y', shape=[1], dtype='int64')
        cat = _inception_head(x)
        pooled = fluid.layers.pool2d(cat, pool_size=8, pool_type='avg')
        logits = fluid.layers.fc(pooled, size=4)
        loss = fluid.layers.mean(
            fluid.layers.softmax_with_cross_entropy(logits=logits,
                                                    label=label))
        fluid.optimizer.Momentum(learning_rate=0.05,
                                 momentum=0.9).minimize(loss)
    return main, startup, loss


def _sibling_feed(rng=None):
    rng = rng or np.random.RandomState(0)
    return {'x': rng.randn(2, 4, 8, 8).astype(np.float32),
            'y': rng.randint(0, 4, (2, 1)).astype(np.int64)}


# ---------------------------------------------------------------------------
# rewrite shape + report contract
# ---------------------------------------------------------------------------
def test_report_names_fusions_and_reasons():
    main, startup, loss = _sibling_train_net()
    prog, report = horizontal_fuse_program(main, fetch_names=[loss.name])
    assert report.details['groups_fused'] == 1
    assert report.details['convs_fused'] == 3
    (grp,) = report.details['fused_groups']
    assert grp['input'] == 'x'
    assert grp['out_channels'] == [3, 5, 2]
    assert len(grp['filters']) == len(grp['outputs']) == 3
    # every declined conv carries a machine-checkable reason
    for entry in report.details['skipped']:
        assert entry['reason'] in REASON_CODES, entry
    # the widened program: one conv where three were, plus concat + split
    types = [op.type for op in prog.global_block().ops]
    assert types.count('conv2d') == \
        [op.type for op in main.global_block().ops].count('conv2d') - 2
    assert 'split' in types
    # source untouched (clone semantics): its convs are still separate
    src_types = [op.type for op in main.global_block().ops]
    assert src_types.count('conv2d') == 3
    assert 'split' not in src_types


def test_bit_identity_sibling_train_grad_path():
    """Fused vs unfused train program agree bit-for-bit across optimizer
    steps — the grad ops re-lower off the original output names that the
    split keeps bound."""
    main, startup, loss = _sibling_train_net()
    exe, snap = _init_state(startup)
    feed = _sibling_feed()
    prog, report = horizontal_fuse_program(main, fetch_names=[loss.name])
    assert report.details['convs_fused'] == 3
    base = _run_from(exe, snap, main, feed, [loss.name], steps=3)
    opt = _run_from(exe, snap, prog, feed, [loss.name], steps=3)
    _assert_identical(base, opt)


def test_bit_identity_full_pipeline():
    """The whole OPTIMIZATION_PIPELINE (which now includes
    horizontal_fuse) stays bit-identical on the sibling net."""
    main, startup, loss = _sibling_train_net()
    exe, snap = _init_state(startup)
    feed = _sibling_feed()
    prog, reports = PassManager(list(passes.OPTIMIZATION_PIPELINE)).apply(
        main, fetch_names=[loss.name])
    hf = next(r for r in reports if r.name == 'horizontal_fuse')
    assert hf.details['convs_fused'] == 3
    base = _run_from(exe, snap, main, feed, [loss.name])
    opt = _run_from(exe, snap, prog, feed, [loss.name])
    _assert_identical(base, opt)


def test_bit_identity_googlenet_train():
    """The real target: googlenet's 9 inception modules each contribute a
    3-conv sibling group (27 convs fused). Documented tolerance: on the
    test env's 8-device virtual CPU platform XLA reduces the widened
    conv with a different grouping than three narrow convs, so losses
    drift in the last float32 ulp by step 2 (7.7490387 vs 7.7490377) —
    rtol 1e-5 here; the small nets above stay exactly bit-identical."""
    from models.googlenet import build_train_net
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = 11
    with fluid.program_guard(main, startup):
        images, label, loss, acc = build_train_net(
            dshape=(3, 64, 64), class_dim=10, lr=0.001)
    exe, snap = _init_state(startup)
    rng = np.random.RandomState(4)
    feed = {'data': rng.randn(2, 3, 64, 64).astype(np.float32),
            'label': rng.randint(0, 10, (2, 1)).astype(np.int64)}
    prog, report = horizontal_fuse_program(main, fetch_names=[loss.name])
    assert report.details['groups_fused'] == 9
    assert report.details['convs_fused'] == 27
    base = _run_from(exe, snap, main, feed, [loss.name])
    opt = _run_from(exe, snap, prog, feed, [loss.name])
    for step_a, step_b in zip(base, opt):
        np.testing.assert_allclose(np.asarray(step_a[0]),
                                   np.asarray(step_b[0]),
                                   rtol=1e-5, atol=0)


def test_smallnet_is_a_noop():
    """A sequential conv net has no sibling groups: the pass must decline
    every conv with a reason and leave the program alone."""
    from models.smallnet import build_train_net
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = 5
    with fluid.program_guard(main, startup):
        images, label, loss, acc = build_train_net()
    n0 = len(main.global_block().ops)
    prog, report = horizontal_fuse_program(main, fetch_names=[loss.name])
    assert report.details['convs_fused'] == 0
    assert len(prog.global_block().ops) == n0
    for entry in report.details['skipped']:
        assert entry['reason'] in REASON_CODES


# ---------------------------------------------------------------------------
# safety guards
# ---------------------------------------------------------------------------
def test_rebound_input_not_merged():
    """Two convs reading the same NAME across an in-place rewrite of it
    see different values — the (name, def site) group key must keep them
    apart, and numerics must hold."""
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = 9
    with fluid.program_guard(main, startup):
        x = fluid.layers.data(name='x', shape=[4, 8, 8], dtype='float32')
        a = fluid.layers.conv2d(x, num_filters=3, filter_size=1)
        fluid.layers.increment(x, value=1.0, in_place=True)
        b = fluid.layers.conv2d(x, num_filters=3, filter_size=1)
        out = fluid.layers.concat([a, b], axis=1)
    prog, report = horizontal_fuse_program(main, fetch_names=[out.name])
    assert report.details['convs_fused'] == 0
    reasons = [e['reason'] for e in report.details['skipped']]
    assert reasons.count(REASON_NO_SIBLING) == 2
    exe, snap = _init_state(startup)
    feed = {'x': np.random.RandomState(1).randn(2, 4, 8, 8)
            .astype(np.float32)}
    base = _run_from(exe, snap, main, feed, [out.name], steps=1)
    opt = _run_from(exe, snap, prog, feed, [out.name], steps=1)
    _assert_identical(base, opt)


def test_grouped_and_mismatched_convs_skip():
    """groups>1 is declined with its own code; different kernel geometry
    lands in different groups (singletons -> no_sibling)."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data(name='x', shape=[4, 8, 8], dtype='float32')
        g = fluid.layers.conv2d(x, num_filters=4, filter_size=1, groups=2)
        k1 = fluid.layers.conv2d(x, num_filters=3, filter_size=1)
        k3 = fluid.layers.conv2d(x, num_filters=3, filter_size=3, padding=1)
        out = fluid.layers.concat([g, k1, k3], axis=1)
    prog, report = horizontal_fuse_program(main, fetch_names=[out.name])
    assert report.details['convs_fused'] == 0
    reasons = report.details['skip_reasons']
    assert reasons.get(REASON_GROUPED) == 1
    assert reasons.get(REASON_NO_SIBLING) == 2


def test_env_disable_and_user_skip(monkeypatch):
    main, startup, loss = _sibling_train_net()
    # PTPU_HFUSE=0: the ablation A/B switch — rewrite off, report says so
    monkeypatch.setenv('PTPU_HFUSE', '0')
    prog, report = horizontal_fuse_program(main, fetch_names=[loss.name])
    assert report.details.get('disabled') is True
    assert len(prog.global_block().ops) == len(main.global_block().ops)
    monkeypatch.delenv('PTPU_HFUSE')
    # skip_vars: pin one branch's output; the other two still fuse
    pinned = next(op.outputs['Output'][0]
                  for op in main.global_block().ops if op.type == 'conv2d')
    prog2, report2 = horizontal_fuse_program(
        main, fetch_names=[loss.name], skip_vars=(pinned,))
    assert report2.details['convs_fused'] == 2
    assert any(e['reason'] == REASON_USER_SKIP
               for e in report2.details['skipped'])


# ---------------------------------------------------------------------------
# fuse_activation interaction (the pipeline order note's regression)
# ---------------------------------------------------------------------------
def test_per_branch_act_epilogues_survive():
    """horizontal_fuse runs BEFORE fuse_activation: the split rebinds
    each branch's conv output, so the per-branch bias-add + relu
    epilogues still sit on per-branch names and fuse_activation folds
    each relu into its own elementwise_add — nothing is lost to the
    widened conv. (Referenced by the OPTIMIZATION_PIPELINE order note in
    passes/__init__.py.)"""
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = 13
    with fluid.program_guard(main, startup):
        x = fluid.layers.data(name='x', shape=[4, 8, 8], dtype='float32')
        out = _inception_head(x, act='relu')
    exe, snap = _init_state(startup)
    feed = {'x': np.random.RandomState(2).randn(2, 4, 8, 8)
            .astype(np.float32)}
    prog, reports = passes.apply_inference_pipeline(
        main, fetch_names=[out.name])
    hf = next(r for r in reports if r.name == 'horizontal_fuse')
    fa = next(r for r in reports if r.name == 'fuse_activation')
    assert hf.details['convs_fused'] == 3
    assert fa.details['fused'] >= 3      # one relu per branch folded
    types = [op.type for op in prog.global_block().ops]
    assert 'relu' not in types
    base = _run_from(exe, snap, main, feed, [out.name], steps=1)
    opt = _run_from(exe, snap, prog, feed, [out.name], steps=1)
    _assert_identical(base, opt)


# ---------------------------------------------------------------------------
# run_steps composition
# ---------------------------------------------------------------------------
def test_run_steps_parity_pass_off_on():
    """Pass-off vs pass-on programs dispatched through run_steps(K) give
    the same per-step losses — the ablation mode's parity invariant."""
    main, startup, loss = _sibling_train_net()
    exe, snap = _init_state(startup)
    rng = np.random.RandomState(3)
    K = 3
    feed = {'x': rng.randn(K, 2, 4, 8, 8).astype(np.float32),
            'y': rng.randint(0, 4, (K, 2, 1)).astype(np.int64)}
    prog, report = horizontal_fuse_program(main, fetch_names=[loss.name])
    assert report.details['convs_fused'] == 3

    def steps_from(program):
        scope = fluid.core.Scope()
        for k, v in snap.items():
            scope.set(k, v)
        with fluid.scope_guard(scope):
            l, = exe.run_steps(program, feed=feed, fetch_list=[loss.name],
                               steps=K, fetch_policy='stack')
        return np.asarray(l)

    np.testing.assert_array_equal(steps_from(main), steps_from(prog))
