"""VGG model family builds and trains (benchmark parity with the
reference's benchmark/fluid/models/vgg.py; the committed Xeon number it
benches against lives in BASELINE.md)."""
import numpy as np

import paddle_tpu as fluid
from models.vgg import build_train_net


def test_vgg16_trains_one_batch():
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 5
    with fluid.program_guard(main, startup):
        # lr=0.01 overshoots to NaN by step 3 on a 2-sample random batch
        # (1.31 -> 0.50 -> nan); at 1e-3 the two dropout(0.5) head layers
        # make per-step loss noisy (1.31 -> 1.15 -> 1.36 under the test
        # env's 8-device virtual CPU platform) but it is reliably below
        # start by step 6 (0.91) -- measure over 6 steps, not 3
        images, label, loss, acc = build_train_net(
            dshape=(3, 32, 32), class_dim=10, depth=16, lr=0.001)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    r = np.random.RandomState(0)
    feed = {'data': r.randn(2, 3, 32, 32).astype(np.float32),
            'label': r.randint(0, 10, (2, 1)).astype(np.int64)}
    vals = []
    for _ in range(6):
        l, = exe.run(main, feed=feed, fetch_list=[loss])
        vals.append(float(np.asarray(l).reshape(-1)[0]))
    assert np.isfinite(vals).all(), vals
    assert vals[-1] < vals[0], vals
