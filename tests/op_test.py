"""OpTest harness (ref: python/paddle/fluid/tests/unittests/op_test.py).

check_output: run a single op via a scratch program and compare against the
test's numpy reference. check_grad: compare the framework's analytic grads
(append_backward's vjp-derived grad ops) against central-difference numeric
gradients of a summed output — the same methodology as the reference
(op_test.py:43 get_numeric_gradient, :303 check_output, :414 check_grad).
"""
from __future__ import annotations

import numpy as np

import paddle_tpu as fluid
from paddle_tpu.core.lod import LoDArray
from paddle_tpu.lod_tensor import create_lod_tensor


def _as_feed_value(v):
    if isinstance(v, tuple):  # (data, recursive_seq_lens) LoD convention
        return create_lod_tensor(v[0], v[1])
    return v


class OpTest(object):
    """Subclass sets: op_type, inputs {slot: np | [(name, np), ...]},
    attrs, outputs {slot: np | [(name, np), ...]}."""

    op_type = None
    inputs = {}
    outputs = {}
    attrs = {}

    def _build(self):
        main = fluid.Program()
        startup = fluid.Program()
        feed = {}
        with fluid.program_guard(main, startup):
            block = main.global_block()
            in_names = {}
            for slot, val in self.inputs.items():
                entries = val if isinstance(val, list) else [(slot, val)]
                names = []
                for name, arr in entries:
                    arr_v = _as_feed_value(arr)
                    data = arr_v.data if isinstance(arr_v, LoDArray) else arr_v
                    lod_level = len(arr_v.lod) if isinstance(arr_v, LoDArray) else 0
                    block.create_var(
                        name=name, shape=list(np.shape(data)),
                        dtype=str(np.asarray(data).dtype)
                        if not isinstance(arr_v, LoDArray)
                        else str(np.asarray(data).dtype),
                        lod_level=lod_level, stop_gradient=False)
                    feed[name] = arr_v
                    names.append(name)
                in_names[slot] = names

            out_names = {}
            out_expect = {}
            for slot, val in self.outputs.items():
                entries = val if isinstance(val, list) else [(slot, val)]
                names = []
                for name, arr in entries:
                    block.create_var(name=name, dtype='float32',
                                     stop_gradient=False)
                    names.append(name)
                    out_expect[name] = arr
                out_names[slot] = names

            block.append_op(type=self.op_type, inputs=in_names,
                            outputs=out_names, attrs=dict(self.attrs))
        return main, startup, feed, out_names, out_expect

    def check_output(self, atol=1e-5, rtol=1e-5, no_check_set=()):
        main, startup, feed, out_names, expect = self._build()
        exe = fluid.Executor(fluid.CPUPlace())
        scope = fluid.core.Scope()
        with fluid.scope_guard(scope):
            exe.run(startup)
            fetch = [n for names in out_names.values() for n in names
                     if n not in no_check_set and expect.get(n) is not None]
            outs = exe.run(program=main, feed=feed, fetch_list=fetch)
        for name, got in zip(fetch, outs):
            want = expect[name]
            if isinstance(want, tuple):
                want = want[0]
            np.testing.assert_allclose(
                got, np.asarray(want), atol=atol, rtol=rtol,
                err_msg="output %r of op %s mismatch" % (name, self.op_type))

    def check_grad(self, inputs_to_check, output_name, max_relative_error=5e-3,
                   numeric_delta=1e-3, no_grad_set=None):
        main, startup, feed, out_names, expect = self._build()
        with fluid.program_guard(main, startup):
            block = main.global_block()
            out_var = block.var(output_name)
            # loss = sum(output * fixed random weights): nonzero cotangents
            # even for outputs with structural zero-sum grads (softmax etc.)
            rng = np.random.RandomState(7)
            weighted = block.create_var(name='__loss_weighted__',
                                        dtype='float32', stop_gradient=False)
            wname = '__loss_w__'
            block.create_var(name=wname, dtype='float32',
                             stop_gradient=True)
            wshape = [int(s) for s in (out_var.shape or (1,))]
            wvals = rng.uniform(0.1, 1.0, size=wshape).astype(np.float32)
            block.append_op(type='assign_value',
                            outputs={'Out': [wname]},
                            attrs={'shape': wshape, 'dtype': 'float32',
                                   'fp32_values': [float(v)
                                                   for v in wvals.flat]})
            block.append_op(type='elementwise_mul',
                            inputs={'X': [output_name], 'Y': [wname]},
                            outputs={'Out': [weighted.name]},
                            attrs={'axis': -1})
            flat = block.create_var(name='__loss_flat__', dtype='float32',
                                    stop_gradient=False)
            block.append_op(type='reduce_sum', inputs={'X': [weighted.name]},
                            outputs={'Out': [flat.name]},
                            attrs={'reduce_all': True, 'dim': [0],
                                   'keep_dim': False})
            grads = fluid.append_backward(flat, no_grad_set=no_grad_set)

        exe = fluid.Executor(fluid.CPUPlace())
        scope = fluid.core.Scope()
        grad_names = [n + '@GRAD' for n in inputs_to_check]
        with fluid.scope_guard(scope):
            exe.run(startup)
            analytic = exe.run(program=main, feed=feed, fetch_list=grad_names)

        # numeric gradients by central difference on the summed output
        def eval_loss(feed_over):
            with fluid.scope_guard(scope):
                out, = exe.run(program=main, feed=feed_over,
                               fetch_list=[flat.name])
            return float(np.asarray(out).reshape(-1)[0])

        for in_name, got in zip(inputs_to_check, analytic):
            base = feed[in_name]
            base_data = np.array(base.data if isinstance(base, LoDArray)
                                 else base, dtype=np.float64)
            num = np.zeros_like(base_data, dtype=np.float64)
            flat_view = base_data.reshape(-1)
            num_flat = num.reshape(-1)
            for i in range(flat_view.size):
                orig = flat_view[i]
                for sign in (+1, -1):
                    flat_view[i] = orig + sign * numeric_delta
                    f2 = dict(feed)
                    pert = base_data.astype(np.float32)
                    f2[in_name] = (LoDArray(pert, base.lod)
                                   if isinstance(base, LoDArray) else pert)
                    if sign > 0:
                        f_pos = eval_loss(f2)
                    else:
                        f_neg = eval_loss(f2)
                flat_view[i] = orig
                num_flat[i] = (f_pos - f_neg) / (2 * numeric_delta)
            got = np.asarray(got, dtype=np.float64)
            abs_max = max(np.abs(num).max(), np.abs(got).max(), 1e-3)
            rel_err = np.abs(got - num).max() / abs_max
            assert rel_err < max_relative_error, (
                "gradient of %s w.r.t %s: max rel err %.5f (analytic vs "
                "numeric)\nanalytic:\n%s\nnumeric:\n%s" %
                (self.op_type, in_name, rel_err, got, num))
