"""Detection layer/op tests, mirroring the reference's
test_prior_box_op.py / test_iou_similarity_op.py / test_box_coder_op.py /
test_bipartite_match_op.py / test_multiclass_nms_op.py / test_ssd_loss.py
numeric methodology (numpy references), plus an SSD train step.
"""
import numpy as np
import pytest

import paddle_tpu as fluid


def _run(fetches, feed=None, startup=True):
    exe = fluid.Executor(fluid.CPUPlace())
    if startup:
        exe.run(fluid.default_startup_program())
    return exe.run(fluid.default_main_program(), feed=feed or {},
                   fetch_list=fetches)


def test_prior_box_values():
    x = fluid.layers.data(name='x', shape=[8, 4, 4], dtype='float32')
    img = fluid.layers.data(name='img', shape=[3, 32, 32], dtype='float32')
    boxes, var = fluid.layers.prior_box(
        x, img, min_sizes=[8.0], max_sizes=[16.0], aspect_ratios=[2.0],
        flip=True, clip=True)
    b, v = _run([boxes, var],
                feed={'x': np.zeros((1, 8, 4, 4), np.float32),
                      'img': np.zeros((1, 3, 32, 32), np.float32)},
                startup=False)
    # priors per location: ar=1(min) + ar=2 + ar=0.5 + max = 4
    assert b.shape == (4, 4, 4, 4)
    # location (0,0): center = (0.5*8, 0.5*8) = (4, 4); min_size prior:
    # [4-4, 4-4, 4+4, 4+4]/32 = [0, 0, .25, .25]
    np.testing.assert_allclose(b[0, 0, 0], [0, 0, 0.25, 0.25], atol=1e-6)
    np.testing.assert_allclose(v[0, 0, 0], [0.1, 0.1, 0.2, 0.2], atol=1e-6)


def test_iou_and_box_coder_roundtrip():
    gt = fluid.layers.data(name='gt', shape=[4], dtype='float32',
                           lod_level=1)
    prior = fluid.layers.data(name='prior', shape=[4], dtype='float32')
    pvar = fluid.layers.data(name='pvar', shape=[4], dtype='float32')
    iou = fluid.layers.iou_similarity(x=gt, y=prior)
    enc = fluid.layers.box_coder(prior_box=prior, prior_box_var=pvar,
                                 target_box=gt,
                                 code_type='encode_center_size')
    gt_np = np.array([[0.1, 0.1, 0.5, 0.5], [0.4, 0.4, 0.8, 0.9]],
                     np.float32)
    prior_np = np.array([[0.0, 0.0, 0.4, 0.4], [0.5, 0.5, 1.0, 1.0]],
                        np.float32)
    pvar_np = np.full((2, 4), 0.1, np.float32)
    o_iou, o_enc = _run(
        [iou, enc],
        feed={'gt': fluid.create_lod_tensor(gt_np, [[2]]),
              'prior': prior_np, 'pvar': pvar_np}, startup=False)
    # manual IoU of gt0 vs prior0: inter = 0.3*0.3 = 0.09;
    # union = 0.16 + 0.16 - 0.09
    np.testing.assert_allclose(o_iou[0, 0], 0.09 / 0.23, rtol=1e-5)
    # encode then decode returns the original gt (roundtrip)
    dec = fluid.layers.box_coder(prior_box=prior, prior_box_var=pvar,
                                 target_box=fluid.layers.data(
                                     name='d', shape=[2, 4],
                                     dtype='float32'),
                                 code_type='decode_center_size')
    o_dec, = _run([dec], feed={'gt': fluid.create_lod_tensor(gt_np, [[2]]),
                               'prior': prior_np, 'pvar': pvar_np,
                               'd': o_enc}, startup=False)
    for i in range(2):
        np.testing.assert_allclose(o_dec[i, i], gt_np[i], rtol=1e-4,
                                   atol=1e-5)


def test_bipartite_match_greedy():
    dist = fluid.layers.data(name='dist', shape=[3], dtype='float32',
                             lod_level=1)
    idx, dv = fluid.layers.bipartite_match(dist)
    d = np.array([[0.8, 0.2, 0.1],
                  [0.7, 0.9, 0.3]], np.float32)  # 2 gt x 3 priors
    o_idx, o_dv = _run([idx, dv],
                       feed={'dist': fluid.create_lod_tensor(d, [[2]])},
                       startup=False)
    # greedy global max: (1,1)=0.9 first, then (0,0)=0.8
    assert o_idx[0, 1] == 1 and o_idx[0, 0] == 0
    assert o_idx[0, 2] == -1
    np.testing.assert_allclose(o_dv[0, :2], [0.8, 0.9], rtol=1e-6)


def test_target_assign_per_prior_semantics():
    """3-D X (encoded boxes [N_gt, M, 4]): Out[b, m] must be
    X[lod[b] + match[b, m], m] — the per-PRIOR column, not a flat row."""
    x = fluid.layers.data(name='enc', shape=[3, 4], dtype='float32',
                          lod_level=1)
    mi = fluid.layers.data(name='mi', shape=[3], dtype='int32')
    out, w = fluid.layers.target_assign(x, mi)
    enc = np.arange(2 * 3 * 4, dtype=np.float32).reshape(2, 3, 4)
    match = np.array([[1, -1, 0]], np.int32)  # 1 image, 3 priors
    o, ow = _run([out, w],
                 feed={'enc': fluid.create_lod_tensor(enc, [[2]]),
                       'mi': match}, startup=False)
    np.testing.assert_allclose(o[0, 0], enc[1, 0])  # gt 1, prior column 0
    np.testing.assert_allclose(o[0, 2], enc[0, 2])  # gt 0, prior column 2
    np.testing.assert_allclose(o[0, 1], np.zeros(4))  # unmatched
    np.testing.assert_allclose(ow[0, :, 0], [1, 0, 1])


def test_multiclass_nms_suppresses():
    bb = fluid.layers.data(name='bb', shape=[4, 4], dtype='float32')
    sc = fluid.layers.data(name='sc', shape=[2, 4], dtype='float32')
    out = fluid.layers.multiclass_nms(bb, sc, score_threshold=0.1,
                                      nms_top_k=4, keep_top_k=3,
                                      nms_threshold=0.5, background_label=0)
    boxes = np.array([[[0, 0, 1, 1], [0, 0, 0.95, 1.0],
                       [0.5, 0.5, 1.0, 1.0], [2, 2, 3, 3]]], np.float32)
    scores = np.zeros((1, 2, 4), np.float32)
    scores[0, 1] = [0.9, 0.8, 0.05, 0.7]  # class 1 scores per box
    o, = _run([out], feed={'bb': boxes, 'sc': scores}, startup=False)
    o = np.asarray(o).reshape(-1, 6)
    kept = o[o[:, 0] >= 0]
    # box1 suppressed by box0 (iou ~0.95); box3 kept (disjoint);
    # box2 below score threshold
    assert len(kept) == 2
    np.testing.assert_allclose(sorted(kept[:, 1]), [0.7, 0.9], rtol=1e-5)


def test_roi_align_and_pool_shapes_and_values():
    x = fluid.layers.data(name='x', shape=[1, 4, 4], dtype='float32')
    rois = fluid.layers.data(name='rois', shape=[4], dtype='float32',
                             lod_level=1)
    al = fluid.layers.roi_align(x, rois, pooled_height=2, pooled_width=2,
                                spatial_scale=1.0, sampling_ratio=2)
    pl = fluid.layers.roi_pool(x, rois, pooled_height=2, pooled_width=2,
                               spatial_scale=1.0)
    img = np.arange(16, dtype=np.float32).reshape(1, 1, 4, 4)
    r = np.array([[0.0, 0.0, 3.0, 3.0]], np.float32)
    o_al, o_pl = _run([al, pl],
                      feed={'x': img,
                            'rois': fluid.create_lod_tensor(r, [[1]])},
                      startup=False)
    assert o_al.shape == (1, 1, 2, 2)
    assert o_pl.shape == (1, 1, 2, 2)
    # roi_pool of the quantized quadrants of rows 0..3 x cols 0..3:
    # max of top-left 2x2 block = 5
    assert o_pl[0, 0, 0, 0] == 5.0
    assert o_pl[0, 0, 1, 1] == 15.0
    # roi_align averages stay within the value range
    assert 0.0 <= float(o_al[0, 0, 0, 0]) <= 15.0


def test_yolov3_loss_decreases():
    main_p, startup_p = fluid.Program(), fluid.Program()
    main_p.random_seed = startup_p.random_seed = 31
    with fluid.program_guard(main_p, startup_p):
        feat = fluid.layers.data(name='feat', shape=[3, 8, 8],
                                 dtype='float32')
        conv = fluid.layers.conv2d(feat, num_filters=3 * (5 + 2),
                                   filter_size=3, padding=1)
        gtb = fluid.layers.data(name='gtb', shape=[2, 4], dtype='float32')
        gtl = fluid.layers.data(name='gtl', shape=[2], dtype='int64')
        loss = fluid.layers.mean(fluid.layers.yolov3_loss(
            conv, gtb, gtl, anchors=[10, 13, 16, 30, 33, 23],
            anchor_mask=[0, 1, 2], class_num=2, ignore_thresh=0.7,
            downsample_ratio=32))
        fluid.optimizer.Adam(5e-3).minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.core.Scope()
    rng = np.random.RandomState(0)
    feed = {'feat': rng.randn(2, 3, 8, 8).astype(np.float32),
            'gtb': np.array([[[0.3, 0.3, 0.2, 0.2], [0.7, 0.7, 0.2, 0.3]],
                             [[0.5, 0.5, 0.4, 0.4], [0, 0, 0, 0]]],
                            np.float32),
            'gtl': np.array([[0, 1], [1, 0]])}
    with fluid.scope_guard(scope):
        exe.run(startup_p)
        losses = []
        # 15 steps lands at 0.815x — a hair over the 0.8 bar, not a
        # plateau: the descent is steady (0.724x @25, 0.686x @30)
        for _ in range(30):
            l, = exe.run(main_p, feed=feed, fetch_list=[loss])
            losses.append(float(np.asarray(l).reshape(-1)[0]))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0] * 0.8


def test_ssd_loss_builds_and_trains():
    """The directive's acceptance test: an SSD-style loss builds and trains
    a step end-to-end (multi_box_head + ssd_loss + detection_output)."""
    main_p, startup_p = fluid.Program(), fluid.Program()
    main_p.random_seed = startup_p.random_seed = 4
    with fluid.program_guard(main_p, startup_p):
        img = fluid.layers.data(name='img', shape=[3, 32, 32],
                                dtype='float32')
        gt_box = fluid.layers.data(name='gt_box', shape=[4],
                                   dtype='float32', lod_level=1)
        gt_lbl = fluid.layers.data(name='gt_lbl', shape=[1],
                                   dtype='int64', lod_level=1)
        c1 = fluid.layers.conv2d(img, 8, 3, stride=2, padding=1,
                                 act='relu')
        c2 = fluid.layers.conv2d(c1, 16, 3, stride=2, padding=1,
                                 act='relu')
        locs, confs, box, var = fluid.layers.multi_box_head(
            inputs=[c1, c2], image=img, base_size=32, num_classes=3,
            aspect_ratios=[[2.0], [2.0]], min_sizes=[8.0, 16.0],
            max_sizes=[16.0, 24.0], flip=True)
        loss = fluid.layers.reduce_sum(fluid.layers.ssd_loss(
            locs, confs, gt_box, gt_lbl, box, var))
        fluid.optimizer.Adam(1e-3).minimize(loss)
        nmsed = fluid.layers.detection_output(
            locs, confs, box, var, score_threshold=0.01, keep_top_k=10)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.core.Scope()
    rng = np.random.RandomState(0)
    gt_b = np.array([[0.1, 0.1, 0.4, 0.4], [0.5, 0.5, 0.9, 0.9],
                     [0.2, 0.3, 0.6, 0.8]], np.float32)
    gt_l = np.array([[1], [2], [1]])
    feed = {'img': rng.randn(2, 3, 32, 32).astype(np.float32),
            'gt_box': fluid.create_lod_tensor(gt_b, [[2, 1]]),
            'gt_lbl': fluid.create_lod_tensor(gt_l, [[2, 1]])}
    with fluid.scope_guard(scope):
        exe.run(startup_p)
        losses = []
        for _ in range(8):
            l, = exe.run(main_p, feed=feed, fetch_list=[loss])
            losses.append(float(np.asarray(l).reshape(-1)[0]))
        det, = exe.run(main_p, feed=feed, fetch_list=[nmsed])
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0]
    det = np.asarray(det).reshape(-1, 6)
    assert det.shape[1] == 6  # [label, score, x0, y0, x1, y1]


def test_anchor_generator_and_proposals_pipeline():
    x = fluid.layers.data(name='x', shape=[8, 4, 4], dtype='float32')
    anchors, avar = fluid.layers.anchor_generator(
        x, anchor_sizes=[32.0], aspect_ratios=[1.0], stride=[8.0, 8.0])
    scores = fluid.layers.data(name='sc', shape=[1, 4, 4], dtype='float32')
    deltas = fluid.layers.data(name='dl', shape=[4, 4, 4], dtype='float32')
    im_info = fluid.layers.data(name='ii', shape=[3], dtype='float32')
    rois, probs = fluid.layers.generate_proposals(
        scores, deltas, im_info, anchors, avar, pre_nms_top_n=16,
        post_nms_top_n=8, nms_thresh=0.7)
    rng = np.random.RandomState(0)
    o_anchors, o_rois, o_probs = _run(
        [anchors, rois, probs],
        feed={'x': np.zeros((1, 8, 4, 4), np.float32),
              'sc': rng.rand(1, 1, 4, 4).astype(np.float32),
              'dl': (0.1 * rng.randn(1, 4, 4, 4)).astype(np.float32),
              'ii': np.array([[32.0, 32.0, 1.0]], np.float32)},
        startup=False)
    assert o_anchors.shape == (4, 4, 1, 4)
    assert np.asarray(o_rois).shape == (8, 4)
    assert np.isfinite(np.asarray(o_rois)).all()


def test_detection_map_perfect_predictions():
    det = fluid.layers.data(name='det', shape=[6], dtype='float32',
                            lod_level=1)
    lbl = fluid.layers.data(name='lbl', shape=[5], dtype='float32',
                            lod_level=1)
    m = fluid.layers.detection_map(det, lbl, class_num=3,
                                   overlap_threshold=0.5)
    gt = np.array([[1, 0.1, 0.1, 0.4, 0.4],
                   [2, 0.5, 0.5, 0.9, 0.9]], np.float32)
    # detections exactly on the gt boxes with high scores
    d = np.array([[1, 0.9, 0.1, 0.1, 0.4, 0.4],
                  [2, 0.8, 0.5, 0.5, 0.9, 0.9]], np.float32)
    o, = _run([m], feed={'det': fluid.create_lod_tensor(d, [[2]]),
                         'lbl': fluid.create_lod_tensor(gt, [[2]])},
              startup=False)
    assert float(np.asarray(o).reshape(-1)[0]) == pytest.approx(1.0)


def test_polygon_box_transform():
    g = fluid.layers.data(name='g', shape=[8, 2, 2], dtype='float32')
    out = fluid.layers.polygon_box_transform(g)
    inp = np.ones((1, 8, 2, 2), np.float32)
    o, = _run([out], feed={'g': inp}, startup=False)
    # channel 0 (x-offset) at pixel (0, 1): 4*1 - 1 = 3
    assert o[0, 0, 0, 1] == 3.0
    # channel 1 (y-offset) at pixel (1, 0): 4*1 - 1 = 3
    assert o[0, 1, 1, 0] == 3.0


def test_mine_hard_examples_hard_example_mode():
    """hard_example mining (ref mine_hard_examples_op.cc kHardExample):
    every prior is eligible, top-sample_size by cls+loc loss selected;
    unselected positives are DEMOTED to -1, selected negatives emitted
    in ascending prior order."""
    from paddle_tpu.core.registry import get as get_op
    from paddle_tpu.core.lod import LoDArray
    import jax.numpy as jnp

    cls = np.array([[0.9, 0.1, 0.8, 0.2, 0.7, 0.3]], np.float32)
    loc = np.array([[0.0, 0.0, 0.0, 0.5, 0.0, 0.0]], np.float32)
    match = np.array([[0, -1, 1, -1, -1, -1]], np.int32)
    dist = np.zeros((1, 6), np.float32)

    class Ctx:
        attrs = {'mining_type': 'hard_example', 'sample_size': 3}
        is_test = False

        def attr(self, k, d=None):
            return self.attrs.get(k, d)

    outs = get_op('mine_hard_examples').lower(Ctx(), {
        'ClsLoss': [jnp.asarray(cls)], 'LocLoss': [jnp.asarray(loc)],
        'MatchIndices': [jnp.asarray(match)],
        'MatchDist': [jnp.asarray(dist)]})
    upd = np.asarray(outs['UpdatedMatchIndices'][0])
    neg = np.asarray(outs['NegIndices'][0].data).reshape(-1)
    # combined loss: [.9, .1, .8, .7, .7, .3] -> top-3 priors {0, 2, 3|4}
    # tie at .7 between priors 3 and 4: argsort keeps the earlier index
    assert upd[0, 0] == 0 and upd[0, 2] == 1     # selected positives kept
    sel_negs = neg[neg >= 0]
    np.testing.assert_array_equal(sel_negs, [3])  # top unmatched negative
    assert (upd[0, [1, 4, 5]] == -1).all()        # unmatched stay -1


def test_ssd_loss_hard_example_trains():
    """ssd_loss with mining_type='hard_example' + sample_size builds and
    trains (the reference's alternative mining mode, previously a
    documented raise)."""
    main_p, startup_p = fluid.Program(), fluid.Program()
    main_p.random_seed = startup_p.random_seed = 4
    with fluid.program_guard(main_p, startup_p):
        img = fluid.layers.data(name='img', shape=[3, 32, 32],
                                dtype='float32')
        gt_box = fluid.layers.data(name='gt_box', shape=[4],
                                   dtype='float32', lod_level=1)
        gt_lbl = fluid.layers.data(name='gt_lbl', shape=[1],
                                   dtype='int64', lod_level=1)
        c1 = fluid.layers.conv2d(img, 8, 3, stride=2, padding=1,
                                 act='relu')
        c2 = fluid.layers.conv2d(c1, 16, 3, stride=2, padding=1,
                                 act='relu')
        locs, confs, box, var = fluid.layers.multi_box_head(
            inputs=[c1, c2], image=img, base_size=32, num_classes=3,
            aspect_ratios=[[2.0], [2.0]], min_sizes=[8.0, 16.0],
            max_sizes=[16.0, 24.0], flip=True)
        loss = fluid.layers.reduce_sum(fluid.layers.ssd_loss(
            locs, confs, gt_box, gt_lbl, box, var,
            mining_type='hard_example', sample_size=20))
        fluid.optimizer.Adam(1e-3).minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.core.Scope()
    rng = np.random.RandomState(0)
    gt_b = np.array([[0.1, 0.1, 0.4, 0.4], [0.5, 0.5, 0.9, 0.9],
                     [0.2, 0.3, 0.6, 0.8]], np.float32)
    gt_l = np.array([[1], [2], [1]])
    feed = {'img': rng.randn(2, 3, 32, 32).astype(np.float32),
            'gt_box': fluid.create_lod_tensor(gt_b, [[2, 1]]),
            'gt_lbl': fluid.create_lod_tensor(gt_l, [[2, 1]])}
    with fluid.scope_guard(scope):
        exe.run(startup_p)
        p0 = np.asarray(scope.get(
            main_p.global_block().all_parameters()[0].name)).copy()
        losses = []
        for _ in range(8):
            l, = exe.run(main_p, feed=feed, fetch_list=[loss])
            losses.append(float(np.asarray(l).reshape(-1)[0]))
        p1 = np.asarray(scope.get(
            main_p.global_block().all_parameters()[0].name))
    assert np.isfinite(losses).all()
    # the mined set RESELECTS harder priors as training moves, so the
    # summed loss need not fall monotonically in 8 steps — the contract
    # is that gradients flow through the mining path and update params
    assert not np.allclose(p0, p1)

    with pytest.raises(ValueError, match='sample_size'):
        with fluid.program_guard(fluid.Program(), fluid.Program()):
            fluid.layers.ssd_loss(locs, confs, gt_box, gt_lbl, box, var,
                                  mining_type='hard_example')
