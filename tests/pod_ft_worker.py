"""One pod-member incarnation for the pod-scale fault-tolerance tests
(tests/test_pod_ft.py, scripts/pod_ft_smoke.py, tools/chaos.py --pod).

usage: pod_ft_worker.py CKPT_DIR OUT_FILE TOTAL_STEPS EVERY \
           [KILL_AT_STEP [MIN_POD_COMMITS]]

env contract (set by the driver):
    PADDLE_TRAINERS / PADDLE_TRAINER_ID / PADDLE_COORDINATOR   pod shape
    PTPU_POD_RUN_ID     incarnation token (fresh per pod launch)
    PTPU_POD_HB_TIMEOUT watchdog heartbeat timeout (default 6s)

Each process joins the simulated pod (2 virtual cpu devices per host),
builds the SAME composed-mesh program (dp spans hosts x mp shards the fc
weight), feeds its LOCAL batch shard, and trains TOTAL steps with a
PodCheckpointManager policy every EVERY steps. KILL_AT_STEP > 0 SIGKILLs
this host once that many steps are trained (after MIN_POD_COMMITS pod
commits exist, so a restart provably has something to resume from) —
survivors detect the death through the heartbeat watchdog and exit 3 in
bounded time instead of blocking forever in the next collective.

OUT_FILE lines (append, flushed per step):
    RESUME <step> <startup_s>    restore point of this incarnation
    <step_idx> <loss>            replicated loss: identical on all hosts
    STALL <ckpt_stall_pct>       checkpoint stall as % of run time
    DONE <params_sha256>         full-pod-gathered params digest
"""
import hashlib
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault('XLA_FLAGS', '--xla_force_host_platform_device_count=2')
os.environ['PTPU_PLATFORM'] = 'cpu'

from paddle_tpu.parallel import multihost

# join the pod BEFORE any backend use
N, RANK = multihost.init_distributed(platform='cpu')

import numpy as np
import paddle_tpu as fluid
from paddle_tpu.core.checkpoint import PodCheckpointManager, HostWatchdog
from paddle_tpu.parallel import shard_parameter
from paddle_tpu.parallel.mesh import make_mesh
from paddle_tpu.parallel.compiler import CompiledProgram
from paddle_tpu.testing import faults

LOCAL_BS = 4


def build(seed=17):
    main_p, startup_p = fluid.Program(), fluid.Program()
    main_p.random_seed = startup_p.random_seed = seed
    with fluid.program_guard(main_p, startup_p):
        x = fluid.layers.data(name='x', shape=[16], dtype='float32')
        lab = fluid.layers.data(name='lab', shape=[1], dtype='int64')
        h = fluid.layers.fc(x, size=32, act='relu',
                            param_attr=fluid.ParamAttr(name='fc1_w'))
        h = fluid.layers.dropout(h, dropout_prob=0.2)
        logits = fluid.layers.fc(h, size=5,
                                 param_attr=fluid.ParamAttr(name='fc2_w'))
        loss = fluid.layers.mean(fluid.layers.softmax_with_cross_entropy(
            logits=logits, label=lab))
        fluid.optimizer.Momentum(learning_rate=0.1,
                                 momentum=0.9).minimize(loss)
    # composed sharding: fc1_w column-parallel over mp (within a host),
    # fc2_w row-sharded over dp — the axis that SPANS hosts — so the pod
    # checkpoint has genuinely cross-host mesh-local shards to write
    # (and its optimizer slots inherit the annotations, executor._build)
    shard_parameter(main_p.global_block().var('fc1_w'), (None, 'mp'))
    shard_parameter(main_p.global_block().var('fc2_w'), ('dp', None))
    return main_p, startup_p, loss


def feed_for(step, rank):
    r = np.random.RandomState(1000 + 10 * step + rank)  # per-host shard
    return {'x': r.randn(LOCAL_BS, 16).astype(np.float32),
            'lab': r.randint(0, 5, (LOCAL_BS, 1))}


def params_sha(program, scope):
    from paddle_tpu.io import _full_value
    from paddle_tpu.core.lod import unwrap
    h = hashlib.sha256()
    for name in sorted(v.name for v in program.list_vars() if v.persistable):
        val = scope.get(name)
        if val is not None:
            h.update(name.encode())
            h.update(np.ascontiguousarray(
                np.asarray(unwrap(_full_value(val)))).tobytes())
    return h.hexdigest()


def main():
    ckpt_dir, out_path = sys.argv[1], sys.argv[2]
    total, every = int(sys.argv[3]), int(sys.argv[4])
    kill_at = int(sys.argv[5]) if len(sys.argv) > 5 else 0
    min_commits = int(sys.argv[6]) if len(sys.argv) > 6 else 1

    import time
    run_id = multihost.pod_run_id()
    hb_timeout = float(os.environ.get('PTPU_POD_HB_TIMEOUT', '6'))

    main_p, startup_p, loss = build()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup_p)
    mesh = make_mesh(axes={'dp': N, 'mp': 2})
    prog = CompiledProgram(main_p).with_data_parallel(loss_name=loss.name,
                                                      mesh=mesh)

    t0 = time.perf_counter()
    mgr = PodCheckpointManager(ckpt_dir, rank=RANK, num_hosts=N,
                               every_steps=every, keep_last_n=3,
                               commit_timeout_s=30,
                               heartbeat_interval_s=0.2, run_id=run_id)
    wd = HostWatchdog(ckpt_dir, rank=RANK, num_hosts=N,
                      timeout_s=hb_timeout, run_id=run_id,
                      action='exit', exit_code=3).start()
    info = mgr.restore(executor=exe, program=prog)
    startup_s = time.perf_counter() - t0
    step = int(info['step']) if info else 0

    out = open(out_path, 'a')

    def emit(line):
        out.write(line + '\n')
        out.flush()
        os.fsync(out.fileno())

    emit('RESUME %d %.3f' % (step, startup_s))
    # a resumed incarnation provably has a pod-committed checkpoint
    if step > 0:
        min_commits = 0
    while step < total:
        l, = exe.run(prog, feed=feed_for(step, RANK), fetch_list=[loss],
                     checkpoint=mgr)
        step += 1
        emit('%d %.17g' % (step - 1, float(np.asarray(l).reshape(-1)[0])))
        if kill_at and step >= kill_at:
            # wait until a POD-committed checkpoint exists ON DISK (the
            # coordinator writes POD_COMMIT — stats only count it on rank
            # 0), so the restart provably has something to resume from;
            # any write beyond that still races the SIGKILL
            import glob
            deadline = time.time() + 30
            while min_commits and time.time() < deadline and not glob.glob(
                    os.path.join(ckpt_dir, 'ckpt-*', 'POD_COMMIT.json')):
                time.sleep(0.01)
            faults.kill_self()
        faults.maybe_kill_at_step(step)
    mgr.save(prog, fluid.global_scope(), step, blocking=True, executor=exe)
    st = exe._dispatch_stats
    emit('STALL %.4f' % (100.0 * st['ckpt_stall_s'] / st['run_s']
                         if st['run_s'] else 0.0))
    emit('DONE %s' % params_sha(main_p, fluid.global_scope()))
    # belt over the close() tombstone: every host clears the finish line
    # together (mgr.barrier salts the name with the run_id)
    mgr.barrier('done', timeout_s=60)
    wd.stop()
    mgr.close()


if __name__ == '__main__':
    main()
