"""Worker process for the multi-host test (spawned by test_multihost.py).

Each process joins the distributed runtime (PADDLE_TRAINERS /
PADDLE_TRAINER_ID / PADDLE_COORDINATOR), builds the SAME program, feeds its
LOCAL batch shard, and prints per-step losses — the in-process port of the
reference's test_dist_base subprocess methodology.
"""
import os
import sys

os.environ.setdefault('XLA_FLAGS', '--xla_force_host_platform_device_count=4')
os.environ['PTPU_PLATFORM'] = 'cpu'
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from paddle_tpu.parallel import multihost

# join the pod BEFORE any backend use; 'cpu' pins the simulated pod platform
multihost.init_distributed(platform='cpu')

import numpy as np
import paddle_tpu as fluid
from paddle_tpu.parallel.mesh import make_mesh
from paddle_tpu.parallel.compiler import CompiledProgram

from models.bert import build_bert_pretrain, shard_for_mesh

TRAINER_ID = int(os.environ['PADDLE_TRAINER_ID'])
TRAINERS = int(os.environ['PADDLE_TRAINERS'])
LOCAL_BS = 8
S = 16


def main():
    main_p, startup_p = fluid.Program(), fluid.Program()
    main_p.random_seed = startup_p.random_seed = 7
    with fluid.program_guard(main_p, startup_p):
        feeds, loss = build_bert_pretrain(
            vocab=500, max_len=S, d_model=32, d_ff=64, n_head=2, n_layer=2,
            dropout=0.0, lr=1e-3)
    shard_for_mesh(main_p)

    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup_p)

    # dp spans both hosts (4 local devices x 2 hosts = dp 4 x mp 2)
    mesh = make_mesh(axes={'dp': 4, 'mp': 2})
    prog = CompiledProgram(main_p).with_data_parallel(loss_name=loss.name,
                                                      mesh=mesh)
    rng = np.random.RandomState(100 + TRAINER_ID)  # per-host data shard
    losses = []
    for _ in range(3):
        feed = {'tok_ids': rng.randint(1, 500, (LOCAL_BS, S)),
                'seg_ids': rng.randint(0, 2, (LOCAL_BS, S)),
                'mlm_labels': rng.randint(1, 500, (LOCAL_BS, S)),
                'mlm_weights': (rng.rand(LOCAL_BS, S) < 0.15)
                .astype(np.float32)}
        l, = exe.run(prog, feed=feed, fetch_list=[loss])
        losses.append(float(np.asarray(l).reshape(-1)[0]))
    # one preformatted write: Gloo's C++ logging shares this fd and can
    # interleave between separate write() calls
    print('MHLOSSES %d %s'
          % (TRAINER_ID, ' '.join('%.6f' % v for v in losses)), flush=True)

    # dist_save_load equivalence (ref: tests/unittests/dist_save_load.py):
    # process 0 alone writes; the load broadcasts from process 0, so wipe
    # the scope first and prove the broadcast restores identical state
    ckpt = os.environ.get('PTPU_MH_CKPT')
    if ckpt:
        from paddle_tpu.core.scope import global_scope
        written = fluid.io.save_persistables(exe, ckpt, main_p)
        print('MHSAVED %d %d' % (TRAINER_ID, len(written)), flush=True)
        scope = global_scope()
        names = [p.name for p in main_p.global_block().all_parameters()]
        before = {n: np.asarray(scope.get(n)) for n in names}
        for n in names:  # corrupt local state; load must repair it
            scope.set(n, np.zeros_like(before[n]))
        fluid.io.load_persistables(exe, ckpt, main_p)
        for n in names:
            np.testing.assert_array_equal(np.asarray(scope.get(n)),
                                          before[n])
        print('MHLOADOK %d' % TRAINER_ID, flush=True)


if __name__ == '__main__':
    main()
