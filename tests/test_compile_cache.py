"""Persistent compile cache + AOT warm-start (core/compile_cache.py,
ISSUE 5): cross-process warm-start bit-identity, fingerprint-miss safety
(changed program / jax version / mesh must MISS, never falsely hit),
corrupt-entry loud fallback, LRU eviction, the shared LRU helper behind
CompiledProgram._opt_cache, and the cache_ctl CLI surface.
"""
import json
import os
import subprocess
import sys
import warnings

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu.core import compile_cache as cc

WORKER = os.path.join(os.path.dirname(__file__), 'compile_cache_worker.py')


@pytest.fixture(autouse=True)
def _cache_off_after():
    """Tests toggle the process-wide cache overrides; every test leaves
    them cleared — and un-points the tier-3 jax persistent cache when we
    set it — so the rest of the suite runs cache-off as before."""
    yield
    cc._override_enabled = None
    cc._override_dir = None
    cc._override_max_mb = None
    if cc._pcache_dir_set is not None:
        import jax
        jax.config.update('jax_compilation_cache_dir', None)
        cc._pcache_dir_set = None
        cc._dir_ready.clear()


def _run_worker(cache_dir, out_path):
    p = subprocess.run([sys.executable, WORKER, cache_dir, out_path],
                       capture_output=True, text=True, timeout=600)
    assert p.returncode == 0, "worker failed:\n%s\n%s" % (p.stdout, p.stderr)
    assert 'CC_OK' in p.stdout, p.stdout
    line = [l for l in p.stdout.splitlines()
            if l.startswith('CC_STATS ')][0]
    return json.loads(line[len('CC_STATS '):])


def test_cross_process_warm_start_bit_identity(tmp_path):
    """The acceptance bar: a fresh process re-running the same program
    performs ZERO XLA compiles for the cached entries (startup program,
    train step, K-step group) and its fetches are byte-identical."""
    cache = str(tmp_path / 'cache')
    cold = _run_worker(cache, str(tmp_path / 'cold.npz'))
    warm = _run_worker(cache, str(tmp_path / 'warm.npz'))

    assert cold['misses'] >= 3          # startup + run step + steps group
    assert cold['compiles'] == cold['misses']
    assert warm['misses'] == 0
    assert warm['compiles'] == 0
    assert warm['exec_hits'] == cold['misses']
    # zero REAL XLA compiles anywhere in the warm process: executable-tier
    # hits skip XLA entirely, and any stray utility jit is absorbed by the
    # jax persistent cache underneath (net = raw - pcache hits)
    assert warm['xla_compiles_net'] == 0, warm

    with np.load(str(tmp_path / 'cold.npz')) as a, \
            np.load(str(tmp_path / 'warm.npz')) as b:
        assert sorted(a.files) == sorted(b.files)
        for k in a.files:
            assert a[k].tobytes() == b[k].tobytes(), \
                "fetch %r differs cold vs warm" % k


def _tiny_program(extra_op=False):
    # unique_name.guard: rebuilding the same model code must produce the
    # same var names, hence the same program desc fingerprint
    with fluid.unique_name.guard():
        prog = fluid.Program()
        with fluid.program_guard(prog, fluid.Program()):
            x = fluid.layers.data(name='x', shape=[4], dtype='float32')
            h = fluid.layers.fc(x, size=3)
            if extra_op:
                h = fluid.layers.relu(h)
    return prog


def test_program_fingerprint_stable_and_content_sensitive():
    # two builds of the SAME model code fingerprint identically (that is
    # what makes the cache cross-process): uid/epoch must not leak in
    fp1 = cc.program_fingerprint(_tiny_program())
    fp2 = cc.program_fingerprint(_tiny_program())
    assert fp1 == fp2
    # any op change is a different program desc
    assert cc.program_fingerprint(_tiny_program(extra_op=True)) != fp1


def test_program_fingerprint_tracks_mutation():
    prog = _tiny_program()
    fp1 = cc.program_fingerprint(prog)
    assert cc.program_fingerprint(prog) == fp1  # memoized per epoch
    with fluid.program_guard(prog, fluid.Program()):
        fluid.layers.data(name='z', shape=[2], dtype='float32')
    assert cc.program_fingerprint(prog) != fp1


def test_entry_key_misses_on_jax_version_change(monkeypatch):
    parts = ('step', 'abc', ('loss',))
    k1 = cc.entry_key((parts, cc.env_fingerprint()))
    monkeypatch.setattr(cc, '_versions',
                        lambda: ('99.99.99', '99.99.98'))
    k2 = cc.entry_key((parts, cc.env_fingerprint()))
    assert k1 != k2


def test_entry_key_misses_on_mesh_change():
    import jax
    from jax.sharding import Mesh
    devs = jax.devices('cpu')
    assert len(devs) >= 4
    m2 = Mesh(np.asarray(devs[:2]).reshape(2), ('dp',))
    m4 = Mesh(np.asarray(devs[:4]).reshape(2, 2), ('dp', 'mp'))
    parts = ('step', 'abc', ('loss',))
    k2 = cc.entry_key((parts, cc.env_fingerprint(mesh=m2)))
    k4 = cc.entry_key((parts, cc.env_fingerprint(mesh=m4)))
    kd = cc.entry_key((parts, cc.env_fingerprint(device=devs[0])))
    assert len({k2, k4, kd}) == 3


def test_entry_key_misses_on_program_change():
    env = cc.env_fingerprint()
    k1 = cc.entry_key((('step', cc.program_fingerprint(_tiny_program())),
                       env))
    k2 = cc.entry_key((('step', cc.program_fingerprint(
        _tiny_program(extra_op=True))), env))
    assert k1 != k2


def test_canon_hashes_ndarray_content():
    a = cc._canon(np.arange(1000, dtype=np.float32))
    b = cc._canon(np.arange(1000, dtype=np.float32) + 1)
    assert a != b  # repr() would truncate both to '...' and collide


def test_corrupt_entry_warns_and_recompiles(tmp_path):
    cc.enable(dir=str(tmp_path / 'c'))

    def run_once():
        # fresh build of the SAME model code: same fingerprint (warm
        # path), fresh uid/step counters (identical rng, so results are
        # comparable bit-for-bit)
        with fluid.unique_name.guard():
            prog, startup = fluid.Program(), fluid.Program()
            prog.random_seed = startup.random_seed = 5
            with fluid.program_guard(prog, startup):
                x = fluid.layers.data(name='x', shape=[4],
                                      dtype='float32')
                out = fluid.layers.fc(x, size=3, act='relu')
        scope = fluid.core.Scope()
        exe = fluid.Executor(fluid.CPUPlace())
        with fluid.scope_guard(scope):
            exe.run(startup)
            return exe.run(prog, feed={'x': np.ones((2, 4), np.float32)},
                           fetch_list=[out])[0]

    want = run_once()
    entries = os.path.join(str(tmp_path / 'c'), 'entries')
    names = [n for n in os.listdir(entries) if not n.endswith('.json')]
    assert names
    for n in names:  # torn/garbage writes in BOTH tiers
        with open(os.path.join(entries, n), 'wb') as f:
            f.write(b'garbage')
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter('always')
        got = run_once()   # re-resolves through the corrupted entries
    assert any('compile cache' in str(x.message) for x in w), \
        "corrupt entry must fall back LOUDLY"
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_disk_lru_eviction(tmp_path):
    cc.enable(dir=str(tmp_path / 'c'), max_mb=0.02)   # ~20 KB budget
    for i in range(8):
        cc.store('k%064d' % i, exported_bytes=b'x' * 8192, tag='t')
    st = cc.disk_stats()
    assert st['bytes'] <= 0.02 * 2**20
    assert st['entries'] < 8
    assert cc.stats()['evicted'] > 0


def test_prune_clear(tmp_path):
    cc.enable(dir=str(tmp_path / 'c'))
    cc.store('k' * 64, exported_bytes=b'y' * 128, tag='t')
    assert cc.disk_stats()['entries'] == 1
    assert cc.prune(clear=True) == 1
    assert cc.disk_stats()['entries'] == 0


def test_opt_cache_lru_capped():
    from paddle_tpu.parallel.compiler import CompiledProgram, _OPT_CACHE_MAX
    prog = fluid.Program()
    with fluid.program_guard(prog, fluid.Program()):
        x = fluid.layers.data(name='x', shape=[4], dtype='float32')
        outs = [fluid.layers.fc(x, size=2) for _ in range(12)]
    cp = CompiledProgram(prog)
    for o in outs:   # 12 distinct fetch sets > the cap
        cp._optimized_program([o.name])
    assert len(cp._opt_cache) <= _OPT_CACHE_MAX
    # most-recent fetch set still hits
    assert cp._opt_cache.get(
        (prog._uid, prog._build_epoch, (outs[-1].name,))) is not None


def test_lru_helper_semantics():
    lru = cc.LRUCache(2)
    lru.put('a', 1)
    lru.put('b', 2)
    assert lru.get('a') == 1        # refresh 'a'
    lru.put('c', 3)                 # evicts 'b', the LRU entry
    assert 'b' not in lru and 'a' in lru and 'c' in lru
    lru.filter_inplace(lambda k: k == 'c')
    assert len(lru) == 1 and 'c' in lru


def test_cache_ctl_cli(tmp_path):
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        'cache_ctl', os.path.join(os.path.dirname(__file__), '..',
                                  'tools', 'cache_ctl.py'))
    ctl = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(ctl)
    d = str(tmp_path / 'c')
    cc.enable(dir=d)
    cc.store('k' * 64, exported_bytes=b'z' * 64, tag='t')
    assert ctl.main(['stats', '--dir', d, '--json']) == 0
    assert ctl.main(['prune', '--dir', d, '--all']) == 0
    assert ctl.main([]) == 2                          # no subcommand
    assert ctl.main(['prewarm', str(tmp_path / 'nope')]) == 2
    assert ctl.main(['prewarm', str(tmp_path)]) == 2  # no module inside
