"""Data pipeline: reader decorators, py_reader queue/EOF semantics,
DataFeeder, datasets (ref: test_py_reader_using_executor.py, reader tests)."""
import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import reader as reader_mod


def test_decorators():
    def r():
        return iter(range(10))
    b = reader_mod.batch(lambda: iter(range(10)), 3)
    batches = list(b())
    assert batches[0] == [0, 1, 2] and batches[-1] == [9]
    s = reader_mod.shuffle(lambda: iter(range(100)), 50)
    assert sorted(s()) == list(range(100))
    f = reader_mod.firstn(lambda: iter(range(100)), 5)
    assert list(f()) == [0, 1, 2, 3, 4]
    c = reader_mod.chain(lambda: iter([1]), lambda: iter([2]))
    assert list(c()) == [1, 2]
    m = reader_mod.map_readers(lambda a: a * 2, lambda: iter([1, 2]))
    assert list(m()) == [2, 4]


def test_bucket_by_length():
    samples = [[0] * l for l in [2, 9, 3, 8, 2, 9]]
    br = reader_mod.bucket_by_length(lambda: iter(samples), len,
                                     [4, 16], 2)
    batches = list(br())
    for b in batches:
        lens = [len(s) for s in b]
        assert all(l <= 4 for l in lens) or all(4 < l <= 16 for l in lens)


def test_py_reader_trains_with_eof():
    reader = fluid.layers.py_reader(
        capacity=8, shapes=[(-1, 4), (-1, 1)], dtypes=['float32', 'int64'])
    x, label = fluid.layers.read_file(reader)
    logits = fluid.layers.fc(input=x, size=3)
    loss = fluid.layers.mean(fluid.layers.softmax_with_cross_entropy(
        logits=logits, label=label))
    fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)

    def data():
        for i in range(7):
            yield [(np.random.rand(4).astype(np.float32),
                    np.array([i % 3], np.int64)) for _ in range(6)]

    reader.decorate_paddle_reader(data)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())

    for epoch in range(2):
        reader.start()
        steps = 0
        while True:
            try:
                l, = exe.run(fetch_list=[loss])
                steps += 1
            except fluid.core.EOFException:
                reader.reset()
                break
        assert steps == 7, steps


def test_prefetch_ring_groups_and_tail():
    """prefetch_to_device(K): the feeder thread stacks K host batches
    into one [K, ...] device buffer per var; EOF flushes a partial tail
    group; the drained ring raises EOFException."""
    import jax
    from paddle_tpu.reader.pipeline import PyReader
    x = fluid.layers.data('px', shape=[4], dtype='float32')
    r = PyReader([x], capacity=8).prefetch_to_device(4, depth=2)

    def gen():
        for i in range(10):
            yield {'px': np.full((2, 4), i, np.float32)}

    r.decorate_tensor_provider(lambda: gen())
    r.start()
    groups = []
    while True:
        try:
            groups.append(r._next_group())
        except fluid.core.EOFException:
            break
    assert [k for _, k in groups] == [4, 4, 2]
    g0 = groups[0][0]['px']
    assert isinstance(g0, jax.Array) and g0.shape == (4, 2, 4)
    # stacked values preserve batch order
    np.testing.assert_array_equal(np.asarray(g0)[:, 0, 0], [0, 1, 2, 3])
    assert groups[2][0]['px'].shape == (2, 2, 4)
    assert r.prefetch_stats['groups'] == 3
    assert r.prefetch_stats['tail_groups'] == 1
    r.reset()


def test_prefetch_ring_stacks_device_arrays_device_side():
    """Batches already on device stack with jnp (no per-batch D2H pull —
    through a remote tunnel each would be an RPC)."""
    import jax
    import jax.numpy as jnp
    from paddle_tpu.reader.pipeline import PyReader
    x = fluid.layers.data('pd', shape=[3], dtype='float32')
    r = PyReader([x], capacity=4).prefetch_to_device(2)

    def gen():
        for i in range(4):
            yield {'pd': jnp.full((2, 3), float(i))}

    r.decorate_tensor_provider(lambda: gen())
    r.start()
    g, k = r._next_group()
    assert k == 2 and isinstance(g['pd'], jax.Array)
    np.testing.assert_array_equal(np.asarray(g['pd'])[:, 0, 0], [0., 1.])
    r.reset()


def test_prefetch_ring_mode_guards():
    """A prefetch-mode reader refuses per-batch pops (it stages groups),
    and a per-batch reader refuses _next_group; bad configs raise."""
    import pytest
    from paddle_tpu.reader.pipeline import PyReader
    x = fluid.layers.data('pg', shape=[2], dtype='float32')
    r = PyReader([x], capacity=4)
    with pytest.raises(ValueError, match='steps'):
        r.prefetch_to_device(0)
    with pytest.raises(ValueError, match='depth'):
        r.prefetch_to_device(2, depth=0)
    with pytest.raises(RuntimeError, match='prefetch'):
        r._next_group()
    r.prefetch_to_device(2)
    r.decorate_tensor_provider(
        lambda: iter([{'pg': np.zeros((1, 2), np.float32)}]))
    r.start()
    with pytest.raises(RuntimeError, match='run_steps'):
        r._next_batch()
    r.reset()


def test_prefetch_ring_rejects_lod_batches():
    """LoD host batches carry per-batch offsets — they cannot stack into
    one [K, ...] ring buffer, and the feeder surfaces a TypeError on the
    consumer side."""
    import pytest
    from paddle_tpu.reader.pipeline import PyReader
    x = fluid.layers.data('pl', shape=[1], dtype='int64', lod_level=1)
    r = PyReader([x], capacity=4).prefetch_to_device(2)

    def gen():
        lt = fluid.create_lod_tensor(np.zeros((3, 1), np.int64), [[2, 1]])
        yield {'pl': lt}
        yield {'pl': lt}

    r.decorate_tensor_provider(lambda: gen())
    r.start()
    with pytest.raises(TypeError, match='dense'):
        r._next_group()
    r.reset()


def test_prefetch_ring_midepoch_reset_no_interleave():
    """reset() mid-epoch then start(): the old feeder thread (captured
    dead queue) must never leak stale groups into the new epoch — the
    restarted ring yields the full sequence from 0, in order."""
    import time
    from paddle_tpu.reader.pipeline import PyReader
    x = fluid.layers.data('pr', shape=[2], dtype='float32')
    r = PyReader([x], capacity=4).prefetch_to_device(2, depth=1)

    def gen():
        for i in range(8):
            time.sleep(0.001)  # keep the feeder mid-flight at reset
            yield {'pr': np.full((1, 2), i, np.float32)}

    r.decorate_tensor_provider(lambda: gen())
    for _ in range(3):
        r.start()
        g, _k = r._next_group()  # consume ONE group, abandon the epoch
        np.testing.assert_array_equal(np.asarray(g['pr'])[:, 0, 0],
                                      [0, 1])
        r.reset()
    r.start()
    seen = []
    while True:
        try:
            g, _k = r._next_group()
            seen.extend(np.asarray(g['pr'])[:, 0, 0].astype(int))
        except fluid.core.EOFException:
            break
    assert seen == list(range(8)), seen
    r.reset()


@pytest.mark.slow
def test_prefetch_ring_threaded_stress():
    """Stress the ring's producer/consumer handoff: a jittery producer,
    shallow depth, many epochs — counts and order must hold, no
    deadlock."""
    import time
    from paddle_tpu.reader.pipeline import PyReader
    x = fluid.layers.data('ps', shape=[3], dtype='float32')
    r = PyReader([x], capacity=8).prefetch_to_device(3, depth=1)
    n_batches = 25
    rng = np.random.RandomState(0)

    def gen():
        for i in range(n_batches):
            if rng.rand() < 0.3:
                time.sleep(0.002)
            yield {'ps': np.full((2, 3), i, np.float32)}

    r.decorate_tensor_provider(lambda: gen())
    for _epoch in range(5):
        r.start()
        seen = []
        while True:
            try:
                g, k = r._next_group()
                if rng.rand() < 0.3:
                    time.sleep(0.002)  # slow consumer: ring backpressure
                seen.extend(np.asarray(g['ps'])[:, 0, 0].astype(int))
                assert k in (3, 1)
            except fluid.core.EOFException:
                break
        assert seen == list(range(n_batches))
        r.reset()


def test_datasets_shapes():
    import paddle_tpu.dataset as ds
    img, lab = next(iter(ds.mnist.train()()))
    assert img.shape == (784,) and isinstance(lab, int)
    x, y = next(iter(ds.uci_housing.train()()))
    assert x.shape == (13,) and y.shape == (1,)
    toks, sent = next(iter(ds.imdb.train()()))
    assert isinstance(toks, list) and sent in (0, 1)
    src, tin, tout = next(iter(ds.wmt14.train(1000)()))
    assert len(tin) == len(src) + 1 and len(tout) == len(src) + 1


def test_data_feeder_lod():
    x = fluid.layers.data('x', shape=[1], dtype='int64', lod_level=1)
    y = fluid.layers.data('y', shape=[1], dtype='int64')
    feeder = fluid.DataFeeder(feed_list=[x, y], place=fluid.CPUPlace())
    feed = feeder.feed([([1, 2, 3], [0]), ([4, 5], [1])])
    lod_val = feed['x']
    assert lod_val.lod[0] == (0, 3, 5)
    assert np.asarray(lod_val.data).shape == (5, 1)
    assert feed['y'].shape == (2, 1)
