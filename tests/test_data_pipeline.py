"""Data pipeline: reader decorators, py_reader queue/EOF semantics,
DataFeeder, datasets (ref: test_py_reader_using_executor.py, reader tests)."""
import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import reader as reader_mod


def test_decorators():
    def r():
        return iter(range(10))
    b = reader_mod.batch(lambda: iter(range(10)), 3)
    batches = list(b())
    assert batches[0] == [0, 1, 2] and batches[-1] == [9]
    s = reader_mod.shuffle(lambda: iter(range(100)), 50)
    assert sorted(s()) == list(range(100))
    f = reader_mod.firstn(lambda: iter(range(100)), 5)
    assert list(f()) == [0, 1, 2, 3, 4]
    c = reader_mod.chain(lambda: iter([1]), lambda: iter([2]))
    assert list(c()) == [1, 2]
    m = reader_mod.map_readers(lambda a: a * 2, lambda: iter([1, 2]))
    assert list(m()) == [2, 4]


def test_bucket_by_length():
    samples = [[0] * l for l in [2, 9, 3, 8, 2, 9]]
    br = reader_mod.bucket_by_length(lambda: iter(samples), len,
                                     [4, 16], 2)
    batches = list(br())
    for b in batches:
        lens = [len(s) for s in b]
        assert all(l <= 4 for l in lens) or all(4 < l <= 16 for l in lens)


def test_py_reader_trains_with_eof():
    reader = fluid.layers.py_reader(
        capacity=8, shapes=[(-1, 4), (-1, 1)], dtypes=['float32', 'int64'])
    x, label = fluid.layers.read_file(reader)
    logits = fluid.layers.fc(input=x, size=3)
    loss = fluid.layers.mean(fluid.layers.softmax_with_cross_entropy(
        logits=logits, label=label))
    fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)

    def data():
        for i in range(7):
            yield [(np.random.rand(4).astype(np.float32),
                    np.array([i % 3], np.int64)) for _ in range(6)]

    reader.decorate_paddle_reader(data)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())

    for epoch in range(2):
        reader.start()
        steps = 0
        while True:
            try:
                l, = exe.run(fetch_list=[loss])
                steps += 1
            except fluid.core.EOFException:
                reader.reset()
                break
        assert steps == 7, steps


def test_prefetch_ring_groups_and_tail():
    """prefetch_to_device(K): the feeder thread stacks K host batches
    into one [K, ...] device buffer per var; EOF flushes a partial tail
    group; the drained ring raises EOFException."""
    import jax
    from paddle_tpu.reader.pipeline import PyReader
    x = fluid.layers.data('px', shape=[4], dtype='float32')
    r = PyReader([x], capacity=8).prefetch_to_device(4, depth=2)

    def gen():
        for i in range(10):
            yield {'px': np.full((2, 4), i, np.float32)}

    r.decorate_tensor_provider(lambda: gen())
    r.start()
    groups = []
    while True:
        try:
            groups.append(r._next_group())
        except fluid.core.EOFException:
            break
    assert [k for _, k in groups] == [4, 4, 2]
    g0 = groups[0][0]['px']
    assert isinstance(g0, jax.Array) and g0.shape == (4, 2, 4)
    # stacked values preserve batch order
    np.testing.assert_array_equal(np.asarray(g0)[:, 0, 0], [0, 1, 2, 3])
    assert groups[2][0]['px'].shape == (2, 2, 4)
    assert r.prefetch_stats['groups'] == 3
    assert r.prefetch_stats['tail_groups'] == 1
    r.reset()


def test_prefetch_ring_stacks_device_arrays_device_side():
    """Batches already on device stack with jnp (no per-batch D2H pull —
    through a remote tunnel each would be an RPC)."""
    import jax
    import jax.numpy as jnp
    from paddle_tpu.reader.pipeline import PyReader
    x = fluid.layers.data('pd', shape=[3], dtype='float32')
    r = PyReader([x], capacity=4).prefetch_to_device(2)

    def gen():
        for i in range(4):
            yield {'pd': jnp.full((2, 3), float(i))}

    r.decorate_tensor_provider(lambda: gen())
    r.start()
    g, k = r._next_group()
    assert k == 2 and isinstance(g['pd'], jax.Array)
    np.testing.assert_array_equal(np.asarray(g['pd'])[:, 0, 0], [0., 1.])
    r.reset()


def test_prefetch_ring_mode_guards():
    """A prefetch-mode reader refuses per-batch pops (it stages groups),
    and a per-batch reader refuses _next_group; bad configs raise."""
    import pytest
    from paddle_tpu.reader.pipeline import PyReader
    x = fluid.layers.data('pg', shape=[2], dtype='float32')
    r = PyReader([x], capacity=4)
    with pytest.raises(ValueError, match='steps'):
        r.prefetch_to_device(0)
    with pytest.raises(ValueError, match='depth'):
        r.prefetch_to_device(2, depth=0)
    with pytest.raises(RuntimeError, match='prefetch'):
        r._next_group()
    r.prefetch_to_device(2)
    r.decorate_tensor_provider(
        lambda: iter([{'pg': np.zeros((1, 2), np.float32)}]))
    r.start()
    with pytest.raises(RuntimeError, match='run_steps'):
        r._next_batch()
    r.reset()


def test_prefetch_ring_rejects_lod_batches():
    """LoD host batches carry per-batch offsets — they cannot stack into
    one [K, ...] ring buffer, and the feeder surfaces a TypeError on the
    consumer side."""
    import pytest
    from paddle_tpu.reader.pipeline import PyReader
    x = fluid.layers.data('pl', shape=[1], dtype='int64', lod_level=1)
    r = PyReader([x], capacity=4).prefetch_to_device(2)

    def gen():
        lt = fluid.create_lod_tensor(np.zeros((3, 1), np.int64), [[2, 1]])
        yield {'pl': lt}
        yield {'pl': lt}

    r.decorate_tensor_provider(lambda: gen())
    r.start()
    with pytest.raises(TypeError, match='dense'):
        r._next_group()
    r.reset()


def test_prefetch_ring_midepoch_reset_no_interleave():
    """reset() mid-epoch then start(): the old feeder thread (captured
    dead queue) must never leak stale groups into the new epoch — the
    restarted ring yields the full sequence from 0, in order."""
    import time
    from paddle_tpu.reader.pipeline import PyReader
    x = fluid.layers.data('pr', shape=[2], dtype='float32')
    r = PyReader([x], capacity=4).prefetch_to_device(2, depth=1)

    def gen():
        for i in range(8):
            time.sleep(0.001)  # keep the feeder mid-flight at reset
            yield {'pr': np.full((1, 2), i, np.float32)}

    r.decorate_tensor_provider(lambda: gen())
    for _ in range(3):
        r.start()
        g, _k = r._next_group()  # consume ONE group, abandon the epoch
        np.testing.assert_array_equal(np.asarray(g['pr'])[:, 0, 0],
                                      [0, 1])
        r.reset()
    r.start()
    seen = []
    while True:
        try:
            g, _k = r._next_group()
            seen.extend(np.asarray(g['pr'])[:, 0, 0].astype(int))
        except fluid.core.EOFException:
            break
    assert seen == list(range(8)), seen
    r.reset()


@pytest.mark.slow
def test_prefetch_ring_threaded_stress():
    """Stress the ring's producer/consumer handoff: a jittery producer,
    shallow depth, many epochs — counts and order must hold, no
    deadlock."""
    import time
    from paddle_tpu.reader.pipeline import PyReader
    x = fluid.layers.data('ps', shape=[3], dtype='float32')
    r = PyReader([x], capacity=8).prefetch_to_device(3, depth=1)
    n_batches = 25
    rng = np.random.RandomState(0)

    def gen():
        for i in range(n_batches):
            if rng.rand() < 0.3:
                time.sleep(0.002)
            yield {'ps': np.full((2, 3), i, np.float32)}

    r.decorate_tensor_provider(lambda: gen())
    for _epoch in range(5):
        r.start()
        seen = []
        while True:
            try:
                g, k = r._next_group()
                if rng.rand() < 0.3:
                    time.sleep(0.002)  # slow consumer: ring backpressure
                seen.extend(np.asarray(g['ps'])[:, 0, 0].astype(int))
                assert k in (3, 1)
            except fluid.core.EOFException:
                break
        assert seen == list(range(n_batches))
        r.reset()


# -- sharded streaming input / decode worker pool (ISSUE 9) -----------------


def _write_shard_files(tmp_path, num_files=3, per_file=25):
    from paddle_tpu import recordio
    files, flat = [], []
    for fi in range(num_files):
        p = str(tmp_path / ('sh%02d.rio' % fi))
        recs = [('f%d-r%03d' % (fi, i)).encode() for i in range(per_file)]
        recordio.write_recordio(p, recs, max_chunk_bytes=80)  # multi-chunk
        files.append(p)
        flat.extend(recs)
    return files, flat


def test_shard_assignment_disjoint_coverage():
    """Across simulated hosts: every item lands on exactly one shard,
    shards balance to within one item, bad ids raise."""
    from paddle_tpu.reader.sharded import shard_assignment
    for n_items, n_shards in [(17, 4), (8, 8), (100, 7), (3, 5), (1, 1)]:
        items = ['it%d' % i for i in range(n_items)]
        parts = [shard_assignment(items, n_shards, s)
                 for s in range(n_shards)]
        assert sorted(sum(parts, [])) == sorted(items)
        for i, a in enumerate(parts):
            for b in parts[i + 1:]:
                assert not set(a) & set(b)
        sizes = [len(p) for p in parts]
        assert max(sizes) - min(sizes) <= 1
    with pytest.raises(ValueError, match='shard_id'):
        shard_assignment([1], 2, 2)
    with pytest.raises(ValueError, match='num_shards'):
        shard_assignment([1], 0, 0)


def test_pooled_map_deterministic_order():
    """Out-of-order decode (jittered latency), in-order delivery: the
    pooled stream is bit-identical to the serial map, twice (the pool
    is reusable per epoch), and the stats counters add up."""
    import time
    from paddle_tpu.reader import pooled_map

    def dec(x):
        time.sleep(0.001 * (x % 5))
        return x * 2

    pr = pooled_map(dec, lambda: iter(range(40)), num_workers=4)
    want = [x * 2 for x in range(40)]
    assert list(pr()) == want
    assert list(pr()) == want
    s = pr.feeder_stats()
    assert s['samples'] == 80 and s['workers'] == 4
    assert s['deaths'] == 0 and s['retries'] == 0
    assert s['decode_ms_avg'] > 0


def test_pooled_map_dead_worker_degrades():
    """A worker death warns loudly, its in-flight sample re-dispatches,
    the epoch completes in order on the survivors; when EVERY worker is
    dead the pool errors instead of deadlocking."""
    import threading
    import warnings as _w
    from paddle_tpu.reader import pooled_map, WorkerDied

    lk = threading.Lock()
    died = {'n': 0}

    def deadly(x):
        with lk:
            if x == 5 and died['n'] == 0:
                died['n'] = 1
                raise WorkerDied('chaos')
        return x

    with _w.catch_warnings(record=True) as rec:
        _w.simplefilter('always')
        pr = pooled_map(deadly, lambda: iter(range(30)), num_workers=3)
        assert list(pr()) == list(range(30))
    assert any('died' in str(x.message) for x in rec)
    assert pr.feeder_stats()['deaths'] == 1

    def everyone_dies(x):
        raise WorkerDied('total chaos')

    with _w.catch_warnings():
        _w.simplefilter('ignore')
        with pytest.raises(RuntimeError, match='workers died'):
            list(pooled_map(everyone_dies, lambda: iter(range(10)),
                            num_workers=2)())


def test_pooled_map_retries_flaky_then_errors_deterministic():
    """A flaky decode retries (with a RuntimeWarning) and the stream
    stays complete and ordered; a DETERMINISTIC decode failure exhausts
    its retry cap and raises with the record position."""
    import threading
    import warnings as _w
    from paddle_tpu.reader import pooled_map

    lk = threading.Lock()
    fails = {7: 1, 13: 2}

    def flaky(x):
        with lk:
            if fails.get(x, 0) > 0:
                fails[x] -= 1
                raise ValueError('flaky %d' % x)
        return x

    with _w.catch_warnings(record=True) as rec:
        _w.simplefilter('always')
        pr = pooled_map(flaky, lambda: iter(range(20)), num_workers=3)
        assert list(pr()) == list(range(20))
    assert any('retrying' in str(x.message) for x in rec)
    assert pr.feeder_stats()['retries'] == 3

    def rotten(x):
        if x == 3:
            raise ValueError('rotten record')
        return x

    with _w.catch_warnings():
        _w.simplefilter('ignore')
        with pytest.raises(RuntimeError, match='sample 3'):
            list(pooled_map(rotten, lambda: iter(range(10)),
                            num_workers=2)())


def test_pooled_map_backpressure_bound():
    """A slow consumer bounds the pool's memory: the source is never
    read more than `window` samples ahead of delivery, and the observed
    max in-flight respects the bound."""
    import time
    from paddle_tpu.reader import pooled_map

    produced = []

    def src():
        for i in range(60):
            produced.append(i)
            yield i

    window = 10
    pr = pooled_map(lambda x: x, src, num_workers=2, window=window)
    delivered = 0
    for v in pr():
        assert v == delivered
        delivered += 1
        if delivered % 7 == 0:
            time.sleep(0.005)  # slow consumer
        # the dispatcher may run at most `window` ahead of delivery
        assert len(produced) - delivered <= window + 1, (
            len(produced), delivered)
    assert delivered == 60
    assert pr.feeder_stats()['max_inflight'] <= window


def test_pooled_map_process_mode():
    """Process workers (fork): same ordered bit-identical delivery for
    GIL-bound decodes."""
    from paddle_tpu.reader import pooled_map
    pr = pooled_map(lambda x: x * 3, lambda: iter(range(30)),
                    num_workers=2, mode='process')
    assert list(pr()) == [x * 3 for x in range(30)]
    assert pr.feeder_stats()['samples'] == 30


def test_pooled_map_process_mode_unpicklable_result_is_loud():
    """mp.Queue's feeder thread silently DROPS values it cannot pickle
    (which would hang the pool forever) — workers pickle results
    themselves, so an unpicklable decode result surfaces as a loud
    per-sample error instead."""
    import threading
    import warnings as _w
    from paddle_tpu.reader import pooled_map

    def unpicklable(x):
        return threading.Lock()

    with _w.catch_warnings():
        _w.simplefilter('ignore')
        with pytest.raises(RuntimeError, match='failed'):
            list(pooled_map(unpicklable, lambda: iter(range(4)),
                            num_workers=2, mode='process')())


def test_sharded_reader_lazy_read_failure_retries(tmp_path):
    """A read_task_fn generator that fails MID-ITERATION (flaky mount)
    routes through the lease/failure machinery: the task backs off and
    retries, already-yielded records are not duplicated, and the epoch
    completes in order."""
    import warnings as _w
    from paddle_tpu.reader import ShardedFileReader
    files = []
    for i in range(2):
        p = str(tmp_path / ('f%d.txt' % i))
        with open(p, 'w') as f:
            f.write(''.join('l%d-%02d\n' % (i, j) for j in range(10)))
        files.append(p)
    state = {'failed': False}

    def read_lines(task):
        with open(task.path) as f:
            for j, line in enumerate(f):
                if task.path.endswith('f1.txt') and j == 5 \
                        and not state['failed']:
                    state['failed'] = True
                    raise IOError('flaky read')
                yield line.strip()

    r = ShardedFileReader(files, chunk_granular=False,
                          read_task_fn=read_lines, max_failures=3)
    r.service._backoff_base = 0.001  # keep the retry quick
    with _w.catch_warnings():
        _w.simplefilter('ignore')
        got = list(r())
    assert got == ['l0-%02d' % j for j in range(10)] \
        + ['l1-%02d' % j for j in range(10)]
    assert state['failed']  # the failure really fired


def test_sharded_reader_chunks_epochs_and_pool(tmp_path):
    """RecordIO shards split into chunk tasks; serial and pooled streams
    are bit-identical in deterministic (file, chunk) order; a drained
    reader starts the next epoch on the next call."""
    from paddle_tpu import recordio
    from paddle_tpu.reader import ShardedFileReader
    files, flat = _write_shard_files(tmp_path)
    assert len(recordio.chunk_index(files[0])) > 1  # chunk-granular

    r = ShardedFileReader(files,
                          journal_path=str(tmp_path / 'j.journal'),
                          progress_every=1)
    assert len(r.tasks) == sum(len(recordio.chunk_index(f))
                               for f in files)
    assert list(r()) == flat
    assert r.epoch_done
    assert list(r.pooled(lambda b: b, num_workers=4)()) == flat
    assert list(r())[:5] == flat[:5]  # third pass: a fresh epoch
    r.close()


def test_sharded_reader_disjoint_across_hosts(tmp_path):
    """Simulated 3-host pod: per-host readers cover the file set exactly
    once with no overlap — chunk tasks stride across hosts."""
    from paddle_tpu.reader import ShardedFileReader
    files, flat = _write_shard_files(tmp_path)
    streams = [list(ShardedFileReader(files, shard_id=s, num_shards=3)())
               for s in range(3)]
    assert sorted(sum(streams, [])) == sorted(flat)
    for i, a in enumerate(streams):
        for b in streams[i + 1:]:
            assert not set(a) & set(b)


def test_sharded_reader_exactly_once_kill_resume(tmp_path):
    """Mid-epoch kill (consumer torn down, leases released), then a
    FRESH reader on the same journal: the union of deliveries is exactly
    one epoch — no sample lost, none duplicated — and delivery order
    continues the same deterministic stream."""
    from paddle_tpu.reader import ShardedFileReader
    files, flat = _write_shard_files(tmp_path)
    jp = str(tmp_path / 'kill.journal')

    r1 = ShardedFileReader(files, journal_path=jp, progress_every=1)
    g = r1.pooled(lambda b: b, num_workers=2)()
    part = [next(g) for _ in range(31)]
    g.close()
    r1.close()

    r2 = ShardedFileReader(files, journal_path=jp, progress_every=1)
    rest = list(r2())
    r2.close()
    assert part + rest == flat  # exactly-once AND order-continuous


def test_sharded_reader_clean_stop_resume_same_reader(tmp_path):
    """In-session stop/resume on the SAME reader with a coarse journal
    cadence: a clean mid-epoch stop journals the exact delivered
    position and releases every held lease — including a task whose
    last record was read ahead but not yet delivered — so the next pass
    continues immediately (no lease-timeout stall), with zero replay
    and zero loss."""
    from paddle_tpu.reader import ShardedFileReader
    files, flat = _write_shard_files(tmp_path)
    r = ShardedFileReader(files, journal_path=str(tmp_path / 'cs.journal'),
                          progress_every=8, lease_timeout_s=3600.0)
    for stop_at in (17, 31):  # two successive partial passes
        g = r.pooled(lambda b: b, num_workers=2)()
        part = [next(g) for _ in range(stop_at)]
        g.close()
        assert part == flat[:stop_at]
        rest = list(r.pooled(lambda b: b, num_workers=2)())
        assert part + rest == flat  # zero replay, zero loss, in order
        assert r.epoch_done
    r.close()


def test_sharded_reader_journal_position_rewind(tmp_path):
    """journal_position()/journal_limit: rewinding the journal to a
    checkpointed position re-dispatches everything consumed after it —
    the checkpoint and the data accounting describe the same history."""
    from paddle_tpu.reader import ShardedFileReader
    files, flat = _write_shard_files(tmp_path)
    jp = str(tmp_path / 'rew.journal')

    r1 = ShardedFileReader(files, journal_path=jp, progress_every=1)
    g = iter(r1())
    for _ in range(10):
        next(g)
    pos = r1.journal_position()  # "checkpoint" here
    for _ in range(20):
        next(g)
    g.close()
    r1.close()

    r2 = ShardedFileReader(files, journal_path=jp, progress_every=1,
                           journal_limit=pos)
    rest = list(r2())
    r2.close()
    assert flat[:10] + rest == flat  # the 20 post-checkpoint replays


def test_sharded_reader_rejects_bad_config(tmp_path):
    from paddle_tpu.reader import ShardedFileReader
    files, _ = _write_shard_files(tmp_path, num_files=1)
    with pytest.raises(ValueError, match='empty file set'):
        ShardedFileReader([])
    with pytest.raises(ValueError, match='read_task_fn'):
        p = str(tmp_path / 'notrio.txt')
        with open(p, 'w') as f:
            f.write('hello\n')
        ShardedFileReader([p])


def test_shuffle_seed_reproducible():
    """shuffle(seed=): every invocation replays the same order; the
    default (no seed) still draws from global random — unchanged."""
    from paddle_tpu import reader as reader_mod
    r = reader_mod.shuffle(lambda: iter(range(50)), 16, seed=7)
    a, b = list(r()), list(r())
    assert a == b and sorted(a) == list(range(50))
    r2 = reader_mod.shuffle(lambda: iter(range(50)), 16, seed=8)
    assert list(r2()) != a
    legacy = reader_mod.shuffle(lambda: iter(range(50)), 16)
    assert sorted(legacy()) == list(range(50))


def test_pyreader_eof_rejoins_feeder_thread():
    """Satellite of ISSUE 9 (parallel/api.py:112): consuming EOF joins
    and clears the feeder thread, so epoch loops that never call
    reset() don't accumulate dead Thread objects."""
    from paddle_tpu.reader.pipeline import PyReader
    x = fluid.layers.data('pj', shape=[2], dtype='float32')
    r = PyReader([x], capacity=4)
    r.decorate_tensor_provider(
        lambda: iter([{'pj': np.zeros((1, 2), np.float32)}] * 3))
    for _ in range(5):  # repeated sessions, no reset() between them
        r.start()
        n = 0
        while True:
            try:
                r._next_batch()
                n += 1
            except fluid.core.EOFException:
                break
        assert n == 3
        assert r._thread is None  # rejoined at EOF, not left dangling


def test_feeder_stats_flow_into_training_report(tmp_path):
    """The pooled reader's decode counters surface through PyReader in
    profiler.training_report()'s feeder table, surviving batch()
    composition."""
    from paddle_tpu import profiler
    from paddle_tpu.reader import ShardedFileReader
    from paddle_tpu.reader.pipeline import PyReader
    from paddle_tpu.dataset import synthetic

    files = synthetic.write_shards(str(tmp_path), num_shards=2,
                                   samples_per_shard=16, seed=3)
    src = ShardedFileReader(files)
    pooled = src.pooled(synthetic.make_decode_fn(), num_workers=2)
    batched = fluid.reader.batch(pooled, 8, drop_last=True)
    assert callable(getattr(batched, 'feeder_stats', None))

    x = fluid.layers.data('fimg', shape=[3, 32, 32], dtype='float32')
    y = fluid.layers.data('flab', shape=[1], dtype='int64')
    r = PyReader([x, y], capacity=4)
    r.decorate_paddle_reader(batched)
    r.start()
    while True:
        try:
            r._next_batch()
        except fluid.core.EOFException:
            break
    report = profiler.feeder_report()
    mine = [s for name, s in report.items() if name.startswith('pyreader')
            and s.get('samples')]
    assert mine, report
    assert mine[0]['samples'] == 32
    assert mine[0]['workers'] == 2
    assert mine[0]['convert_ms'] > 0  # DataFeeder conversion accounted


def test_datasets_shapes():
    import paddle_tpu.dataset as ds
    img, lab = next(iter(ds.mnist.train()()))
    assert img.shape == (784,) and isinstance(lab, int)
    x, y = next(iter(ds.uci_housing.train()()))
    assert x.shape == (13,) and y.shape == (1,)
    toks, sent = next(iter(ds.imdb.train()()))
    assert isinstance(toks, list) and sent in (0, 1)
    src, tin, tout = next(iter(ds.wmt14.train(1000)()))
    assert len(tin) == len(src) + 1 and len(tout) == len(src) + 1


def test_data_feeder_lod():
    x = fluid.layers.data('x', shape=[1], dtype='int64', lod_level=1)
    y = fluid.layers.data('y', shape=[1], dtype='int64')
    feeder = fluid.DataFeeder(feed_list=[x, y], place=fluid.CPUPlace())
    feed = feeder.feed([([1, 2, 3], [0]), ([4, 5], [1])])
    lod_val = feed['x']
    assert lod_val.lod[0] == (0, 3, 5)
    assert np.asarray(lod_val.data).shape == (5, 1)
    assert feed['y'].shape == (2, 1)
