"""Data pipeline: reader decorators, py_reader queue/EOF semantics,
DataFeeder, datasets (ref: test_py_reader_using_executor.py, reader tests)."""
import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import reader as reader_mod


def test_decorators():
    def r():
        return iter(range(10))
    b = reader_mod.batch(lambda: iter(range(10)), 3)
    batches = list(b())
    assert batches[0] == [0, 1, 2] and batches[-1] == [9]
    s = reader_mod.shuffle(lambda: iter(range(100)), 50)
    assert sorted(s()) == list(range(100))
    f = reader_mod.firstn(lambda: iter(range(100)), 5)
    assert list(f()) == [0, 1, 2, 3, 4]
    c = reader_mod.chain(lambda: iter([1]), lambda: iter([2]))
    assert list(c()) == [1, 2]
    m = reader_mod.map_readers(lambda a: a * 2, lambda: iter([1, 2]))
    assert list(m()) == [2, 4]


def test_bucket_by_length():
    samples = [[0] * l for l in [2, 9, 3, 8, 2, 9]]
    br = reader_mod.bucket_by_length(lambda: iter(samples), len,
                                     [4, 16], 2)
    batches = list(br())
    for b in batches:
        lens = [len(s) for s in b]
        assert all(l <= 4 for l in lens) or all(4 < l <= 16 for l in lens)


def test_py_reader_trains_with_eof():
    reader = fluid.layers.py_reader(
        capacity=8, shapes=[(-1, 4), (-1, 1)], dtypes=['float32', 'int64'])
    x, label = fluid.layers.read_file(reader)
    logits = fluid.layers.fc(input=x, size=3)
    loss = fluid.layers.mean(fluid.layers.softmax_with_cross_entropy(
        logits=logits, label=label))
    fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)

    def data():
        for i in range(7):
            yield [(np.random.rand(4).astype(np.float32),
                    np.array([i % 3], np.int64)) for _ in range(6)]

    reader.decorate_paddle_reader(data)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())

    for epoch in range(2):
        reader.start()
        steps = 0
        while True:
            try:
                l, = exe.run(fetch_list=[loss])
                steps += 1
            except fluid.core.EOFException:
                reader.reset()
                break
        assert steps == 7, steps


def test_datasets_shapes():
    import paddle_tpu.dataset as ds
    img, lab = next(iter(ds.mnist.train()()))
    assert img.shape == (784,) and isinstance(lab, int)
    x, y = next(iter(ds.uci_housing.train()()))
    assert x.shape == (13,) and y.shape == (1,)
    toks, sent = next(iter(ds.imdb.train()()))
    assert isinstance(toks, list) and sent in (0, 1)
    src, tin, tout = next(iter(ds.wmt14.train(1000)()))
    assert len(tin) == len(src) + 1 and len(tout) == len(src) + 1


def test_data_feeder_lod():
    x = fluid.layers.data('x', shape=[1], dtype='int64', lod_level=1)
    y = fluid.layers.data('y', shape=[1], dtype='int64')
    feeder = fluid.DataFeeder(feed_list=[x, y], place=fluid.CPUPlace())
    feed = feeder.feed([([1, 2, 3], [0]), ([4, 5], [1])])
    lod_val = feed['x']
    assert lod_val.lod[0] == (0, 3, 5)
    assert np.asarray(lod_val.data).shape == (5, 1)
    assert feed['y'].shape == (2, 1)
