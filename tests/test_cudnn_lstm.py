"""layers.lstm (the reference's cudnn stacked-LSTM path) numeric + grad
tests (ref: operators/cudnn_lstm_op.cc:1, tests/unittests/test_lstm_op.py
methodology): forward vs a float64 numpy oracle, analytic-vs-numeric
gradients via OpTest.check_grad, a composition cross-check against
dynamic_lstm, and layer-level train/infer behavior (dropout gating,
bidirectional shapes, training moves the loss)."""
import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu.lod_tensor import create_lod_tensor

from op_test import OpTest


def _sig(z):
    return 1.0 / (1.0 + np.exp(-z))


def np_stacked_lstm(x, wx, wh, b, h0, c0, nlayers, ndir):
    """float64 oracle, gate packing {i, f, c, o}; no dropout."""
    cur = x.astype(np.float64)
    lh, lc = [], []
    for layer in range(nlayers):
        outs = []
        for d in range(ndir):
            i = layer * ndir + d
            xs = cur[::-1] if d == 1 else cur
            h = h0[i].astype(np.float64)
            c = c0[i].astype(np.float64)
            hidden = wh[i].shape[0]
            hs = []
            for t in range(xs.shape[0]):
                g = xs[t] @ wx[i].astype(np.float64) \
                    + h @ wh[i].astype(np.float64) + b[i].astype(np.float64)
                gi, gf = g[:, :hidden], g[:, hidden:2 * hidden]
                gc, go = g[:, 2 * hidden:3 * hidden], g[:, 3 * hidden:]
                c = _sig(gf) * c + _sig(gi) * np.tanh(gc)
                h = _sig(go) * np.tanh(c)
                hs.append(h)
            hs = np.stack(hs)
            if d == 1:
                hs = hs[::-1]
            outs.append(hs)
            lh.append(h)
            lc.append(c)
        cur = np.concatenate(outs, -1) if ndir > 1 else outs[0]
    return cur, np.stack(lh), np.stack(lc)


def _make_case(S=4, B=3, D=5, H=6, nlayers=1, ndir=1, seed=0):
    rng = np.random.RandomState(seed)
    x = rng.randn(S, B, D).astype(np.float32) * 0.5
    h0 = rng.randn(nlayers * ndir, B, H).astype(np.float32) * 0.3
    c0 = rng.randn(nlayers * ndir, B, H).astype(np.float32) * 0.3
    wx, wh, b = [], [], []
    for layer in range(nlayers):
        in_sz = D if layer == 0 else H * ndir
        for _ in range(ndir):
            wx.append(rng.randn(in_sz, 4 * H).astype(np.float32) * 0.2)
            wh.append(rng.randn(H, 4 * H).astype(np.float32) * 0.2)
            b.append(rng.randn(4 * H).astype(np.float32) * 0.1)
    return x, h0, c0, wx, wh, b


class _CudnnLstmTest(OpTest):
    op_type = 'cudnn_lstm'

    def __init__(self, nlayers, ndir, fuse=False, **kw):
        x, h0, c0, wx, wh, b = _make_case(nlayers=nlayers, ndir=ndir, **kw)
        out, lh, lc = np_stacked_lstm(x, wx, wh, b, h0, c0, nlayers, ndir)
        self.inputs = {
            'Input': x, 'InitH': h0, 'InitC': c0,
            'WeightX': [('wx%d' % i, w) for i, w in enumerate(wx)],
            'WeightH': [('wh%d' % i, w) for i, w in enumerate(wh)],
            'Bias': [('b%d' % i, w) for i, w in enumerate(b)],
        }
        self.attrs = {'hidden_size': wh[0].shape[0], 'num_layers': nlayers,
                      'is_bidirec': ndir == 2, 'dropout_prob': 0.0,
                      'is_test': False, 'fuse_layers': fuse}
        self.outputs = {'Out': out.astype(np.float32),
                        'LastH': lh.astype(np.float32),
                        'LastC': lc.astype(np.float32)}


def test_forward_single_layer():
    _CudnnLstmTest(nlayers=1, ndir=1).check_output(atol=1e-5, rtol=1e-5)


def test_forward_stacked_bidirectional():
    _CudnnLstmTest(nlayers=3, ndir=2).check_output(atol=1e-5, rtol=1e-5)


def test_grad_weights_and_input():
    t = _CudnnLstmTest(nlayers=2, ndir=2, S=3, B=2, D=4, H=3)
    t.check_grad(['Input', 'wx0', 'wh1', 'b2'], 'Out',
                 max_relative_error=1e-2)


# ---------------------------------------------------------------------------
# fuse_layers: the single-scan multi-layer body (PERF_NOTES round 18)
# ---------------------------------------------------------------------------
def test_forward_fused_stack_vs_oracle():
    """fuse_layers=True (ONE lax.scan carrying all layers' (h, c), the
    L gate GEMMs back-to-back per step) must match the same float64
    oracle as the per-layer path."""
    _CudnnLstmTest(nlayers=3, ndir=1, fuse=True).check_output(
        atol=1e-5, rtol=1e-5)


def test_grad_fused_stack():
    """Analytic-vs-numeric gradients through the fused scan body."""
    t = _CudnnLstmTest(nlayers=2, ndir=1, S=3, B=2, D=4, H=3, fuse=True)
    t.check_grad(['Input', 'wx0', 'wh1', 'b0'], 'Out',
                 max_relative_error=1e-2)


def _build_fused_pair(fuse, dropout, seed, S, B, D, H, L):
    """Identically-named/seeded net differing only in fuse_layers —
    unique_name.guard makes param names (and so init draws and dropout
    rng keys) line up across the two builds."""
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = seed
    with fluid.unique_name.guard():
        with fluid.program_guard(main, startup):
            x = fluid.layers.data('x', shape=[S, B, D], dtype='float32',
                                  append_batch_size=False)
            h0 = fluid.layers.data('h0', shape=[L, B, H], dtype='float32',
                                   append_batch_size=False)
            c0 = fluid.layers.data('c0', shape=[L, B, H], dtype='float32',
                                   append_batch_size=False)
            out, lh, lc = fluid.layers.lstm(
                x, h0, c0, max_len=S, hidden_size=H, num_layers=L,
                dropout_prob=dropout, fuse_layers=fuse)
    return main, startup, (out, lh, lc)


@pytest.mark.parametrize('dropout', [0.0, 0.3])
def test_fused_equals_per_layer_bitwise(dropout):
    """Fused vs per-layer stacks agree bit-for-bit, dropout included:
    the fused body pre-samples the between-layer masks with the exact
    key-split order the per-layer path uses."""
    S, B, D, H, L = 5, 3, 4, 6, 3
    rng = np.random.RandomState(1)
    feed = {'x': rng.randn(S, B, D).astype(np.float32),
            'h0': np.zeros((L, B, H), np.float32),
            'c0': np.zeros((L, B, H), np.float32)}
    got = []
    for fuse in (False, True):
        main, startup, fetches = _build_fused_pair(
            fuse, dropout, 11, S, B, D, H, L)
        exe = fluid.Executor(fluid.CPUPlace())
        scope = fluid.core.Scope()
        with fluid.scope_guard(scope):
            exe.run(startup)
            got.append([np.asarray(v) for v in
                        exe.run(main, feed=feed, fetch_list=list(fetches))])
    for a, b in zip(got[0], got[1]):
        np.testing.assert_array_equal(a, b)


def test_fused_training_matches_per_layer():
    """Grad + optimizer path: a fused-stack classifier's per-step Adam
    losses equal the per-layer stack's bit-for-bit."""
    S, B, D, H, L = 6, 4, 5, 8, 2
    rng = np.random.RandomState(7)
    feed = {'x': rng.randn(S, B, D).astype(np.float32),
            'h0': np.zeros((L, B, H), np.float32),
            'c0': np.zeros((L, B, H), np.float32),
            'label': rng.randint(0, 3, (B, 1)).astype(np.int64)}
    traces = []
    for fuse in (False, True):
        main, startup = fluid.Program(), fluid.Program()
        main.random_seed = startup.random_seed = 5
        with fluid.unique_name.guard():
            with fluid.program_guard(main, startup):
                x = fluid.layers.data('x', shape=[S, B, D],
                                      dtype='float32',
                                      append_batch_size=False)
                h0 = fluid.layers.data('h0', shape=[L, B, H],
                                       dtype='float32',
                                       append_batch_size=False)
                c0 = fluid.layers.data('c0', shape=[L, B, H],
                                       dtype='float32',
                                       append_batch_size=False)
                label = fluid.layers.data('label', shape=[B, 1],
                                          dtype='int64',
                                          append_batch_size=False)
                out, _, _ = fluid.layers.lstm(
                    x, h0, c0, max_len=S, hidden_size=H, num_layers=L,
                    dropout_prob=0.3, fuse_layers=fuse)
                logits = fluid.layers.fc(
                    fluid.layers.reduce_mean(out, dim=0), size=3)
                loss = fluid.layers.mean(
                    fluid.layers.softmax_with_cross_entropy(
                        logits=logits, label=label))
                fluid.optimizer.Adam(learning_rate=0.01).minimize(loss)
        exe = fluid.Executor(fluid.CPUPlace())
        scope = fluid.core.Scope()
        with fluid.scope_guard(scope):
            exe.run(startup)
            traces.append([
                float(np.asarray(exe.run(main, feed=feed,
                                         fetch_list=[loss])[0])
                      .reshape(-1)[0]) for _ in range(3)])
    assert np.isfinite(traces[0]).all()
    assert traces[0] == traces[1], traces


def test_cross_check_vs_dynamic_lstm():
    """Single-layer unidirectional layers.lstm must equal dynamic_lstm fed
    the pre-projected input with gates re-packed {i,f,c,o} -> {c,i,f,o}
    (the two ops implement the same recurrence with different packings;
    ref lstm_op.cc vs cudnn_lstm_op.cc)."""
    S, B, D, H = 5, 3, 4, 6
    x, h0, c0, wx, wh, b = _make_case(S=S, B=B, D=D, H=H)
    # my packing {i,f,c,o} -> dynamic_lstm packing {c,i,f,o}
    perm = np.concatenate([np.arange(2 * H, 3 * H), np.arange(0, H),
                           np.arange(H, 2 * H), np.arange(3 * H, 4 * H)])
    proj = (x @ wx[0] + b[0])[..., perm]          # [S, B, 4H] pre-projected
    w_dyn = wh[0][:, perm]

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        inp = fluid.layers.data('inp', shape=[4 * H], dtype='float32',
                                lod_level=1)
        h0v = fluid.layers.data('h0', shape=[B, H], dtype='float32',
                                append_batch_size=False)
        c0v = fluid.layers.data('c0', shape=[B, H], dtype='float32',
                                append_batch_size=False)
        hidden, _ = fluid.layers.dynamic_lstm(
            input=inp, size=4 * H, h_0=h0v, c_0=c0v, use_peepholes=False)
        (weight,) = [p for p in main.global_block().all_parameters()
                     if tuple(p.shape) == (H, 4 * H)]
    # rows: sequence b is x[:, b, :] (all length S)
    rows = np.swapaxes(proj, 0, 1).reshape(B * S, 4 * H)
    scope = fluid.core.Scope()
    exe = fluid.Executor(fluid.CPUPlace())
    with fluid.scope_guard(scope):
        exe.run(startup)
        scope.set(weight.name, w_dyn)
        got, = exe.run(main,
                       feed={'inp': create_lod_tensor(rows, [[S] * B]),
                             'h0': h0[0], 'c0': c0[0]},
                       fetch_list=[hidden])
    want, _, _ = np_stacked_lstm(x, wx, wh, b, h0, c0, 1, 1)
    np.testing.assert_allclose(
        np.asarray(got).reshape(B, S, H), np.swapaxes(want, 0, 1),
        rtol=1e-4, atol=1e-5)


def _build_lstm_net(S, B, D, H, nlayers, is_bidirec, dropout_prob=0.0,
                    is_test=False):
    ndir = 2 if is_bidirec else 1
    x = fluid.layers.data('x', shape=[S, B, D], dtype='float32',
                          append_batch_size=False)
    h0 = fluid.layers.data('h0', shape=[nlayers * ndir, B, H],
                           dtype='float32', append_batch_size=False)
    c0 = fluid.layers.data('c0', shape=[nlayers * ndir, B, H],
                           dtype='float32', append_batch_size=False)
    return fluid.layers.lstm(x, h0, c0, max_len=S, hidden_size=H,
                             num_layers=nlayers, is_bidirec=is_bidirec,
                             dropout_prob=dropout_prob, is_test=is_test)


def test_layer_shapes_and_oracle_parity():
    """layers.lstm end-to-end: shapes per the reference contract and
    numeric parity with the oracle when weights are read back out."""
    S, B, D, H, L = 6, 2, 3, 5, 2
    out, last_h, last_c = _build_lstm_net(S, B, D, H, L, is_bidirec=True)
    rng = np.random.RandomState(1)
    x = rng.randn(S, B, D).astype(np.float32)
    h0 = np.zeros((L * 2, B, H), np.float32)
    c0 = np.zeros((L * 2, B, H), np.float32)
    scope = fluid.core.Scope()
    exe = fluid.Executor(fluid.CPUPlace())
    with fluid.scope_guard(scope):
        exe.run(fluid.default_startup_program())
        params = fluid.default_main_program().global_block().all_parameters()
        vals = {p.name: np.asarray(scope.get(p.name)) for p in params}
        o, lh, lc = exe.run(feed={'x': x, 'h0': h0, 'c0': c0},
                            fetch_list=[out, last_h, last_c])
    assert np.shape(o) == (S, B, 2 * H)
    assert np.shape(lh) == (L * 2, B, H)
    assert np.shape(lc) == (L * 2, B, H)
    # creation order per (layer, dir): wx, wh, bias
    ws = [vals[p.name] for p in params if '.w_' in p.name]
    wx, wh = ws[0::2], ws[1::2]
    b = [vals[p.name] for p in params if '.b_' in p.name]
    want_o, want_h, want_c = np_stacked_lstm(x, wx, wh, b, h0, c0, L, 2)
    np.testing.assert_allclose(np.asarray(o), want_o, rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(np.asarray(lh), want_h, rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(np.asarray(lc), want_c, rtol=1e-4, atol=1e-5)


def test_dropout_between_layers_only():
    """dropout_prob fires only between stacked layers at train time: a
    1-layer net is unaffected; a 2-layer net changes output vs is_test."""
    S, B, D, H = 4, 2, 3, 4
    rng = np.random.RandomState(2)
    x = rng.randn(S, B, D).astype(np.float32)

    def run(nlayers, dropout, is_test):
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            out, _, _ = _build_lstm_net(S, B, D, H, nlayers, False,
                                        dropout_prob=dropout,
                                        is_test=is_test)
        h0 = np.zeros((nlayers, B, H), np.float32)
        scope = fluid.core.Scope()
        exe = fluid.Executor(fluid.CPUPlace())
        with fluid.scope_guard(scope):
            exe.run(startup)
            o, = exe.run(main, feed={'x': x, 'h0': h0, 'c0': h0},
                         fetch_list=[out])
        return np.asarray(o)

    # 1 layer: no between-layer boundary, dropout is a no-op
    np.testing.assert_allclose(run(1, 0.5, False), run(1, 0.5, True),
                               rtol=1e-6)
    # 2 layers: train-time dropout perturbs; is_test restores determinism
    a, bo = run(2, 0.9, False), run(2, 0.9, True)
    assert not np.allclose(a, bo, rtol=1e-3)
    np.testing.assert_allclose(run(2, 0.9, True), run(2, 0.9, True),
                               rtol=1e-6)


def test_training_moves_loss():
    """A stacked-LSTM classifier trains (loss decreases) through the op's
    vjp-derived gradients — the reference's end-to-end bar."""
    S, B, D, H = 8, 4, 6, 8
    out, _, _ = _build_lstm_net(S, B, D, H, nlayers=2, is_bidirec=True)
    label = fluid.layers.data('label', shape=[B, 1], dtype='int64',
                              append_batch_size=False)
    logits = fluid.layers.fc(fluid.layers.reduce_mean(out, dim=0), size=4)
    loss = fluid.layers.mean(fluid.layers.softmax_with_cross_entropy(
        logits=logits, label=label))
    fluid.optimizer.Adam(learning_rate=0.01).minimize(loss)
    rng = np.random.RandomState(3)
    feed = {'x': rng.randn(S, B, D).astype(np.float32),
            'h0': np.zeros((4, B, H), np.float32),
            'c0': np.zeros((4, B, H), np.float32),
            'label': rng.randint(0, 4, (B, 1)).astype(np.int64)}
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    losses = [float(np.asarray(exe.run(feed=feed, fetch_list=[loss])[0])
                    .reshape(-1)[0]) for _ in range(12)]
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0]
