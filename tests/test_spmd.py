"""SPMD execution tests on the 8-device virtual CPU mesh.

In-process port of the reference's distributed loss-parity methodology
(python/paddle/fluid/tests/unittests/test_dist_base.py:35 — run the same
model single-process and distributed, assert per-step losses match). Here
"distributed" is the GSPMD path: one program, one mesh, batch-sharded
feeds; XLA inserts the gradient all-reduces the reference built op handles
for (framework/details/all_reduce_op_handle.cc:55).
"""
import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu.parallel import shard_parameter
from paddle_tpu.parallel.mesh import make_mesh
from paddle_tpu.parallel.compiler import CompiledProgram, BuildStrategy
from paddle_tpu.parallel.parallel_executor import ParallelExecutor

STEPS = 4
BS = 16  # divisible by 8 (dp) and 4 (dp when mp=2)


def _build_net():
    x = fluid.layers.data(name='x', shape=[16], dtype='float32')
    label = fluid.layers.data(name='label', shape=[1], dtype='int64')
    h = fluid.layers.fc(input=x, size=32, act='relu')
    logits = fluid.layers.fc(input=h, size=8)
    loss = fluid.layers.mean(
        fluid.layers.softmax_with_cross_entropy(logits=logits, label=label))
    fluid.optimizer.Momentum(learning_rate=0.1, momentum=0.9).minimize(loss)
    return loss


def _feeds():
    rng = np.random.RandomState(7)
    return [{'x': rng.randn(BS, 16).astype(np.float32),
             'label': rng.randint(0, 8, (BS, 1)).astype(np.int64)}
            for _ in range(STEPS)]


def _init_snapshot(startup):
    """Run the startup program once; return {name: value} of initialized vars."""
    scope = fluid.core.Scope()
    exe = fluid.Executor()
    with fluid.scope_guard(scope):
        exe.run(startup)
    # snapshot as host numpy: the executor donates state buffers to XLA
    # (donate_argnums), so device arrays shared across runs would be deleted
    return {n: np.asarray(scope.get(n)) for n in scope.local_var_names()
            if scope.get(n) is not None}


def _run_steps(program, init, feeds, fetch, wrap=None):
    """Train from `init` for len(feeds) steps; return per-step losses."""
    scope = fluid.core.Scope()
    for n, v in init.items():
        scope.set(n, v)
    exe = fluid.Executor()
    target = wrap(program) if wrap is not None else program
    losses = []
    with fluid.scope_guard(scope):
        for feed in feeds:
            out, = exe.run(program=target, feed=feed, fetch_list=[fetch])
            losses.append(float(np.asarray(out).reshape(-1)[0]))
    return losses


def test_dp_loss_parity_1dev_vs_8dev():
    """Same init, same data: 8-way data-parallel must track single-device
    losses step for step (ref test_dist_base.check_with_place)."""
    loss = _build_net()
    main = fluid.default_main_program()
    startup = fluid.default_startup_program()
    init = _init_snapshot(startup)
    feeds = _feeds()

    single = _run_steps(main, init, feeds, loss)
    mesh = make_mesh(axes={'dp': 8})
    spmd = _run_steps(
        main, init, feeds, loss,
        wrap=lambda p: CompiledProgram(p).with_data_parallel(
            loss_name=loss.name, mesh=mesh))

    assert np.isfinite(single).all() and np.isfinite(spmd).all()
    np.testing.assert_allclose(single, spmd, rtol=1e-4, atol=1e-5)
    # training must actually move
    assert single[-1] != single[0]


def test_parallel_executor_matches_executor():
    """ParallelExecutor wrapper runs the same program over the mesh path
    (ref parallel_executor_test_base.py methodology)."""
    loss = _build_net()
    main = fluid.default_main_program()
    startup = fluid.default_startup_program()
    init = _init_snapshot(startup)
    feeds = _feeds()

    single = _run_steps(main, init, feeds, loss)

    scope = fluid.core.Scope()
    for n, v in init.items():
        scope.set(n, v)
    pe = ParallelExecutor(use_cuda=False, loss_name=loss.name,
                          main_program=main, scope=scope)
    assert pe.device_count == 8
    with fluid.scope_guard(scope):
        pe_losses = [float(np.asarray(pe.run([loss], feed=f)[0]).reshape(-1)[0])
                     for f in feeds]
    np.testing.assert_allclose(single, pe_losses, rtol=1e-4, atol=1e-5)


def test_tensor_parallel_parity():
    """dp=4 x mp=2 mesh with Megatron-style column/row-sharded fc weights:
    same math, different partitioning (the GSPMD replacement for the legacy
    ParallelNeuralNetwork layer-wise model parallelism)."""
    loss = _build_net()
    main = fluid.default_main_program()
    startup = fluid.default_startup_program()

    for p in main.global_block().all_parameters():
        if len(p.shape) == 2 and p.shape[1] == 32:
            shard_parameter(p, (None, 'mp'))   # column-parallel
        elif len(p.shape) == 2 and p.shape[0] == 32:
            shard_parameter(p, ('mp', None))   # row-parallel

    init = _init_snapshot(startup)
    feeds = _feeds()

    single = _run_steps(main, init, feeds, loss)
    mesh = make_mesh(axes={'dp': 4, 'mp': 2})
    tp = _run_steps(
        main, init, feeds, loss,
        wrap=lambda p: CompiledProgram(p).with_data_parallel(
            loss_name=loss.name, mesh=mesh))
    np.testing.assert_allclose(single, tp, rtol=1e-4, atol=1e-5)


def test_se_resnext_dp_parity():
    """SE-ResNeXt under 8-way data parallelism tracks the single-device
    losses — the reference's test_parallel_executor_seresnext tradition
    (its canonical multi-device parity model: grouped convs + SE gates +
    BN stress the partitioner more than plain fc nets)."""
    from models.se_resnext import build_train_net
    images, label, loss, acc = build_train_net(dshape=(3, 32, 32),
                                               class_dim=10, depth=50)
    main = fluid.default_main_program()
    startup = fluid.default_startup_program()
    init = _init_snapshot(startup)
    rng = np.random.RandomState(11)
    feeds = [{'data': rng.randn(BS, 3, 32, 32).astype(np.float32),
              'label': rng.randint(0, 10, (BS, 1)).astype(np.int64)}
             for _ in range(2)]

    single = _run_steps(main, init, feeds, loss)
    mesh = make_mesh(axes={'dp': 8})
    spmd = _run_steps(
        main, init, feeds, loss,
        wrap=lambda p: CompiledProgram(p).with_data_parallel(
            loss_name=loss.name, mesh=mesh))
    assert np.isfinite(single).all() and np.isfinite(spmd).all()
    # GSPMD preserves BN's global batch stats (step-1 parity is ~1e-6
    # relative); step 2 accumulates optimizer-update + deep-net CPU
    # fastmath divergence, measured ~5e-3
    np.testing.assert_allclose(single, spmd, rtol=2e-2, atol=1e-3)


def test_per_device_feed_list_merged():
    """Reference semantics: a list of per-device feed dicts is accepted and
    concatenated along the batch dim (parallel_executor.py feed list)."""
    loss = _build_net()
    main = fluid.default_main_program()
    startup = fluid.default_startup_program()
    exe = fluid.Executor()
    exe.run(startup)
    pe = ParallelExecutor(use_cuda=False, loss_name=loss.name,
                          main_program=main)
    rng = np.random.RandomState(3)
    per_dev = [{'x': rng.randn(2, 16).astype(np.float32),
                'label': rng.randint(0, 8, (2, 1)).astype(np.int64)}
               for _ in range(8)]
    out = pe.run([loss], feed=per_dev)
    assert np.isfinite(np.asarray(out[0])).all()
