"""Subprocess worker for test_quantize.py and quant_smoke.py: one
QUANTIZED-tier serving replica "cold start". Loads the int8 tier of a
compiled artifact by FILE PATH (the framework must never load into a
serving process), runs one batch from IN.npz, and prints the fetches'
sha256 plus the number of XLA backend compiles as a JSON line:

    python quant_serve_worker.py ARTIFACT_DIR IN.npz [TIER]

With per-tier AOT sidecars present (export_compiled default /
cache_ctl prewarm), compiles must be 0 — the ISSUE 11 warm-replica
acceptance bar, tier by tier.
"""
import hashlib
import json
import os
import sys


def main():
    artifact, in_path = sys.argv[1], sys.argv[2]
    tier = sys.argv[3] if len(sys.argv) > 3 else 'int8'
    os.environ.setdefault('JAX_PLATFORMS', 'cpu')
    os.environ.setdefault('PTPU_PLATFORM', 'cpu')
    import numpy as np
    from jax import monitoring

    compiles = [0]

    def _listener(event, secs, **kw):
        if event == '/jax/core/compile/backend_compile_duration':
            compiles[0] += 1

    monitoring.register_event_duration_secs_listener(_listener)

    here = os.path.dirname(os.path.abspath(__file__))
    sys.path.insert(0, os.path.join(os.path.dirname(here), 'paddle_tpu',
                                    'inference'))
    import serve

    pred = serve.CompiledPredictor(artifact, tier=tier)
    with np.load(in_path) as z:
        feed = {k: z[k] for k in z.files}
    outs = pred.run(feed)
    digest = hashlib.sha256()
    for o in outs:
        digest.update(np.ascontiguousarray(o).tobytes())
    assert 'paddle_tpu' not in sys.modules, \
        'the framework leaked into the serving process'
    print('QUANT %s' % json.dumps({
        'compiles': compiles[0], 'tier': pred.tier,
        'sha': digest.hexdigest(),
        'shapes': [list(np.shape(o)) for o in outs]}))
    print('QUANT_OK')


if __name__ == '__main__':
    main()
