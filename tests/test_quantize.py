"""Int8 quantized inference (ISSUE 11): the quantize_program pass
(calibration sweep, per-channel weights, def-use-safe activation quant,
machine-checkable float-op reasons), the quantized artifact tier
(export/load/serve + tier metrics), and the int8 paged KV cache
(fixed-HBM slot doubling, fp-KV transcript tolerance)."""
import json
import os
import subprocess
import sys

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import passes
from paddle_tpu.passes import quantize as quant


def _build_small_net():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        img = fluid.layers.data(name='img', shape=[3, 16, 16],
                                dtype='float32')
        c = fluid.layers.conv2d(img, 8, 3, padding=1, act='relu')
        p = fluid.layers.pool2d(c, 2, 'max', pool_stride=2)
        fc = fluid.layers.fc(p, 32, act='relu')
        logits = fluid.layers.fc(fc, 10, act='softmax')
    return main, startup, logits


def _calibrated(n_batches=3, batch=4):
    main, startup, logits = _build_small_net()
    scope = fluid.core.Scope()
    exe = fluid.Executor(fluid.CPUPlace())
    rng = np.random.RandomState(0)
    batches = [{'img': rng.randn(batch, 3, 16, 16).astype(np.float32)}
               for _ in range(n_batches)]
    with fluid.scope_guard(scope):
        exe.run(startup)
        calib = passes.calibrate_program(main, batches, exe, scope=scope)
    return main, logits, scope, exe, calib, batches


# ---------------------------------------------------------------------------
# calibration
# ---------------------------------------------------------------------------
def test_calibration_targets_and_sweep():
    main, logits, scope, exe, calib, batches = _calibrated()
    targets = passes.calibration_targets(main)
    assert 'img' in targets            # conv activation input
    assert len(targets) == 3           # conv + two fc (mul) inputs
    for t in targets:
        ent = calib.stats[t]
        assert ent['batches'] == 3
        assert ent['abs_max'] >= ent['percentile'] > 0.0
        assert calib.scale(t, 'abs_max') >= calib.scale(t, 'percentile')
    # round-trips through dicts (the signature serialization path)
    back = quant.CalibrationResult.from_dict(calib.as_dict())
    assert back.scale('img') == calib.scale('img')


def test_quantize_weight_per_channel():
    w = np.random.RandomState(0).randn(4, 3, 3, 3).astype(np.float32)
    w[2] = 0.0                                    # dead output channel
    q, s = quant.quantize_weight(w)               # conv OIHW: axis 0
    assert q.dtype == np.int8 and q.shape == w.shape
    assert s.shape == (4,) and s[2] == 1.0        # zero channel -> 1.0
    deq = q.reshape(4, -1).astype(np.float32) * s[:, None]
    assert np.abs(deq.reshape(w.shape) - w).max() <= s.max() * 0.5 + 1e-7
    # mul weights: per output column of the [K, N] form
    w2 = np.random.RandomState(1).randn(6, 5).astype(np.float32)
    q2, s2 = quant.quantize_weight(w2, flatten_cols=1)
    assert s2.shape == (5,)


# ---------------------------------------------------------------------------
# the pass
# ---------------------------------------------------------------------------
def test_quantize_program_parity_and_report():
    main, logits, scope, exe, calib, batches = _calibrated()
    qprog, report = passes.quantize_program(
        main, calib, scope, fetch_names=[logits.name])
    d = report.details
    assert d['quantized_ops'] == 3
    assert d['float_weights_pruned'] == 3
    assert d['weight_bytes_after'] < d['weight_bytes_before']
    types = [op.type for op in qprog.global_block().ops]
    assert 'conv2d_int8' in types and 'mul_int8' in types
    assert 'conv2d' not in types and 'mul' not in types
    # every float op left carries a machine-checkable reason
    for e in d['float_ops']:
        assert e['reason'] in quant.REASON_CODES
    # parity through the executor
    with fluid.scope_guard(scope):
        ref = exe.run(main, feed=batches[0], fetch_list=[logits.name])[0]
        out = exe.run(qprog, feed=batches[0], fetch_list=[logits.name])[0]
    assert (out.argmax(1) == ref.argmax(1)).all()
    assert np.abs(out - ref).max() < 0.05
    # the rewrite is verifier-clean (registry sweep included)
    assert not passes.verify_program(qprog, fetch_names=[logits.name],
                                     level='full')
    # ...and the original program is untouched
    assert 'conv2d' in [op.type for op in main.global_block().ops]


def test_quantize_reason_codes():
    main, logits, scope, exe, calib, batches = _calibrated()
    # no calibration at all: every candidate reports no_calibration
    _, rep = passes.quantize_program(main, None, scope,
                                     fetch_names=[logits.name])
    reasons = rep.details['float_op_reasons']
    assert reasons.get(quant.REASON_NO_CALIBRATION) == 3
    assert rep.details['quantized_ops'] == 0
    # user skip by weight name
    w_names = [op.inputs['Filter'][0]
               for op in main.global_block().ops if op.type == 'conv2d']
    _, rep2 = passes.quantize_program(main, calib, scope,
                                      fetch_names=[logits.name],
                                      skip_vars=w_names)
    assert rep2.details['float_op_reasons'].get(quant.REASON_USER_SKIP) == 1
    assert rep2.details['quantized_ops'] == 2
    # missing weight value in the scope
    empty = fluid.core.Scope()
    _, rep3 = passes.quantize_program(main, calib, empty,
                                      fetch_names=[logits.name])
    assert rep3.details['float_op_reasons'].get(
        quant.REASON_W_VALUE_MISSING) == 3


def test_quantize_rebound_activation_gets_fresh_quant():
    """A var REWRITTEN between two consumers must not reuse the stale
    quantized copy — the def-use chain keys the quant cache."""
    main = fluid.Program()
    startup = fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data(name='x', shape=[6], dtype='float32',
                              append_batch_size=False)
        x.shape = [4, 6]
        w1 = fluid.layers.create_parameter([6, 5], 'float32', name='w1')
        w2 = fluid.layers.create_parameter([6, 5], 'float32', name='w2')
    block = main.global_block()
    block.create_var(name='h1', shape=[4, 5], dtype='float32')
    block.create_var(name='h2', shape=[4, 5], dtype='float32')
    block.append_op('mul', {'X': ['x'], 'Y': ['w1']}, {'Out': ['h1']},
                    {'x_num_col_dims': 1, 'y_num_col_dims': 1})
    # rebind x in place (scale writes the same name)
    block.append_op('scale', {'X': ['x']}, {'Out': ['x']}, {'scale': 2.0})
    block.append_op('mul', {'X': ['x'], 'Y': ['w2']}, {'Out': ['h2']},
                    {'x_num_col_dims': 1, 'y_num_col_dims': 1})
    scope = fluid.core.Scope()
    rng = np.random.RandomState(0)
    scope.set('w1', rng.randn(6, 5).astype(np.float32))
    scope.set('w2', rng.randn(6, 5).astype(np.float32))
    calib = quant.CalibrationResult()
    calib.observe('x', rng.randn(4, 6))
    qprog, rep = passes.quantize_program(main, calib, scope,
                                         fetch_names=['h1', 'h2'])
    assert rep.details['quantized_ops'] == 2
    q_ops = [op for op in qprog.global_block().ops
             if op.type == 'quantize_int8']
    assert len(q_ops) == 2              # one per x BINDING, not per var
    assert len({op.outputs['Out'][0] for op in q_ops}) == 2


def test_quantize_shared_activation_quantized_once():
    """Two consumers of the SAME binding share one quantize op."""
    main = fluid.Program()
    startup = fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data(name='x', shape=[4, 6], dtype='float32',
                              append_batch_size=False)
        w1 = fluid.layers.create_parameter([6, 5], 'float32', name='wa')
        w2 = fluid.layers.create_parameter([6, 5], 'float32', name='wb')
        h1 = fluid.layers.mul(x, w1)
        h2 = fluid.layers.mul(x, w2)
    scope = fluid.core.Scope()
    rng = np.random.RandomState(0)
    scope.set('wa', rng.randn(6, 5).astype(np.float32))
    scope.set('wb', rng.randn(6, 5).astype(np.float32))
    calib = quant.CalibrationResult()
    calib.observe('x', rng.randn(4, 6))
    qprog, rep = passes.quantize_program(
        main, calib, scope, fetch_names=[h1.name, h2.name])
    assert rep.details['quantized_ops'] == 2
    q_ops = [op for op in qprog.global_block().ops
             if op.type == 'quantize_int8']
    assert len(q_ops) == 1


def test_quantize_shared_weight_quantized_once():
    """One weight feeding TWO quantizable consumers is quantized (and
    byte-counted) once; both int8 ops reference the same var pair."""
    main = fluid.Program()
    startup = fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data(name='x', shape=[4, 6], dtype='float32',
                              append_batch_size=False)
        y = fluid.layers.data(name='y', shape=[4, 6], dtype='float32',
                              append_batch_size=False)
        w = fluid.layers.create_parameter([6, 5], 'float32', name='wt')
        h1 = fluid.layers.mul(x, w)
        h2 = fluid.layers.mul(y, w)
    scope = fluid.core.Scope()
    rng = np.random.RandomState(0)
    w_val = rng.randn(6, 5).astype(np.float32)
    scope.set('wt', w_val)
    calib = quant.CalibrationResult()
    calib.observe('x', rng.randn(4, 6))
    calib.observe('y', rng.randn(4, 6))
    qprog, rep = passes.quantize_program(
        main, calib, scope, fetch_names=[h1.name, h2.name])
    assert rep.details['quantized_ops'] == 2
    assert rep.details['weight_bytes_before'] == w_val.nbytes  # once
    muls = [op for op in qprog.global_block().ops
            if op.type == 'mul_int8']
    assert len({op.inputs['Y'][0] for op in muls}) == 1
    assert len({op.inputs['Scale'][0] for op in muls}) == 1


def test_reexport_without_quantize_removes_stale_tier(tiered_artifact,
                                                     tmp_path):
    """A quantize=None re-export into a dir carrying an int8 tier must
    not leave the STALE quantized model servable."""
    from paddle_tpu.inference import (Config, create_predictor,
                                      export_compiled, CompiledPredictor)
    adir, calib = tiered_artifact
    mdir = os.path.join(os.path.dirname(adir), 'model')
    pred = create_predictor(Config(mdir))
    re_dir = str(tmp_path / 're')
    x = calib[0]['img']
    export_compiled(pred, [x], re_dir, batch_sizes=[1, 4],
                    quantize='int8', calibration=calib)
    assert os.path.isdir(os.path.join(re_dir, 'int8'))
    with pytest.warns(RuntimeWarning, match='stale int8 tier'):
        export_compiled(pred, [x], re_dir, batch_sizes=[1, 4])
    assert not os.path.isdir(os.path.join(re_dir, 'int8'))
    with open(os.path.join(re_dir, 'signature.json')) as f:
        assert 'tiers' not in json.load(f)
    with pytest.raises(ValueError, match='has no .* tier'):
        CompiledPredictor(re_dir, tier='int8')


def test_compile_cache_quant_tag():
    from paddle_tpu.core import compile_cache as cc
    main, logits, scope, exe, calib, _ = _calibrated(n_batches=1)
    assert cc.quant_tag('executor_run', main) == 'executor_run'
    qprog, _ = passes.quantize_program(main, calib, scope,
                                       fetch_names=[logits.name])
    assert cc.quant_tag('executor_run', qprog) == 'executor_run-int8'


# ---------------------------------------------------------------------------
# the artifact tier
# ---------------------------------------------------------------------------
@pytest.fixture(scope='module')
def tiered_artifact(tmp_path_factory):
    """One small artifact with both tiers, buckets [1, 4]."""
    from paddle_tpu.inference import (Config, create_predictor,
                                      export_compiled)
    d = tmp_path_factory.mktemp('quant_art')
    main, startup = fluid.Program(), fluid.Program()
    prev_m = fluid.switch_main_program(main)
    prev_s = fluid.switch_startup_program(startup)
    try:
        img = fluid.layers.data(name='img', shape=[3, 16, 16],
                                dtype='float32')
        c = fluid.layers.conv2d(img, 8, 3, padding=1, act='relu')
        fc = fluid.layers.fc(c, 16, act='relu')
        logits = fluid.layers.fc(fc, 10, act='softmax')
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        mdir = str(d / 'model')
        adir = str(d / 'artifact')
        fluid.io.save_inference_model(mdir, ['img'], [logits], exe, main)
        pred = create_predictor(Config(mdir))
        rng = np.random.RandomState(0)
        calib = [{'img': rng.randn(4, 3, 16, 16).astype(np.float32)}
                 for _ in range(2)]
        export_compiled(pred, [calib[0]['img']], adir, batch_sizes=[1, 4],
                        quantize='int8', calibration=calib)
    finally:
        fluid.switch_main_program(prev_m)
        fluid.switch_startup_program(prev_s)
    return adir, calib


def test_tier_layout_and_signature(tiered_artifact):
    adir, _ = tiered_artifact
    assert os.path.isdir(os.path.join(adir, 'int8', 'bucket_00001'))
    assert os.path.isdir(os.path.join(adir, 'int8', 'bucket_00004'))
    with open(os.path.join(adir, 'signature.json')) as f:
        top = json.load(f)
    assert top['tiers'] == ['bf16', 'int8']
    q = top['quantization']
    assert q['quantized_ops'] > 0 and q['act_scales']
    for e in q['float_ops']:
        assert e['reason'] in quant.REASON_CODES
    with open(os.path.join(adir, 'int8', 'signature.json')) as f:
        tier_sig = json.load(f)
    assert tier_sig['tier'] == 'int8'
    assert tier_sig['buckets'] == [1, 4]


def test_tier_loading_and_parity(tiered_artifact):
    from paddle_tpu.inference import CompiledPredictor
    adir, calib = tiered_artifact
    p_b = CompiledPredictor(adir)
    p_q = CompiledPredictor(adir, tier='int8')
    assert (p_b.tier, p_q.tier) == ('bf16', 'int8')
    x = calib[0]['img']
    ob, oq = p_b.run([x])[0], p_q.run([x])[0]
    assert (ob.argmax(1) == oq.argmax(1)).all()
    with pytest.raises(ValueError, match='has no .* tier'):
        CompiledPredictor(adir, tier='fp8')
    # env preference degrades silently when the tier is absent (a bucket
    # dir inside the int8 tree has no further int8/ subdir)
    os.environ['PTPU_SERVE_TIER'] = 'int8'
    try:
        p_env = CompiledPredictor(adir)
        assert p_env.tier == 'int8'
        p_bucket = CompiledPredictor(
            os.path.join(adir, 'int8', 'bucket_00004'))
        assert p_bucket.tier == 'int8'
    finally:
        del os.environ['PTPU_SERVE_TIER']


def test_batching_predictor_int8_tier_and_report(tiered_artifact):
    from paddle_tpu.inference import BatchingPredictor
    from paddle_tpu import profiler
    adir, calib = tiered_artifact
    b = BatchingPredictor(adir, tier='int8', batch_timeout_ms=1.0)
    try:
        b.warmup()
        assert b.tier == 'int8'
        out = b.run([calib[0]['img'][:1]])
        assert out[0].shape == (1, 10)
        snap = b.stats.snapshot()
        assert snap['tier'] == 'int8'
        rep = profiler.serving_report()
        src = next(v for k, v in rep.items() if k.startswith('serving:'))
        assert src['tier'] == 'int8'
    finally:
        b.close()


def test_warm_int8_replica_zero_compiles(tiered_artifact, tmp_path):
    adir, calib = tiered_artifact
    in_npz = str(tmp_path / 'in.npz')
    np.savez(in_npz, img=calib[0]['img'])
    worker = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          'quant_serve_worker.py')
    out = subprocess.run([sys.executable, worker, adir, in_npz, 'int8'],
                         capture_output=True, text=True, timeout=300)
    assert 'QUANT_OK' in out.stdout, out.stdout + out.stderr
    payload = json.loads(next(
        l for l in out.stdout.splitlines()
        if l.startswith('QUANT '))[len('QUANT '):])
    assert payload['compiles'] == 0
    assert payload['tier'] == 'int8'


def test_export_quantize_requires_calibration(tiered_artifact):
    from paddle_tpu.inference import (Config, create_predictor,
                                      export_compiled)
    adir, _ = tiered_artifact
    mdir = os.path.join(os.path.dirname(adir), 'model')
    pred = create_predictor(Config(mdir))
    x = np.zeros((2, 3, 16, 16), np.float32)
    with pytest.raises(ValueError, match='calibration'):
        export_compiled(pred, [x], adir + '_x', quantize='int8')
    with pytest.raises(ValueError, match="quantize must be"):
        export_compiled(pred, [x], adir + '_y', quantize='fp8',
                        calibration=[{'img': x}])


# ---------------------------------------------------------------------------
# the int8 paged KV cache
# ---------------------------------------------------------------------------
def _decode_spec(kv, slots, scope):
    from models.transformer import build_decode_spec
    with fluid.scope_guard(scope):
        spec = build_decode_spec(vocab=41, d_model=16, n_head=2,
                                 n_layer=2, d_ff=32, max_slots=slots,
                                 max_cache_len=24, prompt_buckets=(4,),
                                 eos_id=1, kv_cache_dtype=kv)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(spec['startup'], scope=scope)
    return spec


def test_int8_kv_cache_fixed_hbm_and_transcripts(tmp_path):
    from paddle_tpu.inference import DecodingPredictor, export_decode
    fp_scope, q_scope = fluid.core.Scope(), fluid.core.Scope()
    fp_spec = _decode_spec('float32', 2, fp_scope)
    q_spec = _decode_spec('int8', 4, q_scope)     # 2x slots
    assert set(q_spec['cache_vars']) >= {'kv_ks_0', 'kv_vs_0'}
    cache_names = set(q_spec['cache_vars'])
    for n in q_scope.local_var_names():
        if n not in cache_names and fp_scope.get(n) is not None:
            q_scope.set(n, fp_scope.get(n))

    def serve(spec, scope, art):
        with fluid.scope_guard(scope):
            export_decode(spec, art, scope=scope)
        with open(os.path.join(art, 'decode_signature.json')) as f:
            sig = json.load(f)
        pred = DecodingPredictor(art)
        rng = np.random.RandomState(3)
        prompts = [rng.randint(2, 41, int(rng.randint(2, 5)))
                   for _ in range(6)]
        outs = [pred.generate(p, max_new_tokens=8) for p in prompts]
        snap = pred.stats.snapshot()
        pred.close()
        return outs, sig, snap

    fp_out, fp_sig, fp_snap = serve(fp_spec, fp_scope,
                                    str(tmp_path / 'fp'))
    q_out, q_sig, q_snap = serve(q_spec, q_scope, str(tmp_path / 'q'))
    # 2x slots at LOWER cache bytes: the fixed-HBM doubling
    assert q_sig['max_slots'] == 2 * fp_sig['max_slots']
    assert q_sig['cache_bytes'] < fp_sig['cache_bytes']
    assert q_sig['kv_cache_dtype'] == 'int8'
    assert fp_sig['kv_cache_dtype'] == 'float32'
    assert (fp_snap['tier'], q_snap['tier']) == ('bf16', 'int8')
    # transcripts track the fp reference within tolerance
    match = np.mean([
        np.mean(np.asarray(a[:min(len(a), len(b))])
                == np.asarray(b[:min(len(a), len(b))]))
        for a, b in zip(fp_out, q_out)])
    assert match >= 0.85, 'int8-KV transcripts diverged: %.3f' % match


def test_export_decode_kv_dtype_mismatch(tmp_path):
    from paddle_tpu.inference import export_decode
    scope = fluid.core.Scope()
    spec = _decode_spec('float32', 2, scope)
    with pytest.raises(ValueError, match='kv_cache_dtype'):
        export_decode(spec, str(tmp_path / 'a'), scope=scope,
                      kv_cache_dtype='int8')


def test_kv_quant_ops_roundtrip():
    """Write-then-attend through the quantized kernels tracks the fp
    kernels within the per-page quantization step, and stale garbage in
    masked rows stays exactly invisible."""
    import jax.numpy as jnp
    from paddle_tpu.core.registry import get

    class Ctx:
        def __init__(self, **a):
            self.attrs = a

        def attr(self, n, d=None):
            return self.attrs.get(n, d)

    rng = np.random.RandomState(0)
    S, T, D = 3, 8, 8
    kv = rng.randn(S, D).astype(np.float32)
    pos = np.full((S, 1), 2, np.int32)
    cache = np.zeros((S, T, D), np.int8)
    cscale = np.ones((S, T), np.float32)
    out = get('kv_cache_write_quant').lower(Ctx(), {
        'Cache': [jnp.asarray(cache)], 'Scale': [jnp.asarray(cscale)],
        'KV': [jnp.asarray(kv)], 'Pos': [jnp.asarray(pos)]})
    c2, s2 = np.asarray(out['Out'][0]), np.asarray(out['OutScale'][0])
    deq = c2[:, 2, :].astype(np.float32) * s2[:, 2, None]
    assert np.abs(deq - kv).max() <= np.abs(kv).max() / 127.0 * 0.51
    # attention: garbage in rows > pos must not perturb the result
    q = rng.randn(S, D).astype(np.float32)
    kc = c2.copy()
    kc[:, 3:, :] = 77                      # stale garbage beyond pos
    args = lambda k: {'Q': [jnp.asarray(q)], 'KCache': [jnp.asarray(k)],
                      'KScale': [jnp.asarray(s2)],
                      'VCache': [jnp.asarray(c2)],
                      'VScale': [jnp.asarray(s2)],
                      'Pos': [jnp.asarray(pos)]}
    att = get('kv_cache_attention_quant')
    o1 = np.asarray(att.lower(Ctx(n_head=2), args(c2))['Out'][0])
    o2 = np.asarray(att.lower(Ctx(n_head=2), args(kc))['Out'][0])
    assert np.array_equal(o1, o2)
