"""Serving-fleet control plane (ISSUE 12): FleetRouter routing /
failover / drain semantics, Autoscaler decisions, RollingRollout
promote + loud rollback, predictor drain() hooks, decode tier
plumbing, the profiler fleet table, and the fleet_ctl CLI.

Chaos contract under test: killing one of N replicas mid-stream loses
ONLY that replica's in-flight requests (every other request completes
bit-identical to a single-replica reference); a hung (SIGSTOP) replica
is detected by the heartbeat watchdog in bounded time and its queue
re-routes; scale-in drains with zero dropped in-flight streams.
"""
import json
import os
import signal
import subprocess
import sys
import threading
import time
import warnings

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import profiler
from paddle_tpu.inference import (Autoscaler, BatchingPredictor, Config,
                                  DecodingPredictor, FleetRouter,
                                  ReplicaFailed, RollingRollout,
                                  RolloutRolledBack, ServerOverloaded,
                                  create_predictor, export_compiled,
                                  export_decode)
from paddle_tpu.inference import fleet as fleet_mod

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
DIM = 8
VOCAB = 61


def _patient(router):
    """Raise every fleet timeout that only exists to bound wall-clock:
    under a loaded CI host a busy (not hung) replica must never be
    declared dead by a test."""
    router.hb_timeout_s = 60.0
    return router


@pytest.fixture(scope='module')
def dense_art(tmp_path_factory):
    """One tiny classifier exported single-bucket [8] (requests of
    exactly 8 rows route through the same compiled shape everywhere —
    strict bit-identity) with a calibrated int8 tier, plus the
    in-framework predictor as reference."""
    tmp = str(tmp_path_factory.mktemp('fleet_dense'))
    with fluid.scope_guard(fluid.core.Scope()), fluid.unique_name.guard():
        main, startup = fluid.Program(), fluid.Program()
        main.random_seed = startup.random_seed = 7
        with fluid.program_guard(main, startup):
            img = fluid.layers.data(name='img', shape=[DIM],
                                    dtype='float32')
            h = fluid.layers.fc(img, 32, act='relu')
            out = fluid.layers.fc(h, 4, act='softmax')
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        model_dir = os.path.join(tmp, 'model')
        fluid.io.save_inference_model(model_dir, ['img'], [out], exe,
                                      main)
        pred = create_predictor(Config(model_dir))
        rng = np.random.RandomState(3)
        calib = [[rng.randn(8, DIM).astype(np.float32)]
                 for _ in range(4)]
        art = os.path.join(tmp, 'art')
        export_compiled(pred, calib[0], art, batch_sizes=[8],
                        quantize='int8', calibration=calib)
    return {'art': art, 'pred': pred, 'calib': calib}


@pytest.fixture(scope='module')
def decode_art(tmp_path_factory):
    tmp = str(tmp_path_factory.mktemp('fleet_decode'))
    art = os.path.join(tmp, 'decode')
    from models.transformer import build_decode_spec
    scope = fluid.core.Scope()
    with fluid.scope_guard(scope), fluid.unique_name.guard():
        spec = build_decode_spec(vocab=VOCAB, d_model=8, n_head=2,
                                 n_layer=1, d_ff=16, max_slots=4,
                                 max_cache_len=40, prompt_buckets=(4,),
                                 eos_id=1)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(spec['startup'])
        export_decode(spec, art, scope=scope)
    return art


@pytest.fixture(scope='module')
def block_art(tmp_path_factory):
    """Block-paged decode artifact (ISSUE 13): same model as decode_art
    but with the cache as a block pool + chunked prefill."""
    tmp = str(tmp_path_factory.mktemp('fleet_block'))
    art = os.path.join(tmp, 'block')
    from models.transformer import build_decode_spec
    scope = fluid.core.Scope()
    with fluid.scope_guard(scope), fluid.unique_name.guard():
        spec = build_decode_spec(vocab=VOCAB, d_model=8, n_head=2,
                                 n_layer=1, d_ff=16, max_slots=4,
                                 max_cache_len=40, prompt_buckets=(4,),
                                 eos_id=1, block_size=4)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(spec['startup'])
        export_decode(spec, art, scope=scope)
    return art


def _x(seed, rows=8):
    return np.random.RandomState(100 + seed).randn(
        rows, DIM).astype(np.float32)


def _prompts(n, seed=0):
    rng = np.random.RandomState(seed)
    return [rng.randint(2, VOCAB, rng.randint(2, 5)) for _ in range(n)]


# -- wire protocol / routing units (no subprocesses) -------------------------

def test_frame_roundtrip_and_bounds():
    import socket as socketlib
    a, b = socketlib.socketpair()
    hdr = {'op': 'infer', 'id': 7, 'deadline_ms': 12.5}
    arrays = {'x': np.arange(12, dtype=np.float32).reshape(3, 4),
              'y': np.array([b'ab', b'cd'])}
    fleet_mod._send_frame(a, hdr, arrays)
    fleet_mod._send_frame(a, {'op': 'stop'})
    got_hdr, got_arrays = fleet_mod._recv_frame(b)
    assert got_hdr == hdr
    np.testing.assert_array_equal(got_arrays['x'], arrays['x'])
    np.testing.assert_array_equal(got_arrays['y'], arrays['y'])
    hdr2, arrays2 = fleet_mod._recv_frame(b)
    assert hdr2 == {'op': 'stop'} and arrays2 == {}
    a.close()
    assert fleet_mod._recv_frame(b) is None  # clean EOF
    b.close()
    # corrupt length prefix -> loud IOError, not a hang
    c, d = socketlib.socketpair()
    c.sendall(b'\xff' * 8 + b'junk')
    with pytest.raises(IOError):
        fleet_mod._recv_frame(d)
    c.close()
    d.close()


def test_detect_kind(dense_art, decode_art, tmp_path):
    assert fleet_mod.detect_kind(dense_art['art']) == 'batching'
    assert fleet_mod.detect_kind(decode_art) == 'decoding'
    with pytest.raises(ValueError):
        fleet_mod.detect_kind(str(tmp_path))


def test_agreement_measures():
    a = [np.arange(8, dtype=np.float32).reshape(2, 4)]
    assert fleet_mod.bit_agreement(a, [a[0].copy()]) == 1.0
    b = [a[0] + 1e-6]
    assert fleet_mod.bit_agreement(a, b) == 0.0
    assert fleet_mod.top1_agreement(a, b) == 1.0  # argmax unchanged
    c = [a[0][:, ::-1].copy()]
    assert fleet_mod.top1_agreement(a, c) == 0.0
    # greedy transcripts compare exactly — in BOTH measures ('top1' on
    # a decode fleet is the round-14 transcript-agreement fraction)
    assert fleet_mod.bit_agreement([3, 1, 2], [3, 1, 2]) == 1.0
    assert fleet_mod.bit_agreement([3, 1, 2], [3, 1]) == 0.0
    assert fleet_mod.top1_agreement([3, 1, 2], [3, 1, 2]) == 1.0
    assert fleet_mod.top1_agreement([3, 1, 2], [3, 1, 9]) == 0.0
    assert fleet_mod.top1_agreement([3, 1, 2], [3, 1]) == 0.0


# -- predictor drain() hooks (in-process, the fleet's scale-in lever) --------

def test_batching_drain_sheds_queue_finishes_inflight(dense_art):
    """drain(): queued requests shed loudly (shed+drained counters),
    the in-flight dispatch delivers, submit() afterwards raises. The
    first dispatch is gated on an Event so a real queue backlog exists
    at drain time."""
    batcher = BatchingPredictor(dense_art['art'], batch_timeout_ms=1.0,
                                max_batch_size=8)
    batcher.warmup()
    gate = threading.Event()
    real = batcher._preds[8]._call_flat

    def gated(args):
        gate.wait(30)
        return real(args)
    batcher._preds[8]._call_flat = gated
    # full-bucket requests: each dispatches alone; r0 blocks in the
    # gated dispatch while r1..r4 sit QUEUED behind it
    futs = [batcher.submit([_x(i)]) for i in range(5)]
    drainer = threading.Thread(target=batcher.drain)
    time.sleep(0.2)
    drainer.start()
    time.sleep(0.2)
    gate.set()
    drainer.join(60)
    assert not drainer.is_alive()
    outs = futs[0].result(60)     # the in-flight dispatch delivered
    want, = dense_art['pred'].run([_x(0)])
    np.testing.assert_array_equal(outs[0], want)
    shed = 0
    for f in futs[1:]:
        with pytest.raises(ServerOverloaded, match='draining'):
            f.result(60)
        shed += 1
    snap = batcher.stats.snapshot()
    assert snap['drained'] == shed == 4
    assert snap['shed'] >= 4
    with pytest.raises(RuntimeError):
        batcher.submit([_x(0)])
    batcher.close()  # idempotent after drain


def test_decoding_drain_finishes_active_sheds_waiting(decode_art):
    """drain(): ACTIVE streams decode to completion (zero drops),
    waiting queue sheds re-routably, new submissions shed."""
    with DecodingPredictor(decode_art, platform='cpu') as ref:
        want = ref.generate(_prompts(1)[0], max_new_tokens=24)
    pred = DecodingPredictor(decode_art, platform='cpu')
    try:
        # 4 slots: 4 active + 3 waiting
        streams = [pred.submit(_prompts(1)[0], max_new_tokens=24)
                   for _ in range(7)]
        time.sleep(0.05)
        assert pred.drain(timeout=120)
        results, shed = [], 0
        for s in streams:
            try:
                results.append(s.result(60))
            except ServerOverloaded:
                shed += 1
        assert len(results) >= 4 and shed == 7 - len(results)
        assert all(r == want for r in results)
        snap = pred.stats.snapshot()
        assert snap['drained'] == shed
        # draining endpoint admits nothing, sheds loudly
        with pytest.raises(ServerOverloaded):
            pred.submit(_prompts(1)[0]).result(60)
        assert pred.stats.snapshot()['drained'] == shed + 1
    finally:
        pred.close()


def test_compiled_predictor_drain_hook(dense_art):
    from paddle_tpu.inference import CompiledPredictor
    p = CompiledPredictor(dense_art['art'])
    assert p.drain() is p  # synchronous predictor: no queue, no-op


# -- decode tier plumbing (satellite) ----------------------------------------

def test_decoding_tier_contract(decode_art, tmp_path):
    """DecodingPredictor(tier=): explicit missing tier raises (the
    BatchingPredictor contract); a present tier subdir resolves; the
    env preference degrades silently."""
    with pytest.raises(ValueError, match="has no 'int8' tier"):
        DecodingPredictor(decode_art, tier='int8')
    # build a tier: the quantized-KV artifact exported under int8/
    import shutil
    tiered = str(tmp_path / 'tiered')
    shutil.copytree(decode_art, tiered)
    shutil.copytree(decode_art, os.path.join(tiered, 'int8'))
    sig_p = os.path.join(tiered, 'int8',
                         'decode_signature.json')
    with open(sig_p) as f:
        sig = json.load(f)
    sig['kv_cache_dtype'] = 'int8'  # mark the tier copy
    with open(sig_p, 'w') as f:
        json.dump(sig, f)
    p = DecodingPredictor(tiered, tier='int8', platform='cpu')
    assert p.stats.tier == 'int8'
    p.close()
    # env preference resolves the tier; on artifacts without one it
    # degrades silently to the top level
    os.environ['PTPU_SERVE_TIER'] = 'int8'
    try:
        p = DecodingPredictor(tiered, platform='cpu')
        assert p.stats.tier == 'int8'
        p.close()
        p = DecodingPredictor(decode_art, platform='cpu')
        assert p.stats.tier == 'bf16'
        p.close()
    finally:
        del os.environ['PTPU_SERVE_TIER']


def test_serve_decode_cli_tier_flag(decode_art, tmp_path):
    """serve.py decode --tier: explicit missing tier exits loudly."""
    prompts = np.zeros((2, 4), np.int64)
    prompts[:, :2] = 5
    in_p = str(tmp_path / 'p.npz')
    np.savez(in_p, prompts=prompts, lens=np.array([2, 2], np.int64))
    out_p = str(tmp_path / 'o.npz')
    env = dict(os.environ, JAX_PLATFORMS='cpu', PTPU_PLATFORM='cpu')
    serve_py = os.path.join(REPO, 'paddle_tpu', 'inference', 'serve.py')
    r = subprocess.run(
        [sys.executable, serve_py, 'decode', decode_art, in_p, out_p,
         '4', '--tier', 'int8'], capture_output=True, text=True,
        env=env)
    assert r.returncode != 0 and "has no 'int8' tier" in r.stderr
    r = subprocess.run(
        [sys.executable, serve_py, 'decode', decode_art, in_p, out_p,
         '4'], capture_output=True, text=True, env=env)
    assert r.returncode == 0, r.stderr
    line = json.loads(r.stdout.strip().splitlines()[-1])
    assert line['tier'] == 'bf16' and line['requests'] == 2
    assert os.path.exists(out_p)


# -- fleet end-to-end --------------------------------------------------------

@pytest.fixture(scope='module')
def dense_fleet(dense_art):
    """One 2-replica batching fleet shared by the read-only tests."""
    with warnings.catch_warnings():
        warnings.simplefilter('ignore')
        router = _patient(FleetRouter(dense_art['art'], replicas=2,
                                      platform='cpu',
                                      inflight_per_replica=4))
        yield router
        router.close()


def test_fleet_routes_bit_identical(dense_fleet, dense_art):
    xs = [_x(i) for i in range(10)]
    futs = [dense_fleet.submit({'img': x}) for x in xs]
    res = [f.result(120) for f in futs]
    for x, r in zip(xs, res):
        want, = dense_art['pred'].run([x])
        np.testing.assert_array_equal(r[0], want)
    # replica-side serving counters flow back through the heartbeat
    # files (0.5s interval) — poll until they account for the work
    deadline = time.monotonic() + 15
    while time.monotonic() < deadline:
        st = dense_fleet.status()
        if sum(s['requests'] for s in st['replicas'].values()) >= 10:
            break
        time.sleep(0.2)
    served = [s['requests'] for s in st['replicas'].values()]
    assert sum(served) >= 10 and st['serving'] == 2


def test_fleet_warm_spinup_zero_compiles_framework_free(dense_fleet):
    snap = dense_fleet.fleet_snapshot()
    for rid, s in snap['replicas'].items():
        assert s['compiles'] == 0, (rid, s)
    for rep in dense_fleet._replicas.values():
        assert rep.hello.get('framework_free') is True


def test_fleet_deadline_propagates(dense_fleet):
    from paddle_tpu.inference import DeadlineExceeded
    fut = dense_fleet.submit({'img': _x(0)}, deadline_ms=0.0)
    with pytest.raises(DeadlineExceeded):
        fut.result(120)
    assert dense_fleet.fleet_snapshot()['expired'] >= 1


def test_fleet_submit_validation(dense_fleet):
    with pytest.raises(ValueError):
        dense_fleet.submit({'img': _x(0)}, beam=2)  # not a decode fleet
    fut = dense_fleet.submit({'wrong_feed': _x(0)})
    with pytest.raises(Exception):  # replica-side validation, loudly
        fut.result(120)


def test_fleet_report_renders(dense_fleet, capsys):
    name = 'fleet:test#0'
    profiler.register_fleet_source(name, dense_fleet.fleet_snapshot)
    try:
        out = profiler.fleet_report()
        printed = capsys.readouterr().out
    finally:
        profiler.unregister_fleet_source(name)
    assert name in out
    assert 'Fleet source' in printed and 'replica' in printed
    assert out[name]['serving'] == 2
    assert 'p99_ms' in out[name] and 'ttft_p99_ms' in out[name]


def test_fleet_status_json_and_ctl_cli(dense_fleet):
    st = dense_fleet.status()
    assert st['serving'] == 2 and st['kind'] == 'batching'
    status_path = os.path.join(dense_fleet.fleet_dir, 'status.json')
    deadline = time.monotonic() + 10
    while not os.path.exists(status_path) \
            and time.monotonic() < deadline:
        time.sleep(0.1)
    ctl = [sys.executable, os.path.join(REPO, 'tools', 'fleet_ctl.py')]
    r = subprocess.run(ctl + ['status', dense_fleet.fleet_dir,
                              '--json'],
                       capture_output=True, text=True)
    assert r.returncode == 0, r.stderr
    js = json.loads(r.stdout)
    assert js['healthy'] and js['status']['serving'] == 2
    # usage errors exit 2
    assert subprocess.run(
        ctl + ['status', '/not/a/fleet'],
        capture_output=True).returncode == 2
    assert subprocess.run(
        ctl + ['drain', dense_fleet.fleet_dir, '99'],
        capture_output=True).returncode == 2


def test_fleet_chaos_sigkill_loses_only_victim_inflight(decode_art):
    """SIGKILL one replica mid-stream: bounded-time detection, only its
    in-flight requests fail (loudly), everything else bit-identical,
    the fleet keeps serving."""
    prompts = _prompts(48, seed=5)
    with DecodingPredictor(decode_art, platform='cpu') as ref:
        want = [ref.generate(p, max_new_tokens=24) for p in prompts]
    with warnings.catch_warnings():
        warnings.simplefilter('ignore')
        with _patient(FleetRouter(decode_art, replicas=2,
                                  platform='cpu',
                                  inflight_per_replica=4)) as router:
            futs = [router.submit(p, max_new_tokens=24)
                    for p in prompts]
            time.sleep(0.1)
            victim = max(router._replicas.values(),
                         key=lambda r: len(r.outstanding)).rid
            os.kill(router._replicas[victim].proc.pid, signal.SIGKILL)
            t0 = time.perf_counter()
            done, failed = {}, []
            for i, f in enumerate(futs):
                try:
                    done[i] = f.result(300)
                except ReplicaFailed:
                    failed.append(i)
            assert time.perf_counter() - t0 < 120
            assert router._replicas[victim].state == 'dead'
            assert len(failed) <= 4, failed       # inflight cap
            assert len(done) + len(failed) == len(prompts)
            for i, r in done.items():
                assert r == want[i], 'request %d diverged' % i
            snap = router.fleet_snapshot()
            assert snap['replica_deaths'] == 1
            # survivors keep serving
            assert router.run(prompts[0], max_new_tokens=24,
                              timeout=300) == want[0]


def test_mid_stream_eviction_is_not_requeueable():
    """ISSUE 13: a block-pool eviction of an IN-FLIGHT stream raises
    MidStreamEvicted — still a ServerOverloaded for local callers, but
    the worker's post-dispatch re-route decision must refuse it: tokens
    may already have streamed, so a re-route would replay them on
    another replica and blindly retry device work. Door sheds (base
    ServerOverloaded) stay re-routable."""
    import paddle_tpu.inference.fleet_worker as fw
    door = fw._batching.ServerOverloaded('queue full')
    mid = fw._decoding.MidStreamEvicted('evicted mid-decode')
    assert isinstance(mid, fw._batching.ServerOverloaded)
    assert fw._stream_requeueable(door)
    assert not fw._stream_requeueable(mid)
    assert not fw._stream_requeueable(RuntimeError('dispatch failed'))


def test_fleet_block_paged_artifact_unchanged_protocol(block_art):
    """ISSUE 13: a block-paged decode artifact routes through
    FleetRouter/fleet_worker UNCHANGED — detect_kind sees the decode
    signature, the worker's DecodingPredictor reads the layout, and
    transcripts stay bit-identical to a direct in-process serve. The
    hello frame surfaces layout='block' so fleet_ctl can audit the
    tier, and replica heartbeats carry the block-cache gauges."""
    prompts = _prompts(12, seed=21)
    with DecodingPredictor(block_art, platform='cpu') as ref:
        assert ref.layout == 'block' and ref.mesh_tag is None
        want = [ref.generate(p, max_new_tokens=12) for p in prompts]
        want_beam = ref.generate(prompts[0], max_new_tokens=8, beam=3)
    with warnings.catch_warnings():
        warnings.simplefilter('ignore')
        with _patient(FleetRouter(block_art, replicas=2,
                                  platform='cpu')) as router:
            assert router.kind == 'decoding'
            futs = [router.submit(p, max_new_tokens=12)
                    for p in prompts]
            got = [f.result(300) for f in futs]
            assert got == want
            ids, scores = router.run(prompts[0], max_new_tokens=8,
                                     beam=3, timeout=300)
            np.testing.assert_array_equal(ids, want_beam[0])
            np.testing.assert_array_equal(scores, want_beam[1])
            st = router.status()
            for s in st['replicas'].values():
                assert s['layout'] == 'block'
                assert s['mesh'] is None
            # worker heartbeats surface the block-cache gauges
            # (serving_report's columns work fleet-wide)
            deadline = time.time() + 30
            while time.time() < deadline:
                stats = [s.get('stats', {})
                         for s in router.status()['replicas'].values()]
                if any('blocks_in_use' in x for x in stats):
                    break
                time.sleep(0.2)
            assert any('blocks_in_use' in x for x in stats)


def test_fleet_hung_replica_sigstop_watchdog(decode_art):
    """SIGSTOP (hung, not dead): no socket EOF — the heartbeat watchdog
    detects staleness in bounded time, SIGKILLs the replica, re-routes
    its queued work; the fleet keeps serving."""
    prompts = _prompts(8, seed=9)
    with DecodingPredictor(decode_art, platform='cpu') as ref:
        want = ref.generate(prompts[0], max_new_tokens=12)
    with warnings.catch_warnings():
        warnings.simplefilter('ignore')
        with FleetRouter(decode_art, replicas=2, platform='cpu',
                         hb_timeout_s=2.5, poll_s=0.1) as router:
            victim = router.serving_replicas()[0]
            os.kill(router._replicas[victim].proc.pid, signal.SIGSTOP)
            t0 = time.perf_counter()
            while router._replicas[victim].state != 'dead' \
                    and time.perf_counter() - t0 < 30:
                time.sleep(0.05)
            detect = time.perf_counter() - t0
            assert router._replicas[victim].state == 'dead'
            assert detect < 30, detect
            ev = [e for e in router.stats.events
                  if e['kind'] == 'replica_dead']
            assert ev and 'heartbeat stale' in ev[0]['reason']
            assert router.run(prompts[0], max_new_tokens=12,
                              timeout=300) == want


def test_fleet_scale_in_drains_zero_drops(decode_art):
    """scale_in: the victim finishes its in-flight streams, hands its
    queue back for re-routing; every submitted future resolves."""
    prompts = _prompts(24, seed=13)
    with warnings.catch_warnings():
        warnings.simplefilter('ignore')
        with _patient(FleetRouter(decode_art, replicas=2,
                                  platform='cpu',
                                  inflight_per_replica=3)) as router:
            futs = [router.submit(p, max_new_tokens=16)
                    for p in prompts]
            assert router.scale_in(timeout=300)
            results = [f.result(300) for f in futs]
            assert len(results) == len(prompts)
            snap = router.fleet_snapshot()
            assert snap['failed'] == 0 and snap['scale_in'] == 1
            assert len(router.serving_replicas()) == 1
            states = [r.state for r in router._replicas.values()]
            assert 'retired' in states


def test_autoscaler_decisions(dense_art):
    """Autoscaler.step() against synthetic router metrics: out on
    queue pressure, out on failover below min, in after a sustained
    idle streak, bounded by min/max, cooldown respected."""

    class FakeRouter(object):
        def __init__(self):
            self.n = 1
            self.queue = 0
            self.shed = 0
            self.events = []
            self._closed = False
            self.stats = fleet_mod.FleetStats()

        def status(self):
            reps = {i: {'state': 'serving', 'pending': self.queue
                        if i == 0 else 0, 'outstanding': 0,
                        'queue_depth': 0, 'occupancy': 0.5,
                        'shed': self.shed}
                    for i in range(self.n)}
            return {'replicas': reps, 'counters': {'shed': 0}}

        def scale_out(self, reason=None):
            self.n += 1
            self.events.append('out')

        def scale_in(self, reason=None):
            self.n -= 1
            self.events.append('in')

    r = FakeRouter()
    a = Autoscaler(r, min_replicas=1, max_replicas=3,
                   high_queue_per_replica=4.0, idle_steps=2,
                   cooldown_s=0.0)
    assert a.step() is None          # calm: no action
    r.queue = 10
    assert a.step() == 'out' and r.n == 2
    assert a.step() == 'out' and r.n == 3
    assert a.step() is None          # max_replicas bound
    r.queue = 0
    assert a.step() is None          # idle streak 1 < idle_steps
    assert a.step() == 'in' and r.n == 2
    a.cooldown_s = 3600.0
    assert a.step() is None          # cooldown gates further scale-in
    a.cooldown_s = 0.0
    r.n = 0
    assert a.step() == 'out'         # failover replacement below min
    r.queue = 1
    r.shed += 5
    a.step()
    assert a._idle_streak == 0       # sheds break the idle streak


def test_rolling_rollout_promote_and_loud_rollback(dense_art):
    """int8 canary promotes on top-1 parity over the calibration set at
    unchanged replica count; an injected parity failure (bit agreement
    across tiers) rolls back loudly and leaves the fleet untouched."""
    probes = [{'img': c[0]} for c in dense_art['calib']]
    with warnings.catch_warnings():
        warnings.simplefilter('ignore')
        with _patient(FleetRouter(dense_art['art'], replicas=2,
                                  platform='cpu')) as router:
            report = RollingRollout(
                router, tier='int8', probes=probes, agreement='top1',
                min_agreement=0.99, latency_budget=100.0).run()
            assert report['promoted'] and report['deterministic']
            assert report['agreement'] >= 0.99
            tiers = {rid: s['tier'] for rid, s in
                     router.fleet_snapshot()['replicas'].items()
                     if s['state'] == 'serving'}
            assert len(tiers) == 2 and set(tiers.values()) == {'int8'}
            assert router.stats.rollout['state'] == 'promoted'
            # injected failure: int8 logits can never bit-match bf16
            with pytest.raises(RolloutRolledBack, match='agreement'):
                RollingRollout(router, tier=None, probes=probes,
                               agreement='bit',
                               latency_budget=100.0).run()
            tiers2 = {rid: s['tier'] for rid, s in
                      router.fleet_snapshot()['replicas'].items()
                      if s['state'] == 'serving'}
            assert tiers2 == tiers, 'rollback must not touch the fleet'
            assert router.stats.rollout['state'] == 'rolled_back'
            # the fleet still serves after the rollback
            router.run(probes[0], timeout=120)


def test_serve_fleet_cli_decode_artifact(decode_art, tmp_path):
    """serve.py fleet on a DECODE artifact: prompts npz convention."""
    prompts = np.zeros((3, 4), np.int64)
    prompts[:, :2] = [[5, 7], [9, 3], [2, 8]]
    in_p = str(tmp_path / 'p.npz')
    np.savez(in_p, prompts=prompts, lens=np.array([2, 2, 2], np.int64))
    env = dict(os.environ, JAX_PLATFORMS='cpu', PTPU_PLATFORM='cpu')
    serve_py = os.path.join(REPO, 'paddle_tpu', 'inference', 'serve.py')
    r = subprocess.run(
        [sys.executable, serve_py, 'fleet', decode_art, in_p, '6', '2'],
        capture_output=True, text=True, env=env, timeout=300)
    assert r.returncode == 0, r.stderr
    line = json.loads(r.stdout.strip().splitlines()[-1])
    assert line['requests'] == 6 and line['failed'] == 0
    assert all(s['compiles'] == 0
               for s in line['per_replica'].values())


def test_fleet_submit_rejects_object_arrays(dense_fleet):
    """Object arrays need pickle, which the worker's np.load refuses:
    the request must fail at submit, not poison a replica's stream."""
    with pytest.raises(ValueError, match='object array'):
        dense_fleet.submit({'img': np.array([['a'], [None]],
                                            dtype=object)})


def test_fleet_bad_ctl_file_never_kills_watchdog(dense_fleet):
    """A malformed control file warns and is removed; the watchdog
    (the fleet's failure detector) keeps running."""
    ctl = os.path.join(dense_fleet.fleet_dir, 'ctl')
    bad = os.path.join(ctl, 'drain_x.json')
    with open(bad, 'w') as f:
        f.write('{"cmd": "drain", "replica": "abc"}')
    with open(os.path.join(ctl, 'noise.json'), 'w') as f:
        f.write('not json at all')
    deadline = time.monotonic() + 15
    with warnings.catch_warnings():
        warnings.simplefilter('ignore')
        while os.listdir(ctl) and time.monotonic() < deadline:
            time.sleep(0.1)
    assert os.listdir(ctl) == []
    assert dense_fleet._watchdog_t.is_alive()
    # and the fleet still serves
    dense_fleet.run({'img': _x(3)}, timeout=120)


def test_fleet_spawn_failure_fails_fast(dense_art, tmp_path):
    """A replica that crashes during spin-up (broken artifact) raises
    within the watchdog poll, not after the full spin-up timeout."""
    import shutil
    broken = str(tmp_path / 'broken')
    os.makedirs(broken)
    shutil.copy(os.path.join(dense_art['art'], 'signature.json'),
                broken)  # looks like an artifact; module is missing
    t0 = time.monotonic()
    with warnings.catch_warnings():
        warnings.simplefilter('ignore')
        with pytest.raises(RuntimeError, match='failed to start'):
            FleetRouter(broken, replicas=1, platform='cpu',
                        spinup_timeout_s=300.0).close()
    assert time.monotonic() - t0 < 60


def test_fleet_close_fails_pending_loudly(dense_art):
    with warnings.catch_warnings():
        warnings.simplefilter('ignore')
        router = _patient(FleetRouter(dense_art['art'], replicas=1,
                                      platform='cpu'))
        fut = router.submit({'img': _x(0)})
        router.close()
        with pytest.raises(Exception):
            fut.result(30)
        with pytest.raises(RuntimeError):
            router.submit({'img': _x(1)})
        # idempotent
        router.close()
