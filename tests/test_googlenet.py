"""GoogLeNet + SE-ResNeXt model families build and train (parity with the
reference's benchmark/paddle/image/googlenet.py and
benchmark/fluid/models/se_resnext.py; the committed Xeon numbers they
bench against live in bench.py / BASELINE.md)."""
import numpy as np

import paddle_tpu as fluid
from models.googlenet import build_train_net, googlenet


def test_googlenet_trains_one_batch():
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 5
    with fluid.program_guard(main, startup):
        # lr=0.01 + momentum 0.9 diverges on a 2-sample random batch
        # (loss 2.36 -> 7.83 -> 325.8); 1e-3 descends monotonically
        images, label, loss, acc = build_train_net(
            dshape=(3, 64, 64), class_dim=10, lr=0.001)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    r = np.random.RandomState(0)
    feed = {'data': r.randn(2, 3, 64, 64).astype(np.float32),
            'label': r.randint(0, 10, (2, 1)).astype(np.int64)}
    vals = []
    for _ in range(3):
        l, = exe.run(main, feed=feed, fetch_list=[loss])
        vals.append(float(np.asarray(l).reshape(-1)[0]))
    assert np.isfinite(vals).all(), vals
    assert vals[-1] < vals[0], vals


def test_googlenet_infer_deterministic():
    """is_train=False kills dropout: two runs agree bit-for-bit."""
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 3
    with fluid.program_guard(main, startup):
        images = fluid.layers.data(name='data', shape=[3, 64, 64],
                                   dtype='float32')
        logits = googlenet(images, class_dim=10, is_train=False)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    x = np.random.RandomState(1).randn(2, 3, 64, 64).astype(np.float32)
    a, = exe.run(main, feed={'data': x}, fetch_list=[logits])
    b, = exe.run(main, feed={'data': x}, fetch_list=[logits])
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert np.shape(a) == (2, 10)


def test_se_resnext_grouped_conv_shapes():
    """Cardinality-32 grouped 3x3s produce the documented stage shapes."""
    from models.se_resnext import se_resnext
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        images = fluid.layers.data(name='data', shape=[3, 64, 64],
                                   dtype='float32')
        logits = se_resnext(images, class_dim=7, depth=50, is_train=False)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    x = np.random.RandomState(2).randn(2, 3, 64, 64).astype(np.float32)
    out, = exe.run(main, feed={'data': x}, fetch_list=[logits])
    assert np.shape(out) == (2, 7)
    assert np.isfinite(np.asarray(out)).all()
