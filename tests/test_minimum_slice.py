"""Minimum end-to-end slice (SURVEY §7 phase 2): fc/softmax/cross_entropy/
mean/sgd + append_backward + Executor feed/fetch + save/load."""
import numpy as np
import pytest

import paddle_tpu as fluid


def test_forward_fc():
    x = fluid.layers.data(name='x', shape=[4], dtype='float32')
    y = fluid.layers.fc(input=x, size=3)
    assert y.shape == (-1, 3)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    out, = exe.run(feed={'x': np.ones((2, 4), np.float32)}, fetch_list=[y])
    assert out.shape == (2, 3)


def test_fit_a_line_converges():
    """Linear regression must drive loss to ~0 (ref tests/book/test_fit_a_line)."""
    np.random.seed(0)
    true_w = np.array([[2.0], [-3.4]], np.float32)
    true_b = 4.2

    x = fluid.layers.data(name='x', shape=[2], dtype='float32')
    y = fluid.layers.data(name='y', shape=[1], dtype='float32')
    y_pred = fluid.layers.fc(input=x, size=1)
    cost = fluid.layers.square_error_cost(input=y_pred, label=y)
    avg_cost = fluid.layers.mean(cost)

    opt = fluid.optimizer.SGD(learning_rate=0.5)
    opt.minimize(avg_cost)

    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())

    loss = None
    for i in range(300):
        xs = np.random.rand(16, 2).astype(np.float32)
        ys = xs @ true_w + true_b
        loss, = exe.run(feed={'x': xs, 'y': ys}, fetch_list=[avg_cost])
    assert loss[()] < 1e-3, "final loss %r" % loss


def test_mnist_mlp_learns():
    """Softmax classifier on a toy separable problem (ref
    test_recognize_digits mlp)."""
    np.random.seed(1)
    img = fluid.layers.data(name='img', shape=[8], dtype='float32')
    label = fluid.layers.data(name='label', shape=[1], dtype='int64')
    h = fluid.layers.fc(input=img, size=32, act='relu')
    logits = fluid.layers.fc(input=h, size=4)
    probs = fluid.layers.softmax(logits)
    loss = fluid.layers.mean(
        fluid.layers.cross_entropy(input=probs, label=label))
    acc = fluid.layers.accuracy(input=probs, label=label)
    fluid.optimizer.Adam(learning_rate=1e-2).minimize(loss)

    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())

    centers = np.random.randn(4, 8).astype(np.float32) * 3
    acc_v = None
    for i in range(150):
        lab = np.random.randint(0, 4, size=(32, 1))
        xs = centers[lab[:, 0]] + 0.1 * np.random.randn(32, 8).astype(np.float32)
        loss_v, acc_v = exe.run(feed={'img': xs.astype(np.float32),
                                      'label': lab.astype(np.int64)},
                                fetch_list=[loss, acc])
    assert acc_v[()] > 0.95, "final acc %r" % acc_v


def test_save_load_roundtrip(tmp_path):
    x = fluid.layers.data(name='x', shape=[4], dtype='float32')
    y = fluid.layers.fc(input=x, size=3)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    xs = np.random.rand(2, 4).astype(np.float32)
    out1, = exe.run(feed={'x': xs}, fetch_list=[y])
    fluid.save_persistables(exe, str(tmp_path / 'ckpt'))

    # clobber params, reload, verify identical output
    scope = fluid.global_scope()
    import jax.numpy as jnp
    for p in fluid.default_main_program().all_parameters():
        scope.set(p.name, jnp.zeros_like(scope.get(p.name)))
    out_zero, = exe.run(feed={'x': xs}, fetch_list=[y])
    assert not np.allclose(out1, out_zero)
    fluid.load_persistables(exe, str(tmp_path / 'ckpt'))
    out2, = exe.run(feed={'x': xs}, fetch_list=[y])
    np.testing.assert_allclose(out1, out2, rtol=1e-6)


def test_save_load_inference_model(tmp_path):
    x = fluid.layers.data(name='x', shape=[4], dtype='float32')
    h = fluid.layers.fc(input=x, size=8, act='relu')
    y = fluid.layers.fc(input=h, size=3, act='softmax')
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    xs = np.random.rand(2, 4).astype(np.float32)
    ref, = exe.run(feed={'x': xs}, fetch_list=[y])

    fluid.save_inference_model(str(tmp_path / 'model'), ['x'], [y], exe)

    scope2 = fluid.core.Scope()
    with fluid.scope_guard(scope2):
        exe2 = fluid.Executor(fluid.CPUPlace())
        prog, feeds, fetches = fluid.load_inference_model(
            str(tmp_path / 'model'), exe2)
        out, = exe2.run(program=prog, feed={feeds[0]: xs},
                        fetch_list=fetches)
    np.testing.assert_allclose(ref, out, rtol=1e-5)
