"""Traced-LoD mode: the compiled program must be lod-GENERIC.

The r2 verdict's recompile-bomb directive: two batches with different LoD
but the same bucket shape must hit the SAME executor cache entry (the
reference achieves this with lod-generic kernels,
operators/math/sequence2batch.h; we achieve it by making offsets device
data — core/lod.py traced mode).
"""
import numpy as np
import pytest

import paddle_tpu as fluid


def _mk(data_rows, lens, feat=4, bucket_rows=12):
    rng = np.random.RandomState(sum(lens))
    data = rng.randn(data_rows, feat).astype(np.float32)
    return fluid.create_lod_tensor(data, [lens], traced=True,
                                   bucket_rows=bucket_rows), data


def _np_pool_avg(data, lens):
    out, s = [], 0
    for l in lens:
        out.append(data[s:s + l].mean(0))
        s += l
    return np.stack(out)


def test_same_bucket_hits_one_compile():
    x = fluid.layers.data(name='x', shape=[4], dtype='float32', lod_level=1)
    s1 = fluid.layers.data(name='s1', shape=[1], dtype='float32',
                           lod_level=1)
    pooled = fluid.layers.sequence_pool(x, 'average')
    sm = fluid.layers.sequence_softmax(s1)  # reference contract: width 1
    rev = fluid.layers.sequence_reverse(x)
    exe = fluid.Executor(fluid.CPUPlace())

    # batch A: lens [3, 5, 2] (10 rows); batch B: lens [4, 1, 5] (10 rows)
    # same bucket: 12 padded rows, 3 sequences
    la, da = _mk(10, [3, 5, 2])
    lb, db = _mk(10, [4, 1, 5])
    sa1, _ = _mk(10, [3, 5, 2], feat=1)
    sb1, _ = _mk(10, [4, 1, 5], feat=1)

    pa, sa, ra = exe.run(feed={'x': la, 's1': sa1},
                         fetch_list=[pooled, sm, rev])
    n_entries = len(exe._cache)
    pb, sb, rb = exe.run(feed={'x': lb, 's1': sb1},
                         fetch_list=[pooled, sm, rev])
    # THE test: different lod values, same bucket -> no new compile
    assert len(exe._cache) == n_entries == 1

    np.testing.assert_allclose(pa, _np_pool_avg(da, [3, 5, 2]), rtol=1e-5)
    np.testing.assert_allclose(pb, _np_pool_avg(db, [4, 1, 5]), rtol=1e-5)
    # reverse correctness on batch B
    np.testing.assert_allclose(rb[:4], db[:4][::-1], rtol=1e-6)
    np.testing.assert_allclose(rb[5:10], db[5:10][::-1], rtol=1e-6)
    # softmax sums to 1 per sequence (first sequence of batch B: 4 rows)
    assert np.isclose(np.asarray(sb)[:4].sum(), 1.0, atol=1e-5)


def test_traced_static_value_parity():
    """Every mode-generic op must produce identical values in both modes."""
    x = fluid.layers.data(name='x', shape=[4], dtype='float32', lod_level=1)
    outs = [fluid.layers.sequence_pool(x, 'sum'),
            fluid.layers.sequence_pool(x, 'max'),
            fluid.layers.sequence_pool(x, 'last'),
            fluid.layers.sequence_pool(x, 'first'),
            fluid.layers.sequence_softmax(x),
            fluid.layers.sequence_reverse(x)]
    exe = fluid.Executor(fluid.CPUPlace())
    lens = [2, 4, 3]
    rng = np.random.RandomState(0)
    data = rng.randn(9, 4).astype(np.float32)
    static = fluid.create_lod_tensor(data, [lens])
    traced = fluid.create_lod_tensor(data, [lens], traced=True)
    rs = exe.run(feed={'x': static}, fetch_list=outs)
    rt = exe.run(feed={'x': traced}, fetch_list=outs)
    for a, b in zip(rs, rt):
        np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-6)


def test_traced_windowed_and_expand_as():
    x = fluid.layers.data(name='x', shape=[4], dtype='float32', lod_level=1)
    y = fluid.layers.data(name='yv', shape=[4], dtype='float32', lod_level=1)
    conv = fluid.layers.sequence_conv(x, num_filters=6, filter_size=3,
                                      bias_attr=False)
    exp = fluid.layers.sequence_expand_as(
        fluid.layers.sequence_pool(x, 'sum'), y)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    lens = [3, 2, 4]
    rng = np.random.RandomState(1)
    data = rng.randn(9, 4).astype(np.float32)
    static = fluid.create_lod_tensor(data, [lens])
    traced = fluid.create_lod_tensor(data, [lens], traced=True)
    cs, es = exe.run(feed={'x': static, 'yv': static},
                     fetch_list=[conv, exp])
    ct, et = exe.run(feed={'x': traced, 'yv': traced},
                     fetch_list=[conv, exp])
    np.testing.assert_allclose(cs, ct, rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(es, et, rtol=1e-5, atol=1e-6)


def test_traced_grads_flow():
    """Training through traced-lod sequence ops converges like static."""
    def run(traced):
        main_p, startup_p = fluid.Program(), fluid.Program()
        main_p.random_seed = startup_p.random_seed = 9
        with fluid.program_guard(main_p, startup_p):
            x = fluid.layers.data(name='x', shape=[8], dtype='float32',
                                  lod_level=1)
            yv = fluid.layers.data(name='yv', shape=[1], dtype='float32')
            h = fluid.layers.fc(x, size=16, act='relu')
            pooled = fluid.layers.sequence_pool(h, 'average')
            pred = fluid.layers.fc(pooled, size=1)
            loss = fluid.layers.mean(
                fluid.layers.square_error_cost(pred, yv))
            fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
        exe = fluid.Executor(fluid.CPUPlace())
        scope = fluid.core.Scope()
        rng = np.random.RandomState(4)
        data = rng.randn(9, 8).astype(np.float32)
        tgt = rng.randn(3, 1).astype(np.float32)
        feed_x = fluid.create_lod_tensor(data, [[2, 4, 3]], traced=traced)
        losses = []
        with fluid.scope_guard(scope):
            exe.run(startup_p)
            for _ in range(8):
                l, = exe.run(main_p, feed={'x': feed_x, 'yv': tgt},
                             fetch_list=[loss])
                losses.append(float(l[0]))
        return losses

    ls = run(False)
    lt = run(True)
    np.testing.assert_allclose(ls, lt, rtol=1e-4, atol=1e-5)
    assert lt[-1] < lt[0] * 0.5


def test_traced_content_dependent_op_raises():
    from paddle_tpu.core.lod import TracedLoDError
    x = fluid.layers.data(name='x', shape=[2], dtype='float32', lod_level=1)
    y = fluid.layers.data(name='yv', shape=[2], dtype='float32', lod_level=1)
    out = fluid.layers.sequence_expand(x, y)
    exe = fluid.Executor(fluid.CPUPlace())
    xt = fluid.create_lod_tensor(np.ones((4, 2), np.float32), [[2, 2]],
                                 traced=True)
    yt = fluid.create_lod_tensor(np.ones((6, 2), np.float32), [[2, 4]],
                                 traced=True)
    with pytest.raises(TracedLoDError):
        exe.run(feed={'x': xt, 'yv': yt}, fetch_list=[out])
