"""Native data layer: RecordIO codec, MultiSlot parsing, AsyncExecutor
ingest, open_files / random_data_generator / Preprocessor readers.

RecordIO byte layout per the reference (recordio/header.cc:40-55,
chunk.cc:79-118): both the native C++ codec and the pure-Python fallback
must produce interchangeable files.
"""
import os

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import recordio


def test_recordio_roundtrip_native_and_python(tmp_path):
    recs = [b'hello', b'', b'x' * 3000, 'unicode é'.encode()]
    p = str(tmp_path / 'a.recordio')
    recordio.write_recordio(p, recs)
    assert recordio.read_recordio(p) == recs
    # gzip-compressed chunks
    p2 = str(tmp_path / 'b.recordio')
    recordio.write_recordio(p2, recs, compressor=2)
    assert recordio.read_recordio(p2) == recs

    # cross-engine: native writer -> python reader (and the reverse)
    if recordio._native() is not None:
        w = recordio.Writer.__new__(recordio.Writer)
        w._native = None
        w._compressor = 0
        w._f = open(str(tmp_path / 'c.recordio'), 'wb')
        w._records = []
        w._pending = 0
        w._max = 1 << 20
        for r in recs:
            w.append(r)
        w.close()
        assert recordio.read_recordio(str(tmp_path / 'c.recordio')) == recs

    # chunk boundaries: small max_chunk_bytes forces several chunks
    p3 = str(tmp_path / 'd.recordio')
    with recordio.Writer(p3, max_chunk_bytes=16) as w:
        for i in range(20):
            w.append(b'rec%02d' % i)
    assert recordio.read_recordio(p3) == [b'rec%02d' % i for i in range(20)]


def _force_python_codec(monkeypatch):
    """Route recordio through the pure-Python fallback regardless of the
    built .so (both engines must agree on every behavior)."""
    monkeypatch.setattr(recordio, '_lib', None)
    monkeypatch.setattr(recordio, '_lib_tried', True)


def test_recordio_chunk_index_and_read_chunk(tmp_path):
    """The seek table for sharded dispatch: header-only index, chunk
    random access, and agreement with the sequential scan — for plain
    and gzip chunks."""
    recs = [b'r%03d' % i + b'y' * 40 for i in range(60)]
    for comp in (0, 2):
        p = str(tmp_path / ('idx%d.recordio' % comp))
        recordio.write_recordio(p, recs, compressor=comp,
                                max_chunk_bytes=200)
        idx = recordio.chunk_index(p)
        assert len(idx) > 3
        assert sum(c.num_records for c in idx) == 60
        assert idx[0].offset == 0
        assert all(b.offset == a.offset + 20 + a.size
                   for a, b in zip(idx, idx[1:]))
        got = []
        for c in idx:
            chunk = recordio.read_chunk(p, c.offset)
            assert len(chunk) == c.num_records
            got.extend(chunk)
        assert got == recs
        assert recordio.is_recordio(p)
    assert not recordio.is_recordio(str(tmp_path / 'missing'))


@pytest.mark.parametrize('engine', ['native', 'python'])
def test_recordio_torn_tail_is_loud(tmp_path, monkeypatch, engine):
    """A writer that died mid-chunk leaves a torn tail. Reading it must
    ERROR (IOError mentioning the torn tail), never silently truncate —
    in the scanner, the chunk index, and the random-access chunk read;
    the complete leading chunks stay readable."""
    if engine == 'native':
        if recordio._native() is None:
            pytest.skip('native codec not built')
    else:
        _force_python_codec(monkeypatch)
    recs = [b'rec%02d' % i + b'z' * 30 for i in range(20)]
    p = str(tmp_path / 'whole.recordio')
    recordio.write_recordio(p, recs, max_chunk_bytes=120)
    with open(p, 'rb') as f:
        data = f.read()
    n_chunks = len(recordio.chunk_index(p))
    assert n_chunks > 2

    # torn payload: cut inside the last chunk's payload
    p_torn = str(tmp_path / 'torn.recordio')
    with open(p_torn, 'wb') as f:
        f.write(data[:-9])
    for fn in (lambda: recordio.read_recordio(p_torn),
               lambda: recordio.chunk_index(p_torn)):
        with pytest.raises(IOError, match='torn'):
            fn()
    # ... but every COMPLETE chunk before the tear still reads
    idx = recordio.chunk_index(p)
    assert recordio.read_chunk(p_torn, idx[0].offset) \
        == recordio.read_chunk(p, idx[0].offset)
    with pytest.raises(IOError, match='torn'):
        recordio.read_chunk(p_torn, idx[-1].offset)

    # torn header: a partial 20-byte header at EOF
    p_hdr = str(tmp_path / 'tornhdr.recordio')
    with open(p_hdr, 'wb') as f:
        f.write(data + b'\x04\x03\x02\x01\x07')
    with pytest.raises(IOError, match='torn'):
        recordio.read_recordio(p_hdr)
    with pytest.raises(IOError, match='torn'):
        recordio.chunk_index(p_hdr)

    # a clean file still ends with StopIteration, not an error
    assert recordio.read_recordio(p) == recs


def test_multislot_parse_native_matches_python():
    from paddle_tpu.async_executor import parse_multislot_lines
    slots = [{'name': 's0', 'type': 'uint64', 'is_dense': False,
              'is_used': True},
             {'name': 's1', 'type': 'float', 'is_dense': True,
              'is_used': True}]
    text = "2 11 12 1 0.5\n1 13 1 1.5\n3 1 2 3 1 2.5\n"
    parsed, lines = parse_multislot_lines(text, slots)
    assert lines == 3
    np.testing.assert_array_equal(parsed[0][0], [11, 12, 13, 1, 2, 3])
    np.testing.assert_array_equal(parsed[0][1], [2, 1, 3])
    np.testing.assert_allclose(parsed[1][0], [0.5, 1.5, 2.5])
    np.testing.assert_array_equal(parsed[1][1], [1, 1, 1])


def test_async_executor_trains_from_files(tmp_path):
    """The CTR capability: MultiSlot text files -> threaded ingest ->
    train steps (ref async_executor.cc RunFromFile)."""
    rng = np.random.RandomState(0)
    files = []
    for fi in range(3):
        path = str(tmp_path / ('part-%d.txt' % fi))
        with open(path, 'w') as f:
            for _ in range(32):
                ids = rng.randint(0, 50, 3)
                label = float(rng.randint(0, 2))
                f.write('3 %d %d %d 1 %.1f\n' % (*ids, label))
        files.append(path)

    desc = fluid.DataFeedDesc("""
        name: "MultiSlotDataFeed"
        batch_size: 8
        multi_slot_desc {
          slots {
            name: "ids"
            type: "uint64"
            is_dense: false
            is_used: true
          }
          slots {
            name: "click"
            type: "float"
            is_dense: true
            is_used: true
          }
        }
    """)
    assert desc.batch_size == 8
    assert [s['name'] for s in desc.slots] == ['ids', 'click']

    main_p, startup_p = fluid.Program(), fluid.Program()
    main_p.random_seed = startup_p.random_seed = 3
    with fluid.program_guard(main_p, startup_p):
        ids = fluid.layers.data(name='ids', shape=[1], dtype='int64',
                                lod_level=1)
        click = fluid.layers.data(name='click', shape=[1], dtype='float32')
        emb = fluid.layers.embedding(ids, size=[50, 8], is_sparse=True)
        pooled = fluid.layers.sequence_pool(emb, 'sum')
        logit = fluid.layers.fc(pooled, size=1)
        loss = fluid.layers.mean(
            fluid.layers.sigmoid_cross_entropy_with_logits(logit, click))
        fluid.optimizer.Adam(1e-2, lazy_mode=True).minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.core.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup_p)
        ae = fluid.AsyncExecutor(fluid.CPUPlace())
        results = ae.run(main_p, desc, files, thread_num=2,
                         fetch=[loss], scope=scope)
    assert len(results) == 12  # 96 lines / batch 8
    losses = [float(r[0].reshape(-1)[0]) for r in results]
    assert np.isfinite(losses).all()


def test_open_files_reader_roundtrip(tmp_path):
    """Write LoDTensor records with the reference framing, read them back
    through layers.open_files into a train fetch."""
    import io as _io
    from paddle_tpu.inference.ref_format import write_tensor_stream
    path = str(tmp_path / 'data.recordio')
    rng = np.random.RandomState(1)
    batches = [(rng.randn(4, 3).astype(np.float32),
                rng.randint(0, 5, (4, 1)).astype(np.int64))
               for _ in range(3)]
    with recordio.Writer(path) as w:
        for x, y in batches:
            buf = _io.BytesIO()
            write_tensor_stream(buf, x)
            write_tensor_stream(buf, y)
            w.append(buf.getvalue())

    main_p, startup_p = fluid.Program(), fluid.Program()
    with fluid.program_guard(main_p, startup_p):
        reader = fluid.layers.open_files(
            filenames=[path], shapes=[[-1, 3], [-1, 1]],
            lod_levels=[0, 0], dtypes=['float32', 'int64'])
        x, y = reader.read()
        s = fluid.layers.reduce_sum(x)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.core.Scope()
    got = []
    with fluid.scope_guard(scope):
        reader.start()
        try:
            while True:
                v, = exe.run(main_p, fetch_list=[s])
                got.append(float(np.asarray(v).reshape(-1)[0]))
        except fluid.core.EOFException:
            reader.reset()
    want = [float(b[0].sum()) for b in batches]
    np.testing.assert_allclose(sorted(got), sorted(want), rtol=1e-4)


def test_random_data_generator_and_preprocessor():
    main_p, startup_p = fluid.Program(), fluid.Program()
    with fluid.program_guard(main_p, startup_p):
        reader = fluid.layers.random_data_generator(
            low=0.0, high=1.0, shapes=[[8, 4]])
        p = fluid.layers.Preprocessor(reader)

        @p.transform
        def _shift(x):
            return x + 10.0

        (x,) = reader.read()
        m = fluid.layers.reduce_mean(x)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.core.Scope()
    with fluid.scope_guard(scope):
        reader.start()
        v, = exe.run(main_p, fetch_list=[m])
        reader.reset()
    # uniform [0,1] shifted by +10 -> mean ~ 10.5
    assert 10.0 < float(np.asarray(v).reshape(-1)[0]) < 11.0


def test_multislot_uint64_precision():
    from paddle_tpu.async_executor import parse_multislot_lines
    slots = [{'name': 's0', 'type': 'uint64', 'is_dense': False,
              'is_used': True}]
    big = 9007199254740993  # 2^53 + 1: not representable in double
    parsed, lines = parse_multislot_lines("1 %d\n" % big, slots)
    assert lines == 1
    assert int(parsed[0][0][0]) == big


def test_py_func_forward_and_backward():
    main_p, startup_p = fluid.Program(), fluid.Program()
    with fluid.program_guard(main_p, startup_p):
        x = fluid.layers.data(name='x', shape=[3], dtype='float32')
        x.stop_gradient = False
        out = main_p.global_block().create_var(
            name='pyout', shape=[2, 3], dtype='float32',
            stop_gradient=False)
        # backward receives (inputs + outputs + out grads) per reference
        fluid.layers.py_func(func=lambda a: a * 3.0, x=x, out=out,
                             backward_func=lambda a, o, g: g * 3.0)
        loss = fluid.layers.mean(out)
        grads = fluid.append_backward(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    xs = np.ones((2, 3), np.float32)
    outs = exe.run(main_p, feed={'x': xs},
                   fetch_list=[out, 'x@GRAD'])
    np.testing.assert_allclose(outs[0], xs * 3.0, rtol=1e-6)
    np.testing.assert_allclose(outs[1], np.full((2, 3), 0.5, np.float32),
                               rtol=1e-6)
