"""Fault-tolerant training (ISSUE 6): async crash-consistent
checkpointing (core/checkpoint.py), kill-and-resume elastic restart, and
the fault-injection harness (testing/faults.py).

The headline contract: a trainer SIGKILLed at a random step boundary and
restarted on the same checkpoint dir reproduces the uninterrupted run's
losses and final params BIT-EXACTLY — and no partial or corrupt
checkpoint is ever loaded silently.
"""
import json
import os
import signal
import subprocess
import sys
import time
import warnings

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import unique_name
from paddle_tpu.core.checkpoint import (CheckpointManager, latest_committed,
                                        list_checkpoints, verify_checkpoint)
from paddle_tpu.parallel import MultiStepTrainer
from paddle_tpu.testing import faults

_WORKER = os.path.join(os.path.dirname(__file__),
                       'checkpoint_kill_worker.py')


def _build_net(seed=17):
    with unique_name.guard():
        main_p, startup_p = fluid.Program(), fluid.Program()
        main_p.random_seed = startup_p.random_seed = seed
        with fluid.program_guard(main_p, startup_p):
            x = fluid.layers.data(name='x', shape=[16], dtype='float32')
            lab = fluid.layers.data(name='lab', shape=[1], dtype='int64')
            h = fluid.layers.fc(x, size=32, act='relu')
            h = fluid.layers.dropout(h, dropout_prob=0.3)
            logits = fluid.layers.fc(h, size=5)
            loss = fluid.layers.mean(
                fluid.layers.softmax_with_cross_entropy(logits=logits,
                                                        label=lab))
            fluid.optimizer.Momentum(learning_rate=0.1,
                                     momentum=0.9).minimize(loss)
    return main_p, startup_p, loss


def _feed_for(step0, k, batch=8):
    xs, labs = [], []
    for s in range(step0, step0 + k):
        r = np.random.RandomState(1000 + s)
        xs.append(r.randn(batch, 16).astype(np.float32))
        labs.append(r.randint(0, 5, (batch, 1)))
    return {'x': np.stack(xs), 'lab': np.stack(labs)}


def _state(program, scope):
    return {v.name: np.asarray(scope.get(v.name)).copy()
            for v in program.list_vars()
            if v.persistable and scope.get(v.name) is not None}


def _startup_and_save(tmp_path, steps=(1, 2, 3), **mgr_kw):
    """Build + init a net, save one blocking checkpoint per step value.
    Returns (dir, program, scope, manager stats)."""
    d = str(tmp_path / 'ckpts')
    main_p, startup_p, _loss = _build_net()
    scope = fluid.core.Scope()
    with fluid.scope_guard(scope):
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup_p)
        with CheckpointManager(d, **mgr_kw) as mgr:
            for s in steps:
                mgr.save(main_p, scope, s, blocking=True)
            stats = dict(mgr.stats)
    return d, main_p, scope, stats


# ---------------------------------------------------------------------------
# CheckpointManager mechanics
# ---------------------------------------------------------------------------
def test_save_restore_roundtrip(tmp_path):
    d, main_p, scope, stats = _startup_and_save(tmp_path, steps=(5,))
    assert stats['commits'] == 1 and stats['failed'] == 0
    want = _state(main_p, scope)

    scope2 = fluid.core.Scope()
    exe2 = fluid.Executor(fluid.CPUPlace())
    with fluid.scope_guard(scope2):
        mgr = CheckpointManager(d)
        info = mgr.restore(executor=exe2, program=main_p, scope=scope2)
        mgr.close()
    assert info is not None and info['step'] == 5
    got = _state(main_p, scope2)
    assert set(got) == set(want)
    for n in want:
        np.testing.assert_array_equal(want[n], got[n], err_msg=n)
    # the executor step counter is restored: the per-step rng stream (and
    # therefore every loss after resume) continues bit-exactly
    assert exe2._step_counters[main_p._uid] == 5


def test_restore_on_empty_dir_returns_none(tmp_path):
    mgr = CheckpointManager(str(tmp_path / 'none'))
    assert mgr.restore() is None
    mgr.close()


def test_corrupt_shard_skipped_with_warning(tmp_path):
    d, _p, _s, _ = _startup_and_save(tmp_path, steps=(1, 2))
    faults.corrupt_checkpoint(os.path.join(d, 'ckpt-2'), what='shard')
    with pytest.warns(RuntimeWarning, match='not loadable'):
        got = latest_committed(d)
    assert got is not None and got[0] == 1  # falls back, never loads bad


def test_truncated_shard_skipped(tmp_path):
    d, _p, _s, _ = _startup_and_save(tmp_path, steps=(1, 2))
    faults.corrupt_checkpoint(os.path.join(d, 'ckpt-2'), what='shard',
                              mode='truncate')
    with pytest.warns(RuntimeWarning, match='truncated|mismatch'):
        assert latest_committed(d)[0] == 1


def test_corrupt_manifest_skipped(tmp_path):
    d, _p, _s, _ = _startup_and_save(tmp_path, steps=(1, 2))
    faults.corrupt_checkpoint(os.path.join(d, 'ckpt-2'), what='manifest',
                              mode='truncate')
    with pytest.warns(RuntimeWarning, match='not loadable'):
        assert latest_committed(d)[0] == 1


def test_partial_checkpoint_without_commit_skipped(tmp_path):
    d, _p, _s, _ = _startup_and_save(tmp_path, steps=(1,))
    faults.corrupt_checkpoint(os.path.join(d, 'ckpt-1'), what='commit')
    with pytest.warns(RuntimeWarning, match='no COMMIT'):
        assert latest_committed(d) is None


def test_retention_keeps_last_n_and_journals_evictions(tmp_path):
    d, _p, _s, stats = _startup_and_save(tmp_path, steps=(1, 2, 3, 4, 5),
                                         keep_last_n=2)
    assert [s for s, _ in list_checkpoints(d)] == [4, 5]
    assert stats['evicted'] == 3
    events = [json.loads(l) for l in
              open(os.path.join(d, 'COMMITS.jsonl'))]
    assert [e['step'] for e in events if e['event'] == 'commit'] == \
        [1, 2, 3, 4, 5]
    assert [e['step'] for e in events if e['event'] == 'evict'] == [1, 2, 3]
    verify_checkpoint(os.path.join(d, 'ckpt-5'))  # survivors stay whole


def test_enospc_writer_retries_then_commits(tmp_path):
    d = str(tmp_path / 'ckpts')
    main_p, startup_p, _ = _build_net()
    scope = fluid.core.Scope()
    with fluid.scope_guard(scope):
        fluid.Executor(fluid.CPUPlace()).run(startup_p)
        with CheckpointManager(d, retry_backoff_s=0.01) as mgr:
            with faults.inject_write_errors(code='ENOSPC', fail_next=2) as inj:
                with pytest.warns(RuntimeWarning, match='retrying'):
                    mgr.save(main_p, scope, 1, blocking=True)
            assert inj.injected == 2
            assert mgr.stats['commits'] == 1 and mgr.stats['retries'] == 2
    assert latest_committed(d)[0] == 1


def test_persistent_eio_degrades_without_crashing_the_step_loop(tmp_path):
    """Every write fails: checkpoints are abandoned with loud warnings,
    but run_steps keeps training and its losses are untouched."""
    main_p, startup_p, loss = _build_net()
    scope = fluid.core.Scope()
    exe = fluid.Executor(fluid.CPUPlace())
    ref = []
    with fluid.scope_guard(scope):
        exe.run(startup_p)
        for dsp in range(2):
            l, = exe.run_steps(main_p, feed=_feed_for(dsp * 4, 4),
                               fetch_list=[loss], steps=4,
                               fetch_policy='stack')
            ref += list(np.asarray(l).reshape(-1))

    main_p, startup_p, loss = _build_net()
    scope = fluid.core.Scope()
    exe = fluid.Executor(fluid.CPUPlace())
    d = str(tmp_path / 'ckpts')
    got = []
    with fluid.scope_guard(scope):
        exe.run(startup_p)
        with CheckpointManager(d, every_steps=4, max_retries=1,
                               retry_backoff_s=0.01) as mgr:
            with faults.inject_write_errors(code='EIO', fail_next=10 ** 6):
                with warnings.catch_warnings(record=True) as w:
                    warnings.simplefilter('always')
                    for dsp in range(2):
                        l, = exe.run_steps(main_p, feed=_feed_for(dsp * 4, 4),
                                           fetch_list=[loss], steps=4,
                                           fetch_policy='stack',
                                           checkpoint=mgr)
                        got += list(np.asarray(l).reshape(-1))
                    mgr.flush()
            assert mgr.stats['failed'] >= 1 and mgr.stats['commits'] == 0
            assert 'Input/output error' in (mgr.stats['last_error'] or '')
    assert any('ABANDONED' in str(x.message) for x in w)
    np.testing.assert_array_equal(np.asarray(ref), np.asarray(got))
    assert latest_committed(d) is None  # nothing half-written became live


def test_every_steps_policy_and_busy_skip_accounting(tmp_path):
    main_p, startup_p, loss = _build_net()
    scope = fluid.core.Scope()
    exe = fluid.Executor(fluid.CPUPlace())
    d = str(tmp_path / 'ckpts')
    with fluid.scope_guard(scope):
        exe.run(startup_p)
        with warnings.catch_warnings():
            warnings.simplefilter('ignore', RuntimeWarning)
            with CheckpointManager(d, every_steps=8) as mgr:
                for dsp in range(4):
                    exe.run_steps(main_p, feed=_feed_for(dsp * 4, 4),
                                  fetch_list=[loss], steps=4,
                                  checkpoint=mgr)
                mgr.flush()
                st = dict(mgr.stats)
    # boundaries at 8 and 16 are due; a busy writer may skip one, but
    # every due boundary is either committed or accounted as skipped
    assert st['snapshots'] + st['skipped_busy'] == 2
    assert st['commits'] == st['snapshots']
    assert latest_committed(d) is not None


def test_every_seconds_policy(tmp_path):
    main_p, startup_p, _ = _build_net()
    scope = fluid.core.Scope()
    exe = fluid.Executor(fluid.CPUPlace())
    d = str(tmp_path / 'ckpts')
    with fluid.scope_guard(scope):
        exe.run(startup_p)
        with CheckpointManager(d, every_seconds=0.05) as mgr:
            assert mgr.step_boundary(exe, main_p, scope, 1) == 0.0  # not due
            time.sleep(0.06)
            assert mgr.step_boundary(exe, main_p, scope, 2) > 0.0
            mgr.flush()
            assert mgr.stats['commits'] == 1


def test_ckpt_stall_reported_in_training_report(tmp_path):
    from paddle_tpu import profiler
    main_p, startup_p, loss = _build_net()
    scope = fluid.core.Scope()
    exe = fluid.Executor(fluid.CPUPlace())
    with fluid.scope_guard(scope):
        exe.run(startup_p)
        with CheckpointManager(str(tmp_path / 'c'), every_steps=4) as mgr:
            for dsp in range(2):
                exe.run_steps(main_p, feed=_feed_for(dsp * 4, 4),
                              fetch_list=[loss], steps=4, checkpoint=mgr)
            mgr.flush()
    try:
        snap = profiler.training_report()['executor@%x' % id(exe)]
        assert snap['ckpt_stall_ms'] > 0.0
        assert 0.0 < snap['ckpt_stall_pct'] < 100.0
    finally:
        exe.close()


def test_stale_tmp_dirs_from_dead_writers_are_cleaned(tmp_path):
    d = str(tmp_path / 'ckpts')
    os.makedirs(os.path.join(d, '.tmp-ckpt-3.999999'))  # dead pid
    live = os.path.join(d, '.tmp-ckpt-4.%d' % os.getpid())
    os.makedirs(live)
    mgr = CheckpointManager(d)
    mgr.close()
    assert not os.path.exists(os.path.join(d, '.tmp-ckpt-3.999999'))
    assert os.path.exists(live)  # owning pid alive: not ours to delete


# ---------------------------------------------------------------------------
# io.py manifest satellite: partial/stale save dirs fail loudly at load
# ---------------------------------------------------------------------------
def _save_dir(tmp_path):
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data(name='x', shape=[4], dtype='float32')
        fluid.layers.fc(x, size=3)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    d = str(tmp_path / 'save')
    fluid.io.save_persistables(exe, d, main)
    return d, main, exe


def test_io_manifest_written_and_roundtrips(tmp_path):
    d, main, exe = _save_dir(tmp_path)
    assert os.path.exists(os.path.join(d, '.ptpu_manifest.json'))
    fluid.io.load_persistables(exe, d, main)  # verifies digests


def test_io_load_rejects_truncated_file(tmp_path):
    d, main, exe = _save_dir(tmp_path)
    faults.corrupt_file(os.path.join(d, 'fc_0.w_0'), mode='truncate')
    with pytest.raises(RuntimeError, match='partial or corrupt'):
        fluid.io.load_persistables(exe, d, main)


def test_io_load_rejects_stale_mixed_save(tmp_path):
    """A file whose bytes differ from the manifest (an interrupted later
    save overwrote it) must fail loudly, not load stale params."""
    d, main, exe = _save_dir(tmp_path)
    faults.corrupt_file(os.path.join(d, 'fc_0.w_0'), mode='flip', offset=-1)
    with pytest.raises(RuntimeError, match='manifest'):
        fluid.io.load_persistables(exe, d, main)


def test_io_load_without_manifest_stays_compatible(tmp_path):
    d, main, exe = _save_dir(tmp_path)
    os.remove(os.path.join(d, '.ptpu_manifest.json'))
    fluid.io.load_persistables(exe, d, main)  # pre-manifest dirs still load


# ---------------------------------------------------------------------------
# the headline: SIGKILL at a step boundary + restart = bit-exact resume
# ---------------------------------------------------------------------------
def _read_out(path):
    resume, losses, sha = None, {}, None
    for line in open(path):
        parts = line.split()
        if parts[0] == 'RESUME':
            resume = int(parts[1])
        elif parts[0] == 'DONE':
            sha = parts[1]
        else:
            losses[int(parts[0])] = float(parts[1])
    return resume, losses, sha


def _run_worker(ckpt_dir, out, total=24, k=4, every=4, kill_at=0,
                min_commits=1, check=True):
    argv = [sys.executable, _WORKER, ckpt_dir, out, str(total), str(k),
            str(every)]
    if kill_at:
        argv += [str(kill_at), str(min_commits)]
    r = subprocess.run(argv, capture_output=True, text=True, timeout=300)
    if check:
        assert r.returncode == 0, r.stderr[-3000:]
    return r


def test_sigkill_at_step_boundary_resumes_bit_exact(tmp_path):
    """Kill a trainer with SIGKILL mid-epoch (racing the async checkpoint
    writer), restart it on the same dir: the resumed run restores the
    newest committed checkpoint, re-runs at most the post-checkpoint
    steps, and every loss — including the re-run overlap — plus the
    final params digest bit-match an uninterrupted run."""
    ref_out = str(tmp_path / 'ref.txt')
    _run_worker('-', ref_out)
    _, ref_losses, ref_sha = _read_out(ref_out)
    assert ref_sha is not None and len(ref_losses) == 24

    d = str(tmp_path / 'ckpts')
    kill_at = int(np.random.RandomState(int(time.time())).randint(8, 21))
    kill_at -= kill_at % 4  # the worker kills at a dispatch boundary
    kill_at = max(kill_at, 8)
    out1 = str(tmp_path / 'run1.txt')
    r1 = _run_worker(d, out1, kill_at=kill_at, check=False)
    assert r1.returncode == -signal.SIGKILL, (r1.returncode, r1.stderr)
    resume1, losses1, sha1 = _read_out(out1)
    assert resume1 == 0 and sha1 is None
    assert len(losses1) == kill_at

    out2 = str(tmp_path / 'run2.txt')
    _run_worker(d, out2)
    resume2, losses2, sha2 = _read_out(out2)
    assert resume2 is not None and 0 < resume2 <= kill_at
    assert sha2 == ref_sha, 'final params diverged from uninterrupted run'
    for idx, v in {**losses1, **losses2}.items():
        assert v == ref_losses[idx], \
            'loss at step %d diverged: %r vs %r' % (idx, v, ref_losses[idx])
    # re-run overlap (kill landed past the restored checkpoint): the
    # replayed steps must reproduce the first incarnation bit-exactly
    for idx in set(losses1) & set(losses2):
        assert losses1[idx] == losses2[idx]


def test_resume_skips_corrupted_latest_checkpoint(tmp_path):
    """Corrupt the newest of two committed checkpoints: the restart must
    fall back to the OLDER one with a loud warning and still reach full
    parity with an uninterrupted run."""
    def train(exe, main_p, loss, scope, lo, hi, mgr=None, save_at=()):
        out = {}
        for d0 in range(lo // 4, hi // 4):
            l, = exe.run_steps(main_p, feed=_feed_for(d0 * 4, 4),
                               fetch_list=[loss], steps=4,
                               fetch_policy='stack')
            for i, v in enumerate(np.asarray(l).reshape(-1)):
                out[d0 * 4 + i] = float(v)
            if mgr is not None and (d0 + 1) * 4 in save_at:
                mgr.save(main_p, scope, (d0 + 1) * 4, executor=exe,
                         blocking=True)
        return out

    main_p, startup_p, loss = _build_net()
    scope = fluid.core.Scope()
    exe = fluid.Executor(fluid.CPUPlace())
    with fluid.scope_guard(scope):
        exe.run(startup_p)
        ref_losses = train(exe, main_p, loss, scope, 0, 16)
        ref_state = _state(main_p, scope)

    d = str(tmp_path / 'ckpts')
    main_p, startup_p, loss = _build_net()
    scope = fluid.core.Scope()
    exe = fluid.Executor(fluid.CPUPlace())
    with fluid.scope_guard(scope):
        exe.run(startup_p)
        with CheckpointManager(d) as mgr:
            losses1 = train(exe, main_p, loss, scope, 0, 12, mgr,
                            save_at=(4, 8))
    assert [s for s, _ in list_checkpoints(d)] == [4, 8]
    faults.corrupt_checkpoint(os.path.join(d, 'ckpt-8'), what='shard')

    main_p, startup_p, loss = _build_net()   # "restarted process"
    scope = fluid.core.Scope()
    exe = fluid.Executor(fluid.CPUPlace())
    with fluid.scope_guard(scope):
        exe.run(startup_p)
        with CheckpointManager(d) as mgr:
            with pytest.warns(RuntimeWarning, match='not loadable'):
                info = mgr.restore(executor=exe, program=main_p,
                                   scope=scope)
            assert info['step'] == 4, 'did not fall back to ckpt-4'
            losses2 = train(exe, main_p, loss, scope, 4, 16)
        state2 = _state(main_p, scope)

    for idx, v in {**losses1, **losses2}.items():
        assert v == ref_losses[idx], 'step %d diverged' % idx
    for n in ref_state:
        np.testing.assert_array_equal(ref_state[n], state2[n], err_msg=n)
