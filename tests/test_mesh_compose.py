"""Full five-axis composition with dp>1: the flagship distributed claim.

Round-4 state: sp/ep/pp composed in the 8-device dryrun but dp was 1, and
tests covered dp x {mp,sp,ep,pp} pairwise only. These tests compile ONE
train step over dp=2 x sp=2 x ep=2 x pp=2 (16 virtual devices) and over
all five axes >1 (32 virtual devices), asserting per-step loss parity
against the single-device run of the same program — the reference's
multi-device correctness bar (details/multi_devices_graph_pass.cc:393,
test_dist_base.py methodology) applied to the GSPMD design.

Subprocess-based because the device count must be fixed before jax
initializes (conftest pins this process to 8).
"""
import json
import os
import subprocess
import sys

import pytest

WORKER = os.path.join(os.path.dirname(__file__), 'mesh_compose_worker.py')


def _run(spec, timeout=1200, env_extra=None):
    env = dict(os.environ)
    env.update(env_extra or {})
    p = subprocess.run([sys.executable, WORKER] + spec,
                       capture_output=True, text=True, timeout=timeout,
                       env=env)
    assert p.returncode == 0, "worker failed:\n%s\n%s" % (p.stdout, p.stderr)
    assert 'MESH_COMPOSE_OK' in p.stdout, p.stdout
    cc = [l for l in p.stdout.splitlines() if l.startswith('CC_STATS ')]
    return json.loads(cc[0][len('CC_STATS '):]) if cc else None


def test_16dev_dp2_sp2_ep2_pp2():
    """dp=2 composed with all three novel axes in one compiled step."""
    _run(['dp=2', 'mp=1', 'sp=2', 'ep=2', 'pp=2'])


def test_32dev_all_five_axes():
    """dp=2 x mp=2 x sp=2 x ep=2 x pp=2 — every axis >1 simultaneously."""
    _run(['dp=2', 'mp=2', 'sp=2', 'ep=2', 'pp=2'])


@pytest.mark.slow
def test_64dev_dp4_sp2_ep2_pp4_warm_start(tmp_path):
    """Toward v5p-128 (VERDICT r5: "largest mesh ever compiled is 32 toy
    devices"): dp=4 x sp=2 x ep=2 x pp=4 = 64 virtual devices, run
    TWICE through the persistent compile cache — the cold run records the
    compile time, the warm run (a fresh process, the elastic-restart
    scenario) must hit the executable tier and skip the recompile."""
    spec = ['dp=4', 'mp=1', 'sp=2', 'ep=2', 'pp=4']
    env = {'PTPU_COMPILE_CACHE': '1',
           'PTPU_COMPILE_CACHE_DIR': str(tmp_path / 'cc')}
    cold = _run(spec, timeout=2400, env_extra=env)
    warm = _run(spec, timeout=2400, env_extra=env)
    assert cold is not None and warm is not None
    assert cold['misses'] >= 2          # single-device ref + mesh program
    assert cold['compile_s'] > 0
    assert warm['misses'] == 0, warm    # warm hit must skip recompile
    assert warm['compiles'] == 0, warm
    assert warm['exec_hits'] >= cold['misses'], warm
    # record the 64-device compile time in the test log (PERF_NOTES table)
    print('64dev compose: cold compile_s=%.2f, warm exec_hits=%d'
          % (cold['compile_s'], warm['exec_hits']))
