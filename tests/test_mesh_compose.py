"""Full five-axis composition with dp>1: the flagship distributed claim.

Round-4 state: sp/ep/pp composed in the 8-device dryrun but dp was 1, and
tests covered dp x {mp,sp,ep,pp} pairwise only. These tests compile ONE
train step over dp=2 x sp=2 x ep=2 x pp=2 (16 virtual devices) and over
all five axes >1 (32 virtual devices), asserting per-step loss parity
against the single-device run of the same program — the reference's
multi-device correctness bar (details/multi_devices_graph_pass.cc:393,
test_dist_base.py methodology) applied to the GSPMD design.

Subprocess-based because the device count must be fixed before jax
initializes (conftest pins this process to 8).
"""
import os
import subprocess
import sys

WORKER = os.path.join(os.path.dirname(__file__), 'mesh_compose_worker.py')


def _run(spec, timeout=1200):
    p = subprocess.run([sys.executable, WORKER] + spec,
                       capture_output=True, text=True, timeout=timeout)
    assert p.returncode == 0, "worker failed:\n%s\n%s" % (p.stdout, p.stderr)
    assert 'MESH_COMPOSE_OK' in p.stdout, p.stdout


def test_16dev_dp2_sp2_ep2_pp2():
    """dp=2 composed with all three novel axes in one compiled step."""
    _run(['dp=2', 'mp=1', 'sp=2', 'ep=2', 'pp=2'])


def test_32dev_all_five_axes():
    """dp=2 x mp=2 x sp=2 x ep=2 x pp=2 — every axis >1 simultaneously."""
    _run(['dp=2', 'mp=2', 'sp=2', 'ep=2', 'pp=2'])
