"""LoD-capable compiled-artifact export (VERDICT r4 missing #3): the
reference's deployment API carries lod in PaddleTensor
(inference/api/paddle_api.h:1); here LoD feeds export in traced-offset
form (offsets are runtime inputs — one artifact per BUCKET shape serves
every batch), and LoD fetches come back as (values, [offsets]) pairs.
CRNN — the LoD north-star model — must serve tracer-free with output
parity against the Python Predictor on two bucket shapes."""
import os
import subprocess
import sys

import numpy as np

import paddle_tpu as fluid
from paddle_tpu.inference import (Config, create_predictor, export_compiled,
                                  load_compiled)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------------------
# LoD FEEDS: a text classifier over variable-length token sequences
# ---------------------------------------------------------------------------
def _build_text_model(dirname):
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 5
    with fluid.program_guard(main, startup):
        ids = fluid.layers.data('ids', shape=[1], dtype='int64', lod_level=1)
        emb = fluid.layers.embedding(input=ids, size=[50, 8])
        pooled = fluid.layers.sequence_pool(emb, 'average')
        out = fluid.layers.fc(pooled, size=4, act='softmax')
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    fluid.io.save_inference_model(dirname, ['ids'], [out], exe, main)


def _ids_batch(lens, bucket_rows, seed):
    rng = np.random.RandomState(seed)
    total = int(sum(lens))
    data = rng.randint(0, 50, (total, 1)).astype(np.int64)
    lt = fluid.create_lod_tensor(data, [list(lens)], traced=True,
                                 bucket_rows=bucket_rows)
    offs = np.concatenate([[0], np.cumsum(lens)]).astype(np.int32)
    padded = np.zeros((bucket_rows, 1), np.int64)
    padded[:total] = data
    return lt, (padded, [offs])


def test_lod_feed_export_two_buckets(tmp_path):
    model_dir = str(tmp_path / 'model')
    _build_text_model(model_dir)
    cfg = Config(model_dir)
    cfg.disable_gpu()
    pred = create_predictor(cfg)

    # bucket A: 3 sequences, 12 padded rows; bucket B: 2 sequences, 20 rows
    for bi, (bucket_rows, lens1, lens2) in enumerate(
            [(12, [3, 5, 2], [4, 1, 6]), (20, [8, 9], [12, 5])]):
        art = str(tmp_path / ('artifact%d' % bi))
        lt1, pair1 = _ids_batch(lens1, bucket_rows, seed=bi)
        want1, = pred.run([lt1])
        export_compiled(pred, {'ids': pair1}, art)
        served = load_compiled(art)
        got1, = served.run({'ids': pair1})
        np.testing.assert_allclose(got1[:len(lens1)], want1,
                                   rtol=1e-5, atol=1e-6)
        # same artifact, DIFFERENT lod values in the same bucket: the
        # compiled module is lod-generic (offsets are runtime inputs)
        lt2, pair2 = _ids_batch(lens2, bucket_rows, seed=10 + bi)
        want2, = pred.run([lt2])
        got2, = served.run({'ids': pair2})
        np.testing.assert_allclose(got2[:len(lens2)], want2,
                                   rtol=1e-5, atol=1e-6)


def test_lod_feed_partial_bucket_pads_in_serve(tmp_path):
    """A LoD feed arriving BELOW the bucket capacity is padded up by
    serve.py itself (the executor's bucket_rows discipline) — the values
    array does not need host-side pre-padding. Regression: the dense
    partial-batch pad detection must not clobber this path."""
    model_dir = str(tmp_path / 'model')
    _build_text_model(model_dir)
    cfg = Config(model_dir)
    cfg.disable_gpu()
    pred = create_predictor(cfg)
    bucket_rows, lens = 12, [3, 5, 2]
    lt, (padded, offs) = _ids_batch(lens, bucket_rows, seed=3)
    want, = pred.run([lt])
    art = str(tmp_path / 'artifact')
    export_compiled(pred, {'ids': (padded, offs)}, art)
    served = load_compiled(art)
    got, = served.run({'ids': (padded[:sum(lens)], offs)})  # 10 < 12 rows
    np.testing.assert_allclose(got[:len(lens)], want, rtol=1e-5, atol=1e-6)


# ---------------------------------------------------------------------------
# LoD FETCHES: CRNN serves tracer-free (north star #4)
# ---------------------------------------------------------------------------
def _build_crnn_infer(dirname, img_w):
    from models.crnn import ctc_encoder
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 7
    with fluid.program_guard(main, startup):
        images = fluid.layers.data('pixel', shape=[1, 32, img_w],
                                   dtype='float32')
        logits = ctc_encoder(images, num_classes=10, rnn_hidden=16,
                             is_train=False)
        decoded = fluid.layers.ctc_greedy_decoder(input=logits, blank=10)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    fluid.io.save_inference_model(dirname, ['pixel'], [decoded], exe, main)


def test_crnn_serves_tracer_free_two_buckets(tmp_path):
    """Output parity vs the Python Predictor on two bucket (image width)
    shapes: decoded token values AND lod offsets must match."""
    for img_w in (64, 96):
        model_dir = str(tmp_path / ('model%d' % img_w))
        art = str(tmp_path / ('artifact%d' % img_w))
        _build_crnn_infer(model_dir, img_w)
        cfg = Config(model_dir)
        cfg.disable_gpu()
        pred = create_predictor(cfg)
        x = np.random.RandomState(img_w).randn(3, 1, 32, img_w) \
            .astype(np.float32)
        want = pred.run([x], return_numpy=False)[0]   # LoDArray
        want_data = np.asarray(want.data)
        want_off = np.asarray(want.lod[0])

        export_compiled(pred, [x], art)
        served = load_compiled(art)
        (got_data, got_lod), = served.run([x])
        np.testing.assert_array_equal(got_data, want_data)
        np.testing.assert_array_equal(got_lod[0], want_off)


def test_crnn_artifact_fresh_process_no_framework(tmp_path):
    """The CRNN artifact (LoD output) runs via serve.py in a process that
    never imports the framework — npz carries '<name>.lod<i>' arrays."""
    model_dir = str(tmp_path / 'model')
    art = str(tmp_path / 'artifact')
    _build_crnn_infer(model_dir, 64)
    cfg = Config(model_dir)
    cfg.disable_gpu()
    pred = create_predictor(cfg)
    x = np.random.RandomState(3).randn(2, 1, 32, 64).astype(np.float32)
    want = pred.run([x], return_numpy=False)[0]
    export_compiled(pred, [x], art)
    np.savez(str(tmp_path / 'in.npz'), pixel=x)

    probe = (
        "import runpy, sys\n"
        "sys.argv = ['serve.py', %r, %r, %r]\n"
        "try:\n"
        "    runpy.run_path(%r, run_name='__main__')\n"
        "except SystemExit as e:\n"
        "    assert (e.code or 0) == 0, e.code\n"
        "bad = [m for m in sys.modules if m.startswith('paddle_tpu')]\n"
        "assert not bad, 'framework leaked into serving: %%r' %% bad\n"
        % (art, str(tmp_path / 'in.npz'), str(tmp_path / 'out.npz'),
           os.path.join(REPO, 'paddle_tpu', 'inference', 'serve.py')))
    env = dict(os.environ)
    env['PTPU_PLATFORM'] = 'cpu'
    r = subprocess.run([sys.executable, '-c', probe], env=env,
                       capture_output=True, text=True, timeout=300)
    assert r.returncode == 0, r.stderr[-2000:]
    with np.load(str(tmp_path / 'out.npz')) as out:
        name = [k for k in out.files if not k.endswith('.lod0')][0]
        np.testing.assert_array_equal(out[name], np.asarray(want.data))
        np.testing.assert_array_equal(out[name + '.lod0'],
                                      np.asarray(want.lod[0]))
