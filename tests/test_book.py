"""Book-style end-to-end tests (ref: python/paddle/fluid/tests/book/ —
train a canonical model a few iterations, save an inference model, reload
it, and check the served outputs match the trained program's).
"""
import numpy as np
import pytest

import paddle_tpu as fluid


def _train_save_infer(build_fn, feeds_fn, dirname, steps=8, converge=0.9):
    main_p, startup_p = fluid.Program(), fluid.Program()
    main_p.random_seed = startup_p.random_seed = 42
    with fluid.program_guard(main_p, startup_p):
        feed_names, fetch_var, loss = build_fn()
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.core.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup_p)
        losses = []
        for feed in feeds_fn(steps):
            l, = exe.run(main_p, feed=feed, fetch_list=[loss])
            losses.append(float(np.asarray(l).reshape(-1)[0]))
        assert np.isfinite(losses).all()
        assert losses[-1] < losses[0] * converge, losses
        # save -> reload -> serve
        infer_prog = main_p.clone(for_test=True)
        fluid.save_inference_model(dirname, feed_names, [fetch_var], exe,
                                   main_program=infer_prog)
        feed = next(iter(feeds_fn(1)))
        # the un-pruned test clone still holds the loss ops: feed all vars
        want, = exe.run(infer_prog, feed=feed, fetch_list=[fetch_var])
    scope2 = fluid.core.Scope()
    with fluid.scope_guard(scope2):
        prog, fnames, fvars = fluid.load_inference_model(dirname, exe)
        got, = exe.run(prog, feed={k: feed[k] for k in fnames},
                       fetch_list=[f.name for f in fvars])
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-4, atol=1e-5)
    return losses


def test_book_recognize_digits_mlp(tmp_path):
    """test_recognize_digits.py (MLP flavor) on synthetic mnist."""
    from paddle_tpu.dataset import mnist

    def build():
        img = fluid.layers.data(name='img', shape=[784], dtype='float32')
        label = fluid.layers.data(name='label', shape=[1], dtype='int64')
        h = fluid.layers.fc(img, size=128, act='relu')
        probs = fluid.layers.fc(h, size=10, act='softmax')
        loss = fluid.layers.mean(fluid.layers.cross_entropy(input=probs,
                                                            label=label))
        fluid.optimizer.Adam(1e-3).minimize(loss)
        return ['img'], probs, loss

    reader = fluid.layers.batch(mnist.train(), 64)

    def feeds(n):
        it = reader()
        for _ in range(n):
            batch = next(it)
            imgs = np.stack([b[0] for b in batch]).reshape(-1, 784)
            labs = np.asarray([b[1] for b in batch]).reshape(-1, 1)
            yield {'img': imgs.astype(np.float32), 'label': labs}

    _train_save_infer(build, feeds, str(tmp_path / 'mlp'), steps=12)


def test_book_image_classification_cnn(tmp_path):
    """test_image_classification.py flavor: conv net on synthetic cifar."""
    def build():
        img = fluid.layers.data(name='img', shape=[3, 32, 32],
                                dtype='float32')
        label = fluid.layers.data(name='label', shape=[1], dtype='int64')
        c = fluid.nets.simple_img_conv_pool(
            input=img, num_filters=8, filter_size=3, pool_size=2,
            pool_stride=2, act='relu')
        probs = fluid.layers.fc(c, size=10, act='softmax')
        loss = fluid.layers.mean(fluid.layers.cross_entropy(input=probs,
                                                            label=label))
        fluid.optimizer.Adam(2e-3).minimize(loss)
        return ['img'], probs, loss

    rng = np.random.RandomState(0)
    xs = rng.randn(64, 3, 32, 32).astype(np.float32)
    labs = rng.randint(0, 10, (64, 1))

    def feeds(n):
        for _ in range(n):
            yield {'img': xs, 'label': labs}

    _train_save_infer(build, feeds, str(tmp_path / 'cnn'), steps=10)


def test_book_understand_sentiment_lstm(tmp_path):
    """test_understand_sentiment.py flavor: embedding + dynamic LSTM over
    LoD token sequences."""
    def build():
        words = fluid.layers.data(name='words', shape=[1], dtype='int64',
                                  lod_level=1)
        label = fluid.layers.data(name='label', shape=[1], dtype='int64')
        emb = fluid.layers.embedding(words, size=[200, 32])
        fc = fluid.layers.fc(emb, size=64)
        lstm, _ = fluid.layers.dynamic_lstm(input=fc, size=64)
        last = fluid.layers.sequence_pool(lstm, 'last')
        probs = fluid.layers.fc(last, size=2, act='softmax')
        loss = fluid.layers.mean(fluid.layers.cross_entropy(input=probs,
                                                            label=label))
        fluid.optimizer.Adam(5e-3).minimize(loss)
        return ['words'], probs, loss

    rng = np.random.RandomState(1)
    lens = [7, 5, 9, 6]
    toks = np.concatenate([
        rng.randint(0, 100, lens[i]) if i % 2 == 0
        else rng.randint(100, 200, lens[i]) for i in range(4)])
    words = fluid.create_lod_tensor(toks.reshape(-1, 1).astype(np.int64),
                                    [lens])
    labs = np.array([[0], [1], [0], [1]])

    def feeds(n):
        for _ in range(n):
            yield {'words': words, 'label': labs}

    _train_save_infer(build, feeds, str(tmp_path / 'lstm'), steps=15,
                      converge=0.95)


def test_book_fit_a_line(tmp_path):
    """test_fit_a_line.py: linear regression on uci-housing shapes."""
    def build():
        x = fluid.layers.data(name='x', shape=[13], dtype='float32')
        y = fluid.layers.data(name='y', shape=[1], dtype='float32')
        pred = fluid.layers.fc(x, size=1)
        loss = fluid.layers.mean(
            fluid.layers.square_error_cost(pred, y))
        fluid.optimizer.SGD(0.01).minimize(loss)
        return ['x'], pred, loss

    rng = np.random.RandomState(2)
    xs = rng.randn(64, 13).astype(np.float32)
    w = rng.randn(13, 1).astype(np.float32)
    ys = xs @ w

    def feeds(n):
        for _ in range(n):
            yield {'x': xs, 'y': ys}

    _train_save_infer(build, feeds, str(tmp_path / 'line'), steps=20,
                      converge=0.5)


def test_book_word2vec(tmp_path):
    """test_word2vec.py: N-gram LM — 4 context words through a SHARED
    embedding table predict the 5th."""
    from paddle_tpu.dataset import imikolov
    word_dict = imikolov.build_dict()
    V, EMB = len(word_dict), 32

    def build():
        ws = [fluid.layers.data(name='w%d' % i, shape=[1], dtype='int64')
              for i in range(4)]
        label = fluid.layers.data(name='nextw', shape=[1], dtype='int64')
        embs = [fluid.layers.reshape(
                    fluid.layers.embedding(
                        w, size=[V, EMB],
                        param_attr=fluid.param_attr.ParamAttr(
                            name='shared_emb_w')),
                    shape=[-1, EMB]) for w in ws]
        hidden = fluid.layers.fc(fluid.layers.concat(embs, axis=1),
                                 size=128, act='sigmoid')
        probs = fluid.layers.fc(hidden, size=V, act='softmax')
        loss = fluid.layers.mean(fluid.layers.cross_entropy(input=probs,
                                                            label=label))
        fluid.optimizer.Adam(2e-3).minimize(loss)
        return ['w0', 'w1', 'w2', 'w3'], probs, loss

    reader = fluid.layers.batch(imikolov.train(word_dict), 64)
    batch = np.asarray(next(iter(reader())), dtype=np.int64)   # [B, 5]
    feed = {('w%d' % i): batch[:, i:i + 1] for i in range(4)}
    feed['nextw'] = batch[:, 4:5]

    def feeds(n):
        for _ in range(n):
            yield dict(feed)

    _train_save_infer(build, feeds, str(tmp_path / 'w2v'), steps=15,
                      converge=0.95)


def test_book_recommender_system(tmp_path):
    """test_recommender_system.py: user/movie towers -> cos_sim rating
    regression on movielens shapes (categories/title are LoD)."""
    from paddle_tpu.dataset import movielens

    def build():
        def din(name, lod=0):
            return fluid.layers.data(name=name, shape=[1], dtype='int64',
                                     lod_level=lod)
        uid, gender, age, job = din('uid'), din('gender'), din('age'), \
            din('job')
        mid, cat, title = din('mid'), din('cat', 1), din('title', 1)
        score = fluid.layers.data(name='score', shape=[1], dtype='float32')

        def emb(x, vocab, dim=16):
            return fluid.layers.reshape(
                fluid.layers.embedding(x, size=[vocab, dim]), [-1, dim])

        usr = fluid.layers.fc(fluid.layers.concat(
            [emb(uid, movielens.max_user_id() + 1), emb(gender, 2),
             emb(age, len(movielens.age_table())),
             emb(job, movielens.max_job_id() + 1)], axis=1),
            size=32, act='tanh')
        cat_pool = fluid.layers.sequence_pool(
            fluid.layers.embedding(cat, size=[18, 16]), 'sum')
        title_pool = fluid.layers.sequence_pool(
            fluid.layers.embedding(title, size=[5174, 16]), 'sum')
        mov = fluid.layers.fc(fluid.layers.concat(
            [emb(mid, movielens.max_movie_id() + 1), cat_pool, title_pool],
            axis=1), size=32, act='tanh')
        pred = fluid.layers.scale(fluid.layers.cos_sim(usr, mov), scale=5.0)
        loss = fluid.layers.mean(fluid.layers.square_error_cost(pred,
                                                                score))
        fluid.optimizer.Adam(5e-3).minimize(loss)
        return ['uid', 'gender', 'age', 'job', 'mid', 'cat', 'title'], \
            pred, loss

    reader = fluid.layers.batch(movielens.train(), 32)

    def feeds(n):
        it = reader()
        for _ in range(n):
            rows = next(it)
            col = lambda i: np.asarray([[r[i]] for r in rows], np.int64)
            cat_lens = [len(r[5]) for r in rows]
            title_lens = [len(r[6]) for r in rows]
            yield {
                'uid': col(0), 'gender': col(1), 'age': col(2),
                'job': col(3), 'mid': col(4),
                'cat': fluid.create_lod_tensor(
                    np.concatenate([r[5] for r in rows]).reshape(-1, 1)
                    .astype(np.int64), [cat_lens]),
                'title': fluid.create_lod_tensor(
                    np.concatenate([r[6] for r in rows]).reshape(-1, 1)
                    .astype(np.int64), [title_lens]),
                'score': np.asarray([[r[7]] for r in rows], np.float32),
            }

    _train_save_infer(build, feeds, str(tmp_path / 'rec'), steps=12,
                      converge=0.98)


def test_book_label_semantic_roles(tmp_path):
    """test_label_semantic_roles.py: conll05 SRL — per-slot embeddings ->
    BiLSTM -> emission -> linear_chain_crf loss, crf_decoding served."""
    from paddle_tpu.dataset import conll05
    W, P, L, M = (conll05.WORD_DICT_LEN, conll05.PRED_DICT_LEN,
                  conll05.LABEL_DICT_LEN, conll05.MARK_DICT_LEN)
    EMB, H = 16, 32
    slots = ['word', 'ctx_n2', 'ctx_n1', 'ctx_0', 'ctx_p1', 'ctx_p2',
             'verb', 'mark']

    def build():
        ins = [fluid.layers.data(name=s, shape=[1], dtype='int64',
                                 lod_level=1) for s in slots]
        target = fluid.layers.data(name='target', shape=[1], dtype='int64',
                                   lod_level=1)
        word_attr = fluid.param_attr.ParamAttr(name='word_emb_w')
        embs = [fluid.layers.embedding(v, size=[W, EMB],
                                       param_attr=word_attr)
                for v in ins[:6]]
        embs.append(fluid.layers.embedding(ins[6], size=[P, EMB]))
        embs.append(fluid.layers.embedding(ins[7], size=[M, EMB]))
        feat = fluid.layers.fc(fluid.layers.concat(embs, axis=1),
                               size=H, act='tanh')
        fwd, _ = fluid.layers.dynamic_lstm(
            fluid.layers.fc(feat, size=4 * H), size=4 * H,
            use_peepholes=False)
        rev, _ = fluid.layers.dynamic_lstm(
            fluid.layers.fc(feat, size=4 * H), size=4 * H,
            use_peepholes=False, is_reverse=True)
        emission = fluid.layers.fc(
            fluid.layers.concat([fwd, rev], axis=1), size=L)
        crf_cost = fluid.layers.linear_chain_crf(
            input=emission, label=target,
            param_attr=fluid.param_attr.ParamAttr(name='crfw'))
        loss = fluid.layers.mean(crf_cost)
        decode = fluid.layers.crf_decoding(
            input=emission,
            param_attr=fluid.param_attr.ParamAttr(name='crfw'))
        fluid.optimizer.Adam(5e-3).minimize(loss)
        return slots, decode, loss

    reader = fluid.layers.batch(conll05.train(), 8)

    def feeds(n):
        it = reader()
        for _ in range(n):
            rows = next(it)
            lens = [len(r[0]) for r in rows]

            def lod_col(i):
                return fluid.create_lod_tensor(
                    np.concatenate([r[i] for r in rows]).reshape(-1, 1)
                    .astype(np.int64), [lens])
            feed = {s: lod_col(i) for i, s in enumerate(slots)}
            feed['target'] = lod_col(8)
            yield feed

    _train_save_infer(build, feeds, str(tmp_path / 'srl'), steps=10,
                      converge=0.98)


def test_book_machine_translation(tmp_path):
    """test_machine_translation.py: GRU encoder-decoder trained with
    teacher forcing, then BEAM-SEARCH decoding through a separate infer
    program sharing the trained parameters (by name, the reference's
    pattern), save/load/serve round-trip on the decode program."""
    PA = fluid.param_attr.ParamAttr
    V, E, H, K, T = 64, 16, 32, 4, 6
    BOS, EOS = 1, 0

    def encoder(src):
        src_emb = fluid.layers.embedding(src, size=[V, E],
                                         param_attr=PA(name='src_emb_w'))
        enc_in = fluid.layers.fc(src_emb, size=3 * H,
                                 param_attr=PA(name='enc_proj_w'),
                                 bias_attr=PA(name='enc_proj_b'))
        enc_in.lod_level = src_emb.lod_level
        enc = fluid.layers.dynamic_gru(enc_in, size=H,
                                       param_attr=PA(name='enc_gru_w'),
                                       bias_attr=PA(name='enc_gru_b'))
        return fluid.layers.sequence_pool(enc, 'last')      # [B, H]

    def dec_step_proj(emb2d, ctx2d):
        return fluid.layers.fc(
            fluid.layers.concat([emb2d, ctx2d], axis=1), size=3 * H,
            param_attr=PA(name='dec_proj_w'),
            bias_attr=PA(name='dec_proj_b'))

    # ---- train program: teacher forcing ----
    main_p, startup_p = fluid.Program(), fluid.Program()
    main_p.random_seed = startup_p.random_seed = 9
    with fluid.program_guard(main_p, startup_p):
        src = fluid.layers.data(name='src', shape=[1], dtype='int64',
                                lod_level=1)
        tgt = fluid.layers.data(name='tgt', shape=[1], dtype='int64',
                                lod_level=1)
        tgt_next = fluid.layers.data(name='tgt_next', shape=[1],
                                     dtype='int64', lod_level=1)
        enc_last = encoder(src)
        tgt_emb = fluid.layers.embedding(tgt, size=[V, E],
                                         param_attr=PA(name='tgt_emb_w'))
        ctx = fluid.layers.sequence_expand(enc_last, tgt_emb)
        dec_in = dec_step_proj(tgt_emb, ctx)
        dec_in.lod_level = tgt_emb.lod_level
        dec = fluid.layers.dynamic_gru(dec_in, size=H,
                                       param_attr=PA(name='dec_gru_w'),
                                       bias_attr=PA(name='dec_gru_b'))
        logits = fluid.layers.fc(dec, size=V,
                                 param_attr=PA(name='dec_out_w'),
                                 bias_attr=PA(name='dec_out_b'))
        loss = fluid.layers.mean(fluid.layers.softmax_with_cross_entropy(
            logits=logits, label=tgt_next))
        fluid.optimizer.Adam(5e-3).minimize(loss)

    rng = np.random.RandomState(3)
    lens = [5, 7, 4]

    def make_feed():
        src_toks = np.concatenate([rng.randint(2, V, l) for l in lens])
        # toy task: target = source tokens (copy), learnable fast
        tgt_in, tgt_out = [], []
        src_pos = 0
        for l in lens:
            s = src_toks[src_pos:src_pos + l]
            src_pos += l
            tgt_in.append(np.concatenate([[BOS], s]))
            tgt_out.append(np.concatenate([s, [EOS]]))
        return {
            'src': fluid.create_lod_tensor(
                src_toks.reshape(-1, 1).astype(np.int64), [lens]),
            'tgt': fluid.create_lod_tensor(
                np.concatenate(tgt_in).reshape(-1, 1).astype(np.int64),
                [[l + 1 for l in lens]]),
            'tgt_next': fluid.create_lod_tensor(
                np.concatenate(tgt_out).reshape(-1, 1).astype(np.int64),
                [[l + 1 for l in lens]]),
        }

    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.core.Scope()
    feed = make_feed()
    with fluid.scope_guard(scope):
        exe.run(startup_p)
        losses = []
        for _ in range(15):
            l, = exe.run(main_p, feed=feed, fetch_list=[loss])
            losses.append(float(np.asarray(l).reshape(-1)[0]))
        assert np.isfinite(losses).all()
        assert losses[-1] < losses[0], losses

    # ---- infer program: beam search over the SHARED parameters ----
    infer_p, infer_start = fluid.Program(), fluid.Program()
    with fluid.program_guard(infer_p, infer_start):
        layers = fluid.layers
        src = layers.data(name='src', shape=[1], dtype='int64',
                          lod_level=1)
        enc_last = encoder(src)                              # [1, H]
        ctx_k = layers.expand(enc_last, expand_times=[K, 1])  # [K, H]

        i = layers.fill_constant([1], 'int64', 0)
        limit = layers.fill_constant([1], 'int64', T)
        ids_arr = layers.array_write(
            layers.fill_constant([K, 1], 'int64', BOS), i)
        scores_arr = layers.array_write(
            layers.fill_constant([K, 1], 'float32', 0.0), i)
        parents_arr = layers.array_write(
            layers.fill_constant([K], 'int32', 0), i)
        hidden_arr = layers.array_write(
            layers.fill_constant([K, H], 'float32', 0.0), i)
        layers.increment(i, 1)
        cond = layers.less_than(i, limit)
        w = layers.While(cond)
        with w.block():
            t = layers.elementwise_sub(
                i, layers.fill_constant([1], 'int64', 1))
            pre_ids = layers.array_read(ids_arr, t)
            pre_scores = layers.array_read(scores_arr, t)
            pre_hidden = layers.array_read(hidden_arr, t)
            emb = layers.reshape(
                layers.embedding(pre_ids, size=[V, E],
                                 param_attr=PA(name='tgt_emb_w')),
                shape=[K, E])
            # reshape pins static [K, .] shapes for fc's param inference
            # inside the While block (array_read/expand infer no shape)
            step_in = dec_step_proj(emb, layers.reshape(ctx_k, [K, H]))
            h, _, _ = fluid.layers.gru_unit(
                step_in, pre_hidden, 3 * H,
                param_attr=PA(name='dec_gru_w'),
                bias_attr=PA(name='dec_gru_b'))
            logits = layers.fc(h, size=V,
                               param_attr=PA(name='dec_out_w'),
                               bias_attr=PA(name='dec_out_b'))
            acc = layers.elementwise_add(
                layers.log(layers.softmax(logits)), pre_scores)
            sel_ids, sel_scores, parent = layers.beam_search(
                pre_ids, pre_scores, None, acc, beam_size=K, end_id=EOS,
                return_parent_idx=True)
            layers.array_write(sel_ids, i, array=ids_arr)
            layers.array_write(sel_scores, i, array=scores_arr)
            layers.array_write(parent, i, array=parents_arr)
            # beams reorder on selection: hidden follows its parent beam
            layers.array_write(layers.gather(h, parent), i,
                               array=hidden_arr)
            layers.increment(i, 1)
            layers.less_than(i, limit, cond=cond)
        sent_ids, sent_scores = layers.beam_search_decode(
            ids_arr, scores_arr, beam_size=K, end_id=EOS,
            parents=parents_arr)

    one_src = fluid.create_lod_tensor(
        np.asarray([[5], [9], [3]], np.int64), [[3]])
    with fluid.scope_guard(scope):   # trained params, by name
        want_ids, want_scores = exe.run(
            infer_p, feed={'src': one_src},
            fetch_list=[sent_ids, sent_scores], return_numpy=False)
        want_ids = np.asarray(want_ids.data if hasattr(want_ids, 'data')
                              else want_ids)
        # save the DECODE program: the served artifact is the translator
        d = str(tmp_path / 'nmt')
        fluid.io.save_inference_model(d, ['src'], [sent_ids, sent_scores],
                                      exe, main_program=infer_p)
    scope2 = fluid.core.Scope()
    with fluid.scope_guard(scope2):
        prog, fnames, fvars = fluid.load_inference_model(d, exe)
        got_ids, got_scores = exe.run(
            prog, feed={'src': one_src},
            fetch_list=[f.name for f in fvars], return_numpy=False)
        got_ids = np.asarray(got_ids.data if hasattr(got_ids, 'data')
                             else got_ids)
        got_scores = np.asarray(got_scores.data
                                if hasattr(got_scores, 'data')
                                else got_scores)
    np.testing.assert_array_equal(got_ids, want_ids)
    want_scores = np.asarray(want_scores.data
                             if hasattr(want_scores, 'data')
                             else want_scores)
    np.testing.assert_allclose(got_scores, want_scores,
                               rtol=1e-5, atol=1e-6)
    assert want_ids.size >= K   # K hypotheses came back
