"""Book-style end-to-end tests (ref: python/paddle/fluid/tests/book/ —
train a canonical model a few iterations, save an inference model, reload
it, and check the served outputs match the trained program's).
"""
import numpy as np
import pytest

import paddle_tpu as fluid


def _train_save_infer(build_fn, feeds_fn, dirname, steps=8, converge=0.9):
    main_p, startup_p = fluid.Program(), fluid.Program()
    main_p.random_seed = startup_p.random_seed = 42
    with fluid.program_guard(main_p, startup_p):
        feed_names, fetch_var, loss = build_fn()
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.core.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup_p)
        losses = []
        for feed in feeds_fn(steps):
            l, = exe.run(main_p, feed=feed, fetch_list=[loss])
            losses.append(float(np.asarray(l).reshape(-1)[0]))
        assert np.isfinite(losses).all()
        assert losses[-1] < losses[0] * converge, losses
        # save -> reload -> serve
        infer_prog = main_p.clone(for_test=True)
        fluid.save_inference_model(dirname, feed_names, [fetch_var], exe,
                                   main_program=infer_prog)
        feed = next(iter(feeds_fn(1)))
        # the un-pruned test clone still holds the loss ops: feed all vars
        want, = exe.run(infer_prog, feed=feed, fetch_list=[fetch_var])
    scope2 = fluid.core.Scope()
    with fluid.scope_guard(scope2):
        prog, fnames, fvars = fluid.load_inference_model(dirname, exe)
        got, = exe.run(prog, feed={k: feed[k] for k in fnames},
                       fetch_list=[f.name for f in fvars])
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-4, atol=1e-5)
    return losses


def test_book_recognize_digits_mlp(tmp_path):
    """test_recognize_digits.py (MLP flavor) on synthetic mnist."""
    from paddle_tpu.dataset import mnist

    def build():
        img = fluid.layers.data(name='img', shape=[784], dtype='float32')
        label = fluid.layers.data(name='label', shape=[1], dtype='int64')
        h = fluid.layers.fc(img, size=128, act='relu')
        probs = fluid.layers.fc(h, size=10, act='softmax')
        loss = fluid.layers.mean(fluid.layers.cross_entropy(input=probs,
                                                            label=label))
        fluid.optimizer.Adam(1e-3).minimize(loss)
        return ['img'], probs, loss

    reader = fluid.layers.batch(mnist.train(), 64)

    def feeds(n):
        it = reader()
        for _ in range(n):
            batch = next(it)
            imgs = np.stack([b[0] for b in batch]).reshape(-1, 784)
            labs = np.asarray([b[1] for b in batch]).reshape(-1, 1)
            yield {'img': imgs.astype(np.float32), 'label': labs}

    _train_save_infer(build, feeds, str(tmp_path / 'mlp'), steps=12)


def test_book_image_classification_cnn(tmp_path):
    """test_image_classification.py flavor: conv net on synthetic cifar."""
    def build():
        img = fluid.layers.data(name='img', shape=[3, 32, 32],
                                dtype='float32')
        label = fluid.layers.data(name='label', shape=[1], dtype='int64')
        c = fluid.nets.simple_img_conv_pool(
            input=img, num_filters=8, filter_size=3, pool_size=2,
            pool_stride=2, act='relu')
        probs = fluid.layers.fc(c, size=10, act='softmax')
        loss = fluid.layers.mean(fluid.layers.cross_entropy(input=probs,
                                                            label=label))
        fluid.optimizer.Adam(2e-3).minimize(loss)
        return ['img'], probs, loss

    rng = np.random.RandomState(0)
    xs = rng.randn(64, 3, 32, 32).astype(np.float32)
    labs = rng.randint(0, 10, (64, 1))

    def feeds(n):
        for _ in range(n):
            yield {'img': xs, 'label': labs}

    _train_save_infer(build, feeds, str(tmp_path / 'cnn'), steps=10)


def test_book_understand_sentiment_lstm(tmp_path):
    """test_understand_sentiment.py flavor: embedding + dynamic LSTM over
    LoD token sequences."""
    def build():
        words = fluid.layers.data(name='words', shape=[1], dtype='int64',
                                  lod_level=1)
        label = fluid.layers.data(name='label', shape=[1], dtype='int64')
        emb = fluid.layers.embedding(words, size=[200, 32])
        fc = fluid.layers.fc(emb, size=64)
        lstm, _ = fluid.layers.dynamic_lstm(input=fc, size=64)
        last = fluid.layers.sequence_pool(lstm, 'last')
        probs = fluid.layers.fc(last, size=2, act='softmax')
        loss = fluid.layers.mean(fluid.layers.cross_entropy(input=probs,
                                                            label=label))
        fluid.optimizer.Adam(5e-3).minimize(loss)
        return ['words'], probs, loss

    rng = np.random.RandomState(1)
    lens = [7, 5, 9, 6]
    toks = np.concatenate([
        rng.randint(0, 100, lens[i]) if i % 2 == 0
        else rng.randint(100, 200, lens[i]) for i in range(4)])
    words = fluid.create_lod_tensor(toks.reshape(-1, 1).astype(np.int64),
                                    [lens])
    labs = np.array([[0], [1], [0], [1]])

    def feeds(n):
        for _ in range(n):
            yield {'words': words, 'label': labs}

    _train_save_infer(build, feeds, str(tmp_path / 'lstm'), steps=15,
                      converge=0.95)


def test_book_fit_a_line(tmp_path):
    """test_fit_a_line.py: linear regression on uci-housing shapes."""
    def build():
        x = fluid.layers.data(name='x', shape=[13], dtype='float32')
        y = fluid.layers.data(name='y', shape=[1], dtype='float32')
        pred = fluid.layers.fc(x, size=1)
        loss = fluid.layers.mean(
            fluid.layers.square_error_cost(pred, y))
        fluid.optimizer.SGD(0.01).minimize(loss)
        return ['x'], pred, loss

    rng = np.random.RandomState(2)
    xs = rng.randn(64, 13).astype(np.float32)
    w = rng.randn(13, 1).astype(np.float32)
    ys = xs @ w

    def feeds(n):
        for _ in range(n):
            yield {'x': xs, 'y': ys}

    _train_save_infer(build, feeds, str(tmp_path / 'line'), steps=20,
                      converge=0.5)
