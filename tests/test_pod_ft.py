"""Pod-scale fault tolerance (ISSUE 10): sharded two-phase checkpoints,
host-failure detection, coordinated kill-one-host resume.

Units drive the two-phase commit protocol with duck-typed global arrays
(no jax.distributed needed): per-host manager instances sharing one
checkpoint dir play the pod roles. The subprocess test runs the real
thing — a 2-process composed-mesh train (dp spans hosts x mp within,
gloo collectives) killed mid-step and restarted, asserting bit/loss
parity against an uninterrupted pod run and checkpoint stall < 1%.
"""
import json
import os
import signal
import subprocess
import sys
import time
import warnings

import numpy as np
import pytest

from paddle_tpu.core.checkpoint import (
    CheckpointManager, PodCheckpointManager, HostWatchdog, BarrierTimeout,
    fs_barrier, write_heartbeat, read_heartbeats, stale_hosts,
    pod_latest_committed, pod_verify, list_checkpoints,
    request_preemption, clear_preemption, maybe_drain_preemption)
from paddle_tpu.core.scope import Scope

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------------------
# duck-typed pod fixtures: a fake global (cross-process-sharded) array
# ---------------------------------------------------------------------------
class FakeVar(object):
    def __init__(self, name):
        self.name, self.persistable = name, True


class FakeProgram(object):
    _uid = 4242
    random_seed = 7

    def __init__(self, names=('w', 'b')):
        self._names = names

    def list_vars(self):
        return [FakeVar(n) for n in self._names]


class _Dev(object):
    def __init__(self, pi):
        self.process_index = pi


class _Sharding(object):
    def __init__(self, imap):
        self._imap = imap

    def devices_indices_map(self, shape):
        return self._imap


class _Shard(object):
    def __init__(self, idx, data):
        self.index, self.data = idx, data


class FakeGlobal(object):
    """Quacks like a non-fully-addressable jax.Array: enough surface for
    PodCheckpointManager's owner-deduped sharded snapshot."""
    is_fully_addressable = False

    def __init__(self, shape, shards, imap):
        self.shape = shape
        self.addressable_shards = shards
        self.sharding = _Sharding(imap)


FULL_W = np.arange(16, dtype=np.float32).reshape(4, 4)


def _imap_for():
    # w row-sharded across 2 hosts, with a replica of each row block on a
    # second device so the owner-dedup (min process_index per distinct
    # index) has real work to do
    return {_Dev(0): (slice(0, 2), slice(None)),
            _Dev(1): (slice(2, 4), slice(None)),
            _Dev(1): (slice(0, 2), slice(None))}  # noqa: F601


def scope_for(rank):
    sc = Scope()
    top = _Shard((slice(0, 2), slice(None)), FULL_W[:2])
    bot = _Shard((slice(2, 4), slice(None)), FULL_W[2:])
    # host 1 also ADDRESSES a replica of the top rows — owner-dedup must
    # skip it (process 0 owns that index), so host 1 writes exactly one
    # shard file
    shards = [top] if rank == 0 else [bot, top]
    sc.set('w', FakeGlobal((4, 4), shards, _imap_for()))
    sc.set('b', np.full((3,), 1.5, np.float32))  # host-local: rank 0 writes
    return sc


def make_pod(tmp_path, run_id='run-1', commit_timeout_s=10, **kw):
    d = str(tmp_path / 'ckpts')
    return [PodCheckpointManager(d, rank=r, num_hosts=2, run_id=run_id,
                                 commit_timeout_s=commit_timeout_s, **kw)
            for r in range(2)]


def save_pod(mgrs, prog, step):
    for r, m in enumerate(mgrs):
        m.save(prog, scope_for(r), step)
    for m in mgrs:
        m.flush()


# ---------------------------------------------------------------------------
# two-phase commit + sharded restore
# ---------------------------------------------------------------------------
def test_pod_two_phase_commit_and_sharded_restore(tmp_path):
    mgrs = make_pod(tmp_path)
    prog = FakeProgram()
    save_pod(mgrs, prog, 4)
    res = pod_latest_committed(mgrs[0].dirname, 2)
    assert res is not None
    step, path, pod, manifests = res
    assert step == 4 and sorted(pod['hosts']) == ['0', '1']
    assert pod['run_id'] == 'run-1'
    # host 1 carries ONLY its owned shard of w; the replicated host-local
    # b is written once, by the coordinator
    files1 = manifests[1]['files']
    assert list(files1) == ['w@0']
    assert 'b' in manifests[0]['files']
    # every rank assembles the same global values
    for m in mgrs:
        sc = Scope()
        info = m.restore(scope=sc)
        assert info['step'] == 4
        np.testing.assert_array_equal(np.asarray(sc.get('w')), FULL_W)
        np.testing.assert_array_equal(
            np.asarray(sc.get('b')), np.full((3,), 1.5, np.float32))
    for m in mgrs:
        m.close()


def test_partial_pod_never_restored(tmp_path):
    """A host dying between phase 1 and phase 2 leaves a partial pod dir:
    the coordinator abandons it LOUDLY after commit_timeout_s and
    restore() skips it, falling back to the older fully-committed pod."""
    mgrs = make_pod(tmp_path)
    prog = FakeProgram()
    save_pod(mgrs, prog, 4)                      # fully committed
    mgrs[0].commit_timeout_s = 0.3
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter('always')
        mgrs[0].save(prog, scope_for(0), 8)      # host 1 never writes
        mgrs[0].flush()
    assert any('ABANDONED' in str(x.message) for x in w)
    # PodCommitTimeout is no_retry: exactly one timed-out attempt
    assert mgrs[0].stats['pod_abandoned'] >= 1
    assert mgrs[0].stats['failed'] == 1
    assert mgrs[0].stats['commits'] == 1   # only the POD-committed step 4
    # the partial dir exists but is not restorable
    assert [s for s, _ in list_checkpoints(mgrs[0].dirname)] == [4, 8]
    with pytest.raises(ValueError, match='POD_COMMIT'):
        pod_verify(os.path.join(mgrs[0].dirname, 'ckpt-8'), 2)
    sc = Scope()
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter('always')
        info = mgrs[0].restore(scope=sc)
    assert info['step'] == 4
    assert any('not restorable' in str(x.message) for x in w)
    np.testing.assert_array_equal(np.asarray(sc.get('w')), FULL_W)
    for m in mgrs:
        m.close()


def test_corrupt_host_shard_falls_back(tmp_path):
    mgrs = make_pod(tmp_path)
    prog = FakeProgram()
    save_pod(mgrs, prog, 4)
    save_pod(mgrs, prog, 8)
    # flip a byte in host 1's shard of the newest pod checkpoint
    shard = os.path.join(mgrs[0].dirname, 'ckpt-8', 'host-1', 'w@0')
    raw = bytearray(open(shard, 'rb').read())
    raw[-2] ^= 0xFF
    open(shard, 'wb').write(bytes(raw))
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter('always')
        info = mgrs[1].restore(scope=Scope())
    assert info['step'] == 4
    assert any('sha256 mismatch' in str(x.message) for x in w)
    for m in mgrs:
        m.close()


def test_stale_run_id_never_stitched(tmp_path):
    """A restarted pod re-checkpointing the same step must not stitch a
    dead incarnation's stale host dir into a fresh POD_COMMIT: the
    coordinator only counts manifests carrying its own run id."""
    mgrs = make_pod(tmp_path)
    prog = FakeProgram()
    save_pod(mgrs, prog, 4)
    for m in mgrs:
        m.close()
    # incarnation 2: only rank 0 writes step 8 under a NEW run id; rank
    # 1's dir at step 8 comes from the OLD incarnation
    old = PodCheckpointManager(mgrs[0].dirname, rank=1, num_hosts=2,
                               run_id='run-1', commit_timeout_s=10)
    old.save(prog, scope_for(1), 8)
    old.flush()
    old.close()
    new0 = PodCheckpointManager(mgrs[0].dirname, rank=0, num_hosts=2,
                                run_id='run-2', commit_timeout_s=0.3)
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter('always')
        new0.save(prog, scope_for(0), 8)
        new0.flush()
    assert any('ABANDONED' in str(x.message) for x in w)
    assert not os.path.exists(os.path.join(new0.dirname, 'ckpt-8',
                                           'POD_COMMIT.json'))
    new0.close()


def test_pod_retention_counts_only_committed(tmp_path):
    """Abandoned partial pod dirs must never crowd a restorable
    checkpoint out of the keep_last_n budget: retention keeps the newest
    N POD-COMMITTED checkpoints and clears partials older than the
    newest committed one."""
    mgrs = make_pod(tmp_path, keep_last_n=2)
    prog = FakeProgram()
    save_pod(mgrs, prog, 4)
    mgrs[0].commit_timeout_s = 0.2       # partial: only rank 0 writes 8
    with warnings.catch_warnings(record=True):
        warnings.simplefilter('always')
        mgrs[0].save(prog, scope_for(0), 8)
        mgrs[0].flush()
    mgrs[0].commit_timeout_s = 10
    save_pod(mgrs, prog, 12)
    save_pod(mgrs, prog, 16)
    steps = [s for s, _ in list_checkpoints(mgrs[0].dirname)]
    assert steps == [12, 16], steps      # partial 8 + old 4 both gone
    info = mgrs[0].restore(scope=Scope())
    assert info['step'] == 16
    for m in mgrs:
        m.close()


def test_pod_restore_rejects_wrong_pod_shape(tmp_path):
    mgrs = make_pod(tmp_path)
    save_pod(mgrs, FakeProgram(), 4)
    path = os.path.join(mgrs[0].dirname, 'ckpt-4')
    with pytest.raises(ValueError, match='pod shape changed'):
        pod_verify(path, num_hosts=4)
    for m in mgrs:
        m.close()


# ---------------------------------------------------------------------------
# failure detection: barrier, heartbeats, watchdog
# ---------------------------------------------------------------------------
def test_fs_barrier_meets_and_times_out(tmp_path):
    import threading
    d = str(tmp_path)
    waited = []
    t = threading.Thread(target=lambda: waited.append(
        fs_barrier(d, 'b1', 0, 2, timeout_s=10)))
    t.start()
    time.sleep(0.1)
    fs_barrier(d, 'b1', 1, 2, timeout_s=10)
    t.join()
    assert waited and waited[0] >= 0.0
    with pytest.raises(BarrierTimeout, match=r'hosts \[1\]'):
        fs_barrier(d, 'b2', 0, 2, timeout_s=0.2)


def test_heartbeats_and_stale_hosts(tmp_path):
    d = str(tmp_path)
    write_heartbeat(d, 0, {'step': 7, 'run_id': 'r1'})
    beats = read_heartbeats(d, 2)
    assert beats[0]['step'] == 7 and beats[0]['age_s'] < 5
    # rank 1 never beat; rank 0 fresh
    assert stale_hosts(d, 2, timeout_s=5) == [1]
    # an old-incarnation heartbeat counts as dead under a new run id
    assert stale_hosts(d, 1, timeout_s=5, run_id='r2') == [0]
    # age out rank 0 by backdating the file mtime
    hb = os.path.join(d, 'heartbeats', 'host-0.json')
    past = time.time() - 60
    os.utime(hb, (past, past))
    assert stale_hosts(d, 2, timeout_s=5) == [0, 1]


def test_watchdog_detects_dead_peer(tmp_path):
    d = str(tmp_path)
    write_heartbeat(d, 1, {'run_id': 'r1'})
    hb = os.path.join(d, 'heartbeats', 'host-1.json')
    fired = []
    wd = HostWatchdog(d, rank=0, num_hosts=2, timeout_s=0.3, poll_s=0.05,
                      run_id='r1', action=lambda dead: fired.append(dead))
    with warnings.catch_warnings(record=True):
        warnings.simplefilter('always')
        wd.start()
        deadline = time.time() + 5
        past = time.time() - 10
        os.utime(hb, (past, past))      # peer stops heartbeating
        while not fired and time.time() < deadline:
            time.sleep(0.02)
        wd.stop()
    assert fired and fired[0] == {1}


def test_watchdog_clean_shutdown_grace_then_wedge_exit(tmp_path):
    """A peer that FINISHED (manager.close() writes a done tombstone) is
    a departure, not a death: no immediate fire even though its
    heartbeat goes stale — the first host to finish must not hard-exit
    survivors mid final write. But a pod missing a member can never
    complete another collective, so a host STILL running timeout_s after
    the departure is wedged (staggered preemption) and exits through the
    same bounded path."""
    d = str(tmp_path)
    write_heartbeat(d, 1, {'run_id': 'r1'})
    fired = []
    wd = HostWatchdog(d, rank=0, num_hosts=2, timeout_s=0.6, poll_s=0.05,
                      run_id='r1', action=lambda dead: fired.append(dead))
    with warnings.catch_warnings(record=True):
        warnings.simplefilter('always')
        wd.start()
        time.sleep(0.15)
        write_heartbeat(d, 1, {'run_id': 'r1', 'done': True})
        hb = os.path.join(d, 'heartbeats', 'host-1.json')
        past = time.time() - 10
        os.utime(hb, (past, past))      # stale tombstone: departure
        time.sleep(0.25)
        assert not fired, 'fired inside the departure grace: %r' % fired
        deadline = time.time() + 5
        while not fired and time.time() < deadline:
            time.sleep(0.05)
        wd.stop()
    assert fired and fired[0] == {1}, 'wedge after departure not detected'


def test_pod_manager_close_writes_done_tombstone(tmp_path):
    mgr = PodCheckpointManager(str(tmp_path / 'ck'), rank=0, num_hosts=2,
                               run_id='r1', heartbeat_interval_s=0.05)
    mgr.close()
    beats = read_heartbeats(mgr.dirname, 2)
    assert beats[0].get('done') is True


def test_pod_manager_requires_run_id(tmp_path, monkeypatch):
    """Without an incarnation token the phase-2 stale filter has nothing
    to compare — a bare pod could stitch a corpse's manifest. The
    constructor refuses instead of silently disabling the guard."""
    monkeypatch.delenv('PTPU_POD_RUN_ID', raising=False)
    with pytest.raises(ValueError, match='run_id'):
        PodCheckpointManager(str(tmp_path / 'ck'), rank=0, num_hosts=2)
    # and wall-clock policies are rejected: they desync the snapshot
    # step across hosts, abandoning every pod checkpoint
    with pytest.raises(ValueError, match='every_seconds'):
        PodCheckpointManager(str(tmp_path / 'ck'), rank=0, num_hosts=2,
                             run_id='r1', every_seconds=30)


def test_pod_heartbeat_feeds_profiler_table(tmp_path, capsys):
    from paddle_tpu import profiler
    mgr = PodCheckpointManager(str(tmp_path / 'ck'), rank=0, num_hosts=2,
                               run_id='r1', heartbeat_interval_s=0.05)
    try:
        deadline = time.time() + 5
        while not read_heartbeats(mgr.dirname, 2) and \
                time.time() < deadline:
            time.sleep(0.02)
        out = profiler.pod_report()
        text = capsys.readouterr().out
        src = [k for k in out if k.startswith('pod@')]
        assert src, out
        assert 0 in out[src[0]]['hosts']
        assert 'hb-age(s)' in text and 'ckpt%' in text
    finally:
        mgr.close()
    # close unregisters the source
    assert not [k for k in profiler.pod_report() if k.startswith('pod@')]


# ---------------------------------------------------------------------------
# graceful preemption
# ---------------------------------------------------------------------------
def test_preemption_drains_final_checkpoint(tmp_path):
    clear_preemption()
    mgr = CheckpointManager(str(tmp_path / 'ck'), every_steps=1000)
    prog = FakeProgram(names=('b',))
    sc = Scope()
    sc.set('b', np.arange(4, dtype=np.float32))
    assert maybe_drain_preemption(mgr, None, prog, sc, 3) is False
    request_preemption()
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter('always')
        with pytest.raises(SystemExit) as e:
            maybe_drain_preemption(mgr, None, prog, sc, 3)
    assert e.value.code == 0
    assert any('draining a final checkpoint' in str(x.message) for x in w)
    res = pod_latest_committed(str(tmp_path / 'ck'))  # no POD_COMMIT here
    assert res is None
    from paddle_tpu.core.checkpoint import latest_committed
    got = latest_committed(str(tmp_path / 'ck'))
    assert got is not None and got[0] == 3
    clear_preemption()


def test_sigterm_preemption_resume_parity(tmp_path):
    """SIGTERM mid-training -> exit 0 with a drained final checkpoint at
    a step boundary; the next incarnation resumes and the combined run
    bit-matches an uninterrupted reference."""
    worker = os.path.join(REPO, 'tests', 'checkpoint_kill_worker.py')
    ckpt = str(tmp_path / 'ck')
    env = dict(os.environ, PTPU_PREEMPTIBLE='1')

    ref = str(tmp_path / 'ref.txt')
    r = subprocess.run([sys.executable, worker, '-', ref, '24', '2', '4'],
                       capture_output=True, text=True, cwd=REPO,
                       timeout=240)
    assert r.returncode == 0, r.stderr[-2000:]

    out1 = str(tmp_path / 'run1.txt')
    p = subprocess.Popen([sys.executable, worker, ckpt, out1, '4000', '2',
                          '4'], env=env, cwd=REPO,
                         stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                         text=True)
    # Wait until the worker has provably trained (>=5 logged steps)
    # BEFORE delivering SIGTERM: a worker still compiling has no signal
    # handler installed yet and dies rc!=0, which is a test artifact,
    # not a preemption bug. If the bar is never reached, fail loudly
    # with the worker's stderr instead of SIGTERMing a cold process.
    deadline = time.time() + 300
    progressed = False
    while time.time() < deadline:
        if os.path.exists(out1) and \
                len(open(out1).read().splitlines()) >= 5:
            progressed = True
            break
        if p.poll() is not None:
            _out, err = p.communicate(timeout=30)
            pytest.fail('worker exited rc=%s before writing 5 steps:\n%s'
                        % (p.returncode, err[-2000:]))
        time.sleep(0.05)
    if not progressed:
        p.kill()
        _out, err = p.communicate(timeout=30)
        pytest.fail('worker wrote <5 steps in 300s (machine overloaded '
                    'or training wedged):\n%s' % err[-2000:])
    p.send_signal(signal.SIGTERM)
    _out, err = p.communicate(timeout=240)
    assert p.returncode == 0, 'preempted worker must exit 0: rc=%s\n%s' \
        % (p.returncode, err[-2000:])
    got = pod_latest_committed(ckpt)
    assert got is None            # single-host manager: no POD_COMMIT
    from paddle_tpu.core.checkpoint import latest_committed
    final = latest_committed(ckpt)
    assert final is not None, 'no drained checkpoint on disk'

    out2 = str(tmp_path / 'run2.txt')
    r = subprocess.run([sys.executable, worker, ckpt, out2, '24', '2',
                        '4'], capture_output=True, text=True, cwd=REPO,
                       timeout=240)
    assert r.returncode == 0, r.stderr[-2000:]

    def read(path):
        resume, losses, sha = None, {}, None
        for line in open(path):
            parts = line.split()
            if parts[0] == 'RESUME':
                resume = int(parts[1])
            elif parts[0] == 'DONE':
                sha = parts[1]
            else:
                losses[int(parts[0])] = float(parts[1])
        return resume, losses, sha

    _, ref_losses, ref_sha = read(ref)
    resume2, losses2, sha2 = read(out2)
    assert resume2 > 0, 'second incarnation did not resume'
    _, losses1, _ = read(out1)
    for idx, v in list(losses1.items()) + list(losses2.items()):
        if idx in ref_losses:
            assert v == ref_losses[idx], 'step %d diverged' % idx
    assert sha2 == ref_sha


# ---------------------------------------------------------------------------
# elastic lease board: stale-heartbeat reclaim
# ---------------------------------------------------------------------------
def test_stale_holder_leases_reclaimed(tmp_path):
    from paddle_tpu.reader.elastic import TaskService
    lease_dir = str(tmp_path / 'leases')
    tasks = ['t%d' % i for i in range(4)]
    dead = TaskService(tasks, lease_dir=lease_dir, holder_id='host-9',
                       holder_timeout_s=5.0, lease_timeout_s=3600)
    a = dead.get_task()
    b = dead.get_task()
    assert a and b
    board = os.path.join(lease_dir, 'host-9.leases.json')
    assert sorted(json.load(open(board))['leases']) == sorted([a[0], b[0]])
    # host-9 dies (stops heartbeating): stop its liveness thread — which
    # refreshes the board mtime on its own clock, independent of lease
    # activity — then backdate the file
    dead._hb_stop.set()
    dead._hb_thread.join(timeout=5)
    past = time.time() - 60
    os.utime(board, (past, past))
    survivor = TaskService(tasks, lease_dir=lease_dir, holder_id='host-0',
                           holder_timeout_s=5.0, lease_timeout_s=3600)
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter('always')
        got = survivor.reclaim_stale_leases()
    assert sorted(got) == sorted([a[0], b[0]])
    assert survivor.reclaimed == 2
    msgs = [str(x.message) for x in w]
    assert any("'host-9'" in m and 'DEAD' in m for m in msgs), msgs
    # reclaimed tasks dispatch FIRST (resume order), board entry retired
    assert survivor.get_task()[0] in (a[0], b[0])
    assert not os.path.exists(board)
    assert os.path.exists(board + '.reclaimed')
    # second scan is a no-op (first survivor won)
    assert survivor.reclaim_stale_leases() == []
    dead.close()
    survivor.close()


def test_fresh_holder_not_reclaimed(tmp_path):
    from paddle_tpu.reader.elastic import TaskService
    lease_dir = str(tmp_path / 'leases')
    tasks = ['a', 'b']
    alive = TaskService(tasks, lease_dir=lease_dir, holder_id='h1',
                        holder_timeout_s=30.0)
    lease = alive.get_task()
    assert lease
    other = TaskService(tasks, lease_dir=lease_dir, holder_id='h2',
                        holder_timeout_s=30.0)
    assert other.reclaim_stale_leases() == []
    # progress reports refresh the heartbeat mtime
    before = os.path.getmtime(os.path.join(lease_dir, 'h1.leases.json'))
    time.sleep(0.05)
    alive.report_progress(lease[0], 1, gen=lease.gen)
    assert os.path.getmtime(os.path.join(lease_dir,
                                         'h1.leases.json')) >= before
    alive.close()
    other.close()


# ---------------------------------------------------------------------------
# the real thing: 2-process composed-mesh kill-one-host + full-pod resume
# ---------------------------------------------------------------------------
def test_pod_kill_one_host_resume_parity(tmp_path):
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        'ptpu_chaos_t', os.path.join(REPO, 'tools', 'chaos.py'))
    chaos = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(chaos)

    work = str(tmp_path)
    cache = os.path.join(work, 'compile-cache')
    ckpt = os.path.join(work, 'ckpts')
    outs = lambda tag: [os.path.join(work, '%s-r%d.txt' % (tag, r))  # noqa: E731,E501
                        for r in range(2)]

    # uninterrupted reference pod
    ref_outs = outs('ref')
    res = chaos.run_pod(os.path.join(work, 'ref-ck'), ref_outs, total=10,
                        every=4, cache_dir=cache, timeout=280)
    assert all(rc == 0 for rc, _ in res), \
        '\n'.join(e[-1500:] for _, e in res)
    refs = [chaos.read_out(p) for p in ref_outs]
    assert refs[0][1] == refs[1][1], 'replicated losses differ across hosts'
    assert len(refs[0][1]) == 10
    # checkpoint stall < 1% of run time (ISSUE 10 acceptance)
    for p in ref_outs:
        stall = [float(l.split()[1]) for l in open(p)
                 if l.startswith('STALL')]
        assert stall and stall[0] < 1.0, stall

    # kill host 1 at step 8; survivor must exit in bounded time
    res = chaos.run_pod(ckpt, outs('kill'), total=10, every=4,
                        kill_rank=1, kill_at=8, cache_dir=cache,
                        timeout=280)
    assert res[1][0] == -signal.SIGKILL
    assert not any('WEDGED' in err for _, err in res)
    kills = [chaos.read_out(p) for p in outs('kill')]

    # full-pod restart: resumes from the newest POD-committed checkpoint
    fin_outs = outs('fin')
    res = chaos.run_pod(ckpt, fin_outs, total=10, every=4,
                        cache_dir=cache, timeout=280)
    assert all(rc == 0 for rc, _ in res), \
        '\n'.join(e[-1500:] for _, e in res)
    fins = [chaos.read_out(p) for p in fin_outs]
    assert fins[0][0] >= 4, 'did not resume from a pod checkpoint'
    for r in range(2):
        for idx, v in list(kills[r][1].items()) + list(fins[r][1].items()):
            assert v == refs[r][1].get(idx), \
                'host %d step %d diverged' % (r, idx)
        assert fins[r][2] == refs[r][2], 'host %d params digest' % r
