"""Subprocess worker for test_decode_serving.py and decode_serve_smoke.py:
one decode-serving replica "cold start". Loads a continuous-decode
artifact by FILE PATH (the framework must never load into a serving
process), decodes a fixed set of prompts greedily plus one beam request,
and prints the results and the number of XLA backend compiles as a JSON
line:

    python decode_serve_worker.py ARTIFACT_DIR SEED N_PROMPTS MAX_NEW

With AOT sidecars present (export_decode default / cache_ctl prewarm),
compiles must be 0 — the ISSUE 8 warm fresh-process acceptance bar.
"""
import json
import os
import sys


def main():
    artifact, seed, n, max_new = (sys.argv[1], int(sys.argv[2]),
                                  int(sys.argv[3]), int(sys.argv[4]))
    os.environ.setdefault('JAX_PLATFORMS', 'cpu')
    os.environ.setdefault('PTPU_PLATFORM', 'cpu')
    import numpy as np
    from jax import monitoring

    compiles = [0]

    def _listener(event, secs, **kw):
        if event == '/jax/core/compile/backend_compile_duration':
            compiles[0] += 1

    monitoring.register_event_duration_secs_listener(_listener)

    here = os.path.dirname(os.path.abspath(__file__))
    sys.path.insert(0, os.path.join(os.path.dirname(here), 'paddle_tpu',
                                    'inference'))
    import decoding

    with decoding.DecodingPredictor(artifact) as pred:
        vocab = pred._vocab
        big = max(pred.prompt_buckets)
        rng = np.random.RandomState(seed)
        prompts = [rng.randint(2, vocab, rng.randint(2, big + 1))
                   for _ in range(n)]
        streams = [pred.submit(p, max_new_tokens=max_new) for p in prompts]
        greedy = [s.result(120) for s in streams]
        beam_ids, beam_scores = pred.generate(prompts[0],
                                              max_new_tokens=max_new,
                                              beam=min(3, pred.max_slots))
        snap = pred.stats.snapshot()
    assert 'paddle_tpu' not in sys.modules, \
        'the framework leaked into the serving process'
    print('DECODE %s' % json.dumps({
        'compiles': compiles[0], 'greedy': greedy,
        'beam_ids': np.asarray(beam_ids).tolist(),
        'beam_scores': np.asarray(beam_scores).tolist(),
        'tokens': snap['tokens'], 'steps': snap['steps']}))
    print('DECODE_OK')


if __name__ == '__main__':
    main()
