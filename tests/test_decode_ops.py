"""CTC / CRF / edit distance / chunk eval / beam search
(reference coverage model: test_warpctc_op.py, test_edit_distance_op.py,
test_linear_chain_crf_op.py, test_crf_decoding_op.py, test_chunk_eval_op.py,
test_beam_search_op.py, book test_machine_translation.py decode path,
CRNN-CTC OCR model).
"""
import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu.core.lod import create_lod_array


def _lod(data, lens):
    return create_lod_array(np.asarray(data), recursive_seq_lens=[list(lens)])


def _run(fetch, feed=None, startup=True):
    exe = fluid.Executor(fluid.CPUPlace())
    if startup:
        exe.run(fluid.default_startup_program())
    return exe.run(feed=feed or {}, fetch_list=fetch)


# ---------------------------------------------------------------------------
# CTC
# ---------------------------------------------------------------------------

def test_warpctc_loss_positive_and_differentiable():
    layers = fluid.layers
    C = 6  # classes incl. blank 0
    logits = fluid.layers.data(name='lg', shape=[C], dtype='float32',
                               lod_level=1)
    label = fluid.layers.data(name='lb', shape=[1], dtype='int64', lod_level=1)
    loss = layers.warpctc(input=logits, label=label, blank=0)
    avg = layers.mean(loss)
    fluid.backward.append_backward(avg)

    rng = np.random.RandomState(0)
    t_lens, l_lens = [5, 7], [2, 3]
    lg = _lod(rng.randn(sum(t_lens), C).astype(np.float32), t_lens)
    lb = _lod(rng.randint(1, C, (sum(l_lens), 1)).astype(np.int64), l_lens)
    out, = _run([loss], feed={'lg': lg, 'lb': lb}, startup=False)
    assert out.shape == (2, 1)
    assert (out > 0).all()


def test_ctc_pipeline_trains_ocr_style():
    """OCR CRNN+CTC milestone: conv features → gru → ctc loss decreases,
    greedy decode + edit distance run end-to-end."""
    layers = fluid.layers
    C = 5   # 4 symbols + blank
    T = 8
    feat = layers.data(name='f', shape=[16], dtype='float32', lod_level=1)
    label = layers.data(name='y', shape=[1], dtype='int64', lod_level=1)
    h = layers.fc(input=feat, size=32, act='relu')
    logits = layers.fc(input=h, size=C)
    loss = layers.mean(layers.warpctc(input=logits, label=label, blank=0))
    fluid.optimizer.Adam(learning_rate=0.02).minimize(loss)

    decoded = layers.ctc_greedy_decoder(layers.softmax(logits), blank=0)
    dist, seq_num = layers.edit_distance(decoded, label, normalized=False)

    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    rng = np.random.RandomState(1)
    t_lens = [T, T]
    l_lens = [3, 2]
    feats = rng.randn(sum(t_lens), 16).astype(np.float32)
    labs = rng.randint(1, C, (sum(l_lens), 1)).astype(np.int64)
    feed = {'f': _lod(feats, t_lens), 'y': _lod(labs, l_lens)}
    losses = [float(exe.run(feed=feed, fetch_list=[loss])[0][0])
              for _ in range(60)]
    assert losses[-1] < 0.5 * losses[0], losses[::12]
    d, n = exe.run(feed=feed, fetch_list=[dist, seq_num])
    assert n[0] == 2
    # after fitting two fixed sequences the greedy decode should be close
    assert d.sum() <= 2.0, d


def test_edit_distance_known_values():
    layers = fluid.layers
    hyp = layers.data(name='h', shape=[1], dtype='int64', lod_level=1)
    ref = layers.data(name='r', shape=[1], dtype='int64', lod_level=1)
    dist, _ = layers.edit_distance(hyp, ref, normalized=False)
    # "kitten"->"sitting" famous distance 3 (mapped to ints), plus equal pair
    k = [1, 2, 3, 3, 4, 5]          # kitten
    s = [6, 2, 3, 3, 2, 5, 7]       # sitting
    h_data = np.array(k + [1, 2], np.int64).reshape(-1, 1)
    r_data = np.array(s + [1, 2], np.int64).reshape(-1, 1)
    out, = _run([dist], feed={'h': _lod(h_data, [6, 2]),
                              'r': _lod(r_data, [7, 2])}, startup=False)
    np.testing.assert_allclose(out.reshape(-1), [3.0, 0.0])


def test_edit_distance_with_neg_padding():
    """-1 padding (greedy decoder convention) is ignored."""
    layers = fluid.layers
    hyp = layers.data(name='h', shape=[1], dtype='int64', lod_level=1)
    ref = layers.data(name='r', shape=[1], dtype='int64', lod_level=1)
    dist, _ = layers.edit_distance(hyp, ref, normalized=False)
    h_data = np.array([1, 2, -1, -1], np.int64).reshape(-1, 1)
    r_data = np.array([1, 2, 3], np.int64).reshape(-1, 1)
    out, = _run([dist], feed={'h': _lod(h_data, [4]),
                              'r': _lod(r_data, [3])}, startup=False)
    assert out[0, 0] == pytest.approx(1.0)


# ---------------------------------------------------------------------------
# CRF
# ---------------------------------------------------------------------------

def _brute_force_crf_nll(E, w, y):
    """Enumerate all paths for one sequence: -log p(y)."""
    import itertools
    start, end, A = w[0], w[1], w[2:]
    T, D = E.shape

    def score(path):
        s = start[path[0]] + E[0, path[0]]
        for t in range(1, T):
            s += A[path[t - 1], path[t]] + E[t, path[t]]
        return s + end[path[-1]]

    logZ = np.logaddexp.reduce(
        [score(p) for p in itertools.product(range(D), repeat=T)])
    return logZ - score(y)


def test_linear_chain_crf_matches_brute_force():
    layers = fluid.layers
    D = 3
    em = layers.data(name='e', shape=[D], dtype='float32', lod_level=1)
    lb = layers.data(name='l', shape=[1], dtype='int64', lod_level=1)
    nll = layers.linear_chain_crf(
        input=em, label=lb,
        param_attr=fluid.ParamAttr(name='crfw_test'))

    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    rng = np.random.RandomState(2)
    lens = [4, 2]
    E = rng.randn(sum(lens), D).astype(np.float32)
    y = rng.randint(0, D, (sum(lens), 1)).astype(np.int64)
    out, = exe.run(feed={'e': _lod(E, lens), 'l': _lod(y, lens)},
                   fetch_list=[nll])
    w = np.asarray(fluid.global_scope().get('crfw_test'))
    exp0 = _brute_force_crf_nll(E[:4], w, y[:4, 0])
    exp1 = _brute_force_crf_nll(E[4:], w, y[4:, 0])
    np.testing.assert_allclose(out.reshape(-1), [exp0, exp1], rtol=1e-4)


def test_crf_train_and_decode():
    """label_semantic_roles-style slice: crf loss decreases; decoding with
    label yields the 0/1 correctness vector feeding chunk_eval."""
    layers = fluid.layers
    D = 4
    feat = layers.data(name='x', shape=[8], dtype='float32', lod_level=1)
    lb = layers.data(name='l', shape=[1], dtype='int64', lod_level=1)
    em = layers.fc(input=feat, size=D)
    nll = layers.linear_chain_crf(input=em, label=lb,
                                  param_attr=fluid.ParamAttr(name='crfw'))
    loss = layers.mean(nll)
    fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
    path = layers.crf_decoding(input=em,
                               param_attr=fluid.ParamAttr(name='crfw'))
    correct = layers.crf_decoding(input=em, label=lb,
                                  param_attr=fluid.ParamAttr(name='crfw'))

    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    rng = np.random.RandomState(3)
    lens = [5, 3]
    X = rng.randn(sum(lens), 8).astype(np.float32)
    y = rng.randint(0, D, (sum(lens), 1)).astype(np.int64)
    feed = {'x': _lod(X, lens), 'l': _lod(y, lens)}
    losses = [float(exe.run(feed=feed, fetch_list=[loss])[0][0])
              for _ in range(60)]
    assert losses[-1] < losses[0]
    p, c = exe.run(feed=feed, fetch_list=[path, correct])
    assert p.shape == (sum(lens), 1)
    assert set(np.unique(c)) <= {0, 1}
    # after fitting, viterbi should recover the training labels
    assert c.mean() > 0.8


def test_chunk_eval_iob():
    layers = fluid.layers
    inf = layers.data(name='i', shape=[1], dtype='int64', lod_level=1)
    lab = layers.data(name='l', shape=[1], dtype='int64', lod_level=1)
    prec, rec, f1, n_inf, n_lab, n_cor = layers.chunk_eval(
        input=inf, label=lab, chunk_scheme='IOB', num_chunk_types=2)
    # tags: B-0=0 I-0=1 B-1=2 I-1=3; seq: [B0 I0 B1 I1 B0]
    gold = np.array([0, 1, 2, 3, 0], np.int64).reshape(-1, 1)
    # prediction: first chunk right, second wrong type, third right
    pred = np.array([0, 1, 0, 1, 0], np.int64).reshape(-1, 1)
    outs = _run([prec, rec, f1, n_inf, n_lab, n_cor],
                feed={'i': _lod(pred, [5]), 'l': _lod(gold, [5])},
                startup=False)
    assert outs[3][0] == 3 and outs[4][0] == 3
    assert outs[5][0] == 2
    assert outs[0][0] == pytest.approx(2 / 3)
    assert outs[1][0] == pytest.approx(2 / 3)


def test_chunk_eval_iob_other_tag():
    """O tags (value num_chunk_types * num_tag_types) are not chunks
    (ref chunk_eval_op.h:145 other_chunk_type) — the canonical NER case."""
    layers = fluid.layers
    inf = layers.data(name='io', shape=[1], dtype='int64', lod_level=1)
    lab = layers.data(name='lo', shape=[1], dtype='int64', lod_level=1)
    prec, rec, f1, n_inf, n_lab, n_cor = layers.chunk_eval(
        input=inf, label=lab, chunk_scheme='IOB', num_chunk_types=2)
    # tags: B-0=0 I-0=1 B-1=2 I-1=3 O=4; gold: [B0 I0 O O B1]
    gold = np.array([0, 1, 4, 4, 2], np.int64).reshape(-1, 1)
    # prediction: first chunk right; predicts O where gold has B1
    pred = np.array([0, 1, 4, 4, 4], np.int64).reshape(-1, 1)
    outs = _run([prec, rec, f1, n_inf, n_lab, n_cor],
                feed={'io': _lod(pred, [5]), 'lo': _lod(gold, [5])},
                startup=False)
    # O runs must not inflate the chunk counters
    assert outs[3][0] == 1   # inferred chunks: just [B0 I0]
    assert outs[4][0] == 2   # label chunks: [B0 I0], [B1]
    assert outs[5][0] == 1
    assert outs[0][0] == pytest.approx(1.0)
    assert outs[1][0] == pytest.approx(0.5)


def test_chunk_eval_plain_other_tag():
    layers = fluid.layers
    inf = layers.data(name='ip', shape=[1], dtype='int64', lod_level=1)
    lab = layers.data(name='lp', shape=[1], dtype='int64', lod_level=1)
    prec, rec, f1, n_inf, n_lab, n_cor = layers.chunk_eval(
        input=inf, label=lab, chunk_scheme='plain', num_chunk_types=2)
    # plain scheme: tag == chunk type, tag 2 (num_chunk_types) is Other
    gold = np.array([0, 0, 2, 1], np.int64).reshape(-1, 1)
    pred = np.array([0, 0, 2, 2], np.int64).reshape(-1, 1)
    outs = _run([prec, rec, f1, n_inf, n_lab, n_cor],
                feed={'ip': _lod(pred, [4]), 'lp': _lod(gold, [4])},
                startup=False)
    assert outs[3][0] == 1   # [0,0] only — the 2-run is Other
    assert outs[4][0] == 2   # [0,0] and [1]
    assert outs[5][0] == 1


def _oracle_chunks(tags, scheme, num_chunk_types):
    """Independent chunk extractor (a forward state machine, not the
    op's boundary predicates): {(start, end, chunk_type)} spans per the
    reference tag semantics — B begins, E ends, S is a singleton, I
    continues a same-type chunk or opens one when none is open."""
    ntt = 4 if scheme == 'IOBES' else 2
    roles = {'IOB': 'BI', 'IOE': 'IE', 'IOBES': 'BIES'}[scheme]
    chunks, state = [], [None, None]   # [start index, chunk type]

    def close(end):
        if state[0] is not None:
            chunks.append((state[0], end, state[1]))
        state[0] = state[1] = None

    for i, t in enumerate(tags):
        ct, role = t // ntt, roles[t % ntt]
        if ct == num_chunk_types:      # the Other tag: never a chunk
            close(i - 1)
            continue
        if role == 'S':
            close(i - 1)
            chunks.append((i, i, ct))
            continue
        if role == 'B':
            close(i - 1)
            state[:] = [i, ct]
            continue
        if state[0] is None or state[1] != ct:   # I/E with no open chunk
            close(i - 1)
            state[:] = [i, ct]
        if role == 'E':
            close(i)
    close(len(tags) - 1)
    return set(chunks)


@pytest.mark.parametrize('scheme', ['IOB', 'IOE', 'IOBES'])
def test_chunk_eval_schemes_vs_oracle(scheme):
    """Randomized numeric check of every positional scheme against the
    pure-python span extractor: chunk counts and correct-chunk counts
    must match exactly, per sequence boundaries (lod)."""
    layers = fluid.layers
    nct = 3
    ntt = 4 if scheme == 'IOBES' else 2
    inf = layers.data(name='i_' + scheme, shape=[1], dtype='int64',
                      lod_level=1)
    lab = layers.data(name='l_' + scheme, shape=[1], dtype='int64',
                      lod_level=1)
    prec, rec, f1, n_inf, n_lab, n_cor = layers.chunk_eval(
        input=inf, label=lab, chunk_scheme=scheme, num_chunk_types=nct)
    rng = np.random.RandomState(hash(scheme) % 2 ** 31)
    lens = [7, 5, 9]
    # tag vocabulary includes the Other tag (value nct * ntt)
    gold = rng.randint(0, nct * ntt + 1, (sum(lens), 1)).astype(np.int64)
    pred = rng.randint(0, nct * ntt + 1, (sum(lens), 1)).astype(np.int64)
    outs = _run([prec, rec, f1, n_inf, n_lab, n_cor],
                feed={'i_' + scheme: _lod(pred, lens),
                      'l_' + scheme: _lod(gold, lens)},
                startup=False)
    want_inf = want_lab = want_cor = 0
    off = 0
    for L in lens:
        pc = _oracle_chunks(pred[off:off + L, 0], scheme, nct)
        gc = _oracle_chunks(gold[off:off + L, 0], scheme, nct)
        want_inf += len(pc)
        want_lab += len(gc)
        want_cor += len(pc & gc)
        off += L
    assert outs[3][0] == want_inf
    assert outs[4][0] == want_lab
    assert outs[5][0] == want_cor
    assert outs[0][0] == pytest.approx(
        want_cor / want_inf if want_inf else 0.0)
    assert outs[1][0] == pytest.approx(
        want_cor / want_lab if want_lab else 0.0)


def test_chunk_eval_ioe_iobes_exact():
    """Hand-checked IOE and IOBES cases (ref chunk_eval_op.h tag tables:
    IOE I=0 E=1; IOBES B=0 I=1 E=2 S=3)."""
    layers = fluid.layers
    inf = layers.data(name='ix', shape=[1], dtype='int64', lod_level=1)
    lab = layers.data(name='lx', shape=[1], dtype='int64', lod_level=1)
    # IOE, 2 types: I-0=0 E-0=1 I-1=2 E-1=3 O=4
    outs_ioe = layers.chunk_eval(input=inf, label=lab, chunk_scheme='IOE',
                                 num_chunk_types=2)
    # gold: [I0 E0 | I1 E1 | O]  → chunks (0,1,t0), (2,3,t1)
    gold = np.array([0, 1, 2, 3, 4], np.int64).reshape(-1, 1)
    # pred: [I0 E0 | E1 | O O]   → chunks (0,1,t0), (2,2,t1)
    pred = np.array([0, 1, 3, 4, 4], np.int64).reshape(-1, 1)
    outs = _run(list(outs_ioe), feed={'ix': _lod(pred, [5]),
                                      'lx': _lod(gold, [5])},
                startup=False)
    assert outs[3][0] == 2 and outs[4][0] == 2 and outs[5][0] == 1

    inf2 = layers.data(name='iy', shape=[1], dtype='int64', lod_level=1)
    lab2 = layers.data(name='ly', shape=[1], dtype='int64', lod_level=1)
    # IOBES, 1 type: B=0 I=1 E=2 S=3 O=4
    outs_iobes = layers.chunk_eval(input=inf2, label=lab2,
                                   chunk_scheme='IOBES', num_chunk_types=1)
    # gold: [B I E | S | O] → chunks (0,2), (3,3)
    gold2 = np.array([0, 1, 2, 3, 4], np.int64).reshape(-1, 1)
    # pred: [B I E | O | S] → chunks (0,2), (4,4)
    pred2 = np.array([0, 1, 2, 4, 3], np.int64).reshape(-1, 1)
    outs2 = _run(list(outs_iobes),
                 feed={'ix': _lod(pred, [5]), 'lx': _lod(gold, [5]),
                       'iy': _lod(pred2, [5]), 'ly': _lod(gold2, [5])},
                 startup=False)
    assert outs2[3][0] == 2 and outs2[4][0] == 2 and outs2[5][0] == 1


# ---------------------------------------------------------------------------
# beam search
# ---------------------------------------------------------------------------

def test_beam_search_step_selects_topk():
    layers = fluid.layers
    K, C = 2, 3   # beam 2, 3 candidates/beam; one source sentence
    pre_ids = layers.data(name='pi', shape=[K, 1], dtype='int64',
                          append_batch_size=False)
    pre_scores = layers.data(name='ps', shape=[K, 1], dtype='float32',
                             append_batch_size=False)
    ids = layers.data(name='ids', shape=[K, C], dtype='int64',
                      append_batch_size=False)
    scores = layers.data(name='sc', shape=[K, C], dtype='float32',
                         append_batch_size=False)
    sel_ids, sel_scores, parent = layers.beam_search(
        pre_ids, pre_scores, ids, scores, beam_size=K, end_id=0,
        return_parent_idx=True)
    feed = {
        'pi': np.array([[5], [6]], np.int64),
        'ps': np.array([[0.1], [0.2]], np.float32),
        'ids': np.array([[11, 12, 13], [21, 22, 23]], np.int64),
        'sc': np.array([[0.9, 0.5, 0.1], [0.8, 0.7, 0.2]], np.float32),
    }
    si, ss, pa = _run([sel_ids, sel_scores, parent], feed=feed, startup=False)
    np.testing.assert_array_equal(si.reshape(-1), [11, 21])
    np.testing.assert_allclose(ss.reshape(-1), [0.9, 0.8])
    np.testing.assert_array_equal(pa.reshape(-1), [0, 1])


def test_beam_search_frozen_finished_beam():
    layers = fluid.layers
    K, C = 2, 2
    pre_ids = layers.data(name='pi', shape=[K, 1], dtype='int64',
                          append_batch_size=False)
    pre_scores = layers.data(name='ps', shape=[K, 1], dtype='float32',
                             append_batch_size=False)
    ids = layers.data(name='ids', shape=[K, C], dtype='int64',
                      append_batch_size=False)
    scores = layers.data(name='sc', shape=[K, C], dtype='float32',
                         append_batch_size=False)
    sel_ids, sel_scores = layers.beam_search(
        pre_ids, pre_scores, ids, scores, beam_size=K, end_id=0)
    feed = {
        'pi': np.array([[0], [6]], np.int64),      # beam 0 finished
        'ps': np.array([[2.0], [0.2]], np.float32),
        'ids': np.array([[11, 12], [21, 22]], np.int64),
        'sc': np.array([[9.0, 8.0], [1.0, 0.5]], np.float32),
    }
    si, ss = _run([sel_ids, sel_scores], feed=feed, startup=False)
    # finished beam contributes ONLY (end_id, 2.0); its 9.0/8.0 are ignored
    assert 0 in si.reshape(-1)
    assert 2.0 in ss.reshape(-1)
    assert 9.0 not in ss.reshape(-1)


def test_beam_search_decode_backtrace():
    """While-loop greedy-beam NMT decode: 2 beams over a toy 4-token vocab,
    decode 3 steps, backtrace must follow parent pointers."""
    layers = fluid.layers
    K, V, T = 2, 4, 3
    # logits per step are fed as data for determinism: [T, K, V]
    step_scores = layers.data(name='sc', shape=[T, K, V], dtype='float32',
                              append_batch_size=False)

    i = layers.fill_constant([1], 'int64', 0)
    limit = layers.fill_constant([1], 'int64', T)
    init_ids = layers.fill_constant([K, 1], 'int64', 1)     # <s>
    init_scores = layers.fill_constant([K, 1], 'float32', 0.0)
    ids_arr = layers.array_write(init_ids, i)
    scores_arr = layers.array_write(init_scores, i)
    parents_arr = layers.array_write(
        layers.fill_constant([K], 'int32', 0), i)
    layers.increment(i, 1)
    cond = layers.less_than(i, limit)
    w = layers.While(cond)
    with w.block():
        t = layers.elementwise_sub(i, layers.fill_constant([1], 'int64', 1))
        pre_ids = layers.array_read(ids_arr, t)
        pre_scores = layers.array_read(scores_arr, t)
        # this step's scores [K, V], accumulated onto the beam scores
        acc = layers.elementwise_add(
            layers.reshape(layers.gather(step_scores, t), [K, V]),
            pre_scores)
        sel_ids, sel_scores, parent = layers.beam_search(
            pre_ids, pre_scores, None, acc, beam_size=K, end_id=0,
            return_parent_idx=True)
        layers.array_write(sel_ids, i, array=ids_arr)
        layers.array_write(sel_scores, i, array=scores_arr)
        layers.array_write(parent, i, array=parents_arr)
        layers.increment(i, 1)
        layers.less_than(i, limit, cond=cond)
    sent_ids, sent_scores = layers.beam_search_decode(
        ids_arr, scores_arr, beam_size=K, end_id=0, parents=parents_arr)

    rng = np.random.RandomState(4)
    sc = rng.randn(T, K, V).astype(np.float32)
    out_ids, out_scores = _run([sent_ids, sent_scores],
                               feed={'sc': sc}, startup=False)
    ids_mat = out_ids.reshape(K, -1)
    scores_mat = out_scores.reshape(K, -1)
    assert ids_mat.shape[1] >= T
    assert ((ids_mat >= 0) & (ids_mat < V)).all()

    # numpy reference: fixed-K beam over the same scores. Loop iteration i
    # gathers sc[i-1], so step slots 1..T-1 consume sc[0..T-2].
    rows_hist = [[(1, 0)] * K]  # (token, parent) per step
    cur_scores = np.zeros(K)
    cur_ids = np.full(K, 1)
    for t in range(1, T):
        cand = cur_scores[:, None] + sc[t - 1]            # [K, V]
        for k in range(K):                                 # freeze finished
            if cur_ids[k] == 0:
                cand[k] = -1e9
                cand[k, 0] = cur_scores[k]
        flat = cand.reshape(-1)
        top = np.argsort(-flat, kind='stable')[:K]
        rows_hist.append([(int(i % V), int(i // V)) for i in top])
        cur_scores = flat[top]
        cur_ids = np.array([i % V for i in top])
    # backtrace numpy
    want = np.zeros((K, T), np.int64)
    for k in range(K):
        beam = k
        for t in range(T - 1, -1, -1):
            tok, par = rows_hist[t][beam]
            want[k, t] = tok
            beam = par
    # apply end-id freezing as the op does
    np.testing.assert_array_equal(ids_mat[:, :T], want)
    np.testing.assert_allclose(scores_mat[:, 0], cur_scores, rtol=1e-5)
