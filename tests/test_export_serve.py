"""Non-Python-tracer deploy path (VERDICT r3 missing #1):
export_compiled -> serve.py round-trip, with the serving process proven
framework-free (the parity bar set by the reference's C++ deployment API,
inference/api/paddle_api.h:1 — deploy must not require the training
framework).
"""
import json
import os
import subprocess
import sys

import numpy as np

import paddle_tpu as fluid
from paddle_tpu.inference import (Config, create_predictor, export_compiled,
                                  load_compiled)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _build_and_save(dirname):
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 3
    with fluid.program_guard(main, startup):
        img = fluid.layers.data(name='img', shape=[8], dtype='float32')
        h = fluid.layers.fc(img, 16, act='relu')
        out = fluid.layers.fc(h, 4, act='softmax')
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    fluid.io.save_inference_model(dirname, ['img'], [out], exe, main)


def test_export_and_inprocess_load(tmp_path):
    model_dir = str(tmp_path / 'model')
    art_dir = str(tmp_path / 'artifact')
    _build_and_save(model_dir)
    cfg = Config(model_dir)
    cfg.disable_gpu()
    pred = create_predictor(cfg)
    x = np.random.RandomState(0).randn(5, 8).astype(np.float32)
    want, = pred.run([x])

    export_compiled(pred, [x], art_dir)
    assert os.path.exists(os.path.join(art_dir, 'module.jaxexport'))
    sig = json.load(open(os.path.join(art_dir, 'signature.json')))
    assert sig['feeds'][0]['name'] == 'img'

    served = load_compiled(art_dir)
    assert served.get_input_names() == ['img']
    got, = served.run([x])
    np.testing.assert_allclose(got, want, rtol=1e-6, atol=1e-6)


def test_serve_fresh_process_never_imports_framework(tmp_path):
    model_dir = str(tmp_path / 'model')
    art_dir = str(tmp_path / 'artifact')
    _build_and_save(model_dir)
    cfg = Config(model_dir)
    cfg.disable_gpu()
    pred = create_predictor(cfg)
    x = np.random.RandomState(1).randn(3, 8).astype(np.float32)
    want, = pred.run([x])

    export_compiled(pred, [x], art_dir)
    np.savez(str(tmp_path / 'in.npz'), img=x)

    # drive serve.py BY FILE PATH in a fresh process: the package __init__
    # never runs; a sys.modules audit proves no framework module loaded
    probe = (
        "import runpy, sys\n"
        "sys.argv = ['serve.py', %r, %r, %r]\n"
        "try:\n"
        "    runpy.run_path(%r, run_name='__main__')\n"
        "except SystemExit as e:\n"
        "    assert (e.code or 0) == 0, e.code\n"
        "bad = [m for m in sys.modules if m.startswith('paddle_tpu')]\n"
        "assert not bad, 'framework leaked into serving: %%r' %% bad\n"
        % (art_dir, str(tmp_path / 'in.npz'), str(tmp_path / 'out.npz'),
           os.path.join(REPO, 'paddle_tpu', 'inference', 'serve.py')))
    env = dict(os.environ)
    env['PTPU_PLATFORM'] = 'cpu'
    r = subprocess.run([sys.executable, '-c', probe], env=env,
                       capture_output=True, text=True, timeout=300)
    # SystemExit(0) from main() is fine; any other failure is not
    assert r.returncode == 0, r.stderr[-2000:]
    with np.load(str(tmp_path / 'out.npz')) as out:
        got = out[list(out.files)[0]]
    np.testing.assert_allclose(got, want, rtol=1e-6, atol=1e-6)
