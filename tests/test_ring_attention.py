"""Ring attention (sequence/context parallelism) on the 8-device virtual
CPU mesh: numeric parity against single-device attention, gradient flow,
and the framework-level sequence_parallel lowering path.

TPU-native extension beyond the reference (SURVEY §2.4 lists SP as absent
upstream); math follows the online-softmax/flash recurrence with k/v
blocks rotating over lax.ppermute (parallel/ring_attention.py).
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as fluid
from paddle_tpu.parallel import make_mesh, ring_attention


def _naive(q, k, v, causal, scale):
    s = np.einsum('bhqd,bhkd->bhqk', q * scale, k)
    if causal:
        S = q.shape[2]
        mask = np.tril(np.ones((S, S), bool))
        s = np.where(mask, s, -np.inf)
    s = s - s.max(-1, keepdims=True)
    p = np.exp(s)
    p = p / p.sum(-1, keepdims=True)
    return np.einsum('bhqk,bhkd->bhqd', p, v)


def _qkv(b=2, h=4, s=32, d=16, seed=0):
    r = np.random.RandomState(seed)
    return [r.randn(b, h, s, d).astype(np.float32) for _ in range(3)]


@pytest.mark.parametrize('causal', [False, True])
@pytest.mark.parametrize('axes', [{'sp': 8}, {'dp': 2, 'sp': 4}])
def test_ring_matches_naive(causal, axes):
    q, k, v = _qkv()
    mesh = make_mesh(axes=axes)
    out = jax.jit(lambda q, k, v: ring_attention(
        q, k, v, mesh, causal=causal, scale=0.25))(q, k, v)
    ref = _naive(q, k, v, causal, 0.25)
    np.testing.assert_allclose(np.asarray(out), ref, rtol=2e-5, atol=2e-5)


def test_long_sequence_rings_across_devices():
    """Long-context posture: S=2048 over sp=8 — each device's score block
    is [B,H,256,256] (O(S·S/P)) instead of a monolithic [B,H,2048,2048];
    causal output must still match the dense computation."""
    q, k, v = _qkv(b=1, h=2, s=2048, d=32, seed=5)
    mesh = make_mesh(axes={'sp': 8})
    out = jax.jit(lambda q, k, v: ring_attention(
        q, k, v, mesh, causal=True, scale=0.1))(q, k, v)
    ref = _naive(q, k, v, True, 0.1)
    np.testing.assert_allclose(np.asarray(out), ref, rtol=5e-5, atol=5e-5)


def test_ring_gradients_match_naive():
    q, k, v = _qkv(s=16)
    mesh = make_mesh(num_devices=4, axes={'sp': 4})

    def ring_loss(q, k, v):
        return jnp.sum(ring_attention(q, k, v, mesh, causal=True,
                                      scale=0.25) ** 2)

    def naive_loss(q, k, v):
        s = jnp.einsum('bhqd,bhkd->bhqk', q * 0.25, k)
        S = q.shape[2]
        s = jnp.where(jnp.tril(jnp.ones((S, S), bool)), s, -jnp.inf)
        p = jax.nn.softmax(s, axis=-1)
        return jnp.sum(jnp.einsum('bhqk,bhkd->bhqd', p, v) ** 2)

    g_ring = jax.jit(jax.grad(ring_loss, argnums=(0, 1, 2)))(q, k, v)
    g_ref = jax.jit(jax.grad(naive_loss, argnums=(0, 1, 2)))(q, k, v)
    # tolerance = the measured f32 noise floor: the NAIVE composition's own
    # grads deviate ~1.4e-2 abs (grad magnitude ~4-6) from f64 truth; the
    # ring recurrence matches f64 truth to 1e-13 when run in f64
    for a, b in zip(g_ring, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-2, atol=3e-2)


def test_sequence_parallel_layer_lowering():
    """fused_multihead_attention(sequence_parallel=True) under a mesh with
    an sp axis matches the same program run single-device."""
    from paddle_tpu.parallel.compiler import CompiledProgram

    q_np, k_np, v_np = _qkv(b=4, h=2, s=32, d=8, seed=3)

    def build():
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            qv = fluid.layers.data(name='q', shape=[2, 32, 8],
                                   dtype='float32')
            kv = fluid.layers.data(name='k', shape=[2, 32, 8],
                                   dtype='float32')
            vv = fluid.layers.data(name='v', shape=[2, 32, 8],
                                   dtype='float32')
            out = fluid.layers.fused_multihead_attention(
                qv, kv, vv, causal=True, scale=0.3,
                sequence_parallel=True)
        return main, startup, out

    feed = {'q': q_np, 'k': k_np, 'v': v_np}

    main, startup, out = build()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    single, = exe.run(main, feed=feed, fetch_list=[out])

    main2, startup2, out2 = build()
    mesh = make_mesh(axes={'dp': 2, 'sp': 4})
    prog = CompiledProgram(main2).with_data_parallel(mesh=mesh)
    exe2 = fluid.Executor(fluid.CPUPlace())
    exe2.run(startup2)
    sharded, = exe2.run(prog, feed=feed, fetch_list=[out2])
    np.testing.assert_allclose(np.asarray(sharded), np.asarray(single),
                               rtol=2e-5, atol=2e-5)


def test_sequence_parallel_training_step():
    """A transformer-style block with sp ring attention TRAINS over a
    dp x sp mesh: loss finite and decreasing."""
    from paddle_tpu.parallel.compiler import CompiledProgram

    S, D, H = 32, 16, 2
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 5
    with fluid.program_guard(main, startup):
        x = fluid.layers.data(name='x', shape=[S, D], dtype='float32')
        y = fluid.layers.data(name='y', shape=[1], dtype='float32')
        q = fluid.layers.fc(x, size=D, num_flatten_dims=2, bias_attr=False)
        k = fluid.layers.fc(x, size=D, num_flatten_dims=2, bias_attr=False)
        v = fluid.layers.fc(x, size=D, num_flatten_dims=2, bias_attr=False)
        def split(t):
            t = fluid.layers.reshape(t, shape=[-1, S, H, D // H])
            return fluid.layers.transpose(t, perm=[0, 2, 1, 3])
        ctxv = fluid.layers.fused_multihead_attention(
            split(q), split(k), split(v), causal=True,
            scale=(D // H) ** -0.5, sequence_parallel=True)
        ctxv = fluid.layers.reshape(
            fluid.layers.transpose(ctxv, perm=[0, 2, 1, 3]),
            shape=[-1, S, D])
        pooled = fluid.layers.reduce_mean(ctxv, dim=1)
        pred = fluid.layers.fc(pooled, size=1)
        loss = fluid.layers.mean(fluid.layers.square(pred - y))
        fluid.optimizer.Adam(1e-2).minimize(loss)

    mesh = make_mesh(axes={'dp': 2, 'sp': 4})
    prog = CompiledProgram(main).with_data_parallel(loss_name=loss.name,
                                                    mesh=mesh)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    r = np.random.RandomState(0)
    feed = {'x': r.randn(8, S, D).astype(np.float32),
            'y': r.randn(8, 1).astype(np.float32)}
    vals = []
    for _ in range(10):
        l, = exe.run(prog, feed=feed, fetch_list=[loss])
        vals.append(float(np.asarray(l).reshape(-1)[0]))
    assert np.isfinite(vals).all()
    assert vals[-1] < vals[0], vals
