"""Tracer-free TRAINING deploy path (VERDICT r4 missing #1): the
reference trains from a saved program with no Python
(train/demo_trainer.cc:1, train/test_train_recognize_digits.cc:1); here
export_train_step serializes the full train step (params + optimizer
state as inputs/outputs, rng as input) and serve.py's CompiledTrainer
runs it — losses must bit-match the in-framework Executor step for step,
and the serving process must never import the framework."""
import os
import subprocess
import sys

import numpy as np

import paddle_tpu as fluid
from paddle_tpu.inference import export_train_step, load_trainer

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
STEPS = 3


def _build():
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 7
    with fluid.program_guard(main, startup):
        x = fluid.layers.data('x', shape=[12], dtype='float32')
        label = fluid.layers.data('label', shape=[1], dtype='int64')
        h = fluid.layers.fc(x, 24, act='relu')
        h = fluid.layers.dropout(h, dropout_prob=0.3)  # rng is exercised
        logits = fluid.layers.fc(h, 5)
        loss = fluid.layers.mean(fluid.layers.softmax_with_cross_entropy(
            logits=logits, label=label))
        fluid.optimizer.Momentum(learning_rate=0.05,
                                 momentum=0.9).minimize(loss)
    return main, startup, loss


def _feed():
    rng = np.random.RandomState(0)
    return {'x': rng.randn(16, 12).astype(np.float32),
            'label': rng.randint(0, 5, (16, 1)).astype(np.int64)}


def _init_scope(startup):
    scope = fluid.core.Scope()
    exe = fluid.Executor(fluid.CPUPlace())
    with fluid.scope_guard(scope):
        exe.run(startup)
    return {n: np.asarray(scope.get(n)) for n in scope.local_var_names()
            if scope.get(n) is not None}


def _framework_losses(main, init, loss, feed, steps=STEPS):
    scope = fluid.core.Scope()
    for n, v in init.items():
        scope.set(n, v)
    exe = fluid.Executor(fluid.CPUPlace())
    out = []
    with fluid.scope_guard(scope):
        for _ in range(steps):
            l, = exe.run(main, feed=feed, fetch_list=[loss])
            out.append(np.asarray(l))
    final = {n: np.asarray(scope.get(n)) for n in init}
    return np.stack(out), final


def _export(main, init, loss, feed, art_dir):
    scope = fluid.core.Scope()
    for n, v in init.items():
        scope.set(n, v)
    export_train_step(main, feed, [loss], art_dir, scope=scope)


def test_trainer_bitmatches_executor(tmp_path):
    main, startup, loss = _build()
    init = _init_scope(startup)
    feed = _feed()
    want, want_final = _framework_losses(main, init, loss, feed)

    art = str(tmp_path / 'train_art')
    _export(main, init, loss, feed, art)
    trainer = load_trainer(art)
    got = np.stack([trainer.step(feed)[0] for _ in range(STEPS)])
    np.testing.assert_array_equal(got, want)
    # the carried state equals the in-framework scope after 3 steps
    final = trainer.state
    for n in want_final:
        np.testing.assert_array_equal(final[n], want_final[n], err_msg=n)


def test_trainer_checkpoint_roundtrip(tmp_path):
    """save_state/load_state: resume continues the exact trajectory."""
    main, startup, loss = _build()
    init = _init_scope(startup)
    feed = _feed()
    want, _ = _framework_losses(main, init, loss, feed, steps=4)

    art = str(tmp_path / 'train_art')
    _export(main, init, loss, feed, art)
    t1 = load_trainer(art)
    first = np.stack([t1.step(feed)[0] for _ in range(2)])
    ckpt = str(tmp_path / 'ckpt.npz')
    t1.save_state(ckpt)

    t2 = load_trainer(art)
    t2.load_state(ckpt)  # restores state AND the rng step counter
    rest = np.stack([t2.step(feed)[0] for _ in range(2)])
    np.testing.assert_array_equal(np.concatenate([first, rest]), want)


def test_train_fresh_process_never_imports_framework(tmp_path):
    main, startup, loss = _build()
    init = _init_scope(startup)
    feed = _feed()
    want, want_final = _framework_losses(main, init, loss, feed)

    art = str(tmp_path / 'train_art')
    _export(main, init, loss, feed, art)
    np.savez(str(tmp_path / 'feeds.npz'), **feed)

    probe = (
        "import runpy, sys\n"
        "sys.argv = ['serve.py', 'train', %r, %r, %r, '%d', %r]\n"
        "try:\n"
        "    runpy.run_path(%r, run_name='__main__')\n"
        "except SystemExit as e:\n"
        "    assert (e.code or 0) == 0, e.code\n"
        "bad = [m for m in sys.modules if m.startswith('paddle_tpu')]\n"
        "assert not bad, 'framework leaked into training: %%r' %% bad\n"
        % (art, str(tmp_path / 'feeds.npz'), str(tmp_path / 'out.npz'),
           STEPS, str(tmp_path / 'ckpt.npz'),
           os.path.join(REPO, 'paddle_tpu', 'inference', 'serve.py')))
    env = dict(os.environ)
    env['PTPU_PLATFORM'] = 'cpu'
    r = subprocess.run([sys.executable, '-c', probe], env=env,
                       capture_output=True, text=True, timeout=300)
    assert r.returncode == 0, r.stderr[-2000:]
    with np.load(str(tmp_path / 'out.npz')) as out:
        got = out[list(out.files)[0]]
    np.testing.assert_array_equal(got.reshape(want.shape), want)
    # checkpoint written by the framework-free process matches the
    # in-framework final state
    with np.load(str(tmp_path / 'ckpt.npz')) as z:
        for n in want_final:
            np.testing.assert_array_equal(z[n], want_final[n], err_msg=n)
