"""One pod-member incarnation for the ELASTIC (topology-resize) tests
(tests/test_elastic_pod.py, scripts/elastic_resume_smoke.py,
tools/chaos.py --pod N --resize).

usage: elastic_pod_worker.py CKPT_DIR DATA_FILE OUT_FILE TOTAL EVERY \
           [KILL_AT_STEP]
       elastic_pod_worker.py --make-data DATA_FILE NUM_RECORDS

env contract (set by the driver):
    PADDLE_TRAINERS / PADDLE_TRAINER_ID / PADDLE_COORDINATOR   pod shape
    PTPU_POD_RUN_ID     incarnation token (fresh per pod launch)
    PTPU_POD_HB_TIMEOUT watchdog heartbeat timeout (default 6s)

The difference from pod_ft_worker.py: this worker trains from a REAL
sharded data plane (ShardedFileReader over 1-record recordio chunks,
exactly-once journal) and is topology-elastic — it restores a pod
checkpoint written by ANY host count. The data layout makes the
per-step GLOBAL batch a topology-invariant SET: the global batch is
GLOBAL_BS records, chunks are strided per host (chunk j belongs to host
j %% N), and each host consumes GLOBAL_BS/N records per step, so step s
always trains chunks [s*GLOBAL_BS, (s+1)*GLOBAL_BS) — only the row
ORDER inside the batch depends on N. Mean loss and summed gradients are
row-permutation-invariant up to float accumulation, which is exactly
the resize parity contract: same-shape resume stays BIT-exact, resized
resume matches within float-accumulation tolerance while the rng step
stream and the exactly-once sample accounting stay exact. (The model
deliberately has no dropout: a per-ROW rng op would tie the mask to the
row order and break the permutation invariance.)

OUT_FILE lines (append, flushed per step):
    RESUME <step> <startup_s>        restore point of this incarnation
    TOPO <ckpt_hosts> <now_hosts>    topology this incarnation restored
    RESHARD <programs> <arrays> <stitch_s> <place_s>
    RESTRIDE <done> <progress> <total>   journal re-stride summary
    <step_idx> <loss>                replicated loss (identical on hosts)
    RECS <step_idx> <h1,h2,...>      sha256[:16] of each record trained
    STALL <ckpt_stall_pct>
    DONE <params_sha256>             (bit-comparable only without resize)
"""
import hashlib
import os
import struct
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

GLOBAL_BS = 16
FEAT = 16
CLASSES = 5


def make_record(i):
    r = __import__('numpy').random.RandomState(9000 + i)
    feat = r.randn(FEAT).astype('<f4')
    lab = int(r.randint(0, CLASSES))
    return feat.tobytes() + struct.pack('<q', lab)


def rec_hash(rec):
    return hashlib.sha256(rec).hexdigest()[:16]


def make_data(path, num_records):
    """Write the dataset as 1-record chunks (chunk-granular stride =
    record-granular stride) plus a sidecar .hashes file the drivers use
    for the exactly-once epoch digest."""
    from paddle_tpu import recordio
    recs = [make_record(i) for i in range(int(num_records))]
    recordio.write_recordio(path, recs, max_chunk_bytes=1)
    with open(path + '.hashes', 'w') as f:
        for rec in recs:
            f.write(rec_hash(rec) + '\n')


if __name__ == '__main__' and len(sys.argv) > 1 \
        and sys.argv[1] == '--make-data':
    make_data(sys.argv[2], int(sys.argv[3]))
    sys.exit(0)

os.environ.setdefault('XLA_FLAGS', '--xla_force_host_platform_device_count=2')
os.environ['PTPU_PLATFORM'] = 'cpu'

from paddle_tpu.parallel import multihost  # noqa: E402

# join the pod BEFORE any backend use
N, RANK = multihost.init_distributed(platform='cpu')

import numpy as np                                           # noqa: E402
import paddle_tpu as fluid                                   # noqa: E402
from paddle_tpu.core.checkpoint import (                     # noqa: E402
    PodCheckpointManager, HostWatchdog)
from paddle_tpu.parallel import shard_parameter              # noqa: E402
from paddle_tpu.parallel.mesh import make_mesh               # noqa: E402
from paddle_tpu.parallel.compiler import CompiledProgram     # noqa: E402
from paddle_tpu.reader.sharded import (                      # noqa: E402
    ShardedFileReader, restride_journal)
from paddle_tpu.testing import faults                        # noqa: E402


def build(seed=17):
    main_p, startup_p = fluid.Program(), fluid.Program()
    main_p.random_seed = startup_p.random_seed = seed
    with fluid.program_guard(main_p, startup_p):
        x = fluid.layers.data(name='x', shape=[FEAT], dtype='float32')
        lab = fluid.layers.data(name='lab', shape=[1], dtype='int64')
        h = fluid.layers.fc(x, size=32, act='relu',
                            param_attr=fluid.ParamAttr(name='fc1_w'))
        logits = fluid.layers.fc(h, size=CLASSES,
                                 param_attr=fluid.ParamAttr(name='fc2_w'))
        loss = fluid.layers.mean(fluid.layers.softmax_with_cross_entropy(
            logits=logits, label=lab))
        fluid.optimizer.Momentum(learning_rate=0.1,
                                 momentum=0.9).minimize(loss)
    # composed sharding with genuinely cross-host shards: fc1_w
    # column-parallel over mp (within a host), fc2_w row-sharded over dp
    # (the axis that SPANS hosts); optimizer slots inherit (reshard.py)
    shard_parameter(main_p.global_block().var('fc1_w'), (None, 'mp'))
    shard_parameter(main_p.global_block().var('fc2_w'), ('dp', None))
    return main_p, startup_p, loss


def decode(rec):
    feat = np.frombuffer(rec[:4 * FEAT], '<f4')
    lab = struct.unpack('<q', rec[4 * FEAT:4 * FEAT + 8])[0]
    return feat, lab


def params_sha(program, scope):
    from paddle_tpu.io import _full_value
    from paddle_tpu.core.lod import unwrap
    h = hashlib.sha256()
    for name in sorted(v.name for v in program.list_vars() if v.persistable):
        val = scope.get(name)
        if val is not None:
            h.update(name.encode())
            h.update(np.ascontiguousarray(
                np.asarray(unwrap(_full_value(val)))).tobytes())
    return h.hexdigest()


def main():
    ckpt_dir, data_file, out_path = sys.argv[1], sys.argv[2], sys.argv[3]
    total, every = int(sys.argv[4]), int(sys.argv[5])
    kill_at = int(sys.argv[6]) if len(sys.argv) > 6 else 0
    if GLOBAL_BS % N:
        raise SystemExit('host count %d does not divide the global '
                         'batch %d' % (N, GLOBAL_BS))
    local_bs = GLOBAL_BS // N

    import time
    run_id = multihost.pod_run_id()
    hb_timeout = float(os.environ.get('PTPU_POD_HB_TIMEOUT', '6'))

    main_p, startup_p, loss = build()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup_p)
    mesh = make_mesh(axes={'dp': N, 'mp': 2})
    prog = CompiledProgram(main_p).with_data_parallel(loss_name=loss.name,
                                                      mesh=mesh)

    t0 = time.perf_counter()
    mgr = PodCheckpointManager(ckpt_dir, rank=RANK, num_hosts=N,
                               every_steps=every, keep_last_n=3,
                               commit_timeout_s=30,
                               heartbeat_interval_s=0.2, run_id=run_id,
                               topology={'dp': N, 'mp': 2})
    wd = HostWatchdog(ckpt_dir, rank=RANK, num_hosts=N,
                      timeout_s=hb_timeout, run_id=run_id,
                      action='exit', exit_code=3).start()
    info = mgr.restore(executor=exe, program=prog)
    startup_s = time.perf_counter() - t0
    step = int(info['step']) if info else 0

    out = open(out_path, 'a')

    def emit(line):
        out.write(line + '\n')
        out.flush()
        os.fsync(out.fileno())

    # -- data plane: same-shape resumes continue THIS rank's journal at
    # its checkpointed position; a resize re-strides EVERY old host's
    # journal onto the new disjoint cover (no chunk replayed, none lost)
    my_journal = os.path.join(
        ckpt_dir, 'journal-%s-h%dof%d.jsonl' % (run_id, RANK, N))

    def rebase(tj):
        # the checkpoint records the journal's ABSOLUTE path, but the
        # journal files live inside ckpt_dir, so THIS tree's copy is
        # authoritative: prefer basename-in-this-dir whenever it exists
        # (identical to the recorded path on a normal in-place resume;
        # on a copied/moved tree it keeps the resume from truncating
        # the ORIGINAL tree's journal). run_id in the filename keeps
        # incarnations distinct. Fall back to the recorded path for
        # journals stored outside the checkpoint dir.
        if not tj or not tj.get('path'):
            return tj
        local = os.path.join(ckpt_dir, os.path.basename(tj['path']))
        return dict(tj, path=local) if os.path.exists(local) else tj

    journal_path, journal_limit = my_journal, None
    if info is not None:
        old_hosts = int(info.get('pod_num_hosts') or N)
        journals = {r: rebase(tj)
                    for r, tj in (info.get('task_journals') or {}).items()}
        if old_hosts == N and journals.get(RANK):
            journal_path = journals[RANK]['path']
            journal_limit = journals[RANK]['position']
        else:
            counts = restride_journal(
                [journals.get(r) for r in range(old_hosts)],
                [data_file], N, RANK, my_journal)
            emit('RESTRIDE %d %d %d' % (counts['done'],
                                        counts['progress'],
                                        counts['total']))
    reader = ShardedFileReader(
        [data_file], shard_id=RANK, num_shards=N,
        journal_path=journal_path, journal_limit=journal_limit,
        progress_every=1, holder_id='shard-%d-of-%d' % (RANK, N))
    mgr.task_service = reader

    emit('RESUME %d %.3f' % (step, startup_s))
    emit('TOPO %d %d' % (int(info['pod_num_hosts']) if info else N, N))
    rs = (info or {}).get('reshard') or {}
    emit('RESHARD %d %d %.4f %.4f'
         % (rs.get('programs', 0), rs.get('arrays', 0),
            (info or {}).get('stitch_s', 0.0), rs.get('place_s', 0.0)))

    stream = [None]

    def next_batch():
        xs, labs, hashes = [], [], []
        while len(xs) < local_bs:
            if stream[0] is None:
                stream[0] = reader.records()
            try:
                rec = next(stream[0])
            except StopIteration:
                stream[0] = None      # epoch complete: start the next
                continue
            feat, lab = decode(rec)
            xs.append(feat)
            labs.append(lab)
            hashes.append(rec_hash(rec))
        return (np.stack(xs).astype(np.float32),
                np.asarray(labs, np.int64)[:, None], hashes)

    while step < total:
        xs, labs, hashes = next_batch()
        l, = exe.run(prog, feed={'x': xs, 'lab': labs},
                     fetch_list=[loss], checkpoint=mgr)
        step += 1
        emit('%d %.17g' % (step - 1, float(np.asarray(l).reshape(-1)[0])))
        emit('RECS %d %s' % (step - 1, ','.join(hashes)))
        if kill_at and step >= kill_at:
            # die at a COMMITTED boundary: wait for THIS step's
            # POD_COMMIT on disk so the resize provably resumes here —
            # unless the boundary was skipped/abandoned (writer busy on
            # some host), in which case the newest OLDER commit is the
            # resume point and waiting longer would change nothing
            from paddle_tpu.core.checkpoint import _POD_COMMIT, _PREFIX
            t_kill = time.time()
            deadline = t_kill + 30
            pc = os.path.join(ckpt_dir, '%s%d' % (_PREFIX, step),
                              _POD_COMMIT)
            while time.time() < deadline and not os.path.exists(pc):
                if mgr._idle.is_set() and time.time() > t_kill + 2.0:
                    break      # this host's write concluded without a
                    # pod commit (skip/abandon): nothing more will land
                time.sleep(0.01)
            faults.kill_self()
        faults.maybe_kill_at_step(step)
    mgr.save(prog, fluid.global_scope(), step, blocking=True, executor=exe)
    st = exe._dispatch_stats
    emit('STALL %.4f' % (100.0 * st['ckpt_stall_s'] / st['run_s']
                         if st['run_s'] else 0.0))
    emit('DONE %s' % params_sha(main_p, fluid.global_scope()))
    mgr.barrier('done', timeout_s=60)
    wd.stop()
    reader.close()
    mgr.close()


if __name__ == '__main__':
    main()
