"""Subprocess worker for test_checkpoint.py, scripts/crash_resume_smoke.py
and tools/chaos.py: one trainer incarnation that can be SIGKILLed at an
exact step boundary and later restarted on the same checkpoint dir.

usage: checkpoint_kill_worker.py CKPT_DIR OUT_FILE TOTAL_STEPS K EVERY \
           [KILL_AT_STEP [MIN_COMMITS]]

CKPT_DIR '-' disables checkpointing (the uninterrupted reference run).
KILL_AT_STEP > 0: SIGKILL self once that many steps are trained (after
their losses are flushed to OUT_FILE) — the kill lands at a step
boundary, racing the background checkpoint writer exactly like a real
preemption. MIN_COMMITS (default 1) delays the kill until that many
checkpoints have committed, so the restart provably has something to
resume from while the race with the in-flight write stays live.

OUT_FILE lines (append, flushed+fsynced per dispatch):
    RESUME <step>          restore point of this incarnation (0 = cold)
    <step_idx> <loss>      one per trained step (bit-reproducible)
    DONE <params_sha256>   end of training (digest over sorted params)

The net, data, and seeds are pure functions of the step index, so a
killed+resumed run must reproduce the uninterrupted run's losses and
final params BIT-EXACTLY (run_steps' rng stream is keyed by the restored
step counter).
"""
import hashlib
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ['JAX_PLATFORMS'] = 'cpu'
os.environ['PTPU_PLATFORM'] = 'cpu'

BATCH = 8


def build(seed=17):
    import paddle_tpu as fluid
    main_p, startup_p = fluid.Program(), fluid.Program()
    main_p.random_seed = startup_p.random_seed = seed
    with fluid.program_guard(main_p, startup_p):
        x = fluid.layers.data(name='x', shape=[16], dtype='float32')
        lab = fluid.layers.data(name='lab', shape=[1], dtype='int64')
        h = fluid.layers.fc(x, size=32, act='relu')
        h = fluid.layers.dropout(h, dropout_prob=0.3)
        logits = fluid.layers.fc(h, size=5)
        loss = fluid.layers.mean(fluid.layers.softmax_with_cross_entropy(
            logits=logits, label=lab))
        fluid.optimizer.Momentum(learning_rate=0.1,
                                 momentum=0.9).minimize(loss)
    return main_p, startup_p, loss


def feed_for(step0, k):
    import numpy as np
    xs, labs = [], []
    for s in range(step0, step0 + k):
        r = np.random.RandomState(1000 + s)
        xs.append(r.randn(BATCH, 16).astype(np.float32))
        labs.append(r.randint(0, 5, (BATCH, 1)))
    return {'x': np.stack(xs), 'lab': np.stack(labs)}


def params_sha(program, scope):
    import numpy as np
    h = hashlib.sha256()
    for v in sorted(v.name for v in program.list_vars() if v.persistable):
        val = scope.get(v)
        if val is not None:
            h.update(v.encode())
            h.update(np.ascontiguousarray(np.asarray(val)).tobytes())
    return h.hexdigest()


def main():
    ckpt_dir, out_path = sys.argv[1], sys.argv[2]
    total, k, every = int(sys.argv[3]), int(sys.argv[4]), int(sys.argv[5])
    kill_at = int(sys.argv[6]) if len(sys.argv) > 6 else 0
    min_commits = int(sys.argv[7]) if len(sys.argv) > 7 else 1

    import numpy as np
    import paddle_tpu as fluid
    from paddle_tpu.core.checkpoint import CheckpointManager
    from paddle_tpu.parallel import MultiStepTrainer
    from paddle_tpu.testing import faults

    main_p, startup_p, loss = build()
    mgr = None
    if ckpt_dir != '-':
        mgr = CheckpointManager(ckpt_dir, every_steps=every, keep_last_n=3,
                                retry_backoff_s=0.05)
    trainer = MultiStepTrainer(main_p, steps_per_dispatch=k,
                               fetch_list=[loss], fetch_policy='stack',
                               place=fluid.CPUPlace(), checkpoint=mgr,
                               # PTPU_PREEMPTIBLE=1: SIGTERM drains one
                               # final checkpoint at the next step
                               # boundary and exits 0 (test_pod_ft)
                               preemptible=os.environ.get(
                                   'PTPU_PREEMPTIBLE') == '1')
    import time
    t0 = time.perf_counter()
    trainer.startup(startup_p)
    startup_s = time.perf_counter() - t0
    out = open(out_path, 'a')

    def emit(line):
        out.write(line + '\n')
        out.flush()
        os.fsync(out.fileno())

    emit('RESUME %d %.3f' % (trainer.resume_step, startup_s))
    # a resumed incarnation provably has a committed checkpoint on disk;
    # only a cold start must wait for its first commit before dying
    if trainer.resume_step > 0:
        min_commits = 0
    step = trainer.resume_step
    while step < total:
        vals, = trainer.step_group(feed=feed_for(step, k))
        for i, v in enumerate(np.asarray(vals).reshape(-1)):
            emit('%d %.17g' % (step + i, float(v)))
        step += k
        if kill_at and step >= kill_at:
            if mgr is not None:
                # ensure the restart has min_commits checkpoints to find
                # (only while a write is actually in flight); any write
                # beyond that still races the SIGKILL
                deadline = time.time() + 30
                st = mgr.stats
                while st['commits'] < min_commits \
                        and st['snapshots'] - st['commits'] - st['failed'] \
                        > 0 and time.time() < deadline:
                    time.sleep(0.005)
            faults.kill_self()
        faults.maybe_kill_at_step(step)
    if mgr is not None:
        mgr.save(main_p, fluid.global_scope(), step, blocking=True,
                 executor=trainer.executor)
        mgr.close()
    emit('DONE %s' % params_sha(main_p, fluid.global_scope()))


if __name__ == '__main__':
    main()
