"""Sequence/LoD op tests: feed LoDTensors, check against per-sequence numpy
references (ref: test_sequence_pool.py, test_sequence_expand.py, test_lstm_op.py...)."""
import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu.lod_tensor import create_lod_tensor


def _run(layer_fn, feeds, fetch, lod_feeds=None):
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    return exe.run(feed=feeds, fetch_list=fetch)


def test_sequence_pool_types():
    x = fluid.layers.data('x', shape=[3], dtype='float32', lod_level=1)
    outs = {
        'sum': fluid.layers.sequence_pool(x, 'sum'),
        'avg': fluid.layers.sequence_pool(x, 'average'),
        'max': fluid.layers.sequence_pool(x, 'max'),
        'first': fluid.layers.sequence_first_step(x),
        'last': fluid.layers.sequence_last_step(x),
    }
    data = np.arange(15, dtype=np.float32).reshape(5, 3)
    lt = create_lod_tensor(data, [[2, 3]])
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    names = list(outs)
    vals = exe.run(feed={'x': lt}, fetch_list=[outs[n] for n in names])
    got = dict(zip(names, vals))
    seqs = [data[:2], data[2:]]
    np.testing.assert_allclose(got['sum'], [s.sum(0) for s in seqs], rtol=1e-6)
    np.testing.assert_allclose(got['avg'], [s.mean(0) for s in seqs], rtol=1e-6)
    np.testing.assert_allclose(got['max'], [s.max(0) for s in seqs], rtol=1e-6)
    np.testing.assert_allclose(got['first'], [s[0] for s in seqs], rtol=1e-6)
    np.testing.assert_allclose(got['last'], [s[-1] for s in seqs], rtol=1e-6)


def test_sequence_softmax():
    x = fluid.layers.data('x', shape=[1], dtype='float32', lod_level=1)
    y = fluid.layers.sequence_softmax(x)
    data = np.array([[1.], [2.], [3.], [1.], [1.]], np.float32)
    lt = create_lod_tensor(data, [[3, 2]])
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    out, = exe.run(feed={'x': lt}, fetch_list=[y])

    def sm(v):
        e = np.exp(v - v.max())
        return e / e.sum()
    want = np.concatenate([sm(data[:3, 0]), sm(data[3:, 0])])[:, None]
    np.testing.assert_allclose(out, want, rtol=1e-5)


def test_sequence_expand():
    x = fluid.layers.data('x', shape=[1], dtype='float32', lod_level=1)
    y = fluid.layers.data('y', shape=[1], dtype='float32', lod_level=1)
    out = fluid.layers.sequence_expand(x, y, ref_level=0)
    xd = np.array([[1.], [2.], [3.], [4.]], np.float32)
    yd = np.zeros((5, 1), np.float32)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    o, = exe.run(feed={'x': create_lod_tensor(xd, [[2, 2]]),
                       'y': create_lod_tensor(yd, [[2, 3]])},
                 fetch_list=[out])
    # x seq0=[1,2] repeated 2x, x seq1=[3,4] repeated 3x
    want = np.array([1, 2, 1, 2, 3, 4, 3, 4, 3, 4], np.float32)[:, None]
    np.testing.assert_allclose(o, want)


def test_sequence_pad_unpad_roundtrip():
    x = fluid.layers.data('x', shape=[2], dtype='float32', lod_level=1)
    pad_v = fluid.layers.assign(np.array([0.0], np.float32))
    padded, length = fluid.layers.sequence_pad(x, pad_v)
    unpadded = fluid.layers.sequence_unpad(padded, length)
    data = np.arange(10, dtype=np.float32).reshape(5, 2)
    lt = create_lod_tensor(data, [[2, 3]])
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    p, u = exe.run(feed={'x': lt}, fetch_list=[padded, unpadded])
    assert p.shape == (2, 3, 2)
    np.testing.assert_allclose(p[0, :2], data[:2])
    np.testing.assert_allclose(p[0, 2], 0.0)
    np.testing.assert_allclose(u, data)


def test_sequence_reverse():
    x = fluid.layers.data('x', shape=[1], dtype='float32', lod_level=1)
    rev = fluid.layers.sequence_reverse(x)
    data = np.arange(5, dtype=np.float32)[:, None]
    lt = create_lod_tensor(data, [[3, 2]])
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    r, = exe.run(feed={'x': lt}, fetch_list=[rev])
    np.testing.assert_allclose(r[:, 0], [2, 1, 0, 4, 3])


def test_sequence_mask():
    lens = fluid.layers.data('lens', shape=[3], dtype='int64',
                             append_batch_size=False)
    m = fluid.layers.sequence_mask(lens, maxlen=4, dtype='float32')
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    mv, = exe.run(feed={'lens': np.array([1, 3, 4], np.int64)},
                  fetch_list=[m])
    np.testing.assert_allclose(mv, [[1, 0, 0, 0], [1, 1, 1, 0], [1, 1, 1, 1]])


def test_dynamic_lstm_trains():
    """LSTM text classifier on LoD input learns a simple rule."""
    np.random.seed(0)
    words = fluid.layers.data('words', shape=[1], dtype='int64', lod_level=1)
    label = fluid.layers.data('label', shape=[1], dtype='int64')
    emb = fluid.layers.embedding(input=words, size=[20, 16])
    proj = fluid.layers.fc(input=emb, size=64, bias_attr=False)
    proj.lod_level = 1
    hidden, cell = fluid.layers.dynamic_lstm(input=proj, size=64)
    pooled = fluid.layers.sequence_pool(hidden, 'last')
    logits = fluid.layers.fc(input=pooled, size=2)
    loss = fluid.layers.mean(fluid.layers.softmax_with_cross_entropy(
        logits=logits, label=label))
    fluid.optimizer.Adam(learning_rate=5e-3).minimize(loss)

    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())

    # rule: label = whether token 7 appears in the sequence
    def batch():
        seqs, labels = [], []
        for _ in range(16):
            s = np.random.randint(0, 20, 6)  # fixed length: one compile (bucketed)
            labels.append([int(7 in s)])
            seqs.append(s)
        flat = np.concatenate(seqs)[:, None].astype(np.int64)
        return (create_lod_tensor(flat, [[len(s) for s in seqs]]),
                np.asarray(labels, np.int64))

    losses = []
    for i in range(40):
        w, lab = batch()
        l, = exe.run(feed={'words': w, 'label': lab}, fetch_list=[loss])
        losses.append(float(l[0]))
    assert np.mean(losses[-10:]) < np.mean(losses[:10]), losses[:3] + losses[-3:]


def test_dynamic_gru_runs():
    x = fluid.layers.data('x', shape=[1], dtype='int64', lod_level=1)
    emb = fluid.layers.embedding(input=x, size=[10, 9])
    proj = fluid.layers.fc(input=emb, size=15, bias_attr=False)
    proj.lod_level = 1
    hidden = fluid.layers.dynamic_gru(input=proj, size=5)
    pooled = fluid.layers.sequence_pool(hidden, 'average')
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    flat = np.random.randint(0, 10, (6, 1)).astype(np.int64)
    out, = exe.run(feed={'x': create_lod_tensor(flat, [[4, 2]])},
                   fetch_list=[pooled])
    assert out.shape == (2, 5)
    assert np.isfinite(out).all()
