"""Continuous in-flight decode serving (ISSUE 8): bit-identity of
continuously batched decode vs one-request-at-a-time decode (greedy and
fixed-width beam), slot free/reuse under staggered arrivals, deadline
expiry mid-decode, shedding, and fresh-subprocess warm start with zero
XLA compiles."""
import json
import os
import subprocess
import sys
import time

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu.inference import (DecodingPredictor, export_decode,
                                  ServerOverloaded, DeadlineExceeded)

VOCAB, SLOTS, CACHE, BUCKETS = 37, 4, 64, (4, 8)


@pytest.fixture(scope='module')
def artifact(tmp_path_factory):
    """One tiny decoder-LM artifact per module: 2 layers, 4 slots,
    prompt buckets (4, 8), AOT sidecars on (export default)."""
    from models.transformer import build_decode_spec
    out = str(tmp_path_factory.mktemp('decode') / 'art')
    main, startup = fluid.Program(), fluid.Program()
    prev_m = fluid.switch_main_program(main)
    prev_s = fluid.switch_startup_program(startup)
    scope = fluid.core.Scope()
    try:
        with fluid.scope_guard(scope):
            spec = build_decode_spec(
                vocab=VOCAB, d_model=16, n_head=2, n_layer=2, d_ff=32,
                max_slots=SLOTS, max_cache_len=CACHE,
                prompt_buckets=BUCKETS, eos_id=1)
            exe = fluid.Executor(fluid.CPUPlace())
            exe.run(spec['startup'])
            export_decode(spec, out, scope=scope)
    finally:
        fluid.switch_main_program(prev_m)
        fluid.switch_startup_program(prev_s)
    return out


def _prompts(seed, n, lo=2, hi=None):
    rng = np.random.RandomState(seed)
    return [rng.randint(lo, hi or VOCAB, int(rng.randint(2, 9)))
            for _ in range(n)]


def test_artifact_layout(artifact):
    from paddle_tpu.inference import decoding
    with open(os.path.join(artifact, decoding._DECODE_SIGNATURE)) as f:
        sig = json.load(f)
    assert sig['kind'] == 'decode'
    assert sig['max_slots'] == SLOTS
    assert sig['prompt_buckets'] == sorted(BUCKETS)
    assert len(sig['state']) == 4  # 2 layers x K/V
    for e in sig['state']:
        assert e['shape'][:2] == [SLOTS, CACHE]
    for d in ([decoding._STEP_DIR, decoding._REORDER_DIR] +
              [decoding._PREFILL_DIR % b for b in BUCKETS]):
        assert os.path.exists(os.path.join(artifact, d, 'module.jaxexport'))
        # export-time AOT warm-start sidecar per program
        assert os.path.exists(os.path.join(artifact, d, 'aot_cpu.jaxexec'))


def test_greedy_bit_identity_continuous_vs_sequential(artifact):
    """12 requests over 4 slots: transcripts must be bit-identical to
    serving each request alone (row-independent slots, masked attention),
    and slots must recycle (more requests than slots all complete)."""
    prompts = _prompts(11, 12)
    with DecodingPredictor(artifact) as pred:
        seq = [pred.generate(p, max_new_tokens=10) for p in prompts]
        snap_seq = pred.stats.snapshot()
        assert snap_seq['requests'] == 12
        pred.stats.reset()
        streams = [pred.submit(p, max_new_tokens=10) for p in prompts]
        con = [s.result(120) for s in streams]
        snap = pred.stats.snapshot()
    assert con == seq
    assert snap['requests'] == 12 and snap['prefills'] == 12
    # continuous batching packs multiple requests per step
    assert snap['occupancy'] > snap_seq['occupancy']
    assert snap['steps'] < snap_seq['steps']


def test_greedy_bit_identity_staggered_arrivals(artifact):
    """Requests joining MID-decode (staggered arrivals) change nothing
    about earlier requests' streams."""
    prompts = _prompts(12, 6)
    with DecodingPredictor(artifact) as pred:
        seq = [pred.generate(p, max_new_tokens=12) for p in prompts]
        streams = []
        for p in prompts:
            streams.append(pred.submit(p, max_new_tokens=12))
            time.sleep(0.002)  # land inside the running batch
        con = [s.result(120) for s in streams]
    assert con == seq


def test_beam_bit_identity(artifact):
    """Fixed-width beam (3 slots per request) under co-residency with
    greedy traffic: hypotheses and scores bit-match solo runs."""
    prompts = _prompts(13, 4)
    with DecodingPredictor(artifact) as pred:
        solo = [pred.generate(p, max_new_tokens=8, beam=3) for p in prompts]
        beams = [pred.submit(p, max_new_tokens=8, beam=3)
                 for p in prompts[:2]]
        greedy = pred.submit(prompts[2], max_new_tokens=8)
        beams += [pred.submit(p, max_new_tokens=8, beam=3)
                  for p in prompts[2:]]
        got = [s.result(120) for s in beams]
        greedy.result(120)
    for (ids1, sc1), (ids2, sc2) in zip(solo, got):
        np.testing.assert_array_equal(ids1, ids2)
        np.testing.assert_array_equal(sc1, sc2)
        assert ids1.shape[0] == 3
        # best-first hypothesis ordering
        assert list(sc1) == sorted(sc1, reverse=True)


def test_token_streaming(artifact):
    """submit() yields tokens as steps complete; the iterated stream
    equals the final result."""
    with DecodingPredictor(artifact) as pred:
        stream = pred.submit(_prompts(14, 1)[0], max_new_tokens=9)
        toks = list(stream)
        assert toks == stream.result(10)
        assert 1 <= len(toks) <= 9


def test_prefill_step_cache_consistency(artifact):
    """Teacher-forcing the generated tokens back through the (bucketed)
    prefill program reproduces the decode-step choices: the two programs
    agree on the cache contents."""
    prompt = _prompts(15, 1)[0][:3]
    with DecodingPredictor(artifact) as pred:
        toks = pred.generate(prompt, max_new_tokens=6)
        for k in range(1, 4):
            forced = np.concatenate([prompt, toks[:k]])
            nxt = pred.generate(forced, max_new_tokens=1)
            assert nxt[0] == toks[k]


def test_deadline_expires_in_queue(artifact):
    with DecodingPredictor(artifact) as pred:
        s = pred.submit(_prompts(16, 1)[0], max_new_tokens=4,
                        deadline_ms=0.0)
        with pytest.raises(DeadlineExceeded):
            s.result(30)
        assert pred.stats.snapshot()['expired'] == 1


def test_deadline_expiry_mid_decode_frees_slot(artifact):
    """A deadline elapsing DURING decode resolves the stream with
    DeadlineExceeded at the next step boundary and frees the slot —
    follow-up traffic is unaffected."""
    prompts = _prompts(17, 3)
    with DecodingPredictor(artifact) as pred:
        want = pred.generate(prompts[1], max_new_tokens=5)
        s = pred.submit(prompts[0], max_new_tokens=57, deadline_ms=3.0)
        with pytest.raises(DeadlineExceeded):
            s.result(120)
        assert pred.stats.snapshot()['expired'] == 1
        # every slot is free again and serving continues bit-identically
        assert pred._free_slots() == list(range(SLOTS))
        assert pred.generate(prompts[1], max_new_tokens=5) == want


def test_max_queue_shedding(artifact):
    """Submissions beyond max_queue waiting requests fast-fail with
    ServerOverloaded before any device work; admitted requests finish."""
    prompts = _prompts(18, 16)
    with DecodingPredictor(artifact, max_queue=4) as pred:
        streams = [pred.submit(p, max_new_tokens=30) for p in prompts]
        shed = served = 0
        for s in streams:
            try:
                s.result(120)
                served += 1
            except ServerOverloaded:
                shed += 1
        snap = pred.stats.snapshot()
    assert shed >= 1 and served >= 4
    assert snap['shed'] == shed and snap['requests'] == served


def test_submit_validation(artifact):
    with DecodingPredictor(artifact) as pred:
        with pytest.raises(ValueError):
            pred.submit([], max_new_tokens=4).result(10)
        with pytest.raises(ValueError):  # longer than the largest bucket
            pred.submit(np.arange(2, 12), max_new_tokens=4).result(10)
        with pytest.raises(ValueError):  # beam wider than the slot pool
            pred.submit([3, 4], beam=SLOTS + 1).result(10)
    with pytest.raises(RuntimeError):
        pred.submit([3, 4])


def test_serving_report_decode_rows(artifact, capsys):
    from paddle_tpu import profiler
    with DecodingPredictor(artifact) as pred:
        pred.generate(_prompts(19, 1)[0], max_new_tokens=4)
        out = profiler.serving_report()
        name = [k for k in out if k.startswith('decode:')]
        assert name, out
        snap = out[name[0]]
    for key in ('tokens', 'tokens_s', 'prefills', 'steps', 'occupancy',
                'ttft_p50_ms', 'ttft_p99_ms', 'itl_p50_ms', 'itl_p99_ms'):
        assert key in snap
    text = capsys.readouterr().out
    assert 'Decode source' in text and 'ttftp99(ms)' in text


def test_warm_fresh_subprocess_zero_compiles(artifact):
    """A fresh serving process loading the sidecar'd artifact performs
    ZERO XLA compiles and produces bit-identical transcripts to an
    in-process run — the ISSUE 8 warm-start acceptance bar."""
    worker = os.path.join(os.path.dirname(__file__),
                          'decode_serve_worker.py')
    env = dict(os.environ, JAX_PLATFORMS='cpu', PTPU_PLATFORM='cpu')
    out = subprocess.run(
        [sys.executable, worker, artifact, '23', '5', '7'],
        capture_output=True, text=True, env=env, timeout=300)
    assert out.returncode == 0, out.stderr
    assert 'DECODE_OK' in out.stdout
    payload = json.loads(
        [l for l in out.stdout.splitlines()
         if l.startswith('DECODE ')][0][len('DECODE '):])
    assert payload['compiles'] == 0, payload
    # replicate the worker's prompts in-process and compare transcripts
    rng = np.random.RandomState(23)
    prompts = [rng.randint(2, VOCAB, rng.randint(2, max(BUCKETS) + 1))
               for _ in range(5)]
    with DecodingPredictor(artifact) as pred:
        want = [pred.submit(p, max_new_tokens=7) for p in prompts]
        want = [s.result(120) for s in want]
        ids, scores = pred.generate(prompts[0], max_new_tokens=7, beam=3)
    assert payload['greedy'] == want
    np.testing.assert_array_equal(np.asarray(payload['beam_ids']), ids)
    np.testing.assert_array_equal(np.asarray(payload['beam_scores']),
                                  scores)
